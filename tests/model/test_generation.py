"""Generation loop tests: HF greedy parity, logprob self-consistency, EOS."""

import jax
import numpy as np
import pytest

import areal_tpu.models.hf  # noqa: F401
from areal_tpu.api.model_api import GenerationHyperparameters
from areal_tpu.models.generation import generate_tokens
from areal_tpu.models.hf import get_family, torch_state_dict_to_numpy
from areal_tpu.models.packing import pack_sequences
from areal_tpu.models.transformer import forward
from areal_tpu.ops.loss import next_token_logprobs

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")


@pytest.fixture(scope="module")
def tiny_model():
    torch.manual_seed(0)
    hf_model = transformers.Qwen2ForCausalLM(
        transformers.Qwen2Config(
            vocab_size=128, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=512, tie_word_embeddings=False,
        )
    ).eval()
    fam = get_family("qwen2")
    cfg = fam.config_from_hf(hf_model.config.to_dict(), False)
    cfg.compute_dtype = "float32"
    params = fam.params_from_hf(
        torch_state_dict_to_numpy(hf_model.state_dict()), cfg
    )
    return hf_model, cfg, params


def test_greedy_matches_hf(tiny_model):
    hf_model, cfg, params = tiny_model
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, 128, size=l).tolist() for l in [5, 9, 3]]
    g = GenerationHyperparameters(max_new_tokens=16, greedy=True)
    outs = generate_tokens(params, cfg, prompts, g, jax.random.PRNGKey(0))
    for p, o in zip(prompts, outs):
        with torch.no_grad():
            hf_out = hf_model.generate(
                torch.tensor([p]), max_new_tokens=16, do_sample=False,
                eos_token_id=None, pad_token_id=0,
            )[0, len(p):].tolist()
        assert o["output_ids"] == hf_out, (o["output_ids"], hf_out)
        assert o["no_eos"]  # nothing stopped it


def test_sampled_logprobs_consistent_with_forward(tiny_model):
    _, cfg, params = tiny_model
    rng = np.random.RandomState(1)
    prompts = [rng.randint(0, 128, size=7).tolist() for _ in range(2)]
    g = GenerationHyperparameters(max_new_tokens=12, greedy=False, temperature=1.0)
    outs = generate_tokens(params, cfg, prompts, g, jax.random.PRNGKey(7))
    for p, o in zip(prompts, outs):
        full = np.array(p + o["output_ids"], np.int32)
        b = pack_sequences([full], row_len_multiple=64)
        logits = forward(params, cfg, b.input_ids, b.segment_ids, b.positions,
                         attn_impl="reference")
        lp = np.asarray(next_token_logprobs(
            logits, b.input_ids, b.segment_ids))
        span = b.spans[0]
        # logprob at position t scores token t+1: generated token i sits at
        # position len(p)+i, scored at len(p)+i-1.
        recomputed = lp[span.row, span.start + len(p) - 1 :
                        span.start + len(full) - 1]
        np.testing.assert_allclose(
            recomputed, np.array(o["output_logprobs"]), atol=1e-3, rtol=1e-3
        )


def test_eos_stops_generation(tiny_model):
    _, cfg, params = tiny_model
    prompt = list(range(6))
    g = GenerationHyperparameters(max_new_tokens=24, greedy=True)
    free = generate_tokens(params, cfg, [prompt], g, jax.random.PRNGKey(0))[0]
    assert len(free["output_ids"]) == 24
    stop_tok = free["output_ids"][9]
    stop_idx = free["output_ids"].index(stop_tok)  # first occurrence
    outs = generate_tokens(
        params, cfg, [prompt], g, jax.random.PRNGKey(0), eos_token_id=stop_tok
    )[0]
    assert outs["output_ids"] == free["output_ids"][: stop_idx + 1]
    assert not outs["no_eos"]


def test_min_new_tokens_forbids_eos(tiny_model):
    _, cfg, params = tiny_model
    prompt = list(range(6))
    g = GenerationHyperparameters(max_new_tokens=24, greedy=True)
    free = generate_tokens(params, cfg, [prompt], g, jax.random.PRNGKey(0))[0]
    stop_tok = free["output_ids"][3]
    g2 = GenerationHyperparameters(max_new_tokens=24, greedy=True, min_new_tokens=10)
    outs = generate_tokens(
        params, cfg, [prompt], g2, jax.random.PRNGKey(0), eos_token_id=stop_tok
    )[0]
    assert len(outs["output_ids"]) >= 10
    assert stop_tok not in outs["output_ids"][:10]

"""HF -> JAX conversion parity: logits must match transformers on CPU.

Mirror of the reference's tests/model/test_cpu_inference.py gate
(SURVEY.md §7.3 minimum slice gate).
"""

import numpy as np
import pytest

import areal_tpu.models.hf  # noqa: F401  (registers families)
from areal_tpu.models.hf import (
    get_family,
    save_hf_model,
    load_hf_model,
    torch_state_dict_to_numpy,
)
from areal_tpu.models.packing import pack_sequences
from areal_tpu.models.transformer import forward

transformers = pytest.importorskip("transformers")
torch = pytest.importorskip("torch")


def tiny_hf_model(family: str):
    if family == "llama":
        cfg = transformers.LlamaConfig(
            vocab_size=128, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=256, rms_norm_eps=1e-6, tie_word_embeddings=False,
        )
        return transformers.LlamaForCausalLM(cfg)
    if family == "qwen2":
        cfg = transformers.Qwen2Config(
            vocab_size=128, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=256, tie_word_embeddings=False,
        )
        return transformers.Qwen2ForCausalLM(cfg)
    if family == "qwen3":
        cfg = transformers.Qwen3Config(
            vocab_size=128, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            head_dim=16, max_position_embeddings=256, tie_word_embeddings=False,
        )
        return transformers.Qwen3ForCausalLM(cfg)
    if family == "mistral":
        cfg = transformers.MistralConfig(
            vocab_size=128, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=256, tie_word_embeddings=False,
            sliding_window=None,
        )
        return transformers.MistralForCausalLM(cfg)
    if family == "mixtral":
        cfg = transformers.MixtralConfig(
            vocab_size=128, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            max_position_embeddings=256, tie_word_embeddings=False,
            num_local_experts=4, num_experts_per_tok=2, sliding_window=None,
        )
        return transformers.MixtralForCausalLM(cfg)
    if family == "gemma":
        cfg = transformers.GemmaConfig(
            vocab_size=128, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            head_dim=16, max_position_embeddings=256,
            hidden_act="gelu_pytorch_tanh",
        )
        return transformers.GemmaForCausalLM(cfg)
    if family == "gpt2":
        cfg = transformers.GPT2Config(
            vocab_size=128, n_embd=64, n_layer=2, n_head=4, n_positions=256,
        )
        return transformers.GPT2LMHeadModel(cfg)
    raise ValueError(family)


@pytest.mark.parametrize(
    "family", ["llama", "qwen2", "qwen3", "mistral", "mixtral", "gemma", "gpt2"]
)
def test_logits_match_hf(family):
    torch.manual_seed(0)
    hf_model = tiny_hf_model(family).eval()
    fam = get_family(family)
    cfg = fam.config_from_hf(hf_model.config.to_dict(), False)
    cfg.compute_dtype = "float32"  # parity in fp32
    params = fam.params_from_hf(torch_state_dict_to_numpy(hf_model.state_dict()), cfg)

    rng = np.random.RandomState(0)
    lens = [13, 7, 21]
    seqs = [rng.randint(0, 128, size=l) for l in lens]

    with torch.no_grad():
        hf_logits = [
            hf_model(torch.tensor(s[None], dtype=torch.long)).logits[0].numpy()
            for s in seqs
        ]

    batch = pack_sequences(seqs, row_len_multiple=16)
    logits = np.asarray(
        forward(
            params, cfg,
            batch.input_ids, batch.segment_ids, batch.positions,
            attn_impl="reference",
        )
    )
    ours = batch.gather_per_token(logits)
    for h, o in zip(hf_logits, ours):
        np.testing.assert_allclose(h, o, atol=5e-3, rtol=5e-3)


@pytest.mark.parametrize("family", ["qwen2"])
def test_hf_save_load_roundtrip(family, tmp_path):
    torch.manual_seed(1)
    hf_model = tiny_hf_model(family).eval()
    fam = get_family(family)
    cfg = fam.config_from_hf(hf_model.config.to_dict(), False)
    cfg.compute_dtype = "float32"
    params = fam.params_from_hf(torch_state_dict_to_numpy(hf_model.state_dict()), cfg)

    save_hf_model(str(tmp_path / "ckpt"), cfg, params, family)
    cfg2, params2 = load_hf_model(str(tmp_path / "ckpt"))
    assert cfg2.n_layers == cfg.n_layers and cfg2.attn_bias == cfg.attn_bias

    import jax

    for a, b in zip(
        jax.tree_util.tree_leaves(params), jax.tree_util.tree_leaves(params2)
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)

    # And the roundtripped checkpoint still loads into transformers.
    reloaded = transformers.AutoModelForCausalLM.from_pretrained(str(tmp_path / "ckpt"))
    x = torch.randint(0, 128, (1, 9))
    with torch.no_grad():
        a = hf_model(x).logits
        b = reloaded(x).logits
    np.testing.assert_allclose(a.numpy(), b.numpy(), atol=1e-5)


def test_critic_head_conversion():
    hf_model = tiny_hf_model("qwen2").eval()
    fam = get_family("qwen2")
    cfg = fam.config_from_hf(hf_model.config.to_dict(), True)
    assert cfg.is_critic
    params = fam.params_from_hf(torch_state_dict_to_numpy(hf_model.state_dict()), cfg)
    assert params["head"]["weight"].shape == (64, 1)
    cfg.compute_dtype = "float32"
    batch = pack_sequences([np.arange(10)], row_len_multiple=16)
    values = forward(
        params, cfg, batch.input_ids, batch.segment_ids, batch.positions,
        attn_impl="reference",
    )
    assert values.shape == batch.input_ids.shape

"""Continuous-batching generation engine over a paged KV pool.

TPU-native replacement for the reference's patched-SGLang server stack
(realhf/impl/model/backend/sglang.py:192-500 + patch/sglang/
v0.4.6.post2.patch): a pool of B sequence slots whose KV lives in a
shared paged pool (engine/paged.py), a jitted multi-step decode block,
batched bucketed prefill, per-slot sampling params, and interruption
BETWEEN blocks — which is what makes weight updates cheap: the loop
drains at a block boundary, partial outputs return to the clients (who
resubmit with the concatenated prefix, recomputing KV under the new
weights), and the new params are swapped in.

Differences from the round-2 dense engine (VERDICT r2 missing #1):
- KV memory scales with tokens in flight (`kv_pool_tokens`), not
  `B * max_seq_len`: long-context workloads (the reference benchmark's
  31k generation) fit because slots only hold pages they use.
- Pool exhaustion preempts the requesting slot via the normal interrupt
  path — the partial-rollout protocol (system/partial_rollout.py)
  resubmits with the prefix, so memory pressure degrades to extra
  prefill work instead of a crash.
- Prefill is batched across queued requests (one forward per admit
  round, row-count bucketed to cap compile variants).
- The engine accepts a `jax.sharding.Mesh` (see `serving_mesh`):
  params are tensor-sharded megatron-style (parallel/sharding.py), the
  KV pool is sharded over kv heads, and the Pallas paged-attention
  kernel runs under shard_map (paged.py).

Host<->device discipline: ALL per-slot control state lives on device
between blocks, admits land in one fused update (paged.apply_admits),
and each decode block costs exactly ONE device fetch (the packed result
array). Per-array pushes/fetches are serial round trips — the dominant
cost on remote-tunneled TPUs and still measurable on local ones.

Static shapes throughout: the decode block is one compiled program
reused for the server's lifetime.
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import queue
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from areal_tpu.base import env_registry, logging, tracing
from areal_tpu.base.fault_injection import faults
from areal_tpu.base.latency import LatencyHistogram
from areal_tpu.engine.paged import (
    TRASH_PAGE,
    PageAllocator,
    apply_admits,
    apply_deactivations,
    paged_chunk_prefill,
    paged_chunk_prefill_packed,
    paged_decode_block,
    pages_needed,
    quantize_kv,
    scatter_prefill,
    update_page_rows,
    warp_sample,
)
from areal_tpu.models.config import TransformerConfig

logger = logging.getLogger("serving")


@dataclasses.dataclass
class GenRequest:
    qid: str
    input_ids: List[int]
    max_new_tokens: int = 256
    min_new_tokens: int = 0
    greedy: bool = False
    temperature: float = 1.0
    top_p: float = 1.0
    top_k: int = -1
    stop_token_ids: Tuple[int, ...] = ()
    # Admission class, lower admits first: 0 = session continuation /
    # interrupted re-prefill (the server maps these from resubmissions),
    # 1 = fresh request. The engine additionally promotes any request
    # whose qid holds a parked prefix to class 0 — its pages are already
    # paid for, and finishing the session releases budget fastest.
    priority: int = 1
    # resolved by the engine loop:
    done_cb: Optional[Callable[["GenResult"], None]] = None
    submit_time: float = 0.0
    # Admission rounds this request sat in the backlog while higher-
    # priority work admitted ahead of it (starvation-aging counter).
    starved_rounds: int = 0


@dataclasses.dataclass
class GenResult:
    qid: str
    output_ids: List[int]
    output_logprobs: List[float]
    no_eos: bool  # True if stopped for a non-EOS reason (budget/interrupt)
    interrupted: bool
    version_start: int
    version_end: int
    latency: float = 0.0
    # Set iff the engine's serve loop died before this request finished
    # (e.g. an XLA compile error): outputs are empty/partial and the
    # engine accepts no further submits.
    error: Optional[str] = None


def _round_up(n: int, multiple: int) -> int:
    return max(multiple, -(-n // multiple) * multiple)


def _pow2_at_least(n: int, cap: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return min(p, cap)


def serving_mesh(
    n_devices: Optional[int] = None, axis: str = "tensor"
) -> "jax.sharding.Mesh":
    """Single-axis serving mesh: 4 axes so model-side sharding
    constraints (parallel/sharding.py) resolve, with only ``axis`` > 1
    ("tensor" for TP serving, "fsdp" for expert-parallel serving —
    experts shard over fsdp)."""
    from jax.sharding import Mesh

    devs = jax.devices()
    n = n_devices or len(devs)
    names = ("data", "fsdp", "seq", "tensor")
    shape = [1, 1, 1, 1]
    shape[names.index(axis)] = n
    arr = np.asarray(devs[:n]).reshape(shape)
    return Mesh(arr, names)


@functools.partial(jax.jit, static_argnames=("cfg", "pad_len", "mesh"))
def _prefill_batch(params, cfg: TransformerConfig, input_ids, lengths,
                   pad_len: int, mesh=None):
    """Batched prefill at a bucketed length.

    input_ids: [n, pad_len] right-padded; lengths: [n]. Returns
    (last_logits [n, V], k_pref, v_pref each [L, n, pad_len, Hkv, hd])."""
    from areal_tpu.models.transformer import forward as packed_forward

    n = input_ids.shape[0]
    pos = jnp.arange(pad_len)[None, :]
    seg = (pos < lengths[:, None]).astype(jnp.int32)
    positions = jnp.where(seg > 0, pos, 0).astype(jnp.int32)
    logits, (k, v) = packed_forward(
        params, cfg, input_ids, seg, positions, return_kv=True, mesh=mesh
    )
    last = jnp.take_along_axis(
        logits, jnp.maximum(lengths - 1, 0)[:, None, None], axis=1
    )[:, 0]
    return last, k, v


# Machine-checked engine-loop thread contract (areal_tpu/lint,
# checker `loop-only`; docs/static_analysis.md). The attrs listed here
# are owned by the engine loop thread and have NO locks by design —
# the loop is the only writer/reader; `_run_on_loop` is the one legal
# cross-thread door (closures run between laps). Off-loop code needing
# a value reads a loop-maintained snapshot (e.g. `_backlog_len`,
# `_kv_pages_free`) instead. `instance_hints` extends the check to
# other modules: `self.engine.<attr>` in an HTTP handler is the same
# race spelled differently.
AREAL_LINT_LOOP_ONLY = {
    "ServingEngine": {
        "roots": ["_loop"],
        "door": "_run_on_loop",
        "attrs": [
            "_backlog", "_prefix_cache", "_allocator",
            "_k_pages", "_v_pages", "_dstate", "_page_table",
            "_pt_dirty", "_pt_dirty_slots", "_pt_dev", "_len",
            "_pending_deact",
            "_slot_req", "_slot_out", "_slot_lp", "_slot_vstart",
            "_slot_pages", "_slot_emit_t", "_rng", "_history",
            "_admit_inflight", "_blocks_since_admit",
            # Tiered-KV spill state: the parked-qids snapshot clock is
            # loop-owned (other threads read the _parked_qids snapshot
            # dict itself, replaced wholesale — the _backlog_len
            # pattern — plus the thread-safe _spill_q / kv_tier store).
            "_parked_snap_t",
        ],
        "init_ok": ["__init__"],
        "instance_hints": ["engine", "eng"],
    },
}


@jax.jit
def _first_sample_packed(logits, rng, temps, top_ps, top_ks, greedy_mask,
                         forbid_rows, eos_rows):
    """First-token sampling packed as ONE [n, 2] f32 fetch (tok, logprob)."""
    toks, lps = warp_sample(
        logits, rng, temps, top_ps, top_ks, greedy_mask, forbid_rows, eos_rows
    )
    return jnp.stack([toks.astype(jnp.float32), lps], axis=1)


class ServingEngine:
    """Slot-pool continuous-batching engine driven by a background thread."""

    def __init__(
        self,
        cfg: TransformerConfig,
        params,
        max_batch_size: int = 8,
        max_seq_len: int = 2048,
        decode_block_steps: int = 16,
        prompt_bucket: int = 64,
        eos_token_id: Optional[int] = None,
        seed: int = 1,
        page_size: int = 128,
        kv_pool_tokens: Optional[int] = None,
        mesh=None,
        attn_impl: str = "auto",
        prefill_max_batch: int = 8,
        prefill_chunk: Optional[int] = None,
        chunked_prefill_per_lap: int = 2,
        prefix_cache_tokens: Optional[int] = None,
        kv_cache_dtype: Optional[str] = None,
        speculative_draft_len: int = 0,
        speculative_ngram: int = 2,
        speculative_window: Optional[int] = None,
        decode_weight_dtype: Optional[str] = None,
        prefill_token_budget: Optional[int] = None,
        decode_blocks_per_admit: int = 1,
        kv_tier_bytes: Optional[int] = None,
        kv_tier_disk_dir: Optional[str] = None,
        kv_tier_disk_bytes: Optional[int] = None,
        kv_spill_dtype: Optional[str] = None,
        decode_resident: Optional[bool] = None,
    ):
        self.cfg = cfg
        # Pin AREAL_CE_CHUNK / AREAL_SPLASH_* now: retraces mid-run must
        # not mix tuning settings, and bad values must fail at init.
        from areal_tpu.ops import snapshot_env_tuning

        snapshot_env_tuning()
        # Sampled token ids round-trip through float32 in the packed
        # single-fetch decode result (paged.py); exact only below 2^24.
        assert cfg.vocab_size < 2**24, (
            f"vocab_size {cfg.vocab_size} >= 2^24 would corrupt token ids "
            "in the packed float32 decode fetch"
        )
        self.mesh = mesh
        if mesh is not None:
            from areal_tpu.parallel.sharding import shard_params

            params = shard_params(params, mesh)
        self.params = params
        self.B = max_batch_size
        self.page_size = page_size
        self.max_pages = pages_needed(max_seq_len, page_size)
        self.S = self.max_pages * page_size
        self.block_steps = decode_block_steps
        self.prompt_bucket = prompt_bucket
        self.prefill_max_batch = prefill_max_batch
        # Prompts longer than this prefill chunk-by-chunk through ONE
        # fixed-shape program (paged.paged_chunk_prefill) instead of the
        # per-length-bucket batched path — essential at 16-32k contexts
        # where every new bucket is a fresh multi-second XLA compile.
        assert prefill_chunk is None or prefill_chunk > 0, (
            f"prefill_chunk must be a positive chunk size or None, "
            f"got {prefill_chunk}"
        )
        self.prefill_chunk = prefill_chunk
        assert chunked_prefill_per_lap >= 1, (
            f"chunked_prefill_per_lap must be >= 1, got "
            f"{chunked_prefill_per_lap}"
        )
        self.chunked_prefill_per_lap = chunked_prefill_per_lap
        # Token-budget continuous batching: each admission round admits
        # new prompts only while their UNCACHED prefill tokens fit this
        # budget (the first candidate always admits, so one oversized
        # prompt can't starve). Bounds the prefill work interleaved into
        # a scheduler iteration — the knob that trades TTFT for decode
        # latency (ITL) under load. None = unbounded (legacy behavior).
        assert prefill_token_budget is None or prefill_token_budget >= 1, (
            f"prefill_token_budget must be >= 1 or None, got "
            f"{prefill_token_budget}"
        )
        self.prefill_token_budget = prefill_token_budget
        # Prefill/decode interleave ratio: run this many decode blocks
        # between admission rounds (1 = admit every lap). Raising it
        # favors running requests' ITL over queued requests' TTFT.
        assert decode_blocks_per_admit >= 1, (
            f"decode_blocks_per_admit must be >= 1, got "
            f"{decode_blocks_per_admit}"
        )
        self.decode_blocks_per_admit = decode_blocks_per_admit
        # First lap always admits (counter starts saturated).
        self._blocks_since_admit = decode_blocks_per_admit
        # qid-keyed prefix KV reuse (the radix-cache role of the
        # reference's serving backend): finished/interrupted requests
        # park their pages here; a resubmission with the same qid whose
        # prompt extends the cached tokens prefills only the delta
        # (partial rollouts resubmit prompt+generated with one qid per
        # sample, system/partial_rollout.py:88 — the whole-prefix
        # recompute was their dominant cost). Budget-bounded in tokens;
        # evicted LRU-first under any pool pressure; flushed on weight
        # swaps (old-weight KV is invalid). None disables.
        assert prefix_cache_tokens is None or prefix_cache_tokens >= 0
        self.prefix_cache_tokens = prefix_cache_tokens or 0
        self._prefix_cache: "collections.OrderedDict[str, Tuple[List[int], List[int]]]" = (
            collections.OrderedDict()
        )
        self._cached_tokens = 0
        self.prefix_cache_hits = 0
        self.prefix_tokens_reused = 0
        # Cumulative admissions: fleet hit-rate denominator (the manager
        # aggregates sum(hits)/sum(requests) across servers).
        self.total_requests = 0
        self.eos_token_id = eos_token_id
        self.attn_impl = attn_impl
        self.version = 0

        # KV pool precision: None/"model" stores the compute dtype;
        # "int8" stores (data, scales) pairs — half the decode-side HBM
        # traffic and double the tokens a pool budget holds (paged.py
        # "int8 KV pools"). AREAL_KV_CACHE_DTYPE flips the default so
        # bench/probe A/Bs need no plumbing.
        if kv_cache_dtype is None:
            kv_cache_dtype = env_registry.get_str("AREAL_KV_CACHE_DTYPE")
        if kv_cache_dtype not in (None, "model", "int8"):
            raise ValueError(
                f"kv_cache_dtype={kv_cache_dtype!r}: expected None, "
                f"'model', or 'int8'"
            )
        self.kv_cache_dtype = kv_cache_dtype
        # N-gram (prompt-lookup) speculative decoding (engine/
        # spec_decode.py): draft_len > 0 feeds 1+draft_len rows per slot
        # per step and keeps the verified prefix — lossless (greedy
        # bit-identical; sampled distribution-exact) and device-resident.
        if speculative_draft_len == 0:
            # A/B hook, like AREAL_KV_CACHE_DTYPE: flips the default
            # without plumbing (bench/probe runs). Empty string == unset.
            speculative_draft_len = env_registry.get_int("AREAL_SPEC_DRAFT")
        assert speculative_draft_len >= 0 and speculative_ngram >= 1, (
            f"bad speculative config: draft_len={speculative_draft_len}, "
            f"ngram={speculative_ngram}"
        )
        self.spec_draft_len = speculative_draft_len
        self.spec_ngram = speculative_ngram
        # Backward search window for the draft lookup (ADVICE r5 #4): the
        # n-gram match otherwise scans all max_seq_len positions per step,
        # so draft cost scales with the CONFIGURED context, not the live
        # one. Default 1k recent tokens — where math-RL repeats live.
        # None = default/env; 0 = unbounded full-history scan.
        if speculative_window is None:
            env_w = env_registry.get_int("AREAL_SPEC_WINDOW")
            speculative_window = env_w if env_w is not None else 1024
        assert speculative_window >= 0, (
            f"speculative_window must be >= 0 (0 = unbounded), got "
            f"{speculative_window}"
        )
        self.spec_window = speculative_window
        # Acceptance telemetry: tokens emitted / (block steps * active
        # slots) — the realized speculation yield.
        self._spec_emitted = 0
        self._spec_steps = 0
        # int8 DECODE weights (W8A16, ops/wquant.py): halves the weight
        # stream per decode step; prefill keeps the bf16 params, so
        # prompt processing is identical to the unquantized engine.
        if decode_weight_dtype is None:
            decode_weight_dtype = env_registry.get_str(
                "AREAL_DECODE_WEIGHT_DTYPE"
            )
        if decode_weight_dtype not in (None, "model", "int8"):
            raise ValueError(
                f"decode_weight_dtype={decode_weight_dtype!r}: expected "
                f"None, 'model', or 'int8'"
            )
        # int8 + TP mesh IS supported: the quantize transform runs under
        # jit on the sharded params, so GSPMD places the scales (absmax
        # reduces axis -2 — an all-reduce max for row-parallel weights,
        # free for column-parallel) and the decode block consumes the
        # (q, s) pairs like any other sharded leaf. Greedy parity vs the
        # unsharded int8 engine is pinned by tests/engine/test_wquant_tp.
        self.decode_weight_dtype = decode_weight_dtype
        self._qparams = None
        self._refresh_qparams()
        # Token history per slot (prompt + emitted; one scratch column
        # for masked scatter writes). int32 [B, S+1]: tiny next to KV.
        self._history = (
            jnp.zeros((max_batch_size, self.S + 1), jnp.int32)
            if speculative_draft_len > 0
            else None
        )
        pool_tokens = kv_pool_tokens or max_batch_size * self.S
        self.n_pages = pages_needed(pool_tokens, page_size) + 1  # + trash
        self._allocator = PageAllocator(self.n_pages)
        self._k_pages = None
        self._v_pages = None

        # Device-resident control state (see module docstring); order
        # matches paged.apply_admits.
        B = self.B
        self._dstate = (
            jnp.zeros((B,), jnp.int32),  # lengths
            jnp.zeros((B,), jnp.int32),  # next_input
            jnp.zeros((B,), bool),  # active
            jnp.zeros((B,), jnp.int32),  # remaining
            jnp.zeros((B,), jnp.int32),  # min_remaining
            jnp.ones((B,), jnp.float32),  # temps
            jnp.ones((B,), jnp.float32),  # top_ps
            jnp.full((B,), -1, jnp.int32),  # top_ks
            jnp.zeros((B,), bool),  # greedy
        )
        self._rng = jax.random.PRNGKey(seed)

        # Device-resident decode dispatch (snapshot knob, A/B-able per
        # engine): page-table edits land as donated per-slot row
        # scatters (paged.update_page_rows) and chunked-prefill control
        # crosses as ONE fused array (paged_chunk_prefill_packed), so
        # between decode blocks only admission/eviction DELTAS pay H2D.
        # False restores the legacy full-table restage + per-scalar
        # staging; greedy-token parity between the modes is pinned in
        # tests/engine/test_decode_resident.py.
        if decode_resident is None:
            decode_resident = env_registry.get_bool("AREAL_DECODE_RESIDENT")
        self.decode_resident = bool(decode_resident)

        # Host mirrors + page bookkeeping.
        self._page_table = np.full((B, self.max_pages), TRASH_PAGE, np.int32)
        self._pt_dirty = True
        # Slots whose page-table row changed since the last device flush
        # (engine-thread only): the resident path stages exactly these
        # rows; _pt_dirty stays the "full restage" flag (init, legacy
        # mode, too-many-dirty fallback).
        self._pt_dirty_slots: set = set()
        self._pt_dev = None
        self._len = np.zeros((B,), np.int64)
        self._pending_deact = np.zeros((B,), bool)

        # Decode-dispatch H2D telemetry (engine-thread writers; metrics()
        # reads the plain ints off-thread like total_generated). Counts
        # every host->device staging on the admit/decode hot path — the
        # per-block evidence the kernel_micro_decode_state A/B banks.
        self.h2d_transfers = 0
        self.h2d_bytes = 0
        self.decode_blocks = 0

        # Decode-time MoE router telemetry: last-block layer-mean drop
        # rate / router entropy from the two extra packed columns the
        # decode block emits for MoE models (zeros for dense models and
        # on the spec-decode path, which keeps its own packed layout).
        self.moe_drop_rate = 0.0
        self.moe_router_entropy = 0.0

        # host-side slot bookkeeping
        self._slot_req: List[Optional[GenRequest]] = [None] * self.B
        self._slot_out: List[List[int]] = [[] for _ in range(self.B)]
        self._slot_lp: List[List[float]] = [[] for _ in range(self.B)]
        self._slot_vstart: List[int] = [0] * self.B
        self._slot_pages: List[List[int]] = [[] for _ in range(self.B)]

        self._queue: "queue.Queue[GenRequest]" = queue.Queue()
        self._backlog: List[GenRequest] = []  # engine-thread only
        # qid -> pending (accepted, not yet admitted) request count:
        # the eviction pin set (_pinned_qids). Updated under _fatal_lock
        # at submit and at backlog pop.
        self._queued_qids: Dict[str, int] = {}
        # Loop-thread command queue (disaggregation handoff): closures
        # that must run between laps because they touch engine-thread
        # state (_prefix_cache, the page allocator, the donated KV pool
        # arrays). Drained at the top of every serve-loop lap.
        self._cmds: "queue.Queue" = queue.Queue()
        # Admit entries (slot, req, plen, pages, cached_use) currently
        # inside _admit_impl — reachable by _fail_all on mid-admit death.
        self._admit_inflight: List[Tuple[int, GenRequest, int, List[int], int]] = []
        self._lock = threading.Lock()
        self._interrupt = threading.Event()
        self._pending_params = None
        self._pending_version: Optional[int] = None
        # Serializes concurrent update_params callers (e.g. a manager
        # retry racing the original request after a flush timeout): an
        # older staging finishing last must not overwrite a newer one,
        # and HBM must never hold three weight copies at once.
        self._stage_lock = threading.Lock()
        # Pinned-version history lives in its OWN namespace, never mixed
        # with self.version: unversioned updates bump self.version too,
        # and comparing a trainer-pinned version against that counter
        # would silently blackhole a genuine update (e.g. unversioned
        # apply bumps live to v10, then the trainer's real v10 arrives
        # and would compare stale).
        self._highest_pinned = -1   # highest pinned version staged (not cancelled)
        self._applied_pinned = -1   # highest pinned version actually applied
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.fatal_error: Optional[BaseException] = None
        self._fatal_lock = threading.Lock()
        # metrics
        self.n_running = 0
        self.n_used_tokens = 0
        # Per-request latency SLO telemetry, recorded on the engine loop:
        # TTFT = submit -> first sampled token; ITL = decode-block wall
        # time amortized over the tokens the block emitted for a slot.
        self.ttft_hist = LatencyHistogram()
        self.itl_hist = LatencyHistogram()
        # Prompt tokens sitting in the queue + backlog (not yet admitted)
        # — the server's admission watermark reads this. Updated under
        # _fatal_lock on submit, on the engine thread at each pop.
        self.queued_prompt_tokens = 0
        self.total_generated = 0
        self.n_preempted = 0
        self.last_weight_swap_s = 0.0
        self.last_weight_stage_s = 0.0
        self.last_weight_cutover_s = 0.0
        # Per-slot wall time of the last token delivery: ITL samples
        # measure now - last_emit (NOT bare decode-block wall), so
        # admission-prefill stalls between blocks — the interference
        # disaggregation removes — show up in the histogram.
        self._slot_emit_t = [0.0] * self.B
        # Off-thread telemetry snapshots of loop-only state, refreshed
        # once per serve-loop lap (and by _fail_all): queue_depth and
        # metrics() are polled from the server/manager threads, and
        # len(self._backlog) / self._allocator.n_free there were
        # unlocked reads of engine-thread state (areal-lint loop-only).
        # One-lap staleness is fine for an admission watermark; plain
        # int stores are atomic under the GIL.
        self._backlog_len = 0
        self._kv_pages_free = self._allocator.n_free
        # Disaggregated-serving handoff telemetry.
        self.kv_exports = 0
        self.kv_export_bytes = 0
        self.last_kv_export_ms = 0.0
        self.kv_imports = 0
        self.kv_import_bytes = 0
        self.last_kv_import_ms = 0.0

        # Tiered KV plane (engine/kv_tier.py, docs/serving.md): prefix
        # evictions SPILL to a host-RAM (+ optional disk) tier in the
        # handoff wire format instead of being freed; a returning
        # session restores through the import scatter path instead of
        # paying a full re-prefill. The gather is dispatched ON the
        # loop thread (pool arrays are donated by the decode block),
        # but the device fetch + hashing + quantize run on a dedicated
        # spill thread — the PR 10 blocking-async discipline applied to
        # the serve loop itself.
        if kv_tier_bytes is None:
            kv_tier_bytes = env_registry.get_int("AREAL_KV_TIER_BYTES")
        if kv_tier_disk_dir is None:
            kv_tier_disk_dir = env_registry.get_str("AREAL_KV_TIER_DISK_DIR")
        if kv_tier_disk_bytes is None:
            kv_tier_disk_bytes = env_registry.get_int(
                "AREAL_KV_TIER_DISK_BYTES"
            )
        if kv_spill_dtype is None:
            kv_spill_dtype = env_registry.get_str("AREAL_KV_SPILL_DTYPE")
        if kv_spill_dtype not in (None, "model", "int8", "fp8"):
            raise ValueError(
                f"kv_spill_dtype={kv_spill_dtype!r}: expected None, "
                f"'model', 'int8', or 'fp8'"
            )
        self.kv_spill_dtype = (
            None if kv_spill_dtype == "model" else kv_spill_dtype
        )
        self.kv_tier = None
        if kv_tier_bytes and int(kv_tier_bytes) > 0:
            from areal_tpu.engine.kv_tier import KVTierStore

            self.kv_tier = KVTierStore(
                int(kv_tier_bytes),
                disk_dir=kv_tier_disk_dir,
                disk_capacity_bytes=int(kv_tier_disk_bytes or (1 << 30)),
            )
        # Bounded: each item pins one gathered-KV device array pair
        # until the spill thread drains it; overflow drops the spill
        # (counted as prefix loss) rather than holding device memory.
        self._spill_q: "queue.Queue" = queue.Queue(maxsize=64)
        self._spill_thread: Optional[threading.Thread] = None
        # Weight-swap tier flush, executed BY the spill thread: the
        # clear does per-entry disk unlinks under the store lock —
        # work the serve loop must never pay mid-swap.
        self._tier_clear = threading.Event()
        self.kv_spills = 0          # spill thread
        self.kv_spill_bytes = 0     # spill thread
        self.kv_spill_tokens = 0    # spill thread
        self.kv_restores = 0        # restore callers (server executor)
        self.kv_restore_host = 0
        self.kv_restore_disk = 0
        self.kv_restore_tokens = 0
        # Residual TRUE prefix loss (ISSUE 11 satellite): pages freed
        # while their KV was still valid and could not be spilled —
        # tier disabled, spill queue overflow, or a spill-thread
        # failure. Split per writer thread so the increments never
        # race; /metrics exposes the sum as kv_prefix_lost_total.
        self._kv_lost_evict = 0     # engine loop
        self._kv_lost_spill = 0     # spill thread
        # Off-thread snapshot of the parked-prefix qids (loop-only
        # _prefix_cache must never be read from server threads; the
        # loop refreshes this dict wholesale every ~0.2s — same pattern
        # as _backlog_len / _kv_pages_free).
        self._parked_qids: Dict[str, int] = {}
        self._parked_snap_t = 0.0

    # ------------------------------------------------------------------
    # Public API
    # ------------------------------------------------------------------

    def start(self):
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        if self.kv_tier is not None:
            self._spill_thread = threading.Thread(
                target=self._spill_worker, daemon=True
            )
            self._spill_thread.start()

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=10)
        if self._spill_thread:
            # Best-effort wake only: the worker polls with a short get
            # timeout, and a blocking put on a full queue with a
            # stopped consumer would deadlock shutdown.
            try:
                self._spill_q.put_nowait(None)
            except queue.Full:
                pass
            self._spill_thread.join(timeout=10)

    def submit(self, req: GenRequest):
        # _fatal_lock closes the submit-vs-_fail_all race: without it a
        # request enqueued between the fatal check and the queue drain
        # would sit in the dead queue with no one to fire its done_cb.
        with self._fatal_lock:
            if self.fatal_error is not None:
                raise RuntimeError(
                    f"serving engine loop died: {self.fatal_error!r}"
                ) from self.fatal_error
            req.submit_time = time.monotonic()
            self.total_requests += 1
            self.queued_prompt_tokens += len(req.input_ids)
            self._queued_qids[req.qid] = (
                self._queued_qids.get(req.qid, 0) + 1
            )
            self._queue.put(req)

    def warm(
        self,
        prompt_lens: List[int],
        max_new_tokens: Optional[int] = None,
        timeout_s: float = 1800.0,
    ) -> float:
        """AOT warm hook: compile every program serving these prompt
        lengths needs — the bucketed (or chunked) prefill, the jitted
        decode block, first-token sampling — by running one throwaway
        greedy request per length through the live loop. Serving has no
        trainable state, so executing is the honest way to cover the
        whole dispatch surface; with a persistent compilation cache the
        XLA work outlives this process (the bench compile pass banks it,
        production servers use `warm_on_start` to pre-compile before
        registering for traffic). Returns seconds spent.

        Must be called after start(). Raises on timeout — a warm that
        cannot finish means the engine cannot serve."""
        assert self._thread is not None, "warm() requires start()"
        if max_new_tokens is None:
            max_new_tokens = 2 * self.block_steps
        done = threading.Event()
        got: List[GenResult] = []
        n = len(prompt_lens)

        def cb(res):
            got.append(res)
            if len(got) == n:
                done.set()

        t0 = time.perf_counter()
        for i, plen in enumerate(prompt_lens):
            # Token 1 everywhere: content is irrelevant, shapes compile.
            self.submit(GenRequest(
                qid=f"__warm{i}",
                input_ids=[1] * max(1, int(plen)),
                max_new_tokens=max_new_tokens,
                min_new_tokens=max_new_tokens,  # don't let EOS cut the
                greedy=True,                    # decode block short
                done_cb=cb,
            ))
        if not done.wait(timeout_s):
            raise TimeoutError(
                f"serving warm stalled: {len(got)}/{n} within {timeout_s:.0f}s"
            )
        errs = [r.error for r in got if r.error]
        if errs:
            raise RuntimeError(f"serving warm failed: {errs[0]}")
        dt = time.perf_counter() - t0
        logger.info(f"serving warm: {n} request(s), {dt:.1f}s")
        return dt

    # ------------------------------------------------------------------
    # Disaggregated prefill/decode: KV-handoff export/import
    # ------------------------------------------------------------------

    def _run_on_loop(self, fn, timeout_s: float = 60.0):
        """Run ``fn()`` on the engine loop thread between laps and return
        its result. Engine-thread state (_prefix_cache, the allocator,
        the donated pool arrays) has no locks by design — the loop owns
        it; this is the one cross-thread door."""
        if threading.current_thread() is self._thread:
            return fn()
        done = threading.Event()
        cell: Dict[str, Any] = {}
        self._cmds.put((fn, done, cell))
        deadline = time.monotonic() + timeout_s
        while not done.wait(0.05):
            if self.fatal_error is not None:
                raise RuntimeError(
                    f"serving engine loop died: {self.fatal_error!r}"
                ) from self.fatal_error
            if (
                self._thread is None
                or not self._thread.is_alive()
                or self._stop.is_set()
            ):
                raise RuntimeError("serving engine loop is not running")
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"engine-loop command not served within {timeout_s}s"
                )
        if "exc" in cell:
            raise cell["exc"]
        return cell.get("ret")

    def _drain_cmds(self):
        while True:
            try:
                fn, done, cell = self._cmds.get_nowait()
            except queue.Empty:
                return
            try:
                cell["ret"] = fn()
            except BaseException as e:  # delivered to the waiting caller
                cell["exc"] = e
            finally:
                done.set()

    def export_kv_handoff(
        self, qid: str, compress: Optional[str] = None
    ) -> Tuple[Dict[str, Any], bytes]:
        """Export the parked KV prefix for ``qid`` as a versioned
        handoff blob (meta, payload) — the prefill side of disaggregated
        serving (engine/kv_handoff.py wire format).

        The entry is consumed: its pages transfer to the blob and are
        freed here (the decode pool owns the sequence now). Raises
        KeyError when ``qid`` holds no parked prefix (the request never
        finished, pool pressure evicted it, or the prompt was shorter
        than one page — callers fall back to serving locally).
        ``compress="int8"`` quantizes a float pool's KV on the wire
        (quantize_kv) and ``compress="fp8"`` onto the e4m3 wire
        (kv_handoff.quantize_kv_fp8); int8 pools always ship their
        (data, scales) form.
        """
        from areal_tpu.engine import kv_handoff as kvh
        from areal_tpu.engine.paged import gather_kv_tokens

        t0 = time.monotonic()

        def _peek_and_gather():
            # PEEK, don't pop: if the caller's loop-door wait times out,
            # the entry (and its pages) stay owned by the cache — a
            # popping closure executed after the caller abandoned it
            # would leak the pages forever (nobody left to free them).
            ent = self._prefix_cache.get(qid)
            if ent is None:
                raise KeyError(f"no parked KV prefix for qid {qid!r}")
            toks, pages = ent
            n = len(toks)
            n_pg = pages_needed(n, self.page_size)
            # Dispatch the gather HERE, on the loop thread: the decode
            # block donates the pool arrays, so a stale off-thread
            # reference could point at a freed buffer. The gathered
            # slices are fresh arrays, safe to device_get off-loop.
            k = gather_kv_tokens(self._k_pages, pages[:n_pg], n)
            v = gather_kv_tokens(self._v_pages, pages[:n_pg], n)
            return ent, toks, pages, self.version, k, v

        def _consume(ent):
            # Self-contained pop+free (identity-checked: an admission
            # may have consumed the entry meanwhile — ownership moved,
            # nothing to free here). Safe to run arbitrarily late.
            cur = self._prefix_cache.get(qid)
            if cur is ent:
                self._prefix_cache.pop(qid, None)
                self._cached_tokens -= len(ent[0])
                self._allocator.free(ent[1])

        try:
            ent, toks, pages, version, k, v = self._run_on_loop(
                _peek_and_gather
            )
        except KeyError:
            # Pool pressure spilled the park to the host tier: serve the
            # blob from there — the tier makes the old evicted-before-
            # export silent-loss window a served export instead. The
            # entry is consumed, like the HBM pop (the decode side owns
            # the sequence now).
            got = (
                self.kv_tier.get(qid, count=False)
                if self.kv_tier is not None else None
            )
            if got is None:
                raise
            meta, payload, _tier = got
            self.kv_tier.discard(qid)
            self.kv_exports += 1
            self.kv_export_bytes += len(payload)
            self.last_kv_export_ms = (time.monotonic() - t0) * 1000.0
            return meta, payload
        try:
            arrays, wire = self._pack_kv_wire(k, v, compress)
            segments, chunks, payload = kvh.pack_arrays(arrays)
            meta = kvh.build_meta(
                qid, version, toks, wire, self.cfg, segments, chunks
            )
        finally:
            self._run_on_loop(lambda: _consume(ent))
        self.kv_exports += 1
        self.kv_export_bytes += len(payload)
        self.last_kv_export_ms = (time.monotonic() - t0) * 1000.0
        return meta, payload

    def import_kv_handoff(self, meta: Dict[str, Any], payload: bytes):
        """Import a handoff blob: allocate pages, scatter the KV into the
        pool, park it as ``qid``'s prefix — the decode side. The caller
        then submits the continuation request (prompt + first token,
        priority 0); admission finds the parked prefix and prefills only
        the one-token delta.

        Raises KVHandoffVersionMismatch when the blob's weight version
        differs from the live engine's (checked ON the loop thread,
        atomically with the park, so a concurrent weight swap can never
        leave stale KV parked), and KVHandoffError on geometry/hash
        problems or pool exhaustion."""
        from areal_tpu.engine import kv_handoff as kvh
        from areal_tpu.engine.paged import scatter_prefill_int8

        t0 = time.monotonic()
        kvh.check_geometry(meta, self.cfg)
        qid = str(meta["qid"])
        toks = [int(t) for t in meta["tokens"]]
        n = len(toks)
        n_pg = pages_needed(n, self.page_size)
        pad = n_pg * self.page_size

        if meta["kv_wire"] == "int8" and self.kv_cache_dtype == "int8":
            # int8-preserving fast path (ISSUE 11 satellite): the wire's
            # (data, scales) pairs ARE an int8 pool's encoding, so they
            # scatter straight in — no dequantize→re-quantize round
            # trip (a spill + restore is bit-exact) and a quarter the
            # staged host/transfer bytes of the float path.
            kd, ks, vd, vs = kvh.unpack_kv_int8(meta, payload)
            if n != int(meta["n_tokens"]) or kd.shape[2] != n:
                raise kvh.KVHandoffError(
                    f"token/KV length mismatch: {n} tokens, KV {kd.shape}"
                )

            def pad_d(x):
                L, H, _, hd = x.shape
                out = np.zeros((L, H, pad, hd), x.dtype)
                out[:, :, :n] = x
                return out

            def pad_s(s):
                L, H, _ = s.shape
                out = np.zeros((L, H, pad), np.float32)
                out[:, :, :n] = s
                return out

            kd_dev, ks_dev = jnp.asarray(pad_d(kd)), jnp.asarray(pad_s(ks))
            vd_dev, vs_dev = jnp.asarray(pad_d(vd)), jnp.asarray(pad_s(vs))

            # Pools in, pools out: the loop-only attr writes stay inside
            # the door-passed _write below (areal-lint loop-only).
            def scatter(k_pages, v_pages, pages_dev):
                return scatter_prefill_int8(
                    k_pages, v_pages,
                    kd_dev, ks_dev, vd_dev, vs_dev, pages_dev,
                )
        else:
            kf, vf = kvh.unpack_kv_float(meta, payload)  # [L, Hkv, n, hd]
            if n != int(meta["n_tokens"]) or kf.shape[2] != n:
                raise kvh.KVHandoffError(
                    f"token/KV length mismatch: {n} tokens, KV {kf.shape}"
                )

            def to_pref(x):
                # [L, Hkv, n, hd] -> scatter_prefill's [L, 1, pad, Hkv, hd]
                L, H, _, hd = x.shape
                out = np.zeros((L, 1, pad, H, hd), np.float32)
                out[:, 0, :n] = x.transpose(0, 2, 1, 3)
                return out

            # Stage the (small) host->device transfers off the loop
            # thread; only the scatter dispatch runs on it.
            k_dev = jnp.asarray(to_pref(kf))
            v_dev = jnp.asarray(to_pref(vf))

            def scatter(k_pages, v_pages, pages_dev):
                return scatter_prefill(
                    k_pages, v_pages, k_dev, v_dev, pages_dev,
                )

        def _write():
            if int(meta["version"]) != self.version:
                raise kvh.KVHandoffVersionMismatch(
                    f"blob v{meta['version']} vs engine v{self.version}"
                )
            self._ensure_pool()
            pages = self._alloc_pages(n_pg)
            if pages is None:
                raise kvh.KVHandoffError(
                    f"pool exhausted: need {n_pg} pages, "
                    f"{self._allocator.n_free} free"
                )
            self._k_pages, self._v_pages = scatter(
                self._k_pages, self._v_pages, jnp.asarray(pages, jnp.int32)
            )
            old = self._prefix_cache.pop(qid, None)
            if old is not None:
                self._allocator.free(old[1])
                self._cached_tokens -= len(old[0])
            self._prefix_cache[qid] = (toks, pages)
            self._cached_tokens += n

        self._run_on_loop(_write)
        self.kv_imports += 1
        self.kv_import_bytes += len(payload)
        self.last_kv_import_ms = (time.monotonic() - t0) * 1000.0

    def is_stale_update(self, version: Optional[int]) -> bool:
        """True iff update_params(version=version) would drop the update
        as stale. Lets callers skip the (potentially multi-GB) weight
        load on a retry of a version that already landed."""
        if version is None:
            return False
        with self._stage_lock:
            return version <= self._highest_pinned

    def escalate_pending_interrupt(self):
        """Interrupt running requests iff a staged update is waiting to
        apply — the allow_interrupt side of a retry whose reload was
        skipped as stale (see is_stale_update). A bare interrupt with
        nothing pending would kill running requests for nothing."""
        with self._lock:
            if self._pending_params is not None:
                self._interrupt.set()

    def update_params(self, params, allow_interrupt: bool = True,
                      version: Optional[int] = None):
        """Swap weights at the next block boundary. With allow_interrupt,
        running requests are interrupted and returned partially (the AReaL
        protocol); without it, admission pauses and the swap happens once
        running requests drain. `version` pins the new weight version to
        the trainer's published one (self-incrementing would drift when
        the trainer publishes faster than the manager flushes).

        The host->device transfer is staged HERE, on the caller's
        thread, so decoding continues while the weights stream in; the
        serve loop's swap is then just a pointer flip + sync. Peak HBM
        holds two weight copies during staging (live + staged) — same
        as the old swap-time peak, just for longer. Staging seconds
        (dispatch + transfer completion) land in last_weight_stage_s.

        Concurrent callers (manager retry after a flush timeout) are
        serialized under _stage_lock, and a pinned update that is not
        newer than the highest pinned version already staged (and not
        since cancelled) is dropped — an older staging finishing last
        must never overwrite newer weights with stale ones. Unversioned
        updates are never dropped and never consume a pinned version."""

        def build():
            if self.mesh is not None:
                from areal_tpu.parallel.sharding import shard_params

                return shard_params(params, self.mesh)
            return jax.tree_util.tree_map(jnp.asarray, params)

        self._stage_update(build, allow_interrupt, version)

    def _stage_update(self, build, allow_interrupt: bool,
                      version: Optional[int]):
        """Shared staging machinery behind update_params /
        stage_shard_leaves: version gating, pending-copy eviction, the
        host->device transfer via ``build()`` (returns the staged device
        tree), and the pending-params publish + optional interrupt."""
        with self._stage_lock:
            if version is not None and version <= self._highest_pinned:
                logger.info(
                    f"dropping stale weight update v{version} "
                    f"(highest pinned v{self._highest_pinned}, "
                    f"live v{self.version})"
                )
                # Still honor interrupt escalation: a retry of a version
                # staged with allow_interrupt=False may be the manager
                # asking to stop waiting for the drain. The helper takes
                # _lock so the pending check-and-set is atomic against
                # _apply_pending_params' pop — a bare interrupt with
                # nothing pending would kill running requests for
                # nothing.
                if allow_interrupt:
                    self.escalate_pending_interrupt()
                return
            with self._lock:
                # A faster publisher must not stack staged copies: drop
                # any not-yet-applied pending weights BEFORE staging, or
                # HBM would briefly hold three copies (live + old staged
                # + new). A cancelled pinned staging never went live, so
                # its version must not block a later retry of the same
                # version (roll back to the last APPLIED pinned version;
                # _apply_pending_params removes pending under this same
                # lock, so a concurrently-applying update is never
                # rolled back here).
                if (
                    self._pending_params is not None
                    and self._pending_version is not None
                ):
                    self._highest_pinned = self._applied_pinned
                self._pending_params = None
                self._pending_version = None
            t0 = time.monotonic()
            staged = build()
            # Bound transfer completion (safe here: we're off the serve
            # loop): block_until_ready doesn't wait on tunneled devices,
            # so fetch one element of the last-dispatched leaf instead.
            jax.block_until_ready(staged)
            last_leaf = jax.tree_util.tree_leaves(staged)[-1]
            jax.device_get(last_leaf.ravel()[:1])
            self.last_weight_stage_s = time.monotonic() - t0
            with self._lock:
                self._pending_params = staged
                self._pending_version = version
                if version is not None:
                    self._highest_pinned = max(self._highest_pinned, version)
        if allow_interrupt:
            self._interrupt.set()

    def cutover_params(
        self,
        params,
        version: int,
        allow_interrupt: bool = True,
        timeout_s: float = 120.0,
    ) -> float:
        """Weight-plane cutover hook: swap to `params` (pinned to
        `version`) and BLOCK until the serve loop has landed it — the
        full interrupt -> device-transfer -> pointer-flip window, end to
        end. This is the number the distribution plane bounds separately
        from network transfer time: the bytes were already prefetched to
        host memory, so everything timed here is cutover cost (running
        requests interrupted via the pending-update escalation path and
        returned partial for client-side re-prefill).

        Returns seconds; recorded as ``last_weight_cutover_s``. Raises
        TimeoutError if the version never lands (serve loop dead)."""
        t0 = time.monotonic()
        self.update_params(
            params, allow_interrupt=allow_interrupt, version=int(version)
        )
        return self._await_pinned(int(version), t0, timeout_s)

    def _await_pinned(self, version: int, t0: float,
                      timeout_s: float) -> float:
        deadline = t0 + timeout_s
        while self._applied_pinned < version:
            if self.fatal_error is not None:
                raise RuntimeError(
                    f"cutover v{version}: serve loop died: "
                    f"{self.fatal_error!r}"
                ) from self.fatal_error
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"cutover v{version} did not land within {timeout_s}s "
                    f"(live v{self.version})"
                )
            time.sleep(0.002)
        self.last_weight_cutover_s = time.monotonic() - t0
        return self.last_weight_cutover_s

    # -- shard-aware cutover (the weight plane's sliced-manifest path) --

    def _addressable_axis_coords(self, axis: str) -> Dict[Any, int]:
        """{device: ``axis`` coordinate} for this PROCESS's devices.
        Under multi-host sharding each process sees only its own mesh
        slice (so it needs only its own ranks' shard leaves);
        single-process meshes see every coordinate."""
        coords: Dict[Any, int] = {}
        t_ax = list(self.mesh.axis_names).index(axis)
        local = {d.id for d in jax.local_devices()}
        for idx, dev in np.ndenumerate(self.mesh.devices):
            if dev.id in local:
                coords[dev] = int(idx[t_ax])
        return coords

    def _build_from_shard_leaves(self, leaves_by_rank, degree: int,
                                 global_shapes=None, axis: str = "tensor"):
        """Staged device tree from per-rank HOST shard leaves (flat
        {path: local ndarray} per shard rank, e.g. assemble_leaves of
        shard-manifest ChunkStores): each addressable device gets its
        rank's slab via device_put, then the global arrays form through
        jax.make_array_from_single_device_arrays under the engine's own
        NamedSharding. No model-sized host buffer and no resharding
        copy ever exists — the sliced wire bytes ARE the device shards.

        ``axis`` is the mesh axis the ranks shard: "tensor" (TP-sliced
        streams) or "fsdp" (expert-sliced streams — the EP stream ships
        each rank only its experts, with non-expert leaves replicated;
        a replicated slab that the serving mesh nonetheless shards gets
        sliced down host-side to the device's window)."""
        from jax.sharding import NamedSharding

        from areal_tpu.parallel.sharding import fitted_param_spec
        from areal_tpu.system.weight_transfer import unflatten_leaves

        mesh = self.mesh
        if mesh is None:
            raise ValueError(
                "shard-leaves cutover needs a mesh-sharded engine"
            )
        if axis not in mesh.shape:
            raise ValueError(f"mesh has no axis {axis!r}")
        t_size = mesh.shape.get(axis, 1)
        if degree != t_size:
            raise ValueError(
                f"shard degree {degree} != mesh {axis} size {t_size}"
            )
        for ax, size in mesh.shape.items():
            if ax != axis and size != 1:
                raise ValueError(
                    f"shard-leaves cutover supports single-axis meshes; "
                    f"axis {ax!r} has size {size}"
                )
        if axis != "tensor" and global_shapes is None:
            # TP shapes are inferrable (every fitted-tensor dim scales
            # by degree); an EP stream mixes sliced expert leaves with
            # replicated ones, so only the manifest's recorded global
            # shapes disambiguate.
            raise ValueError(
                f"shard-leaves cutover over {axis!r} needs global_shapes"
            )
        coords = self._addressable_axis_coords(axis)
        missing = sorted(
            {t for t in coords.values()} - set(leaves_by_rank)
        )
        if missing:
            raise ValueError(
                f"missing shard leaves for addressable tensor ranks "
                f"{missing}"
            )
        any_rank = next(iter(leaves_by_rank))
        paths = sorted(leaves_by_rank[any_rank])
        sizes = dict(mesh.shape)
        flat = {}
        for path in paths:
            local0 = leaves_by_rank[any_rank][path]
            if global_shapes is not None and path in global_shapes:
                # Shard manifests record each leaf's global shape —
                # authoritative (no inference edge cases on tiny dims).
                gshape = list(global_shapes[path])
            else:
                if axis != "tensor":
                    raise ValueError(
                        f"{path}: global shape required for "
                        f"{axis!r}-sharded leaves"
                    )
                # Infer: local shapes agree with the global on every dim
                # except those the fitted spec shards on 'tensor', which
                # concatenate across ranks. Fit against the local shape,
                # scale the tensor-sharded dims, then re-fit against the
                # recovered global shape.
                gshape = list(local0.shape)
                spec = fitted_param_spec(path, gshape, sizes)
                entries = list(spec) + [None] * (len(gshape) - len(spec))
                for i, entry in enumerate(entries):
                    names = (
                        entry if isinstance(entry, tuple)
                        else (entry,) if entry else ()
                    )
                    if "tensor" in names:
                        gshape[i] *= t_size
            spec = fitted_param_spec(path, gshape, sizes)
            sharding = NamedSharding(mesh, spec)
            idx_map = sharding.devices_indices_map(tuple(gshape))
            shards = []
            for dev, t in coords.items():
                local = leaves_by_rank[t][path]
                want = tuple(
                    (sl.stop if sl.stop is not None else dim)
                    - (sl.start or 0)
                    for sl, dim in zip(idx_map[dev], gshape)
                )
                if tuple(local.shape) != want:
                    if tuple(local.shape) == tuple(gshape):
                        # The stream replicated this leaf (e.g. an EP
                        # stream's attention weights) but the serving
                        # mesh shards it: take the device's window.
                        local = local[idx_map[dev]]
                    else:
                        raise ValueError(
                            f"{path}: rank-{t} shard shape {local.shape}"
                            f" != device shard {want} "
                            f"(global {tuple(gshape)})"
                        )
                shards.append(jax.device_put(local, dev))
            flat[path] = jax.make_array_from_single_device_arrays(
                tuple(gshape), sharding, shards
            )
        return unflatten_leaves(flat)

    def stage_shard_leaves(self, leaves_by_rank, degree: int,
                           version: Optional[int] = None,
                           allow_interrupt: bool = True,
                           global_shapes=None, axis: str = "tensor"):
        """update_params for pre-sliced host shards (see
        _build_from_shard_leaves)."""
        self._stage_update(
            lambda: self._build_from_shard_leaves(
                leaves_by_rank, degree, global_shapes, axis=axis
            ),
            allow_interrupt, version,
        )

    def cutover_shard_leaves(
        self, leaves_by_rank, degree: int, version: int,
        allow_interrupt: bool = True, timeout_s: float = 120.0,
        global_shapes=None, axis: str = "tensor",
    ) -> float:
        """cutover_params for pre-sliced host shards: stage each rank's
        slabs straight onto its devices, then block until the serve
        loop lands the version. ``axis="fsdp"`` lands expert-sliced
        (EP) streams on an expert-parallel serving mesh."""
        t0 = time.monotonic()
        self.stage_shard_leaves(
            leaves_by_rank, degree, version=int(version),
            allow_interrupt=allow_interrupt, global_shapes=global_shapes,
            axis=axis,
        )
        return self._await_pinned(int(version), t0, timeout_s)

    @property
    def queue_depth(self) -> int:
        """Requests accepted but not yet admitted to a slot. Uses the
        loop-maintained backlog-length snapshot (loop-only contract)."""
        return self._queue.qsize() + self._backlog_len

    def latency_snapshot(self, reset: bool = False) -> Dict[str, Any]:
        """Raw TTFT/ITL bucket counts (areal_tpu.base.latency edges) +
        percentiles; reset=True zeroes the histograms (the open-loop
        bench reads one snapshot per sweep point)."""
        from areal_tpu.base.latency import percentile_from_counts

        ttft = self.ttft_hist.counts(reset=reset)
        itl = self.itl_hist.counts(reset=reset)
        return {
            "ttft_counts": ttft,
            "itl_counts": itl,
            "ttft_p50_ms": percentile_from_counts(ttft, 50.0),
            "ttft_p99_ms": percentile_from_counts(ttft, 99.0),
            "itl_p50_ms": percentile_from_counts(itl, 50.0),
            "itl_p99_ms": percentile_from_counts(itl, 99.0),
        }

    def metrics(self) -> Dict[str, float]:
        return {
            "num_running_reqs": float(self.n_running),
            "num_used_tokens": float(self.n_used_tokens),
            "total_generated": float(self.total_generated),
            "queue_depth": float(self.queue_depth),
            "queued_prompt_tokens": float(self.queued_prompt_tokens),
            "ttft_p50_ms": self.ttft_hist.percentile(50.0),
            "ttft_p99_ms": self.ttft_hist.percentile(99.0),
            "itl_p50_ms": self.itl_hist.percentile(50.0),
            "itl_p99_ms": self.itl_hist.percentile(99.0),
            "ttft_count": float(self.ttft_hist.total()),
            "itl_count": float(self.itl_hist.total()),
            "kv_pages_free": float(self._kv_pages_free),
            "kv_pages_total": float(self.n_pages - 1),
            # Decode-dispatch H2D accounting (device-resident decode
            # state, docs/perf_notes.md Round 15): stagings + bytes on
            # the admit/decode hot path, and the decode-block count they
            # amortize over. The kernel_micro_decode_state A/B banks the
            # per-block ratio resident-vs-legacy.
            "h2d_transfers_total": float(self.h2d_transfers),
            "h2d_bytes_total": float(self.h2d_bytes),
            "decode_blocks_total": float(self.decode_blocks),
            "h2d_per_decode_block": float(self.h2d_transfers)
            / max(1.0, float(self.decode_blocks)),
            "decode_resident": 1.0 if self.decode_resident else 0.0,
            "moe_drop_rate": float(self.moe_drop_rate),
            "moe_router_entropy": float(self.moe_router_entropy),
            "num_preempted_reqs": float(self.n_preempted),
            "last_weight_swap_s": float(self.last_weight_swap_s),
            "last_weight_stage_s": float(self.last_weight_stage_s),
            "last_weight_cutover_s": float(self.last_weight_cutover_s),
            "prefix_cache_hits": float(self.prefix_cache_hits),
            "prefix_tokens_reused": float(self.prefix_tokens_reused),
            "prefix_cached_tokens": float(self._cached_tokens),
            "total_requests": float(self.total_requests),
            # Disaggregated-serving KV handoff (export on prefill-role
            # engines, import on decode-role ones).
            "kv_export_total": float(self.kv_exports),
            "kv_export_bytes": float(self.kv_export_bytes),
            "last_kv_export_ms": float(self.last_kv_export_ms),
            "kv_import_total": float(self.kv_imports),
            "kv_import_bytes": float(self.kv_import_bytes),
            "last_kv_import_ms": float(self.last_kv_import_ms),
            # Tiered KV plane: spill/restore counters + per-tier store
            # telemetry (zeros when the tier is disabled).
            "kv_spill_total": float(self.kv_spills),
            "kv_spill_bytes": float(self.kv_spill_bytes),
            "kv_spill_tokens": float(self.kv_spill_tokens),
            "kv_restore_total": float(self.kv_restores),
            "kv_restore_host": float(self.kv_restore_host),
            "kv_restore_disk": float(self.kv_restore_disk),
            "kv_restore_tokens": float(self.kv_restore_tokens),
            "kv_prefix_lost_total": float(
                self._kv_lost_evict + self._kv_lost_spill
            ),
            **{
                f"kv_tier_{k}": v
                for k, v in (
                    self.kv_tier.stats() if self.kv_tier is not None
                    else {}
                ).items()
            },
            # Speculative decoding yield: emitted tokens per decode STEP
            # across slots that were active (1.0 = no speculation value;
            # the ceiling is 1 + draft_len). The number that decides
            # whether AREAL_SPEC_DRAFT stays on.
            "spec_tokens_per_step": float(
                self._spec_emitted / self._spec_steps
            ) if self._spec_steps else 0.0,
            # Raw numerator/denominator for fleet-level aggregation.
            "spec_emitted_tokens": float(self._spec_emitted),
            "spec_active_steps": float(self._spec_steps),
        }

    # ------------------------------------------------------------------
    # Engine loop
    # ------------------------------------------------------------------

    def _refresh_qparams(self):
        """(Re)build the int8 decode-weight tree from the live params —
        at init and after every weight swap."""
        if self.decode_weight_dtype is None:
            return
        from areal_tpu.ops.wquant import maybe_quantize_decode_weights

        self._qparams = maybe_quantize_decode_weights(
            self.params, self.cfg.tied_embeddings, self.decode_weight_dtype
        )

    @property
    def _decode_params(self):
        """Param tree the DECODE blocks run on (quantized when
        decode_weight_dtype is set); prefill always uses self.params."""
        return self._qparams if self._qparams is not None else self.params

    def _ensure_pool(self):
        if self._k_pages is not None:
            return
        c = self.cfg
        cdt = jnp.dtype(c.compute_dtype)
        shape = (c.n_layers, c.n_kv_heads, self.n_pages, self.page_size,
                 c.head_dim)

        def fresh_pool():
            if self.kv_cache_dtype == "int8":
                # Scales squeezed to [L, Hkv, N, pg]: pg is the lane dim
                # (a trailing size-1 dim would pad 128x under TPU tiled
                # layouts — see paged.py "int8 KV pools").
                return (jnp.zeros(shape, jnp.int8),
                        jnp.zeros(shape[:-1], jnp.float32))
            return jnp.zeros(shape, cdt)

        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            tensor = self.mesh.shape.get("tensor", 1)
            if c.n_kv_heads % tensor == 0:
                spec_d = P(None, "tensor", None, None, None)
                spec_s = P(None, "tensor", None, None)  # squeezed scales
            else:
                spec_d = spec_s = P()

            def put(pool):
                if isinstance(pool, tuple):
                    return (
                        jax.device_put(
                            pool[0], NamedSharding(self.mesh, spec_d)),
                        jax.device_put(
                            pool[1], NamedSharding(self.mesh, spec_s)),
                    )
                return jax.device_put(pool, NamedSharding(self.mesh, spec_d))

            self._k_pages = put(fresh_pool())
            self._v_pages = put(fresh_pool())
        else:
            self._k_pages = fresh_pool()
            self._v_pages = fresh_pool()

    def _free_slots(self) -> List[int]:
        return [i for i in range(self.B) if self._slot_req[i] is None]

    def _drain_queue(self):
        try:
            while True:
                self._backlog.append(self._queue.get_nowait())
        except queue.Empty:
            pass
        # Keep the off-thread snapshot near-live across the queue ->
        # backlog move, so queue_depth doesn't under-report for a lap.
        self._backlog_len = len(self._backlog)

    def _pop_backlog(self, idx: int = 0) -> GenRequest:
        req = self._backlog.pop(idx)
        self._backlog_len = len(self._backlog)
        with self._fatal_lock:
            self.queued_prompt_tokens = max(
                0, self.queued_prompt_tokens - len(req.input_ids)
            )
            n = self._queued_qids.get(req.qid, 0)
            if n > 1:
                self._queued_qids[req.qid] = n - 1
            else:
                self._queued_qids.pop(req.qid, None)
        return req

    # Admission rounds a class-1 request may be passed over before it
    # is promoted to class 0. With more live sessions than slots the
    # continuation stream never dries up, so without aging a fresh
    # request could wait forever behind promoted continuations.
    STARVATION_ROUNDS = 16

    def _effective_priority(self, req: GenRequest) -> int:
        if req.starved_rounds >= self.STARVATION_ROUNDS:
            return 0
        # A parked prefix marks a session continuation regardless of the
        # caller-declared class: its KV is already paid for.
        if req.qid in self._prefix_cache:
            return 0
        return req.priority

    def _order_backlog(self):
        """Priority-aware admission order: continuations / interrupted
        re-prefills (class 0) ahead of fresh requests; FIFO within a
        class (sort is stable). Fresh requests age (counter bumped in
        _admit_impl for requests passed over by an admitting round):
        after STARVATION_ROUNDS they join class 0, so a sustained
        continuation stream cannot starve them."""
        if any(self._effective_priority(r) != 0 for r in self._backlog):
            self._backlog.sort(key=self._effective_priority)

    def _h2d(self, arr) -> jnp.ndarray:
        """jnp.asarray with decode-dispatch H2D accounting (engine
        thread only): every staging on the admit/decode hot path goes
        through here so the per-block transfer counts the decode-state
        A/B banks are measured, not estimated."""
        a = jnp.asarray(arr)
        self.h2d_transfers += 1
        self.h2d_bytes += int(a.nbytes)
        return a

    def _chunked_prefill_one(
        self, input_ids: List[int], pages: List[int], start: int = 0
    ):
        """Prefill one prompt chunk-by-chunk into its allocated pages,
        beginning at position `start` (nonzero for prefix-cache hits:
        positions below `start` already hold valid KV in `pages`).
        Returns the device [V] logits row of the final token (for
        first-token sampling). One compiled program total — chunk size,
        page-table width, and pool shapes are all static. Resident mode
        fuses each chunk's (tokens, start, valid) control into ONE
        staged array; legacy mode keeps the three separate transfers."""
        # Cache-hit deltas run even when chunked prefill is not
        # configured; the prompt bucket doubles as the chunk size then.
        C = self.prefill_chunk or self.prompt_bucket
        self._ensure_pool()
        prow = np.full((self.max_pages,), TRASH_PAGE, np.int32)
        prow[: len(pages)] = pages
        prow_dev = self._h2d(prow)
        last = None
        for s0 in range(start, len(input_ids), C):
            seg = input_ids[s0 : s0 + C]
            valid = len(seg)
            if self.decode_resident:
                ctl = np.zeros((C + 2,), np.int32)
                ctl[:valid] = seg
                ctl[C] = s0
                ctl[C + 1] = valid
                last, self._k_pages, self._v_pages = (
                    paged_chunk_prefill_packed(
                        self.params, self.cfg, self._h2d(ctl),
                        self._k_pages, self._v_pages, prow_dev,
                        attn_impl=self.attn_impl, mesh=self.mesh,
                    )
                )
                continue
            toks = np.zeros((C,), np.int32)
            toks[:valid] = seg
            last, self._k_pages, self._v_pages = paged_chunk_prefill(
                self.params, self.cfg, self._h2d(toks), self._k_pages,
                self._v_pages, prow_dev,
                self._h2d(np.int32(s0)), self._h2d(np.int32(valid)),
                attn_impl=self.attn_impl,
                mesh=self.mesh,
            )
        return last

    def _takes_chunked_path(
        self, req: "GenRequest", plen: int,
        cached_use: Optional[int] = None,
    ) -> bool:
        """Single source of truth for which prompts run the one-at-a-time
        chunked prefill (vs the batched bucketed path): cache hits always
        (only the delta past cached_use needs compute), fresh prompts when
        longer than the configured chunk. With cached_use=None this is the
        pre-validation PREDICTION used by the per-lap admission cap — any
        parked cache entry counts, conservatively, since prefix validation
        happens later; a mispredicted entry just defers to the next lap."""
        if cached_use is None:
            hit = req.qid in self._prefix_cache
        else:
            hit = cached_use > 0
        return hit or bool(self.prefill_chunk and plen > self.prefill_chunk)

    def _admit(self):
        """Fill free slots from the backlog with ONE batched prefill and
        ONE fused device state update. Thin wrapper: the in-flight admit
        batch lives on the engine so _fail_all can reach requests that a
        mid-admit prefill failure (e.g. an XLA compile error) would
        otherwise strand in a dead stack frame."""
        batch = self._admit_inflight
        batch.clear()
        t0 = tracing.now_ns() if tracing.enabled() else 0
        self._admit_impl(batch)
        if batch and tracing.enabled():
            # Generation-busy evidence for the merged RL timeline (the
            # overlap score unions these with decode blocks).
            tracing.record_span(
                "server.prefill", t0, n_prompts=len(batch),
            )
        batch.clear()  # normal completion: requests now live in _slot_req

    def _admit_impl(self, batch):
        # Drain semantics for non-interrupting weight updates: stop
        # admitting so running requests finish and the swap can land.
        # (Before the counter reset: a pending swap must not consume the
        # interleave window — admission retries the lap after it lands.)
        if self._pending_params is not None:
            return
        self._blocks_since_admit = 0
        self._drain_queue()
        self._order_backlog()
        free = self._free_slots()
        # Chunked / cache-hit prefills run one prompt at a time on the
        # serve loop; admitting many long prompts in one lap would stall
        # decode for every running slot for the full sequential prefill.
        # Cap them per lap (the rest stay in the backlog for the next
        # lap, after a decode block has run).
        n_chunked = 0
        # Per-round prefill-token budget (token-budget continuous
        # batching): estimated from the parked prefix BEFORE validation
        # — a misprediction only shifts a prompt to the next round.
        tok_budget = self.prefill_token_budget
        while free and self._backlog and len(batch) < self.prefill_max_batch:
            req = self._backlog[0]
            plen = len(req.input_ids)
            if (
                self._takes_chunked_path(req, plen)
                and n_chunked >= self.chunked_prefill_per_lap
            ):
                break
            est_new = plen
            if tok_budget is not None:
                ent = self._prefix_cache.get(req.qid)
                if ent is not None:
                    est_new = plen - min(len(ent[0]), plen - 1)
                est_new = max(1, est_new)
                # The first admission of a round always proceeds: a
                # single over-budget prompt must not starve forever.
                if batch and est_new > tok_budget:
                    break
            if plen + req.max_new_tokens > self.S:
                req.max_new_tokens = max(0, self.S - plen)
            if plen >= self.S or req.max_new_tokens == 0:
                self._pop_backlog()
                self._finish_host(req, [], [], no_eos=True, interrupted=False,
                                  vstart=self.version)
                continue
            n_need = pages_needed(plen, self.page_size)
            if n_need > self.n_pages - 1:
                # The prompt alone exceeds the ENTIRE pool: no amount of
                # waiting frees enough pages. Reject now — blocking here
                # would stall this request forever and head-of-line-block
                # everything behind it. (Reachable via partial-rollout
                # resubmission growing the prefix past pool capacity.)
                self._pop_backlog()
                logger.warning(
                    f"rejecting {req.qid}: prompt needs {n_need} pages, "
                    f"pool has {self.n_pages - 1}"
                )
                self._finish_host(req, [], [], no_eos=True, interrupted=False,
                                  vstart=self.version)
                continue
            # Reserve through the first decode block, not just the prompt:
            # a prompt-only reservation can be preempted by _ensure_pages
            # before producing a single block, cycling admit -> preempt ->
            # resubmit with a full batched prefill each lap.
            n_reserve = pages_needed(plen + self.block_steps, self.page_size)
            n_reserve = min(n_reserve, self.max_pages, self.n_pages - 1)
            # Prefix-cache lookup: a resubmission whose prompt extends
            # the cached tokens keeps those pages and prefills only the
            # delta (positions cached_use..plen-1).
            pages = None
            cached_use = 0
            ent = self._prefix_cache.pop(req.qid, None)
            if ent is not None:
                ctoks, cpages = ent
                self._cached_tokens -= len(ctoks)
                use = min(len(ctoks), plen - 1)
                if (
                    use >= self.page_size
                    and ctoks[:use] == req.input_ids[:use]
                ):
                    if len(cpages) < n_reserve:
                        got = self._alloc_pages(n_reserve - len(cpages))
                        if got is None:
                            # Pool pressure mid-extension: re-park the
                            # entry and stop admitting.
                            self._prefix_cache[req.qid] = ent
                            self._cached_tokens += len(ctoks)
                            break
                        cpages = cpages + got
                    pages = cpages
                    cached_use = use
                    self.prefix_cache_hits += 1
                    self.prefix_tokens_reused += use
                else:
                    self._allocator.free(cpages)
            if pages is None:
                pages = self._alloc_pages(n_reserve)
                if pages is None:
                    break  # pool pressure: wait for frees
            self._pop_backlog()
            batch.append((free.pop(0), req, plen, pages, cached_use))
            if tok_budget is not None:
                tok_budget = max(0, tok_budget - est_new)
            if self._takes_chunked_path(req, plen, cached_use):
                n_chunked += 1
        if batch:
            # Starvation aging: only requests genuinely PASSED OVER age —
            # someone else admitted ahead of them this round. Rounds with
            # no admission capacity (all slots busy, pool dry) age no one,
            # so sustained saturation can't promote the whole backlog.
            for r in self._backlog:
                r.starved_rounds += 1
        if not batch:
            return
        # Long prompts go through the fixed-shape chunked prefill (one
        # compiled program regardless of length); short ones keep the
        # batched bucketed path. Chunked entries first so logits rows
        # stay aligned with `batch` order.
        def _is_chunked(e):
            return self._takes_chunked_path(e[1], e[2], e[4])

        long = [e for e in batch if _is_chunked(e)]
        short = [e for e in batch if not _is_chunked(e)]
        batch[:] = long + short  # in place: _admit_inflight keeps tracking
        logits_rows = [
            self._chunked_prefill_one(req.input_ids, pages, start=cu)
            for _, req, _, pages, cu in long
        ]
        if short:
            pad = _round_up(max(p for _, _, p, _, _ in short), self.prompt_bucket)
            pad = _round_up(min(pad, self.S), self.page_size)
            n_s = _pow2_at_least(len(short), self.prefill_max_batch)
            ids = np.zeros((n_s, pad), np.int32)
            lens = np.ones((n_s,), np.int32)  # dummy rows: 1-token prompts
            for i, (_, req, plen, _, _) in enumerate(short):
                ids[i, :plen] = req.input_ids
                lens[i] = plen
            short_logits, k_pref, v_pref = _prefill_batch(
                self.params, self.cfg, self._h2d(ids), self._h2d(lens),
                pad_len=pad, mesh=self.mesh,
            )
            # Scatter prefill KV into the pool. Chunks past a row's
            # allocation (prompt-bucket padding) and dummy rows land on
            # the trash page.
            n_chunks = pad // self.page_size
            flat = np.full((n_s, n_chunks), TRASH_PAGE, np.int32)
            for i, (_, _, plen_i, pages, _) in enumerate(short):
                # Only the prompt's chunks carry prefill KV; pages
                # reserved beyond the prompt (first-decode-block
                # headroom) receive decode writes later.
                n_p = pages_needed(plen_i, self.page_size)
                flat[i, :n_p] = pages[:n_p]
            self._ensure_pool()
            self._k_pages, self._v_pages = scatter_prefill(
                self._k_pages, self._v_pages, k_pref, v_pref,
                self._h2d(flat.reshape(-1)),
            )
            if long:
                # Only the mixed case pays for per-row slicing; the
                # all-short fast path below uses short_logits whole.
                logits_rows.extend(
                    short_logits[i] for i in range(len(short))
                )
        n_b = _pow2_at_least(len(batch), self.prefill_max_batch)
        if not long:
            last_logits = short_logits  # already [n_b, V]: fast path
        else:
            last_logits = jnp.stack(
                logits_rows
                + [jnp.zeros_like(logits_rows[0])] * (n_b - len(batch))
            )
        # Sample each row's first token (same warp as the decode block).
        self._rng, sub = jax.random.split(self._rng)
        eos_rows = np.stack(
            [self._eos_mask_np(req) for _, req, *_ in batch]
            + [self._eos_mask_np(None)] * (n_b - len(batch))
        )

        def col(fn, dtype, fill):
            return np.asarray(
                [fn(r) for _, r, *_ in batch]
                + [fill] * (n_b - len(batch)), dtype,
            )

        temps = col(lambda r: r.temperature, np.float32, 1.0)
        tps = col(lambda r: r.top_p, np.float32, 1.0)
        tks = col(lambda r: r.top_k, np.int32, -1)
        greedy = col(lambda r: r.greedy, bool, False)
        packed = np.asarray(_first_sample_packed(
            last_logits, sub, self._h2d(temps), self._h2d(tps),
            self._h2d(tks), self._h2d(greedy),
            self._h2d(col(lambda r: r.min_new_tokens > 0, bool, False)),
            self._h2d(eos_rows),
        ))  # one fetch: [n_b, 2]
        # First token is on host: TTFT = submit -> now (queue wait +
        # prefill + first sample, the SLO number the openloop bench
        # sweeps).
        t_first = time.monotonic()
        for slot_i, req_i, *_ in batch:
            self.ttft_hist.add((t_first - req_i.submit_time) * 1000.0)
            # ITL for this slot measures from its first token's arrival.
            self._slot_emit_t[slot_i] = t_first

        # Host bookkeeping + one fused device admit.
        adm_slots, adm_valid = [], []
        adm_plens, adm_toks, adm_budget, adm_minr = [], [], [], []
        adm_t, adm_tp, adm_tk, adm_g = [], [], [], []
        for i, (slot, req, plen, pages, _) in enumerate(batch):
            tok_i, lp_f = int(packed[i, 0]), float(packed[i, 1])
            # A stale deactivation from this slot's PREVIOUS request must
            # not clobber the fresh activation (apply_admits fully
            # overwrites the slot's device state anyway).
            self._pending_deact[slot] = False
            self._slot_req[slot] = req
            self._slot_out[slot] = [tok_i]
            self._slot_lp[slot] = [lp_f]
            self._slot_vstart[slot] = self.version
            self._slot_pages[slot] = pages
            self._page_table[slot, :] = TRASH_PAGE
            self._page_table[slot, : len(pages)] = pages
            self._pt_dirty = True
            self._pt_dirty_slots.add(slot)
            is_eos = tok_i in self._eos_set(req)
            budget_left = req.max_new_tokens - 1
            if (is_eos and req.min_new_tokens <= 1) or budget_left <= 0:
                # The prompt's KV is fully in the pool even though no
                # decode step ran; record it so _finish_slot can park
                # the pages for a same-qid extension instead of freeing
                # a fresh (possibly 16-32k-token) prefill.
                self._len[slot] = plen
                self._finish_slot(slot, hit_eos=is_eos)
                continue
            # `self._len` counts cache fill EXCLUDING the pending
            # next_input token: the first decode step writes the sampled
            # first token's k/v at position plen, then advances.
            self._len[slot] = plen
            adm_slots.append(slot)
            adm_valid.append(True)
            adm_plens.append(plen)
            adm_toks.append(tok_i)
            adm_budget.append(budget_left)
            adm_minr.append(max(0, req.min_new_tokens - 1))
            adm_t.append(req.temperature)
            adm_tp.append(req.top_p)
            adm_tk.append(req.top_k)
            adm_g.append(req.greedy)
        if not adm_slots:
            return
        m = _pow2_at_least(len(adm_slots), self.prefill_max_batch)
        pad_n = m - len(adm_slots)
        self._dstate = apply_admits(
            self._dstate,
            self._h2d(np.asarray(adm_slots + [0] * pad_n, np.int32)),
            self._h2d(np.asarray(adm_valid + [False] * pad_n)),
            self._h2d(np.asarray(adm_plens + [0] * pad_n, np.int32)),
            self._h2d(np.asarray(adm_toks + [0] * pad_n, np.int32)),
            self._h2d(np.asarray(adm_budget + [0] * pad_n, np.int32)),
            self._h2d(np.asarray(adm_minr + [0] * pad_n, np.int32)),
            self._h2d(np.asarray(adm_t + [1.0] * pad_n, np.float32)),
            self._h2d(np.asarray(adm_tp + [1.0] * pad_n, np.float32)),
            self._h2d(np.asarray(adm_tk + [-1] * pad_n, np.int32)),
            self._h2d(np.asarray(adm_g + [False] * pad_n)),
            n_slots=self.B,
        )
        if self._history is not None:
            from areal_tpu.engine.spec_decode import set_history

            rows = np.zeros((m, self.S + 1), np.int32)
            for i, slot in enumerate(adm_slots):
                req = self._slot_req[slot]
                plen = min(len(req.input_ids), self.S)
                rows[i, :plen] = req.input_ids[:plen]
                rows[i, plen] = self._slot_out[slot][0]
            self._history = set_history(
                self._history,
                self._h2d(np.asarray(adm_slots + [0] * pad_n, np.int32)),
                self._h2d(np.asarray(adm_valid + [False] * pad_n)),
                self._h2d(rows),
            )

    def _evict_one_prefix(self, pinned: Optional[set] = None,
                          spill: bool = True) -> bool:
        """Evict the least-recently-used cached prefix's pages — but
        SPILL the KV to the host tier first when one is configured
        (handoff wire format; the gather dispatches here on the loop,
        the device fetch + pack run on the spill thread), so eviction
        demotes the prefix instead of destroying it. Entries whose qid
        is in `pinned` (a request for them is already queued — a
        KV-handoff import or a continuation about to admit) are
        skipped: evicting them turns a one-token delta prefill into a
        full re-prefill ON the serve loop, stalling every running decode
        stream. Returns False when nothing (unpinned) is evictable.
        ``spill=False`` is the weight-swap flush: that KV is stale the
        moment the swap lands, so spilling it would only poison the
        tier."""
        if not self._prefix_cache:
            return False
        qid = None
        if pinned:
            for q in self._prefix_cache:  # oldest-first iteration
                if q not in pinned:
                    qid = q
                    break
            if qid is None:
                return False
            toks, pages = self._prefix_cache.pop(qid)
        else:
            qid, (toks, pages) = self._prefix_cache.popitem(last=False)
        self._spill_or_lose(qid, toks, pages, spill)
        self._allocator.free(pages)
        self._cached_tokens -= len(toks)
        return True

    def _spill_or_lose(self, qid: str, toks: List[int], pages: List[int],
                       spill: bool):
        """Loop-thread half of a spill: dispatch the token-major gather
        while the pages are still allocated (the results are fresh
        arrays, safe to device_get off-loop), then hand the rest to the
        spill thread. Anything that prevents the spill while the KV was
        still valid counts as a TRUE prefix loss (kv_prefix_lost_total
        on /metrics — the residual the tier exists to eliminate)."""
        if not spill:
            return  # weight-swap flush: the KV is stale, not lost
        if self.kv_tier is None:
            self._kv_lost_evict += 1
            return
        from areal_tpu.engine.paged import gather_kv_tokens

        n = len(toks)
        n_pg = pages_needed(n, self.page_size)
        k = gather_kv_tokens(self._k_pages, pages[:n_pg], n)
        v = gather_kv_tokens(self._v_pages, pages[:n_pg], n)
        try:
            self._spill_q.put_nowait(
                (qid, list(toks), self.version, k, v)
            )
        except queue.Full:
            # Dropping here (not blocking) keeps the serve loop's
            # latency bounded; the continuation pays a re-prefill.
            self._kv_lost_evict += 1

    def _pack_kv_wire(self, k, v, compress: Optional[str]):
        """(arrays, wire) for a gathered (possibly int8-pool) KV pair —
        shared by the handoff export and the spill worker. int8 pools
        ship their (data, scales) form unchanged; float pools
        optionally quantize on the wire (``compress='int8'`` or the
        e4m3 ``compress='fp8'`` — same 1-byte wire footprint, floating
        mantissa)."""
        if isinstance(k, tuple):  # int8 pool: (data, scales)
            arrays = [
                ("k_data", np.asarray(k[0])),
                ("k_scales", np.asarray(k[1], np.float32)),
                ("v_data", np.asarray(v[0])),
                ("v_scales", np.asarray(v[1], np.float32)),
            ]
            return arrays, "int8"
        if compress == "int8":
            kw, ks = quantize_kv(k)
            vw, vs = quantize_kv(v)
            arrays = [
                ("k_data", np.asarray(kw)),
                ("k_scales", np.asarray(ks[..., 0], np.float32)),
                ("v_data", np.asarray(vw)),
                ("v_scales", np.asarray(vs[..., 0], np.float32)),
            ]
            return arrays, "int8"
        if compress == "fp8":
            from areal_tpu.engine import kv_handoff as kvh

            kw, ks = kvh.quantize_kv_fp8(np.asarray(k))
            vw, vs = kvh.quantize_kv_fp8(np.asarray(v))
            arrays = [
                ("k_data", kw),
                ("k_scales", ks),
                ("v_data", vw),
                ("v_scales", vs),
            ]
            return arrays, "fp8"
        kh, vh = np.asarray(k), np.asarray(v)
        return [("k", kh), ("v", vh)], kh.dtype.name

    def _spill_worker(self):
        """Dedicated spill thread: device fetch (np.asarray of the
        fresh gathered arrays), optional int8 quantize, chunk hashing,
        and the tier insert — all the blocking work the serve loop must
        never pay (PR 10 discipline). One failure loses one prefix
        (counted), never the thread."""
        from areal_tpu.engine import kv_handoff as kvh

        while not self._stop.is_set():
            if self._tier_clear.is_set():
                # Weight swap landed: every tiered prefix is stale.
                # Cleared HERE (disk unlinks, store lock) so the serve
                # loop's swap window never pays for it.
                self._tier_clear.clear()
                self.kv_tier.clear()
            try:
                item = self._spill_q.get(timeout=0.2)
            except queue.Empty:
                continue
            if item is None:
                continue
            qid, toks, version, k, v = item
            if version != self.version:
                # Spilled under weights that are no longer live (a swap
                # landed while the item queued): restoring it would be
                # version-rejected anyway — stale, not lost. Dropping
                # here also keeps post-clear re-population impossible.
                continue
            t0 = tracing.now_ns() if tracing.enabled() else 0
            try:
                faults.maybe_fail("engine.kv_spill")
                arrays, wire = self._pack_kv_wire(
                    k, v, self.kv_spill_dtype
                )
                segments, chunks, payload = kvh.pack_arrays(arrays)
                meta = kvh.build_meta(
                    qid, version, toks, wire, self.cfg, segments, chunks
                )
                self.kv_tier.put(qid, meta, payload)
                self.kv_spills += 1
                self.kv_spill_bytes += len(payload)
                self.kv_spill_tokens += len(toks)
                if tracing.enabled():
                    tracing.record_span(
                        "server.kv_spill", t0, qid=qid,
                        n_tokens=len(toks), bytes=len(payload), wire=wire,
                    )
            except Exception:
                self._kv_lost_spill += 1
                logger.warning(f"kv spill failed for {qid!r}",
                               exc_info=True)

    def restore_from_tier(self, qid: str,
                          prompt_ids: Optional[List[int]] = None) -> int:
        """Pull a spilled prefix back from the tier into the paged pool
        (import scatter path) and park it, so the continuation about to
        be submitted admits as a delta prefill. Returns the restored
        token count, 0 on a miss/mismatch. Runs on server executor
        threads — never the event loop, never the serve loop directly
        (import_kv_handoff takes the loop door itself).

        A version-mismatched entry (spilled under older weights) is
        dropped; a prompt that does not extend the spilled tokens leaves
        the entry in place (another turn may still match)."""
        from areal_tpu.engine import kv_handoff as kvh

        if self.kv_tier is None:
            return 0
        # Validate against the META first (always host-resident): a
        # rejected probe must not pay a disk read/promotion nor count a
        # tier hit — that would churn the LRU and overstate the tier's
        # effectiveness vs kv_restore_total.
        meta0 = self.kv_tier.peek_meta(qid, count_miss=True)
        if meta0 is None:
            return 0
        if int(meta0.get("version", -1)) != self.version:
            self.kv_tier.discard(qid)  # stale forever under new weights
            return 0
        if prompt_ids is not None:
            toks = [int(t) for t in meta0["tokens"]]
            use = min(len(toks), len(prompt_ids) - 1)
            if use < self.page_size or toks[:use] != [
                int(t) for t in prompt_ids[:use]
            ]:
                return 0
        got = self.kv_tier.get(qid)
        if got is None:
            return 0  # raced an LRU ageout between peek and get
        meta, payload, tier = got
        try:
            self.import_kv_handoff(meta, payload)
        except kvh.KVHandoffVersionMismatch:
            self.kv_tier.discard(qid)  # stale forever under new weights
            return 0
        except (kvh.KVHandoffError, RuntimeError, TimeoutError):
            # Pool exhaustion / transient loop trouble: keep the entry —
            # this continuation re-prefills, a later one may restore.
            return 0
        self.kv_tier.discard(qid)  # HBM owns the prefix again
        self.kv_restores += 1
        self.kv_restore_tokens += int(meta["n_tokens"])
        if tier == "disk":
            self.kv_restore_disk += 1
        else:
            self.kv_restore_host += 1
        return int(meta["n_tokens"])

    def has_parked(self, qid: str) -> bool:
        """Whether the engine holds a parked HBM prefix for qid, from
        the loop-refreshed snapshot (up to ~0.2s stale — callers use it
        to skip redundant tier probes, and admission revalidates)."""
        return qid in self._parked_qids

    def parked_qids_now(self, timeout_s: float = 30.0) -> Dict[str, int]:
        """Authoritative qid -> token-count map of parked HBM prefixes,
        read ON the loop thread via the door. The off-thread
        ``_parked_qids`` snapshot is up to ~0.2s stale — fine for index
        advertisement, NOT for a drain enumerating what it must migrate
        (a just-parked prefix missed there would silently die with the
        process)."""
        def _read():
            return {
                q: len(e[0]) for q, e in self._prefix_cache.items()
            }

        return self._run_on_loop(_read, timeout_s)

    def parked_index(self, cap: int = 8192) -> List[Dict[str, Any]]:
        """HBM-parked entries for the /kv/index surface (snapshot-fed;
        tier entries come from kv_tier.held())."""
        out = []
        for q, n in list(self._parked_qids.items()):
            if len(out) >= cap:
                break
            out.append({
                "qid": q, "tier": "hbm", "n_tokens": int(n),
                "content_hash": "", "version": int(self.version),
            })
        return out

    def stage_peer_export(self, qid: str) -> Dict[str, Any]:
        """Peer-pull staging (/kv/manifest): return the handoff meta for
        a prefix this server holds, guaranteeing its payload is servable
        from the tier. A tier entry is served as-is (kept until LRU ages
        it); an HBM park is exported (consumed — the session is moving)
        and parked in the tier so /kv/chunk can stream its bytes.
        Raises KeyError when neither tier holds qid."""
        if self.kv_tier is None:
            raise KeyError(f"no kv tier to stage peer export for {qid!r}")
        got = self.kv_tier.get(qid, count=False)
        if got is not None:
            return got[0]
        meta, payload = self.export_kv_handoff(qid)
        self.kv_tier.put(qid, meta, payload)
        return meta

    def peer_payload(self, qid: str) -> Optional[Tuple[Dict, bytes]]:
        """(meta, payload) for /kv/chunk byte serving — no hit
        accounting, no consume (the peer may pull many chunks)."""
        if self.kv_tier is None:
            return None
        got = self.kv_tier.get(qid, count=False)
        return None if got is None else (got[0], got[1])

    def _flush_prefix_cache(self):
        while self._evict_one_prefix(spill=False):
            pass

    def _pinned_qids(self) -> set:
        """Qids with a pending (accepted, not yet admitted) request —
        submit queue AND backlog: their parked KV is about to be
        consumed."""
        with self._fatal_lock:
            return set(self._queued_qids)

    def _alloc_pages(self, n: int) -> Optional[List[int]]:
        """Allocate, evicting cached prefixes under pressure: speculative
        cache pages must never cost an active request its admission or
        its next decode block. Prefixes with a queued consumer go last —
        a hard pool need may still take them, but only after every
        speculative park is gone."""
        got = self._allocator.alloc(n)
        if got is not None:
            return got
        pinned = self._pinned_qids()
        while got is None and self._evict_one_prefix(pinned):
            got = self._allocator.alloc(n)
        while got is None and self._evict_one_prefix():
            got = self._allocator.alloc(n)
        return got

    def _ensure_pages(self):
        """Grow each active slot's page allocation to cover the next
        decode block; preempt (interrupt-partial) the slot itself on pool
        exhaustion — the client resubmits with the prefix once pages free
        up (vLLM/SGLang preempt-and-recompute semantics)."""
        for slot in range(self.B):
            if self._slot_req[slot] is None or self._pending_deact[slot]:
                continue
            # Cap at the page-table width: a slot at max_seq_len stops on
            # budget within the block, and overflow writes are
            # trash-routed on device, so capping is safe — not capping
            # would overrun the page-table row and kill the loop thread.
            # Speculative blocks feed 1+draft_len rows per step; every
            # fed row writes KV, so reservation covers the worst case —
            # clamped by the slot's remaining budget: the device never
            # writes past len + remaining (eff <= remaining - 1 and the
            # len+remaining sum is invariant across steps), so a
            # nearly-done slot must not over-reserve 5x and trip
            # pool-pressure preemption it doesn't need.
            block_tokens = self.block_steps * (1 + self.spec_draft_len)
            req = self._slot_req[slot]
            remaining = max(
                1, req.max_new_tokens - len(self._slot_out[slot])
            )
            need = min(
                pages_needed(
                    int(self._len[slot]) + min(block_tokens, remaining),
                    self.page_size,
                ),
                self.max_pages,
            )
            cur = len(self._slot_pages[slot])
            if need <= cur:
                continue
            got = self._alloc_pages(need - cur)
            if got is None:
                self.n_preempted += 1
                self._finish_slot(slot, hit_eos=False, interrupted=True)
                continue
            self._page_table[slot, cur:need] = got
            self._pt_dirty = True
            self._pt_dirty_slots.add(slot)
            self._slot_pages[slot].extend(got)

    def _eos_set(self, req: Optional[GenRequest]) -> set:
        s = set(req.stop_token_ids) if req is not None else set()
        if self.eos_token_id is not None:
            s.add(self.eos_token_id)
        return s

    def _eos_mask_np(self, req: Optional[GenRequest] = None) -> np.ndarray:
        """[V] bool mask of stop-token columns (empty set -> all False;
        an index-based encoding would need a pad index, and any pad value
        lands on a real vocab column)."""
        mask = np.zeros((self.cfg.vocab_size,), bool)
        for t in self._eos_set(req):
            if 0 <= t < self.cfg.vocab_size:
                mask[t] = True
        return mask

    def _finish_host(self, req, out, lps, no_eos, interrupted, vstart):
        res = GenResult(
            qid=req.qid,
            output_ids=list(out),
            output_logprobs=list(lps),
            no_eos=no_eos,
            interrupted=interrupted,
            version_start=vstart,
            version_end=self.version,
            latency=time.monotonic() - req.submit_time,
        )
        self.total_generated += len(out)
        if req.done_cb:
            req.done_cb(res)

    def _finish_slot(self, slot: int, hit_eos: bool, interrupted: bool = False):
        req = self._slot_req[slot]
        self._finish_host(
            req, self._slot_out[slot], self._slot_lp[slot],
            no_eos=not hit_eos, interrupted=interrupted,
            vstart=self._slot_vstart[slot],
        )
        pages = self._slot_pages[slot]
        if pages:
            # Park the sequence's KV for qid resubmission instead of
            # freeing (budget permitting): the covered tokens are the
            # prompt plus emitted tokens whose K/V actually landed in
            # the pool (self._len excludes the pending next-input token).
            covered = (list(req.input_ids) + self._slot_out[slot])[
                : int(self._len[slot])
            ]
            if (
                self.prefix_cache_tokens
                and len(covered) >= self.page_size
                # A pending weight swap invalidates this KV the moment it
                # lands — parking it would only churn the eviction loop
                # before _apply_pending_params flushes everything.
                and self._pending_params is None
            ):
                old = self._prefix_cache.pop(req.qid, None)
                if old is not None:
                    self._allocator.free(old[1])
                    self._cached_tokens -= len(old[0])
                self._prefix_cache[req.qid] = (covered, pages)
                self._cached_tokens += len(covered)
                # Budget trim is SOFT: entries with a queued consumer
                # are never trimmed for budget (only for hard pool
                # pressure, _alloc_pages) — under a handoff-import burst
                # the oldest parks are exactly the queued continuations.
                trim_pinned = self._pinned_qids()
                while (
                    self._cached_tokens > self.prefix_cache_tokens
                    and self._evict_one_prefix(trim_pinned)
                ):
                    pass
            else:
                self._allocator.free(pages)
        self._slot_req[slot] = None
        self._slot_out[slot] = []
        self._slot_lp[slot] = []
        self._slot_pages[slot] = []
        self._page_table[slot, :] = TRASH_PAGE
        self._pt_dirty = True
        self._pt_dirty_slots.add(slot)
        # The device active mask may still have this slot on (host-side
        # stop, preemption, interrupt): deactivate before the next block
        # so its freed pages are never written again.
        self._pending_deact[slot] = True
        self._len[slot] = 0

    def _interrupt_all(self):
        for slot in range(self.B):
            if self._slot_req[slot] is not None:
                self._finish_slot(slot, hit_eos=False, interrupted=True)

    def _apply_pending_params(self):
        with self._lock:
            pending = self._pending_params
            version = self._pending_version
            self._pending_params = None
            self._pending_version = None
            # Commit the pinned version HERE, atomically with the pop: a
            # popped update always applies, and recording it only after
            # the (multi-second) swap would let update_params' cancel
            # -rollback read a not-yet-bumped _applied_pinned and regress
            # _highest_pinned below a version that is about to go live.
            if pending is not None and version is not None:
                self._applied_pinned = max(self._applied_pinned, version)
        if pending is not None:
            # Cached prefixes hold KV computed under the OLD weights:
            # reusing them after the swap would decode against a stale
            # attention state. Flush before the new version goes live.
            self._flush_prefix_cache()
            t0 = time.monotonic()
            # Transfers were staged on the updater's thread
            # (update_params); this is a pointer flip + completion sync.
            self.params = pending
            self._refresh_qparams()
            jax.block_until_ready(self.params)
            # block_until_ready does NOT wait on tunneled devices (see
            # docs/perf_notes.md); fetch one element of the last leaf —
            # transfers execute in order on the device stream, so its
            # completion bounds the swap. Approximate, but two orders of
            # magnitude better than timing dispatch.
            last_leaf = jax.tree_util.tree_leaves(self.params)[-1]
            jax.device_get(last_leaf.ravel()[:1])
            self.last_weight_swap_s = time.monotonic() - t0
            self.version = version if version is not None else self.version + 1
            # The spill tier holds KV from the OLD version: flag the
            # flush for the spill thread (disk unlinks + store lock are
            # its kind of work, never this loop's) AFTER the version
            # bump, so its version gate also drops any pre-swap items
            # still sitting in the spill queue. Until it runs (<0.2s),
            # restores of stale entries are version-rejected anyway.
            if self.kv_tier is not None:
                self._tier_clear.set()
            logger.info(
                f"serving engine weights updated to v{self.version} "
                f"in {self.last_weight_swap_s:.3f}s"
            )
        self._interrupt.clear()

    def _flush_device_control(self):
        """Apply pending deactivations + page-table changes (async
        dispatches, no host sync).

        Resident mode stages only the DIRTY page-table rows (donated
        scatter, paged.update_page_rows) — the full [B, max_pages]
        restage is kept for init / legacy mode / more-than-half-dirty
        laps (at that point one bulk transfer beats many row
        scatters)."""
        if self._pending_deact.any():
            (lengths, next_input, active, remaining, min_remaining,
             temps, top_ps, top_ks, greedy) = self._dstate
            active = apply_deactivations(
                active, self._h2d(self._pending_deact)
            )
            self._dstate = (lengths, next_input, active, remaining,
                            min_remaining, temps, top_ps, top_ks, greedy)
            self._pending_deact[:] = False
        dirty = self._pt_dirty_slots
        if self._pt_dev is None or (self._pt_dirty and not dirty) or (
            dirty
            and (not self.decode_resident or len(dirty) > self.B // 2)
        ):
            self._pt_dev = self._h2d(self._page_table)
        elif dirty:
            slots = sorted(dirty)
            m = _pow2_at_least(len(slots), self.B)
            packed = np.full((m, self.max_pages + 1), -1, np.int32)
            packed[: len(slots), 0] = slots
            packed[: len(slots), 1:] = self._page_table[slots]
            self._pt_dev = update_page_rows(
                self._pt_dev, self._h2d(packed), n_slots=self.B,
            )
        self._pt_dirty = False
        dirty.clear()

    def _loop(self):
        try:
            self._serve()
        except Exception as e:  # serve-loop death must not strand clients
            self.fatal_error = e
            logger.exception("serving engine loop died: %s", e)
            self._fail_all(e)

    def _fail_all(self, exc: BaseException):
        """Deliver an error GenResult to every running + queued request so
        callers blocked on done_cb unwind instead of hanging (measured
        failure mode: a chunk-prefill XLA compile error left the 16k gen
        probe waiting out its full 1800 s timeout)."""
        msg = f"{type(exc).__name__}: {exc}"
        reqs = [r for r in self._slot_req if r is not None]
        self._slot_req = [None] * len(self._slot_req)
        # _backlog holds requests _drain_queue accepted but couldn't admit
        # yet (pool pressure / per-lap caps); _admit_inflight holds the
        # batch a mid-admit prefill failure abandoned — both are engine-
        # thread-only state, and the engine thread is dead by now. Dedup
        # by identity: a request can be in _admit_inflight AND _slot_req
        # if the failure hit partway through the slotting loop.
        reqs.extend(self._backlog)
        self._backlog.clear()
        self._backlog_len = 0
        seen = {id(r) for r in reqs}
        reqs.extend(e[1] for e in self._admit_inflight
                    if id(e[1]) not in seen)
        self._admit_inflight.clear()
        with self._fatal_lock:  # no submit can interleave with the drain
            while True:
                try:
                    reqs.append(self._queue.get_nowait())
                except queue.Empty:
                    break
            self.queued_prompt_tokens = 0
            self._queued_qids.clear()
        for req in reqs:
            if req.done_cb:
                try:
                    req.done_cb(GenResult(
                        qid=req.qid, output_ids=[], output_logprobs=[],
                        no_eos=True, interrupted=True,
                        version_start=self.version, version_end=self.version,
                        latency=time.monotonic() - req.submit_time,
                        error=msg,
                    ))
                except Exception:
                    logger.exception("done_cb failed during _fail_all")

    def _serve(self):
        self._ensure_pool()
        eos_global = jnp.asarray(self._eos_mask_np())
        # Column count of the packed block result: the spec block emits
        # up to (1 + draft_len) tokens per step.
        n = self.block_steps * (1 + self.spec_draft_len)
        while not self._stop.is_set():
            # Handoff export/import closures (engine-thread state only).
            self._drain_cmds()
            # Refresh the off-thread telemetry snapshots (see __init__).
            self._backlog_len = len(self._backlog)
            self._kv_pages_free = self._allocator.n_free
            now_lap = time.monotonic()
            if now_lap - self._parked_snap_t > 0.2:
                # Parked-prefix snapshot for off-thread consumers
                # (has_parked / the /kv/index surface): replaced
                # wholesale, like _backlog_len.
                self._parked_qids = {
                    q: len(e[0]) for q, e in self._prefix_cache.items()
                }
                self._parked_snap_t = now_lap
            if self._interrupt.is_set():
                self._interrupt_all()
                self._apply_pending_params()
            # Prefill/decode interleave: admission (which runs prefill on
            # this thread) only every decode_blocks_per_admit blocks —
            # except when idle, where admitting immediately is free.
            if (
                self._blocks_since_admit >= self.decode_blocks_per_admit
                or not any(r is not None for r in self._slot_req)
            ):
                # _admit resets the interleave counter itself, AFTER its
                # pending-weight-swap guard: a swap-blocked attempt keeps
                # the counter saturated so admission retries next lap
                # instead of waiting a fresh interleave period.
                self._admit()
            if not any(r is not None for r in self._slot_req):
                # idle: apply updates immediately, then wait for work
                if self._pending_params is not None:
                    self._apply_pending_params()
                time.sleep(0.002)
                self.n_running = 0
                continue
            self._ensure_pages()
            self._flush_device_control()
            if not any(r is not None for r in self._slot_req):
                continue
            self.n_running = sum(r is not None for r in self._slot_req)
            self.n_used_tokens = int(self._len.sum())

            (lengths, next_input, active, remaining, min_remaining,
             temps, top_ps, top_ks, greedy) = self._dstate
            decode_t0 = tracing.now_ns() if tracing.enabled() else 0
            t_blk0 = time.monotonic()
            if self.spec_draft_len > 0:
                from areal_tpu.engine.spec_decode import (
                    paged_spec_decode_block,
                )

                (packed, self._k_pages, self._v_pages, lengths,
                 next_input, active, remaining, min_remaining, self._rng,
                 self._history) = paged_spec_decode_block(
                    self._decode_params, self.cfg, self._k_pages,
                    self._v_pages,
                    self._pt_dev, lengths, next_input, active, remaining,
                    min_remaining, temps, top_ps, top_ks, greedy,
                    eos_global, self._rng, self._history,
                    n_steps=self.block_steps,
                    draft_len=self.spec_draft_len,
                    ngram=self.spec_ngram,
                    ngram_window=self.spec_window,
                    attn_impl=self.attn_impl, mesh=self.mesh,
                )
            else:
                (packed, self._k_pages, self._v_pages, lengths, next_input,
                 active, remaining, min_remaining,
                 self._rng) = paged_decode_block(
                    self._decode_params, self.cfg, self._k_pages,
                    self._v_pages,
                    self._pt_dev, lengths, next_input, active, remaining,
                    min_remaining, temps, top_ps, top_ks, greedy,
                    eos_global, self._rng,
                    n_steps=n, attn_impl=self.attn_impl, mesh=self.mesh,
                )
            self._dstate = (lengths, next_input, active, remaining,
                            min_remaining, temps, top_ps, top_ks, greedy)
            p = np.asarray(packed)  # the block's single device fetch
            self._blocks_since_admit += 1
            self.decode_blocks += 1
            if self.cfg.moe is not None and p.shape[1] >= 2 * n + 6:
                # MoE packed layout appends [moe_drop_rate,
                # moe_router_entropy] broadcast columns (paged.py).
                self.moe_drop_rate = float(p[0, 2 * n + 4])
                self.moe_router_entropy = float(p[0, 2 * n + 5])
            t_blk1 = time.monotonic()
            if tracing.enabled():
                tracing.record_span(
                    "server.decode_block", decode_t0,
                    n_running=self.n_running,
                )
            toks_h = p[:, :n]
            lps_h = p[:, n:2 * n]
            n_emitted = p[:, 2 * n].astype(np.int64)
            # Inter-token latency: wall time since the slot's PREVIOUS
            # token delivery, amortized over the tokens this block
            # emitted (uniform within the block — the device doesn't
            # timestamp individual steps). Measuring from the last
            # delivery rather than the block start charges the
            # admission-prefill stalls between blocks to the running
            # slots that actually waited through them — the decode-
            # latency interference the disaggregated fleet removes.
            for slot in range(self.B):
                k = int(n_emitted[slot])
                if k > 0 and self._slot_req[slot] is not None:
                    t_prev = self._slot_emit_t[slot] or t_blk0
                    self.itl_hist.add(
                        (t_blk1 - t_prev) * 1000.0 / k, count=k
                    )
                    self._slot_emit_t[slot] = t_blk1
            if self.spec_draft_len > 0:
                # Spec block appends a per-slot active-steps column: the
                # exact yield denominator (early-finishing slots charge
                # only the steps they actually ran).
                self._spec_emitted += int(n_emitted.sum())
                self._spec_steps += int(p[:, 2 * n + 4].sum())
            hit_eos_h = p[:, 2 * n + 1] > 0.5
            active_h = p[:, 2 * n + 2] > 0.5
            # Mirror lengths for occupied slots only: the device array is
            # never reset for freed slots, so copying it wholesale would
            # resurrect stale counts into num_used_tokens (and skew the
            # manager's least_token_usage routing).
            occupied = np.asarray(
                [r is not None for r in self._slot_req], bool
            )
            self._len = np.where(
                occupied, p[:, 2 * n + 3].astype(np.int64), 0
            )
            for slot in range(self.B):
                req = self._slot_req[slot]
                if req is None:
                    continue
                k = int(n_emitted[slot])
                if k:
                    self._slot_out[slot].extend(
                        toks_h[slot, :k].astype(np.int64).tolist()
                    )
                    self._slot_lp[slot].extend(lps_h[slot, :k].tolist())
                # Per-request extra stop tokens (beyond the global EOS set)
                # are enforced on host: trim at the first occurrence AFTER
                # the min_new_tokens floor (the device forbid mask only
                # covers the global EOS set).
                extra = set(req.stop_token_ids) - self._eos_set(None)
                if extra:
                    for j, t in enumerate(self._slot_out[slot]):
                        if j < req.min_new_tokens:
                            continue
                        if t in extra:
                            self._slot_out[slot] = self._slot_out[slot][: j + 1]
                            self._slot_lp[slot] = self._slot_lp[slot][: j + 1]
                            self._finish_slot(slot, hit_eos=True)
                            break
                    if self._slot_req[slot] is None:
                        continue
                if not active_h[slot]:
                    self._finish_slot(slot, hit_eos=bool(hit_eos_h[slot]))
        # drain on stop
        self._interrupt_all()

"""GenerationServer worker over HTTP with tensor_parallel=2: the
mesh-sharded ServingEngine (GSPMD param + KV-pool sharding) behind the
SGLang-contract endpoints, plus the tmpfs weight-update fast path —
end-to-end across two processes."""

import json
import os
import subprocess
import sys
import time
import urllib.request
import uuid

import numpy as np
import pytest

# Two-process TP e2e with a 600s ceiling: keep it off shared workers.
pytestmark = pytest.mark.serial

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

CHILD = '''
import os, sys
sys.path.insert(0, %(repo)r)
import jax; jax.config.update("jax_platforms", "cpu")
from areal_tpu.base import name_resolve
name_resolve.reconfigure("nfs", record_root=%(nr)r)
from areal_tpu.api.system_api import GenerationServerConfig
from areal_tpu.api.config import ModelAbstraction
from areal_tpu.system.generation_server import GenerationServer
import areal_tpu.engine.factories  # registry
cfg = GenerationServerConfig(
    experiment_name=%(exp)r, trial_name=%(trial)r, server_index=0,
    model=ModelAbstraction("tpu_transformer", args=dict(config=dict(
        n_layers=2, hidden_dim=32, n_q_heads=2, n_kv_heads=2, head_dim=16,
        intermediate_dim=64, vocab_size=64, compute_dtype="float32",
        param_dtype="float32"))),
    max_concurrent_requests=2, max_seq_len=128, kv_page_size=8,
    decode_block_steps=4, tensor_parallel=2, seed=0,
)
w = GenerationServer()
w.configure(cfg, experiment_name=cfg.experiment_name, trial_name=cfg.trial_name,
            worker_name=cfg.worker_name)
w.run()
'''


@pytest.mark.timeout(600)
def test_generation_server_tensor_parallel(tmp_path):
    from areal_tpu.base import name_resolve, names
    from areal_tpu.models.config import TransformerConfig
    from areal_tpu.models.transformer import init_params
    from areal_tpu.system.weight_transfer import dump_raw_params, shm_transfer_dir

    nr = str(tmp_path / "nr")
    # Unique experiment name: the shm fast path is keyed by it globally
    # (/dev/shm/areal_tpu/<exp>/...), so concurrent runs must not collide.
    exp, trial = f"tpserve-{uuid.uuid4().hex[:6]}", "t0"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    # Child output to a file: an unread PIPE deadlocks the server once
    # its logs exceed the pipe buffer, and hides the traceback on crash.
    log_path = tmp_path / "server.log"
    log_f = open(log_path, "w")
    proc = subprocess.Popen(
        [sys.executable, "-c",
         CHILD % dict(repo=REPO, nr=nr, exp=exp, trial=trial)],
        env=env, cwd=REPO, stdout=log_f, stderr=subprocess.STDOUT,
    )
    try:
        name_resolve.reconfigure("nfs", record_root=nr)
        from tests.fixtures import scale_timeout

        deadline = time.monotonic() + scale_timeout(240)
        url = None
        while url is None:
            assert proc.poll() is None, (
                "server died during startup:\n" + log_path.read_text()[-3000:]
            )
            try:
                url = name_resolve.get(names.gen_server_url(exp, trial, "0"))
            except name_resolve.NameEntryNotFoundError:
                assert time.monotonic() < deadline, "server never registered"
                time.sleep(0.2)

        def post(path, payload):
            r = urllib.request.urlopen(urllib.request.Request(
                url + path, json.dumps(payload).encode(),
                {"Content-Type": "application/json"}), timeout=240)
            return json.loads(r.read())

        out = post("/generate", {"qid": "q1", "input_ids": [5, 6, 7],
                                 "gconfig": {"max_new_tokens": 6, "greedy": True}})
        assert len(out["output_ids"]) >= 1
        assert all(lp <= 0 for lp in out["output_logprobs"])

        # Weight update via the tmpfs raw fast path; role name = the
        # basename of model_path (generation_server._load_params).
        import jax as j

        cfg = TransformerConfig(
            n_layers=2, hidden_dim=32, n_q_heads=2, n_kv_heads=2, head_dim=16,
            intermediate_dim=64, vocab_size=64, compute_dtype="float32",
            param_dtype="float32",
        )
        new_params = j.tree_util.tree_map(
            lambda x: np.asarray(x), init_params(cfg, j.random.PRNGKey(9))
        )
        role_dir = str(tmp_path / "realloc" / "actor")
        os.makedirs(role_dir, exist_ok=True)
        dump_raw_params(new_params, role_dir, version=5)
        shm = shm_transfer_dir(exp, trial, "actor")
        if shm is not None:
            dump_raw_params(new_params, shm, version=5)
        res = post("/update_weights_from_disk",
                   {"model_path": role_dir, "allow_interrupt": True, "version": 5})
        assert res["success"]
        assert res["source"] == ("shm_raw" if shm is not None else "disk_raw")

        out2 = post("/generate", {"qid": "q2", "input_ids": [9, 10],
                                  "gconfig": {"max_new_tokens": 4, "greedy": True}})
        assert out2["version_start"] == 5

        metrics = urllib.request.urlopen(url + "/metrics", timeout=60).read().decode()
        assert "areal:kv_pages_total" in metrics
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            proc.kill()
        log_f.close()
        # tmpfs dumps are keyed by experiment name; clean up.
        import shutil

        shutil.rmtree(f"/dev/shm/areal_tpu/{exp}", ignore_errors=True)

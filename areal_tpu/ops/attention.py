"""Packed variable-length causal attention with GQA.

Replaces the reference's flash-attn varlen path
(realhf/impl/model/modules/attn.py:272-289) the TPU way: batches are packed
token streams with *segment ids* (0 = padding, sequences numbered from 1)
and per-token positions; attention is masked to (same segment) AND
(causal by position). Two implementations share one signature:

- `reference_packed_attention`: dense jnp einsum + mask. O(T^2) memory;
  used on CPU tests and as the numerical oracle.
- `flash_packed_attention` (areal_tpu.ops.pallas.flash_attn): blocked
  Pallas kernel, online softmax, segment-aware block skipping.

`packed_attention` dispatches on platform/size.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -2.0**30


def segment_causal_mask(
    q_seg: jnp.ndarray, kv_seg: jnp.ndarray, q_pos: jnp.ndarray, kv_pos: jnp.ndarray
) -> jnp.ndarray:
    """Boolean [Tq, Tk]: token i may attend to token j."""
    same = q_seg[:, None] == kv_seg[None, :]
    causal = q_pos[:, None] >= kv_pos[None, :]
    valid = (q_seg[:, None] > 0) & (kv_seg[None, :] > 0)
    return same & causal & valid


def reference_packed_attention(
    q: jnp.ndarray,  # [T, Hq, hd]
    k: jnp.ndarray,  # [T, Hkv, hd]
    v: jnp.ndarray,  # [T, Hkv, hd]
    segment_ids: jnp.ndarray,  # [T] int32, 0 = pad
    positions: jnp.ndarray,  # [T] int32 within-sequence positions
    softmax_scale: Optional[float] = None,
) -> jnp.ndarray:
    T, Hq, hd = q.shape
    Hkv = k.shape[1]
    group = Hq // Hkv
    scale = softmax_scale if softmax_scale is not None else hd**-0.5
    qg = q.reshape(T, Hkv, group, hd).astype(jnp.float32)
    kf = k.astype(jnp.float32)
    vf = v.astype(jnp.float32)
    # scores: [Hkv, group, Tq, Tk]
    scores = jnp.einsum("qhgd,khd->hgqk", qg, kf) * scale
    mask = segment_causal_mask(segment_ids, segment_ids, positions, positions)
    scores = jnp.where(mask[None, None], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    # Fully-masked (padding) rows: zero out.
    probs = jnp.where(mask.any(axis=-1)[None, None, :, None], probs, 0.0)
    out = jnp.einsum("hgqk,khd->qhgd", probs, vf)
    return out.reshape(T, Hq, hd).astype(q.dtype)


def decode_attention(
    q: jnp.ndarray,  # [B, Hq, hd] — one new token per sequence
    k_cache: jnp.ndarray,  # [B, S, Hkv, hd]
    v_cache: jnp.ndarray,  # [B, S, Hkv, hd]
    cache_lens: jnp.ndarray,  # [B] valid lengths INCLUDING the new token
    softmax_scale: Optional[float] = None,
) -> jnp.ndarray:
    """Single-step decode attention against a padded KV cache."""
    B, Hq, hd = q.shape
    S, Hkv = k_cache.shape[1], k_cache.shape[2]
    group = Hq // Hkv
    scale = softmax_scale if softmax_scale is not None else hd**-0.5
    qg = q.reshape(B, Hkv, group, hd).astype(jnp.float32)
    scores = jnp.einsum("bhgd,bshd->bhgs", qg, k_cache.astype(jnp.float32)) * scale
    pos = jnp.arange(S)[None, :]  # [1, S]
    mask = pos < cache_lens[:, None]  # [B, S]
    scores = jnp.where(mask[:, None, None, :], scores, NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhgs,bshd->bhgd", probs, v_cache.astype(jnp.float32))
    return out.reshape(B, Hq, hd).astype(q.dtype)


def packed_attention(q, k, v, segment_ids, positions, softmax_scale=None, impl="auto"):
    """Dispatch between implementations. Static decision (trace-time): `impl`
    is 'reference', 'flash', or 'auto' (flash on TPU backends when T is a
    multiple of the kernel block, reference otherwise)."""
    T = q.shape[0]
    if impl == "auto":
        on_tpu = jax.default_backend() in ("tpu", "axon")
        impl = "flash" if (on_tpu and T >= 128 and T % 128 == 0) else "reference"
    if impl == "flash":
        from areal_tpu.ops.pallas.flash_attn import flash_packed_attention

        return flash_packed_attention(
            q, k, v, segment_ids, positions, softmax_scale=softmax_scale
        )
    return reference_packed_attention(
        q, k, v, segment_ids, positions, softmax_scale=softmax_scale
    )

"""Standalone worker entry point for cluster launches.

Counterpart of the reference's `python -m realhf.apps.remote worker`
(realhf/apps/remote.py — what SLURM srun lines execute on every node).
The ClusterController (system/controller.py) writes each worker's config
as a pickle into the run's spool directory (shared filesystem on real
clusters) and submits this module through the scheduler client; discovery
then happens via name_resolve (typically the 'kv' TCP service, which
needs no shared FS).

    python -m areal_tpu.system.worker_main \
        --worker-type model_worker --config /spool/model_worker_0.pkl \
        --name-resolve '{"backend": "kv", "address": "10.0.0.2:2379"}'
"""

from __future__ import annotations

import argparse
import json
import os
import pickle


def main(argv=None):
    ap = argparse.ArgumentParser(description="areal_tpu worker process")
    ap.add_argument("--worker-type", required=True)
    ap.add_argument("--config", required=True, help="pickled worker config path")
    ap.add_argument("--name-resolve", required=True,
                    help="JSON kwargs for name_resolve.reconfigure")
    args = ap.parse_args(argv)

    from areal_tpu.utils.jaxenv import apply_jax_platform_override

    apply_jax_platform_override()

    from areal_tpu.base import name_resolve

    name_resolve.reconfigure(**json.loads(args.name_resolve))

    with open(args.config, "rb") as f:
        config = pickle.load(f)

    from areal_tpu.system import load_worker

    cls = load_worker(args.worker_type)
    w = cls()
    w.configure(
        config,
        experiment_name=config.experiment_name,
        trial_name=config.trial_name,
        worker_name=config.worker_name,
    )
    w.run()


if __name__ == "__main__":
    main()

"""Shared lint plumbing: parsed-module model, findings, allowlist."""

from __future__ import annotations

import ast
import dataclasses
import os
from typing import Dict, List, Optional, Tuple


class LintConfigError(Exception):
    """Bad linter configuration (malformed allowlist, missing registry).

    Distinct from findings: config errors exit 2, findings exit 1."""


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str  # repo-relative, forward slashes
    line: int
    checker: str
    message: str

    def key(self) -> Tuple[str, int, str]:
        return (self.path, self.line, self.checker)

    def render(self) -> str:
        return f"{self.path}:{self.line} [{self.checker}] {self.message}"


class Module:
    """One parsed source file plus the derived maps every checker needs.

    ``parents``: child node -> parent node (ast has no parent links).
    ``imports``: local name -> dotted module/attr it refers to, e.g.
      ``import urllib.request``        -> {"urllib": "urllib"}
      ``import numpy as np``           -> {"np": "numpy"}
      ``from time import sleep``       -> {"sleep": "time.sleep"}
      ``from areal_tpu.base import env_registry as envr``
                                       -> {"envr": "areal_tpu.base.env_registry"}
    ``str_constants``: module-level ``NAME = "literal"`` bindings, so a
    read like ``os.environ.get(_ENV_DIR)`` resolves through the
    constant.
    """

    def __init__(self, path: str, rel: str, source: str, tree: ast.AST):
        self.path = path
        self.rel = rel
        self.source = source
        self.tree = tree
        self.parents: Dict[ast.AST, ast.AST] = {}
        # One walk serves every checker: ``nodes`` is the full
        # pre-order node list (8 checkers re-walking a 2.6k-line
        # module each was the gate's hot path).
        self.nodes: List[ast.AST] = []
        for node in ast.walk(tree):
            self.nodes.append(node)
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node
        self.imports: Dict[str, str] = {}
        self.str_constants: Dict[str, str] = {}
        for node in self.nodes:
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.asname:
                        # ``import urllib.request as ur`` binds the full
                        # dotted path to the alias.
                        self.imports[a.asname] = a.name
                    else:
                        # ``import urllib.request`` binds only the root.
                        root = a.name.split(".")[0]
                        self.imports[root] = root
            elif isinstance(node, ast.ImportFrom) and node.module:
                for a in node.names:
                    self.imports[a.asname or a.name] = f"{node.module}.{a.name}"
        for node in tree.body if isinstance(tree, ast.Module) else []:
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Constant)
                and isinstance(node.value.value, str)
            ):
                self.str_constants[node.targets[0].id] = node.value.value

    # -- helpers ---------------------------------------------------------

    def parent(self, node: ast.AST) -> Optional[ast.AST]:
        return self.parents.get(node)

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        """Nearest enclosing FunctionDef/AsyncFunctionDef/Lambda, or
        None at module/class level. A ``def`` line itself belongs to the
        *outer* scope (decorators/defaults evaluate there)."""
        cur = self.parents.get(node)
        while cur is not None:
            if isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                return cur
            cur = self.parents.get(cur)
        return None

    def dotted_name(self, node: ast.AST) -> Optional[str]:
        """``a.b.c`` for Name/Attribute chains, with the root resolved
        through the import map (``np.x`` -> ``numpy.x``)."""
        parts: List[str] = []
        cur = node
        while isinstance(cur, ast.Attribute):
            parts.append(cur.attr)
            cur = cur.value
        if not isinstance(cur, ast.Name):
            return None
        root = self.imports.get(cur.id, cur.id)
        parts.append(root)
        return ".".join(reversed(parts))

    def resolve_str(self, node: ast.AST) -> Optional[str]:
        """Literal string value of an expression, following module-level
        string constants one hop."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        if isinstance(node, ast.Name):
            return self.str_constants.get(node.id)
        return None


def parse_module(path: str, root: str) -> Tuple[Optional[Module], Optional[Finding]]:
    rel = os.path.relpath(os.path.abspath(path), root).replace(os.sep, "/")
    try:
        with open(path, "r", encoding="utf-8") as f:
            source = f.read()
        tree = ast.parse(source, filename=path)
    except (OSError, SyntaxError, ValueError) as e:
        return None, Finding(rel, getattr(e, "lineno", 1) or 1, "parse",
                             f"cannot parse: {e}")
    return Module(path, rel, source, tree), None


def iter_py_files(paths: List[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                out.append(p)
        else:
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = sorted(
                    d for d in dirnames
                    if d != "__pycache__" and not d.startswith(".")
                )
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        out.append(os.path.join(dirpath, fn))
    return out


# -- allowlist -----------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class AllowEntry:
    path: str
    line: int
    checker: str
    justification: str
    src_line: int  # line in the allowlist file (for diagnostics)

    def key(self) -> Tuple[str, int, str]:
        return (self.path, self.line, self.checker)


def parse_allowlist(path: str) -> List[AllowEntry]:
    """Format, one entry per line::

        <repo-rel-path>:<line> <checker> -- <justification>

    ``#`` comments and blank lines are skipped. The justification is
    MANDATORY — an entry without one is a config error, not a finding:
    the allowlist exists to record *why* a contract is waived."""
    entries: List[AllowEntry] = []
    try:
        with open(path, "r", encoding="utf-8") as f:
            lines = f.readlines()
    except OSError as e:
        raise LintConfigError(f"cannot read allowlist {path}: {e}")
    for i, raw in enumerate(lines, start=1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        head, sep, justification = line.partition(" -- ")
        justification = justification.strip()
        if not sep or not justification:
            raise LintConfigError(
                f"{path}:{i}: allowlist entry missing ' -- <justification>'"
            )
        parts = head.split()
        if len(parts) != 2 or ":" not in parts[0]:
            raise LintConfigError(
                f"{path}:{i}: expected '<path>:<line> <checker> -- "
                f"<justification>', got {line!r}"
            )
        loc, checker = parts
        fpath, _, lineno = loc.rpartition(":")
        try:
            n = int(lineno)
        except ValueError:
            raise LintConfigError(f"{path}:{i}: bad line number {lineno!r}")
        entries.append(AllowEntry(fpath.replace(os.sep, "/"), n, checker,
                                  justification, i))
    return entries


def apply_allowlist(
    findings: List[Finding], entries: List[AllowEntry], allowlist_rel: str,
    scanned_rels: Optional[set] = None,
    active_checkers: Optional[set] = None,
) -> List[Finding]:
    """Drop allowlisted findings; report stale entries (nothing matched)
    as findings themselves so the allowlist can't accrete dead waivers.

    Staleness is only judged for entries IN SCOPE of this run — the
    entry's file was scanned and its checker was active. A subset run
    (``--checker env-knob``, a single file path) never generates the
    waived finding, and must not spuriously fail on the waiver."""
    allowed = {e.key(): e for e in entries}
    matched = set()
    kept: List[Finding] = []
    for f in findings:
        if f.key() in allowed:
            matched.add(f.key())
        else:
            kept.append(f)
    for e in entries:
        if e.key() in matched:
            continue
        if scanned_rels is not None and e.path not in scanned_rels:
            continue
        if active_checkers is not None and e.checker not in active_checkers:
            continue
        kept.append(Finding(
            allowlist_rel, e.src_line, "allowlist",
            f"stale allowlist entry (no such finding): "
            f"{e.path}:{e.line} [{e.checker}]",
        ))
    return kept

"""Multi-host runtime initialization over the name_resolve rendezvous.

Counterpart of the reference's NCCL global-comm setup
(realhf/impl/model/comm/global_comm.py:48-163, torch.distributed TCP
rendezvous): on TPU the collective fabric is managed by the JAX runtime,
so "setting up comm" reduces to electing a coordinator through
name_resolve and calling `jax.distributed.initialize` on every host of a
partition. ICI collectives then happen inside jitted programs; DCN traffic
(weight sync, trajectories) stays on the host side (ZMQ / shared FS).
"""

from __future__ import annotations

import dataclasses
import os
from typing import List, Optional

from areal_tpu.base import logging as areal_logging
from areal_tpu.base import name_resolve, names, network

logger = areal_logging.getLogger("distributed")


@dataclasses.dataclass
class HostGroupInfo:
    """What a host process knows after joining its partition's group."""

    coordinator_address: str
    process_id: int
    num_processes: int


def setup_host_group(
    experiment_name: str,
    trial_name: str,
    group_name: str,
    host_rank: int,
    n_hosts: int,
    timeout: float = 300.0,
) -> HostGroupInfo:
    """Elect a coordinator via name_resolve and initialize jax.distributed.

    Single-host (n_hosts == 1) is a no-op besides returning the info —
    jax.distributed is not required, and local meshes work as-is.
    """
    if n_hosts == 1:
        return HostGroupInfo("localhost", 0, 1)

    key = names.distributed_coordinator(experiment_name, trial_name) + f"/{group_name}"
    if host_rank == 0:
        addr = f"{network.gethostip()}:{network.find_free_port()}"
        name_resolve.add(key, addr, keepalive_ttl=timeout, replace=True)
    else:
        addr = name_resolve.wait(key, timeout=timeout)

    import jax

    jax.distributed.initialize(
        coordinator_address=addr,
        num_processes=n_hosts,
        process_id=host_rank,
    )
    logger.info(
        "joined host group %s as %d/%d (coordinator %s)",
        group_name, host_rank, n_hosts, addr,
    )
    return HostGroupInfo(addr, host_rank, n_hosts)


def verify_host_mesh_slice(mesh, host_rank: int, n_hosts: int) -> dict:
    """Startup verification that this process hosts exactly its slice of
    a train mesh — the training-side mirror of the serving fleet's
    weight-shard check (generation_server: a sliced fetch must fail at
    configure time, not after a full transfer). Returns a small summary
    dict for logging; raises RuntimeError with an actionable message
    when the mesh/process topology is inconsistent:

    - the mesh spans a different number of processes than
      ``n_hosts`` (e.g. a single-process fake-device mesh configured as
      multi-host: ``jax.distributed`` never initialized on the peers);
    - the processes' device contributions are uneven (a mesh slice must
      be exactly 1/n_hosts of the devices);
    - this process contributes no devices at all.
    """
    import jax

    devs = list(mesh.devices.flat)
    procs = sorted({d.process_index for d in devs})
    if len(procs) != n_hosts:
        raise RuntimeError(
            f"train mesh spans {len(procs)} process(es) but "
            f"train_n_hosts={n_hosts}: each host must contribute exactly "
            f"its mesh slice. A single-process mesh cannot satisfy a "
            f"multi-host config — did setup_host_group "
            f"(jax.distributed.initialize) run on every host?"
        )
    mine = [d for d in devs if d.process_index == jax.process_index()]
    if not mine:
        raise RuntimeError(
            f"host {host_rank}/{n_hosts} contributes no devices to the "
            f"train mesh ({len(devs)} devices, processes {procs})"
        )
    if len(mine) * n_hosts != len(devs):
        raise RuntimeError(
            f"host {host_rank}/{n_hosts} hosts {len(mine)} of {len(devs)} "
            f"mesh devices — not an even 1/{n_hosts} slice; the mesh "
            f"shape must divide across hosts"
        )
    return {
        "n_hosts": n_hosts,
        "host_rank": host_rank,
        "local_devices": len(mine),
        "mesh_devices": len(devs),
    }

"""Decode-time MoE dispatch (ISSUE 17 satellite): decode routes through
the dropless grouped matmul by default (the training capacity formula
quantizes badly at decode row counts), AREAL_MOE_DECODE_* are the A/B
hooks, and the paged server's greedy stream matches the batch generator
token-for-token for MoE models. Also covers the packed decode-block MoE
telemetry columns surfaced via ServingEngine.metrics()."""

import jax
import numpy as np
import pytest

from areal_tpu.api.model_api import GenerationHyperparameters
from areal_tpu.engine.serving import GenRequest, ServingEngine
from areal_tpu.models.config import MoEConfig, TransformerConfig
from areal_tpu.models.generation import generate_tokens
from areal_tpu.models.moe import decode_moe_overrides
from areal_tpu.models.transformer import init_params
from tests.engine.serving_utils import run_requests as _run


def _cfg(dispatch="dropless"):
    # A fresh instance per engine: TransformerConfig hashes by identity,
    # so each gets its own jit trace — decode_moe_overrides is read at
    # trace time and must see the env of ITS run.
    return TransformerConfig(
        n_layers=2, hidden_dim=32, n_q_heads=4, n_kv_heads=2, head_dim=8,
        intermediate_dim=32, vocab_size=64, compute_dtype="float32",
        param_dtype="float32",
        moe=MoEConfig(num_experts=4, top_k=2, dispatch=dispatch,
                      expert_intermediate_dim=32),
    )


@pytest.fixture(scope="module")
def moe_params():
    return init_params(_cfg(), jax.random.PRNGKey(3))


def _serve_greedy(cfg, params, prompt, n=10):
    eng = ServingEngine(
        cfg, params, max_batch_size=2, max_seq_len=128,
        decode_block_steps=4, prompt_bucket=8, seed=0,
    )
    eng.start()
    try:
        res = _run(eng, [GenRequest(qid="g", input_ids=list(prompt),
                                    max_new_tokens=n, greedy=True)])["g"]
        if res.error is not None:
            raise RuntimeError(res.error)
        return res.output_ids, res.output_logprobs, eng.metrics()
    finally:
        eng.stop()


def test_decode_moe_overrides_env():
    assert decode_moe_overrides(_cfg("capacity")) == ("dropless", None)


def test_decode_moe_overrides_follows_model(monkeypatch):
    monkeypatch.setenv("AREAL_MOE_DECODE_DISPATCH", "model")
    monkeypatch.setenv("AREAL_MOE_DECODE_CAPACITY", "2.5")
    assert decode_moe_overrides(_cfg("capacity")) == ("capacity", 2.5)
    assert decode_moe_overrides(_cfg("dropless")) == ("dropless", 2.5)
    monkeypatch.setenv("AREAL_MOE_DECODE_DISPATCH", "bogus")
    with pytest.raises(ValueError, match="AREAL_MOE_DECODE_DISPATCH"):
        decode_moe_overrides(_cfg())


def test_moe_serving_greedy_matches_batch_generator(moe_params):
    prompt = [9, 21, 33, 4]
    g = GenerationHyperparameters(max_new_tokens=10, greedy=True)
    ref = generate_tokens(
        moe_params, _cfg(), [prompt], g, jax.random.PRNGKey(1),
        prompt_pad_multiple=8,
    )[0]
    out, lps, m = _serve_greedy(_cfg(), moe_params, prompt)
    assert out == ref["output_ids"]
    np.testing.assert_allclose(
        lps, ref["output_logprobs"], rtol=1e-4, atol=1e-5
    )
    # Decode-block router telemetry flowed through the packed columns:
    # dropless decode never drops, and a real router has entropy.
    assert m["moe_drop_rate"] == 0.0
    assert m["moe_router_entropy"] > 0.0


def test_moe_decode_capacity_override_matches_dropless(
    moe_params, monkeypatch
):
    """A generous decode capacity (no realized drops) must produce the
    same greedy stream as the default dropless decode — the two decode
    dispatches agree whenever nothing is dropped."""
    prompt = [5, 17, 2]
    base, _, m0 = _serve_greedy(_cfg(), moe_params, prompt)
    monkeypatch.setenv("AREAL_MOE_DECODE_DISPATCH", "capacity")
    monkeypatch.setenv("AREAL_MOE_DECODE_CAPACITY", "8.0")
    cap, _, m1 = _serve_greedy(_cfg(), moe_params, prompt)
    assert cap == base
    assert m0["moe_drop_rate"] == 0.0
    assert m1["moe_drop_rate"] == 0.0

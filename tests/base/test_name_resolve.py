"""name_resolve backend tests (mirrors reference tests/distributed/test_name_resolve.py)."""

import threading
import time

import pytest

from areal_tpu.base import name_resolve
from areal_tpu.base.name_resolve import (
    MemoryNameRecordRepository,
    NameEntryExistsError,
    NameEntryNotFoundError,
    NfsNameRecordRepository,
)


@pytest.fixture(params=["memory", "nfs"])
def repo(request, tmp_path):
    if request.param == "memory":
        r = MemoryNameRecordRepository()
    else:
        r = NfsNameRecordRepository(record_root=str(tmp_path / "nr"))
    yield r
    r.reset()


def test_add_get_delete(repo):
    repo.add("a/b/c", "v1")
    assert repo.get("a/b/c") == "v1"
    with pytest.raises(NameEntryExistsError):
        repo.add("a/b/c", "v2")
    repo.add("a/b/c", "v2", replace=True)
    assert repo.get("a/b/c") == "v2"
    repo.delete("a/b/c")
    with pytest.raises(NameEntryNotFoundError):
        repo.get("a/b/c")
    with pytest.raises(NameEntryNotFoundError):
        repo.delete("a/b/c")


def test_subtree(repo):
    repo.add("root/x/1", "a")
    repo.add("root/x/2", "b")
    repo.add("root/y", "c")
    assert repo.get_subtree("root/x") == ["a", "b"]
    assert len(repo.find_subtree("root")) == 3
    repo.clear_subtree("root/x")
    assert repo.get_subtree("root/x") == []
    assert repo.get("root/y") == "c"


def test_add_subentry(repo):
    k1 = repo.add_subentry("servers", "url1")
    k2 = repo.add_subentry("servers", "url2")
    assert k1 != k2
    assert sorted(repo.get_subtree("servers")) == ["url1", "url2"]


def test_wait(repo):
    def _later():
        time.sleep(0.2)
        repo.add("late/key", "done")

    t = threading.Thread(target=_later)
    t.start()
    assert repo.wait("late/key", timeout=5) == "done"
    t.join()
    with pytest.raises(TimeoutError):
        repo.wait("never/key", timeout=0.2)


def test_module_facade(tmp_path):
    name_resolve.reconfigure("nfs", record_root=str(tmp_path / "nr2"))
    name_resolve.add("k", "v")
    assert name_resolve.get("k") == "v"
    name_resolve.reset()


def test_nfs_cross_instance(tmp_path):
    # Two repo instances over the same root see each other's records.
    r1 = NfsNameRecordRepository(record_root=str(tmp_path / "shared"))
    r2 = NfsNameRecordRepository(record_root=str(tmp_path / "shared"))
    r1.add("peer/0", "addr0")
    assert r2.get("peer/0") == "addr0"
    r1.reset()

"""Opportunistic scheduler: spend every tunnel window on the most
valuable unbanked phase that fits it.

The daemon polls device availability with exponential backoff (each
probe is its own subprocess so a *wedged* probe — the 03:18 failure
mode — costs a timeout, not the daemon). Failures are classified
(:mod:`areal_tpu.bench.devices`): tunnel-down keeps polling, a
driver/version error aborts the daemon immediately — no amount of
waiting fixes a jaxlib mismatch.

The moment a window opens it dispatches, in priority order, the first
phase action that fits the *observed* window length:

- a phase whose compile record is banked but measure is not runs its
  measure pass (cache-warm, cheap);
- a phase with no compile record runs its compile pass first — banked
  as ``compile``, so even a window too short to measure anything still
  moves the round forward;
- estimates come from the phase registry; the observed window estimate
  is the median of recently completed up-windows (first window: the
  ``AREAL_BENCH_WINDOW_HINT_S`` optimistic default).

Every dispatch goes through :mod:`areal_tpu.bench.runner`, so a phase
that wedges mid-window is killed at its deadline and the daemon goes
back to polling.
"""

from __future__ import annotations

import dataclasses
import json
import os
import statistics
import subprocess
import sys
import time
from typing import Callable, Dict, List, Optional, Tuple

from areal_tpu.base import env_registry
from areal_tpu.bench import bank, phases, runner
from areal_tpu.bench._util import log, repo_root
from areal_tpu.bench.devices import classify_device_error


@dataclasses.dataclass
class ProbeResult:
    status: str  # "up" | "tunnel" | "driver" | "wedged"
    platform: Optional[str] = None
    n_devices: int = 0
    device_kind: Optional[str] = None
    detail: str = ""


_PROBE_SNIPPET = """\
import json, sys
from areal_tpu.utils.jaxenv import apply_jax_platform_override
apply_jax_platform_override()
try:
    import jax
    devs = jax.devices()
    print(json.dumps({
        "ok": True, "platform": devs[0].platform, "n": len(devs),
        "kind": getattr(devs[0], "device_kind", None),
    }))
except Exception as e:
    print(json.dumps({"ok": False, "error": repr(e)}))
"""


def probe_devices(timeout_s: float = 60.0) -> ProbeResult:
    """Ask a throwaway subprocess what `jax.devices()` says right now.
    A probe that neither answers nor dies within `timeout_s` is reported
    as 'wedged' (half-up tunnels hang device init indefinitely — that
    must never hang the daemon)."""
    repo = repo_root()
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    try:
        out = subprocess.run(
            [sys.executable, "-c", _PROBE_SNIPPET], env=env, cwd=repo,
            capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return ProbeResult("wedged", detail=f"probe exceeded {timeout_s:.0f}s")
    try:
        payload = json.loads(out.stdout.strip().splitlines()[-1])
    except (ValueError, IndexError):
        # The snippet never reached its print — a native abort (SIGABRT
        # in the PJRT plugin, import-time jaxlib mismatch) looks exactly
        # like this. Classify the captured output before defaulting to
        # tunnel, or a version skew polls for the whole runtime budget.
        text = (out.stderr or "") + (out.stdout or "")
        kind = classify_device_error(text)
        return ProbeResult(
            "driver" if kind == "driver" else "tunnel",
            detail=f"probe rc={out.returncode}: {text[-500:]}",
        )
    if payload.get("ok"):
        return ProbeResult(
            "up", platform=payload["platform"], n_devices=payload["n"],
            device_kind=payload.get("kind"),
        )
    kind = classify_device_error(payload.get("error", ""))
    return ProbeResult(
        "driver" if kind == "driver" else "tunnel",
        detail=payload.get("error", ""),
    )


class BenchDaemon:
    """Poll-classify-dispatch loop. All timing/IO seams are injectable
    so the scheduling policy is unit-testable without devices."""

    def __init__(
        self,
        bank_path: Optional[str] = None,
        phase_list: Optional[List[phases.PhaseSpec]] = None,
        probe_fn: Callable[[], ProbeResult] = None,
        dispatch_fn: Callable[[str, str, str], Dict] = None,
        poll_interval_s: Optional[float] = None,
        max_poll_interval_s: float = 120.0,
        window_hint_s: Optional[float] = None,
        clock=time.monotonic,
        sleep=time.sleep,
    ):
        self.bank_path = bank.bank_dir(bank_path)
        self.phase_list = (
            phase_list if phase_list is not None else phases.default_phases()
        )
        self.probe_fn = probe_fn or probe_devices
        self.dispatch_fn = dispatch_fn or (
            lambda name, pass_, b: runner.run_phase(name, pass_, bank_path=b)
        )
        self.poll_interval_s = (
            poll_interval_s
            if poll_interval_s is not None
            else env_registry.get_float("AREAL_BENCH_POLL_S")
        )
        self.max_poll_interval_s = max_poll_interval_s
        self.window_hint_s = (
            window_hint_s
            if window_hint_s is not None
            else env_registry.get_float("AREAL_BENCH_WINDOW_HINT_S")
        )
        self.clock = clock
        self.sleep = sleep
        # Completed up-window durations, most recent last.
        self.window_history: List[float] = []
        self._window_opened_at: Optional[float] = None
        # In-memory failure counts per (phase, pass): a deterministically
        # crashing phase must not eat every window the tunnel offers.
        self.max_attempts = env_registry.get_int("AREAL_BENCH_MAX_ATTEMPTS")
        self._attempts: Dict[Tuple[str, str], int] = {}

    # -- window accounting ---------------------------------------------

    def window_estimate_s(self) -> float:
        """Median of recently completed up-windows — floored by the AGE
        of the current window: a device that has already stayed up
        longer than the historical estimate is evidently in a longer
        window, so min_window-gated phases must not livelock on stale
        history."""
        if not self.window_history:
            est = self.window_hint_s
        else:
            est = statistics.median(self.window_history[-5:])
        if self._window_opened_at is not None:
            est = max(est, self.clock() - self._window_opened_at)
        return est

    def _note_up(self):
        if self._window_opened_at is None:
            self._window_opened_at = self.clock()

    def _note_down(self):
        if self._window_opened_at is not None:
            self.window_history.append(self.clock() - self._window_opened_at)
            self._window_opened_at = None

    # -- phase selection -----------------------------------------------

    def pending_actions(self, platform: str) -> List[Tuple[phases.PhaseSpec, str]]:
        """(spec, pass) pairs still unbanked, priority order. A proxy
        phase banks on any platform; a driver phase's records only count
        on the platform the daemon is currently facing."""
        out = []
        for spec in self.phase_list:
            plat = "cpu" if spec.proxy else platform
            if bank.is_banked(self.bank_path, spec.name, "measure", plat):
                continue
            if spec.est_compile_s > 0 and not bank.is_banked(
                    self.bank_path, spec.name, "compile", plat):
                action = (spec, "compile")
            else:
                action = (spec, "measure")
            if self._attempts.get((spec.name, action[1]), 0) \
                    >= self.max_attempts:
                continue
            out.append(action)
        return out

    def _all_measured(self, platform: str) -> bool:
        return all(
            bank.is_banked(self.bank_path, s.name, "measure",
                           "cpu" if s.proxy else platform)
            for s in self.phase_list
        )

    def select_action(
        self, platform: str
    ) -> Optional[Tuple[phases.PhaseSpec, str]]:
        """Highest-priority pending action whose estimated cost fits the
        observed window; if nothing fits, the cheapest pending action —
        trying beats idling inside an open window."""
        pending = self.pending_actions(platform)
        if not pending:
            return None
        if platform == "cpu":
            return pending[0]  # no tunnel to flap: just go in order
        window = self.window_estimate_s()
        # min_window is a hard gate: dispatching a measure pass into a
        # window known to be too short burns an attempt for nothing.
        eligible = [
            (spec, pass_) for spec, pass_ in pending
            if not (pass_ == "measure" and spec.min_window_s > window)
        ]
        if not eligible:
            # Wait: window_estimate_s grows with the current window's
            # age, so a genuinely long window unlocks these eventually.
            return None
        for spec, pass_ in eligible:
            if spec.cost(pass_) <= window:
                return spec, pass_
        return min(eligible, key=lambda sp: sp[0].cost(sp[1]))

    # -- main loop ------------------------------------------------------

    def step(self) -> str:
        """One poll-or-dispatch iteration. Returns the daemon state:
        'complete' | 'gave_up' | 'driver_error' | 'dispatched' |
        'waiting' (up, but every eligible action is window-gated) |
        'down'."""
        probe = self.probe_fn()
        if probe.status == "driver":
            self._note_down()
            log(f"bench-daemon: driver/version error, aborting: "
                f"{probe.detail[:300]}")
            return "driver_error"
        if probe.status in ("tunnel", "wedged"):
            self._note_down()
            return "down"
        self._note_up()
        action = self.select_action(probe.platform)
        if action is None:
            if self._all_measured(probe.platform):
                return "complete"
            if self.pending_actions(probe.platform):
                # Work remains but every eligible action is min_window-
                # gated: hold on — the estimate grows with this window's
                # age, so a long window unlocks them without burning an
                # attempt.
                return "waiting"
            # Pending work exists but every action exhausted its attempt
            # budget: that is giving up, not completing — the caller must
            # not publish (or clear) this round as done.
            log("bench-daemon: unbanked phases exhausted "
                f"{self.max_attempts} attempts; giving up")
            return "gave_up"
        spec, pass_ = action
        log(f"bench-daemon: window open (est {self.window_estimate_s():.0f}s) "
            f"-> {spec.name}/{pass_} (est {spec.cost(pass_):.0f}s)")
        rec = self.dispatch_fn(spec.name, pass_, self.bank_path)
        log(f"bench-daemon: {spec.name}/{pass_} -> {rec['status']}")
        if rec["status"] != "ok":
            key = (spec.name, pass_)
            self._attempts[key] = self._attempts.get(key, 0) + 1
            # Mid-phase device loss closes the window for estimation
            # purposes; a plain phase bug should not.
            tail = (rec.get("tail") or "") + (rec.get("error") or "")
            if rec["status"] == "timeout" or \
                    classify_device_error(tail) == "tunnel":
                self._note_down()
        return "dispatched"

    def run(self, max_runtime_s: Optional[float] = None) -> str:
        """Loop until every phase is banked, a driver error aborts, or
        the runtime budget expires. Returns the final state."""
        deadline = (
            self.clock() + max_runtime_s if max_runtime_s is not None else None
        )
        delay = self.poll_interval_s
        while True:
            state = self.step()
            if state in ("complete", "gave_up", "driver_error"):
                return state
            # Budget check on EVERY non-terminal state — a dispatch can
            # burn a whole phase deadline, and repeated dispatches must
            # not overrun the caller's budget unchecked.
            if deadline is not None and self.clock() >= deadline:
                return "budget_exhausted"
            if state == "dispatched":
                delay = self.poll_interval_s  # device was just up: stay hot
                continue
            if state == "waiting":
                # Up but window-gated: re-check at the base cadence (no
                # backoff — the estimate grows as this window ages).
                delay = self.poll_interval_s
                self.sleep(delay)
                continue
            self.sleep(delay)
            delay = min(delay * 2, self.max_poll_interval_s)

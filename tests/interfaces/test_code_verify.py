"""Code verifier tests (reference functioncall/code/testing_util.py
behaviors: stdin/stdout + call-based styles, per-case limits, sandboxing)."""

import json
import os
import time

import pytest

from areal_tpu.functioncall.code_verify import (
    code_verify,
    extract_code_block,
    run_test_cases,
)

# Per-case verifier timeout for tests that EXPECT success: each case is a
# fresh subprocess (interpreter startup + rlimit setup), so under a
# parallel test run on a loaded machine the 8s default can be overshot by
# scheduling alone (VERDICT r5: these pass in isolation, fail under
# load). Generous here — a healthy case finishes in well under a second,
# so the slack only ever buys deflaking, never hides a real hang.
# AREAL_TEST_TIMEOUT_SCALE stretches it further on loaded CI.
from tests.fixtures import scale_timeout

T = scale_timeout(float(os.environ.get("AREAL_TEST_VERIFY_TIMEOUT", 30.0)))

STDIN_SOLUTION = """Here is my solution:
```python
n = int(input())
print(n * 2)
```
"""

CALL_SOLUTION = """```python
def add(a, b):
    return a + b
```"""

CLASS_SOLUTION = """```python
class Solution:
    def twice(self, x):
        return [v * 2 for v in x]
```"""


def test_stdin_style_pass_and_fail():
    cases = {"inputs": ["3\n", "10\n"], "outputs": ["6\n", "20\n"]}
    assert code_verify(STDIN_SOLUTION, cases, timeout=T)
    bad = {"inputs": ["3\n"], "outputs": ["7\n"]}
    assert not code_verify(STDIN_SOLUTION, bad, timeout=T)


def test_stdin_wire_format_as_string():
    cases = json.dumps({"inputs": ["4\n"], "outputs": ["8\n"]})
    assert code_verify(STDIN_SOLUTION, cases, timeout=T)


def test_float_tolerant_stdout():
    sol = "```python\nprint(0.1 + 0.2)\n```"
    assert code_verify(sol, [{"input": "", "output": "0.3\n"}], timeout=T)


def test_call_based_function():
    cases = {"inputs": [[1, 2], [5, -3]], "outputs": [3, 2], "fn_name": "add"}
    assert code_verify(CALL_SOLUTION, cases, timeout=T)
    bad = {"inputs": [[1, 2]], "outputs": [4], "fn_name": "add"}
    assert not code_verify(CALL_SOLUTION, bad, timeout=T)


def test_call_based_solution_class():
    cases = {
        "inputs": [[[1, 2, 3]]],
        "outputs": [[2, 4, 6]],
        "fn_name": "twice",
    }
    assert code_verify(CLASS_SOLUTION, cases, timeout=T)


def test_per_case_results_and_cap():
    cases = {"inputs": ["1\n", "2\n", "3\n"], "outputs": ["2\n", "5\n", "6\n"]}
    res = run_test_cases(STDIN_SOLUTION, cases, timeout=T)
    assert res == [True, False, True]
    assert len(run_test_cases(STDIN_SOLUTION, cases, max_cases=2, timeout=T)) == 2


def test_timeout_kills_infinite_loop():
    sol = "```python\nwhile True:\n    pass\n```"
    t0 = time.monotonic()
    assert not code_verify(sol, [{"input": "", "output": ""}], timeout=2.0)
    # The kill must not take unboundedly long, but the wall bound is
    # wide (vs the 2s verifier timeout): subprocess spawn + reap under a
    # loaded parallel test run can eat many seconds by itself.
    assert time.monotonic() - t0 < T


def test_no_code_block_fails_all():
    res = run_test_cases("no code here", {"inputs": ["1"], "outputs": ["1"]})
    assert res == [False]


def test_sandbox_blocks_os_system():
    sol = "```python\nimport os\nos.system('echo pwned')\nprint('done')\n```"
    # os.system is None'd by the guard preamble -> TypeError -> case fails
    assert not code_verify(sol, [{"input": "", "output": "done\n"}], timeout=T)


def test_extract_code_block_picks_last():
    text = "```python\nprint(1)\n```\nand\n```python\nprint(2)\n```"
    assert extract_code_block(text) == "print(2)\n"

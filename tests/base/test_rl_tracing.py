"""RL-trace recorder unit tests (ISSUE 3 tentpole).

Pins the two hard contracts:

- DISABLED is a true no-op: span calls cost one branch, no recorder is
  ever allocated, no shard files appear (the acceptance criterion).
- ENABLED records parent-linked spans into per-worker JSONL shards that
  the aggregator merges with intact flow links, and the trace context
  survives both transports' metadata (request_reply_stream Payload,
  push/pull JSON).
"""

import json
import os

import pytest

from areal_tpu.base import tracing
from areal_tpu.system import push_pull_stream as pps
from areal_tpu.system import request_reply_stream as rrs
from areal_tpu.utils import rl_trace


@pytest.fixture
def traced(tmp_path, monkeypatch):
    """Tracing ON into a fresh shard dir; restored + reset afterwards."""
    d = str(tmp_path / "rl_trace")
    monkeypatch.setenv("AREAL_RL_TRACE", "1")
    monkeypatch.setenv("AREAL_RL_TRACE_DIR", d)
    tracing.reconfigure()
    tracing.configure_worker("test_worker/0")
    yield d
    tracing.reconfigure()


@pytest.fixture
def untraced(tmp_path, monkeypatch):
    d = str(tmp_path / "rl_trace_off")
    monkeypatch.setenv("AREAL_RL_TRACE", "0")
    monkeypatch.setenv("AREAL_RL_TRACE_DIR", d)
    tracing.reconfigure()
    yield d
    tracing.reconfigure()


def _load_spans(trace_dir):
    spans = []
    for name in os.listdir(trace_dir):
        if not name.endswith(".jsonl"):
            continue
        with open(os.path.join(trace_dir, name)) as f:
            for line in f:
                rec = json.loads(line)
                if rec.get("kind") == "span":
                    spans.append(rec)
    return spans


# ---------------------------------------------------------------------------
# No-op fast path
# ---------------------------------------------------------------------------


def test_disabled_is_true_noop(untraced):
    with tracing.span("a", attr=1) as ctx:
        assert ctx is None
        tracing.event("b")
        tracing.record_span("c", tracing.now_ns())
        assert tracing.start_span("d") is None
        assert tracing.inject() is None
        assert tracing.current() is None
    tracing.flush()
    # The acceptance pin: no recorder allocation, no shard files.
    assert tracing.recorder() is None
    assert not os.path.exists(untraced) or not os.listdir(untraced)


def test_disabled_inject_into_returns_same_dict(untraced):
    d = {"x": 1}
    assert tracing.inject_into(d) is d
    assert tracing.extract_from({"x": 1}) is None


# ---------------------------------------------------------------------------
# Recording + shard format
# ---------------------------------------------------------------------------


def test_nested_spans_share_trace_and_parent_link(traced):
    with tracing.span("outer") as outer:
        with tracing.span("inner") as inner:
            assert inner.trace_id == outer.trace_id
    tracing.flush()
    spans = {s["name"]: s for s in _load_spans(traced)}
    assert spans["inner"]["trace"] == spans["outer"]["trace"]
    assert spans["inner"]["parent"] == spans["outer"]["span"]
    assert spans["outer"]["parent"] is None
    assert spans["inner"]["start_ns"] >= spans["outer"]["start_ns"]


def test_manual_span_and_explicit_record(traced):
    ms = tracing.start_span("episode", qid="q0")
    t0 = tracing.now_ns()
    tracing.record_span("residency", t0, t0 + 1000, ctx=ms.ctx, version_start=3)
    ms.end(accepted=True)
    ms.end(accepted=False)  # idempotent: second end is a no-op
    tracing.flush()
    spans = {s["name"]: s for s in _load_spans(traced)}
    assert spans["episode"]["attrs"]["accepted"] is True
    assert spans["residency"]["parent"] == spans["episode"]["span"]
    assert spans["residency"]["attrs"]["version_start"] == 3
    header = [
        json.loads(line)
        for line in open(
            os.path.join(traced, os.listdir(traced)[0])
        )
    ][0]
    assert header["kind"] == "header"
    assert header["worker"] == "test_worker/0"
    assert header["anchor_wall_ns"] > 0 and header["anchor_mono_ns"] > 0


def test_inject_extract_roundtrip(traced):
    with tracing.span("root") as ctx:
        d = tracing.inject_into({"payload": 1})
        assert d["payload"] == 1
        got = tracing.extract_from(d)
        assert got == ctx
        assert "__rl_trace__" not in d  # extract_from pops the key


def test_ring_buffer_overflow_drops_oldest(tmp_path, monkeypatch):
    d = str(tmp_path / "ring")
    monkeypatch.setenv("AREAL_RL_TRACE", "1")
    monkeypatch.setenv("AREAL_RL_TRACE_DIR", d)
    monkeypatch.setenv("AREAL_RL_TRACE_RING", "8")
    tracing.reconfigure()
    try:
        # Below the flush batch size but above the ring capacity: the
        # ring must drop oldest instead of growing.
        for i in range(20):
            tracing.event(f"e{i}")
        rec = tracing.recorder()
        assert rec is not None
        tracing.flush()
        shard = rl_trace.load_shard(
            os.path.join(d, os.listdir(d)[0])
        )
        assert shard.n_dropped > 0
        assert len(shard.spans) <= 8
    finally:
        tracing.reconfigure()


# ---------------------------------------------------------------------------
# Transport metadata propagation
# ---------------------------------------------------------------------------


def test_request_reply_stream_propagates_ctx(
    traced, tmp_name_resolve, experiment_context
):
    exp, trial = experiment_context
    master = rrs.make_master_stream(exp, trial)
    worker = rrs.make_worker_stream(exp, trial, "model_worker/0")
    try:
        with tracing.span("master.step") as ctx:
            [rid] = master.request(["model_worker/0"], "mfc", [{"x": 1}])
        req = worker.poll(block=True, timeout_ms=5000)
        got = tracing.extract(req.trace_ctx)
        assert got is not None
        assert got.trace_id == ctx.trace_id
        assert got.span_id == ctx.span_id
        worker.reply_to(req, data=None)
        master.poll(rid, block=True, timeout=10)
    finally:
        master.close()
        worker.close()


def test_push_pull_stream_propagates_and_strips_ctx(traced):
    puller = pps.ZMQJsonPuller(host="127.0.0.1")
    pusher = pps.ZMQJsonPusher("127.0.0.1", puller.port)
    try:
        with tracing.span("episode") as ctx:
            pusher.push({"ids": ["a"], "v": 2})
        got = puller.pull(timeout_ms=5000)
        # Payload intact, reserved key stripped, ctx surfaced.
        assert got == {"ids": ["a"], "v": 2}
        assert puller.last_trace_ctx is not None
        assert puller.last_trace_ctx.trace_id == ctx.trace_id
    finally:
        pusher.close()
        puller.close()


def test_push_pull_disabled_has_no_ctx(untraced):
    puller = pps.ZMQJsonPuller(host="127.0.0.1")
    pusher = pps.ZMQJsonPusher("127.0.0.1", puller.port)
    try:
        pusher.push({"k": 1})
        got = puller.pull(timeout_ms=5000)
        assert got == {"k": 1}
        assert puller.last_trace_ctx is None
    finally:
        pusher.close()
        puller.close()


# ---------------------------------------------------------------------------
# Aggregation + validation
# ---------------------------------------------------------------------------


def test_validate_catches_dangling_parent(tmp_path):
    shard_path = tmp_path / "w0.1.jsonl"
    shard_path.write_text(
        "\n".join(
            [
                json.dumps(
                    {
                        "kind": "header", "worker": "w0", "pid": 1,
                        "anchor_wall_ns": 10**18, "anchor_mono_ns": 10**9,
                    }
                ),
                json.dumps(
                    {
                        "kind": "span", "name": "orphan", "trace": "t1",
                        "span": "s1", "parent": "NO_SUCH_SPAN",
                        "start_ns": 10**9, "end_ns": 10**9 + 100,
                    }
                ),
            ]
        )
        + "\n"
    )
    shards = rl_trace.load_shards(str(tmp_path))
    problems = rl_trace.validate(shards)
    assert any("dangling parent" in p for p in problems)


def test_dangling_parent_waived_when_ring_overflowed(tmp_path):
    """A shard that RECORDED ring-buffer drops may legitimately have
    dangling parents (the oldest spans were dropped by design): validate
    marks them waived and the merge script exits 0."""
    import subprocess
    import sys

    shard_path = tmp_path / "w0.1.jsonl"
    shard_path.write_text(
        "\n".join(
            [
                json.dumps(
                    {
                        "kind": "header", "worker": "w0", "pid": 1,
                        "anchor_wall_ns": 10**18, "anchor_mono_ns": 10**9,
                    }
                ),
                json.dumps({"kind": "dropped", "count": 5}),
                json.dumps(
                    {
                        "kind": "span", "name": "orphan", "trace": "t1",
                        "span": "s1", "parent": "DROPPED_SPAN",
                        "start_ns": 10**9, "end_ns": 10**9 + 100,
                    }
                ),
            ]
        )
        + "\n"
    )
    shards = rl_trace.load_shards(str(tmp_path))
    problems = rl_trace.validate(shards)
    assert problems and all(
        p.startswith(rl_trace.WAIVED_PREFIX) for p in problems
    )
    r = subprocess.run(
        [sys.executable, "scripts/merge_rl_trace.py", str(tmp_path)],
        cwd=os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        ))),
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, r.stderr


def test_merge_script_exits_nonzero_on_dangling_ref(tmp_path):
    import subprocess
    import sys

    shard_path = tmp_path / "w0.1.jsonl"
    shard_path.write_text(
        json.dumps(
            {
                "kind": "span", "name": "x", "trace": "t", "span": "s",
                "parent": "missing", "start_ns": 1, "end_ns": 2,
            }
        )
        + "\n"
    )
    r = subprocess.run(
        [sys.executable, "scripts/merge_rl_trace.py", str(tmp_path)],
        cwd=os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)
        ))),
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 1
    assert "dangling parent" in r.stderr


def test_merge_and_reports_end_to_end(traced):
    # A miniature rollout timeline recorded in-process: episode ->
    # chunk -> buffer residency -> train step consuming the trace.
    ep = tracing.start_span("rollout.episode", qid="q0")
    with tracing.use_ctx(ep.ctx):
        with tracing.span("gen.chunk", server="s0", reprefill_tokens=12):
            pass
        tracing.event("gen.interrupted", qid="q0")
    t0 = tracing.now_ns()
    tracing.record_span(
        "buffer.wait", t0, t0 + 5_000_000, ctx=ep.ctx,
        version_start=1, version_end=2, train_step=3, rpc="actor_train",
    )
    ep.end(accepted=True)
    with tracing.span(
        "master.mfc.actor_train", itype="train_step",
        consumed_traces=[ep.ctx.trace_id],
    ):
        pass
    tracing.flush()

    shards = rl_trace.load_shards(traced)
    assert rl_trace.validate(shards) == []
    merged = rl_trace.merge_to_chrome(shards)
    events = merged["traceEvents"]
    slices = [e for e in events if e.get("ph") == "X"]
    flows = [e for e in events if e.get("ph") in ("s", "t", "f")]
    assert {e["name"] for e in slices} >= {
        "rollout.episode", "gen.chunk", "buffer.wait", "master.mfc.actor_train",
    }
    assert flows, "expected flow events stitching the rollout trace"
    # Derived reports.
    hist = rl_trace.staleness_histogram(shards)
    assert hist == {2: 1}  # train_step 3 - version_start 1
    phases = rl_trace.phase_latency(shards)
    assert phases["interrupted_reprefill"]["tokens"] == 12
    assert phases["buffer_wait"]["count"] == 1
    summary = rl_trace.summarize(traced)
    assert "overlap_score" in summary
    report = rl_trace.format_report(shards)
    assert "staleness histogram" in report
    assert "overlap score" in report

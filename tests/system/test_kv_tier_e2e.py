"""ISSUE 11 acceptance: fleet-wide tiered KV plane across real process
boundaries — 2 unified GenerationServer processes (real ServingEngines
on CPU jax, host KV tiers armed, SMALL prefix budgets so pool pressure
spills) behind a real GserverManager with session affinity DISABLED.

Asserted end to end:
- a session parks its prefix on server A (turn 1), the manager's
  /kv/index poll folds it into the global prefix index, and the turn-2
  request routed to server B carries ``kv_source`` — B pulls the prefix
  from A over /kv/{manifest,chunk} (hash-verified chunks), imports it,
  and the continuation admits as a delta prefill with greedy output
  IDENTICAL to a session that never left A;
- chaos (AREAL_FAULTS): a later restore on B is injected to fail — the
  continuation silently degrades to a full re-prefill and still
  completes (restore is an optimization, never a correctness
  dependency);
- under sustained pressure (4 concurrent 2-turn sessions against
  64-token prefix budgets) every continuation completes, spills
  happened fleet-wide, and kv_prefix_lost_total stays ZERO — spill,
  not loss.

Time budget: ~45 s (2 CPU-jax child processes + warm XLA cache; one
fleet serves all three phases).
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request
import uuid

import pytest

from tests import fixtures

# Multi-process, compile-bound: keep off shared workers (pytest.ini).
pytestmark = [pytest.mark.serial, pytest.mark.chaos]

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

MODEL_CFG = dict(
    n_layers=2, hidden_dim=32, n_q_heads=2, n_kv_heads=2, head_dim=16,
    intermediate_dim=64, vocab_size=64, compute_dtype="float32",
    param_dtype="float32",
)

CHILD = '''
import os, sys
sys.path.insert(0, %(repo)r)
import jax; jax.config.update("jax_platforms", "cpu")
from areal_tpu.base import name_resolve
name_resolve.reconfigure("nfs", record_root=%(nr)r)
from areal_tpu.api.system_api import GenerationServerConfig
from areal_tpu.api.config import ModelAbstraction
from areal_tpu.system.generation_server import GenerationServer
import areal_tpu.engine.factories  # registry
cfg = GenerationServerConfig(
    experiment_name=%(exp)r, trial_name=%(trial)r, server_index=%(idx)d,
    model=ModelAbstraction("tpu_transformer", args=dict(config=%(model_cfg)r)),
    max_concurrent_requests=2, max_seq_len=256, kv_page_size=8,
    decode_block_steps=4, prompt_bucket=16, prefill_chunk=16,
    prefix_cache_tokens=64, kv_tier_bytes=1 << 20, seed=0,
)
w = GenerationServer()
w.configure(cfg, experiment_name=cfg.experiment_name, trial_name=cfg.trial_name,
            worker_name=cfg.worker_name)
w.run()
'''

PROMPT = list(range(1, 33))  # 32 tokens: chunked-prefill path
TURN2_EXTRA = [50, 51]


def _post(url, path, payload, timeout=120):
    req = urllib.request.Request(
        url + path, json.dumps(payload).encode(),
        {"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _get_json(url, timeout=30):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def _metrics(url):
    text = urllib.request.urlopen(url + "/metrics", timeout=30).read().decode()
    out = {}
    for line in text.splitlines():
        parts = line.split()
        if len(parts) == 2:
            try:
                out[parts[0]] = float(parts[1])
            except ValueError:
                out[parts[0]] = parts[1]
    return out


def _gen(url, qid, input_ids, max_new, kv_source=None):
    payload = {
        "qid": qid, "input_ids": list(input_ids),
        "gconfig": {"max_new_tokens": max_new, "greedy": True},
    }
    if kv_source:
        payload["kv_source"] = kv_source
    return _post(url, "/generate", payload)


def _wait_until(cond, timeout, msg, proc_check=None):
    deadline = time.monotonic() + fixtures.scale_timeout(timeout)
    while time.monotonic() < deadline:
        if proc_check is not None:
            proc_check()
        if cond():
            return
        time.sleep(0.1)
    raise AssertionError(f"timed out waiting for {msg}")


@pytest.mark.timeout(600)
def test_session_resumes_on_other_server_via_global_index(tmp_path):
    from areal_tpu.api.system_api import GserverManagerConfig
    from areal_tpu.base import name_resolve, names
    from areal_tpu.system.gserver_manager import GserverManager

    nr = str(tmp_path / "nr")
    exp, trial = f"kvtier-{uuid.uuid4().hex[:6]}", "t0"
    repo = name_resolve.reconfigure("nfs", record_root=nr)

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""
    env["AREAL_HEALTH_TTL"] = "60"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")

    procs, logs, cleanup = [], [], []
    try:
        for idx in range(2):
            child_env = dict(env)
            if idx == 1:
                # Chaos arm: server 1's SECOND restore attempt fails
                # (the first is the parity peer pull below, which must
                # succeed). The affected continuation degrades to a
                # full re-prefill and still completes.
                child_env["AREAL_FAULTS"] = (
                    "gserver.kv_restore@generation_server/1=raise:k=2"
                )
            log_path = tmp_path / f"server{idx}.log"
            log_f = open(log_path, "w")
            logs.append(log_path)
            cleanup.append(log_f.close)
            procs.append(subprocess.Popen(
                [sys.executable, "-c", CHILD % dict(
                    repo=REPO, nr=nr, exp=exp, trial=trial, idx=idx,
                    model_cfg=MODEL_CFG,
                )],
                env=child_env, cwd=REPO, stdout=log_f,
                stderr=subprocess.STDOUT,
            ))

        def alive():
            for i, p in enumerate(procs):
                assert p.poll() is None, (
                    f"server {i} died:\n" + logs[i].read_text()[-3000:]
                )

        urls = {}

        def discovered():
            alive()
            for i in range(2):
                if i not in urls:
                    try:
                        urls[i] = name_resolve.get(
                            names.gen_server_url(exp, trial, str(i))
                        )
                    except name_resolve.NameEntryNotFoundError:
                        return False
            return True

        _wait_until(discovered, 240, "server discovery")
        a_url, b_url = urls[0], urls[1]

        m = GserverManager()
        m.configure(GserverManagerConfig(
            experiment_name=exp, trial_name=trial, model_name="actor",
            n_servers=2, train_batch_size=4, max_head_offpolicyness=1000,
            health_check_interval=0.5, session_affinity=False,
            schedule_policy="round_robin",
        ))
        mt = threading.Thread(target=m.run, daemon=True)
        mt.start()
        cleanup.append(lambda: mt.join(timeout=10))
        _wait_until(lambda: len(m._healthy_urls()) == 2, 60,
                    "manager sees 2 healthy servers", proc_check=alive)

        # --- Turn 1: two sessions park on server A. "sess/0" parks
        # first, so when "ref/0" parks after it the 64-token budget
        # trims the OLDEST entry — sess/0's prefix SPILLS to A's host
        # tier instead of being destroyed.
        t1 = _gen(a_url, "sess/0", PROMPT, 8)
        assert len(t1["output_ids"]) == 8, t1
        ref1 = _gen(a_url, "ref/0", PROMPT, 8)
        # Same weights on both sessions: greedy turn-1 outputs agree.
        assert ref1["output_ids"] == t1["output_ids"]
        _wait_until(
            lambda: _metrics(a_url)["areal:kv_spill_total"] >= 1.0,
            30, "turn-1 prefix spilled to A's tier", proc_check=alive,
        )

        # --- The manager's /kv/index poll folds A's holdings into the
        # global prefix index.
        _wait_until(
            lambda: _get_json(m.address + "/status")["kv_tier"][
                "index_entries"] >= 1,
            30, "global prefix index learned A's holdings",
            proc_check=alive,
        )

        # --- Turn 2 for sess/0, scheduled through the manager with
        # affinity DISABLED, until round-robin lands it on B: the
        # response must carry kv_source=A (the index hint).
        turn2 = PROMPT + [int(t) for t in t1["output_ids"]] + TURN2_EXTRA
        sched = None
        for _ in range(4):
            s = _post(m.address, "/schedule_request", {
                "qid": "sess/0", "prompt_len": len(turn2),
                "new_token_budget": 6,
            }, timeout=30)
            if s.get("url") == b_url:
                sched = s
                break
        assert sched is not None, "round robin never offered server B"
        assert sched.get("kv_source") == a_url, sched

        out_b = _gen(b_url, "sess/0", turn2, 6, kv_source=sched["kv_source"])
        assert len(out_b["output_ids"]) == 6, out_b

        # Greedy parity: the same turn-2 on the server that never lost
        # the session (ref/0 stayed parked on A) produces identical
        # tokens — the pulled prefix is the real KV, not an
        # approximation.
        ref2_prompt = (
            PROMPT + [int(t) for t in ref1["output_ids"]] + TURN2_EXTRA
        )
        out_ref = _gen(a_url, "ref/0", ref2_prompt, 6)
        assert out_ref["output_ids"] == out_b["output_ids"], (
            out_ref["output_ids"], out_b["output_ids"],
        )

        # The hop really happened: B pulled from a peer and admitted a
        # delta prefill; A served the manifest+chunks.
        m_b = _metrics(b_url)
        assert m_b["areal:kv_tier_peer_hits"] >= 1.0, m_b
        assert m_b["areal:prefix_cache_hits"] >= 1.0
        m_a = _metrics(a_url)
        assert m_a["areal:kv_manifests_served"] >= 1.0
        assert m_a["areal:kv_chunks_served"] >= 1.0

        # --- Pressure + chaos phase: 4 concurrent 2-turn sessions
        # against the 64-token budgets force spills on both servers;
        # server 1's armed restore failure (k=2) hits one of the
        # continuations. EVERY turn must still complete.
        results = {}
        rlock = threading.Lock()

        def run_session(i):
            qid = f"load/{i}"
            prompt = [(3 + i + j) % 60 + 1 for j in range(24)]
            try:
                sched = _post(m.address, "/schedule_request", {
                    "qid": qid, "prompt_len": len(prompt),
                    "new_token_budget": 6,
                }, timeout=30)
                o1 = _gen(sched["url"], qid, prompt, 6,
                          kv_source=sched.get("kv_source"))
                p2 = prompt + [int(t) for t in o1["output_ids"]] + [9]
                sched2 = _post(m.address, "/schedule_request", {
                    "qid": qid, "prompt_len": len(p2),
                    "new_token_budget": 6,
                }, timeout=30)
                o2 = _gen(sched2["url"], qid, p2, 6,
                          kv_source=sched2.get("kv_source"))
                ok = len(o1["output_ids"]) == 6 and len(o2["output_ids"]) == 6
            except Exception as e:  # noqa: BLE001 — counted as failure
                ok = False, repr(e)
            with rlock:
                results[qid] = ok

        threads = [
            threading.Thread(target=run_session, args=(i,), daemon=True)
            for i in range(4)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=fixtures.scale_timeout(180))
        assert all(v is True for v in results.values()), results

        # Spill, not loss: pressure evicted prefixes fleet-wide, yet
        # the residual true-loss counter stayed ZERO.
        m_a, m_b = _metrics(a_url), _metrics(b_url)
        assert m_a["areal:kv_spill_total"] + m_b["areal:kv_spill_total"] >= 1
        assert m_a["areal:kv_prefix_lost_total"] == 0.0, m_a
        assert m_b["areal:kv_prefix_lost_total"] == 0.0, m_b

        name_resolve.add(
            names.experiment_status(exp, trial), "COMPLETE", replace=True
        )
    finally:
        try:
            name_resolve.add(
                names.experiment_status(exp, trial), "COMPLETE",
                replace=True,
            )
        except Exception:
            pass
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()
        for fn in cleanup:
            try:
                fn()
            except Exception:
                pass
        repo.reset()

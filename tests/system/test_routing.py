"""Routing-policy units for the gserver manager's production scheduler:
prefix-/session-affinity, shed-aware + saturation spill, and the
in-flight fold that keeps least_token_usage honest between /metrics
polls (ISSUE 6 satellite: a burst must not pile onto one server just
because the snapshot is stale)."""

import collections
import threading
import time

from areal_tpu.api.system_api import GserverManagerConfig
from areal_tpu.system.gserver_manager import GserverManager

A, B = "http://a:1", "http://b:2"


def _manager(policy="round_robin", **cfg_kw):
    m = GserverManager.__new__(GserverManager)
    m.cfg = GserverManagerConfig(
        n_servers=2, schedule_policy=policy, **cfg_kw
    )
    m.server_urls = [A, B]
    m._healthy = set(m.server_urls)
    m._rr = 0
    m._lock = threading.Lock()
    m._server_reqs = {u: 0 for u in m.server_urls}
    m._server_tokens = {u: 0.0 for u in m.server_urls}
    m._server_tokens_pending = {u: 0.0 for u in m.server_urls}
    m._server_shed_until = {u: 0.0 for u in m.server_urls}
    m._server_shed_total = {u: 0.0 for u in m.server_urls}
    m._affinity = collections.OrderedDict()
    # Disaggregated-pool state (all-unified here: single-pool routing).
    m._server_roles = {u: "unified" for u in m.server_urls}
    m._server_queued_toks = {u: 0.0 for u in m.server_urls}
    m._server_free_pages = {}
    m._server_total_pages = {}
    m._server_elastic = {}
    m._rerole_orig = {}
    m._rerole_log = []
    m.weight_version = 0
    return m


def test_least_token_usage_folds_inflight_between_polls():
    """Equal snapshots + a burst of schedules: without the pending fold
    every request would land on the min-snapshot server; with it they
    alternate."""
    m = _manager("least_token_usage")
    placed = [
        m._route({"prompt_len": 100, "new_token_budget": 100})[0]
        for _ in range(6)
    ]
    assert placed.count(A) == 3 and placed.count(B) == 3


def test_affinity_routes_follow_up_to_prefix_holder_across_versions():
    m = _manager("least_requests")
    url1, policy1, _d = m._route({"qid": "s/0", "prompt_len": 10})
    assert policy1 == "least_requests"
    # Load the affinity target heavily: affinity still wins (the prefix
    # is there), and survives a weight-version bump.
    m._server_reqs[url1] = 50
    m.weight_version = 7
    url2, policy2, _d = m._route({"qid": "s/0", "prompt_len": 20})
    assert (url2, policy2) == (url1, "affinity")


def test_affinity_spills_on_shed_window_then_returns():
    m = _manager("round_robin")
    url1, _, _d = m._route({"qid": "s/1", "prompt_len": 10})
    other = B if url1 == A else A
    # The server shed a client with 429: routed around for Retry-After.
    m._server_shed_until[url1] = time.monotonic() + 30.0
    url2, policy2, _d = m._route({"qid": "s/1", "prompt_len": 10})
    assert (url2, policy2) == (other, "spill")
    # Spill re-recorded the affinity on the server now holding the
    # session's newest prefix.
    m._server_shed_until[url1] = 0.0
    url3, policy3, _d = m._route({"qid": "s/1", "prompt_len": 10})
    assert (url3, policy3) == (other, "affinity")


def test_affinity_spills_on_saturation_threshold():
    m = _manager("least_requests", affinity_saturation_requests=4)
    url1, _, _d = m._route({"qid": "s/2", "prompt_len": 10})
    m._server_reqs[url1] = 4
    other = B if url1 == A else A
    m._server_reqs[other] = 0
    url2, policy2, _d = m._route({"qid": "s/2", "prompt_len": 10})
    assert (url2, policy2) == (other, "spill")


def test_affinity_ignores_unhealthy_target_and_map_is_bounded():
    m = _manager("round_robin", affinity_map_size=2)
    url1, _, _d = m._route({"qid": "s/3", "prompt_len": 10})
    m._healthy.discard(url1)
    url2, policy2, _d = m._route({"qid": "s/3", "prompt_len": 10})
    assert url2 != url1 and policy2 != "affinity"
    # LRU bound: oldest entries fall out.
    for i in range(5):
        m._route({"qid": f"lru/{i}", "prompt_len": 1})
    assert len(m._affinity) <= 2


def test_whole_fleet_shedding_still_routes():
    m = _manager("least_requests")
    now = time.monotonic()
    m._server_shed_until = {A: now + 30, B: now + 30}
    url, _, _d = m._route({"qid": "s/4", "prompt_len": 10})
    assert url in (A, B)

"""JAX environment helpers shared by every process entry point."""

from __future__ import annotations

import os


def apply_jax_platform_override():
    """Honor a JAX_PLATFORMS env override even when an early jax import
    already happened.

    This environment's sitecustomize imports jax (and its TPU plugin) at
    interpreter startup, so setting the env var alone doesn't stick — but
    backends initialize lazily, so a `jax.config.update` before first
    device use wins. Every spawned entry point (workers, eval jobs,
    multihost SPMD hosts) calls this first."""
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)

"""Registry-backed jsonl datasets (counterpart of reference impl/dataset/).

Importing this package registers: "prompt", "prompt_answer", "rw_pair",
"math_code_prompt". All produce numpy-backed `SequenceSample`s.
"""

from areal_tpu.datasets import (  # noqa: F401
    math_code_prompt,
    prompt,
    prompt_answer,
    rw_paired,
)

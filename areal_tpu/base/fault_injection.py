"""Deterministic chaos-injection harness.

Production code declares *named injection points* — one-line calls like
``faults.maybe_fail("gserver.generate")`` — that are free no-ops until a
test arms them. An armed point fires a chosen action on its k-th hit:

- ``raise``:   raise ``FaultInjected`` (a transient software failure)
- ``die``:     ``os._exit(1)`` (a killed process / native crash)
- ``delay``:   sleep ``delay_s`` seconds, then proceed (a slow peer)
- ``hang``:    sleep effectively forever (a dropped request / wedged peer)
- ``flaky``:   raise ``FaultInjected`` for the first ``n`` hits, then
  succeed (defaults to n=2) — the canonical retry-policy exercise:
  a substrate with attempts > n MUST absorb it invisibly
- ``corrupt``: flip payload bytes AFTER the hash was stamped — only
  meaningful at ``maybe_corrupt`` points (byte-serving sites); the
  sha256 verify on the receiving side must catch and reject it

Arming is either in-process (``faults.arm(...)``, unit/integration
tests in one process) or via the ``AREAL_FAULTS`` environment variable
for workers spawned as subprocesses by the controller. The env spec is a
semicolon-separated list of entries::

    <point>[@<scope>]=<action>[:k=<int>][:n=<int>][:delay=<float>]

e.g. ``AREAL_FAULTS="gserver.generate@generation_server/1=die:k=3"``
kills generation server 1 on the third generate request it serves.
``k`` is the first hit that fires (default 1), ``n`` how many
consecutive hits fire from there (default 1; ``n=0`` means every hit
from k on). A ``@scope`` entry only arms in the process whose
``set_scope()`` matches — workers set their worker_name as scope during
configure, so one env var can target one worker role out of a fleet.

Everything is counted deterministically (no randomness): a chaos test
states exactly which hit of which point fails, so failures reproduce.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Callable, Dict, List, Optional

from areal_tpu.base import env_registry, logging

logger = logging.getLogger("fault_injection")

_HANG_SECONDS = 3600.0


class FaultInjected(RuntimeError):
    """Raised by an armed injection point (action='raise')."""


class _Arm:
    __slots__ = ("action", "at_hit", "times", "delay_s", "scope",
                 "on_trigger", "fired")

    def __init__(self, action: str, at_hit: int = 1,
                 times: Optional[int] = None,
                 delay_s: float = 0.0, scope: Optional[str] = None,
                 on_trigger: Optional[Callable[[], None]] = None):
        if action not in ("raise", "die", "delay", "hang", "flaky",
                          "corrupt"):
            raise ValueError(f"unknown fault action {action!r}")
        self.action = action
        self.at_hit = max(1, int(at_hit))
        if times is None:
            # flaky's whole point is fail-then-SUCCEED under one knob:
            # the bare spec "<point>=flaky" fails twice then passes.
            times = 2 if action == "flaky" else 1
        self.times = int(times)  # 0 = every hit from at_hit on
        self.delay_s = float(delay_s)
        self.scope = scope
        self.on_trigger = on_trigger
        self.fired = 0

    def should_fire(self, hit: int, scope: Optional[str]) -> bool:
        if self.scope is not None and self.scope != scope:
            return False
        if hit < self.at_hit:
            return False
        return self.times == 0 or self.fired < self.times


class FaultInjector:
    def __init__(self):
        self._lock = threading.Lock()
        self._arms: Dict[str, List[_Arm]] = {}
        self._hits: Dict[str, int] = {}
        self._scope: Optional[str] = None
        self._env_loaded = False

    # -- configuration --------------------------------------------------

    def set_scope(self, scope: str):
        """Identify this process (worker_name) for @scope-filtered arms."""
        with self._lock:
            self._scope = scope

    def arm(self, point: str, action: str = "raise", at_hit: int = 1,
            times: Optional[int] = None, delay_s: float = 0.0,
            scope: Optional[str] = None,
            on_trigger: Optional[Callable[[], None]] = None):
        """Arm `point` to fire `action` on its at_hit-th hit (then for
        `times` consecutive hits; times=0 = forever; None = the
        action's default, 1 for everything but flaky's 2). `on_trigger`
        runs right before the action — chaos tests use it to flip
        auxiliary state (e.g. stop a fake server's heartbeat)
        atomically with the injected failure."""
        with self._lock:
            self._arms.setdefault(point, []).append(
                _Arm(action, at_hit, times, delay_s, scope, on_trigger)
            )

    def reset(self):
        with self._lock:
            self._arms.clear()
            self._hits.clear()
            self._env_loaded = False

    def _ensure_env_loaded(self):
        with self._lock:
            if self._env_loaded:
                return
            self._env_loaded = True
        self.load_env()

    def load_env(self, spec: Optional[str] = None):
        """Parse AREAL_FAULTS (or an explicit spec) into arms. Called
        lazily on the first maybe_fail so spawned workers pick the spec
        up without any bootstrap wiring."""
        if spec is None:
            spec = env_registry.get_str("AREAL_FAULTS")
        with self._lock:
            self._env_loaded = True
        for entry in filter(None, (e.strip() for e in spec.split(";"))):
            try:
                target, _, rhs = entry.partition("=")
                point, _, scope = target.partition("@")
                parts = rhs.split(":")
                action = parts[0]
                kwargs: Dict[str, float] = {}
                for p in parts[1:]:
                    key, _, val = p.partition("=")
                    if key == "k":
                        kwargs["at_hit"] = int(val)
                    elif key == "n":
                        kwargs["times"] = int(val)
                    elif key == "delay":
                        kwargs["delay_s"] = float(val)
                    else:
                        raise ValueError(f"unknown fault option {key!r}")
                self.arm(point.strip(), action=action,
                         scope=scope.strip() or None if scope else None,
                         **kwargs)
            except Exception:
                logger.error(f"bad AREAL_FAULTS entry {entry!r}; ignored",
                             exc_info=True)

    # -- registry-verified dynamic API ----------------------------------
    # The chaos-registry lint checker verifies LITERAL point names
    # statically; sweeps that iterate the registry (the all-points
    # chaos campaign, the manager's HTTP faults_hits query) can't name
    # points literally. These variants are the runtime equivalent of
    # the static check: an undeclared point raises instead of arming a
    # silent no-op, so the "renamed point keeps the test green" failure
    # mode the checker exists for stays impossible.

    @staticmethod
    def check_declared(point: str):
        from areal_tpu.base import fault_points

        if point.startswith(fault_points.TEST_PREFIX):
            return
        if point not in fault_points.REGISTRY:
            raise ValueError(
                f"undeclared chaos point {point!r}: declare it in "
                f"areal_tpu.base.fault_points (or use the reserved "
                f"{fault_points.TEST_PREFIX!r} namespace)"
            )

    def arm_declared(self, point: str, **kwargs):
        self.check_declared(point)
        return self.arm(point, **kwargs)

    def hits_declared(self, point: str) -> int:
        self.check_declared(point)
        return self.hits(point)

    # -- introspection --------------------------------------------------

    def hits(self, point: str) -> int:
        with self._lock:
            return self._hits.get(point, 0)

    def armed_points(self) -> List[str]:
        with self._lock:
            return sorted(self._arms)

    # -- injection points -----------------------------------------------

    def _step(self, point: str) -> Optional[_Arm]:
        """Count a hit; return the arm to fire, if any."""
        self._ensure_env_loaded()
        with self._lock:
            hit = self._hits.get(point, 0) + 1
            self._hits[point] = hit
            for arm in self._arms.get(point, ()):
                if arm.should_fire(hit, self._scope):
                    arm.fired += 1
                    return arm
        return None

    def _fire(self, arm: _Arm, point: str) -> float:
        """Run the non-blocking part of the action; returns seconds the
        caller must sleep (sync and async paths sleep differently)."""
        logger.warning(
            f"fault injection: firing {arm.action!r} at {point!r} "
            f"(hit {self._hits.get(point)})"
        )
        if arm.on_trigger is not None:
            arm.on_trigger()
        if arm.action == "die":
            # Mimic a hard kill: no cleanup, no exit hooks, nonzero code.
            os._exit(1)
        if arm.action in ("raise", "flaky"):
            raise FaultInjected(f"injected fault at {point!r}")
        if arm.action == "delay":
            return arm.delay_s
        if arm.action == "corrupt":
            # Only byte-serving maybe_corrupt sites can corrupt; at a
            # plain maybe_fail point the arm is inert by design (the
            # chaos campaign sweeps every (point, action) pair).
            return 0.0
        return _HANG_SECONDS  # hang

    def maybe_fail(self, point: str):
        """Synchronous injection point. A no-op unless armed."""
        arm = self._step(point)
        if arm is not None:
            time.sleep(self._fire(arm, point))

    async def maybe_fail_async(self, point: str):
        """Async injection point: delay/hang sleep on the event loop so
        the faulted coroutine stalls without blocking its peers."""
        arm = self._step(point)
        if arm is not None:
            import asyncio

            await asyncio.sleep(self._fire(arm, point))

    def maybe_corrupt(self, point: str, data: bytes) -> bytes:
        """Byte-serving injection point: a no-op pass-through unless
        armed. A ``corrupt`` arm flips bytes AFTER every hash was
        stamped — the receiving side's sha256 verify must catch it and
        re-fetch (the silent-corruption drill). Any other action fires
        exactly like ``maybe_fail`` (so raise/delay/flaky sweeps cover
        these points too). Cheap and sync on purpose: one dict lookup
        when unarmed, a byte-flip when armed — safe at serving sites."""
        arm = self._step(point)
        if arm is None:
            return data
        if arm.action == "corrupt":
            logger.warning(
                f"fault injection: corrupting {len(data)} bytes at "
                f"{point!r} (hit {self._hits.get(point)})"
            )
            if arm.on_trigger is not None:
                arm.on_trigger()
            return corrupt_bytes(data)
        time.sleep(self._fire(arm, point))
        return data

    async def maybe_corrupt_async(self, point: str, data: bytes) -> bytes:
        """``maybe_corrupt`` for byte-serving sites that run ON an
        event loop (aiohttp handlers building a response inline): a
        ``delay``/``hang`` arm sleeps via asyncio so it wedges the one
        request it targets, never the whole server process. Sites that
        serve bytes from executor threads keep the sync variant."""
        arm = self._step(point)
        if arm is None:
            return data
        if arm.action == "corrupt":
            logger.warning(
                f"fault injection: corrupting {len(data)} bytes at "
                f"{point!r} (hit {self._hits.get(point)})"
            )
            if arm.on_trigger is not None:
                arm.on_trigger()
            return corrupt_bytes(data)
        import asyncio

        await asyncio.sleep(self._fire(arm, point))
        return data


def corrupt_bytes(data: bytes) -> bytes:
    """Deterministically flip bytes (first, middle, last) so a
    content-hash verifier MUST reject the payload; empty payloads pass
    through (nothing to corrupt, nothing to verify)."""
    if not data:
        return data
    buf = bytearray(data)
    for pos in {0, len(buf) // 2, len(buf) - 1}:
        buf[pos] ^= 0xFF
    return bytes(buf)


# Process-global injector: production code imports this singleton so
# tests arm points without plumbing an injector through constructors.
faults = FaultInjector()

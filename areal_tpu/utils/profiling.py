"""Per-MFC profiling: jax.profiler trace capture + wall-time breakdown.

TPU counterpart of the reference's env-gated per-MFC torch profiler
(realhf/system/model_worker.py:136-139, __maybe_profile_rpc:828-909) and
its chrome-trace post-processing (realhf/base/monitor.py:404-610): on TPU
the trace IS the XLA/TensorBoard profile produced by `jax.profiler`, so
there is no kernel-classification re-parser — point TensorBoard (or
xprof) at the dump directory instead.

Environment knobs (mirroring the reference's `REAL_DUMP_TRACE`):
- AREAL_DUMP_TRACE=1       enable jax.profiler trace capture per MFC
- AREAL_TRACE_DIR=<dir>    dump root (default /tmp/areal_tpu/traces)
- AREAL_TRACE_STEPS=a,b,c  only capture these global steps (default: all)
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Dict, Iterator, List, Optional

from areal_tpu.base import env_registry
from areal_tpu.base import logging as areal_logging

logger = areal_logging.getLogger("profiling")


def trace_enabled() -> bool:
    return env_registry.get_bool("AREAL_DUMP_TRACE")


def _trace_dir() -> str:
    # NOT AREAL_RL_TRACE_DIR: this is the jax-profiler dump root; the
    # RL span recorder has its own tree (see env_registry docs).
    return env_registry.get_str("AREAL_TRACE_DIR")


def _step_selected(step: Optional[int]) -> bool:
    sel = env_registry.get_str("AREAL_TRACE_STEPS")
    if not sel or step is None:
        return True
    try:
        return step in {int(s) for s in sel.split(",") if s}
    except ValueError:
        return True


@contextlib.contextmanager
def maybe_profile(name: str, step: Optional[int] = None) -> Iterator[None]:
    """Capture a jax.profiler trace around the block when enabled.

    The dump lands in `<AREAL_TRACE_DIR>/<name>/step<step>/` in the
    TensorBoard profile format (open with `tensorboard --logdir` or
    xprof). No-op unless AREAL_DUMP_TRACE is set.
    """
    if not trace_enabled() or not _step_selected(step):
        yield
        return
    import jax

    sub = name if step is None else os.path.join(name, f"step{step}")
    path = os.path.join(_trace_dir(), sub)
    os.makedirs(path, exist_ok=True)
    logger.info(f"capturing jax.profiler trace for {name!r} -> {path}")
    with jax.profiler.trace(path):
        yield


class TimeMarks:
    """Wall-time breakdown recorder (reference time-mark parsing,
    realhf/base/monitor.py): label spans of work, export totals.

    Used by the model worker to ship a per-hook/per-MFC wall-time
    breakdown back to the master in the reply stats
    (reference model_function_call.py:460-472).
    """

    def __init__(self) -> None:
        self._totals: Dict[str, float] = {}
        self._counts: Dict[str, int] = {}

    @contextlib.contextmanager
    def record(self, label: str) -> Iterator[None]:
        t0 = time.monotonic()
        try:
            yield
        finally:
            dt = time.monotonic() - t0
            self._totals[label] = self._totals.get(label, 0.0) + dt
            self._counts[label] = self._counts.get(label, 0) + 1

    def export(self, prefix: str = "timeperf", reset: bool = True) -> Dict[str, float]:
        out = {f"{prefix}/{k}": v for k, v in self._totals.items()}
        if reset:
            self._totals.clear()
            self._counts.clear()
        return out

"""On-device token sampling: temperature, top-k, top-p, min-new-tokens.

Counterpart of the reference's genstep + logits warpers
(realhf/impl/model/nn/real_llm_generate.py:30-148, utils/logits_warper.py),
without the TP gather / broadcast dance: under GSPMD the logits arrive
already global, and sampling runs on device inside the jitted decode step.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def apply_top_k(logits: jnp.ndarray, top_k: int) -> jnp.ndarray:
    """Mask all but the k highest logits. top_k <= 0 disables."""
    if top_k <= 0:
        return logits
    v = logits.shape[-1]
    k = min(top_k, v)
    kth = jax.lax.top_k(logits, k)[0][..., -1:]
    return jnp.where(logits < kth, NEG_INF, logits)


def apply_top_p(logits: jnp.ndarray, top_p) -> jnp.ndarray:
    """Nucleus filtering: keep the smallest set with cumulative prob >= p.

    `top_p` may be a traced scalar; the op is branchless (p >= 1 keeps all)."""
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # Keep tokens where the cumulative prob *before* them is < p.
    keep_sorted = (cum - probs) < top_p
    cutoff_idx = jnp.sum(keep_sorted, axis=-1, keepdims=True) - 1
    cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
    return jnp.where(logits < cutoff, NEG_INF, logits)


def sample_token(
    logits: jnp.ndarray,  # [B, V] fp32
    rng: jax.Array,
    greedy: bool = False,
    temperature: float = 1.0,
    top_k: int = -1,
    top_p: float = 1.0,
    forbid_token_ids: Optional[jnp.ndarray] = None,  # e.g. EOS under min_new_tokens
    forbid_mask: Optional[jnp.ndarray] = None,  # [B] rows where forbid applies
):
    """Returns (tokens [B], logprobs [B]) — logprob is of the *unwarped*
    distribution (what PPO needs), sampling uses the warped one."""
    logits = logits.astype(jnp.float32)
    if forbid_token_ids is not None and forbid_token_ids.size:
        penalty = jnp.zeros_like(logits).at[:, forbid_token_ids].set(NEG_INF)
        if forbid_mask is not None:
            penalty = penalty * forbid_mask[:, None].astype(jnp.float32)
        logits = logits + penalty
    base_logp = jax.nn.log_softmax(logits, axis=-1)
    if greedy:
        tokens = jnp.argmax(logits, axis=-1)
    else:
        warped = logits / jnp.maximum(temperature, 1e-6)
        warped = apply_top_k(warped, top_k)
        warped = apply_top_p(warped, top_p)
        tokens = jax.random.categorical(rng, warped, axis=-1)
    logprobs = jnp.take_along_axis(base_logp, tokens[:, None], axis=-1)[:, 0]
    return tokens, logprobs

"""The overlapped input pipeline (engine/prefetch.py + the pipelined
train_batch/forward paths in engine/jax_engine.py): ordering,
backpressure, exception propagation, structural overlap evidence
(pack/H2D of micro-batch i+1 while step i runs), dispatch-gap-vs-eager,
and bit-level equivalence of the prefetched and eager engine paths."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from areal_tpu.api.data_api import MicroBatchSpec, SequenceSample
from areal_tpu.base import stats_tracker
from areal_tpu.engine.jax_engine import JaxTrainEngine
from areal_tpu.engine.optimizer import OptimizerConfig
from areal_tpu.engine.prefetch import HostPrefetcher
from areal_tpu.models.config import TransformerConfig
from areal_tpu.models.transformer import init_params
from areal_tpu.ops.loss import sft_loss_from_logprobs


# ----------------------------------------------------------------------
# HostPrefetcher harness
# ----------------------------------------------------------------------


def test_prefetcher_preserves_order():
    # Variable per-item work: a pool would reorder; the single staged
    # stream must not.
    def stage(i):
        time.sleep(0.002 * ((i * 7) % 5))
        return i * 10

    got = list(HostPrefetcher(range(12), stage, depth=3))
    assert got == [i * 10 for i in range(12)]


def test_prefetcher_backpressure_bounds_staged_items():
    """With a slow consumer, the worker may run at most `depth` staged
    results ahead (queue slots) plus the one blocked on put — host
    memory for staged micro-batches is bounded."""
    depth = 2
    pf = HostPrefetcher(range(10), lambda i: i, depth=depth)
    lead = []
    for _ in range(10):
        pf.get()
        time.sleep(0.02)  # let the worker run as far ahead as it can
        lead.append(pf.n_staged - pf.n_consumed)
    assert max(lead) <= depth + 1, lead


def test_prefetcher_propagates_stage_exception_in_order():
    class Boom(RuntimeError):
        pass

    def stage(i):
        if i == 2:
            raise Boom("item 2")
        return i

    pf = HostPrefetcher(range(5), stage, depth=2)
    assert pf.get() == 0
    assert pf.get() == 1
    with pytest.raises(Boom, match="item 2"):
        pf.get()
    # Pipeline terminated: the worker staged nothing past the failure
    # and the thread wound down.
    pf._thread.join(timeout=2)
    assert not pf._thread.is_alive()


def test_prefetcher_early_close_unblocks_worker():
    pf = HostPrefetcher(range(100), lambda i: i, depth=2)
    assert pf.get() == 0
    pf.close()
    pf._thread.join(timeout=2)
    assert not pf._thread.is_alive()


def test_prefetcher_overlaps_stage_with_mock_step():
    """The structural overlap claim: while the consumer runs a mock
    device step for item i, the worker is already staging item i+1 —
    asserted from recorded timestamps (stage start of item i+1 precedes
    consumption of item i), not wall-clock ratios, so CI load cannot
    flip it."""
    n = 5

    def stage(i):
        time.sleep(0.05)  # mock pack + H2D
        return i

    pf = HostPrefetcher(range(n), stage, depth=2)
    for _ in pf:
        time.sleep(0.1)  # mock device step
    # Every non-first item was being staged while an earlier item was
    # still in the consumer's hands.
    assert pf.overlap_count() >= n - 2, pf.spans


def test_dispatch_gap_prefetched_below_eager_baseline():
    """The acceptance metric: mean gap between dispatches with the
    prefetcher must undercut the eager baseline, where every mock step
    pays the pack latency inline. Sleeps are generous so load skew
    cannot close a 2x structural difference."""
    pack_s, step_s, n = 0.08, 0.12, 5

    def stage(i):
        time.sleep(pack_s)
        return i

    gaps_eager = []
    mark = time.perf_counter()
    for i in range(n):
        stage(i)
        gaps_eager.append(time.perf_counter() - mark)
        time.sleep(step_s)
        mark = time.perf_counter()

    pf = HostPrefetcher(range(n), stage, depth=2)
    gaps_pf = []
    mark = time.perf_counter()
    for _ in pf:
        gaps_pf.append(time.perf_counter() - mark)
        time.sleep(step_s)
        mark = time.perf_counter()

    eager_mean = np.mean(gaps_eager)  # ~pack_s
    pf_steady = np.mean(gaps_pf[1:])  # lead-in excluded: steady state ~0
    assert pf_steady < eager_mean * 0.5, (gaps_eager, gaps_pf)


# ----------------------------------------------------------------------
# Engine integration: prefetched vs eager equivalence + telemetry
# ----------------------------------------------------------------------


def small_cfg():
    return TransformerConfig(
        n_layers=2, hidden_dim=32, n_q_heads=4, n_kv_heads=2, head_dim=8,
        intermediate_dim=64, vocab_size=64, compute_dtype="float32",
    )


def make_batch(n=8, seed=0):
    rng = np.random.RandomState(seed)
    seqlens = rng.randint(5, 30, size=n).tolist()
    total = sum(seqlens)
    return SequenceSample.from_default(
        ids=[f"p{seed}-{i}" for i in range(n)],
        seqlens=seqlens,
        data={
            "packed_input_ids": rng.randint(0, 64, size=total),
            "loss_mask": np.ones(total, np.float32),
        },
    )


def packed_loss(lp, rows):
    total, n = sft_loss_from_logprobs(lp, rows["loss_mask"])
    return total, {"n_valid_tokens": n}


def loss_weight(mb):
    return float(np.sum(mb.data["loss_mask"]))


def mk_engine(params, depth, **kw):
    return JaxTrainEngine(
        small_cfg(), jax.tree_util.tree_map(jnp.copy, params),
        optimizer_config=OptimizerConfig(lr=1e-3, warmup_steps_proportion=0.0),
        total_train_steps=10, row_len_multiple=32,
        prefetch_depth=depth, **kw,
    )


def test_train_batch_prefetched_equals_eager():
    """Fixed-seed CPU run: the prefetched pipeline must produce the same
    losses/stats and the same updated parameters as the eager fused
    path — the overlap is a scheduling change, not a numeric one."""
    params = init_params(small_cfg(), jax.random.PRNGKey(17))
    eager = mk_engine(params, depth=0)
    pref = mk_engine(params, depth=2)
    batch = make_batch(n=8, seed=17)
    for step in range(3):
        se = eager.train_batch(batch, MicroBatchSpec(n_mbs=3), packed_loss,
                               loss_weight, version_steps=step, loss_name="t")
        sp = pref.train_batch(batch, MicroBatchSpec(n_mbs=3), packed_loss,
                              loss_weight, version_steps=step, loss_name="t")
        assert pref.last_overlap["overlap_events"] >= 0  # pipeline ran
        np.testing.assert_allclose(sp["t/loss"], se["t/loss"],
                                   rtol=1e-5, atol=1e-7)
        np.testing.assert_allclose(sp["t/grad_norm"], se["t/grad_norm"],
                                   rtol=1e-5, atol=1e-7)
        assert sp["t/n_tokens"] == se["t/n_tokens"]
        assert sp["t/n_mbs"] == se["t/n_mbs"]
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(eager.params)),
                    jax.tree_util.tree_leaves(jax.device_get(pref.params))):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=2e-5, atol=1e-6)


def test_train_batch_overlap_telemetry_and_tracker_series():
    """The pipelined path must (a) show structural overlap (transfer
    thread staging mb i+1 while step i is in flight), (b) report a
    packing density in (0, 1], and (c) ship all three series through the
    stats tracker under perf/* — the path the model worker exports to
    the master's perf history."""
    stats_tracker.export()  # drain whatever other tests left behind
    params = init_params(small_cfg(), jax.random.PRNGKey(3))
    eng = mk_engine(params, depth=2)
    batch = make_batch(n=12, seed=3)
    eng.train_batch(batch, MicroBatchSpec(n_mbs=4), packed_loss, loss_weight,
                    loss_name="t")
    ov = eng.last_overlap
    assert ov["overlap_events"] >= 1, ov  # mb i+1 staged during step i
    assert 0.0 < ov["packing_efficiency"] <= 1.0
    assert ov["h2d_wait_ms"] >= 0.0 and ov["dispatch_gap_ms"] >= 0.0
    out, types = stats_tracker.export(return_types=True)
    assert "perf/packing_efficiency" in out
    assert "perf/h2d_wait_ms" in out and "perf/dispatch_gap_ms" in out
    # Worst-case merge semantics across DP workers for the wait metrics.
    assert types["perf/h2d_wait_ms"] == "max"
    assert types["perf/packing_efficiency"] == "avg"


def test_train_batch_mesh_paths_match_single_device():
    """PR 9 satellite: the fused-vs-overlapped numerics pin was
    single-device only — extend it to TP2 and FSDP2 fake-device meshes.
    On CPU these meshes take the `_serial_dispatch` fused fallback; the
    mesh trajectory (losses, grad norms, final params) must match the
    single-device (overlapped-path) trajectory — GSPMD placement is a
    scheduling change, not a numeric one. Budget: ~8 s on the virtual
    CPU mesh (tiny model, warm XLA cache; tier-1 headroom per the PR 7
    note discipline)."""
    from areal_tpu.base.topology import MeshSpec
    from areal_tpu.parallel.mesh import make_mesh

    params = init_params(small_cfg(), jax.random.PRNGKey(11))
    batch = make_batch(n=8, seed=11)
    trajs = {}
    finals = {}
    for name, mesh in (
        ("single", None),
        ("tp2", make_mesh(MeshSpec.parse("t2"), jax.devices()[:2])),
        ("f2", make_mesh(MeshSpec.parse("f2"), jax.devices()[:2])),
    ):
        eng = mk_engine(params, depth=2, mesh=mesh)
        if mesh is not None:
            assert eng._serial_dispatch  # CPU mesh -> fused fallback
        traj = []
        for step in range(3):
            st = eng.train_batch(
                batch, MicroBatchSpec(n_mbs=3), packed_loss, loss_weight,
                version_steps=step, loss_name="t",
            )
            traj.append((st["t/loss"], st["t/grad_norm"]))
        trajs[name] = traj
        finals[name] = [
            np.asarray(x, np.float32)
            for x in jax.tree_util.tree_leaves(jax.device_get(eng.params))
        ]
    for name in ("tp2", "f2"):
        for (l, g), (lr_, gr) in zip(trajs[name], trajs["single"]):
            np.testing.assert_allclose(l, lr_, rtol=1e-4, atol=1e-6)
            np.testing.assert_allclose(g, gr, rtol=1e-4, atol=1e-6)
        for a, b in zip(finals[name], finals["single"]):
            np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)


def test_forward_prefetched_equals_eager():
    """Same programs, same inputs — the deferred single-fetch forward
    must be bit-identical to the eager per-mb-fetch forward."""
    params = init_params(small_cfg(), jax.random.PRNGKey(5))
    eager = JaxTrainEngine(small_cfg(),
                           jax.tree_util.tree_map(jnp.copy, params),
                           row_len_multiple=32, prefetch_depth=0)
    pref = JaxTrainEngine(small_cfg(),
                          jax.tree_util.tree_map(jnp.copy, params),
                          row_len_multiple=32, prefetch_depth=2)
    batch = make_batch(n=9, seed=5)
    a = eager.forward(batch, MicroBatchSpec(n_mbs=3), output_key="logprobs")
    b = pref.forward(batch, MicroBatchSpec(n_mbs=3), output_key="logprobs")
    np.testing.assert_array_equal(a.data["logprobs"], b.data["logprobs"])
    assert a.ids == b.ids


def test_train_batch_stage_exception_leaves_engine_usable():
    """A loss_weight_fn blowing up mid-pipeline must surface at the
    train_batch call (not hang, not kill the worker thread silently) and
    leave the engine able to train the next batch."""
    params = init_params(small_cfg(), jax.random.PRNGKey(7))
    eng = mk_engine(params, depth=2)
    batch = make_batch(n=8, seed=7)

    calls = {"n": 0}

    def bad_weight(mb):
        calls["n"] += 1
        if calls["n"] >= 2:
            raise ValueError("boom in mb 2")
        return loss_weight(mb)

    with pytest.raises(ValueError, match="boom in mb 2"):
        eng.train_batch(batch, MicroBatchSpec(n_mbs=3), packed_loss,
                        bad_weight, loss_name="t")
    st = eng.train_batch(batch, MicroBatchSpec(n_mbs=3), packed_loss,
                         loss_weight, loss_name="t")
    assert np.isfinite(st["t/loss"])


def test_stats_fetch_interval_caches_between_fetches():
    """stats_fetch_interval=2: odd calls after the first return the last
    fetched values tagged stale=1 (no device round trip), with host-side
    fields (n_tokens/n_mbs) kept exact; even calls re-fetch."""
    params = init_params(small_cfg(), jax.random.PRNGKey(9))
    eng = mk_engine(params, depth=2, stats_fetch_interval=2)
    batch = make_batch(n=8, seed=9)

    s1 = eng.train_batch(batch, MicroBatchSpec(n_mbs=2), packed_loss,
                         loss_weight, loss_name="t")
    assert s1["t/stats_stale"] == 0.0  # first call always fetches
    s2 = eng.train_batch(batch, MicroBatchSpec(n_mbs=2), packed_loss,
                         loss_weight, loss_name="t")
    assert s2["t/stats_stale"] == 0.0  # call 2: 2 % 2 == 0 -> fetch
    s3 = eng.train_batch(batch, MicroBatchSpec(n_mbs=2), packed_loss,
                         loss_weight, loss_name="t")
    assert s3["t/stats_stale"] == 1.0  # call 3: cached
    assert s3["t/loss"] == s2["t/loss"]  # last fetched value served
    assert s3["t/n_tokens"] == s2["t/n_tokens"]
    s4 = eng.train_batch(batch, MicroBatchSpec(n_mbs=2), packed_loss,
                         loss_weight, loss_name="t")
    assert s4["t/stats_stale"] == 0.0
    assert s4["t/loss"] != s3["t/loss"]  # fresh fetch of a moving loss


def test_split_lazy_matches_split():
    """split_lazy yields the same micro-batches/indices as split(), one
    at a time."""
    batch = make_batch(n=10, seed=21)
    spec = MicroBatchSpec(n_mbs=3)
    mbs, fwd, bwd = batch.split(spec)
    it, groups, fwd2, bwd2 = batch.split_lazy(spec)
    assert fwd == fwd2 and bwd == bwd2
    lazy = list(it)
    assert len(lazy) == len(mbs) == len(groups)
    for a, b in zip(mbs, lazy):
        assert a.ids == b.ids
        np.testing.assert_array_equal(
            a.data["packed_input_ids"], b.data["packed_input_ids"]
        )


def test_packing_density_estimator_matches_realized():
    """datapack.pack_shape/packing_density (the host-side estimator the
    model worker falls back to) agrees with what pack_sequences actually
    allocates."""
    from areal_tpu.base import datapack
    from areal_tpu.models.packing import pack_sequences

    rng = np.random.RandomState(2)
    lens = rng.randint(5, 100, size=13).tolist()
    seqs = [rng.randint(0, 64, size=l) for l in lens]
    packed = pack_sequences(seqs, row_len_multiple=32)
    n_rows, row_len = datapack.pack_shape(lens, row_len_multiple=32)
    assert (n_rows, row_len) == (packed.n_rows, packed.row_len)
    np.testing.assert_allclose(
        datapack.packing_density(lens, row_len_multiple=32), packed.density
    )

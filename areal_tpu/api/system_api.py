"""Worker configuration dataclasses.

Counterpart of the reference's system API (realhf/api/core/system_api.py:
ModelWorker:95, MasterWorker:159, ExperimentConfig:190 and friends). A
deployment here is: one master worker + N model workers (each driving its
own jax mesh over local TPU devices = one DP rank of each model it hosts)
+ the async stack (rollout workers, gserver manager, generation servers).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

from areal_tpu.api.config import (
    AgentAbstraction,
    DatasetAbstraction,
    EnvServiceAbstraction,
    ModelAbstraction,
    ModelBackendAbstraction,
    ModelInterfaceAbstraction,
    ModelName,
    ModelShardID,
)
from areal_tpu.api.data_api import MicroBatchSpec
from areal_tpu.api.dfg import MFCDef
from areal_tpu.api.model_api import GenerationHyperparameters


@dataclasses.dataclass
class ModelShardSpec:
    """One model hosted on a model worker: how to build + wrap it.

    `id.host_rank` is this worker's DP coordinate for the model;
    `mesh_spec` describes the worker-local device mesh axes.
    """

    id: ModelShardID
    model: ModelAbstraction = None
    backend: ModelBackendAbstraction = None
    interface: ModelInterfaceAbstraction = None
    eval_dataset: Optional[DatasetAbstraction] = None
    # initial HF checkpoint path (None = random init from model args)
    model_path: Optional[str] = None


@dataclasses.dataclass
class ModelWorkerConfig:
    experiment_name: str = ""
    trial_name: str = ""
    worker_index: int = 0
    shards: List[ModelShardSpec] = dataclasses.field(default_factory=list)
    # Dataset hosting (only on workers that serve the src MFC's model):
    datasets: List[DatasetAbstraction] = dataclasses.field(default_factory=list)
    tokenizer_path: Optional[str] = None
    use_dataset_cache: bool = False
    # dp coordinates for dataset sharding
    dataset_dp_rank: int = 0
    dataset_dp_size: int = 1
    train_batch_size: int = 8
    total_train_epochs: int = 1
    seed: int = 1
    # async mode: pull trajectories from rollout workers instead of a dataset
    stream_dataset: bool = False
    n_pullers: int = 1
    shuffle_dataset: bool = True
    # Multi-host sharded training: when > 1, this worker is ONE host of
    # the train partition's jax.distributed world — it joins the host
    # group (coordinator elected via name_resolve) BEFORE building any
    # model, builds the global train mesh, and its mesh slice is
    # verified at startup (parallel/distributed.verify_host_mesh_slice).
    train_n_hosts: int = 1
    train_host_rank: int = 0
    # Streaming weight-distribution plane: when True the dump rank
    # serves its raw-bin dumps over chunked HTTP and registers as the
    # fanout origin (system/weight_plane.WeightPlaneSource). Mirrors
    # GserverManagerConfig.weight_plane; AREAL_WEIGHT_PLANE=1 also arms
    # it for legacy launch paths that bypass the experiment builder.
    weight_plane: bool = False
    # Chunk size for that source (mirrors the manager-hosted fallback's
    # GserverManagerConfig.weight_chunk_bytes).
    weight_chunk_bytes: int = 8 << 20
    # Quantized weight wire: "int8" makes every raw dump also publish a
    # params-v{N}.int8.bin companion (matmul leaves as int8 data +
    # float32 per-output-channel scales, ops/wquant.py convention) the
    # plane can serve instead of the raw bytes — roughly half the
    # transfer per version; servers dequantize at assembly. Mirrors
    # GserverManagerConfig.weight_wire_dtype. None disables.
    weight_wire_dtype: Optional[str] = None

    @property
    def worker_name(self) -> str:
        return f"model_worker/{self.worker_index}"


@dataclasses.dataclass
class ExperimentSaveEvalControl:
    """Frequency control (reference api/cli_args.py ExperimentSaveEvalControl)."""

    # None = inherit the experiment's top-level total_train_epochs (the
    # documented knob); set explicitly to override it.
    total_train_epochs: Optional[int] = None
    # Exactly one of *_freq_{epochs,steps,secs} may be set per action.
    save_freq_epochs: Optional[int] = None
    save_freq_steps: Optional[int] = None
    save_freq_secs: Optional[int] = None
    ckpt_freq_epochs: Optional[int] = None
    ckpt_freq_steps: Optional[int] = None
    ckpt_freq_secs: Optional[int] = None
    eval_freq_epochs: Optional[int] = None
    eval_freq_steps: Optional[int] = None
    eval_freq_secs: Optional[int] = None
    benchmark_steps: Optional[int] = None  # stop early after N steps


@dataclasses.dataclass
class MasterWorkerConfig:
    experiment_name: str = ""
    trial_name: str = ""
    exp_ctrl: ExperimentSaveEvalControl = dataclasses.field(
        default_factory=ExperimentSaveEvalControl
    )
    rpcs: List[MFCDef] = dataclasses.field(default_factory=list)
    # model_name(str) -> list of model-worker names hosting it (DP order)
    model_topos: Dict[str, List[str]] = dataclasses.field(default_factory=dict)
    # worker names hosting the dataset ("fetch" targets, DP order)
    data_hosts: List[str] = dataclasses.field(default_factory=list)
    n_model_workers: int = 1
    train_batch_size: int = 8
    dataset_size: int = 0
    buffer_max_size: int = 16384
    recover_mode: str = "disabled"  # disabled | auto | resume

    @property
    def worker_name(self) -> str:
        return "master"


@dataclasses.dataclass
class GenerationServerConfig:
    experiment_name: str = ""
    trial_name: str = ""
    server_index: int = 0
    # Which registered model family this server hosts (multi-model
    # serving plane, system/model_registry.py). Stamped into the
    # heartbeat payload so the manager pools the fleet per model; a
    # mismatch is a routing error, never a silent cross-model KV or
    # weight hit. None = the manager's default model_name (the
    # single-model fleets every pre-registry deployment runs).
    model_id: Optional[str] = None
    model_path: Optional[str] = None
    model: ModelAbstraction = None
    tokenizer_path: Optional[str] = None
    max_concurrent_requests: int = 64
    max_seq_len: int = 2048
    kv_page_size: int = 128
    # Token capacity of the paged KV pool (None -> B * max_seq_len, i.e.
    # no memory pressure). Sizing it below that serves long contexts in
    # bounded HBM with preempt-and-resubmit under pressure.
    kv_pool_tokens: Optional[int] = None
    decode_block_steps: int = 16
    # Prompts pad up to a multiple of this (bounds compiled prefill
    # shapes); prefill_max_batch caps prompts per batched prefill.
    prompt_bucket: int = 64
    prefill_max_batch: int = 8
    # Prompts longer than this prefill chunk-by-chunk through one
    # fixed-shape program (None disables; essential for 16-32k prompts
    # where each new length bucket is a fresh multi-second compile).
    prefill_chunk: Optional[int] = None
    # Chunked / cache-hit prefills run one prompt at a time on the serve
    # loop; this caps how many are admitted per lap so decode latency
    # jitter for running slots stays bounded.
    chunked_prefill_per_lap: int = 2
    # qid-keyed prefix KV reuse budget in tokens (None disables): a
    # resubmission extending a parked sequence prefills only the delta —
    # the radix-cache role for partial-rollout chunking.
    prefix_cache_tokens: Optional[int] = None
    # KV pool precision: None/"model" stores the compute dtype; "int8"
    # stores quantized (data, scales) pages — half the decode HBM
    # traffic, double the tokens per pool budget (engine/paged.py).
    kv_cache_dtype: Optional[str] = None
    # N-gram (prompt-lookup) speculative decoding: >0 drafts that many
    # tokens per decode step and keeps the verified prefix — lossless,
    # device-resident (engine/spec_decode.py). 0 disables.
    speculative_draft_len: int = 0
    speculative_ngram: int = 2
    # Backward search window (tokens) for the draft lookup; bounds the
    # per-step match cost at long contexts. None = engine default (1024);
    # 0 = unbounded full-history scan.
    speculative_window: Optional[int] = None
    # int8 DECODE weights (W8A16, ops/wquant.py): halves the per-step
    # weight stream; prefill stays bf16. None/"model" disables.
    decode_weight_dtype: Optional[str] = None
    # Token-budget continuous batching: per-admission-round cap on
    # UNCACHED prefill tokens (None = unbounded). Bounds how much
    # prefill work interleaves into one scheduler iteration — the
    # TTFT-vs-ITL knob under load (engine/serving.py, docs/serving.md).
    prefill_token_budget: Optional[int] = None
    # Prefill/decode interleave ratio: decode blocks run between
    # admission rounds (1 = admit every block boundary).
    decode_blocks_per_admit: int = 1
    # Bounded admission queue (backpressure): beyond either watermark,
    # /generate sheds with 429 + Retry-After instead of queueing
    # unboundedly — the open-loop tail-latency guarantee. None disables.
    max_queue_depth: Optional[int] = None
    max_queued_tokens: Optional[int] = None
    # Retry-After hint handed to shed clients (partial_rollout backs off
    # with jitter around it; the manager routes around the server for
    # this long).
    shed_retry_after_s: float = 1.0
    # Disaggregated prefill/decode serving (docs/serving.md): the
    # server's starting pool role. "prefill" servers take fresh prompts,
    # run chunked prefill to the first token, and hand the KV off to a
    # decode server; "decode" servers import handoff blobs and run the
    # decode stream; "unified" serves both (legacy) and is the manager's
    # elastic re-role pool — /set_role flips the live role at runtime
    # (drain + flip; weights stay resident). Any role still serves plain
    # /generate: the handoff path only engages when the manager pairs a
    # decode server into the request.
    role: str = "unified"
    # int8-compress exported KV handoff blobs (halves the
    # server-to-server hop; the importer dequantizes). None ships the
    # pool's own precision.
    kv_handoff_compress: Optional[str] = None
    # Tiered KV plane (engine/kv_tier.py, docs/serving.md): host-RAM
    # capacity for spilled prefixes. Prefix-cache evictions spill here
    # (handoff wire format) instead of being freed; returning sessions
    # restore instead of re-prefilling, and peers can pull held
    # prefixes over /kv/{manifest,chunk}. None = AREAL_KV_TIER_BYTES
    # (default 0 = disabled).
    kv_tier_bytes: Optional[int] = None
    # Optional local-disk second tier: host-LRU evictions demote here
    # (hash-verified on read-back). None = AREAL_KV_TIER_DISK_DIR.
    kv_tier_disk_dir: Optional[str] = None
    kv_tier_disk_bytes: Optional[int] = None
    # Spill wire precision: 'int8' quantizes FLOAT pools' prefixes on
    # the spill wire (halves tier bytes; int8 pools always spill their
    # (data, scales) form). None = AREAL_KV_SPILL_DTYPE.
    kv_spill_dtype: Optional[str] = None
    # Shard the engine over this many local devices (megatron-style TP
    # via GSPMD; see engine/serving.serving_mesh).
    tensor_parallel: int = 1
    # Shard-aware weight plane (docs/weight_updates.md): this server's
    # coordinates in a FLEET-level tensor-parallel group. When set, the
    # server fetches only its slice of each weight version (a sliced
    # shard manifest — per-server ingress and host staging drop by
    # ~degree; same-shard peers fan chunks to each other) and cutover
    # device_puts the shard slabs directly under the engine's
    # NamedSharding. Both set or both None; requires a multi-host-style
    # deployment where this process hosts exactly the mesh slice for
    # weight_shard_rank (the manager groups fanout trees by shard).
    weight_shard_rank: Optional[int] = None
    weight_shard_degree: Optional[int] = None
    # Pre-compile the serving programs (prefill bucket + decode block,
    # ServingEngine.warm) BEFORE the server registers for discovery:
    # the first real rollout request then never eats a multi-second XLA
    # compile. Costs startup latency; pays off whenever a persistent
    # compilation cache is configured.
    warm_on_start: bool = False
    # Drain-then-leave (POST /drain): upper bound on waiting for
    # in-flight requests to finish before the parked-prefix migration
    # starts (admission is already shedding by then).
    drain_wait_s: float = 60.0
    seed: int = 1

    @property
    def worker_name(self) -> str:
        return f"generation_server/{self.server_index}"


@dataclasses.dataclass
class GserverManagerConfig:
    experiment_name: str = ""
    trial_name: str = ""
    model_name: str = "actor"
    n_servers: int = 1
    schedule_policy: str = "round_robin"  # | least_requests | least_token_usage
    # Prefix-/session-affinity routing: a rollout's next chunk/turn is
    # routed to the server holding its KV prefix (affinity map keyed by
    # the request qid, surviving weight-version bumps), with load-aware
    # spill to the least-loaded server when the target is saturated or
    # shedding. Applies on top of schedule_policy (which places the
    # FIRST chunk of each session).
    session_affinity: bool = True
    # Spill threshold: an affinity target with at least this many
    # estimated in-flight requests is considered saturated and the
    # session spills. None = spill only on shed/unhealthy.
    affinity_saturation_requests: Optional[int] = None
    # LRU cap on the affinity map (entries are one url per qid).
    affinity_map_size: int = 65536
    # Global prefix index (tiered KV plane, docs/serving.md): LRU cap
    # on the qid -> (holder, tier) map fed from each server's
    # /kv/index. Affinity is the fast path; this index lets ANY server
    # serve a returning session by pulling its prefix from whichever
    # peer/tier holds it. None = AREAL_KV_INDEX_SIZE (default 65536);
    # 0 disables index-aware routing.
    kv_index_size: Optional[int] = None
    max_head_offpolicyness: int = 0
    train_batch_size: int = 8
    flush_request_timeout: float = 120.0
    max_concurrent_rollouts: Optional[int] = None
    # Cadence of the health-registry fold (eviction of dead servers,
    # re-sync + readmission of returning ones). Chaos tests shrink it
    # together with AREAL_HEALTH_TTL for sub-second failover.
    health_check_interval: float = 2.0
    # Streaming weight-distribution plane (system/weight_plane.py): when
    # True, weight updates fan out over a peer tree (origin uploads each
    # byte once; holders serve siblings) instead of every server
    # re-reading the full checkpoint from NFS. The origin is the
    # trainer-side source registered in name_resolve, falling back to a
    # manager-hosted source over the NFS dump dir.
    weight_plane: bool = False
    # Chunk size for the manager-hosted origin (a trainer-side source
    # uses its own); per-chunk hashed, Range-resumable.
    weight_chunk_bytes: int = 8 << 20
    # Quantized weight wire for plane fanouts: "int8" fetches/ships the
    # dump's quantized companion stream (~half the bytes per version;
    # servers dequantize at assembly). Requires the dump side to arm
    # ModelWorkerConfig.weight_wire_dtype with the same value. None
    # ships raw bytes.
    weight_wire_dtype: Optional[str] = None
    # Children per node in the fanout tree: origin egress is bounded by
    # degree * payload; deeper trees trade origin egress for extra hops.
    weight_fanout_degree: int = 2
    # Target bound for the serve-interrupting cutover window (interrupt
    # + device swap), measured separately from transfer. Overruns are
    # surfaced (within_budget=false + warning), not fatal.
    weight_cutover_budget_s: float = 3.0
    # Elastic prefill/decode pool sizing (docs/serving.md): when True
    # the manager re-roles servers whose CONFIGURED role is "unified"
    # between the prefill and decode pools from queue-depth/free-page
    # watermarks. Re-role is drain + flip — the manager stops routing
    # new work of the old kind first, in-flight requests finish, weights
    # stay resident.
    elastic_pools: bool = False
    # Minimum seconds between re-role decisions (flapping guard).
    rerole_cooldown_s: float = 10.0
    # Queued-prompt-token watermarks over the prefill-capable pool: at
    # or above `high` an elastic decode-side server flips to prefill; at
    # or below `low` a server this manager flipped to prefill flips
    # back.
    prefill_queue_high_tokens: int = 4096
    prefill_queue_low_tokens: int = 0
    # Decode-pool free-page floor: below this fraction an elastic
    # prefill-side server flips to decode (and blocks further
    # prefill-ward flips).
    decode_free_page_min_frac: float = 0.1
    # Each pool keeps at least this many servers through re-roles.
    pool_min_prefill: int = 1
    pool_min_decode: int = 1
    # ---- Elastic fleet control plane (system/fleet_controller.py,
    # docs/fault_tolerance.md "Fleet elasticity + manager HA") --------
    # Runtime join/leave + manager HA: unknown heartbeating servers are
    # ADOPTED (weight-bootstrapped from peers before routing), graceful
    # departures are forgotten cleanly, and the manager persists an
    # epoch/weight-version lease so a restart rebuilds everything else
    # from heartbeats + /metrics. False = fixed fleet, no lease (the
    # pre-ISSUE-12 behavior).
    elastic_fleet: bool = True
    # Warm standby: block in configure until the current lease holder's
    # record expires, then take over (instead of failing after 300 s).
    standby: bool = False
    # Joiner weight source: "peers" fetches chunk streams from
    # same-shard holders (origin last resort, never NFS); "origin"
    # forces the plane origin (the bench's baseline arm).
    join_bootstrap: str = "peers"
    # A drain that hasn't completed (graceful departure observed) by
    # this deadline is EVICTED while it finishes quiescing — a drain
    # cannot be cancelled server-side, so the server could never take
    # traffic again; its graceful stop (or death) stays the terminal
    # transition.
    drain_timeout_s: float = 120.0
    # Watermark autoscaling (fleet_controller.WatermarkAutoscaler):
    # scale-out/in decisions from the SAME queued-token / free-page
    # signals the re-role sizer polls, actuated through a launcher
    # attached via GserverManager.attach_launcher. Off by default —
    # policy without actuation only logs a warning.
    autoscale: bool = False
    # Fleet-average queued prompt tokens per routable server at/above
    # which the fleet grows; at/below scale_in the least-loaded server
    # is drained (only while free pages are comfortable).
    scale_out_queued_tokens: int = 4096
    scale_in_queued_tokens: int = 64
    scale_free_page_min_frac: float = 0.5
    pool_min_servers: int = 1
    pool_max_servers: int = 8
    scale_cooldown_s: float = 15.0
    # Consecutive over/under-watermark metrics polls before acting.
    scale_sustain_polls: int = 2
    # ---- Multi-model serving plane (system/model_registry.py) -------
    # When True the manager partitions the fleet into per-model pools
    # from registry records + heartbeat model_ids: routing, affinity,
    # the KV prefix index, shed/breaker candidacy, and the autoscaler
    # all become model-scoped, and each registered model's weight
    # version is watched (and fanned out) independently. Heartbeats
    # naming an UNREGISTERED model_id are quarantined instead of
    # adopted. False = the legacy single-model fleet: every server is
    # assumed to host `model_name` and extra model_version keys are
    # ignored.
    multi_model: bool = False

    @property
    def worker_name(self) -> str:
        return "gserver_manager"


@dataclasses.dataclass
class RolloutWorkerConfig:
    experiment_name: str = ""
    trial_name: str = ""
    worker_index: int = 0
    n_rollout_workers: int = 1
    n_pullers: int = 1
    model_name: str = "actor"
    agent: AgentAbstraction = None
    env: EnvServiceAbstraction = None
    datasets: List[DatasetAbstraction] = dataclasses.field(default_factory=list)
    tokenizer_path: Optional[str] = None
    gconfig: GenerationHyperparameters = dataclasses.field(
        default_factory=GenerationHyperparameters
    )
    new_tokens_per_chunk: int = 1 << 30  # chunked interruptible generation
    max_concurrent_rollouts: int = 32
    rollout_request_timeout: float = 300.0
    # Per-sample failover budget: dead-server resubmissions + no-healthy-
    # server backoff rounds before the episode errors (and is dropped).
    rollout_max_retries: int = 8
    seed: int = 1

    @property
    def worker_name(self) -> str:
        return f"rollout_worker/{self.worker_index}"


@dataclasses.dataclass
class ExperimentConfig:
    """Everything the controller needs to launch one trial."""

    experiment_name: str = ""
    trial_name: str = ""
    master: MasterWorkerConfig = None
    model_workers: List[ModelWorkerConfig] = dataclasses.field(default_factory=list)
    rollout_workers: List[RolloutWorkerConfig] = dataclasses.field(default_factory=list)
    gserver_manager: Optional[GserverManagerConfig] = None
    generation_servers: List[GenerationServerConfig] = dataclasses.field(
        default_factory=list
    )

"""Fetch side of the streaming weight-distribution plane.

A generation server prefetches the next weight version into HOST memory
while it keeps serving the current one: a :class:`ChunkStore` pulls the
raw-bin payload chunk-by-chunk over HTTP from an ordered list of
upstreams (its fanout-tree parent first, surviving peer holders next,
the trainer origin last), verifying every chunk's content hash and
resuming torn connections mid-chunk via HTTP Range. Once complete, the
store's buffer is reinterpreted zero-copy into the params pytree
(``assemble_params``) and handed to ``ServingEngine.cutover_params`` —
the short interrupt + device-swap window that is measured separately
from the transfer.

Synchronous stdlib HTTP on purpose: the caller runs it on an executor
thread (generation_server) or a plain thread (bench workload), so no
event-loop interplay and no aiohttp dependency on the fetch path.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.error
import urllib.parse
import urllib.request
from typing import Any, Dict, List, Optional, Tuple

from areal_tpu.base import logging, rpc
from areal_tpu.base.chunking import CHUNK_SCHEMA, chunk_spans, verify_chunk

logger = logging.getLogger("weight_client")

# Per-chunk, per-upstream (re)connection budget (base/rpc.py policy).
# Mid-chunk drops resume with a Range request, so each retry re-pays at
# most the torn tail.
_CHUNK_ATTEMPTS = 3


class WeightFetchError(RuntimeError):
    """The payload could not be completed from any upstream."""


class ChunkHashMismatch(ValueError):
    """A chunk's bytes failed sha256 verification (torn or corrupted
    upstream). Retryable: the re-fetch restarts the whole chunk."""


def http_get_json(url: str, timeout: float = 10.0) -> Dict:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def stream_params(
    wire: Optional[str] = None,
    tp_degree: Optional[int] = None,
    tp_rank: Optional[int] = None,
    ep_degree: Optional[int] = None,
    ep_rank: Optional[int] = None,
) -> Dict[str, str]:
    """Query params that pick ONE chunk stream of a version: the wire
    precision and (for shard-aware fetch) the tensor- and/or
    expert-parallel slice. Omitted/default values are left off the URL
    so unsharded holders keep the PR 5 contract byte-for-byte."""
    q: Dict[str, str] = {}
    if wire and wire != "raw":
        q["wire"] = str(wire)
    if tp_degree and int(tp_degree) > 1:
        q["tp_degree"] = str(int(tp_degree))
        q["tp_rank"] = str(int(tp_rank or 0))
    if ep_degree and int(ep_degree) > 1:
        q["ep_degree"] = str(int(ep_degree))
        q["ep_rank"] = str(int(ep_rank or 0))
    return q


def manifest_stream_params(manifest: Dict) -> Dict[str, str]:
    """The stream-identity params of a fetched manifest (what
    ChunkStore appends to every chunk URL so peers and the origin serve
    the matching stream)."""
    shard = manifest.get("shard") or {}
    return stream_params(
        wire=manifest.get("wire"),
        tp_degree=shard.get("tp_degree"),
        tp_rank=shard.get("tp_rank"),
        ep_degree=shard.get("ep_degree"),
        ep_rank=shard.get("ep_rank"),
    )


def fetch_manifest(
    base_url: str, version: Optional[int] = None, timeout: float = 10.0,
    wire: Optional[str] = None,
    tp_degree: Optional[int] = None, tp_rank: Optional[int] = None,
    ep_degree: Optional[int] = None, ep_rank: Optional[int] = None,
) -> Dict:
    """GET ``{base_url}/weights/manifest`` (optionally pinned to a
    version: the holder 404s until it can serve exactly that one).
    ``wire``/``tp_degree``/``tp_rank``/``ep_degree``/``ep_rank`` pick a
    quantized and/or sliced chunk stream (the origin builds shard
    streams on demand; an ep stream ships only that rank's experts)."""
    q = stream_params(
        wire=wire, tp_degree=tp_degree, tp_rank=tp_rank,
        ep_degree=ep_degree, ep_rank=ep_rank,
    )
    if version is not None:
        q["version"] = str(int(version))
    url = f"{base_url}/weights/manifest"
    if q:
        url += "?" + urllib.parse.urlencode(q)
    man = http_get_json(url, timeout=timeout)
    if man.get("schema") != CHUNK_SCHEMA:
        raise WeightFetchError(
            f"{base_url}: manifest schema {man.get('schema')!r} != "
            f"{CHUNK_SCHEMA!r}"
        )
    return man


class ChunkStore:
    """Host-memory staging buffer for one (version, payload).

    Verified chunks are immediately servable to sibling fetchers (the
    peer-fanout hop), so ``has``/``chunk_bytes_at`` are safe to call from
    the HTTP thread while ``fetch`` runs on an executor thread: ``_have``
    flips True only AFTER the chunk's bytes are fully written+verified.
    """

    def __init__(self, manifest: Dict):
        if manifest.get("schema") != CHUNK_SCHEMA:
            raise WeightFetchError(
                f"bad manifest schema: {manifest.get('schema')!r}"
            )
        self.manifest = manifest
        self.version = int(manifest["version"])
        self.total_bytes = int(manifest["total_bytes"])
        self.chunk_bytes = int(manifest["chunk_bytes"])
        self.spans = chunk_spans(self.total_bytes, self.chunk_bytes)
        self.n_chunks = len(self.spans)
        assert self.n_chunks == int(manifest["n_chunks"]), (
            f"manifest n_chunks {manifest['n_chunks']} != computed "
            f"{self.n_chunks}"
        )
        # Shard-aware staging: for a sliced manifest this buffer is
        # SHARD-sized (total_bytes is the shard stream's length), so a
        # TP-degree-D fleet's per-server host high-water drops by ~D.
        self.buf = bytearray(self.total_bytes)
        self._have = [False] * self.n_chunks
        # Stream identity (wire + shard) appended to every chunk URL so
        # upstreams serve the matching stream.
        self._stream_q = manifest_stream_params(manifest)
        # Telemetry: who served us how much (origin vs peer accounting
        # for the O(1)-egress assertion), and time split fetch vs verify.
        self.bytes_from: Dict[str, int] = {}
        self.fetch_s = 0.0
        self.verify_s = 0.0
        self.resumed_chunks = 0
        self._lock = threading.Lock()

    # -- serving side (safe during fetch) ------------------------------

    def complete(self) -> bool:
        return all(self._have)

    def has(self, idx: int) -> bool:
        return 0 <= idx < self.n_chunks and self._have[idx]

    def chunk(self, idx: int) -> memoryview:
        off, length = self.spans[idx]
        return memoryview(self.buf)[off : off + length]

    # -- fetch side ----------------------------------------------------

    def _get_range(
        self, base_url: str, idx: int, start: int, length: int,
        timeout: float,
    ) -> bytes:
        url = (
            f"{base_url}/weights/chunk?"
            + urllib.parse.urlencode(
                {"version": self.version, "idx": idx, **self._stream_q}
            )
        )
        req = urllib.request.Request(url)
        if start:
            req.add_header("Range", f"bytes={start}-")
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.read(length - start)

    def _fetch_chunk(
        self, base_url: str, idx: int, timeout: float,
        deadline: Optional[rpc.Deadline] = None,
    ) -> Optional[bytes]:
        """One chunk from one upstream under the unified RPC policy
        (base/rpc.py): budget-derived attempt timeouts, jittered
        backoff, mid-chunk Range resume on torn reads, and a full
        re-fetch on hash mismatch (a corrupted upstream is retryable —
        the ``corrupt`` chaos action must never complete a transfer).
        Returns verified bytes or None (upstream exhausted)."""
        _, length = self.spans[idx]
        expected = self.manifest["hashes"][idx]
        part = b""

        def attempt(attempt_timeout: float) -> bytes:
            nonlocal part
            got = self._get_range(
                base_url, idx, len(part), length,
                min(timeout, attempt_timeout),
            )
            if part:
                with self._lock:
                    self.resumed_chunks += 1
            part += got
            if len(part) < length:
                raise OSError(
                    f"short read {len(part)}/{length}"
                )  # resume from the new offset next attempt
            t0 = time.monotonic()
            ok = verify_chunk(part, expected)
            with self._lock:
                self.verify_s += time.monotonic() - t0
            if not ok:
                part = b""  # poisoned: restart the whole chunk
                raise ChunkHashMismatch(
                    f"chunk {idx} from {base_url}: content-hash mismatch"
                )
            return part

        try:
            return rpc.retry_sync(
                attempt,
                policy=rpc.default_policy(attempts=_CHUNK_ATTEMPTS),
                deadline=deadline,
                retryable=(urllib.error.URLError, OSError, ValueError),
                what=f"weights/chunk {idx} <- {base_url}",
            )
        except rpc.RpcDeadlineExceeded:
            raise
        except rpc.RpcError as e:
            logger.debug(f"chunk {idx} from {base_url}: {e}")
            return None

    def fetch(
        self,
        upstreams: List[str],
        origin: Optional[str] = None,
        timeout: float = 30.0,
        deadline_s: float = 600.0,
        deadline: Optional[rpc.Deadline] = None,
        hedge: Optional[bool] = None,
    ) -> Dict[str, Any]:
        """Pull every missing chunk, trying ``upstreams`` in order per
        chunk (sticky: the last upstream that delivered is tried first
        for the next chunk). When several PEER holders can serve the
        stream, each chunk pull is HEDGED (base/rpc.py): a second
        holder races the first after ``AREAL_RPC_HEDGE_DELAY_S`` of
        silence, first verified chunk wins, and only the winner's
        bytes land in ``bytes_from`` — losers are abandoned, so the
        ingress accounting can never double-count. The origin is
        deliberately excluded from hedges: the O(1)-origin-egress
        assertion must hold even under tail latency. Raises
        WeightFetchError if any chunk cannot be completed from any
        upstream before the deadline.

        Returns the transfer stats dict (also kept on the store)."""
        t_start = time.monotonic()
        order = list(dict.fromkeys(u.rstrip("/") for u in upstreams if u))
        if not order:
            raise WeightFetchError("no upstreams to fetch from")
        origin = origin.rstrip("/") if origin else None
        if deadline is None:
            deadline = rpc.Deadline.after(deadline_s)
        if hedge is None:
            hedge = rpc.hedging_enabled()
        preferred = 0
        for idx in range(self.n_chunks):
            if self._have[idx]:
                continue
            if deadline.expired():
                raise WeightFetchError(
                    f"weight fetch v{self.version} deadline after "
                    f"{idx}/{self.n_chunks} chunks"
                )
            got = None
            tried = [order[preferred]] + [
                u for i, u in enumerate(order) if i != preferred
            ]
            # Hedge candidates: the first two PEER upstreams in sticky
            # order (never the origin).
            peers = [u for u in tried if u != origin]
            if hedge and len(peers) >= 2:
                def _mk(u):
                    return lambda: self._hedge_fetch(u, idx, timeout, deadline)
                try:
                    got, winner = rpc.hedged_sync(
                        [_mk(peers[0]), _mk(peers[1])],
                        deadline=deadline,
                        what=f"weights/chunk {idx} v{self.version}",
                    )
                    winner_url = peers[winner]
                except rpc.RpcDeadlineExceeded:
                    raise
                except rpc.RpcError:
                    got = None
                # Hedge losers resolved: fall through to the remaining
                # upstreams (origin included) only on total miss.
                if got is None:
                    rest = [u for u in tried if u not in peers[:2]]
                    for u in rest:
                        got = self._fetch_chunk(u, idx, timeout, deadline)
                        if got is not None:
                            winner_url = u
                            break
            else:
                winner_url = None
                for u in tried:
                    got = self._fetch_chunk(u, idx, timeout, deadline)
                    if got is not None:
                        winner_url = u
                        break
            if got is None:
                raise WeightFetchError(
                    f"chunk {idx}/{self.n_chunks} of v{self.version} "
                    f"unavailable from all of {tried}"
                )
            if winner_url in order:
                preferred = order.index(winner_url)
            with self._lock:
                self.bytes_from[winner_url] = (
                    self.bytes_from.get(winner_url, 0) + len(got)
                )
            off, _ = self.spans[idx]
            self.buf[off : off + len(got)] = got
            self._have[idx] = True
        self.fetch_s = time.monotonic() - t_start
        return self.stats(origin)

    def _hedge_fetch(
        self, url: str, idx: int, timeout: float, deadline: rpc.Deadline
    ) -> bytes:
        """One hedge leg: like _fetch_chunk but raising on miss so the
        race can distinguish failure from success."""
        got = self._fetch_chunk(url, idx, timeout, deadline)
        if got is None:
            raise OSError(f"chunk {idx} unavailable from {url}")
        return got

    def stats(self, origin: Optional[str] = None) -> Dict[str, Any]:
        origin = origin.rstrip("/") if origin else None
        from_origin = sum(
            n for u, n in self.bytes_from.items() if u == origin
        )
        total_in = sum(self.bytes_from.values())
        # Shard-aware expectations: a sliced fetch is COMPLETE at its
        # own shard bytes (total_bytes of ITS manifest), not the full
        # model's — dashboards divide ingress by expected_bytes, so a
        # TP shard at 1.0 reads as complete, never as a torn transfer.
        expected = self.total_bytes
        return {
            "version": self.version,
            "total_bytes": self.total_bytes,
            "expected_bytes": expected,
            "model_total_bytes": int(
                self.manifest.get("model_total_bytes", self.total_bytes)
            ),
            "wire": self.manifest.get("wire", "raw"),
            "shard": self.manifest.get("shard"),
            "ingress_payload_equivalents": (
                total_in / expected if expected else 0.0
            ),
            "n_chunks": self.n_chunks,
            "fetch_s": self.fetch_s,
            "verify_s": self.verify_s,
            "resumed_chunks": self.resumed_chunks,
            "bytes_from": dict(self.bytes_from),
            "bytes_from_origin": from_origin,
            "bytes_from_peers": total_in - from_origin,
        }


def assemble_leaves(store: ChunkStore) -> Dict[str, Any]:
    """Flat {path: array} view of a complete store's buffer.

    Raw-wire leaves are ZERO-COPY numpy views over the host buffer
    (jax.device_put during cutover streams straight from these pages,
    exactly like the mmap fast path in weight_transfer.load_raw_params).
    int8-wire leaves dequantize here (one float multiply per element,
    cast back to the logical dtype). For a SHARD manifest the arrays are
    the leaf's local shard (``shape`` is already the local shape) — the
    engine device_puts them directly under its NamedSharding, so no
    model-sized host buffer ever exists on a sharded server."""
    import ml_dtypes  # noqa: F401  registers bfloat16 et al. by name
    import numpy as np

    if not store.complete():
        raise WeightFetchError(
            f"assemble on incomplete store v{store.version}"
        )
    base = np.frombuffer(store.buf, dtype=np.uint8)

    def view(off, nbytes, dtype, shape):
        return base[off : off + nbytes].view(dtype).reshape(shape)

    leaves = {}
    for e in store.manifest["leaves"]:
        dt = np.dtype(e["dtype"])
        if e.get("wire", "raw") == "int8":
            from areal_tpu.system.weight_transfer import dequantize_wire_leaf

            q = view(e["offset"], int(e["nbytes"]), np.int8, e["shape"])
            s = view(
                int(e["scale_offset"]), int(e["scale_nbytes"]),
                np.float32, e["scale_shape"],
            )
            leaves[e["path"]] = dequantize_wire_leaf(q, s, dt)
        else:
            nbytes = int(
                e.get("nbytes")
                or int(np.prod(e["shape"], dtype=np.int64)) * dt.itemsize
            )
            leaves[e["path"]] = view(e["offset"], nbytes, dt, e["shape"])
    return leaves


def assemble_params(store: ChunkStore) -> Tuple[Any, int]:
    """A complete store's buffer as the (nested-dict) params pytree +
    its version — full manifests yield full leaves; shard manifests
    yield each leaf's LOCAL shard (see assemble_leaves)."""
    from areal_tpu.system.weight_transfer import unflatten_leaves

    return unflatten_leaves(assemble_leaves(store)), store.version

"""AsyncIOSequenceBuffer semantics (mirrors reference buffer behavior:
key readiness gates MFC batches, oldest-first, GC after full consumption)."""

import asyncio

import numpy as np
import pytest

from areal_tpu.api.config import ModelName
from areal_tpu.api.data_api import SequenceSample
from areal_tpu.api.dfg import MFCDef, ModelInterfaceType, build_graph
from areal_tpu.system.buffer import AsyncIOSequenceBuffer


def _sample(i, keys=("packed_prompts",), seqlen=4):
    data = {k: np.arange(seqlen, dtype=np.int32) for k in keys}
    return SequenceSample.from_default(
        ids=[f"s{i}"], seqlens=[seqlen], data=data
    )


def _rpcs(n_seqs=2):
    gen = MFCDef(
        name="gen",
        model_name=ModelName("actor", 0),
        interface_type=ModelInterfaceType.GENERATE,
        interface_impl=None,
        n_seqs=n_seqs,
        input_keys=("packed_prompts",),
        output_keys=("seq", "logp"),
    )
    train = MFCDef(
        name="train",
        model_name=ModelName("actor", 1),
        interface_type=ModelInterfaceType.TRAIN_STEP,
        interface_impl=None,
        n_seqs=n_seqs,
        input_keys=("seq", "logp"),
        output_keys=(),
    )
    build_graph([gen, train])
    return gen, train


def test_batch_waits_for_keys_and_gc():
    gen, train = _rpcs()
    buf = AsyncIOSequenceBuffer([gen, train])

    async def main():
        await buf.put_batch([_sample(0), _sample(1), _sample(2)])

        ids, batch = await buf.get_batch_for_rpc(gen)
        assert ids == ["s0", "s1"]  # oldest first
        assert batch.bs == 2

        # train's keys aren't ready: it must block until gen's outputs land.
        task = asyncio.create_task(buf.get_batch_for_rpc(train))
        await asyncio.sleep(0.05)
        assert not task.done()

        out = SequenceSample.from_default(
            ids=ids,
            seqlens=[5, 5],
            data={
                "seq": np.zeros(10, dtype=np.int32),
                "logp": np.zeros(10, dtype=np.float32),
            },
        )
        await buf.amend_batch(out)
        got_ids, _ = await asyncio.wait_for(task, timeout=5)
        assert got_ids == ["s0", "s1"]
        # Both RPCs consumed s0/s1 -> GC'd; s2 remains.
        assert buf.size == 1

    asyncio.run(main())


def test_no_duplicate_consumption():
    gen, train = _rpcs()
    buf = AsyncIOSequenceBuffer([gen, train])

    async def main():
        await buf.put_batch([_sample(i) for i in range(4)])
        ids1, _ = await buf.get_batch_for_rpc(gen)
        ids2, _ = await buf.get_batch_for_rpc(gen)
        assert set(ids1) & set(ids2) == set()
        # resident duplicate (epoch carryover) is skipped but COUNTED
        # (ADVICE r1 d: no silent drop)
        n = await buf.put_batch([_sample(0)])
        assert n == 0
        assert buf.n_dropped_duplicates == 1

    asyncio.run(main())


def test_overflow_raises():
    gen, train = _rpcs()
    buf = AsyncIOSequenceBuffer([gen, train], max_size=2)

    async def main():
        with pytest.raises(RuntimeError):
            await buf.put_batch([_sample(i) for i in range(3)])

    asyncio.run(main())


def test_duplicate_id_semantics():
    """ADVICE r1 (d): no silent drops. Resident duplicates (legal epoch
    carryover) are skipped but counted; duplicates WITHIN one call are a
    producer bug and raise before anything is inserted."""
    buf = AsyncIOSequenceBuffer(_rpcs(), max_size=8)

    async def run():
        await buf.put_batch([_sample(1)])
        n = await buf.put_batch([_sample(1)])  # resident duplicate
        assert n == 0
        assert buf.n_dropped_duplicates == 1
        with pytest.raises(ValueError, match="duplicate"):
            await buf.put_batch([_sample(2), _sample(2)])  # in-call dup
        # the failed call must not have inserted s2
        assert buf.size == 1

    asyncio.run(run())


def _seq_sample(i, seq, keys=("packed_prompts",), seqlen=4):
    data = {k: np.arange(seqlen, dtype=np.int32) for k in keys}
    return SequenceSample.from_default(
        ids=[f"s{i}"], seqlens=[seqlen], data=data,
        metadata={"wal_seq": [seq]},
    )


async def _consume_fully(buf, gen, train, n=2):
    ids, _ = await buf.get_batch_for_rpc(gen)
    out = SequenceSample.from_default(
        ids=ids, seqlens=[5] * len(ids),
        data={
            "seq": np.zeros(5 * len(ids), dtype=np.int32),
            "logp": np.zeros(5 * len(ids), dtype=np.float32),
        },
    )
    await buf.amend_batch(out)
    await buf.get_batch_for_rpc(train)
    return ids


def test_seq_ledger_blocks_redelivery_after_consumption():
    """ISSUE 16 exactly-once pin: a redelivered/replayed sample whose
    seq was fully consumed is dropped at admission — it trains exactly
    once, and the duplicate-consumption DETECTOR stays 0."""
    gen, train = _rpcs()
    buf = AsyncIOSequenceBuffer([gen, train])

    async def main():
        await buf.put_batch([_seq_sample(0, "w0/0"), _seq_sample(1, "w0/1")])
        await _consume_fully(buf, gen, train)
        assert buf.size == 0
        assert "w0/0" in buf.seq_ledger and "w0/1" in buf.seq_ledger
        # Pusher redelivery of the same seqs (same OR different ids):
        n = await buf.put_batch(
            [_seq_sample(0, "w0/0"), _seq_sample(9, "w0/1")]
        )
        assert n == 0
        assert buf.n_ledger_filtered == 2
        assert buf.counters["areal:train_samples_duplicated_total"] == 0

    asyncio.run(main())


def test_seq_pending_blocks_readmission_under_new_id():
    """A redelivered copy of a RESIDENT seq under a different sample id
    must not slip past the resident-id dedup — the pending-seq check
    catches it."""
    gen, train = _rpcs()
    buf = AsyncIOSequenceBuffer([gen, train])

    async def main():
        await buf.put_batch([_seq_sample(0, "w0/0")])
        # Same seq, different id: dropped at admission.
        n = await buf.put_batch([_seq_sample(7, "w0/0")])
        assert n == 0 and buf.n_ledger_filtered == 1
        # Same seq, SAME id: the resident-duplicate path (counted there).
        n = await buf.put_batch([_seq_sample(0, "w0/0")])
        assert n == 0 and buf.n_dropped_duplicates == 1
        assert buf.size == 1

    asyncio.run(main())


def test_seeded_ledger_filters_wal_replay():
    """Recovery: the ledger snapshot from the recover record re-arms
    admission, so WAL replay of already-consumed seqs is filtered
    against the same cut the engine state was taken at."""
    gen, train = _rpcs(n_seqs=1)
    buf = AsyncIOSequenceBuffer([gen, train])
    buf.seed_consumed_seqs({"water": {"w0": 0}, "extras": {"w0": [2]}})

    async def main():
        n = await buf.put_batch([
            _seq_sample(0, "w0/0"),  # below watermark: consumed pre-kill
            _seq_sample(1, "w0/1"),  # the gap: NOT consumed, admitted
            _seq_sample(2, "w0/2"),  # extra: consumed pre-kill
        ])
        assert n == 1
        assert buf.n_ledger_filtered == 2
        ids = await _consume_fully(buf, gen, train)
        assert ids == ["s1"]
        # The next barrier's snapshot now covers all three.
        snap = buf.consumed_seqs()
        assert snap == {"water": {"w0": 2}, "extras": {}}

    asyncio.run(main())


def test_samples_without_seq_bypass_ledger():
    """Dataset-sourced samples (no wal_seq metadata) never touch the
    ledger — exactly-once for them stays the ignore_ids contract."""
    gen, train = _rpcs()
    buf = AsyncIOSequenceBuffer([gen, train])

    async def main():
        await buf.put_batch([_sample(0), _sample(1)])
        await _consume_fully(buf, gen, train)
        assert buf.consumed_seqs() == {"water": {}, "extras": {}}
        # Epoch 2 re-put of the same row ids is legal.
        n = await buf.put_batch([_sample(0), _sample(1)])
        assert n == 2

    asyncio.run(main())


def _task_sample(i, task, v_end, seqlen=4):
    data = {"packed_prompts": np.arange(seqlen, dtype=np.int32)}
    return SequenceSample.from_default(
        ids=[f"s{i}"], seqlens=[seqlen], data=data,
        metadata={"task": [task], "version_end": [v_end]},
    )


def test_per_task_staleness_windows_gate_admission():
    """ISSUE 18: per-task staleness — trajectories carry a `task` tag and
    admission applies a PER-TASK version window (math tight, agentic
    loose), so slow agentic episodes survive the gate that drops stale
    math samples. Drops are counted, never silent."""
    gen, train = _rpcs()
    buf = AsyncIOSequenceBuffer([gen, train])
    assert buf.task_windows == {"math": 2, "agentic": 8}  # registry default
    buf.current_train_step = 10

    async def main():
        n = await buf.put_batch([
            _task_sample(0, "math", 8),      # lag 2 == window: admitted
            _task_sample(1, "math", 7),      # lag 3 > 2: dropped
            _task_sample(2, "agentic", 2),   # lag 8 == window: admitted
            _task_sample(3, "agentic", 1),   # lag 9 > 8: dropped
            _task_sample(4, "mystery", 0),   # no window for the task
            _sample(5),                      # no task tag at all
        ])
        assert n == 4
        assert buf.counters["areal:train_stale_dropped_total"] == 2
        assert buf.size == 4

    asyncio.run(main())


def test_stale_drops_attributed_per_task():
    """ISSUE 19 (mixed-stream remainder): staleness drops are
    attributed to the task stream that suffered them, so a mixed
    math+agentic run can tell WHICH stream is falling behind (the
    trainer surfaces these as perf/task_stale_dropped_*)."""
    gen, train = _rpcs()
    buf = AsyncIOSequenceBuffer([gen, train])
    buf.current_train_step = 10

    async def main():
        await buf.put_batch([
            _task_sample(0, "math", 7),     # stale
            _task_sample(1, "math", 6),     # stale
            _task_sample(2, "agentic", 1),  # stale
            _task_sample(3, "agentic", 2),  # admitted
            _task_sample(4, "math", 10),    # admitted
        ])
        assert buf.stale_dropped_by_task == {"math": 2, "agentic": 1}
        assert buf.counters["areal:train_stale_dropped_total"] == 3
        # The attribution accumulates across batches, like the counter.
        await buf.put_batch([_task_sample(5, "math", 0)])
        assert buf.stale_dropped_by_task == {"math": 3, "agentic": 1}

    asyncio.run(main())


def test_task_windows_env_override(monkeypatch):
    """The windows knob parses operator overrides and shrugs off
    malformed entries instead of taking the trainer down."""
    monkeypatch.setenv(
        "AREAL_TASK_STALENESS_WINDOWS", "math:0,agentic:4,junk,bad:x"
    )
    gen, train = _rpcs()
    buf = AsyncIOSequenceBuffer([gen, train])
    assert buf.task_windows == {"math": 0, "agentic": 4}
    buf.current_train_step = 1

    async def main():
        # math window 0: anything behind the current step is stale.
        n = await buf.put_batch([
            _task_sample(0, "math", 1),
            _task_sample(1, "math", 0),
        ])
        assert n == 1
        assert buf.counters["areal:train_stale_dropped_total"] == 1

    asyncio.run(main())


def test_overflow_precheck_counts_unique_ids():
    """ADVICE r1 (e): the capacity precheck must not overcount — filling
    to exactly max_size succeeds."""
    buf = AsyncIOSequenceBuffer(_rpcs(), max_size=3)

    async def run():
        await buf.put_batch([_sample(1), _sample(2), _sample(3)])
        assert buf.size == 3
        with pytest.raises(RuntimeError, match="overflow"):
            await buf.put_batch([_sample(4)])

    asyncio.run(run())


def test_resident_ids_spares_carryover_copies():
    """The step-end cache clear asks the buffer which consumed ids were
    re-admitted mid-step (epoch carryover): those must keep their tracker
    entries and worker-side data for the next step."""
    gen, train = _rpcs()
    buf = AsyncIOSequenceBuffer([gen, train])

    async def main():
        await buf.put_batch([_sample(0), _sample(1)])
        assert buf.resident_ids({"s0", "s1", "zz"}) == {"s0", "s1"}
        # Consume s0/s1 through both rpcs -> GC'd.
        _, b = await buf.get_batch_for_rpc(gen)
        await buf.amend_batch(
            SequenceSample(
                ids=list(b.ids),
                keys={"seq", "logp"},
                data={
                    "seq": np.zeros(b.bs, dtype=np.int32),
                    "logp": np.zeros(b.bs, dtype=np.float32),
                },
                seqlens={"seq": [[1]] * b.bs, "logp": [[1]] * b.bs},
            )
        )
        await buf.get_batch_for_rpc(train)
        assert buf.resident_ids({"s0", "s1"}) == set()
        # Re-admission of the same row id (next epoch) makes it resident
        # again, so the clear must defer it.
        await buf.put_batch([_sample(0)])
        assert buf.resident_ids({"s0", "s1"}) == {"s0"}

    asyncio.run(main())

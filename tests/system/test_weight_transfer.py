"""weight_transfer: raw dump/mmap-load round trip, versioned GC, torn-write
rejection, and the serving load-path priority (shm raw -> disk raw ->
pickle)."""

import os
import pickle

import numpy as np
import pytest

from areal_tpu.system.weight_transfer import (
    dump_raw_params,
    load_for_serving,
    load_raw_params,
    shm_transfer_dir,
)


def _params(seed=0):
    import ml_dtypes

    rng = np.random.default_rng(seed)
    return {
        "embedding": {"weight": rng.standard_normal((16, 8)).astype(np.float32)},
        "layers": {
            # bfloat16 leaf: the flagship dumps bf16 params, and the
            # manifest must round-trip ml_dtypes names.
            "attn": {"wq": rng.standard_normal((2, 8, 8)).astype(ml_dtypes.bfloat16)},
            "ln": {"scale": np.ones((2, 8), np.float32)},
        },
    }


def test_bf16_dtype_roundtrip(tmp_path):
    import ml_dtypes

    d = str(tmp_path / "dump")
    dump_raw_params(_params(0), d, version=1)
    got, _ = load_raw_params(d)
    assert got["layers"]["attn"]["wq"].dtype == ml_dtypes.bfloat16


def _assert_tree_equal(a, b):
    assert sorted(a.keys()) == sorted(b.keys())
    for k in a:
        if isinstance(a[k], dict):
            _assert_tree_equal(a[k], b[k])
        else:
            np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]))


def test_roundtrip_and_versions(tmp_path):
    d = str(tmp_path / "dump")
    p1 = _params(1)
    dt = dump_raw_params(p1, d, version=1)
    assert dt >= 0
    got, v = load_raw_params(d)
    assert v == 1
    _assert_tree_equal(p1, got)

    p2 = _params(2)
    dump_raw_params(p2, d, version=2)
    got2, v2 = load_raw_params(d)
    assert v2 == 2
    _assert_tree_equal(p2, got2)

    # GC keeps the newest 2 bins.
    for ver in (3, 4, 5):
        dump_raw_params(_params(ver), d, version=ver)
    bins = [b for b in os.listdir(d) if b.endswith(".bin")]
    assert sorted(bins) == ["params-v4.bin", "params-v5.bin"]


def test_torn_write_rejected(tmp_path):
    d = str(tmp_path / "dump")
    dump_raw_params(_params(0), d, version=1)
    # Truncate the bin: manifest's total_bytes no longer matches.
    bin_path = os.path.join(d, "params-v1.bin")
    with open(bin_path, "r+b") as f:
        f.truncate(os.path.getsize(bin_path) - 8)
    assert load_raw_params(d) is None


def test_rejects_non_dict_trees(tmp_path):
    with pytest.raises(TypeError, match="dict-of-array"):
        dump_raw_params({"a": [np.zeros(2)]}, str(tmp_path), version=1)


def test_load_for_serving_priority(tmp_path):
    model_path = str(tmp_path / "realloc")
    shm = str(tmp_path / "shm")
    os.makedirs(model_path)

    # Only pickle present -> pickle source.
    p_pkl = _params(10)
    with open(os.path.join(model_path, "engine_state.pkl"), "wb") as f:
        pickle.dump({"params": p_pkl}, f)
    params, info = load_for_serving(model_path, shm_dir=shm)
    assert info["source"] == "pickle"
    _assert_tree_equal(p_pkl, params)

    # Disk raw beats pickle.
    p_disk = _params(11)
    dump_raw_params(p_disk, model_path, version=7)
    params, info = load_for_serving(model_path, shm_dir=shm)
    assert info["source"] == "disk_raw" and info["version"] == 7
    _assert_tree_equal(p_disk, params)

    # shm raw beats disk raw.
    p_shm = _params(12)
    dump_raw_params(p_shm, shm, version=8)
    params, info = load_for_serving(model_path, shm_dir=shm)
    assert info["source"] == "shm_raw" and info["version"] == 8
    _assert_tree_equal(p_shm, params)
    assert info["load_s"] >= 0


def test_shm_dir_shape():
    d = shm_transfer_dir("exp", "trial", "actor")
    if d is not None:  # machines without /dev/shm skip the path check
        assert d.endswith("areal_tpu/exp/trial/actor")

"""MoE layer: routing correctness, aux losses, decode/forward parity,
and end-to-end training through the engine."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from areal_tpu.models.config import MoEConfig, TransformerConfig
from areal_tpu.models.moe import moe_mlp
from areal_tpu.models.transformer import forward, init_params

CFG = TransformerConfig(
    n_layers=2,
    hidden_dim=32,
    n_q_heads=2,
    n_kv_heads=1,
    head_dim=16,
    intermediate_dim=64,
    vocab_size=64,
    max_position_embeddings=128,
    compute_dtype="float32",
    param_dtype="float32",
    # capacity_factor >= E/k = 2 -> no capacity drops, so the packed
    # forward and the per-step decode path route identically (drops are a
    # batch-global, non-causal approximation that would break parity).
    moe=MoEConfig(
        num_experts=4, top_k=2, capacity_factor=2.5,
        aux_loss_coef=1e-2, z_loss_coef=1e-3,
    ),
)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.PRNGKey(0))


def test_moe_mlp_shapes_and_gates(params):
    rng = jax.random.PRNGKey(1)
    x = jax.random.normal(rng, (3, 8, CFG.hidden_dim), jnp.float32)
    lp = jax.tree_util.tree_map(lambda a: a[0], params["layers"]["mlp"])
    y, aux = moe_mlp(x, lp, CFG, jnp.float32)
    assert y.shape == x.shape
    assert np.isfinite(np.asarray(y)).all()
    assert 0.5 < float(aux["load_balance_loss"]) < 4.0  # ~1 near-uniform routing
    assert float(aux["z_loss"]) >= 0.0


def test_moe_capacity_drops_dont_crash(params):
    """Tiny capacity: some tokens get dropped, output stays finite."""
    x = jax.random.normal(jax.random.PRNGKey(2), (1, 64, CFG.hidden_dim))
    lp = jax.tree_util.tree_map(lambda a: a[0], params["layers"]["mlp"])
    y, _ = moe_mlp(x, lp, CFG, jnp.float32, capacity_factor=0.25)
    assert np.isfinite(np.asarray(y)).all()


def test_moe_forward_and_grads(params):
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 64, (2, 16)), jnp.int32)
    seg = jnp.ones_like(ids)
    pos = jnp.tile(jnp.arange(16)[None, :], (2, 1))
    logits, aux = forward(params, CFG, ids, seg, pos, return_aux=True)
    assert logits.shape == (2, 16, 64)
    assert 0.5 * CFG.n_layers < float(aux["load_balance_loss"]) < 4.0 * CFG.n_layers

    def loss(p):
        lg, aux = forward(p, CFG, ids, seg, pos, return_aux=True)
        return jnp.mean(lg**2) + 0.01 * aux["load_balance_loss"]

    grads = jax.grad(loss)(params)
    gr = grads["layers"]["mlp"]["router"]
    assert np.abs(np.asarray(gr)).sum() > 0  # router receives gradient
    ge = grads["layers"]["mlp"]["w_gate"]
    assert np.isfinite(np.asarray(ge)).all()


def test_moe_decode_matches_forward(params):
    """Greedy generation through the decode path must match the packed
    forward's next-token argmax (same tokens step by step)."""
    from areal_tpu.api.model_api import GenerationHyperparameters
    from areal_tpu.models.generation import generate_tokens

    prompt = [5, 9, 11]
    g = GenerationHyperparameters(max_new_tokens=6, greedy=True)
    out = generate_tokens(
        params, CFG, [prompt], g, jax.random.PRNGKey(0), eos_token_id=None,
        prompt_pad_multiple=8,
    )[0]
    toks = prompt + out["output_ids"]
    # Teacher-force through the packed forward; each next token must be the
    # argmax at the previous position.
    ids = jnp.asarray([toks], jnp.int32)
    seg = jnp.ones_like(ids)
    pos = jnp.tile(jnp.arange(len(toks))[None, :], (1, 1))
    logits = forward(params, CFG, ids, seg, pos)
    preds = np.asarray(jnp.argmax(logits[0], -1))
    for i in range(len(prompt) - 1, len(toks) - 1):
        assert preds[i] == toks[i + 1], f"mismatch at {i}"


def test_moe_engine_train_step():
    from areal_tpu.api.data_api import MicroBatchSpec, SequenceSample
    from areal_tpu.engine.jax_engine import JaxTrainEngine
    from areal_tpu.engine.optimizer import OptimizerConfig
    from areal_tpu.interfaces.sft import sft_loss_weight, sft_row_loss

    params = init_params(CFG, jax.random.PRNGKey(3))
    eng = JaxTrainEngine(
        CFG, params, optimizer_config=OptimizerConfig(lr=1e-3),
        total_train_steps=10, remat=False, row_len_multiple=8,
    )
    rng = np.random.RandomState(0)
    seqlens = [10, 14, 7]
    toks = np.concatenate([rng.randint(0, 64, n) for n in seqlens]).astype(np.int32)
    pm = np.concatenate(
        [np.r_[np.ones(3, bool), np.zeros(n - 3, bool)] for n in seqlens]
    )
    s = SequenceSample.from_default(
        ids=["a", "b", "c"],
        seqlens=seqlens,
        data=dict(packed_input_ids=toks, prompt_mask=pm),
    )
    stats = eng.train_batch(
        s, MicroBatchSpec(), loss_fn=sft_row_loss, loss_weight_fn=sft_loss_weight,
        loss_name="sft",
    )
    assert np.isfinite(stats["sft/loss"])
    assert stats["sft/moe_load_balance"] > 0


def _skewed_input(params, n_tokens=64, seed=3):
    """An input batch steered toward one expert: take the direction that
    maximizes one router logit and add it to every token."""
    lp = jax.tree_util.tree_map(lambda a: a[0], params["layers"]["mlp"])
    router = np.asarray(lp["router"], np.float32)  # [D, E]
    bias_dir = router[:, 0] / max(np.linalg.norm(router[:, 0]), 1e-6)
    rng = np.random.RandomState(seed)
    x = rng.randn(1, n_tokens, CFG.hidden_dim).astype(np.float32)
    x = x + 6.0 * bias_dir[None, None, :]
    return jnp.asarray(x), lp


def test_moe_drop_rate_under_skew(params):
    """The capacity dispatcher's quality risk is measured, not assumed:
    skewed routing overflows the hot expert and drop_rate reports it;
    balanced routing at ample capacity reports ~0 (VERDICT r4 weak #6)."""
    x, lp = _skewed_input(params)
    _, aux = moe_mlp(x, lp, CFG, jnp.float32, capacity_factor=1.0)
    skew_drop = float(aux["drop_rate"])
    # Every token's top choice is expert 0 -> its capacity buffer
    # (1.0 * T * k / E slots) overflows badly.
    assert skew_drop > 0.2

    x_bal = jax.random.normal(jax.random.PRNGKey(4), (1, 64, CFG.hidden_dim))
    _, aux_bal = moe_mlp(x_bal, lp, CFG, jnp.float32, capacity_factor=2.5)
    assert float(aux_bal["drop_rate"]) == 0.0
    # Rate is a fraction of (token, choice) routings.
    assert 0.0 <= skew_drop <= 1.0


def test_moe_dropless_matches_capacity_when_no_drops(params):
    """At capacity_factor >= E/k nothing drops, so the ragged-dot
    dropless path must agree with the einsum capacity path."""
    import dataclasses

    x = jax.random.normal(jax.random.PRNGKey(5), (2, 16, CFG.hidden_dim))
    lp = jax.tree_util.tree_map(lambda a: a[0], params["layers"]["mlp"])
    y_cap, aux_cap = moe_mlp(x, lp, CFG, jnp.float32, capacity_factor=2.5)

    cfg_dropless = dataclasses.replace(
        CFG, moe=dataclasses.replace(CFG.moe, dispatch="dropless")
    )
    y_dl, aux_dl = moe_mlp(x, lp, cfg_dropless, jnp.float32)
    np.testing.assert_allclose(
        np.asarray(y_dl), np.asarray(y_cap), rtol=1e-5, atol=1e-5
    )
    assert float(aux_dl["drop_rate"]) == 0.0


def test_moe_dropless_exact_under_skew(params):
    """Under routing skew the capacity path loses tokens but the
    dropless path still computes every (token, choice) contribution:
    it must match a reference dense per-token mixture exactly."""
    import dataclasses

    x, lp = _skewed_input(params, n_tokens=32)
    cfg_dropless = dataclasses.replace(
        CFG, moe=dataclasses.replace(CFG.moe, dispatch="dropless")
    )
    y_dl, aux = moe_mlp(x, lp, cfg_dropless, jnp.float32)
    assert float(aux["drop_rate"]) == 0.0

    # Dense reference: route every token through its top-k experts.
    xt = np.asarray(x, np.float32).reshape(-1, CFG.hidden_dim)
    router = np.asarray(lp["router"], np.float32)
    logits = xt @ router
    probs = np.exp(logits - logits.max(-1, keepdims=True))
    probs = probs / probs.sum(-1, keepdims=True)
    k = CFG.moe.top_k
    top_e = np.argsort(-probs, axis=-1)[:, :k]
    top_p = np.take_along_axis(probs, top_e, axis=-1)
    top_p = top_p / np.maximum(top_p.sum(-1, keepdims=True), 1e-9)
    wg = np.asarray(lp["w_gate"], np.float32)
    wu = np.asarray(lp["w_up"], np.float32)
    wd = np.asarray(lp["w_down"], np.float32)

    def silu(a):
        return a / (1.0 + np.exp(-a))

    y_ref = np.zeros_like(xt)
    for t in range(xt.shape[0]):
        for j in range(k):
            e = top_e[t, j]
            h = silu(xt[t] @ wg[e]) * (xt[t] @ wu[e])
            y_ref[t] += top_p[t, j] * (h @ wd[e])
    np.testing.assert_allclose(
        np.asarray(y_dl).reshape(-1, CFG.hidden_dim), y_ref,
        rtol=2e-4, atol=2e-4,
    )


def test_moe_dropless_gradients_finite(params):
    """ragged_dot + scatter-add combine must be differentiable end to
    end (training uses the same path)."""
    import dataclasses

    cfg_dropless = dataclasses.replace(
        CFG, moe=dataclasses.replace(CFG.moe, dispatch="dropless")
    )
    x = jax.random.normal(jax.random.PRNGKey(6), (1, 16, CFG.hidden_dim))
    lp = jax.tree_util.tree_map(lambda a: a[0], params["layers"]["mlp"])

    def loss(p, xx):
        y, aux = moe_mlp(xx, p, cfg_dropless, jnp.float32)
        return jnp.sum(y**2) + aux["load_balance_loss"]

    grads = jax.grad(loss)(lp, x)
    for leaf in jax.tree_util.tree_leaves(grads):
        assert np.isfinite(np.asarray(leaf)).all()


def test_moe_drop_rate_reaches_train_stats():
    """The engine surfaces moe_drop_rate through the train-step stats
    (normalized to a per-layer mean fraction)."""
    from areal_tpu.api.data_api import MicroBatchSpec, SequenceSample
    from areal_tpu.engine.jax_engine import JaxTrainEngine
    from areal_tpu.engine.optimizer import OptimizerConfig
    from areal_tpu.interfaces.sft import sft_loss_weight, sft_row_loss

    params = init_params(CFG, jax.random.PRNGKey(3))
    eng = JaxTrainEngine(
        CFG, params, optimizer_config=OptimizerConfig(lr=1e-3),
        total_train_steps=10, remat=False, row_len_multiple=8,
    )
    rng = np.random.RandomState(1)
    seqlens = [10, 14, 7]
    toks = np.concatenate(
        [rng.randint(0, 64, n) for n in seqlens]
    ).astype(np.int32)
    pm = np.concatenate(
        [np.r_[np.ones(3, bool), np.zeros(n - 3, bool)] for n in seqlens]
    )
    s = SequenceSample.from_default(
        ids=["a", "b", "c"],
        seqlens=seqlens,
        data=dict(packed_input_ids=toks, prompt_mask=pm),
    )
    stats = eng.train_batch(
        s, MicroBatchSpec(), loss_fn=sft_row_loss,
        loss_weight_fn=sft_loss_weight, loss_name="sft",
    )
    assert "sft/moe_drop_rate" in stats
    assert 0.0 <= stats["sft/moe_drop_rate"] <= 1.0


def test_moe_dispatch_validated():
    with pytest.raises(ValueError, match="dispatch"):
        MoEConfig(num_experts=4, top_k=2, dispatch="Dropless")


def test_moe_drop_rate_counts_real_tokens_only(params):
    """Padding rows route too (static shapes) but must not dilute the
    reported drop rate: with token_mask, the rate is over real routings."""
    x, lp = _skewed_input(params, n_tokens=32)
    # Second half of the tokens are padding.
    mask = jnp.asarray(np.r_[np.ones(16, bool), np.zeros(16, bool)])
    _, aux_masked = moe_mlp(
        x, lp, CFG, jnp.float32, capacity_factor=1.0,
        token_mask=mask.reshape(x.shape[:-1]) if x.ndim == 2
        else jnp.broadcast_to(mask, x.shape[:-1]),
    )
    _, aux_unmasked = moe_mlp(x, lp, CFG, jnp.float32, capacity_factor=1.0)
    # All tokens (real + pad) fight for the same capacity. Under full
    # skew the capacity buffer keeps the EARLIEST routings in priority
    # order — the real (first-half) tokens — so the real-token rate is
    # strictly below the all-token rate. Equal rates would mean the
    # mask was ignored.
    assert 0.0 <= float(aux_masked["drop_rate"]) <= 1.0
    assert 0.0 <= float(aux_unmasked["drop_rate"]) <= 1.0
    assert float(aux_masked["drop_rate"]) < float(aux_unmasked["drop_rate"])


def test_moe_dropless_trains_on_expert_parallel_mesh():
    """The old dropless x fsdp guard is gone: on an fsdp>1 mesh the
    engine dispatches into the shard_map expert-parallel path
    (models/moe._moe_mlp_ep) — zero drops, expert weights never
    all-gathered — and the router telemetry flows through train stats
    (a2a_bytes > 0 proves the EP exchange path was taken, not the
    single-device ragged_dot fallback)."""
    import dataclasses

    from areal_tpu.api.data_api import MicroBatchSpec, SequenceSample
    from areal_tpu.base.topology import MeshSpec
    from areal_tpu.engine.jax_engine import JaxTrainEngine
    from areal_tpu.engine.optimizer import OptimizerConfig
    from areal_tpu.interfaces.sft import sft_loss_weight, sft_row_loss
    from areal_tpu.parallel.mesh import make_mesh

    cfg = dataclasses.replace(
        CFG, moe=dataclasses.replace(CFG.moe, dispatch="dropless")
    )
    mesh = make_mesh(MeshSpec.parse("d1f2t1"), devices=jax.devices()[:2])
    params = init_params(cfg, jax.random.PRNGKey(0))
    eng = JaxTrainEngine(
        cfg, params, optimizer_config=OptimizerConfig(lr=1e-3),
        total_train_steps=10, remat=False, mesh=mesh, row_len_multiple=16,
    )
    rng = np.random.RandomState(0)
    seqlens = [16, 16, 16, 16]
    toks = np.concatenate(
        [rng.randint(0, 64, n) for n in seqlens]
    ).astype(np.int32)
    pm = np.concatenate(
        [np.r_[np.ones(3, bool), np.zeros(n - 3, bool)] for n in seqlens]
    )
    s = SequenceSample.from_default(
        ids=["a", "b", "c", "d"],
        seqlens=seqlens,
        data=dict(packed_input_ids=toks, prompt_mask=pm),
    )
    stats = eng.train_batch(
        s, MicroBatchSpec(), loss_fn=sft_row_loss,
        loss_weight_fn=sft_loss_weight, loss_name="sft",
    )
    assert np.isfinite(stats["sft/loss"])
    assert stats["sft/moe_drop_rate"] == 0.0
    assert stats["sft/moe_a2a_bytes"] > 0.0
    assert stats["sft/moe_router_entropy"] > 0.0


def test_moe_env_dispatch_override(monkeypatch):
    """AREAL_MOE_DISPATCH rewrites the model config's moe.dispatch at
    engine construction — the env-shaped end of the cli knob."""
    from areal_tpu.engine.jax_engine import JaxTrainEngine
    from areal_tpu.engine.optimizer import OptimizerConfig

    monkeypatch.setenv("AREAL_MOE_DISPATCH", "dropless")
    params = init_params(CFG, jax.random.PRNGKey(0))
    eng = JaxTrainEngine(
        CFG, params, optimizer_config=OptimizerConfig(lr=1e-3),
        total_train_steps=10, remat=False,
    )
    assert eng.model_cfg.moe.dispatch == "dropless"
    assert CFG.moe.dispatch == "capacity"  # caller's config untouched

    monkeypatch.setenv("AREAL_MOE_DISPATCH", "bogus")
    with pytest.raises(ValueError, match="dispatch"):
        JaxTrainEngine(
            CFG, params, optimizer_config=OptimizerConfig(lr=1e-3),
            total_train_steps=10, remat=False,
        )


def test_moe_config_dict_coercion():
    """Experiment configs arrive as plain kwargs dicts (cli_args ->
    factories TransformerConfig(**config)); the nested moe block must
    coerce to an MoEConfig, typos and all."""
    cfg = TransformerConfig(
        n_layers=2, hidden_dim=32, n_q_heads=2, n_kv_heads=1, head_dim=16,
        intermediate_dim=64, vocab_size=64,
        moe={"num_experts": 8, "top_k": 2, "dispatch": "dropless"},
    )
    assert isinstance(cfg.moe, MoEConfig)
    assert cfg.moe.num_experts == 8 and cfg.moe.dispatch == "dropless"
    with pytest.raises(ValueError, match="dispatch"):
        TransformerConfig(
            n_layers=2, hidden_dim=32, n_q_heads=2, n_kv_heads=1,
            head_dim=16, intermediate_dim=64, vocab_size=64,
            moe={"dispatch": "droppless"},
        )


def test_moe_cli_overrides_end_to_end():
    """The flat moe_* knobs on ModelTrainEvalConfig overlay the nested
    config['moe'] block through model_abstraction, and setting them on
    a dense model refuses instead of silently no-opping."""
    from areal_tpu.api.cli_args import ModelTrainEvalConfig
    from areal_tpu.experiments.common import model_abstraction

    base = {
        "n_layers": 2, "hidden_dim": 32, "n_q_heads": 2, "n_kv_heads": 1,
        "head_dim": 16, "intermediate_dim": 64, "vocab_size": 64,
        "moe": {"num_experts": 4, "top_k": 2},
    }
    m = ModelTrainEvalConfig(
        config=dict(base), init_from_scratch=True,
        moe_dispatch="dropless", moe_capacity_factor=2.0,
    )
    out = model_abstraction(m, tokenizer_path=None).args["config"]
    assert out["moe"]["dispatch"] == "dropless"
    assert out["moe"]["capacity_factor"] == 2.0
    assert out["moe"]["num_experts"] == 4  # untouched fields survive
    assert base["moe"] == {"num_experts": 4, "top_k": 2}  # no mutation
    # The overlaid dict builds a real model config.
    cfg = TransformerConfig(**out)
    assert cfg.moe.dispatch == "dropless"
    # No knobs -> config passes through untouched.
    plain = ModelTrainEvalConfig(config=dict(base), init_from_scratch=True)
    assert model_abstraction(
        plain, tokenizer_path=None
    ).args["config"]["moe"] == base["moe"]
    dense = dict(base)
    del dense["moe"]
    with pytest.raises(ValueError, match="no 'moe' block"):
        model_abstraction(
            ModelTrainEvalConfig(
                config=dense, init_from_scratch=True, moe_dispatch="dropless"
            ),
            tokenizer_path=None,
        )

"""HF checkpoint conversion registry.

Counterpart of the reference's HF registry + per-family converters
(realhf/impl/model/conversion/hf_registry.py, realhf/api/from_hf/*). Each
family module registers an `HFFamily` with config and state-dict mappers;
`load_hf_model` / `save_hf_model` go through safetensors on disk so
checkpoints interoperate with the HF ecosystem (and with vLLM/SGLang-style
servers if ever needed).
"""

from __future__ import annotations

import dataclasses
import json
import os
from typing import Any, Callable, Dict, Optional

import numpy as np

from areal_tpu.api.model_api import HF_FAMILY_REGISTRY, register_hf_family
from areal_tpu.models.config import TransformerConfig


@dataclasses.dataclass
class HFFamily:
    name: str
    hf_model_type: str
    config_from_hf: Callable[[Dict[str, Any], bool], TransformerConfig]
    config_to_hf: Callable[[TransformerConfig], Dict[str, Any]]
    params_from_hf: Callable[[Dict[str, np.ndarray], TransformerConfig], Dict]
    params_to_hf: Callable[[Dict, TransformerConfig], Dict[str, np.ndarray]]


def get_family(name: str) -> HFFamily:
    if name not in HF_FAMILY_REGISTRY:
        raise KeyError(
            f"unknown HF family {name!r}; registered: {sorted(HF_FAMILY_REGISTRY)}"
        )
    return HF_FAMILY_REGISTRY[name]


def family_from_hf_config(hf_config: Dict[str, Any]) -> HFFamily:
    mt = hf_config.get("model_type")
    for fam in HF_FAMILY_REGISTRY.values():
        if fam.hf_model_type == mt:
            return fam
    raise KeyError(f"no registered family for HF model_type {mt!r}")


# ---------------------------------------------------------------------------
# Disk IO (safetensors sharded or single, else torch .bin)
# ---------------------------------------------------------------------------


def load_hf_state_dict(path: str) -> Dict[str, np.ndarray]:
    """Read all tensors of an HF checkpoint directory into numpy."""
    import safetensors.numpy

    out: Dict[str, np.ndarray] = {}
    st_files = sorted(f for f in os.listdir(path) if f.endswith(".safetensors"))
    if st_files:
        for f in st_files:
            out.update(safetensors.numpy.load_file(os.path.join(path, f)))
        return out
    bin_files = sorted(f for f in os.listdir(path) if f.endswith(".bin"))
    if bin_files:
        import torch

        for f in bin_files:
            sd = torch.load(os.path.join(path, f), map_location="cpu", weights_only=True)
            out.update({k: v.float().numpy() if v.dtype == torch.bfloat16 else v.numpy()
                        for k, v in sd.items()})
        return out
    raise FileNotFoundError(f"no safetensors/bin weights under {path}")


def torch_state_dict_to_numpy(sd) -> Dict[str, np.ndarray]:
    import torch

    out = {}
    for k, v in sd.items():
        v = v.detach().cpu()
        if v.dtype == torch.bfloat16:
            v = v.float()
        out[k] = v.numpy()
    return out


def load_hf_config(path: str) -> Dict[str, Any]:
    with open(os.path.join(path, "config.json")) as f:
        return json.load(f)


def load_hf_model(
    path: str, is_critic: bool = False, family: Optional[str] = None
):
    """(TransformerConfig, params) from an HF checkpoint directory."""
    hf_cfg = load_hf_config(path)
    fam = get_family(family) if family else family_from_hf_config(hf_cfg)
    cfg = fam.config_from_hf(hf_cfg, is_critic)
    sd = load_hf_state_dict(path)
    params = fam.params_from_hf(sd, cfg)
    return cfg, params


def save_hf_model(
    save_dir: str,
    cfg: TransformerConfig,
    params: Dict,
    family: str,
    tokenizer=None,
):
    """Write an HF-format checkpoint (config.json + model.safetensors)."""
    import safetensors.numpy

    fam = get_family(family)
    os.makedirs(save_dir, exist_ok=True)
    sd = fam.params_to_hf(params, cfg)
    sd = {k: np.ascontiguousarray(v) for k, v in sd.items()}
    safetensors.numpy.save_file(sd, os.path.join(save_dir, "model.safetensors"))
    with open(os.path.join(save_dir, "config.json"), "w") as f:
        json.dump(fam.config_to_hf(cfg), f, indent=2)
    if tokenizer is not None:
        tokenizer.save_pretrained(save_dir)


# ---------------------------------------------------------------------------
# Shared stacking helpers for llama-style families
# ---------------------------------------------------------------------------


def stack_layers(per_layer: list) -> Dict:
    """List of per-layer pytrees -> one pytree with stacked leading axis."""
    import jax

    return jax.tree_util.tree_map(lambda *xs: np.stack(xs, axis=0), *per_layer)


def unstack_layers(stacked: Dict, n_layers: int) -> list:
    import jax

    return [
        jax.tree_util.tree_map(lambda x: np.asarray(x)[i], stacked)
        for i in range(n_layers)
    ]


# Register families on import.
from areal_tpu.models.hf import llama as _llama  # noqa: E402,F401
from areal_tpu.models.hf import qwen2 as _qwen2  # noqa: E402,F401
from areal_tpu.models.hf import qwen3 as _qwen3  # noqa: E402,F401
from areal_tpu.models.hf import mistral as _mistral  # noqa: E402,F401
from areal_tpu.models.hf import mixtral as _mixtral  # noqa: E402,F401
from areal_tpu.models.hf import gemma as _gemma  # noqa: E402,F401
from areal_tpu.models.hf import gpt2 as _gpt2  # noqa: E402,F401

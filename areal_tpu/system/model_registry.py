"""Model registry: the discovery-plane source of truth for WHICH model
families a fleet serves (ROADMAP item 6, multi-model serving plane).

Every subsystem below the gateway used to assume exactly one model per
fleet. The registry makes "which model" a first-class runtime
dimension: one ``MODEL_REGISTRY_V1`` JSON record per served family
lives in name_resolve under ``names.model_registry(exp, trial,
model_id)``, carrying the model's config hash, family/tokenizer
metadata, and pool policy. Consumers:

- The **gserver manager** builds its per-model pool map from
  ``list_models`` at configure time and re-reads it when an unknown
  ``model_id`` beats: a heartbeat naming a REGISTERED model joins that
  model's pool; one naming an unregistered id is QUARANTINED — never
  adopted — because routing it would risk silent cross-model KV or
  weight hits (`test_model_registry.py` pins this).
- The **gateway** resolves the OpenAI ``"model"`` request field and
  per-tenant entitlements against registered ids (unknown → 404,
  unentitled → 403).
- The **weight plane** stays keyed by model name
  (``names.model_version`` / ``names.weight_plane_source`` already
  are); the registry's ``current_weight_version`` helper reads that
  same pointer so two models publish versions independently.

Records are written with ``delete_on_exit=False``: registration is a
deployment act that must survive the registering process — like the
manager lease, not like a heartbeat. Duplicate registration of a
``model_id`` is REFUSED (``DuplicateModelError``) unless the new
record's config hash matches the existing one (an idempotent re-run of
the same deployment is not a conflict).

Poll-thread / configure-time only: every function here does
name_resolve file I/O (the areal-lint blocking-async contract).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import re
import time
from typing import Any, Dict, List, Optional

from areal_tpu.base import name_resolve, names
from areal_tpu.base.wire_schemas import MODEL_REGISTRY_V1

# model_id becomes a name_resolve path segment, a metrics label, a
# weight-plane namespace, and a gateway wire field — keep it to a
# conservative charset so no consumer needs escaping.
_MODEL_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,63}$")


class DuplicateModelError(Exception):
    """A different record already holds this model_id."""


class UnknownModelError(Exception):
    """No registry record exists for this model_id."""


def config_hash(model_config: Any) -> str:
    """Canonical short hash of a model config (dict / dataclass /
    anything json-able): the registry's identity check for idempotent
    re-registration, and what the bench record pins so two 'families'
    in a parity run are provably different configs."""
    if dataclasses.is_dataclass(model_config) and not isinstance(
        model_config, type
    ):
        model_config = dataclasses.asdict(model_config)
    blob = json.dumps(model_config, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


@dataclasses.dataclass
class ModelRecord:
    """One served model family, as registered.

    ``pool_policy`` is advisory capacity intent for the model-scoped
    autoscaler: ``min_servers`` is the floor a pool must keep even when
    idle; ``max_servers`` (0 = fleet default) caps its growth.
    """

    model_id: str
    family: str                 # engine family, e.g. "tpu_transformer"
    config_hash: str            # config_hash(model config)
    tokenizer: str = ""         # tokenizer family/path metadata
    pool_policy: str = "shared"  # "shared" | "reserved"
    min_servers: int = 1
    max_servers: int = 0
    ts: float = 0.0

    def to_json(self) -> str:
        d = dataclasses.asdict(self)
        d["schema"] = MODEL_REGISTRY_V1
        return json.dumps(d, sort_keys=True, separators=(",", ":"))

    @classmethod
    def from_json(cls, raw: str) -> Optional["ModelRecord"]:
        try:
            d = json.loads(raw)
        except ValueError:
            return None
        if d.get("schema") != MODEL_REGISTRY_V1:
            return None
        try:
            return cls(
                model_id=str(d["model_id"]),
                family=str(d.get("family", "")),
                config_hash=str(d.get("config_hash", "")),
                tokenizer=str(d.get("tokenizer", "")),
                pool_policy=str(d.get("pool_policy", "shared")),
                min_servers=int(d.get("min_servers", 1)),
                max_servers=int(d.get("max_servers", 0)),
                ts=float(d.get("ts", 0.0)),
            )
        except (KeyError, TypeError, ValueError):
            return None


def validate_model_id(model_id: str) -> str:
    if not _MODEL_ID_RE.match(model_id or ""):
        raise ValueError(
            f"invalid model_id {model_id!r}: must match "
            f"{_MODEL_ID_RE.pattern} (it becomes a name_resolve path "
            f"segment and a wire field)"
        )
    return model_id


def register_model(
    experiment_name: str,
    trial_name: str,
    record: ModelRecord,
) -> ModelRecord:
    """Register one model family; refuses a CONFLICTING duplicate.

    Same model_id + same config hash is an idempotent re-run (returns
    the existing record untouched); same model_id with a different
    hash raises ``DuplicateModelError`` — two deployments disagreeing
    about what a model_id means is exactly the confusion the registry
    exists to refuse.
    """
    validate_model_id(record.model_id)
    if record.ts <= 0.0:
        record = dataclasses.replace(record, ts=time.time())
    key = names.model_registry(
        experiment_name, trial_name, record.model_id
    )
    try:
        name_resolve.add(
            key, record.to_json(), delete_on_exit=False, replace=False
        )
        return record
    except name_resolve.NameEntryExistsError:
        existing = get_model(experiment_name, trial_name, record.model_id)
        if existing is not None and existing.config_hash == record.config_hash:
            return existing
        raise DuplicateModelError(
            f"model_id {record.model_id!r} already registered with "
            f"config hash {existing.config_hash if existing else '?'} "
            f"(attempted {record.config_hash}); unregister it first if "
            f"this is an intentional replacement"
        ) from None


def unregister_model(
    experiment_name: str, trial_name: str, model_id: str
) -> None:
    try:
        name_resolve.delete(
            names.model_registry(experiment_name, trial_name, model_id)
        )
    except name_resolve.NameEntryNotFoundError:
        pass


def get_model(
    experiment_name: str, trial_name: str, model_id: str
) -> Optional[ModelRecord]:
    try:
        raw = name_resolve.get(
            names.model_registry(experiment_name, trial_name, model_id)
        )
    except name_resolve.NameEntryNotFoundError:
        return None
    return ModelRecord.from_json(raw)


def list_models(
    experiment_name: str, trial_name: str
) -> Dict[str, ModelRecord]:
    """All registered families, model_id -> record (malformed or
    wrong-schema records are skipped, not fatal — one bad write must
    not unroute every model)."""
    root = names.model_registry_root(experiment_name, trial_name)
    out: Dict[str, ModelRecord] = {}
    try:
        raws: List[str] = name_resolve.get_subtree(root)
    except name_resolve.NameEntryNotFoundError:
        return out
    for raw in raws:
        rec = ModelRecord.from_json(raw)
        if rec is not None and _MODEL_ID_RE.match(rec.model_id):
            out[rec.model_id] = rec
    return out


def current_weight_version(
    experiment_name: str, trial_name: str, model_id: str
) -> Optional[int]:
    """The model's live weight-version pointer — read from the SAME
    ``names.model_version`` key the trainer publishes and the manager
    watches, so the registry never forks the version source of truth."""
    try:
        return int(
            name_resolve.get(
                names.model_version(experiment_name, trial_name, model_id)
            )
        )
    except (name_resolve.NameEntryNotFoundError, ValueError):
        return None

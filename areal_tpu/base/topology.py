"""Process/device topology math.

Counterpart of the reference's topology layer (realhf/base/topology.py) —
re-thought for TPU. In the reference, a (pipe, data, tensor) grid maps one
process per GPU. On TPU under GSPMD there is one process per *host* and a
`jax.sharding.Mesh` spans all devices of a partition, so the heavy rank
bookkeeping collapses into mesh axis math. What remains host-side:

- `ProcessTopology`: generic N-axis coordinate<->rank math, still used for
  placing *worker processes* (hosts) and for parity with reference
  semantics in the control plane.
- `MeshSpec`: named per-model parallelism shape (data/fsdp/tensor axes +
  optional seq for context parallelism) that `areal_tpu.parallel.mesh`
  turns into a real `jax.sharding.Mesh` over a device subset.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Dict, List, Optional, Tuple


class ProcessTopology:
    """Maps between flat ranks and named-axis coordinates (row-major)."""

    def __init__(self, axes: List[str], dims: List[int]):
        if len(axes) != len(dims):
            raise ValueError("axes and dims length mismatch")
        self.axes = list(axes)
        self.dims = list(dims)
        self._strides = []
        s = 1
        for d in reversed(dims):
            self._strides.append(s)
            s *= d
        self._strides.reverse()
        self.world_size = s

    def get_rank(self, **coords) -> int:
        if set(coords) != set(self.axes):
            raise ValueError(f"expected coords for axes {self.axes}, got {list(coords)}")
        rank = 0
        for ax, stride, dim in zip(self.axes, self._strides, self.dims):
            c = coords[ax]
            if not 0 <= c < dim:
                raise ValueError(f"coordinate {ax}={c} out of range [0,{dim})")
            rank += c * stride
        return rank

    def get_coord(self, rank: int) -> Dict[str, int]:
        if not 0 <= rank < self.world_size:
            raise ValueError(f"rank {rank} out of range")
        out = {}
        for ax, stride, dim in zip(self.axes, self._strides, self.dims):
            out[ax] = (rank // stride) % dim
        return out

    def get_dim(self, axis: str) -> int:
        return self.dims[self.axes.index(axis)]

    def filter_match(self, **constraints) -> List[int]:
        """Ranks whose coordinates match every given axis=value constraint."""
        out = []
        for rank in range(self.world_size):
            coord = self.get_coord(rank)
            if all(coord[ax] == v for ax, v in constraints.items()):
                out.append(rank)
        return out

    def get_axis_list(self, axis: str, rank: int) -> List[int]:
        """All ranks sharing this rank's coordinates except along `axis`."""
        coord = self.get_coord(rank)
        coord.pop(axis)
        return self.filter_match(**coord)

    def all_coords(self) -> List[Dict[str, int]]:
        return [self.get_coord(r) for r in range(self.world_size)]

    def __repr__(self):
        body = ",".join(f"{a}={d}" for a, d in zip(self.axes, self.dims))
        return f"ProcessTopology({body})"

    def __eq__(self, other):
        return (
            isinstance(other, ProcessTopology)
            and self.axes == other.axes
            and self.dims == other.dims
        )


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """Named parallelism shape for one model's device mesh.

    TPU equivalent of the reference's (pipe, data, tensor) topology: `data`
    is pure data parallelism, `fsdp` additionally shards params/optimizer
    state (ZeRO), `tensor` is megatron-style tensor parallelism realised as
    GSPMD sharding annotations, and `seq` (optional, >1) enables
    sequence/context parallelism for long-context attention. The product
    must equal the number of devices of the partition the model runs on.
    """

    data: int = 1
    fsdp: int = 1
    tensor: int = 1
    seq: int = 1

    AXIS_NAMES = ("data", "fsdp", "seq", "tensor")

    @property
    def size(self) -> int:
        return self.data * self.fsdp * self.tensor * self.seq

    @property
    def shape(self) -> Dict[str, int]:
        return {
            "data": self.data,
            "fsdp": self.fsdp,
            "seq": self.seq,
            "tensor": self.tensor,
        }

    @property
    def dp_size(self) -> int:
        """Global data-parallel degree (data x fsdp): batch is split this many ways."""
        return self.data * self.fsdp

    def __str__(self):
        return f"d{self.data}f{self.fsdp}s{self.seq}t{self.tensor}"

    @classmethod
    def parse(cls, s: str) -> "MeshSpec":
        """Parse 'd2f2s1t2'-style strings (missing axes default to 1)."""
        import re

        if not re.fullmatch(r"([a-z]\d+)+", s):
            raise ValueError(
                f"malformed mesh spec {s!r}: expected axis-letter/size pairs "
                "like 'd2t4' (axes: d=data, f=fsdp, s=seq, t/m=tensor)"
            )
        vals = dict(data=1, fsdp=1, seq=1, tensor=1)
        key_map = {"d": "data", "f": "fsdp", "s": "seq", "t": "tensor", "m": "tensor", "p": "pipe"}
        seen = set()
        for m in re.finditer(r"([a-z])(\d+)", s):
            k, v = m.group(1), int(m.group(2))
            if k in seen:
                raise ValueError(f"duplicate axis {k!r} in mesh spec {s!r}")
            seen.add(k)
            name = key_map.get(k)
            if name == "pipe":
                if v != 1:
                    raise ValueError(
                        "pipeline parallelism is expressed as extra data/fsdp axes on TPU; "
                        f"got p{v} in {s!r}"
                    )
                continue
            if name is None:
                raise ValueError(f"unknown axis {k!r} in mesh spec {s!r}")
            vals[name] = v
        return cls(**vals)


def device_grid_iter(dims: List[int]):
    """Iterate coordinates of an N-D grid row-major."""
    yield from itertools.product(*[range(d) for d in dims])

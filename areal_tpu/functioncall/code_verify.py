"""Local code-correctness verification: run candidate code against IO tests.

Counterpart of the reference's local code verifier
(functioncall/code/local_verify.py, testing_util.py), from scratch:
candidate programs are executed in a subprocess with resource limits and
judged on stdin/stdout test cases. Remote verifier services can be plugged
behind the same `code_verify` signature later.
"""

from __future__ import annotations

import json
import subprocess
import sys
import tempfile
from typing import Dict, List, Optional

DEFAULT_TIMEOUT = 8.0


def extract_code_block(text: str) -> Optional[str]:
    """Last fenced code block (``` or ```python), else None."""
    import re

    blocks = re.findall(r"```(?:python|py)?\n(.*?)```", text, re.DOTALL)
    return blocks[-1] if blocks else None


def run_one_case(code: str, stdin_data: str, timeout: float = DEFAULT_TIMEOUT):
    """Execute code with stdin; returns (ok, stdout, err)."""
    preamble = (
        "import resource, sys\n"
        "resource.setrlimit(resource.RLIMIT_AS, (2 << 30, 2 << 30))\n"
        "sys.setrecursionlimit(100000)\n"
    )
    with tempfile.NamedTemporaryFile("w", suffix=".py", delete=False) as f:
        f.write(preamble + code)
        path = f.name
    try:
        proc = subprocess.run(
            [sys.executable, path],
            input=stdin_data,
            capture_output=True,
            text=True,
            timeout=timeout,
        )
        return proc.returncode == 0, proc.stdout, proc.stderr
    except subprocess.TimeoutExpired:
        return False, "", "timeout"
    finally:
        import os

        os.unlink(path)


def _normalize_output(s: str) -> List[str]:
    return [line.rstrip() for line in s.rstrip().splitlines()]


def normalize_test_cases(obj) -> List[Dict[str, str]]:
    """Accept either the dataset wire format {"inputs": [...], "outputs":
    [...]} (reference math_code_dataset rows) or an explicit list of
    {input, output} dicts."""
    if isinstance(obj, dict) and "inputs" in obj:
        return [
            {"input": i, "output": o}
            for i, o in zip(obj["inputs"], obj["outputs"])
        ]
    return list(obj)


def code_verify(
    solution_text: str,
    test_cases,
    timeout: float = DEFAULT_TIMEOUT,
) -> bool:
    """True if the extracted program passes every {input, output} case.
    `test_cases` may be either supported format (see normalize_test_cases)."""
    test_cases = normalize_test_cases(test_cases)
    code = extract_code_block(solution_text)
    if code is None:
        return False
    for case in test_cases:
        ok, out, _ = run_one_case(code, case.get("input", ""), timeout)
        if not ok:
            return False
        if _normalize_output(out) != _normalize_output(case.get("output", "")):
            return False
    return True

"""weight_transfer: raw dump/mmap-load round trip, versioned GC, torn-write
rejection, the serving load-path priority (shm raw -> disk raw ->
pickle -> HF), the GC-race retry, and the want_version accounting gate
(ISSUE 5 satellites)."""

import os
import pickle
import threading
import time

import numpy as np
import pytest

from areal_tpu.system.weight_transfer import (
    WeightVersionMismatch,
    dump_raw_params,
    load_for_serving,
    load_raw_params,
    shm_transfer_dir,
)


def _params(seed=0):
    import ml_dtypes

    rng = np.random.default_rng(seed)
    return {
        "embedding": {"weight": rng.standard_normal((16, 8)).astype(np.float32)},
        "layers": {
            # bfloat16 leaf: the flagship dumps bf16 params, and the
            # manifest must round-trip ml_dtypes names.
            "attn": {"wq": rng.standard_normal((2, 8, 8)).astype(ml_dtypes.bfloat16)},
            "ln": {"scale": np.ones((2, 8), np.float32)},
        },
    }


def test_bf16_dtype_roundtrip(tmp_path):
    import ml_dtypes

    d = str(tmp_path / "dump")
    dump_raw_params(_params(0), d, version=1)
    got, _ = load_raw_params(d)
    assert got["layers"]["attn"]["wq"].dtype == ml_dtypes.bfloat16


def _assert_tree_equal(a, b):
    assert sorted(a.keys()) == sorted(b.keys())
    for k in a:
        if isinstance(a[k], dict):
            _assert_tree_equal(a[k], b[k])
        else:
            np.testing.assert_array_equal(np.asarray(a[k]), np.asarray(b[k]))


def test_roundtrip_and_versions(tmp_path):
    d = str(tmp_path / "dump")
    p1 = _params(1)
    dt = dump_raw_params(p1, d, version=1)
    assert dt >= 0
    got, v = load_raw_params(d)
    assert v == 1
    _assert_tree_equal(p1, got)

    p2 = _params(2)
    dump_raw_params(p2, d, version=2)
    got2, v2 = load_raw_params(d)
    assert v2 == 2
    _assert_tree_equal(p2, got2)

    # GC keeps the newest 2 bins.
    for ver in (3, 4, 5):
        dump_raw_params(_params(ver), d, version=ver)
    bins = [b for b in os.listdir(d) if b.endswith(".bin")]
    assert sorted(bins) == ["params-v4.bin", "params-v5.bin"]


def test_torn_write_rejected(tmp_path):
    d = str(tmp_path / "dump")
    dump_raw_params(_params(0), d, version=1)
    # Truncate the bin: manifest's total_bytes no longer matches.
    bin_path = os.path.join(d, "params-v1.bin")
    with open(bin_path, "r+b") as f:
        f.truncate(os.path.getsize(bin_path) - 8)
    assert load_raw_params(d) is None


def test_rejects_non_dict_trees(tmp_path):
    with pytest.raises(TypeError, match="dict-of-array"):
        dump_raw_params({"a": [np.zeros(2)]}, str(tmp_path), version=1)


def test_load_for_serving_priority(tmp_path):
    model_path = str(tmp_path / "realloc")
    shm = str(tmp_path / "shm")
    os.makedirs(model_path)

    # Only pickle present -> pickle source.
    p_pkl = _params(10)
    with open(os.path.join(model_path, "engine_state.pkl"), "wb") as f:
        pickle.dump({"params": p_pkl}, f)
    params, info = load_for_serving(model_path, shm_dir=shm)
    assert info["source"] == "pickle"
    _assert_tree_equal(p_pkl, params)

    # Disk raw beats pickle.
    p_disk = _params(11)
    dump_raw_params(p_disk, model_path, version=7)
    params, info = load_for_serving(model_path, shm_dir=shm)
    assert info["source"] == "disk_raw" and info["version"] == 7
    _assert_tree_equal(p_disk, params)

    # shm raw beats disk raw.
    p_shm = _params(12)
    dump_raw_params(p_shm, shm, version=8)
    params, info = load_for_serving(model_path, shm_dir=shm)
    assert info["source"] == "shm_raw" and info["version"] == 8
    _assert_tree_equal(p_shm, params)
    assert info["load_s"] >= 0


def test_gc_race_retries_refreshed_manifest(tmp_path, monkeypatch):
    """A reader that grabbed a manifest naming a just-GC'd bin must
    re-read the (refreshed) manifest once and succeed — not silently
    fall through to a stale pickle."""
    import areal_tpu.system.weight_transfer as wt

    d = str(tmp_path / "dump")
    p = _params(1)
    dump_raw_params(p, d, version=5)
    real_read = wt._read_manifest
    real_man = real_read(d)
    # The racy first read: a manifest whose bin the GC already unlinked.
    stale_man = dict(real_man, bin="params-v3.bin", version=3)
    calls = []

    def racy_read(dump_dir):
        calls.append(dump_dir)
        return stale_man if len(calls) == 1 else real_read(dump_dir)

    monkeypatch.setattr(wt, "_read_manifest", racy_read)
    got, v = load_raw_params(d)
    assert v == 5 and len(calls) == 2
    _assert_tree_equal(p, got)


def test_gc_race_gives_up_after_one_retry(tmp_path, monkeypatch):
    """If the refreshed manifest STILL names a missing bin (dump dir
    being torn down), the loader returns None for the caller's fallback
    chain instead of spinning."""
    import areal_tpu.system.weight_transfer as wt

    d = str(tmp_path / "dump")
    dump_raw_params(_params(1), d, version=5)
    stale_man = dict(wt._read_manifest(d), bin="params-v3.bin")
    calls = []

    def always_stale(dump_dir):
        calls.append(dump_dir)
        return dict(stale_man)

    monkeypatch.setattr(wt, "_read_manifest", always_stale)
    assert load_raw_params(d) is None
    assert len(calls) == 2


def test_want_version_accepts_exact_match(tmp_path):
    model_path = str(tmp_path / "realloc")
    dump_raw_params(_params(0), model_path, version=7)
    params, info = load_for_serving(model_path, want_version=7)
    assert info["source"] == "disk_raw" and info["version"] == 7


def test_want_version_mismatch_fails_update(tmp_path):
    """The accounting hole: a raw dump lagging the published version (or
    a version:-1 pickle fallback) must FAIL the update, not serve stale
    weights under the new version label."""
    model_path = str(tmp_path / "realloc")
    dump_raw_params(_params(0), model_path, version=7)
    with pytest.raises(WeightVersionMismatch, match="requested weight version 8"):
        load_for_serving(model_path, want_version=8, retries=2, retry_s=0.01)

    # Pickle-only dir: version is unverifiable (-1) — the pinned chain
    # skips the deserialization entirely and reports no raw dump.
    pkl_dir = str(tmp_path / "pkl")
    os.makedirs(pkl_dir)
    with open(os.path.join(pkl_dir, "engine_state.pkl"), "wb") as f:
        pickle.dump({"params": _params(1)}, f)
    with pytest.raises(WeightVersionMismatch, match="no raw dump"):
        load_for_serving(pkl_dir, want_version=1, retries=1)
    # Unpinned loads keep the legacy behavior.
    _, info = load_for_serving(pkl_dir)
    assert info["source"] == "pickle" and info["version"] == -1


def test_want_version_retries_until_dump_lands(tmp_path):
    """Version publication can race the dump hitting disk: the brief
    retry window must pick up the landing dump."""
    model_path = str(tmp_path / "realloc")
    p_old, p_new = _params(2), _params(3)
    dump_raw_params(p_old, model_path, version=1)

    def late_dump():
        time.sleep(0.2)
        dump_raw_params(p_new, model_path, version=2)

    t = threading.Thread(target=late_dump)
    t.start()
    try:
        params, info = load_for_serving(
            model_path, want_version=2, retries=20, retry_s=0.05
        )
    finally:
        t.join()
    assert info["version"] == 2
    _assert_tree_equal(p_new, params)


def test_load_for_serving_hf_fallback(tmp_path):
    """The cold-start end of the fallback chain: an HF checkpoint dir
    with no raw dump and no pickle loads with source='hf', version -1 —
    and is refused when a specific version was requested."""
    pytest.importorskip("torch")
    pytest.importorskip("transformers")
    from areal_tpu.models.hf import get_family, save_hf_model, torch_state_dict_to_numpy
    from tests.model.test_hf_parity import tiny_hf_model

    hf_model = tiny_hf_model("llama").eval()
    fam = get_family("llama")
    cfg = fam.config_from_hf(hf_model.config.to_dict(), False)
    params = fam.params_from_hf(
        torch_state_dict_to_numpy(hf_model.state_dict()), cfg
    )
    d = str(tmp_path / "hf_ckpt")
    save_hf_model(d, cfg, params, family="llama")

    got, info = load_for_serving(d)
    assert info["source"] == "hf" and info["version"] == -1
    assert got["embedding"]["weight"].shape[0] == cfg.vocab_size
    with pytest.raises(WeightVersionMismatch, match="no raw dump"):
        load_for_serving(d, want_version=3, retries=1)


def test_shm_dir_shape():
    d = shm_transfer_dir("exp", "trial", "actor")
    if d is not None:  # machines without /dev/shm skip the path check
        assert d.endswith("areal_tpu/exp/trial/actor")

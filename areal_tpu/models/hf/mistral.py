"""Mistral HF conversion: llama layout, silu, GQA.
Reference parity: realhf/api/from_hf/mistral.py.

Sliding-window attention is intentionally NOT replicated: the TPU build
always attends over the full (packed) context — a superset of the
sliding window, matching how the reference treats mistral weights in its
own flash-attn path for training.
"""

from __future__ import annotations

from typing import Any, Dict

from areal_tpu.api.model_api import register_hf_family
from areal_tpu.models.config import TransformerConfig
from areal_tpu.models.hf import HFFamily
from areal_tpu.models.hf.llama import (
    _config_from_hf as llama_config_from_hf,
    _config_to_hf as llama_config_to_hf,
    params_from_hf_llama_style,
    params_to_hf_llama_style,
)


def _config_from_hf(hf: Dict[str, Any], is_critic: bool = False) -> TransformerConfig:
    return llama_config_from_hf(hf, is_critic)


def _config_to_hf(cfg: TransformerConfig) -> Dict[str, Any]:
    hf = llama_config_to_hf(cfg)
    hf["architectures"] = ["MistralForCausalLM"]
    hf["model_type"] = "mistral"
    hf.pop("attention_bias", None)
    return hf


register_hf_family(
    "mistral",
    HFFamily(
        name="mistral",
        hf_model_type="mistral",
        config_from_hf=_config_from_hf,
        config_to_hf=_config_to_hf,
        params_from_hf=lambda sd, cfg: params_from_hf_llama_style(sd, cfg),
        params_to_hf=lambda p, cfg: params_to_hf_llama_style(p, cfg),
    ),
)

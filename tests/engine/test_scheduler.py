"""Production-scheduler admission on the ServingEngine: token-budget
continuous batching, priority-aware admission (continuations ahead of
fresh requests), the prefill/decode interleave knob, and the TTFT/ITL
latency histograms the server/manager SLO surfaces read.

Budget/priority tests drive `_admit()` directly on an UNSTARTED engine:
admission runs on the caller thread, so what a scheduling round admits
is observable deterministically instead of racing the serve loop."""

import pytest

from areal_tpu.engine.serving import GenRequest, ServingEngine
from tests.engine.serving_utils import (
    TINY_EOS as EOS,
    TINY_SERVING_CFG as CFG,
    run_requests as _run,
)


def _engine(params, **kw):
    kw.setdefault("max_batch_size", 4)
    kw.setdefault("max_seq_len", 128)
    kw.setdefault("decode_block_steps", 4)
    kw.setdefault("prompt_bucket", 8)
    return ServingEngine(CFG, params, **kw)


def test_token_budget_caps_admissions_per_round(params):
    eng = _engine(params, prefill_token_budget=10)
    reqs = [
        GenRequest(qid=f"q{i}", input_ids=[3] * 8, max_new_tokens=4,
                   greedy=True)
        for i in range(3)
    ]
    for r in reqs:
        eng.submit(r)
    assert eng.queued_prompt_tokens == 24
    # 8 <= 10 admits the first; a second 8 would exceed the remaining 2.
    eng._admit()
    assert sum(r is not None for r in eng._slot_req) == 1
    assert eng.queued_prompt_tokens == 16
    eng._admit()
    assert sum(r is not None for r in eng._slot_req) == 2
    eng._admit()
    assert sum(r is not None for r in eng._slot_req) == 3
    assert eng.queued_prompt_tokens == 0


def test_token_budget_oversized_prompt_still_admits(params):
    """The first candidate of a round always admits: one prompt bigger
    than the whole budget must not starve forever."""
    eng = _engine(params, prefill_token_budget=4)
    eng.submit(GenRequest(qid="big", input_ids=[3] * 16, max_new_tokens=4,
                          greedy=True))
    eng._admit()
    assert eng._slot_req.count(None) == eng.B - 1


def test_priority_admits_continuations_before_fresh(params):
    """Class-0 requests (interrupted re-prefills / session
    continuations) jump the FIFO; class-1 order is preserved."""
    eng = _engine(params, prefill_token_budget=8)  # one admission/round
    eng.submit(GenRequest(qid="fresh1", input_ids=[3] * 8, priority=1))
    eng.submit(GenRequest(qid="fresh2", input_ids=[4] * 8, priority=1))
    eng.submit(GenRequest(qid="cont", input_ids=[5] * 8, priority=0))
    eng._admit()
    admitted = [r.qid for r in eng._slot_req if r is not None]
    assert admitted == ["cont"]
    eng._admit()
    admitted = [r.qid for r in eng._slot_req if r is not None]
    assert set(admitted) == {"cont", "fresh1"}


def test_starved_fresh_request_ages_into_class0(params):
    """A sustained continuation stream (more live sessions than slots
    keep the backlog stocked with class-0 work) must not starve fresh
    requests forever: after STARVATION_ROUNDS passed-over admission
    rounds a class-1 request is promoted to class 0 and, being older,
    admits ahead of the next continuation (stable FIFO within class)."""
    # Slots outnumber the rounds needed so every round has admission
    # capacity; budget 8 admits exactly one 8-token prompt per round.
    eng = _engine(params, max_batch_size=24, prefill_token_budget=8)
    eng.submit(GenRequest(qid="fresh", input_ids=[3] * 8, priority=1,
                          max_new_tokens=4))
    rounds = 0
    while True:
        # Each round a new continuation arrives and (until the aging
        # bound) jumps the queue.
        eng.submit(GenRequest(qid=f"cont{rounds}", input_ids=[5] * 8,
                              priority=0, max_new_tokens=4))
        eng._admit()
        rounds += 1
        if any(r is not None and r.qid == "fresh" for r in eng._slot_req):
            break
        assert rounds <= eng.STARVATION_ROUNDS + 1, "fresh never promoted"
    assert rounds == eng.STARVATION_ROUNDS + 1


def test_rejected_overlong_prompt_releases_queued_tokens(params):
    """A prompt at/over max_seq_len finishes from the backlog without a
    slot; its tokens must leave the admission-watermark counter."""
    eng = _engine(params)
    got = []
    eng.submit(GenRequest(
        qid="huge", input_ids=[3] * 200, max_new_tokens=4,
        done_cb=got.append,
    ))
    assert eng.queued_prompt_tokens == 200
    eng._admit()
    assert eng.queued_prompt_tokens == 0
    assert len(got) == 1 and got[0].output_ids == [] and got[0].no_eos


def test_latency_histograms_and_snapshot_reset(params):
    eng = _engine(params, eos_token_id=None)
    eng.start()
    try:
        reqs = [
            GenRequest(qid=f"h{i}", input_ids=[7, 8, 9], max_new_tokens=8,
                       greedy=True)
            for i in range(3)
        ]
        _run(eng, reqs)
        m = eng.metrics()
        assert m["ttft_count"] == 3.0
        assert m["itl_count"] >= 3.0  # block-emitted tokens past the first
        assert 0.0 < m["ttft_p50_ms"] <= m["ttft_p99_ms"]
        assert 0.0 < m["itl_p50_ms"] <= m["itl_p99_ms"]
        snap = eng.latency_snapshot(reset=True)
        assert sum(snap["ttft_counts"]) == 3
        assert snap["ttft_p99_ms"] == m["ttft_p99_ms"]
        after = eng.latency_snapshot()
        assert sum(after["ttft_counts"]) == 0 and sum(after["itl_counts"]) == 0
    finally:
        eng.stop()


def test_interleave_knob_preserves_results(params):
    """decode_blocks_per_admit > 1 (decode-favoring interleave) changes
    scheduling only: every request still completes with its budget, and
    greedy output matches the admit-every-block engine."""
    outs = {}
    for ratio in (1, 3):
        eng = _engine(
            params, eos_token_id=EOS, decode_blocks_per_admit=ratio,
            prefill_token_budget=16,
        )
        eng.start()
        try:
            reqs = [
                GenRequest(qid=f"r{i}", input_ids=[9 + i, 11, 13],
                           max_new_tokens=12, greedy=True)
                for i in range(6)  # > B: forces multi-round admission
            ]
            res = _run(eng, reqs)
            outs[ratio] = {q: r.output_ids for q, r in res.items()}
            for r in res.values():
                assert 1 <= len(r.output_ids) <= 12
        finally:
            eng.stop()
    assert outs[1] == outs[3]

"""Math grader tests (mirrors reference tests/reward/test_math_reward.py)."""

import pytest

from areal_tpu.functioncall.math_grader import (
    answers_equal,
    extract_answer,
    extract_boxed,
    grade_answer,
    normalize_answer,
)


def test_extract_boxed_nested():
    assert extract_boxed(r"so \boxed{\frac{1}{2}} is it") == r"\frac{1}{2}"
    assert extract_boxed(r"a \boxed{1} then \boxed{2}") == "2"
    assert extract_boxed("no box") is None


def test_extract_answer_fallbacks():
    assert extract_answer("The answer is 42.") == "42"
    assert extract_answer("blah 3 then 7 end") == "7"
    assert extract_answer("") is None


@pytest.mark.parametrize(
    "a,b",
    [
        ("42", "42"),
        (r"\frac{1}{2}", "0.5"),
        (r"\frac{1}{2}", "1/2"),
        ("1,234", "1234"),
        (r"2\pi", "2pi"),
        (r"\sqrt{2}", "sqrt(2)"),
        ("0.50", "1/2"),
        (r"\text{east}", "east"),
        ("(1, 2)", "(1,2)"),
        ("-1/3", r"-\frac{1}{3}"),
    ],
)
def test_answers_equal(a, b):
    assert answers_equal(a, b)


@pytest.mark.parametrize("a,b", [("42", "43"), ("1/2", "1/3"), ("x+1", "x+2")])
def test_answers_not_equal(a, b):
    assert not answers_equal(a, b)


def test_sympy_equivalence():
    assert answers_equal("2*(x+1)", "2x+2")
    assert answers_equal(r"\frac{x^2-1}{x-1}", "x+1")


def test_grade_answer_end_to_end():
    sol = r"We compute ... therefore the result is $\boxed{\dfrac{3}{4}}$."
    assert grade_answer(sol, "0.75")
    assert grade_answer(sol, "3/4")
    assert not grade_answer(sol, "0.8")
    assert not grade_answer("no final answer here", "5") or True  # must not crash


def test_grade_multiple_refs():
    assert grade_answer(r"\boxed{2}", ["1", "2"])


# ---------------------------------------------------------------------------
# Hardened grader vectors (behavior parity with the reference's
# functioncall/math/function/grader.py math_equal)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize(
    "a,b",
    [
        # percentages
        ("50%", "0.5"),
        ("0.5", "50%"),
        ("12.5%", "1/8"),
        ("150", "1.5"),  # x*100 == y form
        # intervals
        ("[2,5)", "[2, 5)"),
        (r"[1,\infty)", r"[1, \infty)"),
        (r"(-\infty,3]\cup(7,9)", r"(-\infty, 3] \cup (7, 9)"),
        ("[0.5,1)", r"[\frac{1}{2}, 1)"),
        # matrices
        (
            r"\begin{pmatrix}1&2\\3&4\end{pmatrix}",
            r"\begin{pmatrix} 1 & 2 \\ 3 & 4 \end{pmatrix}",
        ),
        (
            r"\begin{bmatrix}1/2&0\\0&1\end{bmatrix}",
            r"\begin{pmatrix}0.5&0\\0&1\end{pmatrix}",
        ),
        # equations
        ("x=5", "5"),
        ("y = 2x + 3", "2x+3"),
        # plus-minus
        (r"2\pm\sqrt{3}", r"2 \pm \sqrt{3}"),
        # choices
        ("(C)", "C"),
        ("b.", "B"),
    ],
)
def test_answers_equal_hardened(a, b):
    assert answers_equal(a, b)


@pytest.mark.parametrize(
    "a,b",
    [
        ("[2,5)", "(2,5)"),      # bracket kind differs
        ("[2,5)", "[2,6)"),      # endpoint differs
        (r"\begin{pmatrix}1&2\end{pmatrix}",
         r"\begin{pmatrix}1&3\end{pmatrix}"),
        ("x=5", "6"),
        ("(A)", "B"),
        ("50%", "0.6"),
    ],
)
def test_answers_not_equal_hardened(a, b):
    assert not answers_equal(a, b)


def test_sympy_timeout_on_adversarial_input():
    """A pathological expression must return (False) within the timeout
    budget, not hang the reward pipeline."""
    import time

    t0 = time.monotonic()
    # deeply nested powers: sympy.simplify may take extremely long
    bad = "(x+1)**(x**(x**(x**9)))" + "+1" * 120
    result = answers_equal(bad, "q+z")
    assert result is False
    assert time.monotonic() - t0 < 30.0


def test_pm_expansion_matches_pair():
    assert answers_equal(r"1\pm2", "(3,-1)")
    assert not answers_equal(r"1\pm2", "(3,0)")


@pytest.mark.parametrize(
    "a,b",
    [
        (r"50\%", "50"),        # latex percent vs plain
        (r"50\%", "0.5"),
        ("(1,2)", "1,2"),       # tuple vs bare pair
        (r"\begin{pmatrix}1\\2\end{pmatrix}", "(1,2)"),  # vector vs tuple
    ],
)
def test_review_regressions_equal(a, b):
    assert answers_equal(a, b)


def test_grade_numeric_reference():
    assert grade_answer(r"\boxed{42}", 42)
    assert not grade_answer(r"\boxed{41}", 42)


def test_code_verify_stops_on_first_failure():
    from areal_tpu.functioncall.code_verify import run_test_cases

    sol = "```python\nn=int(input())\nprint(n)\n```"
    cases = {"inputs": ["1\n", "2\n", "3\n"], "outputs": ["9\n", "2\n", "3\n"]}
    res = run_test_cases(sol, cases, stop_on_first_failure=True)
    assert res == [False, False, False]


class _StubPool:
    """Minimal ExecutorPoolClient stand-in for routing tests."""

    def __init__(self, live=True, results=None):
        self.live = live
        self.results = results
        self.calls = []

    def available(self):
        return self.live

    def submit(self, jobs, timeout_s=None):
        self.calls.append(jobs)
        if self.results is not None:
            return self.results
        return [
            {"ok": True, "equal": j["a"].strip() == j["b"].strip()}
            for j in jobs
        ]


@pytest.fixture
def _pool_registry():
    from areal_tpu.functioncall import remote

    yield remote
    remote.register_executor_pool(None)


def test_sympy_routes_through_registered_pool(_pool_registry):
    """ISSUE 18: with a live executor pool registered, sympy
    equivalence rides the warm pool instead of forking a sandbox."""
    from areal_tpu.functioncall.math_grader import _sympy_equal

    pool = _StubPool()
    _pool_registry.register_executor_pool(pool)
    assert _sympy_equal("x", "x")
    assert pool.calls and pool.calls[0][0]["kind"] == "sympy_equal"


def test_sympy_local_fallback_when_no_pool(_pool_registry):
    """The pinned degradation path: no pool registered (or none live)
    -> the local fork-per-call sandbox still grades correctly."""
    from areal_tpu.functioncall.math_grader import _sympy_equal

    _pool_registry.register_executor_pool(None)
    assert _sympy_equal("x + x", "2*x")
    dead = _StubPool(live=False)
    _pool_registry.register_executor_pool(dead)
    assert _sympy_equal("x + x", "2*x")
    assert dead.calls == []  # an unavailable pool is never submitted to


def test_sympy_pool_error_degrades_to_local(_pool_registry):
    """A pooled job that errors must degrade to slower local grading,
    never to a wrong grade."""
    from areal_tpu.functioncall.math_grader import _sympy_equal

    broken = _StubPool(results=[{"ok": False, "error": "worker died"}])
    _pool_registry.register_executor_pool(broken)
    assert _sympy_equal("x + x", "2*x")
    assert broken.calls  # the pool WAS tried first

"""N-gram speculative decoding (engine/spec_decode.py): draft proposal,
vectorized verification vs a scalar reference, and the lossless-ness
guarantee — a spec-decoding engine's GREEDY output is bit-identical to
the plain engine's (the reference's serving stack has no speculative
decoding; this is a TPU-side extension)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from areal_tpu.engine.serving import GenRequest, ServingEngine
from areal_tpu.engine.spec_decode import propose_ngram_drafts, spec_verify
from tests.engine.serving_utils import (
    TINY_EOS as EOS,
    TINY_SERVING_CFG as CFG,
    run_requests as _run,
)


# ----------------------------------------------------------------------
# propose_ngram_drafts
# ----------------------------------------------------------------------


def _hist(tokens, width):
    h = np.zeros((1, width + 1), np.int32)
    h[0, : len(tokens)] = tokens
    return jnp.asarray(h)


def test_propose_simple_repeat():
    # history: 1 2 3 9 1 2 |pending=3|  -> window (2, 3) matched at
    # positions (1, 2); continuation 9 1 2 ... from position 3.
    toks = [1, 2, 3, 9, 1, 2, 3]
    draft, eff = propose_ngram_drafts(
        _hist(toks, 16), jnp.asarray([6], jnp.int32), ngram=2, draft_len=4
    )
    assert int(eff[0]) == 4
    assert draft[0, :4].tolist() == [9, 1, 2, 3]


def test_propose_no_match():
    draft, eff = propose_ngram_drafts(
        _hist([1, 2, 3, 4], 16), jnp.asarray([3], jnp.int32),
        ngram=2, draft_len=4,
    )
    assert int(eff[0]) == 0


def test_propose_most_recent_occurrence_wins():
    # (7, 8) occurs at 0 and 4; continuation after the later one is 2.
    toks = [7, 8, 1, 9, 7, 8, 2, 9, 7, 8]
    draft, eff = propose_ngram_drafts(
        _hist(toks, 16), jnp.asarray([9], jnp.int32), ngram=2, draft_len=3
    )
    assert int(eff[0]) >= 1
    assert int(draft[0, 0]) == 2


def test_propose_short_history():
    draft, eff = propose_ngram_drafts(
        _hist([3], 16), jnp.asarray([0], jnp.int32), ngram=2, draft_len=4
    )
    assert int(eff[0]) == 0


def test_propose_continuation_capped_at_known():
    # window matches right before the end: continuation shorter than d.
    toks = [4, 4, 4]  # window (4,4) at pending=2 matches s=0; cont = [4]
    draft, eff = propose_ngram_drafts(
        _hist(toks, 16), jnp.asarray([2], jnp.int32), ngram=2, draft_len=4
    )
    assert int(eff[0]) == 1
    assert int(draft[0, 0]) == 4


def test_propose_windowed_matches_full_scan_for_recent_match():
    """A match inside the backward window proposes the same draft as the
    unbounded scan (the window only bounds how far back we look)."""
    toks = [1, 2, 3, 9, 1, 2, 3]
    full = propose_ngram_drafts(
        _hist(toks, 64), jnp.asarray([6], jnp.int32), ngram=2, draft_len=4
    )
    win = propose_ngram_drafts(
        _hist(toks, 64), jnp.asarray([6], jnp.int32), ngram=2, draft_len=4,
        window=8,
    )
    assert int(win[1][0]) == int(full[1][0]) == 4
    np.testing.assert_array_equal(np.asarray(win[0]), np.asarray(full[0]))


def test_propose_window_drops_stale_match():
    """A match older than the window is not proposed (eff=0) while the
    unbounded scan still finds it — the cost/recall tradeoff the window
    knob buys at long contexts."""
    # (5, 6) occurs only at position 0; pending n-gram is (5, 6).
    toks = [5, 6] + [10 + i for i in range(20)] + [5, 6]
    pend = len(toks) - 1  # pending token = the trailing 6
    full = propose_ngram_drafts(
        _hist(toks, 64), jnp.asarray([pend], jnp.int32), ngram=2, draft_len=3
    )
    assert int(full[1][0]) >= 1  # unbounded scan finds the old match
    win = propose_ngram_drafts(
        _hist(toks, 64), jnp.asarray([pend], jnp.int32), ngram=2, draft_len=3,
        window=4,
    )
    assert int(win[1][0]) == 0  # match is ~20 tokens back, window is 4


def test_propose_window_most_recent_still_wins():
    toks = [7, 8, 1, 9, 7, 8, 2, 9, 7, 8]
    draft, eff = propose_ngram_drafts(
        _hist(toks, 32), jnp.asarray([9], jnp.int32), ngram=2, draft_len=3,
        window=8,
    )
    assert int(eff[0]) >= 1
    assert int(draft[0, 0]) == 2


def test_spec_windowed_greedy_bit_identical_to_plain(params):
    """Losslessness holds with a bounded window (the window changes WHAT
    gets drafted, never what gets emitted): greedy output with a window
    smaller than max_seq_len is still bit-identical to plain decode."""
    eng_plain = _engine(params)
    eng_plain.start()
    try:
        plain = _run(eng_plain, _greedy_reqs())
    finally:
        eng_plain.stop()
    # window=16 < S=128 exercises the windowed gather branch.
    eng_spec = _engine(params, speculative_draft_len=3,
                       speculative_window=16)
    assert eng_spec.spec_window == 16
    eng_spec.start()
    try:
        spec = _run(eng_spec, _greedy_reqs())
    finally:
        eng_spec.stop()
    for qid in plain:
        assert spec[qid].output_ids == plain[qid].output_ids, qid


# ----------------------------------------------------------------------
# spec_verify vs a scalar reference
# ----------------------------------------------------------------------


def _scalar_verify(probs, draft, eff, greedy, u, final_sample_fn):
    """Reference implementation of the published point-mass speculative
    sampling, one slot."""
    a = 0
    for j in range(eff):
        t = draft[j]
        if greedy:
            ok = int(np.argmax(probs[j])) == t
        else:
            ok = u[j] < probs[j, t]
        if not ok:
            break
        a += 1
    p_final = probs[a].copy()
    if a < eff:  # rejected: remove the draft token, renormalize
        p_final[draft[a]] = 0.0
        p_final = p_final / p_final.sum()
    if greedy:
        final = int(np.argmax(p_final))
    else:
        final = final_sample_fn(p_final)
    return a, final


@pytest.mark.parametrize("greedy", [True, False])
def test_verify_matches_scalar_reference(greedy):
    rng = np.random.RandomState(0)
    B, d, V = 4, 3, 11
    logits = jnp.asarray(rng.randn(B, d + 1, V).astype(np.float32) * 2)
    draft = jnp.asarray(rng.randint(0, V, size=(B, d)), jnp.int32)
    eff = jnp.asarray([3, 1, 0, 2], jnp.int32)
    key = jax.random.PRNGKey(42)
    temps = jnp.ones((B,), jnp.float32)
    ones = jnp.ones((B,), jnp.float32)
    negs = jnp.full((B,), -1, jnp.int32)
    gm = jnp.full((B,), greedy)
    forbid = jnp.zeros((B,), bool)
    eos_mask = jnp.zeros((V,), bool)

    emitted, n_emit, logprobs = spec_verify(
        logits, draft, eff, key, temps, ones, negs, gm, forbid, eos_mask,
    )
    emitted, n_emit = np.asarray(emitted), np.asarray(n_emit)

    # Recover the exact uniforms/categoricals spec_verify drew so the
    # scalar reference is deterministic against it.
    rng_u, rng_cat = jax.random.split(key)
    u = np.asarray(jax.random.uniform(rng_u, (B, d)))
    probs = np.asarray(jax.nn.softmax(logits, axis=-1))

    for b in range(B):
        a_ref, _ = _scalar_verify(
            probs[b], np.asarray(draft)[b], int(eff[b]), greedy, u[b],
            lambda p: None,
        )
        assert n_emit[b] == a_ref + 1, (b, n_emit[b], a_ref)
        np.testing.assert_array_equal(
            emitted[b, :a_ref], np.asarray(draft)[b, :a_ref]
        )
        if greedy:
            p_final = probs[b, a_ref].copy()
            if a_ref < int(eff[b]):
                p_final[int(np.asarray(draft)[b, a_ref])] = 0.0
            assert emitted[b, a_ref] == int(np.argmax(p_final))
        # logprobs are under the base distribution
        for j in range(int(n_emit[b])):
            want = np.log(probs[b, j, emitted[b, j]])
            np.testing.assert_allclose(logprobs[b, j], want, rtol=1e-4)


def test_verify_eff_zero_reduces_to_plain_sample():
    """eff=0 greedy must emit exactly argmax of position 0 — the same
    token plain warp_sample would pick."""
    rng = np.random.RandomState(1)
    B, d, V = 2, 2, 7
    logits = jnp.asarray(rng.randn(B, d + 1, V).astype(np.float32))
    emitted, n_emit, _ = spec_verify(
        logits,
        jnp.zeros((B, d), jnp.int32),
        jnp.zeros((B,), jnp.int32),
        jax.random.PRNGKey(0),
        jnp.ones((B,), jnp.float32), jnp.ones((B,), jnp.float32),
        jnp.full((B,), -1, jnp.int32), jnp.full((B,), True),
        jnp.zeros((B,), bool), jnp.zeros((V,), bool),
    )
    assert np.asarray(n_emit).tolist() == [1, 1]
    np.testing.assert_array_equal(
        np.asarray(emitted)[:, 0],
        np.asarray(jnp.argmax(logits[:, 0], axis=-1)),
    )


# ----------------------------------------------------------------------
# Engine e2e: lossless greedy + budget/EOS handling
# ----------------------------------------------------------------------


def _greedy_reqs():
    return [
        GenRequest(qid="a", input_ids=[9, 21, 33, 4, 9, 21], max_new_tokens=24,
                   greedy=True),
        GenRequest(qid="b", input_ids=[7, 11, 13], max_new_tokens=17,
                   greedy=True),
        GenRequest(qid="c", input_ids=[2, 2, 2, 2, 2, 2, 2, 2],
                   max_new_tokens=24, greedy=True),
    ]


def _engine(params, **kw):
    base = dict(
        max_batch_size=4, max_seq_len=128, decode_block_steps=4,
        prompt_bucket=8, eos_token_id=EOS, seed=0, page_size=8,
    )
    base.update(kw)
    return ServingEngine(CFG, params, **base)


@pytest.mark.parametrize("kv", [None, "int8"])
def test_spec_greedy_bit_identical_to_plain(params, kv):
    """The whole point: speculative greedy decode emits EXACTLY the
    plain engine's tokens (and logprobs), for both bf16 and int8 pools."""
    eng_plain = _engine(params, kv_cache_dtype=kv)
    eng_plain.start()
    try:
        plain = _run(eng_plain, _greedy_reqs())
    finally:
        eng_plain.stop()

    eng_spec = _engine(params, kv_cache_dtype=kv, speculative_draft_len=3)
    eng_spec.start()
    try:
        spec = _run(eng_spec, _greedy_reqs())
    finally:
        eng_spec.stop()

    for qid in plain:
        assert spec[qid].output_ids == plain[qid].output_ids, qid
        np.testing.assert_allclose(
            spec[qid].output_logprobs, plain[qid].output_logprobs,
            rtol=1e-4, atol=1e-5,
        )
        assert spec[qid].no_eos == plain[qid].no_eos, qid


def test_spec_sampled_completes_with_sane_outputs(params):
    eng = _engine(params, speculative_draft_len=4)
    eng.start()
    try:
        res = _run(eng, [
            GenRequest(qid=f"s{i}", input_ids=[3 + i, 1, 4, 1, 3 + i, 1],
                       max_new_tokens=20, temperature=1.0)
            for i in range(3)
        ])
        for r in res.values():
            assert r.error is None
            assert 1 <= len(r.output_ids) <= 20
            assert len(r.output_logprobs) == len(r.output_ids)
            assert all(lp <= 1e-6 for lp in r.output_logprobs)
            if not r.no_eos:
                assert r.output_ids[-1] == EOS
                assert EOS not in r.output_ids[:-1]
    finally:
        eng.stop()


def test_spec_respects_min_new_tokens(params):
    eng = _engine(params, speculative_draft_len=3)
    eng.start()
    try:
        res = _run(eng, [GenRequest(
            qid="m", input_ids=[6, 6, 6, 6], max_new_tokens=16,
            min_new_tokens=8, greedy=True,
        )])
        r = res["m"]
        assert len(r.output_ids) >= 8
        assert EOS not in r.output_ids[:7]
    finally:
        eng.stop()


def test_spec_yield_metric(params):
    """metrics() surfaces the realized speculation yield (tokens per
    decode step over active slots) — the number the chip A/B reads."""
    eng = _engine(params, speculative_draft_len=3, eos_token_id=None)
    eng.start()
    try:
        _run(eng, [GenRequest(qid="y", input_ids=[2, 3, 2, 3, 2, 3],
                              max_new_tokens=16, greedy=True)])
        m = eng.metrics()
        # Exact accounting (active-steps denominator): an active slot
        # emits >= 1 token per step, so the yield floor is 1.0.
        assert m["spec_tokens_per_step"] >= 1.0
    finally:
        eng.stop()


def test_spec_with_prefix_cache_resubmission(params):
    """Partial-rollout resubmission under speculation: the cache-hit
    admit prefills only the delta but the history row must hold the FULL
    prompt (drafts match against cached-prefix content too)."""
    eng = _engine(params, speculative_draft_len=3, eos_token_id=None,
                  prefill_chunk=8, prefix_cache_tokens=256)
    eng.start()
    try:
        prompt = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3]
        r1 = _run(eng, [GenRequest(qid="pc", input_ids=list(prompt),
                                   max_new_tokens=6, greedy=True)])["pc"]
        assert len(r1.output_ids) == 6
        r2 = _run(eng, [GenRequest(
            qid="pc", input_ids=list(prompt) + list(r1.output_ids),
            max_new_tokens=5, greedy=True)])["pc"]
        assert len(r2.output_ids) == 5
        assert eng.prefix_cache_hits == 1

        # Same continuation as a spec-less engine run end-to-end
        # (lossless under greedy, even across the resubmission).
        eng0 = _engine(params, eos_token_id=None, prefill_chunk=8,
                       prefix_cache_tokens=256)
        eng0.start()
        try:
            p1 = _run(eng0, [GenRequest(qid="pc", input_ids=list(prompt),
                                        max_new_tokens=6,
                                        greedy=True)])["pc"]
            p2 = _run(eng0, [GenRequest(
                qid="pc", input_ids=list(prompt) + list(p1.output_ids),
                max_new_tokens=5, greedy=True)])["pc"]
        finally:
            eng0.stop()
        assert r1.output_ids == p1.output_ids
        assert r2.output_ids == p2.output_ids
    finally:
        eng.stop()


def test_spec_under_tensor_parallel_mesh():
    """Speculation on a TP=2 mesh: the multi-row decode step partitions
    like the plain one; history stays replicated."""
    from areal_tpu.engine.serving import serving_mesh
    from areal_tpu.models.config import TransformerConfig
    from areal_tpu.models.transformer import init_params

    cfg = TransformerConfig(
        n_layers=2, hidden_dim=32, n_q_heads=4, n_kv_heads=2, head_dim=8,
        intermediate_dim=64, vocab_size=64, max_position_embeddings=256,
        compute_dtype="float32", param_dtype="float32",
    )
    p = init_params(cfg, jax.random.PRNGKey(5))
    eng = ServingEngine(
        cfg, p, mesh=serving_mesh(2), speculative_draft_len=3,
        max_batch_size=2, max_seq_len=64, decode_block_steps=4,
        prompt_bucket=8, eos_token_id=None, seed=0, page_size=8,
    )
    eng.start()
    try:
        res = _run(eng, [GenRequest(qid="tp", input_ids=[5, 6, 5, 6],
                                    max_new_tokens=10, greedy=True)])
        assert res["tp"].error is None
        assert len(res["tp"].output_ids) == 10
    finally:
        eng.stop()


def test_spec_budget_exact(params):
    eng = _engine(params, speculative_draft_len=4, eos_token_id=None)
    eng.start()
    try:
        res = _run(eng, [GenRequest(
            qid="b", input_ids=[2, 3, 2, 3, 2, 3], max_new_tokens=11,
            greedy=True,
        )])
        assert len(res["b"].output_ids) == 11
        assert res["b"].no_eos
    finally:
        eng.stop()

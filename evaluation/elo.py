"""Codeforces-style Elo rating estimation from per-problem outcomes.

Counterpart of the reference's evaluation/cf_elo_caculator.py, which
replays cached Codeforces contest standings to place the model in the
human rating ladder. That flow needs a contest-standings cache; this
TPU-repo equivalent estimates the rating directly by maximum likelihood
under the standard Elo solve model

    P(solve | rating r, difficulty d) = 1 / (1 + 10^((d - r) / 400))

over the model's per-problem pass/fail outcomes (the same logistic the
CF rating system induces), then reports the percentile against a human
ratings distribution ({rating: count} JSON, the same file format the
reference consumes).

Usage:
    python evaluation/elo.py results=/evals/step10/lcb.json \
        difficulties=/data/lcb_difficulty.jsonl \
        [ratings=/data/cf_ratings.json] [output=/evals/step10/elo.json]

`results` is a results.json from code_eval.py (details: query_id ->
correct); `difficulties` is a jsonl of {"query_id", "rating"}.
"""

from __future__ import annotations

import bisect
import json
import math
import os
import sys
from typing import Dict, List, Optional, Sequence, Tuple


def solve_probability(rating: float, difficulty: float) -> float:
    return 1.0 / (1.0 + 10.0 ** ((difficulty - rating) / 400.0))


def log_likelihood(rating: float, outcomes: Sequence[Tuple[float, bool]]) -> float:
    ll = 0.0
    for difficulty, solved in outcomes:
        p = min(max(solve_probability(rating, difficulty), 1e-12), 1 - 1e-12)
        ll += math.log(p if solved else 1.0 - p)
    return ll


def estimate_rating(
    outcomes: Sequence[Tuple[float, bool]],
    lo: float = 0.0,
    hi: float = 4000.0,
    tol: float = 0.5,
) -> float:
    """MLE rating via ternary search (the log-likelihood is strictly
    concave in r for the logistic model). All-solved/none-solved degenerate
    cases clamp to the search bounds."""
    if not outcomes:
        raise ValueError("no outcomes to rate")
    if all(s for _, s in outcomes):
        return hi
    if not any(s for _, s in outcomes):
        return lo
    while hi - lo > tol:
        m1 = lo + (hi - lo) / 3
        m2 = hi - (hi - lo) / 3
        if log_likelihood(m1, outcomes) < log_likelihood(m2, outcomes):
            lo = m1
        else:
            hi = m2
    return (lo + hi) / 2


def read_ratings(path: str) -> List[float]:
    """{rating: count} JSON -> sorted flat list (reference file format)."""
    with open(path) as f:
        dist = json.load(f)
    out: List[float] = []
    for rating, count in dist.items():
        out.extend([float(rating)] * int(count))
    return sorted(out)


def get_percentile(rating: float, sorted_ratings: List[float]) -> float:
    idx = bisect.bisect_left(sorted_ratings, float(rating))
    return round(idx / len(sorted_ratings) * 100, 1)


def rate_results(
    results: Dict,
    difficulties: Dict[str, float],
    sorted_ratings: Optional[List[float]] = None,
) -> Dict:
    """Join a code_eval results.json with per-problem difficulties and
    estimate the rating (+ percentile when a distribution is given).
    Problems without a known difficulty are skipped (counted)."""
    outcomes: List[Tuple[float, bool]] = []
    skipped = 0
    for row in results.get("details", []):
        d = difficulties.get(str(row["query_id"]))
        if d is None:
            skipped += 1
            continue
        outcomes.append((float(d), bool(row["correct"])))
    rating = estimate_rating(outcomes)
    out = {
        "rating": round(rating, 1),
        "n_problems": len(outcomes),
        "n_skipped_no_difficulty": skipped,
        "n_solved": sum(1 for _, s in outcomes if s),
    }
    if sorted_ratings:
        out["percentile"] = get_percentile(rating, sorted_ratings)
    return out


def _load_difficulties(path: str) -> Dict[str, float]:
    out: Dict[str, float] = {}
    with open(path) as f:
        for line in f:
            if not line.strip():
                continue
            row = json.loads(line)
            out[str(row["query_id"])] = float(row["rating"])
    return out


if __name__ == "__main__":
    kwargs = dict(arg.split("=", 1) for arg in sys.argv[1:])
    with open(kwargs["results"]) as f:
        results = json.load(f)
    difficulties = _load_difficulties(kwargs["difficulties"])
    ratings = read_ratings(kwargs["ratings"]) if "ratings" in kwargs else None
    report = rate_results(results, difficulties, ratings)
    if kwargs.get("output"):
        os.makedirs(os.path.dirname(kwargs["output"]) or ".", exist_ok=True)
        with open(kwargs["output"], "w") as f:
            json.dump(report, f)
    print(json.dumps(report))

"""Engine state checkpointing (recover checkpoints).

Counterpart of the reference's backend save/load
(realhf/impl/model/backend/megatron.py:711-760: optimizer + param state
for fault recovery; persistent HF-format saves are a separate path via
the interfaces). State = params pytree + optax opt state + step counter.

Two storage backends, selected by AREAL_CKPT_BACKEND (or the `backend`
argument):

- "pickle" (default): numpy-on-host single file per worker. Simple and
  exactly round-trippable, but np.asarray on a GSPMD-sharded array
  gathers the FULL global value to this host — fine single-host, wrong
  at pod scale.
- "orbax": orbax.checkpoint StandardCheckpointer — each host writes only
  its own shards (OCDBT), and restore places shards directly onto the
  engine's NamedShardings without a host gather. The TPU-native path
  for multi-host models.

Loading auto-detects which backend wrote a directory, so the flag only
matters for new saves.

Crash consistency (the durable-training-plane contract): every artifact
lands tmp+fsync+rename, and a ``manifest.json`` (areal-train-ckpt/v1)
is written LAST as the commit record — carrying version, the LR
schedule position (version_steps), RNG state, and dataset cursors. A
kill anywhere mid-save leaves the previous complete checkpoint intact,
so recovery resumes at most one version behind. With AREAL_CKPT_ASYNC
the pickle backend routes through `AsyncCheckpointWriter`: the step
loop pays only an on-device snapshot dispatch (donation-safe copies,
see `_snapshot_tree`) and the device->host fetch + serialization +
fsync run on a background thread.
"""

from __future__ import annotations

import json
import os
import pickle
import queue
import threading
import time
from typing import Any, Dict, Optional

import jax
import numpy as np

from areal_tpu.base import env_registry, logging, seeding
from areal_tpu.base.fault_injection import faults
from areal_tpu.base.wire_schemas import TRAIN_CKPT_V1

logger = logging.getLogger("checkpoint")

_STATE_FILE = "engine_state.pkl"
_ORBAX_DIR = "engine_state_orbax"
_MANIFEST_FILE = "manifest.json"
_RNG_SIDECAR = "rng_state.pkl"

# Step-loop stall of the most recent save on this process: full save
# duration when synchronous, submit-handoff only when async (the
# recovery_slo bench reads this A/B).
ckpt_stats = {"areal:train_ckpt_stall_ms": 0.0}

# The loop-only contract for the background writer: `_run` (the writer
# thread) owns the in-flight job state; everyone else goes through
# submit()/wait(), which only touch the condition-guarded counters.
AREAL_LINT_LOOP_ONLY = {
    "AsyncCheckpointWriter": {
        "roots": ["_run"],
        "attrs": ["_active", "_completed"],
        "init_ok": ["__init__"],
        "instance_hints": ["ckpt_writer"],
    },
}


def _to_host(tree: Any) -> Any:
    return jax.tree_util.tree_map(lambda x: np.asarray(x), tree)


def _snapshot_tree(tree: Any) -> Any:
    """Donation-safe snapshot for the async writer: the train step's
    fused program donates params/opt buffers (jax_engine donate_argnums),
    so a bare reference would be DELETED once training races ahead.
    jnp.copy dispatches an on-device copy asynchronously — the step loop
    pays a dispatch, not a transfer; host (numpy) leaves are replaced,
    never mutated, so references suffice there."""
    import jax.numpy as jnp

    return jax.tree_util.tree_map(
        lambda x: jnp.copy(x) if isinstance(x, jax.Array) else x, tree
    )


def _engine_state(engine):
    # Accessors, not attributes: an offloaded engine keeps params on host
    # (engine.params is None) and get_params/get_opt_state return the
    # host copies without re-occupying HBM.
    params = engine.get_params() if hasattr(engine, "get_params") else engine.params
    opt = (
        engine.get_opt_state()
        if hasattr(engine, "get_opt_state")
        else engine.opt_state
    )
    return params, opt


def _ckpt_backend(backend: Optional[str]) -> str:
    return backend or env_registry.get_str("AREAL_CKPT_BACKEND")


def _fsync_dir(path: str):
    dir_fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(dir_fd)
    finally:
        os.close(dir_fd)


def _collect_meta(engine, dataset_cursors: Optional[Dict] = None) -> Dict[str, Any]:
    """Everything a resume needs beyond the weight/opt pytrees, captured
    on the CALLER thread (atomically with the param refs)."""
    version = int(engine.version)
    return {
        "version": version,
        "version_steps": int(getattr(engine, "_lr_steps", version)),
        "rng": engine.rng_state() if hasattr(engine, "rng_state") else {},
        "host_rng": seeding.state_dict(),
        "dataset_cursors": dataset_cursors,
    }


def _write_manifest(save_dir: str, meta: Dict[str, Any], artifact: str):
    """The commit record, written LAST: a checkpoint without a current
    manifest is not a checkpoint (recovery falls back to the previous
    complete one). host_rng is pickled state, not JSON — it rides the
    artifact (pickle backend) or the rng sidecar (orbax), never here."""
    manifest = {
        "schema": TRAIN_CKPT_V1,
        "version": meta["version"],
        "version_steps": meta["version_steps"],
        "rng": meta["rng"],
        "dataset_cursors": meta["dataset_cursors"],
        "artifact": artifact,
    }
    path = os.path.join(save_dir, _MANIFEST_FILE)
    tmp = path + f".tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(manifest, f)
        f.flush()
        os.fsync(f.fileno())
    # The commit point: a kill here (armed in the chaos campaign) must
    # leave either the old manifest or the new one, never a torn file.
    faults.maybe_fail("train.checkpoint")
    os.replace(tmp, path)
    _fsync_dir(save_dir)


def load_manifest(load_dir: str) -> Optional[Dict[str, Any]]:
    path = os.path.join(load_dir, _MANIFEST_FILE)
    if not os.path.exists(path):
        return None
    with open(path) as f:
        m = json.load(f)
    if m.get("schema") != TRAIN_CKPT_V1:
        logger.warning("ignoring manifest with schema %r at %s",
                       m.get("schema"), load_dir)
        return None
    return m


def _write_pickle_state(save_dir: str, state: Dict[str, Any]):
    tmp = os.path.join(save_dir, f"{_STATE_FILE}.tmp.{os.getpid()}")
    with open(tmp, "wb") as f:
        pickle.dump(state, f, protocol=pickle.HIGHEST_PROTOCOL)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, os.path.join(save_dir, _STATE_FILE))
    _fsync_dir(save_dir)
    stale_dir = os.path.join(save_dir, _ORBAX_DIR)
    if os.path.isdir(stale_dir):
        import shutil

        shutil.rmtree(stale_dir, ignore_errors=True)


class AsyncCheckpointWriter:
    """Background pickle-checkpoint writer (AREAL_CKPT_ASYNC).

    `submit()` runs on the step loop and only dispatches an on-device
    snapshot copy plus the resume metadata — donation-safe against the
    train step's buffer reuse, so the snapshot stays crash-consistent
    while training races ahead (`_snapshot_tree`); the
    device->host gather, pickling, fsync and manifest commit all happen
    on the single writer thread (one thread, so overlapping submits for
    the same directory serialize instead of interleaving). Errors
    surface at the next submit()/wait(); `wait()` is the read barrier
    load/has_engine_state take before trusting the directory.
    """

    def __init__(self):
        self._q: "queue.Queue" = queue.Queue()
        self._cond = threading.Condition()
        self._pending = 0
        self._last_error: Optional[BaseException] = None
        self._last_write_s = 0.0
        self._active: Optional[str] = None
        self._completed = 0
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="ckpt-writer"
        )
        self._thread.start()

    def submit(self, engine, save_dir: str,
               dataset_cursors: Optional[Dict] = None) -> float:
        """Snapshot + enqueue; returns the step-loop stall in ms."""
        t0 = time.monotonic()
        self._raise_pending_error()
        params, opt = _engine_state(engine)
        job = {
            "save_dir": save_dir,
            "params": _snapshot_tree(params),
            "opt": _snapshot_tree(opt) if opt is not None else None,
            "meta": _collect_meta(engine, dataset_cursors),
        }
        with self._cond:
            self._pending += 1
        self._q.put(job)
        stall_ms = (time.monotonic() - t0) * 1e3
        ckpt_stats["areal:train_ckpt_stall_ms"] = stall_ms
        return stall_ms

    def wait(self, timeout: Optional[float] = None):
        """Block until every submitted write committed; re-raise the
        first writer-thread error, if any."""
        with self._cond:
            if not self._cond.wait_for(
                lambda: self._pending == 0, timeout=timeout
            ):
                raise TimeoutError(
                    f"async checkpoint writes still pending after {timeout}s"
                )
        self._raise_pending_error()

    def _raise_pending_error(self):
        with self._cond:
            err, self._last_error = self._last_error, None
        if err is not None:
            raise err

    def pending(self) -> int:
        with self._cond:
            return self._pending

    def last_write_s(self) -> float:
        with self._cond:
            return self._last_write_s

    def _run(self):
        while True:
            job = self._q.get()
            if job is None:
                return
            self._active = job["save_dir"]
            err: Optional[BaseException] = None
            t0 = time.monotonic()
            try:
                os.makedirs(job["save_dir"], exist_ok=True)
                state = {
                    "params": _to_host(job["params"]),
                    "opt_state": (
                        _to_host(job["opt"]) if job["opt"] is not None else None
                    ),
                    "version": job["meta"]["version"],
                    "version_steps": job["meta"]["version_steps"],
                    "rng": job["meta"]["rng"],
                    "host_rng": job["meta"]["host_rng"],
                }
                _write_pickle_state(job["save_dir"], state)
                _write_manifest(job["save_dir"], job["meta"], _STATE_FILE)
                logger.info("saved engine state (async) to %s", job["save_dir"])
            except BaseException as e:  # surfaced at next submit()/wait()
                logger.exception("async checkpoint write failed")
                err = e
            self._active = None
            self._completed += 1
            elapsed = time.monotonic() - t0
            with self._cond:
                self._pending -= 1
                self._last_write_s = elapsed
                if err is not None and self._last_error is None:
                    self._last_error = err
                self._cond.notify_all()

    def close(self):
        self._q.put(None)
        self._thread.join(timeout=30)


_ASYNC_WRITER: Optional[AsyncCheckpointWriter] = None
_WRITER_INIT_LOCK = threading.Lock()


def get_async_writer() -> AsyncCheckpointWriter:
    global _ASYNC_WRITER
    with _WRITER_INIT_LOCK:
        if _ASYNC_WRITER is None:
            _ASYNC_WRITER = AsyncCheckpointWriter()
        return _ASYNC_WRITER


def wait_pending_writes(timeout: Optional[float] = None):
    """Read barrier: block until any in-flight async checkpoint writes
    committed (no-op when the writer was never created)."""
    writer = _ASYNC_WRITER
    if writer is not None:
        writer.wait(timeout)


def save_engine_state(engine, save_dir: str, backend: Optional[str] = None,
                      dataset_cursors: Optional[Dict] = None):
    if _ckpt_backend(backend) != "orbax" and env_registry.get_bool(
        "AREAL_CKPT_ASYNC"
    ):
        get_async_writer().submit(engine, save_dir, dataset_cursors)
        return
    t0 = time.monotonic()
    _save_engine_state_sync(engine, save_dir, backend, dataset_cursors)
    ckpt_stats["areal:train_ckpt_stall_ms"] = (time.monotonic() - t0) * 1e3


def _save_engine_state_sync(engine, save_dir: str,
                            backend: Optional[str] = None,
                            dataset_cursors: Optional[Dict] = None):
    os.makedirs(save_dir, exist_ok=True)
    params, opt = _engine_state(engine)
    meta = _collect_meta(engine, dataset_cursors)
    if _ckpt_backend(backend) == "orbax":
        import orbax.checkpoint as ocp

        # Version rides inside the checkpoint so it commits atomically
        # with the weights (a side file could be torn by a preemption,
        # silently resetting step counters / LR schedule on recovery).
        state = {
            "params": params,
            "opt_state": opt,
            "version": np.asarray(engine.version, dtype=np.int64),
        }
        path = os.path.join(os.path.abspath(save_dir), _ORBAX_DIR)
        # Orbax save is a collective for multi-host GSPMD arrays, but
        # recover checkpoints go to per-worker directories (the model
        # worker's _ckpt_dir embeds the dp rank) — each process saving
        # a collective checkpoint to a DIFFERENT directory hangs or
        # corrupts it. Mirror the _load_orbax guard on the save side.
        for leaf in jax.tree_util.tree_leaves(state):
            if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
                raise NotImplementedError(
                    "orbax save of non-fully-addressable (multi-host) "
                    "arrays requires all processes to agree on one "
                    "checkpoint directory; per-worker recover dirs do "
                    "not. Use the pickle backend or a shared directory."
                )
        with ocp.StandardCheckpointer() as ck:
            # Orbax refuses to overwrite; recover checkpoints are
            # overwritable by contract (reference recover ckpts likewise
            # replace the previous one).
            ck.save(path, state, force=True)
        # Each save leaves exactly ONE backend's artifact behind —
        # loading prefers orbax, so a stale dir next to a newer pkl
        # would silently shadow it.
        stale = os.path.join(save_dir, _STATE_FILE)
        if os.path.exists(stale):
            os.remove(stale)
        # RNG state rides a pickle sidecar (numpy generator state is not
        # JSON and not worth an orbax tree); the manifest written after
        # it is still the commit record for the whole set.
        tmp = os.path.join(save_dir, f"{_RNG_SIDECAR}.tmp.{os.getpid()}")
        with open(tmp, "wb") as f:
            pickle.dump(
                {"rng": meta["rng"], "host_rng": meta["host_rng"]},
                f, protocol=pickle.HIGHEST_PROTOCOL,
            )
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(save_dir, _RNG_SIDECAR))
        _write_manifest(save_dir, meta, _ORBAX_DIR)
        logger.info(f"saved engine state (orbax) to {save_dir}")
        return
    state = {
        "params": _to_host(params),
        "opt_state": _to_host(opt) if opt is not None else None,
        "version": meta["version"],
        "version_steps": meta["version_steps"],
        "rng": meta["rng"],
        "host_rng": meta["host_rng"],
    }
    _write_pickle_state(save_dir, state)
    _write_manifest(save_dir, meta, _STATE_FILE)
    logger.info(f"saved engine state to {save_dir}")


def _load_orbax(engine, path: str) -> dict:
    """Restore directly onto the engine's shardings (no host gather):
    the abstract target carries each leaf's shape/dtype/sharding.

    Multi-host caveat: orbax save/restore of GSPMD-sharded arrays is a
    COLLECTIVE — every process of the jax.distributed world must call
    with the same directory. An offloaded engine (host numpy copies, no
    shardings to target) can only restore single-process."""
    import orbax.checkpoint as ocp

    params, opt = _engine_state(engine)
    shardingless = False

    def absify(x):
        nonlocal shardingless
        sharding = getattr(x, "sharding", None)
        if sharding is not None:
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)
        shardingless = True
        return jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype)

    with ocp.StandardCheckpointer() as ck:
        # Target follows what the CHECKPOINT contains, not what this
        # engine has: a params-only checkpoint (gradient-free engine)
        # must load into a training engine and vice versa (the pickle
        # path supports both by construction).
        meta = ck.metadata(path)
        meta_tree = getattr(meta, "item_metadata", None) or meta
        has_opt = False
        try:
            has_opt = (
                meta_tree["opt_state"] is not None
                and len(jax.tree_util.tree_leaves(meta_tree["opt_state"])) > 0
            )
        except (KeyError, TypeError):
            pass
        target = {
            "params": jax.tree_util.tree_map(absify, params),
            "opt_state": (
                jax.tree_util.tree_map(absify, opt)
                if (opt is not None and has_opt)
                else None
            ),
            "version": np.zeros((), dtype=np.int64),
        }
        if shardingless and jax.process_count() > 1:
            raise NotImplementedError(
                "orbax restore into an offloaded engine (host copies, no "
                "shardings) is single-process only; restore to device "
                "first or use the pickle backend"
            )
        # Same guard as the save side: restoring non-fully-addressable
        # (multi-host) arrays is a collective needing ONE shared
        # directory, but recover checkpoints live in per-dp-rank dirs —
        # a mismatched-directory collective hangs or corrupts state.
        for leaf in jax.tree_util.tree_leaves(target):
            sh = getattr(leaf, "sharding", None)
            if sh is not None and not sh.is_fully_addressable:
                raise NotImplementedError(
                    "orbax restore of non-fully-addressable (multi-host) "
                    "arrays requires all processes to agree on one "
                    "checkpoint directory; per-worker recover dirs do "
                    "not. Use the pickle backend or a shared directory."
                )
        state = ck.restore(path, target)
    return {
        "params": state["params"],
        "opt_state": state.get("opt_state"),
        "version": int(state.get("version", 0)),
    }


def load_engine_state(engine, load_dir: str):
    # Read barrier: an in-flight async write to this (or any) directory
    # must commit before the artifacts are trusted.
    wait_pending_writes()
    orbax_path = os.path.join(os.path.abspath(load_dir), _ORBAX_DIR)
    if os.path.isdir(orbax_path):
        state = _load_orbax(engine, orbax_path)
        rng_path = os.path.join(load_dir, _RNG_SIDECAR)
        if os.path.exists(rng_path):
            with open(rng_path, "rb") as f:
                state.update(pickle.load(f))
        manifest = load_manifest(load_dir)
        if manifest is not None:
            state.setdefault("version_steps", manifest.get("version_steps"))
    else:
        path = os.path.join(load_dir, _STATE_FILE)
        with open(path, "rb") as f:
            state = pickle.load(f)
    if hasattr(engine, "drop_offloaded_state") and state["opt_state"] is not None:
        # About to overwrite both params and optimizer state: discard any
        # offloaded host copies instead of restoring them to HBM first.
        # A params-only checkpoint must NOT drop offloaded Adam moments —
        # set_params alone keeps the host opt-state copy intact.
        engine.drop_offloaded_state()
    engine.set_params(state["params"])
    opt_shardings = getattr(engine, "_opt_shardings", None)
    if state["opt_state"] is not None and (
        engine.opt_state is not None or opt_shardings is not None
    ):
        # Restore optimizer state with the engine's shardings (prefer the
        # sharding pytree: valid even when opt_state itself is None).
        flat_new, treedef = jax.tree_util.tree_flatten(state["opt_state"])
        if opt_shardings is not None:
            flat_ref = jax.tree_util.tree_leaves(opt_shardings)
            assert len(flat_new) == len(flat_ref), "optimizer state mismatch"
            restored = [
                jax.device_put(n, s) for n, s in zip(flat_new, flat_ref)
            ]
        else:
            flat_ref = jax.tree_util.tree_leaves(engine.opt_state)
            assert len(flat_new) == len(flat_ref), "optimizer state mismatch"
            restored = [
                jax.device_put(n, r.sharding) if hasattr(r, "sharding") else n
                for n, r in zip(flat_new, flat_ref)
            ]
        engine.opt_state = jax.tree_util.tree_unflatten(treedef, restored)
    engine.version = int(state.get("version", 0))
    if hasattr(engine, "_lr_steps"):
        # The LR schedule position for callers that omit version_steps:
        # pre-PR-9 it rode in opt_state's scale_by_schedule count (now a
        # constant unit-LR schedule, see make_optimizer external_lr);
        # resume it at the checkpointed position (legacy checkpoints
        # without version_steps fall back to the version) so a recovery
        # restart does not snap the schedule back to warmup start.
        vs = state.get("version_steps")
        engine._lr_steps = int(vs if vs is not None else state.get("version", 0))
    # RNG restore: "recovered" must mean "same stream as uninterrupted".
    rng = state.get("rng")
    if rng and hasattr(engine, "load_rng_state"):
        engine.load_rng_state(rng)
    host_rng = state.get("host_rng")
    if host_rng:
        seeding.load_state(host_rng)
    logger.info(f"loaded engine state from {load_dir}")


def has_engine_state(load_dir: str) -> bool:
    wait_pending_writes()
    return os.path.exists(os.path.join(load_dir, _STATE_FILE)) or os.path.isdir(
        os.path.join(load_dir, _ORBAX_DIR)
    )

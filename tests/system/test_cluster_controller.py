"""ClusterController: scheduler-submitted workers + KV-service discovery
(no shared-FS name_resolve) running a full mock-SFT experiment e2e — the
multi-host control-plane topology (reference apps/main.py + SLURM
scheduler) simulated on one machine."""

import uuid

import pytest

from areal_tpu.api.config import (
    DatasetAbstraction,
    ModelAbstraction,
    ModelBackendAbstraction,
    ModelInterfaceAbstraction,
    ModelName,
    ModelShardID,
)
from areal_tpu.api.data_api import MicroBatchSpec
from areal_tpu.api.dfg import MFCDef, ModelInterfaceType
from areal_tpu.api.system_api import (
    ExperimentConfig,
    ExperimentSaveEvalControl,
    MasterWorkerConfig,
    ModelShardSpec,
    ModelWorkerConfig,
)
from areal_tpu.system.controller import ClusterController
from tests import fixtures

TINY_CFG = dict(
    vocab_size=128, hidden_dim=32, n_layers=2, n_q_heads=2, n_kv_heads=1,
    head_dim=16, intermediate_dim=64, max_position_embeddings=256,
    compute_dtype="float32",
)


@pytest.mark.slow  # ~44s: full mock-SFT through the cluster controller
def test_cluster_controller_sft_mock(tmp_path):
    exp, trial = f"cc-sft-{uuid.uuid4().hex[:6]}", "t0"
    rows = fixtures.make_sft_rows(32, seed=3)
    texts = [r["prompt"] + " " + r["answer"] for r in rows]
    tok = fixtures.train_tiny_tokenizer(texts, tmp_path)
    tok_dir = str(tmp_path / "tok_full")
    tok.save_pretrained(tok_dir)
    data_path = fixtures.write_jsonl(rows, tmp_path / "sft.jsonl")

    n_workers = 2
    sft = MFCDef(
        name="sft_train",
        model_name=ModelName("default", 0),
        interface_type=ModelInterfaceType.TRAIN_STEP,
        interface_impl=None,
        n_seqs=8,
        input_keys=("packed_input_ids", "prompt_mask"),
        mb_spec=MicroBatchSpec(n_mbs=1),
    )
    workers = [f"model_worker/{i}" for i in range(n_workers)]
    model_workers = [
        ModelWorkerConfig(
            experiment_name=exp,
            trial_name=trial,
            worker_index=i,
            shards=[
                ModelShardSpec(
                    id=ModelShardID(
                        ModelName("default", 0), host_rank=i, n_hosts=n_workers
                    ),
                    model=ModelAbstraction(
                        "tpu_transformer",
                        args=dict(config=TINY_CFG, tokenizer_path=tok_dir),
                    ),
                    backend=ModelBackendAbstraction("mock_train"),
                    interface=ModelInterfaceAbstraction("sft"),
                )
            ],
            datasets=[
                DatasetAbstraction(
                    "prompt_answer",
                    args=dict(max_length=64, dataset_path=data_path),
                )
            ],
            tokenizer_path=tok_dir,
            dataset_dp_rank=i,
            dataset_dp_size=n_workers,
            train_batch_size=8,
            total_train_epochs=2,
        )
        for i in range(n_workers)
    ]
    master = MasterWorkerConfig(
        experiment_name=exp,
        trial_name=trial,
        exp_ctrl=ExperimentSaveEvalControl(
            total_train_epochs=2, benchmark_steps=4
        ),
        rpcs=[sft],
        model_topos={str(ModelName("default", 0)): workers},
        data_hosts=workers,
        n_model_workers=n_workers,
        train_batch_size=8,
    )
    cfg = ExperimentConfig(
        experiment_name=exp, trial_name=trial, master=master,
        model_workers=model_workers,
    )
    ctl = ClusterController(
        cfg,
        spool_dir=str(tmp_path / "spool"),
        scheduler_mode="local",
        worker_env={
            "JAX_PLATFORMS": "cpu",
            "XLA_FLAGS": "--xla_force_host_platform_device_count=2",
            "AREAL_FILEROOT": str(tmp_path / "fileroot"),
        },
    )
    result = ctl.run()
    assert result["global_step"] == 4


def test_cluster_controller_surfaces_worker_failure(tmp_path):
    """A worker that dies must surface its log tail, not hang the master."""
    exp, trial = f"cc-fail-{uuid.uuid4().hex[:6]}", "t0"
    bad = ModelWorkerConfig(
        experiment_name=exp, trial_name=trial, worker_index=0,
        shards=[
            ModelShardSpec(
                id=ModelShardID(ModelName("default", 0), host_rank=0, n_hosts=1),
                model=ModelAbstraction(
                    "tpu_transformer", args=dict(config=dict(TINY_CFG))
                ),
                backend=ModelBackendAbstraction("no_such_backend"),
                interface=ModelInterfaceAbstraction("sft"),
            )
        ],
        train_batch_size=8,
    )
    master = MasterWorkerConfig(
        experiment_name=exp, trial_name=trial,
        exp_ctrl=ExperimentSaveEvalControl(benchmark_steps=2),
        rpcs=[
            MFCDef(
                name="sft_train",
                model_name=ModelName("default", 0),
                interface_type=ModelInterfaceType.TRAIN_STEP,
                interface_impl=None,
                n_seqs=8,
                input_keys=("packed_input_ids", "prompt_mask"),
                mb_spec=MicroBatchSpec(n_mbs=1),
            )
        ],
        model_topos={str(ModelName("default", 0)): ["model_worker/0"]},
        data_hosts=["model_worker/0"],
        n_model_workers=1,
        train_batch_size=8,
    )
    cfg = ExperimentConfig(
        experiment_name=exp, trial_name=trial, master=master,
        model_workers=[bad],
    )
    ctl = ClusterController(
        cfg, spool_dir=str(tmp_path / "spool"), scheduler_mode="local",
        worker_env={"JAX_PLATFORMS": "cpu"},
    )
    with pytest.raises(RuntimeError, match="model_worker/0"):
        ctl.run()

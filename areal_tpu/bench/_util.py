"""Shared helpers for the bench package."""

from __future__ import annotations

import os
import sys


def log(*a) -> None:
    print(*a, file=sys.stderr, flush=True)


def repo_root() -> str:
    """Absolute path of the repository root (this file lives at
    <root>/areal_tpu/bench/_util.py — keep the depth in sync if the
    package ever moves)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))

"""Rollout WAL + seq ledger unit semantics (ISSUE 16): append/replay
round-trip, torn-tail tolerance (a kill mid-append must cost the torn
record only — redelivery covers it — never the journal), checkpoint-
barrier compaction, and the ledger's watermark+extras compression."""

import json
import os

import pytest

from areal_tpu.base.wire_schemas import BUFFER_WAL_V1
from areal_tpu.system.wal import RolloutWAL, SeqLedger


# ======================================================================
# SeqLedger
# ======================================================================


def test_ledger_mark_and_contains():
    led = SeqLedger()
    assert "w0/0" not in led
    led.mark("w0/0")
    led.mark("w0/1")
    assert "w0/0" in led and "w0/1" in led
    assert "w0/2" not in led
    assert "w1/0" not in led  # per-pusher namespaces


def test_ledger_out_of_order_absorbs_into_watermark():
    led = SeqLedger()
    led.mark("w0/2")  # gap: 0,1 pending
    assert "w0/2" in led and "w0/0" not in led
    assert led.to_dict() == {"water": {"w0": -1}, "extras": {"w0": [2]}}
    led.mark("w0/0")
    led.mark("w0/1")  # closes the gap: extras collapse into the water
    assert led.to_dict() == {"water": {"w0": 2}, "extras": {}}
    for n in range(3):
        assert f"w0/{n}" in led


def test_ledger_mark_is_idempotent_and_permanent():
    led = SeqLedger()
    led.mark("w0/0")
    led.mark("w0/0")
    assert led.to_dict() == {"water": {"w0": 0}, "extras": {}}
    # membership is permanent (unlike the buffer's skip-once ignore_ids)
    assert "w0/0" in led
    assert "w0/0" in led


def test_ledger_roundtrip_through_dict():
    led = SeqLedger()
    for seq in ["a/0", "a/1", "a/5", "b/0"]:
        led.mark(seq)
    clone = SeqLedger.from_dict(led.to_dict())
    for seq in ["a/0", "a/1", "a/5", "b/0"]:
        assert seq in clone
    for seq in ["a/2", "a/4", "b/1"]:
        assert seq not in clone
    assert clone.to_dict() == led.to_dict()
    # None/empty snapshots (legacy RecoverInfo) give an empty ledger.
    assert SeqLedger.from_dict(None).to_dict() == {"water": {}, "extras": {}}


def test_ledger_seq_with_slash_in_pusher_name():
    led = SeqLedger()
    led.mark("host/worker/3/7")  # pusher = "host/worker/3"
    assert "host/worker/3/7" in led
    assert "host/worker/3/6" not in led


# ======================================================================
# RolloutWAL
# ======================================================================


def _wal(tmp_path, name="j.wal", **kw):
    kw.setdefault("fsync_ms", 0)
    return RolloutWAL(str(tmp_path / name), **kw)


def test_wal_append_replay_roundtrip(tmp_path):
    w = _wal(tmp_path)
    assert w.replay() == []
    recs = [{"seq": f"w0/{i}", "data": {"x": i}} for i in range(3)]
    for r in recs:
        w.append(r)
    w.close()
    w2 = _wal(tmp_path)
    try:
        assert w2.replay() == recs
    finally:
        w2.close()


def test_wal_schema_header_is_first_line(tmp_path):
    w = _wal(tmp_path)
    w.replay()
    w.append({"seq": "w0/0"})
    w.close()
    with open(w.path) as f:
        first = json.loads(f.readline())
    assert first == {"schema": BUFFER_WAL_V1}


def test_wal_rejects_unknown_schema(tmp_path):
    path = tmp_path / "bad.wal"
    path.write_text('{"schema":"somebody-elses/v9"}\n')
    w = RolloutWAL(str(path), fsync_ms=0)
    with pytest.raises(ValueError, match="unsupported schema"):
        w.replay()


def test_wal_torn_tail_truncated_not_fatal(tmp_path):
    """A kill between append and fsync tears the final record: replay
    must return every intact record, truncate the torn bytes off the
    file, and leave the journal appendable."""
    w = _wal(tmp_path)
    w.replay()
    w.append({"seq": "w0/0", "data": {"x": 0}})
    w.append({"seq": "w0/1", "data": {"x": 1}})
    w.close()
    # Simulate the torn append: half a record, no terminating newline.
    with open(w.path, "ab") as f:
        f.write(b'{"seq":"w0/2","da')
    w2 = _wal(tmp_path)
    try:
        assert [r["seq"] for r in w2.replay()] == ["w0/0", "w0/1"]
        # The torn bytes are gone from disk (later appends never
        # interleave with them)...
        w2.append({"seq": "w0/3", "data": {"x": 3}})
    finally:
        w2.close()
    w3 = _wal(tmp_path)
    try:
        # ...and a third incarnation sees a clean journal.
        assert [r["seq"] for r in w3.replay()] == ["w0/0", "w0/1", "w0/3"]
    finally:
        w3.close()


def test_wal_torn_tail_with_newline_garbage(tmp_path):
    """Garbage that IS newline-terminated (torn then overwritten by
    noise) still truncates at the first undecodable line."""
    w = _wal(tmp_path)
    w.replay()
    w.append({"seq": "w0/0"})
    w.close()
    with open(w.path, "ab") as f:
        f.write(b"\x00\xff not json\n")
        f.write(b'{"seq":"w0/9"}\n')  # after garbage: unreachable
    w2 = _wal(tmp_path)
    try:
        assert [r["seq"] for r in w2.replay()] == ["w0/0"]
    finally:
        w2.close()


def test_wal_empty_and_header_only_files(tmp_path):
    # Zero-byte file (kill before the header fsync'd): clean replay.
    path = tmp_path / "empty.wal"
    path.write_bytes(b"")
    w = RolloutWAL(str(path), fsync_ms=0)
    assert w.replay() == []
    w.close()
    # Header-only journal replays empty too.
    w2 = RolloutWAL(str(path), fsync_ms=0)
    assert w2.replay() == []
    w2.close()


def test_wal_on_durable_fires_after_fsync_batching(tmp_path):
    """The deferred-ack contract: on_durable callbacks fire only when
    the fsync covering their record lands — with a large fsync window
    nothing fires until forced."""
    w = _wal(tmp_path, fsync_ms=60_000)
    w.replay()
    acked = []
    w.append({"seq": "w0/0"}, on_durable=lambda: acked.append("w0/0"))
    w.append({"seq": "w0/1"}, on_durable=lambda: acked.append("w0/1"))
    assert acked == []  # window not elapsed: ack would be premature
    assert w.maybe_sync() is False
    assert w.maybe_sync(force=True) is True
    assert acked == ["w0/0", "w0/1"]
    # Idempotent: a later sync with nothing dirty fires nothing.
    assert w.sync() is False
    assert acked == ["w0/0", "w0/1"]
    w.close()


def test_wal_zero_window_acks_inline(tmp_path):
    w = _wal(tmp_path, fsync_ms=0)
    w.replay()
    acked = []
    w.append({"seq": "w0/0"}, on_durable=lambda: acked.append(1))
    assert acked == [1]
    w.close()


def test_wal_compact_drops_consumed_keeps_pending(tmp_path):
    led = SeqLedger()
    led.mark("w0/0")
    led.mark("w0/2")
    w = _wal(tmp_path)
    w.replay()
    for i in range(4):
        w.append({"seq": f"w0/{i}", "data": {"x": i}})
    dropped = w.compact(lambda rec: rec.get("seq") not in led)
    assert dropped == 2
    # The journal stays appendable after the atomic rewrite.
    w.append({"seq": "w0/4", "data": {"x": 4}})
    w.close()
    w2 = _wal(tmp_path)
    try:
        assert [r["seq"] for r in w2.replay()] == ["w0/1", "w0/3", "w0/4"]
    finally:
        w2.close()
    # No tmp litter from the rewrite.
    assert not [p for p in os.listdir(tmp_path) if ".tmp." in p]


def test_wal_compact_before_any_replay(tmp_path):
    """Compaction on a fresh (never-replayed) WAL must not crash — the
    model worker's barrier can fire before the stream saw traffic."""
    w = _wal(tmp_path)
    w.replay()
    assert w.compact(lambda rec: True) == 0
    w.close()

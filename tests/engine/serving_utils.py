"""Shared serving-engine test harness: the tiny 2-layer model and the
submit-and-wait runner the engine test modules were each copying."""

import threading

from areal_tpu.models.config import TransformerConfig

TINY_SERVING_CFG = TransformerConfig(
    n_layers=2,
    hidden_dim=32,
    n_q_heads=2,
    n_kv_heads=1,
    head_dim=16,
    intermediate_dim=64,
    vocab_size=64,
    max_position_embeddings=512,
    compute_dtype="float32",
    param_dtype="float32",
)
TINY_EOS = 5


def run_requests(engine, reqs, timeout=120):
    """Submit all requests, wait for every callback, return {qid: result}."""
    results = {}
    done = threading.Event()

    def cb(res):
        results[res.qid] = res
        if len(results) == len(reqs):
            done.set()

    for r in reqs:
        r.done_cb = cb
        engine.submit(r)
    assert done.wait(timeout), f"only {len(results)}/{len(reqs)} finished"
    return results

"""GSPMD partition rules: megatron-equivalent shardings by annotation.

Replaces the reference's hand-written tensor/sequence-parallel modules
(realhf/impl/model/parallelism/tensor_parallel/modules.py — Column/Row
parallel linears, parallel embedding, vocab-parallel CE) with
`PartitionSpec`s over the (data, fsdp, seq, tensor) mesh:

- attention qkv projections: column-parallel  -> output dim on `tensor`
- attention output proj:     row-parallel     -> input dim on `tensor`
- MLP gate/up:               column-parallel; down: row-parallel
- embedding + LM head:       vocab on `tensor` (vocab-parallel CE falls out
  of the sharded logits + psum XLA inserts for logsumexp)
- every weight's other big dim on `fsdp` (ZeRO-3-style param sharding);
  optimizer state inherits these specs (ZeRO-1/2)
- activations: rows on (data, fsdp), sequence dim on `seq` (context
  parallelism; megatron-SP's activation sharding falls out here too)

The reference's parameter-flattening + interval scatter/gather machinery
(flatten_param.py, csrc/interval_op) has no TPU counterpart by design:
resharding is `jax.device_put` between NamedShardings (see realloc.py).
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

Params = Dict[str, Any]


def _path_str(path) -> str:
    parts = []
    for p in path:
        if hasattr(p, "key"):
            parts.append(str(p.key))
        elif hasattr(p, "idx"):
            parts.append(str(p.idx))
        else:
            parts.append(str(p))
    return "/".join(parts)


def param_partition_spec(path: str, ndim: int) -> P:
    """PartitionSpec for one parameter, by pytree path.

    Layer-stacked params have a leading L axis (never sharded). Biases and
    norms are small: replicated.
    """
    name = path.split("/")[-1]
    if "embedding" in path:
        return P("tensor", "fsdp")  # [V, D]
    if path.startswith("head") or "/head/" in path or path == "head/weight":
        return P("fsdp", "tensor")  # [D, V] or [D, 1]
    if name in ("wq", "wk", "wv", "w_gate", "w_up", "w_in"):
        if ndim == 4:
            # MoE stacked experts [L, E, D, F]: expert parallelism —
            # E shards over the ZeRO/fsdp axis (the einsum dispatch
            # "tec,td->ecd" with tokens on (data,fsdp) and experts on
            # fsdp makes XLA emit the token all-to-all; DeepSeek-style
            # EP-over-DP without custom collectives), F stays
            # column-parallel on tensor.
            return P(None, "fsdp", None, "tensor")
        return P(None, "fsdp", "tensor")  # [L, D, out]: column parallel
    if name in ("wo", "w_down", "w_out"):
        if ndim == 4:
            return P(None, "fsdp", "tensor", None)  # [L, E, F, D]
        return P(None, "tensor", "fsdp")  # [L, in, D]: row parallel
    if name in ("bq", "bk", "bv", "b_gate", "b_up", "b_in"):
        return P(None, "tensor")  # [L, out]
    # norms, small biases (b_down/b_out [L, D]), router [L, D, E],
    # q_norm/k_norm: replicated.
    return P(*([None] * ndim))


def _moe_fsdp_fallback(name: str, ndim: int) -> Optional[P]:
    """When num_experts doesn't divide the fsdp axis, EP is impossible —
    but the expert weights are the bulk of model memory, so ZeRO-3 must
    not silently degrade to full replication: shard the hidden dim on
    fsdp instead."""
    if ndim != 4:
        return None
    if name in ("w_gate", "w_up"):
        return P(None, None, "fsdp", "tensor")  # [L, E, D, F]
    if name == "w_down":
        return P(None, None, "tensor", "fsdp")  # [L, E, F, D]
    return None


def _axis_size(mesh: Mesh, entry) -> int:
    if entry is None:
        return 1
    names = entry if isinstance(entry, tuple) else (entry,)
    size = 1
    for n in names:
        size *= mesh.shape[n]
    return size


def fit_spec_to_shape(spec: P, shape, mesh: Mesh) -> P:
    """Drop sharded axes a dimension cannot honor (not divisible by the
    mesh-axis size — e.g. the critic head's [D, 1] output dim)."""
    entries = list(spec) + [None] * (len(shape) - len(spec))
    fitted = []
    for dim, entry in zip(shape, entries):
        fitted.append(entry if dim % _axis_size(mesh, entry) == 0 else None)
    return P(*fitted)


def param_shardings(params: Params, mesh: Mesh) -> Params:
    """Pytree of NamedShardings matching `params`' structure."""

    def one(path, leaf):
        ps = _path_str(path)
        spec = param_partition_spec(ps, leaf.ndim)
        fitted = fit_spec_to_shape(spec, leaf.shape, mesh)
        if len(spec) > 1 and spec[1] == "fsdp" and fitted[1] is None:
            # Expert dim indivisible by fsdp: fall back to hidden-dim
            # ZeRO sharding rather than replicating the expert weights.
            alt = _moe_fsdp_fallback(ps.split("/")[-1], leaf.ndim)
            if alt is not None:
                fitted = fit_spec_to_shape(alt, leaf.shape, mesh)
        return NamedSharding(mesh, fitted)

    return jax.tree_util.tree_map_with_path(one, params)


def shard_params(params: Params, mesh: Mesh) -> Params:
    """Place a host pytree onto the mesh with megatron-equivalent sharding."""
    return jax.device_put(params, param_shardings(params, mesh))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """[R, T] token rows: rows over (data, fsdp), sequence over seq."""
    return NamedSharding(mesh, P(("data", "fsdp"), "seq"))


def activation_constraint(x, mesh: Mesh):
    """Constrain [R, T, D] activations inside jit."""
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(("data", "fsdp"), "seq", None))
    )


def logits_constraint(x, mesh: Mesh):
    return jax.lax.with_sharding_constraint(
        x, NamedSharding(mesh, P(("data", "fsdp"), "seq", "tensor"))
    )


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())

"""DFG construction tests (mirrors reference tests/data/test_dfg.py)."""

import pytest

from areal_tpu.api.config import ModelInterfaceAbstraction, ModelName
from areal_tpu.api.dfg import MFCDef, ModelInterfaceType, build_graph


def _mfc(name, role, itype, inputs, outputs, **kw):
    return MFCDef(
        name=name,
        model_name=ModelName(role, 0),
        interface_type=itype,
        interface_impl=ModelInterfaceAbstraction("null"),
        input_keys=inputs,
        output_keys=outputs,
        **kw,
    )


def make_ppo_rpcs():
    gen = _mfc(
        "actor_gen", "actor", ModelInterfaceType.GENERATE,
        ["packed_prompts"], ["packed_input_ids", "prompt_mask", "logprobs"],
    )
    rew = _mfc(
        "rew_inf", "reward", ModelInterfaceType.INFERENCE,
        ["packed_input_ids"], ["rewards"],
    )
    ref = _mfc(
        "ref_inf", "ref", ModelInterfaceType.INFERENCE,
        ["packed_input_ids"], ["ref_logprobs"],
    )
    critic_inf = _mfc(
        "critic_inf", "critic", ModelInterfaceType.INFERENCE,
        ["packed_input_ids"], ["values"],
    )
    actor_train = _mfc(
        "actor_train", "actor", ModelInterfaceType.TRAIN_STEP,
        ["packed_input_ids", "prompt_mask", "logprobs", "rewards", "ref_logprobs", "values"],
        [],
    )
    critic_train = _mfc(
        "critic_train", "critic", ModelInterfaceType.TRAIN_STEP,
        ["packed_input_ids", "prompt_mask", "logprobs", "rewards", "ref_logprobs", "values"],
        [],
    )
    return [gen, rew, ref, critic_inf, actor_train, critic_train]


def test_ppo_graph_structure():
    rpcs = make_ppo_rpcs()
    g = build_graph(rpcs)
    by = g.rpcs
    assert by["actor_gen"].is_src
    assert set(by["actor_gen"].children) == {"rew_inf", "ref_inf", "critic_inf",
                                             "actor_train", "critic_train"}
    assert by["actor_train"].is_dst and by["critic_train"].is_dst
    assert set(by["actor_train"].parents) == {"actor_gen", "rew_inf", "ref_inf", "critic_inf"}
    assert g.topo_order[0] == ["actor_gen"]
    assert set(g.topo_order[1]) == {"critic_inf", "ref_inf", "rew_inf"}
    assert set(g.topo_order[2]) == {"actor_train", "critic_train"}
    # packed_prompts comes from the dataset.
    assert g.data_keys == {"packed_prompts"}


def test_output_key_remap():
    a = _mfc("a", "m", ModelInterfaceType.INFERENCE, ["x"], ["logprobs"],
             output_key_remap={"logprobs": "old_logprobs"})
    b = _mfc("b", "m", ModelInterfaceType.TRAIN_STEP, ["old_logprobs"], [])
    g = build_graph([a, b])
    assert g.rpcs["b"].parents == ["a"]
    assert g.producers["old_logprobs"] == "a"


def test_duplicate_producer_raises():
    a = _mfc("a", "m", ModelInterfaceType.INFERENCE, [], ["y"])
    b = _mfc("b", "m", ModelInterfaceType.INFERENCE, [], ["y"])
    with pytest.raises(ValueError):
        build_graph([a, b])


def test_cycle_detection():
    a = _mfc("a", "m", ModelInterfaceType.INFERENCE, ["u"], ["v"])
    b = _mfc("b", "m", ModelInterfaceType.INFERENCE, ["v"], ["u"])
    with pytest.raises(ValueError):
        build_graph([a, b])


def test_sft_single_node():
    t = _mfc("sft_train", "default", ModelInterfaceType.TRAIN_STEP,
             ["packed_input_ids", "prompt_mask"], [])
    g = build_graph([t])
    assert t.is_src and t.is_dst
    assert g.data_keys == {"packed_input_ids", "prompt_mask"}

"""Pluggable cluster-wide key-value naming/discovery service.

TPU-native counterpart of the reference name-resolve layer
(reference: realhf/base/name_resolve.py). Workers publish addresses,
versions, and statuses under hierarchical string keys; peers `get`/`wait`/
`watch` them. Two backends are provided:

- ``memory``: in-process dict (unit tests, single-process runs).
- ``nfs``: file-per-key under a shared directory (multi-process on one
  host, or cross-host over NFS). This is the default for tests and
  single-host launches; etcd/Redis equivalents can be added behind the
  same ABC when a real cluster KV is available.

All values are strings. `add(..., keepalive_ttl=...)` spawns a background
toucher so stale records from dead workers expire.
"""

from __future__ import annotations

import dataclasses
import os
import random
import shutil
import threading
import time
import uuid
from abc import ABC, abstractmethod
from typing import Callable, Dict, List, Optional

from areal_tpu.base import env_registry
from areal_tpu.base import logging as areal_logging

logger = areal_logging.getLogger("name_resolve")


class NameEntryExistsError(Exception):
    pass


class NameEntryNotFoundError(Exception):
    pass


class NameRecordRepository(ABC):
    """Abstract KV repository for cluster naming."""

    @abstractmethod
    def add(
        self,
        name: str,
        value: str,
        delete_on_exit: bool = True,
        keepalive_ttl: Optional[float] = None,
        replace: bool = False,
    ):
        ...

    def add_subentry(self, name: str, value: str, **kwargs) -> str:
        """Add under a unique sub-key of `name`; returns the sub-key."""
        sub_name = f"{name.rstrip('/')}/{uuid.uuid4().hex[:8]}"
        self.add(sub_name, value, **kwargs)
        return sub_name

    @abstractmethod
    def delete(self, name: str):
        ...

    @abstractmethod
    def clear_subtree(self, name_root: str):
        ...

    @abstractmethod
    def get(self, name: str) -> str:
        ...

    @abstractmethod
    def get_subtree(self, name_root: str) -> List[str]:
        """Values of all keys under `name_root`."""
        ...

    @abstractmethod
    def find_subtree(self, name_root: str) -> List[str]:
        """Keys (sorted) under `name_root`."""
        ...

    def wait(
        self,
        name: str,
        timeout: Optional[float] = None,
        poll_frequency: float = 0.1,
    ) -> str:
        """Block until `name` exists, then return its value."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            try:
                return self.get(name)
            except NameEntryNotFoundError:
                if deadline is not None and time.monotonic() > deadline:
                    raise TimeoutError(f"name_resolve.wait timeout on key: {name}")
                time.sleep(poll_frequency * (0.8 + 0.4 * random.random()))

    def watch_names(
        self,
        names: List[str],
        call_back: Callable[[], None],
        poll_frequency: float = 5.0,
        grace_period: float = 300.0,
    ):
        """Invoke `call_back` once any of `names` disappears (polling watcher).

        Names are first given `grace_period` seconds to appear (workers still
        registering are not dead); a name that never shows up within the
        grace period also triggers the callback (worker died during startup).
        """

        def _watch():
            try:
                for n in names:
                    self.wait(n, timeout=grace_period, poll_frequency=poll_frequency)
            except TimeoutError:
                call_back()
                return
            while True:
                for n in names:
                    try:
                        self.get(n)
                    except NameEntryNotFoundError:
                        call_back()
                        return
                time.sleep(poll_frequency)

        t = threading.Thread(target=_watch, daemon=True)
        t.start()
        return t

    def reset(self):
        """Remove every entry added by this repository instance."""

    def close(self):
        self.reset()


class MemoryNameRecordRepository(NameRecordRepository):
    """In-process dict backend (single-process tests)."""

    # Class-level store so that separate instances within one process share
    # names, mirroring how a external KV service would behave.
    _store: Dict[str, str] = {}
    _lock = threading.Lock()

    def __init__(self):
        self._my_keys = set()

    def add(self, name, value, delete_on_exit=True, keepalive_ttl=None, replace=False):
        name = name.rstrip("/")
        with self._lock:
            if name in self._store and not replace:
                raise NameEntryExistsError(name)
            self._store[name] = str(value)
            if delete_on_exit:
                self._my_keys.add(name)

    def delete(self, name):
        name = name.rstrip("/")
        with self._lock:
            if name not in self._store:
                raise NameEntryNotFoundError(name)
            del self._store[name]
            self._my_keys.discard(name)

    def clear_subtree(self, name_root):
        root = name_root.rstrip("/")
        with self._lock:
            for k in [k for k in self._store if k == root or k.startswith(root + "/")]:
                del self._store[k]
                self._my_keys.discard(k)

    def get(self, name):
        name = name.rstrip("/")
        with self._lock:
            if name not in self._store:
                raise NameEntryNotFoundError(name)
            return self._store[name]

    def get_subtree(self, name_root):
        root = name_root.rstrip("/")
        with self._lock:
            keys = sorted(
                k for k in self._store if k == root or k.startswith(root + "/")
            )
            return [self._store[k] for k in keys]

    def find_subtree(self, name_root):
        root = name_root.rstrip("/")
        with self._lock:
            return sorted(k for k in self._store if k == root or k.startswith(root + "/"))

    def reset(self):
        with self._lock:
            for k in list(self._my_keys):
                self._store.pop(k, None)
            self._my_keys.clear()


class NfsNameRecordRepository(NameRecordRepository):
    """File-per-key backend under a shared directory.

    Works across processes on one host (default root under /tmp) and across
    hosts when the root lives on NFS. TTL records carry a heartbeat mtime;
    a reader treats records older than their TTL as absent.
    """

    RECORD_ROOT = env_registry.get_str("AREAL_NAME_RESOLVE_ROOT")

    def __init__(self, record_root: Optional[str] = None):
        self._root = record_root or self.RECORD_ROOT
        self._my_keys: Dict[str, bool] = {}
        self._keepalive_threads: Dict[str, threading.Event] = {}

    def _path(self, name: str) -> str:
        return os.path.join(self._root, name.strip("/"), "ENTRY")

    def add(self, name, value, delete_on_exit=True, keepalive_ttl=None, replace=False):
        path = self._path(name)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + f".tmp.{uuid.uuid4().hex[:8]}"
        with open(tmp, "w") as f:
            f.write(str(value))
            if keepalive_ttl is not None:
                f.write(f"\n__TTL__={keepalive_ttl}")
        if replace:
            os.replace(tmp, path)
        else:
            # Atomic create-if-absent: hard-link fails with EEXIST if a live
            # record is present, so two concurrent adders cannot both win.
            # A TTL'd record whose owner died can be replaced.
            while True:
                try:
                    os.link(tmp, path)
                    os.remove(tmp)
                    break
                except FileExistsError:
                    if self._is_expired(path):
                        try:
                            os.remove(path)
                        except FileNotFoundError:
                            pass
                        continue
                    os.remove(tmp)
                    raise NameEntryExistsError(name)
        if delete_on_exit:
            self._my_keys[name] = True
        if keepalive_ttl is not None:
            self._start_keepalive(name, path, keepalive_ttl)

    def _start_keepalive(self, name: str, path: str, ttl: float):
        old = self._keepalive_threads.pop(name, None)
        if old is not None:
            old.set()
        stop = threading.Event()
        self._keepalive_threads[name] = stop

        def _touch():
            while not stop.wait(max(ttl / 3, 0.2)):
                try:
                    os.utime(path, None)
                except OSError:
                    return

        threading.Thread(target=_touch, daemon=True).start()

    @staticmethod
    def _read(path: str):
        with open(path) as f:
            content = f.read()
        ttl = None
        if "\n__TTL__=" in content:
            content, ttl_s = content.rsplit("\n__TTL__=", 1)
            ttl = float(ttl_s)
        return content, ttl

    @classmethod
    def _is_expired(cls, path: str) -> bool:
        try:
            _, ttl = cls._read(path)
            if ttl is None:
                return False
            return time.time() - os.path.getmtime(path) > ttl * 3
        except OSError:
            return True

    def delete(self, name):
        path = self._path(name)
        if not os.path.isfile(path):
            raise NameEntryNotFoundError(name)
        os.remove(path)
        stop = self._keepalive_threads.pop(name, None)
        if stop is not None:
            stop.set()
        self._my_keys.pop(name, None)
        # Prune now-empty directories up the tree. Best-effort: a concurrent
        # add may repopulate (ENOTEMPTY) or a sibling delete may win the
        # rmdir race (ENOENT); either just ends the pruning.
        d = os.path.dirname(path)
        try:
            while d != self._root and os.path.isdir(d) and not os.listdir(d):
                os.rmdir(d)
                d = os.path.dirname(d)
        except OSError:
            pass

    def clear_subtree(self, name_root):
        d = os.path.join(self._root, name_root.strip("/"))
        if os.path.isdir(d):
            shutil.rmtree(d, ignore_errors=True)

    def get(self, name):
        path = self._path(name)
        try:
            if self._is_expired(path):
                raise NameEntryNotFoundError(name)
            value, _ = self._read(path)
        except (FileNotFoundError, NotADirectoryError):
            raise NameEntryNotFoundError(name)
        return value

    def find_subtree(self, name_root):
        d = os.path.join(self._root, name_root.strip("/"))
        found = []
        for dirpath, _, filenames in os.walk(d):
            if "ENTRY" in filenames and not self._is_expired(os.path.join(dirpath, "ENTRY")):
                found.append(os.path.relpath(dirpath, self._root))
        return sorted(found)

    def get_subtree(self, name_root):
        out = []
        for k in self.find_subtree(name_root):
            try:
                out.append(self.get(k))
            except NameEntryNotFoundError:
                # Record vanished between listing and read; skip it.
                pass
        return out

    def reset(self):
        for stop in self._keepalive_threads.values():
            stop.set()
        self._keepalive_threads.clear()
        for name in list(self._my_keys):
            try:
                self.delete(name)
            except NameEntryNotFoundError:
                pass
        self._my_keys.clear()


@dataclasses.dataclass
class _DefaultRepo:
    repo: NameRecordRepository = dataclasses.field(default_factory=NfsNameRecordRepository)


_default = _DefaultRepo()


def reconfigure(backend: str = "nfs", **kwargs):
    """Switch the process-global repository backend: 'memory', 'nfs', or
    'kv' (the networked lease service, name_resolve_kv.py — the etcd3
    equivalent for real clusters; kwargs: address="host:port")."""
    if backend == "memory":
        _default.repo = MemoryNameRecordRepository()
    elif backend == "nfs":
        _default.repo = NfsNameRecordRepository(**kwargs)
    elif backend == "kv":
        from areal_tpu.base.name_resolve_kv import KvNameRecordRepository

        _default.repo = KvNameRecordRepository(**kwargs)
    else:
        raise NotImplementedError(f"name_resolve backend: {backend}")
    return _default.repo


def default_repo() -> NameRecordRepository:
    return _default.repo


# Module-level facade mirroring the reference's usage style
# (`name_resolve.add(...)`, `name_resolve.wait(...)`).
def add(name, value, **kwargs):
    return _default.repo.add(name, value, **kwargs)


def add_subentry(name, value, **kwargs):
    return _default.repo.add_subentry(name, value, **kwargs)


def delete(name):
    return _default.repo.delete(name)


def clear_subtree(name_root):
    return _default.repo.clear_subtree(name_root)


def get(name):
    return _default.repo.get(name)


def get_subtree(name_root):
    return _default.repo.get_subtree(name_root)


def find_subtree(name_root):
    return _default.repo.find_subtree(name_root)


def wait(name, timeout=None, poll_frequency=0.1):
    return _default.repo.wait(name, timeout=timeout, poll_frequency=poll_frequency)


def watch_names(names, call_back, poll_frequency=5.0, grace_period=300.0):
    return _default.repo.watch_names(names, call_back, poll_frequency, grace_period)


def reset():
    return _default.repo.reset()

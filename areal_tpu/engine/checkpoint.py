"""Engine state checkpointing (recover checkpoints).

Counterpart of the reference's backend save/load
(realhf/impl/model/backend/megatron.py:711-760: optimizer + param state
for fault recovery; persistent HF-format saves are a separate path via
the interfaces). State = params pytree + optax opt state + step counter.

Two storage backends, selected by AREAL_CKPT_BACKEND (or the `backend`
argument):

- "pickle" (default): numpy-on-host single file per worker. Simple and
  exactly round-trippable, but np.asarray on a GSPMD-sharded array
  gathers the FULL global value to this host — fine single-host, wrong
  at pod scale.
- "orbax": orbax.checkpoint StandardCheckpointer — each host writes only
  its own shards (OCDBT), and restore places shards directly onto the
  engine's NamedShardings without a host gather. The TPU-native path
  for multi-host models.

Loading auto-detects which backend wrote a directory, so the flag only
matters for new saves.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Optional

import jax
import numpy as np

from areal_tpu.base import env_registry, logging

logger = logging.getLogger("checkpoint")

_STATE_FILE = "engine_state.pkl"
_ORBAX_DIR = "engine_state_orbax"


def _to_host(tree: Any) -> Any:
    return jax.tree_util.tree_map(lambda x: np.asarray(x), tree)


def _engine_state(engine):
    # Accessors, not attributes: an offloaded engine keeps params on host
    # (engine.params is None) and get_params/get_opt_state return the
    # host copies without re-occupying HBM.
    params = engine.get_params() if hasattr(engine, "get_params") else engine.params
    opt = (
        engine.get_opt_state()
        if hasattr(engine, "get_opt_state")
        else engine.opt_state
    )
    return params, opt


def _ckpt_backend(backend: Optional[str]) -> str:
    return backend or env_registry.get_str("AREAL_CKPT_BACKEND")


def save_engine_state(engine, save_dir: str, backend: Optional[str] = None):
    os.makedirs(save_dir, exist_ok=True)
    params, opt = _engine_state(engine)
    if _ckpt_backend(backend) == "orbax":
        import orbax.checkpoint as ocp

        # Version rides inside the checkpoint so it commits atomically
        # with the weights (a side file could be torn by a preemption,
        # silently resetting step counters / LR schedule on recovery).
        state = {
            "params": params,
            "opt_state": opt,
            "version": np.asarray(engine.version, dtype=np.int64),
        }
        path = os.path.join(os.path.abspath(save_dir), _ORBAX_DIR)
        # Orbax save is a collective for multi-host GSPMD arrays, but
        # recover checkpoints go to per-worker directories (the model
        # worker's _ckpt_dir embeds the dp rank) — each process saving
        # a collective checkpoint to a DIFFERENT directory hangs or
        # corrupts it. Mirror the _load_orbax guard on the save side.
        for leaf in jax.tree_util.tree_leaves(state):
            if isinstance(leaf, jax.Array) and not leaf.is_fully_addressable:
                raise NotImplementedError(
                    "orbax save of non-fully-addressable (multi-host) "
                    "arrays requires all processes to agree on one "
                    "checkpoint directory; per-worker recover dirs do "
                    "not. Use the pickle backend or a shared directory."
                )
        with ocp.StandardCheckpointer() as ck:
            # Orbax refuses to overwrite; recover checkpoints are
            # overwritable by contract (reference recover ckpts likewise
            # replace the previous one).
            ck.save(path, state, force=True)
        # Each save leaves exactly ONE backend's artifact behind —
        # loading prefers orbax, so a stale dir next to a newer pkl
        # would silently shadow it.
        stale = os.path.join(save_dir, _STATE_FILE)
        if os.path.exists(stale):
            os.remove(stale)
        logger.info(f"saved engine state (orbax) to {save_dir}")
        return
    state = {
        "params": _to_host(params),
        "opt_state": _to_host(opt) if opt is not None else None,
        "version": engine.version,
    }
    tmp = os.path.join(save_dir, f"{_STATE_FILE}.tmp.{os.getpid()}")
    with open(tmp, "wb") as f:
        pickle.dump(state, f, protocol=pickle.HIGHEST_PROTOCOL)
    os.replace(tmp, os.path.join(save_dir, _STATE_FILE))
    stale_dir = os.path.join(save_dir, _ORBAX_DIR)
    if os.path.isdir(stale_dir):
        import shutil

        shutil.rmtree(stale_dir, ignore_errors=True)
    logger.info(f"saved engine state to {save_dir}")


def _load_orbax(engine, path: str) -> dict:
    """Restore directly onto the engine's shardings (no host gather):
    the abstract target carries each leaf's shape/dtype/sharding.

    Multi-host caveat: orbax save/restore of GSPMD-sharded arrays is a
    COLLECTIVE — every process of the jax.distributed world must call
    with the same directory. An offloaded engine (host numpy copies, no
    shardings to target) can only restore single-process."""
    import orbax.checkpoint as ocp

    params, opt = _engine_state(engine)
    shardingless = False

    def absify(x):
        nonlocal shardingless
        sharding = getattr(x, "sharding", None)
        if sharding is not None:
            return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sharding)
        shardingless = True
        return jax.ShapeDtypeStruct(np.shape(x), np.asarray(x).dtype)

    with ocp.StandardCheckpointer() as ck:
        # Target follows what the CHECKPOINT contains, not what this
        # engine has: a params-only checkpoint (gradient-free engine)
        # must load into a training engine and vice versa (the pickle
        # path supports both by construction).
        meta = ck.metadata(path)
        meta_tree = getattr(meta, "item_metadata", None) or meta
        has_opt = False
        try:
            has_opt = (
                meta_tree["opt_state"] is not None
                and len(jax.tree_util.tree_leaves(meta_tree["opt_state"])) > 0
            )
        except (KeyError, TypeError):
            pass
        target = {
            "params": jax.tree_util.tree_map(absify, params),
            "opt_state": (
                jax.tree_util.tree_map(absify, opt)
                if (opt is not None and has_opt)
                else None
            ),
            "version": np.zeros((), dtype=np.int64),
        }
        if shardingless and jax.process_count() > 1:
            raise NotImplementedError(
                "orbax restore into an offloaded engine (host copies, no "
                "shardings) is single-process only; restore to device "
                "first or use the pickle backend"
            )
        # Same guard as the save side: restoring non-fully-addressable
        # (multi-host) arrays is a collective needing ONE shared
        # directory, but recover checkpoints live in per-dp-rank dirs —
        # a mismatched-directory collective hangs or corrupts state.
        for leaf in jax.tree_util.tree_leaves(target):
            sh = getattr(leaf, "sharding", None)
            if sh is not None and not sh.is_fully_addressable:
                raise NotImplementedError(
                    "orbax restore of non-fully-addressable (multi-host) "
                    "arrays requires all processes to agree on one "
                    "checkpoint directory; per-worker recover dirs do "
                    "not. Use the pickle backend or a shared directory."
                )
        state = ck.restore(path, target)
    return {
        "params": state["params"],
        "opt_state": state.get("opt_state"),
        "version": int(state.get("version", 0)),
    }


def load_engine_state(engine, load_dir: str):
    orbax_path = os.path.join(os.path.abspath(load_dir), _ORBAX_DIR)
    if os.path.isdir(orbax_path):
        state = _load_orbax(engine, orbax_path)
    else:
        path = os.path.join(load_dir, _STATE_FILE)
        with open(path, "rb") as f:
            state = pickle.load(f)
    if hasattr(engine, "drop_offloaded_state") and state["opt_state"] is not None:
        # About to overwrite both params and optimizer state: discard any
        # offloaded host copies instead of restoring them to HBM first.
        # A params-only checkpoint must NOT drop offloaded Adam moments —
        # set_params alone keeps the host opt-state copy intact.
        engine.drop_offloaded_state()
    engine.set_params(state["params"])
    opt_shardings = getattr(engine, "_opt_shardings", None)
    if state["opt_state"] is not None and (
        engine.opt_state is not None or opt_shardings is not None
    ):
        # Restore optimizer state with the engine's shardings (prefer the
        # sharding pytree: valid even when opt_state itself is None).
        flat_new, treedef = jax.tree_util.tree_flatten(state["opt_state"])
        if opt_shardings is not None:
            flat_ref = jax.tree_util.tree_leaves(opt_shardings)
            assert len(flat_new) == len(flat_ref), "optimizer state mismatch"
            restored = [
                jax.device_put(n, s) for n, s in zip(flat_new, flat_ref)
            ]
        else:
            flat_ref = jax.tree_util.tree_leaves(engine.opt_state)
            assert len(flat_new) == len(flat_ref), "optimizer state mismatch"
            restored = [
                jax.device_put(n, r.sharding) if hasattr(r, "sharding") else n
                for n, r in zip(flat_new, flat_ref)
            ]
        engine.opt_state = jax.tree_util.tree_unflatten(treedef, restored)
    engine.version = int(state.get("version", 0))
    if hasattr(engine, "_lr_steps"):
        # The LR schedule position for callers that omit version_steps:
        # pre-PR-9 it rode in opt_state's scale_by_schedule count (now a
        # constant unit-LR schedule, see make_optimizer external_lr);
        # resume it at the restored version so a recovery restart does
        # not snap the schedule back to warmup start.
        engine._lr_steps = int(state.get("version", 0))
    logger.info(f"loaded engine state from {load_dir}")


def has_engine_state(load_dir: str) -> bool:
    return os.path.exists(os.path.join(load_dir, _STATE_FILE)) or os.path.isdir(
        os.path.join(load_dir, _ORBAX_DIR)
    )

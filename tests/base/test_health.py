"""Health registry: lease freshness, transitions, graceful stop."""

import time

import pytest

from areal_tpu.base import name_resolve
from areal_tpu.base.health import Heartbeat, HealthRegistry, STALE_FACTOR


@pytest.fixture()
def kv(tmp_path):
    repo = name_resolve.reconfigure(
        "nfs", record_root=str(tmp_path / "name_resolve")
    )
    yield repo
    repo.reset()


EXP, TRIAL = "health-test", "t0"


def test_beat_keeps_member_alive(kv):
    hb = Heartbeat(EXP, TRIAL, "worker/0", payload={"url": "http://x"}, ttl=0.2)
    reg = HealthRegistry(EXP, TRIAL)
    assert "worker/0" in reg.snapshot()
    assert reg.snapshot()["worker/0"]["url"] == "http://x"
    # Keep beating past several TTLs: stays alive.
    for _ in range(4):
        time.sleep(0.1)
        hb.beat()
    assert "worker/0" in reg.snapshot()
    hb.stop()


def test_missed_beats_go_stale(kv):
    hb = Heartbeat(EXP, TRIAL, "worker/1", ttl=0.1)
    reg = HealthRegistry(EXP, TRIAL)
    assert "worker/1" in reg.snapshot()
    time.sleep(0.1 * STALE_FACTOR + 0.15)  # no beats
    assert "worker/1" not in reg.snapshot()
    # The record still exists (no TTL deletion) — staleness is judged
    # from the value, so any backend behaves identically.
    hb.beat(force=True)
    assert "worker/1" in reg.snapshot()
    hb.stop()


def test_transition_callbacks(kv):
    dead, alive = [], []
    reg = HealthRegistry(
        EXP, TRIAL,
        on_dead=lambda m, r: dead.append(m),
        on_alive=lambda m, r: alive.append(m),
    )
    hb = Heartbeat(EXP, TRIAL, "worker/2", ttl=0.1)
    reg.poll()
    assert alive == ["worker/2"] and dead == []
    time.sleep(0.1 * STALE_FACTOR + 0.15)
    reg.poll()
    assert dead == ["worker/2"]
    hb.beat(force=True)
    reg.poll()
    assert alive == ["worker/2", "worker/2"]
    hb.stop()


def test_graceful_stop_is_departure_not_death(kv):
    hb = Heartbeat(EXP, TRIAL, "worker/3", ttl=10.0)
    reg = HealthRegistry(EXP, TRIAL)
    assert "worker/3" in reg.snapshot()
    hb.stop()
    # Leaves the live set immediately, but is flagged as stopped so
    # supervisors don't treat it as a crash.
    assert "worker/3" not in reg.snapshot()
    assert "worker/3" in reg.stopped_members()


def test_prefix_scopes_the_view(kv):
    a = Heartbeat(EXP, TRIAL, "generation_server/0",
                  payload={"url": "http://a"}, ttl=5.0)
    b = Heartbeat(EXP, TRIAL, "rollout_worker/0", ttl=5.0)
    scoped = HealthRegistry(EXP, TRIAL, prefix="generation_server")
    assert set(scoped.snapshot()) == {"generation_server/0"}
    full = HealthRegistry(EXP, TRIAL)
    assert set(full.snapshot()) == {"generation_server/0", "rollout_worker/0"}
    a.stop()
    b.stop()

"""Model and backend factories wired into the registries.

Counterpart of the reference's registered models/backends
(realhf/impl/model/__init__.py, realhf/impl/model/backend/megatron.py:761,
inference.py:230, mock_train.py:240): `make_model("tpu_transformer")`
builds params (random init or HF checkpoint), and the backends wrap them
into engines — "jax_train" (optax + GSPMD), "jax_inference"
(gradient-free), and "mock_train"/"mock_inference" (compute-free engines
for CPU control-plane tests).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

import jax
import numpy as np

from areal_tpu.api import data_api
from areal_tpu.api.config import ModelName
from areal_tpu.api.data_api import MicroBatchSpec, SequenceSample
from areal_tpu.api.model_api import (
    FinetuneSpec,
    GenerationHyperparameters,
    Model,
    ModelBackend,
    TrainEngine,
    register_backend,
    register_model,
)
from areal_tpu.base import logging, seeding
from areal_tpu.engine.jax_engine import JaxTrainEngine
from areal_tpu.engine.optimizer import OptimizerConfig
from areal_tpu.models.config import TransformerConfig
from areal_tpu.models.transformer import init_params
from areal_tpu.parallel.mesh import make_mesh, single_device_mesh
from areal_tpu.base.topology import MeshSpec

logger = logging.getLogger("factories")


def _build_mesh(mesh_spec: Optional[str], device_ids: Optional[List[int]] = None):
    devices = jax.devices()
    if device_ids is not None:
        devices = [devices[i] for i in device_ids]
    if mesh_spec is None:
        return single_device_mesh(devices[0])
    return make_mesh(MeshSpec.parse(mesh_spec), devices)


def make_transformer_model(
    name: ModelName | str = "default",
    tokenizer_path: Optional[str] = None,
    model_path: Optional[str] = None,
    config: Optional[Dict[str, Any]] = None,
    is_critic: bool = False,
    mesh_spec: Optional[str] = None,
    device_ids: Optional[List[int]] = None,
    hf_family: Optional[str] = None,
    dtype: str = "bfloat16",
    init_seed: int = 1,
) -> Model:
    """Build a Model whose raw params/config are stashed for the backend.

    Either `model_path` (HF checkpoint dir; config+weights+family inferred)
    or `config` (TransformerConfig kwargs, random init) must be given.
    """
    if isinstance(name, str):
        name = ModelName.parse(name)
    mesh = _build_mesh(mesh_spec, device_ids)
    if model_path is not None:
        from areal_tpu.models.hf import family_from_hf_config, load_hf_config, load_hf_model

        if hf_family is None:
            hf_family = family_from_hf_config(load_hf_config(model_path)).name
        cfg, params = load_hf_model(model_path, is_critic=is_critic, family=hf_family)
        tokenizer_path = tokenizer_path or model_path
    else:
        assert config is not None, "need model_path or config"
        cfg = TransformerConfig(**{**config, "is_critic": is_critic})
        rng = jax.random.fold_in(
            jax.random.PRNGKey(init_seed), seeding._hash_key(f"model_init/{name}")
        )
        params = init_params(cfg, rng)
    tokenizer = (
        data_api.load_hf_tokenizer(tokenizer_path) if tokenizer_path else None
    )
    model = Model(name=name, module=None, tokenizer=tokenizer)
    model._raw = dict(  # consumed by backends
        cfg=cfg, params=params, mesh=mesh, hf_family=hf_family, dtype=dtype
    )
    return model


register_model("tpu_transformer", make_transformer_model)


@dataclasses.dataclass
class JaxTrainBackend(ModelBackend):
    """Wraps a model into a training JaxTrainEngine (reference
    MegatronTrainBackend, backend/megatron.py:561)."""

    optimizer: OptimizerConfig = dataclasses.field(default_factory=OptimizerConfig)
    attn_impl: str = "auto"
    remat: bool = True
    row_len_multiple: int = 128
    max_row_len: Optional[int] = None
    # Overlapped input pipeline depth (0 = eager) and packed-stats fetch
    # cadence — see JaxTrainEngine.
    prefetch_depth: int = 2
    stats_fetch_interval: int = 1

    def __post_init__(self):
        if isinstance(self.optimizer, dict):
            self.optimizer = OptimizerConfig(**self.optimizer)

    def initialize(self, model: Model, spec: FinetuneSpec) -> Model:
        raw = model._raw
        model.module = JaxTrainEngine(
            model_cfg=raw["cfg"],
            params=raw["params"],
            mesh=raw["mesh"],
            optimizer_config=self.optimizer,
            total_train_steps=max(1, spec.total_train_steps),
            attn_impl=self.attn_impl,
            remat=self.remat,
            row_len_multiple=self.row_len_multiple,
            max_row_len=self.max_row_len,
            hf_family=raw.get("hf_family"),
            prefetch_depth=self.prefetch_depth,
            stats_fetch_interval=self.stats_fetch_interval,
        )
        model.ft_spec = spec
        return model

    def save(self, model: Model, save_dir: str):
        from areal_tpu.engine.checkpoint import save_engine_state

        save_engine_state(model.module, save_dir)

    def load(self, model: Model, load_dir: str):
        from areal_tpu.engine.checkpoint import load_engine_state

        load_engine_state(model.module, load_dir)


@dataclasses.dataclass
class JaxInferenceBackend(JaxTrainBackend):
    """Gradient-free engine for ref/reward models (reference
    PipelinableInferenceEngine, backend/inference.py:25)."""

    def initialize(self, model: Model, spec: FinetuneSpec) -> Model:
        raw = model._raw
        model.module = JaxTrainEngine(
            model_cfg=raw["cfg"],
            params=raw["params"],
            mesh=raw["mesh"],
            optimizer_config=None,
            attn_impl=self.attn_impl,
            remat=False,
            row_len_multiple=self.row_len_multiple,
            max_row_len=self.max_row_len,
            hf_family=raw.get("hf_family"),
            prefetch_depth=self.prefetch_depth,
            stats_fetch_interval=self.stats_fetch_interval,
        )
        model.ft_spec = spec
        return model


register_backend("jax_train", JaxTrainBackend)
register_backend("jax_inference", JaxInferenceBackend)


class MockEngine(TrainEngine):
    """Compute-free engine for control-plane tests (reference
    MockTrainEngine, backend/mock_train.py). Deterministic, shape-correct
    outputs with no device work."""

    def __init__(self, seed: int = 0, vocab_size: int = 128):
        self.seed = seed
        self.vocab_size = vocab_size
        self.version = 0
        self.n_train_calls = 0

    def train_batch(self, input_, mb_spec, loss_fn, loss_weight_fn,
                    token_normalize_scope="global", version_steps=0,
                    loss_name="loss"):
        self.n_train_calls += 1
        self.version += 1
        return {
            f"{loss_name}/loss": 1.0 / self.n_train_calls,
            f"{loss_name}/n_tokens": float(input_.total_seqlen()),
        }

    def forward(self, input_, mb_spec, output_key="logprobs", post_hook=None):
        key = input_._main_key()
        seqlens = input_.seqlens[key]
        total = sum(sum(sl) for sl in seqlens)
        rng = np.random.RandomState(self.seed + total)
        data = rng.uniform(-1, 0, size=(total,)).astype(np.float32)
        return SequenceSample(
            ids=list(input_.ids),
            keys={output_key},
            data={output_key: data},
            seqlens={output_key: [list(sl) for sl in seqlens]},
        )

    def generate(self, input_, mb_spec, tokenizer, gconfig: GenerationHyperparameters):
        key = "packed_prompts" if "packed_prompts" in input_.keys else input_._main_key()
        plens = [sum(sl) for sl in input_.seqlens[key]]
        outs = []
        rng = np.random.RandomState(self.seed + sum(plens))
        for pl in plens:
            for _ in range(gconfig.n):
                glen = int(rng.randint(1, max(2, gconfig.max_new_tokens)))
                outs.append(
                    dict(
                        output_ids=rng.randint(0, self.vocab_size, size=glen).tolist(),
                        output_logprobs=(-rng.uniform(0, 1, size=glen)).astype(np.float32),
                        no_eos=bool(rng.rand() < 0.2),
                    )
                )
        return outs

    def get_params(self):
        return {}

    def set_params(self, params):
        pass


@dataclasses.dataclass
class MockTrainBackend(ModelBackend):
    seed: int = 0
    vocab_size: int = 128

    def initialize(self, model: Model, spec: FinetuneSpec) -> Model:
        model.module = MockEngine(seed=self.seed, vocab_size=self.vocab_size)
        model.ft_spec = spec
        return model


register_backend("mock_train", MockTrainBackend)
register_backend("mock_inference", MockTrainBackend)

"""Pooled reward-executor tests (ISSUE 18): warm worker reuse, rlimit
containment, timeout kill + respawn, bounded-queue shed, chaos-point
failure shapes, and client failover across a real executor death."""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import pytest

from areal_tpu.base import name_resolve, names
from areal_tpu.base.fault_injection import faults
from areal_tpu.functioncall.remote import ExecutorPoolClient
from areal_tpu.system.reward_executor import RewardExecutorService, WorkerPool

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@pytest.fixture
def pool():
    p = WorkerPool(n_workers=1)
    yield p
    p.close()


class TestWorkerPool:
    def test_warm_reuse_same_pid(self, pool):
        r1 = pool.submit([{"kind": "ping"}])[0]
        r2 = pool.submit([{"kind": "ping"}])[0]
        assert r1["ok"] and r2["ok"]
        # The SAME warm subprocess served both jobs — no per-call spawn.
        assert r1["pid"] == r2["pid"]
        assert r2["reuse"] > r1["reuse"]
        assert pool.counters["warm_hits"] >= 1
        assert pool.counters["worker_respawns"] == 0

    def test_python_job_stdout_stdin(self, pool):
        res = pool.submit([
            {"kind": "python",
             "code": "import sys; print(int(sys.stdin.read()) * 2)",
             "stdin": "21"},
        ])[0]
        assert res["ok"], res
        assert "42" in res["stdout"]

    def test_failed_python_job_is_result_not_raise(self, pool):
        res = pool.submit([{"kind": "python", "code": "1/0"}])[0]
        assert not res["ok"]
        assert "ZeroDivisionError" in res.get("stderr", "") + res.get(
            "error", ""
        )
        # The worker survives a guarded-exec failure (no respawn).
        assert pool.counters["worker_respawns"] == 0
        assert pool.submit([{"kind": "ping"}])[0]["ok"]

    def test_timeout_kills_and_respawns(self, pool):
        t0 = time.monotonic()
        res = pool.submit(
            [{"kind": "python", "code": "import time; time.sleep(60)"}],
            timeout_s=0.5,
        )[0]
        assert time.monotonic() - t0 < 10.0
        assert not res["ok"] and res.get("timeout"), res
        assert pool.counters["timeouts"] == 1
        assert pool.counters["worker_respawns"] == 1
        # A fresh warm worker replaced the killed one.
        assert pool.submit([{"kind": "ping"}])[0]["ok"]

    def test_rlimit_contains_oom(self):
        p = WorkerPool(n_workers=1, mem_mb=128)
        try:
            res = p.submit([
                {"kind": "python", "code": "x = bytearray(1 << 30)"},
            ])[0]
            assert not res["ok"], res
            assert p.submit([{"kind": "ping"}])[0]["ok"]
        finally:
            p.close()

    def test_sympy_equal_job(self, pool):
        eq = pool.submit(
            [{"kind": "sympy_equal", "a": "x + x", "b": "2*x"},
             {"kind": "sympy_equal", "a": "x + 1", "b": "x + 2"}],
            timeout_s=30.0,
        )
        assert eq[0]["ok"] and eq[0]["equal"] is True
        assert eq[1]["ok"] and eq[1]["equal"] is False

    def test_chaos_case_comes_back_as_failed_result(self, pool):
        faults.reset()
        faults.arm("rexec.case", "raise")
        try:
            res = pool.submit([{"kind": "ping"}])[0]
            assert not res["ok"]
            assert "case fault" in res["error"]
            # One-shot arm: the pool is healthy again afterwards.
            assert pool.submit([{"kind": "ping"}])[0]["ok"]
        finally:
            faults.reset()


class TestServiceHTTP:
    def _post(self, url, payload, timeout=60.0):
        req = urllib.request.Request(
            url + "/rexec/submit", json.dumps(payload).encode(),
            {"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return json.loads(r.read())

    def test_submit_metrics_health_and_shed(self):
        name_resolve.reconfigure("memory")
        svc = RewardExecutorService(
            "rexec-ut", "t0", executor_id=0, n_workers=1, queue_max=2,
        )
        url = svc.start()
        try:
            out = self._post(
                url, {"jobs": [{"kind": "python", "code": "print(7)"}]}
            )
            assert out["results"][0]["ok"]
            with urllib.request.urlopen(url + "/health", timeout=10) as r:
                h = json.loads(r.read())
            assert h["status"] == "ok" and h["workers_alive"] >= 1

            # Saturate the 1-worker pool past queue_max=2 with slow
            # jobs from concurrent submitters: 429s with Retry-After.
            slow = {"kind": "python",
                    "code": "import time; time.sleep(0.3); print(1)"}
            codes = []

            def fire():
                try:
                    self._post(url, {"jobs": [slow, slow]})
                    codes.append(200)
                except urllib.error.HTTPError as e:
                    codes.append(e.code)
                    if e.code == 429:
                        assert e.headers.get("Retry-After") is not None

            ts = [threading.Thread(target=fire) for _ in range(6)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            assert 429 in codes, codes
            with urllib.request.urlopen(url + "/metrics", timeout=10) as r:
                text = r.read().decode()
            metrics = dict(
                line.split() for line in text.splitlines() if line
            )
            assert float(metrics["areal:rexec_shed_total"]) >= 1
            assert float(metrics["areal:rexec_jobs_total"]) >= 1
            assert float(metrics["areal:rexec_workers_alive"]) >= 1
        finally:
            svc.stop()

    def test_expired_deadline_sheds(self):
        name_resolve.reconfigure("memory")
        svc = RewardExecutorService(
            "rexec-dl", "t0", executor_id=0, n_workers=1,
        )
        url = svc.start()
        try:
            req = urllib.request.Request(
                url + "/rexec/submit",
                json.dumps({"jobs": [{"kind": "ping"}]}).encode(),
                # The wire deadline is REMAINING seconds; 0 = expired.
                {"Content-Type": "application/json",
                 "X-Areal-Deadline": "0"},
            )
            with pytest.raises(urllib.error.HTTPError) as ei:
                urllib.request.urlopen(req, timeout=10)
            assert ei.value.code == 429
        finally:
            svc.stop()


def _spawn_executor(idx, exp, trial, nr_root, extra_env=None):
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    env["AREAL_HEALTH_TTL"] = "2"
    env.update(extra_env or {})
    return subprocess.Popen(
        [sys.executable, "-m", "areal_tpu.system.reward_executor",
         "--experiment", exp, "--trial", trial, "--index", str(idx),
         "--workers", "1", "--name-resolve-root", nr_root],
        env=env, cwd=REPO,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


def test_client_fails_over_when_executor_dies(tmp_path, monkeypatch):
    """The executor-death chaos arm: two REAL executor subprocesses, one
    armed to die (`rexec.die` via AREAL_FAULTS) on its first submit. The
    client's retry loop must re-discover and land the batch on the
    survivor — failed RESULTS never reach the caller."""
    monkeypatch.setenv("AREAL_HEALTH_TTL", "2")
    nr_root = str(tmp_path / "nr")
    name_resolve.reconfigure("nfs", record_root=nr_root)
    exp, trial = "rexec-chaos", "t0"
    procs = [
        _spawn_executor(
            0, exp, trial, nr_root,
            {"AREAL_FAULTS": "rexec.die=die"},
        ),
        _spawn_executor(1, exp, trial, nr_root),
    ]
    try:
        deadline = time.monotonic() + 60
        urls = {}
        while len(urls) < 2 and time.monotonic() < deadline:
            for i in range(2):
                try:
                    urls[i] = name_resolve.get(
                        names.reward_executor_url(exp, trial, str(i))
                    )
                except name_resolve.NameEntryNotFoundError:
                    pass
            time.sleep(0.2)
        assert len(urls) == 2, "executors never registered"

        client = ExecutorPoolClient(exp, trial)
        # Round-robin starts somewhere; submit twice so executor 0 is
        # guaranteed to be hit (and die) within the first batch's retry
        # loop or the second's.
        for k in range(2):
            res = client.submit(
                [{"kind": "python", "code": f"print({k} + 40)"}],
                timeout_s=20.0,
            )[0]
            assert res["ok"], res
        # The armed executor really died (chaos engaged, not skipped).
        assert procs[0].wait(timeout=30) is not None
        assert procs[1].poll() is None
        # Steady state after the death: the survivor serves alone.
        res = client.submit([{"kind": "ping"}])[0]
        assert res["ok"], res
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
        name_resolve.reconfigure("memory")

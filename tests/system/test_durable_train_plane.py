"""Kill-anywhere recovery e2e (ISSUE 16 tentpole): SIGKILL-equivalent
faults (`die` = os._exit) at every durable-plane point — WAL append,
buffer consume, checkpoint manifest commit — while a live pusher keeps
feeding samples, then a clean incarnation finishes the run.

The trainer child (tests/system/durable_harness.py) folds the integer in
each sample id, so exactly-once is ONE equality at the end: the fold sum
over n samples trained exactly once is n*(n-1)/2. Any loss or duplicate
across any kill shifts it. The parent plays the rollout side with a
single ack-enabled pusher surviving all child incarnations: unacked
samples are redelivered to each restarted puller, and the child's
WAL + seq ledger must make that redelivery storm invisible to training.
"""

import json
import os
import subprocess
import sys
import time
import uuid

import numpy as np
import pytest

from areal_tpu.api.data_api import SequenceSample, sample_to_json
from areal_tpu.base import name_resolve, recover
from areal_tpu.system import push_pull_stream as pps
from tests import fixtures

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
HARNESS = os.path.join(REPO, "tests", "system", "durable_harness.py")

pytestmark = [pytest.mark.serial, pytest.mark.chaos]

N_TOTAL = 24
BATCH = 4

# One incarnation per fault point, then a clean run to drain. k values
# are chosen so each incarnation makes SOME progress before dying (the
# interesting recoveries are mid-stream, not at-start).
KILL_PLAN = [
    "buffer.wal_append=die:k=5",
    "buffer.consume=die:k=2",
    "train.checkpoint=die:k=2",
    "",
]


def _payloads():
    out = []
    for i in range(N_TOTAL):
        s = SequenceSample.from_default(
            ids=[f"s{i}"], seqlens=[4],
            data={"packed_prompts": np.arange(4, dtype=np.int32)},
        )
        out.append(sample_to_json(s))
    return out


def _progress_events(path):
    """Torn-tolerant JSONL parse — the child can die mid-write."""
    events = []
    if not os.path.exists(path):
        return events
    with open(path) as f:
        for line in f:
            try:
                events.append(json.loads(line))
            except ValueError:
                continue
    return events


@pytest.mark.timeout(900)
def test_kill_anywhere_trains_every_sample_exactly_once(tmp_path, monkeypatch):
    nr = str(tmp_path / "nr")
    recover_root = str(tmp_path / "recover")
    exp, trial = f"durable-{uuid.uuid4().hex[:6]}", "t0"
    name_resolve.reconfigure("nfs", record_root=nr)

    spec = {
        "nr_root": nr,
        "exp": exp,
        "trial": trial,
        "ckpt_root": str(tmp_path / "ckpt"),
        "recover_root": recover_root,
        "progress_path": str(tmp_path / "progress.jsonl"),
        "result_path": str(tmp_path / "result.json"),
        "n_total": N_TOTAL,
        "batch": BATCH,
        "ckpt_every": 1,
    }
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = ""
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["AREAL_WAL"] = "1"
    env["AREAL_CKPT_ASYNC"] = "1"
    env["AREAL_CKPT_BACKEND"] = "pickle"
    env["AREAL_WAL_FSYNC_MS"] = "5"
    env.pop("AREAL_FAULTS", None)

    payloads = _payloads()
    n_pushed = 0
    pusher = None
    exits = []
    logs = []
    try:
        for incarnation, faults_spec in enumerate(KILL_PLAN):
            child_env = dict(env)
            if faults_spec:
                child_env["AREAL_FAULTS"] = faults_spec
            log_path = tmp_path / f"child{incarnation}.log"
            logs.append(log_path)
            with open(log_path, "w") as log_f:
                proc = subprocess.Popen(
                    [sys.executable, HARNESS, json.dumps(spec)],
                    env=child_env, cwd=REPO,
                    stdout=log_f, stderr=subprocess.STDOUT,
                )
            try:
                if pusher is None:
                    # Blocks until the first incarnation's puller
                    # registers; later incarnations re-register the same
                    # name and re_resolve() below follows them.
                    pusher = pps.NameResolvingZmqPusher(
                        exp, trial, pusher_index=0, n_pushers=1,
                        n_pullers=1, ack=True,
                    )
                deadline = time.monotonic() + fixtures.scale_timeout(180)
                while proc.poll() is None:
                    assert time.monotonic() < deadline, (
                        f"incarnation {incarnation} "
                        f"({faults_spec or 'clean'}) hung:\n"
                        + log_path.read_text()[-3000:]
                    )
                    while n_pushed < len(payloads):
                        pusher.push(payloads[n_pushed], seq=f"p0/{n_pushed}")
                        n_pushed += 1
                    pusher.drain_acks()
                    if pusher.unacked():
                        # Follow the (possibly restarted) puller, then
                        # re-send anything unacked past the timeout —
                        # the child's dedup must absorb the storm.
                        pusher.re_resolve(timeout=0.2)
                        pusher.redeliver(timeout_s=0.5)
                    time.sleep(0.05)
            finally:
                if proc.poll() is None:
                    proc.kill()
            exits.append(proc.returncode)
            if os.path.exists(spec["result_path"]):
                break

        # The three fault'd incarnations died; the clean one finished.
        assert len(exits) == len(KILL_PLAN), exits
        assert all(code != 0 for code in exits[:-1]), (exits, KILL_PLAN)
        assert exits[-1] == 0, (
            exits, logs[-1].read_text()[-3000:]
        )

        with open(spec["result_path"]) as f:
            result = json.load(f)

        # THE invariant: every sample trained exactly once, across three
        # kills, redelivery, and WAL replay.
        assert result["count"] == N_TOTAL
        assert result["fold_sum"] == float(sum(range(N_TOTAL)))
        # The duplicate-consumption DETECTOR (not the prevention
        # counters) must be zero.
        assert result["duplicated_total"] == 0

        # Transport: nothing dropped from the unacked window.
        assert pusher.counters["areal:train_samples_lost_total"] == 0

        # Recovery actually happened: later incarnations resumed from
        # journaled state (this fails if the WAL silently lost its job).
        events = _progress_events(spec["progress_path"])
        resumes = [e for e in events if e["event"] == "resume"]
        assert len(resumes) == len(exits)
        assert resumes[0]["count"] == 0
        assert sum(e["replayed"] for e in resumes) > 0
        assert all(
            e["dup"] == 0 for e in events if e["event"] == "barrier"
        )

        # The recover record rides the same snapshot discipline.
        from areal_tpu.base import constants

        monkeypatch.setattr(constants, "RECOVER_ROOT", recover_root)
        info = recover.load(exp, trial)
        assert info.last_step_info.global_step == result["version"]
        water = (info.consumed_seqs or {}).get("water", {})
        assert water.get("p0") == N_TOTAL - 1  # ledger covers every seq
    finally:
        if pusher is not None:
            pusher.close()

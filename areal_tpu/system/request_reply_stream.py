"""Master <-> model-worker messaging over ZMQ with name_resolve discovery.

Counterpart of the reference's request-reply stream
(realhf/system/request_reply_stream.py:47-446). Protocol shape is kept:
the master posts a request `Payload`, the worker immediately acknowledges
it with a `syn` frame (so the master knows the worker is alive and has
ordered the request), and later posts the actual reply. Payloads carry
only metadata + small host arrays; bulk tensors move through the data
manager, not through this stream.

Sockets: every participant binds one PULL socket (its inbox) and keeps
lazily-connected PUSH sockets to its peers' inboxes. Addresses are
registered under `names.request_reply_stream` in name_resolve.
"""

from __future__ import annotations

import dataclasses
import pickle
import time
import uuid
import zlib
from typing import Any, Dict, Hashable, List, Optional

import zmq

from areal_tpu.base import logging, name_resolve, names, network, tracing

logger = logging.getLogger("request_reply_stream")

ZMQ_IO_THREADS = 1
# Compress payloads above this many pickled bytes (reference compresses all;
# small control frames are cheaper uncompressed).
_COMPRESS_THRESHOLD = 16 * 1024


class NoMessage(Exception):
    pass


@dataclasses.dataclass
class Payload:
    """One message on the stream.

    handler: destination peer name (e.g. 'model_worker/3' or 'master').
    handle_name: what to do ('train_step', 'inference', 'generate',
        'fetch', 'spec', 'initialize', 'model_config', 'clear_data_cache',
        'flush', 'save', 'evaluate', ...).
    request_id: unique id; replies echo it.
    syn_reply_id: id under which the receiver posts the syn ack.
    data: arbitrary pickled payload (metadata / host numpy arrays).
    pre_hooks/post_hooks: hook dicts executed around the main handler.
    """

    handler: str = ""
    handle_name: str = ""
    request_id: str = ""
    syn_reply_id: str = ""
    sender: str = ""
    data: Any = None
    pre_hooks: List[Dict] = dataclasses.field(default_factory=list)
    post_hooks: List[Dict] = dataclasses.field(default_factory=list)
    no_syn: bool = True
    send_time: float = 0.0
    # RL-trace context (base/tracing.inject()): stamped by post() when
    # tracing is on so receivers parent their spans under the sender's
    # (e.g. an MFC request under the master's train-step span).
    trace_ctx: Optional[Dict] = None

    def __post_init__(self):
        if not self.request_id:
            self.request_id = str(uuid.uuid4())
        if not self.syn_reply_id:
            self.syn_reply_id = str(uuid.uuid4())


def _encode(payload: Payload) -> List[bytes]:
    raw = pickle.dumps(payload, protocol=pickle.HIGHEST_PROTOCOL)
    if len(raw) > _COMPRESS_THRESHOLD:
        return [b"z", zlib.compress(raw, level=1)]
    return [b"r", raw]


def _decode(frames: List[bytes]) -> Payload:
    tag, raw = frames
    if tag == b"z":
        raw = zlib.decompress(raw)
    return pickle.loads(raw)


class _Peer:
    """A bound PULL inbox + lazily connected PUSH sockets to other peers."""

    def __init__(self, experiment_name: str, trial_name: str, peer_name: str):
        self.experiment_name = experiment_name
        self.trial_name = trial_name
        self.peer_name = peer_name
        self._ctx = zmq.Context.instance(ZMQ_IO_THREADS)
        self._recv = self._ctx.socket(zmq.PULL)
        self._recv.setsockopt(zmq.LINGER, 0)
        host_ip = network.gethostip()
        port = self._recv.bind_to_random_port(f"tcp://{host_ip}")
        self.address = f"{host_ip}:{port}"
        name_resolve.add(
            names.request_reply_stream(experiment_name, trial_name, peer_name),
            self.address,
            keepalive_ttl=60,
            replace=True,
        )
        self._send_sockets: Dict[str, zmq.Socket] = {}

    def _peer_address(self, peer: str) -> str:
        key = names.request_reply_stream(self.experiment_name, self.trial_name, peer)
        return name_resolve.wait(key, timeout=60)

    def _send_socket(self, peer: str) -> zmq.Socket:
        if peer not in self._send_sockets:
            sock = self._ctx.socket(zmq.PUSH)
            sock.setsockopt(zmq.LINGER, 0)
            sock.connect(f"tcp://{self._peer_address(peer)}")
            self._send_sockets[peer] = sock
        return self._send_sockets[peer]

    def post(self, payload: Payload) -> str:
        payload.sender = self.peer_name
        payload.send_time = time.monotonic()
        if payload.trace_ctx is None:
            payload.trace_ctx = tracing.inject()
        self._send_socket(payload.handler).send_multipart(_encode(payload))
        return payload.request_id

    def poll(self, block: bool = False, timeout_ms: int = 100) -> Payload:
        if block:
            if not self._recv.poll(timeout_ms):
                raise NoMessage()
        else:
            if not self._recv.poll(0):
                raise NoMessage()
        return _decode(self._recv.recv_multipart())

    def close(self):
        key = names.request_reply_stream(
            self.experiment_name, self.trial_name, self.peer_name
        )
        try:
            name_resolve.delete(key)
        except name_resolve.NameEntryNotFoundError:
            pass
        self._recv.close()
        for s in self._send_sockets.values():
            s.close()


class NameResolvingRequestClient:
    """The master's end: post requests to workers, gather replies.

    Mirrors reference NameResolvingRequestClient
    (realhf/system/request_reply_stream.py:78): request() returns ids,
    poll()/poll_batched() collect replies, call() is the blocking
    convenience used for configuration RPCs.
    """

    def __init__(self, experiment_name: str, trial_name: str, name: str = "master"):
        self._peer = _Peer(experiment_name, trial_name, name)
        self.name = name
        self._reply_cache: Dict[str, Payload] = {}
        self._syn_cache: Dict[str, Payload] = {}

    @property
    def address(self) -> str:
        return self._peer.address

    def post(self, payload: Payload) -> str:
        return self._peer.post(payload)

    def request(
        self,
        handlers: List[str],
        handle_type: str,
        datas: Optional[List[Any]] = None,
        no_syn: bool = True,
        pre_hooks: Optional[List[List[Dict]]] = None,
        post_hooks: Optional[List[List[Dict]]] = None,
    ) -> List[str]:
        if datas is None:
            datas = [None for _ in handlers]
        if len(datas) != len(handlers):
            raise ValueError(
                f"{len(handlers)} handlers but {len(datas)} datas"
            )
        ids = []
        for i, (h, d) in enumerate(zip(handlers, datas)):
            p = Payload(
                handler=h,
                handle_name=handle_type,
                data=d,
                no_syn=no_syn,
                pre_hooks=list(pre_hooks[i]) if pre_hooks else [],
                post_hooks=list(post_hooks[i]) if post_hooks else [],
            )
            ids.append(self.post(p))
        return ids

    def _drain(self, block: bool, timeout_ms: int = 100):
        try:
            while True:
                p = self._peer.poll(block=block, timeout_ms=timeout_ms)
                block = False
                if p.handle_name == "syn":
                    self._syn_cache[p.request_id] = p
                else:
                    self._reply_cache[p.request_id] = p
        except NoMessage:
            pass

    def poll(self, request_id: str, block: bool = False, timeout: Optional[float] = None) -> Payload:
        """Fetch the reply for one request id."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            if request_id in self._reply_cache:
                return self._reply_cache.pop(request_id)
            self._drain(block=block)
            if request_id in self._reply_cache:
                return self._reply_cache.pop(request_id)
            if not block:
                raise NoMessage()
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f"no reply for request {request_id}")

    def await_syn(self, request_id: str, timeout: float = 60.0) -> Payload:
        deadline = time.monotonic() + timeout
        while request_id not in self._syn_cache:
            self._drain(block=True)
            if time.monotonic() > deadline:
                raise TimeoutError(f"no syn for request {request_id}")
        return self._syn_cache.pop(request_id)

    def gather(self, request_ids: List[str], timeout: Optional[float] = None) -> List[Payload]:
        return [self.poll(rid, block=True, timeout=timeout) for rid in request_ids]

    def call(
        self,
        handlers: List[str],
        handle_type: str,
        datas: Optional[List[Any]] = None,
        timeout: Optional[float] = None,
    ) -> List[Any]:
        """Blocking request → gather; returns reply datas in handler order."""
        ids = self.request(handlers, handle_type, datas)
        return [p.data for p in self.gather(ids, timeout=timeout)]

    def close(self):
        self._peer.close()


class NameResolvingReplyServer:
    """A worker's end: poll requests, send syn acks and replies.

    Mirrors reference NameResolvingReplyServer
    (realhf/system/request_reply_stream.py:351).
    """

    def __init__(self, experiment_name: str, trial_name: str, name: str, master_name: str = "master"):
        self._peer = _Peer(experiment_name, trial_name, name)
        self.name = name
        self.master_name = master_name

    @property
    def address(self) -> str:
        return self._peer.address

    def poll(self, block: bool = False, timeout_ms: int = 100) -> Payload:
        p = self._peer.poll(block=block, timeout_ms=timeout_ms)
        if not p.no_syn:
            self._peer.post(
                Payload(
                    handler=p.sender,
                    handle_name="syn",
                    request_id=p.request_id,
                    data=None,
                )
            )
        return p

    def post(self, reply: Payload):
        self._peer.post(reply)

    def reply_to(self, request: Payload, data: Any, handle_name: str = "reply"):
        self.post(
            Payload(
                handler=request.sender or self.master_name,
                handle_name=handle_name,
                request_id=request.request_id,
                data=data,
            )
        )

    def close(self):
        self._peer.close()


def make_master_stream(experiment_name: str, trial_name: str, name: str = "master") -> NameResolvingRequestClient:
    return NameResolvingRequestClient(experiment_name, trial_name, name)


def make_worker_stream(
    experiment_name: str, trial_name: str, name: str
) -> NameResolvingReplyServer:
    return NameResolvingReplyServer(experiment_name, trial_name, name)

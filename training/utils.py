"""Experiment runner with a fault-tolerant relaunch loop.

Counterpart of the reference's launcher (realhf/apps/main.py:77-289 +
training/utils.py): run the experiment via the LocalController; on
worker/master failure, relaunch with recover_mode=auto up to
`recover_retries` times, resuming from the last recover checkpoint.
"""

from __future__ import annotations

import argparse
import dataclasses
import sys
import time
from typing import Optional, Type

from areal_tpu.api.cli_args import apply_overrides
from areal_tpu.base import constants, logging, name_resolve
from areal_tpu.experiments import make_experiment
from areal_tpu.system.controller import LocalController

logger = logging.getLogger("launcher")


def parse_args(cfg_cls: Type, argv=None):
    parser = argparse.ArgumentParser(
        description=f"areal_tpu launcher ({cfg_cls.__name__}). "
        "Overrides: dotted key=value pairs, e.g. actor.path=/ckpt lr=1e-5",
    )
    parser.add_argument("overrides", nargs="*", help="a.b.c=value overrides")
    parser.add_argument(
        "--help-config",
        action="store_true",
        help="list every dotted override path with type/default/help "
        "(the Hydra --help surface of the reference)",
    )
    args = parser.parse_args(argv)
    cfg = cfg_cls()
    if args.help_config:
        from areal_tpu.api.cli_args import format_options

        print(format_options(cfg))
        sys.exit(0)
    apply_overrides(cfg, args.overrides)
    return cfg


def run_experiment(experiment_type: str, cfg, worker_env: Optional[dict] = None) -> dict:
    """Build + run, relaunching with recovery on failure
    (reference apps/main.py:236-289)."""
    name_resolve_cfg = {"backend": cfg.name_resolve_backend}
    if cfg.name_resolve_root:
        name_resolve_cfg["record_root"] = cfg.name_resolve_root
    constants.set_experiment_trial_names(cfg.experiment_name, cfg.trial_name)

    # Propagate a JAX platform override into the worker bootstrap: env
    # vars alone don't stick in spawned children (this environment's
    # sitecustomize imports jax before user env takes effect), so the
    # controller must jax.config.update in each worker — which it only
    # does for platforms named in worker_env.
    worker_env = dict(worker_env or {})
    import os as _os

    if _os.environ.get("JAX_PLATFORMS") and "JAX_PLATFORMS" not in worker_env:
        worker_env["JAX_PLATFORMS"] = _os.environ["JAX_PLATFORMS"]

    evaluator_stop = _start_auto_evaluator(cfg)
    result = None
    try:
        attempt = 0
        while True:
            exp_cfg = make_experiment(experiment_type, cfg)
            ctl = LocalController(
                exp_cfg, name_resolve_cfg=name_resolve_cfg,
                worker_env=worker_env,
                # Inner fault domain: individual serving-plane workers
                # restart in place; only escalations reach the relaunch
                # loop below.
                max_worker_restarts=getattr(cfg, "worker_restarts", 2),
            )
            try:
                result = ctl.run()
                break
            except Exception:
                attempt += 1
                if (
                    cfg.recover_mode == "disabled"
                    or attempt > cfg.recover_retries
                ):
                    raise
                logger.exception(
                    f"experiment failed; relaunching with recovery "
                    f"(attempt {attempt}/{cfg.recover_retries})"
                )
                cfg.recover_mode = "auto"
                time.sleep(2)
    finally:
        # Evaluator teardown runs OUTSIDE the recovery try: a drain
        # failure must never relaunch a finished run, and a permanently
        # failed run must not orphan in-flight eval jobs.
        if evaluator_stop is not None:
            try:
                evaluator_stop(drain=result is not None)
            except Exception:
                logger.warning("auto-eval teardown failed", exc_info=True)
    return result


def _start_auto_evaluator(cfg):
    """When cfg.auto_eval is set, watch the save dir from a daemon thread
    and evaluate each new checkpoint through the scheduler client
    (reference: master worker starts AutomaticEvaluator under auto_eval,
    realhf/system/master_worker.py + scheduler/evaluator.py:160-348).

    Returns a stop() callable that drains pending evals, or None."""
    if not getattr(cfg, "auto_eval", False):
        return None
    if not cfg.auto_eval_data_path:
        raise ValueError("auto_eval=True requires auto_eval_data_path")
    import os
    import threading

    from areal_tpu.scheduler.evaluator import AutomaticEvaluator

    save_root = os.path.join(
        constants.get_save_path(cfg.experiment_name, cfg.trial_name),
        cfg.auto_eval_model_role,
    )
    output_root = os.path.join(
        constants.get_log_path(cfg.experiment_name, cfg.trial_name), "eval"
    )
    evaluator = AutomaticEvaluator(
        save_root=save_root,
        data_path=cfg.auto_eval_data_path,
        output_root=output_root,
        task=cfg.auto_eval_task,
        max_concurrent_jobs=cfg.auto_eval_max_concurrent_jobs,
        eval_args={"max_new_tokens": cfg.auto_eval_max_new_tokens},
        # Keep eval jobs off the accelerator the workers hold.
        job_env={"JAX_PLATFORMS": cfg.auto_eval_device},
    )
    stop_event = threading.Event()

    def _tick():
        while not stop_event.wait(2.0):
            try:
                evaluator.step()
            except Exception:
                logger.warning("auto-eval step failed", exc_info=True)

    tick_thread = threading.Thread(target=_tick, daemon=True)
    tick_thread.start()

    def stop(drain_timeout: float = 600.0, drain: bool = True):
        stop_event.set()
        # The evaluator is not thread-safe: an in-flight tick must finish
        # before the drain touches evaluator state from this thread.
        tick_thread.join(timeout=60)
        if tick_thread.is_alive():
            logger.warning(
                "auto-eval tick thread still busy after 60s; skipping the "
                "final drain to avoid racing it"
            )
            drain = False
        try:
            if drain:
                # One final discovery pass + drain so the last checkpoint
                # (saved right before exit) still gets scored.
                evaluator.run_until_idle(timeout=drain_timeout)
        except TimeoutError:
            logger.warning("auto-eval drain timed out; results incomplete")
        finally:
            evaluator.scheduler.stop_all()
        if evaluator.results():
            logger.info(f"auto-eval accuracies by step: {evaluator.results()}")

    return stop


def main(experiment_type: str, cfg_cls: Type, argv=None):
    cfg = parse_args(cfg_cls, argv)
    result = run_experiment(experiment_type, cfg)
    logger.info(f"experiment finished: {result}")
    return result

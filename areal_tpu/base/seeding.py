"""Deterministic per-key seeding.

Counterpart of the reference's seeding utilities (realhf/base/seeding.py):
a single experiment-level base seed plus stable per-key offsets, so every
worker / dataset / sampler derives a reproducible but distinct stream.
JAX-native: `prng_key(key)` returns a `jax.random.PRNGKey` folded with the
per-key hash.
"""

from __future__ import annotations

import hashlib
import random

import numpy as np

_BASE_SEED = 0
_SEED_FROM = "default"


def _hash_key(key: str) -> int:
    return int(hashlib.sha256(key.encode()).hexdigest(), 16) % (2**31)


def set_random_seed(base_seed: int, key: str):
    """Seed python/numpy for this process deterministically from (seed, key)."""
    global _BASE_SEED, _SEED_FROM
    _BASE_SEED = base_seed
    _SEED_FROM = key
    seed = base_seed + _hash_key(key)
    random.seed(seed)
    np.random.seed(seed % (2**32))


def get_seed() -> int:
    return _BASE_SEED


def get_shuffle_seed(key: str = "shuffle") -> int:
    return (_BASE_SEED + _hash_key(f"{_SEED_FROM}/{key}")) % (2**31)


def state_dict() -> dict:
    """Snapshot of this process's host-side RNG state for checkpointing:
    the (base_seed, key) identity plus the live python/numpy generator
    states, so a recovered run continues the exact sample stream an
    uninterrupted one would have produced."""
    return {
        "base_seed": _BASE_SEED,
        "seed_from": _SEED_FROM,
        "python_random": random.getstate(),
        "numpy_random": np.random.get_state(),
    }


def load_state(state: dict):
    """Restore a state_dict() snapshot taken at checkpoint time."""
    global _BASE_SEED, _SEED_FROM
    _BASE_SEED = int(state["base_seed"])
    _SEED_FROM = state["seed_from"]
    random.setstate(state["python_random"])
    np.random.set_state(state["numpy_random"])


def prng_key(key: str):
    """A jax PRNGKey derived from the experiment seed, this process's
    identity key (from set_random_seed), and a string key — distinct
    processes get distinct streams for the same `key`."""
    import jax

    return jax.random.fold_in(
        jax.random.PRNGKey(_BASE_SEED), _hash_key(f"{_SEED_FROM}/{key}")
    )

"""PAL-style python answer execution for the offline eval harness.

Role counterpart of the reference's evaluation/python_executor.py
(GenericRuntime/PythonExecutor: run model-generated programs and take
the return value / printed output as the answer, used by the 'pal' and
'tora' prompt styles). Rebuilt on this repo's sandboxed-subprocess
machinery instead of the reference's in-process exec() + ProcessPool:
every candidate runs in a fresh subprocess under the same rlimit +
os-neutering guard the code verifier uses (code_verify.py), so a
malicious or runaway program cannot touch the evaluator process.

Contract: extract the LAST fenced code block from the model output;
if it defines `solution()`, call it and use the repr of the return
value (PAL convention); otherwise run the block and use the last
non-empty stdout line (tora convention). Returns None when there is no
code block, execution fails, or nothing is produced.
"""

from __future__ import annotations

from typing import Optional

from areal_tpu.functioncall.code_verify import (
    extract_code_block,
    run_one_case,
)

_SOLUTION_DRIVER = """
if __name__ == "__main__":
    _fn = globals().get("solution")
    if _fn is not None:
        _res = _fn()
        print("\\n___PY_ANSWER___")
        print(repr(_res) if not isinstance(_res, str) else _res)
"""

_MARKER = "___PY_ANSWER___"


def _extract_candidate_code(text: str) -> Optional[str]:
    """The program to run: the last COMPLETE fenced block when one
    exists; otherwise the continuation of a fence the PROMPT opened —
    the 'pal' template ends with '```python\\n', so a compliant
    completion is bare code (optionally ending in a closing fence) with
    no opening fence of its own. Prose-only text returns None."""
    block = extract_code_block(text)
    if block is not None:
        return block
    if "```" in text:
        # Closing fence only: everything before it is the program.
        return text.split("```", 1)[0]
    # No fence at all (generation hit the token budget before closing):
    # only accept it when it plausibly IS the program — a bare
    # solution() definition — never arbitrary prose.
    if "def solution" in text:
        return text
    return None


def execute_python_answer(
    text: str, timeout: float = 6.0,
) -> Optional[str]:
    """Run the candidate program in `text` (see
    _extract_candidate_code); return its answer string or None."""
    code = _extract_candidate_code(text)
    if code is None:
        return None
    has_solution = "def solution" in code
    if has_solution:
        code = code + _SOLUTION_DRIVER
    ok, stdout, _err = run_one_case(code, stdin_data="", timeout=timeout)
    if not ok:
        return None
    if has_solution and _MARKER in stdout:
        tail = stdout.rsplit(_MARKER, 1)[1].strip()
        return tail.splitlines()[0].strip() if tail else None
    lines = [ln.strip() for ln in stdout.splitlines() if ln.strip()]
    return lines[-1] if lines else None


def compare_python_answer(ans: Optional[str], reference) -> bool:
    """Grade an already-executed answer against the reference(s) with
    the math grader's rules, including \\boxed{} unboxing of solution-
    form ground truth — the SAME reference normalization grade_answer
    applies, so text and python modes score identically-stored data
    identically."""
    from areal_tpu.functioncall.math_grader import (
        answers_equal,
        extract_boxed,
    )

    if ans is None:
        return False
    refs = (
        list(reference)
        if isinstance(reference, (list, tuple, set))
        else [reference]
    )
    refs = [
        b if (b := extract_boxed(str(r))) is not None else r for r in refs
    ]
    return any(answers_equal(ans, str(r)) for r in refs)


def grade_python_answer(text: str, reference, timeout: float = 6.0) -> bool:
    """Execute the candidate program and grade its answer."""
    return compare_python_answer(
        execute_python_answer(text, timeout=timeout), reference
    )

"""Qwen2/Qwen2.5 HF conversion: llama layout + qkv bias.
Reference parity: realhf/api/from_hf/qwen2.py."""

from __future__ import annotations

from typing import Any, Dict

from areal_tpu.api.model_api import register_hf_family
from areal_tpu.models.config import TransformerConfig
from areal_tpu.models.hf import HFFamily
from areal_tpu.models.hf.llama import (
    _config_from_hf as llama_config_from_hf,
    _config_to_hf as llama_config_to_hf,
    params_from_hf_llama_style,
    params_to_hf_llama_style,
)


def _config_from_hf(hf: Dict[str, Any], is_critic: bool = False) -> TransformerConfig:
    cfg = llama_config_from_hf(hf, is_critic)
    cfg.attn_bias = True  # qwen2 always uses qkv bias
    return cfg


def _config_to_hf(cfg: TransformerConfig) -> Dict[str, Any]:
    hf = llama_config_to_hf(cfg)
    hf["architectures"] = ["Qwen2ForCausalLM"]
    hf["model_type"] = "qwen2"
    hf.pop("attention_bias", None)
    hf.pop("head_dim", None)
    return hf


register_hf_family(
    "qwen2",
    HFFamily(
        name="qwen2",
        hf_model_type="qwen2",
        config_from_hf=_config_from_hf,
        config_to_hf=_config_to_hf,
        params_from_hf=lambda sd, cfg: params_from_hf_llama_style(sd, cfg, qkv_bias=True),
        params_to_hf=lambda p, cfg: params_to_hf_llama_style(p, cfg, qkv_bias=True),
    ),
)

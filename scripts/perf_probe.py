"""Single-chip perf probe: time train-step components in isolation.

Used to diagnose the bench.py bottleneck (VERDICT r2 weak #1). Run on the
real TPU chip; prints a component timing table to stderr.

  python scripts/perf_probe.py [--trace]
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from areal_tpu.models.config import TransformerConfig
from areal_tpu.models.transformer import count_params, forward, init_params
from areal_tpu.ops.loss import sft_loss


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def timeit(fn, *args, n=5, warmup=2):
    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n


def main():
    trace = "--trace" in sys.argv
    cfg = TransformerConfig(
        n_layers=24, hidden_dim=896, n_q_heads=14, n_kv_heads=2, head_dim=64,
        intermediate_dim=4864, vocab_size=32768, attn_bias=True,
        compute_dtype="bfloat16",
    )
    R, T = 16, 2048
    params = init_params(cfg, jax.random.PRNGKey(0))
    n_params = count_params(params)
    log(f"probe: n_params={n_params/1e6:.1f}M R={R} T={T}")

    rng = np.random.RandomState(0)
    input_ids = jnp.asarray(rng.randint(0, cfg.vocab_size, size=(R, T)), jnp.int32)
    segment_ids = jnp.ones((R, T), jnp.int32)
    positions = jnp.tile(jnp.arange(T, dtype=jnp.int32)[None], (R, 1))
    loss_mask = jnp.ones((R, T), jnp.float32)

    total_tokens = R * T
    fwd_flops = 2.0 * n_params * total_tokens + 2.0 * cfg.n_layers * (
        cfg.n_q_heads * cfg.head_dim) * T * T * R * 0.5 * 2
    train_flops = 3.0 * fwd_flops  # fwd + 2x bwd

    # --- forward only, per attention impl ---
    for impl in ("flash", "reference"):
        f = jax.jit(lambda p, impl=impl: forward(
            p, cfg, input_ids, segment_ids, positions, attn_impl=impl))
        dt = timeit(f, params)
        log(f"probe: fwd  attn={impl:9s}              {dt*1e3:7.1f} ms "
            f"{fwd_flops/dt/1e12:6.1f} TFLOP/s")

    # --- forward returning hidden only (no LM head) ---
    f_hidden = jax.jit(lambda p: forward(
        p, cfg, input_ids, segment_ids, positions, attn_impl="flash",
        output="hidden"))
    dt = timeit(f_hidden, params)
    log(f"probe: fwd  hidden-only (no head)       {dt*1e3:7.1f} ms")

    # --- full grad step, remat x attn ---
    def loss_of(p, impl, remat):
        logits = forward(p, cfg, input_ids, segment_ids, positions,
                         attn_impl=impl, remat=remat)
        tot, n = sft_loss(logits, input_ids, segment_ids, loss_mask)
        return tot / n

    for impl in ("flash", "reference"):
        for remat in (True, False):
            g = jax.jit(jax.grad(lambda p: loss_of(p, impl, remat)))
            try:
                dt = timeit(g, params)
            except Exception as e:  # noqa: BLE001
                log(f"probe: grad attn={impl:9s} remat={int(remat)}  FAILED {type(e).__name__}")
                continue
            log(f"probe: grad attn={impl:9s} remat={int(remat)}      {dt*1e3:7.1f} ms "
                f"{train_flops/dt/1e12:6.1f} TFLOP/s")

    # --- loss tail in isolation: logits materialization + CE ---
    hidden = jax.block_until_ready(f_hidden(params))

    def ce_materialized(p, h):
        head_w = p["embedding"]["weight"] if cfg.tied_embeddings else p["head"]["weight"]
        logits = (h @ head_w.astype(h.dtype)).astype(jnp.float32)
        tot, n = sft_loss(logits, input_ids, segment_ids, loss_mask)
        return tot / n

    g_ce = jax.jit(jax.grad(ce_materialized, argnums=(0, 1)))
    dt = timeit(g_ce, params, hidden)
    log(f"probe: grad(head+CE) materialized       {dt*1e3:7.1f} ms")

    if trace:
        import os
        path = "/tmp/areal_tpu/probe_trace"
        os.makedirs(path, exist_ok=True)
        g = jax.jit(jax.grad(lambda p: loss_of(p, "flash", True)))
        jax.block_until_ready(g(params))
        with jax.profiler.trace(path):
            jax.block_until_ready(g(params))
        log(f"probe: trace -> {path}")


if __name__ == "__main__":
    main()

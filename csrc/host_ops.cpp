// Native host-side ops for areal_tpu.
//
// TPU-native counterpart of the reference's csrc/ extensions:
//   - gae_1d_packed     <- csrc/cugae/gae.cu:10 (gae_1d_nolp_misalign).
//     On TPU the in-jit GAE is a lax.scan (areal_tpu/ops/gae.py); this C++
//     version is the *host* path used by the control plane (reward
//     post-processing on CPU workers, verification) where no accelerator
//     is attached.
//   - merge/slice/set_intervals <- csrc/interval_op/interval_op.{cpp,cu}.
//     On TPU live-weight resharding is jitted device_put between shardings,
//     but the disk-mediated param-realloc path (the reference default,
//     model_worker.py:1055) slices flattened checkpoint buffers on the
//     host — these run that path at memcpy speed, dtype-agnostic.
//   - ffd_allocate      <- realhf/base/datapack.py:153 (ffd_allocate).
//     The micro-batch token-budget packer; called per dispatch on the
//     master's hot control path, so it gets a native implementation.
//
// Exposed as a plain C ABI consumed via ctypes (no pybind11 in this
// toolchain). All functions are single-threaded and allocation-free
// except ffd_allocate's scratch vectors.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <numeric>
#include <vector>

extern "C" {

// Partition items into bins of at most `capacity` total length, producing at
// least `min_groups` bins; a single item longer than capacity gets its own
// bin. Writes a group id per item into `group_ids` and returns the number of
// groups. Semantics match areal_tpu.base.datapack.ffd_allocate exactly
// (stable descending order; least-loaded candidate bin, lowest index on
// ties; empty bins always accept).
int64_t ffd_allocate(const int64_t* lengths, int64_t n, int64_t capacity,
                     int64_t min_groups, int64_t* group_ids) {
  std::vector<int64_t> order(n);
  std::iota(order.begin(), order.end(), 0);
  std::stable_sort(order.begin(), order.end(), [&](int64_t a, int64_t b) {
    return lengths[a] > lengths[b];
  });

  std::vector<int64_t> sums(min_groups > 0 ? min_groups : 1, 0);
  std::vector<int64_t> counts(sums.size(), 0);
  if (min_groups <= 0) {
    sums.clear();
    counts.clear();
  }

  for (int64_t oi = 0; oi < n; ++oi) {
    const int64_t idx = order[oi];
    const int64_t l = lengths[idx];
    int64_t best = -1;
    int64_t best_sum = 0;
    for (size_t g = 0; g < sums.size(); ++g) {
      if (sums[g] + l <= capacity || counts[g] == 0) {
        if (best < 0 || sums[g] < best_sum) {
          best = static_cast<int64_t>(g);
          best_sum = sums[g];
        }
      }
    }
    if (best < 0) {
      sums.push_back(0);
      counts.push_back(0);
      best = static_cast<int64_t>(sums.size()) - 1;
    }
    group_ids[idx] = best;
    sums[best] += l;
    counts[best] += 1;
  }

  // Compact away empty bins (possible when min_groups > n items), keeping
  // group order, and remap ids.
  std::vector<int64_t> remap(sums.size(), -1);
  int64_t n_groups = 0;
  for (size_t g = 0; g < sums.size(); ++g) {
    if (counts[g] > 0) remap[g] = n_groups++;
  }
  for (int64_t i = 0; i < n; ++i) group_ids[i] = remap[group_ids[i]];
  return n_groups;
}

// Merge overlapping/adjacent [start, end) intervals in place. Intervals must
// be sorted by start. Returns the merged count.
int64_t merge_intervals(int64_t* starts, int64_t* ends, int64_t n) {
  if (n == 0) return 0;
  int64_t w = 0;
  for (int64_t i = 1; i < n; ++i) {
    if (starts[i] <= ends[w]) {
      ends[w] = std::max(ends[w], ends[i]);
    } else {
      ++w;
      starts[w] = starts[i];
      ends[w] = ends[i];
    }
  }
  return w + 1;
}

// Gather n [start, end) element ranges of `src` (element size `elem` bytes)
// contiguously into `out`.
void slice_intervals(const char* src, int64_t elem, const int64_t* starts,
                     const int64_t* ends, int64_t n, char* out) {
  int64_t off = 0;
  for (int64_t i = 0; i < n; ++i) {
    const int64_t cnt = ends[i] - starts[i];
    std::memcpy(out + off * elem, src + starts[i] * elem, cnt * elem);
    off += cnt;
  }
}

// Scatter a contiguous `src` into n [start, end) element ranges of `dst`.
void set_intervals(const char* src, char* dst, int64_t elem,
                   const int64_t* starts, const int64_t* ends, int64_t n) {
  int64_t off = 0;
  for (int64_t i = 0; i < n; ++i) {
    const int64_t cnt = ends[i] - starts[i];
    std::memcpy(dst + starts[i] * elem, src + off * elem, cnt * elem);
    off += cnt;
  }
}

// GAE over packed variable-length sequences, "misaligned values" layout
// (reference gae_1d_nolp_misalign): rewards has total_len = cu_seqlens[n_seqs]
// entries; values has total_len + n_seqs entries (each sequence contributes
// len+1 values, the extra one being the bootstrap V(s_T)). `truncate[i]`
// nonzero keeps the bootstrap value for sequence i; zero (episode done)
// replaces it with 0.
void gae_1d_packed(const float* rewards, const float* values,
                   const int64_t* cu_seqlens, const uint8_t* truncate,
                   int64_t n_seqs, float gamma, float lam, float* adv,
                   float* ret) {
  for (int64_t s = 0; s < n_seqs; ++s) {
    const int64_t r0 = cu_seqlens[s];
    const int64_t r1 = cu_seqlens[s + 1];
    const int64_t v0 = r0 + s;  // values are shifted by one slot per prior seq
    const int64_t len = r1 - r0;
    float next_adv = 0.0f;
    float v_next = truncate[s] ? values[v0 + len] : 0.0f;
    for (int64_t t = len - 1; t >= 0; --t) {
      const float delta = rewards[r0 + t] + gamma * v_next - values[v0 + t];
      next_adv = delta + gamma * lam * next_adv;
      adv[r0 + t] = next_adv;
      ret[r0 + t] = next_adv + values[v0 + t];
      v_next = values[v0 + t];
    }
  }
}

}  // extern "C"

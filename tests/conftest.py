"""Test configuration: force an 8-device virtual CPU platform.

Mirrors the reference's CPU-only multi-process test strategy (SURVEY.md §4)
the TPU way: a single process with 8 virtual CPU devices so every sharding
path (data/fsdp/tensor/seq mesh axes) exercises real XLA collectives
without TPU hardware.

Note: this environment's sitecustomize imports jax at interpreter startup
(JAX_PLATFORMS=axon), so env vars alone don't stick — but backends are
lazily initialized, so `jax.config.update` before first device use wins.
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()
# Sandboxed python-answer programs get generous wall time under CI load
# (interpreter spawn alone can take seconds on a busy machine); the
# runaway-program test passes its own tight timeout explicitly.
os.environ.setdefault("AREAL_PYEXEC_TIMEOUT", "30")

import jax

if not os.environ.get("AREAL_ONCHIP_TESTS"):
    # AREAL_ONCHIP_TESTS=1 keeps the real platform so the compiled-kernel
    # parity gates (e.g. test_splash_compiled_matches_reference_on_tpu)
    # can run on hardware; everything else pins the virtual CPU mesh.
    jax.config.update("jax_platforms", "cpu")

import uuid

import pytest


@pytest.fixture
def tmp_name_resolve(tmp_path):
    """Fresh NFS-backend name_resolve rooted in a tmp dir."""
    from areal_tpu.base import name_resolve

    repo = name_resolve.reconfigure("nfs", record_root=str(tmp_path / "name_resolve"))
    yield repo
    repo.reset()


@pytest.fixture
def experiment_context():
    from areal_tpu.base import constants

    exp, trial = f"test-exp-{uuid.uuid4().hex[:6]}", "trial0"
    constants.set_experiment_trial_names(exp, trial)
    yield exp, trial

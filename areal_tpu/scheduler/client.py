"""Job scheduler clients.

Counterpart of the reference's scheduler layer (realhf/scheduler/
client.py:52-154 + slurm/): `SchedulerClient` submits job arrays, waits
on states, and stops everything. The local client manages OS
subprocesses; TPU-pod deployments submit the same specs through an
external scheduler (GKE/XPK/Ray), for which `make_scheduler` exposes the
registry hook.
"""

from __future__ import annotations

import dataclasses
import enum
import os
import signal
import subprocess
import time
from typing import Dict, List, Optional

from areal_tpu.base import logging

logger = logging.getLogger("scheduler")


class JobState(str, enum.Enum):
    NOT_FOUND = "NOT_FOUND"
    PENDING = "PENDING"
    RUNNING = "RUNNING"
    COMPLETED = "COMPLETED"
    FAILED = "FAILED"
    CANCELLED = "CANCELLED"


@dataclasses.dataclass
class JobInfo:
    name: str
    state: JobState
    host: str = "localhost"
    exit_code: Optional[int] = None


class JobException(Exception):
    def __init__(self, job: JobInfo, msg: str = ""):
        self.job = job
        super().__init__(f"job {job.name} -> {job.state} {msg}")


class SchedulerClient:
    def submit(self, name: str, cmd: List[str], env: Optional[Dict[str, str]] = None,
               cwd: Optional[str] = None, **kwargs) -> str:
        raise NotImplementedError()

    def submit_array(self, name: str, cmd_list: List[List[str]], **kwargs) -> List[str]:
        return [self.submit(f"{name}/{i}", c, **kwargs) for i, c in enumerate(cmd_list)]

    def find(self, name: str) -> JobInfo:
        raise NotImplementedError()

    def wait(self, names: Optional[List[str]] = None, timeout: Optional[float] = None,
             raise_on_failure: bool = True) -> List[JobInfo]:
        raise NotImplementedError()

    def stop_all(self):
        raise NotImplementedError()


class LocalSchedulerClient(SchedulerClient):
    """Subprocess-backed scheduler (reference local scheduler)."""

    def __init__(self, log_dir: Optional[str] = None):
        self._procs: Dict[str, subprocess.Popen] = {}
        self._log_files: Dict[str, object] = {}
        self.log_dir = log_dir

    def submit(self, name: str, cmd: List[str], env: Optional[Dict[str, str]] = None,
               cwd: Optional[str] = None, **kwargs) -> str:
        if name in self._procs and self._procs[name].poll() is None:
            raise ValueError(f"job {name!r} already running")
        stdout = None
        if self.log_dir:
            os.makedirs(self.log_dir, exist_ok=True)
            stdout = open(
                os.path.join(self.log_dir, name.replace("/", "_") + ".log"), "w"
            )
            self._log_files[name] = stdout
        full_env = dict(os.environ)
        if env:
            full_env.update(env)
        proc = subprocess.Popen(
            cmd, env=full_env, cwd=cwd, stdout=stdout,
            stderr=subprocess.STDOUT if stdout else None,
            start_new_session=True,
        )
        self._procs[name] = proc
        logger.info(f"submitted job {name}: pid={proc.pid}")
        return name

    def find(self, name: str) -> JobInfo:
        proc = self._procs.get(name)
        if proc is None:
            return JobInfo(name, JobState.NOT_FOUND)
        rc = proc.poll()
        if rc is None:
            return JobInfo(name, JobState.RUNNING)
        state = JobState.COMPLETED if rc == 0 else JobState.FAILED
        return JobInfo(name, state, exit_code=rc)

    def wait(self, names: Optional[List[str]] = None, timeout: Optional[float] = None,
             raise_on_failure: bool = True) -> List[JobInfo]:
        names = list(names) if names is not None else list(self._procs)
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            infos = [self.find(n) for n in names]
            if raise_on_failure:
                for i in infos:
                    if i.state in (JobState.FAILED, JobState.CANCELLED):
                        raise JobException(i)
            if all(
                i.state in (JobState.COMPLETED, JobState.FAILED,
                            JobState.CANCELLED, JobState.NOT_FOUND)
                for i in infos
            ):
                return infos
            if deadline is not None and time.monotonic() > deadline:
                raise TimeoutError(f"jobs still running: "
                                   f"{[i.name for i in infos if i.state == JobState.RUNNING]}")
            time.sleep(0.2)

    def stop(self, name: str):
        proc = self._procs.get(name)
        if proc is not None and proc.poll() is None:
            try:
                os.killpg(os.getpgid(proc.pid), signal.SIGTERM)
            except ProcessLookupError:
                pass

    def stop_all(self):
        for name in list(self._procs):
            self.stop(name)
        for proc in self._procs.values():
            try:
                proc.wait(timeout=10)
            except subprocess.TimeoutExpired:
                proc.kill()
        for f in self._log_files.values():
            try:
                f.close()
            except Exception:
                pass


_SCHEDULERS = {"local": LocalSchedulerClient}


def register_scheduler(name: str, cls):
    _SCHEDULERS[name] = cls


def make_scheduler(mode: str = "local", **kwargs) -> SchedulerClient:
    if mode not in _SCHEDULERS:
        raise ValueError(
            f"unknown scheduler {mode!r}; available: {sorted(_SCHEDULERS)} "
            "(TPU pod deployments: register a client for your cluster "
            "scheduler, e.g. XPK/GKE/Ray)"
        )
    return _SCHEDULERS[mode](**kwargs)

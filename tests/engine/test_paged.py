"""Paged KV pool: allocator semantics, paged-attention parity vs the
dense decode oracle, prefill scatter round-trip, pool-pressure
preemption, long (8k) context service, and the mesh-sharded engine."""


import jax
import jax.numpy as jnp
import numpy as np
import pytest

from areal_tpu.engine.paged import (
    TRASH_PAGE,
    PageAllocator,
    paged_decode_attention,
    pages_needed,
    scatter_prefill,
)
from areal_tpu.engine.serving import GenRequest, ServingEngine, serving_mesh
from areal_tpu.models.config import TransformerConfig
from areal_tpu.models.transformer import init_params
from areal_tpu.ops.attention import decode_attention
from tests.engine.serving_utils import run_requests as _run

CFG = TransformerConfig(
    n_layers=2,
    hidden_dim=32,
    n_q_heads=2,
    n_kv_heads=1,
    head_dim=16,
    intermediate_dim=64,
    vocab_size=64,
    max_position_embeddings=16384,
    compute_dtype="float32",
    param_dtype="float32",
)
EOS = 5


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.PRNGKey(0))


# CFG here differs from serving_utils.TINY_SERVING_CFG (16k positions
# for the long-context test), so the module keeps its own params
# fixture; the runner is shared.


# ----------------------------------------------------------------------
# Allocator
# ----------------------------------------------------------------------


def test_allocator_basics():
    a = PageAllocator(6)  # pages 1..5 usable
    assert a.n_free == 5
    got = a.alloc(3)
    assert len(got) == 3 and TRASH_PAGE not in got
    assert a.alloc(3) is None  # only 2 left, no state change
    assert a.n_free == 2
    more = a.alloc(2)
    assert set(got) | set(more) == {1, 2, 3, 4, 5}
    a.free(got)
    assert a.n_free == 3
    with pytest.raises(ValueError):
        a.free([TRASH_PAGE])


def test_pages_needed():
    assert pages_needed(1, 128) == 1
    assert pages_needed(128, 128) == 1
    assert pages_needed(129, 128) == 2
    assert pages_needed(0, 128) == 1


# ----------------------------------------------------------------------
# Paged attention parity vs the dense oracle
# ----------------------------------------------------------------------


def test_paged_attention_matches_dense():
    rng = np.random.default_rng(0)
    B, Hq, Hkv, hd, pg, P = 3, 4, 2, 16, 8, 5
    N = 1 + B * P  # trash + enough pages
    lengths = np.array([11, 29, 40], np.int32)  # incl. current token
    q = rng.standard_normal((B, Hq, hd), np.float32)

    # Dense cache [B, S, Hkv, hd] and an equivalent paged pool.
    S = P * pg
    dense_k = rng.standard_normal((B, S, Hkv, hd), np.float32)
    dense_v = rng.standard_normal((B, S, Hkv, hd), np.float32)
    k_pages = np.zeros((Hkv, N, pg, hd), np.float32)
    v_pages = np.zeros((Hkv, N, pg, hd), np.float32)
    page_indices = np.zeros((B, P), np.int32)
    next_page = 1
    for b in range(B):
        for p in range(P):
            page_indices[b, p] = next_page
            k_pages[:, next_page] = dense_k[b, p * pg:(p + 1) * pg].transpose(1, 0, 2)
            v_pages[:, next_page] = dense_v[b, p * pg:(p + 1) * pg].transpose(1, 0, 2)
            next_page += 1

    want = decode_attention(
        jnp.asarray(q), jnp.asarray(dense_k), jnp.asarray(dense_v),
        jnp.asarray(lengths),
    )
    got = paged_decode_attention(
        jnp.asarray(q), jnp.asarray(k_pages), jnp.asarray(v_pages),
        jnp.asarray(lengths), jnp.asarray(page_indices), impl="xla",
    )
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)


def test_scatter_prefill_roundtrip():
    rng = np.random.default_rng(1)
    L, n, pad, Hkv, hd, pg = 2, 2, 16, 2, 4, 8
    N = 6
    k_pages = jnp.zeros((L, Hkv, N, pg, hd), jnp.float32)
    v_pages = jnp.zeros_like(k_pages)
    k_pref = rng.standard_normal((L, n, pad, Hkv, hd)).astype(np.float32)
    v_pref = rng.standard_normal((L, n, pad, Hkv, hd)).astype(np.float32)
    # row 0 -> pages [1, 2]; row 1 -> page [3] + trash overflow
    flat = np.array([1, 2, 3, TRASH_PAGE], np.int32)
    k_pages, v_pages = scatter_prefill(
        k_pages, v_pages, jnp.asarray(k_pref), jnp.asarray(v_pref),
        jnp.asarray(flat),
    )
    k_pages = np.asarray(k_pages)
    np.testing.assert_allclose(
        k_pages[:, :, 1], k_pref[:, 0, :pg].transpose(0, 2, 1, 3)
    )
    np.testing.assert_allclose(
        k_pages[:, :, 2], k_pref[:, 0, pg:].transpose(0, 2, 1, 3)
    )
    np.testing.assert_allclose(
        k_pages[:, :, 3], k_pref[:, 1, :pg].transpose(0, 2, 1, 3)
    )


# ----------------------------------------------------------------------
# Engine under pool pressure
# ----------------------------------------------------------------------


def test_pool_pressure_preempts_and_recovers(params):
    # Pool of 40 tokens (5 pages of 8) for 2 slots: two 23-token
    # sequences need 8 pages at their peak, so one gets preempted
    # (interrupted partial) while the other runs to budget; resubmission
    # with the prefix makes progress once pages free up.
    eng = ServingEngine(
        CFG, params, max_batch_size=2, max_seq_len=64,
        decode_block_steps=4, prompt_bucket=8, eos_token_id=None, seed=0,
        page_size=8, kv_pool_tokens=40,
    )
    eng.start()
    try:
        reqs = [
            GenRequest(qid=f"p{i}", input_ids=[7, 8, 9], max_new_tokens=20)
            for i in range(2)
        ]
        results = _run(eng, reqs)
        preempted = [r for r in results.values() if r.interrupted]
        finished = [r for r in results.values() if not r.interrupted]
        assert preempted, "expected at least one preemption under pool pressure"
        assert eng.n_preempted >= 1
        # The non-preempted one ran to its budget.
        assert finished
        for r in finished:
            assert len(r.output_ids) == 20
        # Resubmit the preempted prefix (partial-rollout protocol).
        for r in preempted:
            full_prefix = [7, 8, 9] + r.output_ids
            res2 = _run(eng, [GenRequest(
                qid="resume", input_ids=full_prefix,
                max_new_tokens=20 - len(r.output_ids),
            )])["resume"]
            assert len(res2.output_ids) >= 1
    finally:
        eng.stop()


def test_prompt_exceeding_pool_rejected_not_stalled(params):
    """A prompt needing more pages than the WHOLE pool must be rejected
    immediately (empty result), not head-of-line-block the queue forever;
    requests behind it still complete."""
    eng = ServingEngine(
        CFG, params, max_batch_size=2, max_seq_len=256,
        decode_block_steps=4, prompt_bucket=8, eos_token_id=None, seed=0,
        page_size=8, kv_pool_tokens=32,  # 4 usable pages
    )
    eng.start()
    try:
        results = _run(eng, [
            GenRequest(qid="huge", input_ids=list(range(10, 60)),  # 7 pages
                       max_new_tokens=8),
            GenRequest(qid="ok", input_ids=[3, 4, 5], max_new_tokens=4),
        ])
        assert results["huge"].output_ids == [] and results["huge"].no_eos
        assert len(results["ok"].output_ids) == 4
    finally:
        eng.stop()


def test_slot_near_max_seq_len_caps_page_need(params):
    """A slot whose lengths + block_steps projects past max_seq_len must
    cap its page need at the table width instead of overrunning the
    page-table row (which would kill the engine thread)."""
    eng = ServingEngine(
        CFG, params, max_batch_size=1, max_seq_len=16,
        decode_block_steps=8, prompt_bucket=8, eos_token_id=None, seed=0,
        page_size=8,
    )
    eng.start()
    try:
        # plen 12 -> budget trimmed to 4; 12 + 8 block steps > 16.
        res = _run(eng, [GenRequest(qid="edge", input_ids=list(range(10, 22)),
                                    max_new_tokens=50)])["edge"]
        assert len(res.output_ids) == 4  # S - plen
        assert res.no_eos
    finally:
        eng.stop()


def test_long_context_8k(params):
    # ≥8k context service (VERDICT r2 item 4): a 5k-token prompt decodes
    # past page boundaries in an 8k-page-table engine with a pool much
    # smaller than B * max_seq_len.
    plen = 5000
    eng = ServingEngine(
        CFG, params, max_batch_size=2, max_seq_len=8192,
        decode_block_steps=4, prompt_bucket=128, eos_token_id=None, seed=0,
        page_size=128, kv_pool_tokens=8192 + 1024,
    )
    eng.start()
    try:
        prompt = (np.arange(plen) % 50 + 10).tolist()
        res = _run(eng, [GenRequest(qid="long", input_ids=prompt,
                                    max_new_tokens=12)], timeout=600)["long"]
        assert len(res.output_ids) == 12
        assert len(res.output_logprobs) == 12
        assert all(lp <= 0 for lp in res.output_logprobs)
    finally:
        eng.stop()


def test_mesh_sharded_engine(params):
    # Tensor-parallel serving over the virtual CPU devices: same greedy
    # output as the single-device engine.
    mesh = serving_mesh(2)
    prompt = [9, 21, 33, 4]
    eng0 = ServingEngine(
        CFG, params, max_batch_size=2, max_seq_len=128,
        decode_block_steps=3, prompt_bucket=8, eos_token_id=EOS, seed=0,
        page_size=8,
    )
    eng0.start()
    try:
        ref = _run(eng0, [GenRequest(qid="a", input_ids=prompt,
                                     max_new_tokens=10, greedy=True)])["a"]
    finally:
        eng0.stop()

    eng = ServingEngine(
        CFG, params, max_batch_size=2, max_seq_len=128,
        decode_block_steps=3, prompt_bucket=8, eos_token_id=EOS, seed=0,
        page_size=8, mesh=mesh,
    )
    eng.start()
    try:
        res = _run(eng, [GenRequest(qid="b", input_ids=prompt,
                                    max_new_tokens=10, greedy=True)])["b"]
        assert res.output_ids == ref.output_ids
        np.testing.assert_allclose(
            res.output_logprobs, ref.output_logprobs, rtol=1e-4, atol=1e-4
        )
    finally:
        eng.stop()


def test_topk_topp_requests(params):
    """The sort-cutoff branch (lax.cond) actually masks: a top_k=1
    SAMPLED request must reproduce the greedy request's tokens exactly
    (only the argmax survives the cutoff), mixed with a plain request in
    the same batch so both cond branches run in one engine."""
    eng = ServingEngine(
        CFG, params, max_batch_size=2, max_seq_len=64,
        decode_block_steps=4, prompt_bucket=8, eos_token_id=None, seed=0,
        page_size=8,
    )
    eng.start()
    try:
        results = _run(eng, [
            GenRequest(qid="k1", input_ids=[9, 10, 11], max_new_tokens=8,
                       top_k=1, temperature=0.8),  # sampled, but only argmax survives
            GenRequest(qid="plain", input_ids=[12, 13], max_new_tokens=8),
        ])
        greedy = _run(eng, [
            GenRequest(qid="g", input_ids=[9, 10, 11], max_new_tokens=8,
                       greedy=True),
        ])["g"]
        assert results["k1"].output_ids == greedy.output_ids
        for r in results.values():
            assert len(r.output_ids) == 8
            assert all(lp <= 0 for lp in r.output_logprobs)
    finally:
        eng.stop()


def test_warp_sample_topk_fast_tier_matches_sort_tier():
    """Tier invariance: a top-k row samples the SAME token whether the
    batch took the lax.top_k fast tier or the full-sort tier (forced by
    a top-p row elsewhere in the batch) — the warped logits are
    identical, and categorical noise depends only on key and shape."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from areal_tpu.engine.paged import warp_sample

    rng = np.random.RandomState(0)
    V = 512
    logits = jnp.asarray(rng.standard_normal((2, V)).astype(np.float32) * 3)
    key = jax.random.PRNGKey(7)
    temps = jnp.asarray([0.8, 1.0], jnp.float32)
    greedy = jnp.zeros((2,), bool)
    forbid = jnp.zeros((2,), bool)
    eos = jnp.zeros((V,), bool)

    def run(tks, tps):
        return warp_sample(
            logits, key, temps, jnp.asarray(tps, jnp.float32),
            jnp.asarray(tks, jnp.int32), greedy, forbid, eos,
        )

    # fast tier: both rows top-k (<= TOPK_FAST_MAX), no top-p
    t_fast, lp_fast = run([50, 50], [1.0, 1.0])
    # sort tier: row 1 adds top-p, row 0 unchanged
    t_sort, lp_sort = run([50, 50], [1.0, 0.9])
    assert int(t_fast[0]) == int(t_sort[0])
    np.testing.assert_allclose(float(lp_fast[0]), float(lp_sort[0]), rtol=1e-6)
    # the sampled token respects top-k in both tiers
    topk_set = set(np.argsort(np.asarray(logits[0]))[::-1][:50].tolist())
    assert int(t_fast[0]) in topk_set

    # huge top-k falls back to the sort tier and still respects k
    t_big, _ = run([400, 400], [1.0, 1.0])
    big_set = set(np.argsort(np.asarray(logits[1]))[::-1][:400].tolist())
    assert int(t_big[1]) in big_set

    # no-k row inside a fast-tier batch stays unrestricted: greedy-check
    # via temperature ~0 (sharpest mode) stays the argmax
    t_mix, _ = warp_sample(
        logits, key, jnp.asarray([1e-6, 1.0], jnp.float32),
        jnp.asarray([1.0, 1.0], jnp.float32),
        jnp.asarray([0, 50], jnp.int32), greedy, forbid, eos,
    )
    assert int(t_mix[0]) == int(jnp.argmax(logits[0]))

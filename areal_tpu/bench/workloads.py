"""Phase bodies: the actual benchmark workloads.

Moved out of the old monolithic ``bench.py``. Every function here is a
phase entrypoint ``fn(pass_) -> value dict`` run inside its own runner
subprocess (see :mod:`areal_tpu.bench.runner`):

- ``pass_ == "compile"``: build the workload and compile every program
  it needs — via the engines' AOT warm hooks — so the persistent XLA
  cache holds them. Returns compile timings.
- ``pass_ == "measure"``: warm briefly (cache hits), then time the
  steady state and return the metrics.

The split is the point: a one-minute tunnel window is never spent
compiling what a previous window already cached.
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

from areal_tpu.bench._util import log, repo_root
from areal_tpu.bench.devices import get_devices_with_retry

BASELINE_TFLOPS = 198.0


def flagship_cfg(max_pos: int = 40960, attn_bias: bool = True):
    """The benchmark model shape: R1-Distill-Qwen-1.5B-class layers
    (hidden 1536, 12 q / 2 kv heads, head_dim 128, ffn 8960 — the family
    the reference's headline benchmark trains,
    benchmark/verl_v0_3_0_post1_76084d3/README.md:38-44), trimmed to 16
    layers / 32k vocab so params + fp32 Adam moments + activations fit
    one v5e chip's 16 GB HBM. Shared by every bench phase and the perf
    scripts (mfu_sweep, long_context_probe) so every banked number
    measures the SAME model."""
    from areal_tpu.models.config import TransformerConfig

    return TransformerConfig(
        n_layers=16, hidden_dim=1536, n_q_heads=12, n_kv_heads=2,
        head_dim=128, intermediate_dim=8960, vocab_size=32768,
        attn_bias=attn_bias, compute_dtype="bfloat16",
        param_dtype="bfloat16", max_position_embeddings=max_pos,
    )


def smoke_cfg():
    """CPU smoke shape so dev runs terminate quickly."""
    from areal_tpu.models.config import TransformerConfig

    return TransformerConfig(
        n_layers=2, hidden_dim=64, n_q_heads=4, n_kv_heads=2, head_dim=16,
        intermediate_dim=128, vocab_size=256, compute_dtype="float32",
    )


def train_step_flops(cfg, n_params: int, seqlens) -> float:
    """Analytic fwd+bwd FLOPs for a packed batch (llama-formula style:
    6*N per token for matmuls, plus causal attention score/context terms)."""
    total = 0.0
    q_dim = cfg.n_q_heads * cfg.head_dim
    for l in seqlens:
        total += 6.0 * n_params * l
        # QK^T + AV: 2 * (2 * l^2 * q_dim) * 0.5 (causal) per layer, x3 for bwd.
        total += 6.0 * cfg.n_layers * q_dim * float(l) * l
    return total


# ----------------------------------------------------------------------
# train_tflops
# ----------------------------------------------------------------------


def _train_setup():
    import jax

    from areal_tpu.api.data_api import MicroBatchSpec, SequenceSample
    from areal_tpu.engine.jax_engine import JaxTrainEngine
    from areal_tpu.engine.optimizer import OptimizerConfig
    from areal_tpu.models.transformer import count_params, init_params
    from areal_tpu.ops.loss import sft_loss_from_logprobs

    devices = get_devices_with_retry()
    platform = devices[0].platform
    on_tpu = platform == "tpu"
    log(f"bench: platform={platform} n_devices={len(devices)}")

    if on_tpu:
        # flagship_cfg: params in bf16 with fp32 optimizer moments
        # (weights stream at half the bytes; update math stays fp32 —
        # measured +18 TFLOP/s over fp32 params, scripts/perf_probe.py).
        cfg = flagship_cfg()
        seqlen, n_seqs, n_warmup, n_steps = 2048, 16, 2, 5
    else:
        cfg = smoke_cfg()
        seqlen, n_seqs, n_warmup, n_steps = 128, 4, 1, 2

    params = init_params(cfg, jax.random.PRNGKey(0))
    n_params = count_params(params)
    log(f"bench: n_params={n_params/1e6:.1f}M")

    eng = JaxTrainEngine(
        cfg, params,
        optimizer_config=OptimizerConfig(lr=1e-4, warmup_steps_proportion=0.0),
        total_train_steps=1000, row_len_multiple=seqlen, max_row_len=seqlen,
        # save_attn: keep the flash kernel's residuals, recompute the rest
        # in backward — the best single-chip throughput/memory point for
        # this model size (see scripts/perf_probe.py measurements).
        remat="save_attn" if on_tpu else "full",
    )

    rng = np.random.RandomState(0)
    seqlens = [seqlen] * n_seqs
    total = sum(seqlens)
    batch = SequenceSample.from_default(
        ids=[f"b{i}" for i in range(n_seqs)],
        seqlens=seqlens,
        data={
            "packed_input_ids": rng.randint(0, cfg.vocab_size, size=total),
            "loss_mask": np.ones(total, np.float32),
        },
    )

    def packed_loss(lp, rows):
        tot, n = sft_loss_from_logprobs(lp, rows["loss_mask"])
        return tot, {}

    def weight(mb):
        return float(np.sum(mb.data["loss_mask"]))

    mb_spec = MicroBatchSpec(n_mbs=1)
    return eng, batch, mb_spec, packed_loss, weight, dict(
        cfg=cfg, n_params=n_params, seqlens=seqlens, total=total,
        n_warmup=n_warmup, n_steps=n_steps, on_tpu=on_tpu,
    )


def train_phase(pass_: str) -> dict:
    import jax

    eng, batch, mb_spec, loss_fn, weight, meta = _train_setup()

    def one_step(i):
        return eng.train_batch(batch, mb_spec, loss_fn, weight,
                               version_steps=i, loss_name="bench")

    if pass_ == "compile":
        t0 = time.perf_counter()
        aot_s = eng.warm(batch, mb_spec, loss_fn, loss_name="bench")
        # One executed step on top of the AOT pass: covers whatever the
        # lowered program does not (stats fetch path, eager helpers) and
        # proves the compiled program actually runs on this device.
        one_step(0)
        jax.block_until_ready(eng.params)
        dt = time.perf_counter() - t0
        log(f"bench: train compile pass {dt:.1f}s (aot {aot_s:.1f}s)")
        return {"compile_s": dt, "aot_compile_s": aot_s}

    for i in range(meta["n_warmup"]):
        t = time.perf_counter()
        one_step(i)
        log(f"bench: warmup step {i} {time.perf_counter() - t:.2f}s")

    # Drain warmup-recorded pipeline stats so the exported overlap
    # telemetry below covers ONLY the timed steps.
    from areal_tpu.base import stats_tracker

    stats_tracker.export(key="perf")

    t0 = time.perf_counter()
    for i in range(meta["n_steps"]):
        one_step(meta["n_warmup"] + i)
    jax.block_until_ready(eng.params)
    dt = (time.perf_counter() - t0) / meta["n_steps"]

    flops = train_step_flops(meta["cfg"], meta["n_params"], meta["seqlens"])
    tflops = flops / dt / 1e12
    tokens_per_sec = meta["total"] / dt
    log(f"bench: {dt:.3f}s/step {tokens_per_sec:.0f} tok/s {tflops:.1f} TFLOP/s")
    perf = stats_tracker.export(key="perf")
    overlap = {
        k[len("perf/"):]: float(v) for k, v in perf.items()
        if k in ("perf/packing_efficiency", "perf/h2d_wait_ms",
                 "perf/dispatch_gap_ms")
    }
    log(f"bench: overlap telemetry {overlap}")
    return {
        "train_tflops": tflops,
        "tokens_per_sec": tokens_per_sec,
        "step_s": dt,
        "vs_baseline": tflops / BASELINE_TFLOPS,
        "overlap": overlap,
    }


# ----------------------------------------------------------------------
# gen_tps / gen_long_tps
# ----------------------------------------------------------------------


def _gen_run(pass_: str, long_form: bool) -> dict:
    """Generation throughput on the ServingEngine (paged KV, batched
    prefill, jitted decode blocks): sustained output tokens/sec/chip at a
    realistic batch + context. The reference's headline gains are
    generation-side (async RL is generation-bound, blog/AReaL_v0_3.md:125)
    but it publishes only relative deltas, so this is reported as an
    absolute alongside the train metric.

    long_form=True is the 8k-new-tokens-class workload (the reference's
    headline benchmark generates ~31k tokens/sample): moderate batch,
    fixed-shape chunked prefill, and sustained long decode through the
    paged pool — the regime the async design is supposed to win on,
    which the 512+512 short mode does not speak to."""
    import threading

    import jax

    from areal_tpu.engine.serving import GenRequest, ServingEngine
    from areal_tpu.models.transformer import init_params

    devices = get_devices_with_retry()
    on_tpu = devices[0].platform == "tpu"

    if on_tpu:
        cfg = flagship_cfg()
        if long_form:
            # ~1.2 GB of paged KV at bf16 alongside the 3.5 GB params.
            n_reqs, plen, max_new, page, block = 8, 1024, 8192, 128, 32
            chunk = 512
        else:
            n_reqs, plen, max_new, page, block = 32, 512, 512, 128, 32
            chunk = None
    else:
        cfg = smoke_cfg()
        if long_form:
            n_reqs, plen, max_new, page, block = 2, 32, 64, 8, 4
            chunk = 16
        else:
            n_reqs, plen, max_new, page, block = 2, 16, 8, 8, 4
            chunk = None

    params = init_params(cfg, jax.random.PRNGKey(1))
    eng = ServingEngine(
        cfg, params,
        max_batch_size=n_reqs,
        max_seq_len=plen + max_new + page,
        decode_block_steps=block,
        prompt_bucket=page,
        eos_token_id=None,  # budget-bound: every request emits max_new
        page_size=page,
        kv_pool_tokens=n_reqs * (plen + max_new + page),
        prefill_chunk=chunk,
    )
    eng.start()
    try:
        tag = "gen-long" if long_form else "gen"
        if pass_ == "compile":
            t0 = time.perf_counter()
            eng.warm([plen] * min(n_reqs, 8))
            dt = time.perf_counter() - t0
            log(f"bench: {tag} compile pass {dt:.1f}s")
            return {"compile_s": dt}

        rng = np.random.RandomState(1)

        def run(n, new_tokens, req_tag):
            done = threading.Event()
            got = []

            def cb(res):
                got.append(len(res.output_ids))
                if len(got) == n:
                    done.set()

            t0 = time.perf_counter()
            for i in range(n):
                eng.submit(GenRequest(
                    qid=f"{req_tag}{i}",
                    input_ids=rng.randint(
                        0, cfg.vocab_size, size=plen
                    ).tolist(),
                    max_new_tokens=new_tokens,
                    done_cb=cb,
                ))
            assert done.wait(1800), f"gen bench stalled: {len(got)}/{n}"
            return sum(got), time.perf_counter() - t0

        # Warmup compiles (or cache-loads) prefill buckets + the decode
        # block; cheap when the compile pass already banked them.
        _, wdt = run(min(n_reqs, 8), 2 * block, "w")
        log(f"bench: {tag} warmup {wdt:.2f}s")
        toks, dt = run(n_reqs, max_new, "g")
        tps = toks / dt
        log(f"bench: {tag} {toks} tokens in {dt:.2f}s -> {tps:.0f} tok/s/chip")
        key = "gen_long_tps" if long_form else "gen_tps"
        return {key: tps, "tokens": toks, "wall_s": dt}
    finally:
        eng.stop()


def gen_phase(pass_: str) -> dict:
    return _gen_run(pass_, long_form=False)


def gen_long_phase(pass_: str) -> dict:
    return _gen_run(pass_, long_form=True)


# ----------------------------------------------------------------------
# serving_http: the system-layer serving path (GenerationServer worker
# behind the SGLang-contract HTTP endpoints) — what the RL system
# actually drives, including HTTP + JSON + engine-thread handoff costs.
# ----------------------------------------------------------------------


def serving_http_phase(pass_: str) -> dict:
    import json
    import subprocess
    import tempfile
    import urllib.request
    import uuid

    # Platform via a PROBE subprocess, never an in-process backend init:
    # this phase spawns a second jax process (the server), and a TPU
    # client acquired here would be exclusive — the server child would
    # fail 'device busy' on the one platform the phase exists to measure.
    from areal_tpu.bench.daemon import probe_devices

    p = probe_devices(timeout_s=float(
        os.environ.get("AREAL_BENCH_DEVICE_BUDGET_S", 300.0)))
    if p.status != "up":
        raise RuntimeError(f"serving_http: no device ({p.status}): "
                           f"{p.detail[:300]}")
    on_tpu = p.platform == "tpu"
    if on_tpu:
        import dataclasses as _dc

        # Same flagship shape as the train/gen phases — derived, not
        # duplicated, so a retune keeps every banked number comparable.
        model_cfg = _dc.asdict(flagship_cfg())
        n_reqs, plen, max_new = 16, 256, 256
        srv = dict(max_concurrent_requests=16, max_seq_len=1024,
                   kv_page_size=128, decode_block_steps=32, prompt_bucket=128)
    else:
        model_cfg = dict(
            n_layers=2, hidden_dim=32, n_q_heads=2, n_kv_heads=2, head_dim=16,
            intermediate_dim=64, vocab_size=64, compute_dtype="float32",
            param_dtype="float32",
        )
        n_reqs, plen, max_new = 4, 8, 8
        srv = dict(max_concurrent_requests=4, max_seq_len=64,
                   kv_page_size=8, decode_block_steps=4, prompt_bucket=8)

    repo = repo_root()
    tmp = tempfile.mkdtemp(prefix="areal_bench_http_")
    nr = os.path.join(tmp, "nr")
    exp, trial = f"bench-http-{uuid.uuid4().hex[:6]}", "t0"
    child = (
        "import os, sys\n"
        f"sys.path.insert(0, {repo!r})\n"
        "from areal_tpu.utils.jaxenv import apply_jax_platform_override\n"
        "apply_jax_platform_override()\n"
        "from areal_tpu.base import name_resolve\n"
        f"name_resolve.reconfigure('nfs', record_root={nr!r})\n"
        "from areal_tpu.api.system_api import GenerationServerConfig\n"
        "from areal_tpu.api.config import ModelAbstraction\n"
        "from areal_tpu.system.generation_server import GenerationServer\n"
        "import areal_tpu.engine.factories\n"
        "cfg = GenerationServerConfig(\n"
        f"    experiment_name={exp!r}, trial_name={trial!r}, server_index=0,\n"
        "    model=ModelAbstraction('tpu_transformer',\n"
        f"        args=dict(config={model_cfg!r})),\n"
        f"    warm_on_start=True, seed=0, **{srv!r})\n"
        "w = GenerationServer()\n"
        "w.configure(cfg, experiment_name=cfg.experiment_name,\n"
        "            trial_name=cfg.trial_name, worker_name=cfg.worker_name)\n"
        "w.run()\n"
    )
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    log_path = os.path.join(tmp, "server.log")
    t_spawn = time.monotonic()
    with open(log_path, "w") as log_f:
        proc = subprocess.Popen(
            [sys.executable, "-c", child], env=env, cwd=repo,
            stdout=log_f, stderr=subprocess.STDOUT,
        )
    try:
        from areal_tpu.base import name_resolve, names

        name_resolve.reconfigure("nfs", record_root=nr)
        url = None
        deadline = time.monotonic() + 600
        while url is None:
            if proc.poll() is not None:
                with open(log_path) as f:
                    tail = f.read()[-3000:]
                raise RuntimeError(f"serving_http server died:\n{tail}")
            try:
                url = name_resolve.get(names.gen_server_url(exp, trial, "0"))
            except Exception:
                if time.monotonic() > deadline:
                    raise TimeoutError("serving_http server never registered")
                time.sleep(0.5)

        def generate(i, new_tokens):
            body = json.dumps({
                "qid": f"h{i}",
                "input_ids": list(range(1, plen + 1)),
                "gconfig": {"max_new_tokens": new_tokens, "greedy": True},
            }).encode()
            req = urllib.request.Request(
                f"{url}/generate", data=body,
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=600) as resp:
                return json.loads(resp.read())

        if pass_ == "compile":
            generate(0, srv["decode_block_steps"])
            # From spawn, not from registration: with warm_on_start the
            # XLA compiles happen BEFORE the server registers, and the
            # banked compile_s must not hide them.
            dt = time.monotonic() - t_spawn
            log(f"bench: serving_http compile pass {dt:.1f}s")
            return {"compile_s": dt}

        generate(0, srv["decode_block_steps"])  # warm
        t0 = time.monotonic()
        toks = 0
        for i in range(1, n_reqs + 1):
            out = generate(i, max_new)
            toks += len(out.get("output_ids", []))
        dt = time.monotonic() - t0
        tps = toks / dt
        log(f"bench: serving_http {toks} tokens in {dt:.2f}s "
            f"-> {tps:.0f} tok/s (serial HTTP)")
        return {"serving_http_tps": tps, "tokens": toks, "wall_s": dt}
    finally:
        proc.kill()
        proc.wait()


# ----------------------------------------------------------------------
# serving_openloop: open-loop (Poisson-arrival) tail-latency benchmark
# over a small in-process fleet. Closed-loop throughput (gen_tps,
# serving_http) cannot see overload behavior — an open-loop generator
# keeps submitting at the offered rate regardless of completions, which
# is what "millions of users" do. Sweeps arrival rates against measured
# capacity and A/Bs admission control (queue-depth watermark shedding)
# against a no-backpressure baseline at deliberate overload: with
# admission, p99 TTFT stays bounded by the watermark; without it, the
# queue (and therefore TTFT) grows with the length of the run.
# Scheduling-policy effects are visible on CPU; banked as CPU-proxy
# evidence until a device window returns.
# ----------------------------------------------------------------------


def _openloop_point(
    engines, rate, duration_s, watermark, rng, plen, max_new, vocab, tag,
):
    """One sweep point: Poisson arrivals at `rate` req/s for
    `duration_s`, least-loaded routing across `engines`, shedding when
    the least-loaded queue depth reaches `watermark` (None = no
    backpressure). Drains admitted requests, then reads the engines'
    TTFT/ITL histograms (reset per point)."""
    from areal_tpu.base.latency import merge_counts, percentile_from_counts
    from areal_tpu.engine.serving import GenRequest

    for e in engines:
        e.latency_snapshot(reset=True)
    completed = []  # list.append is atomic under the GIL
    n_arrivals = n_shed = n_admitted = 0
    # Fixed arrival COUNT (ceil(rate * duration)): at short windows the
    # Poisson-realized load of a time-based loop is too noisy for the
    # overload A/B to be deterministic; realized offered_rps is still
    # what gets recorded and bounds goodput.
    n_target = max(2, int(-(-rate * duration_s // 1)))
    t0 = time.monotonic()
    t_next = t0
    while n_arrivals < n_target:
        now = time.monotonic()
        if now < t_next:
            time.sleep(t_next - now)
        target = min(engines, key=lambda e: (e.queue_depth, e.n_running))
        if watermark is not None and target.queue_depth >= watermark:
            n_shed += 1
        else:
            n_admitted += 1
            target.submit(GenRequest(
                qid=f"{tag}{n_arrivals}",
                input_ids=rng.randint(0, vocab, size=plen).tolist(),
                max_new_tokens=max_new,
                greedy=True,
                done_cb=completed.append,
            ))
        n_arrivals += 1
        t_next += rng.exponential(1.0 / rate)
    arrival_window = time.monotonic() - t0
    drain_deadline = time.monotonic() + max(60.0, duration_s * 20.0)
    while len(completed) < n_admitted and time.monotonic() < drain_deadline:
        time.sleep(0.01)
    elapsed = time.monotonic() - t0
    snaps = [e.latency_snapshot(reset=True) for e in engines]
    ttft = merge_counts(s["ttft_counts"] for s in snaps)
    itl = merge_counts(s["itl_counts"] for s in snaps)
    return {
        "nominal_rate_rps": float(rate),
        # Realized offered load (Poisson variance makes it differ from
        # nominal at short windows); goodput can never exceed it.
        "offered_rps": n_arrivals / arrival_window,
        "duration_s": arrival_window,
        "n_arrivals": float(n_arrivals),
        "n_admitted": float(n_admitted),
        "n_shed": float(n_shed),
        "n_completed": float(len(completed)),
        "goodput_rps": len(completed) / elapsed,
        "p50_ttft_ms": percentile_from_counts(ttft, 50.0),
        "p99_ttft_ms": percentile_from_counts(ttft, 99.0),
        "itl_p50_ms": percentile_from_counts(itl, 50.0),
    }


def serving_openloop_phase(pass_: str) -> dict:
    import threading

    import jax

    from areal_tpu.engine.serving import GenRequest, ServingEngine
    from areal_tpu.models.config import TransformerConfig
    from areal_tpu.models.transformer import init_params

    n_servers = int(os.environ.get("AREAL_OPENLOOP_SERVERS") or 2)
    point_s = float(os.environ.get("AREAL_OPENLOOP_POINT_S") or 3.0)
    # Multiples of the CLOSED-LOOP capacity (batched admission, the
    # engine's peak). Open-loop sustainable throughput is lower — a
    # trickle arrival admits in singletons and loses prefill batching —
    # so ~1.0 is already past saturation and the top multiple is deep
    # overload.
    rate_mults = [
        float(x)
        for x in (os.environ.get("AREAL_OPENLOOP_RATES") or "0.25,1.0,3.0")
        .split(",")
        if x
    ]
    watermark = int(os.environ.get("AREAL_OPENLOOP_WATERMARK") or 8)
    # Geometry matches the engine test harness (tests/engine/
    # test_prefix_cache.py) so an in-process tier-1 run reuses compiled
    # programs instead of paying fresh XLA compiles.
    cfg = TransformerConfig(
        n_layers=2, hidden_dim=64, n_q_heads=4, n_kv_heads=2, head_dim=16,
        intermediate_dim=128, vocab_size=256, max_position_embeddings=512,
        compute_dtype="float32",
    )
    plen, max_new, B = 16, 16, 4
    params = init_params(cfg, jax.random.PRNGKey(3))
    engines = [
        ServingEngine(
            cfg, params,
            max_batch_size=B,
            max_seq_len=256,
            decode_block_steps=4,
            prompt_bucket=16,
            eos_token_id=None,  # budget-bound: deterministic service time
            page_size=16,
            seed=10 + i,
            prefill_token_budget=4 * plen,
        )
        for i in range(n_servers)
    ]
    for e in engines:
        e.start()
    t_start = time.monotonic()
    try:
        if pass_ == "compile":
            t0 = time.perf_counter()
            engines[0].warm([plen])
            dt = time.perf_counter() - t0
            log(f"bench: serving_openloop compile pass {dt:.1f}s")
            return {"compile_s": dt}

        rng = np.random.RandomState(5)

        def closed_loop(n, tag):
            done = threading.Event()
            got = []

            def cb(res):
                got.append(res)
                if len(got) == n:
                    done.set()

            t0 = time.monotonic()
            for i in range(n):
                engines[i % n_servers].submit(GenRequest(
                    qid=f"{tag}{i}",
                    input_ids=rng.randint(0, cfg.vocab_size, size=plen).tolist(),
                    max_new_tokens=max_new, greedy=True, done_cb=cb,
                ))
            assert done.wait(600), f"openloop warmup stalled {len(got)}/{n}"
            return n / (time.monotonic() - t0)

        # Warm every admit-batch shape the run can hit (pow2 prefill
        # batches 1/2/4 + the queued-up capacity pattern): open-loop
        # trickle arrivals admit in singletons, and an XLA compile
        # landing inside a sweep point would masquerade as queueing
        # delay in the TTFT histogram.
        for k in (1, 2):
            closed_loop(k * n_servers, f"w{k}-")
        closed_loop(4 * B * n_servers, "w")
        capacity = closed_loop(4 * B * n_servers, "c")
        log(f"bench: serving_openloop capacity ~{capacity:.1f} req/s "
            f"({n_servers} servers)")
        for e in engines:
            e.latency_snapshot(reset=True)

        sweep = []
        for mult in rate_mults:
            pt = _openloop_point(
                engines, mult * capacity, point_s, watermark, rng,
                plen, max_new, cfg.vocab_size, f"s{mult}-",
            )
            pt["rate_multiple"] = float(mult)
            sweep.append(pt)
            log(f"bench: serving_openloop x{mult}: {pt}")

        # Deliberate overload A/B at the highest sweep multiple: the
        # admission-control point above vs a no-backpressure baseline.
        overload_mult = max(rate_mults)
        adm = sweep[rate_mults.index(overload_mult)]
        base = _openloop_point(
            engines, overload_mult * capacity, point_s, None, rng,
            plen, max_new, cfg.vocab_size, "b-",
        )
        log(f"bench: serving_openloop baseline (no backpressure): {base}")
        return {
            # Closed-loop peak (admission batches full prefill rounds);
            # open-loop goodput saturates below this by design.
            "capacity_rps": capacity,
            "n_servers": float(n_servers),
            "watermark": float(watermark),
            "sweep": sweep,
            "overload_offered_rps": adm["offered_rps"],
            "overload_admission_p99_ttft_ms": adm["p99_ttft_ms"],
            "overload_admission_goodput_rps": adm["goodput_rps"],
            "overload_admission_shed": adm["n_shed"],
            "overload_baseline_p99_ttft_ms": base["p99_ttft_ms"],
            "overload_baseline_goodput_rps": base["goodput_rps"],
            "wall_s": time.monotonic() - t_start,
        }
    finally:
        for e in engines:
            e.stop()


# ----------------------------------------------------------------------
# CPU-proxy phases (never driver-verified; the runner pins them to
# JAX_PLATFORMS=cpu and the report labels them proxy evidence).
# ----------------------------------------------------------------------


def pack_density_phase(pass_: str) -> dict:
    """FFD packing density on realistic length mixes — the host-side
    fraction of shipped device cells that hold real tokens. Pure-host
    evidence for the input pipeline; pairs with the on-chip
    packing_efficiency telemetry the train phase exports."""
    from areal_tpu.base.datapack import packing_density

    if pass_ == "compile":
        return {"compile_s": 0.0}  # nothing to compile: host-only
    rng = np.random.RandomState(7)
    mixes = {
        # Short chat-style responses with a long tail.
        "chat_tail": np.clip(
            rng.lognormal(5.5, 0.8, size=512), 16, 4096
        ).astype(int),
        # Reasoning-style long generations (the reference's ~31k regime,
        # scaled to the flagship bench context).
        "reasoning": np.clip(
            rng.lognormal(7.8, 0.5, size=256), 256, 16384
        ).astype(int),
        # Uniform mid-length SFT corpus.
        "sft_uniform": rng.randint(128, 2048, size=512),
    }
    t0 = time.perf_counter()
    out = {}
    for name, lengths in mixes.items():
        out[f"density_{name}"] = packing_density(
            lengths.tolist(), row_len_multiple=128, max_row_len=16384
        )
    out["wall_s"] = time.perf_counter() - t0
    log(f"bench: pack_density {out}")
    return out


def prefetch_overlap_phase(pass_: str) -> dict:
    """Input-pipeline overlap telemetry on the 1-device CPU engine: the
    packing_efficiency / h2d_wait_ms / dispatch_gap_ms series from a
    multi-microbatch train loop through the prefetched pipeline. Proxy
    evidence that the overlap path engages and its telemetry is sane —
    absolute numbers only mean anything on-chip."""
    import jax

    from areal_tpu.api.data_api import MicroBatchSpec, SequenceSample
    from areal_tpu.base import stats_tracker
    from areal_tpu.engine.jax_engine import JaxTrainEngine
    from areal_tpu.engine.optimizer import OptimizerConfig
    from areal_tpu.models.transformer import init_params
    from areal_tpu.ops.loss import sft_loss_from_logprobs

    cfg = smoke_cfg()
    seqlen, n_seqs = 128, 8
    params = init_params(cfg, jax.random.PRNGKey(0))
    eng = JaxTrainEngine(
        cfg, params,
        optimizer_config=OptimizerConfig(lr=1e-4, warmup_steps_proportion=0.0),
        total_train_steps=100, row_len_multiple=seqlen, max_row_len=seqlen,
        remat="full", prefetch_depth=2,
    )
    rng = np.random.RandomState(0)
    total = seqlen * n_seqs
    batch = SequenceSample.from_default(
        ids=[f"b{i}" for i in range(n_seqs)],
        seqlens=[seqlen] * n_seqs,
        data={
            "packed_input_ids": rng.randint(0, cfg.vocab_size, size=total),
            "loss_mask": np.ones(total, np.float32),
        },
    )

    def packed_loss(lp, rows):
        tot, n = sft_loss_from_logprobs(lp, rows["loss_mask"])
        return tot, {}

    def weight(mb):
        return float(np.sum(mb.data["loss_mask"]))

    spec = MicroBatchSpec(n_mbs=4)
    if pass_ == "compile":
        t0 = time.perf_counter()
        eng.train_batch(batch, spec, packed_loss, weight, loss_name="bench")
        jax.block_until_ready(eng.params)
        return {"compile_s": time.perf_counter() - t0}

    eng.train_batch(batch, spec, packed_loss, weight, loss_name="bench")
    stats_tracker.export(key="perf")  # drain warmup telemetry
    n_steps = 3
    t0 = time.perf_counter()
    for i in range(n_steps):
        eng.train_batch(batch, spec, packed_loss, weight,
                        version_steps=i + 1, loss_name="bench")
    jax.block_until_ready(eng.params)
    dt = (time.perf_counter() - t0) / n_steps
    perf = stats_tracker.export(key="perf")
    out = {
        k[len("perf/"):]: float(v) for k, v in perf.items()
        if k in ("perf/packing_efficiency", "perf/h2d_wait_ms",
                 "perf/dispatch_gap_ms", "perf/overlap_events")
    }
    out["step_s"] = dt
    log(f"bench: prefetch_overlap {out}")
    return out


def weight_update_phase(pass_: str) -> dict:
    """Weight-distribution plane end-to-end on loopback HTTP: dump a
    raw-bin payload, serve it from a WeightPlaneSource origin, fan it
    out to 3 holders along a degree-1 chain (the maximum-peer-hop
    shape), then host-assemble each holder's buffer as the cutover
    proxy. Proxy evidence by construction (no device swap, no real
    serving engine): what it banks is the plane's software overhead —
    chunk/hash/HTTP cost per MB — and the O(1)-origin-egress invariant
    (``origin_full_payloads`` must stay ~1.0; the validator refuses
    records where peer fanout silently degraded to origin broadcast)."""
    if pass_ == "compile":
        return {"compile_s": 0.0}  # host + loopback only
    import shutil
    import tempfile

    from areal_tpu.engine.weight_client import assemble_params
    from areal_tpu.system.weight_plane import (
        WeightPlaneSource, distribute_to_stores,
    )
    from areal_tpu.system.weight_transfer import dump_raw_params

    rng = np.random.RandomState(0)
    # ~16 MiB payload: big enough that per-chunk overhead is amortized
    # like production, small enough for a sub-30s proxy phase.
    params = {
        "layers": {
            f"l{i:02d}": {
                "w": rng.standard_normal((512, 256)).astype(np.float32)
            }
            for i in range(32)
        }
    }
    n_holders, version = 3, 1
    tmp = tempfile.mkdtemp(prefix="areal_wp_bench_")
    holders, src = [], None
    try:
        dump_raw_params(params, tmp, version=version, chunk_bytes=1 << 20)
        src = WeightPlaneSource(tmp, chunk_bytes=1 << 20).start()
        t0 = time.perf_counter()
        holders, stats = distribute_to_stores(
            src.address, n_holders, degree=1, version=version
        )
        cutover_ms = []
        for h in holders:
            t1 = time.perf_counter()
            assemble_params(h.store)
            cutover_ms.append((time.perf_counter() - t1) * 1000.0)
        wall_ms = (time.perf_counter() - t0) * 1000.0
        origin_eq = src.stats()["full_payload_equivalents"].get(version, 0.0)
        out = {
            "weight_update_ms": wall_ms,
            "weight_transfer_ms": max(
                s["fetch_s"] for s in stats["per_holder"].values()
            ) * 1000.0,
            "weight_cutover_ms": max(cutover_ms),
            "origin_full_payloads": origin_eq,
            "n_holders": float(n_holders),
            "payload_mb": stats["total_bytes"] / float(1 << 20),
            "n_chunks": float(stats["n_chunks"]),
        }
        log(f"bench: weight_update {out}")
        return out
    finally:
        for h in holders:
            h.close()
        if src is not None:
            src.close()
        shutil.rmtree(tmp, ignore_errors=True)

"""Worker lifecycle: control commands pause/start/exit, status mirror."""

import threading
import time

from areal_tpu.system.worker_base import (
    PollResult,
    Worker,
    WorkerControl,
    WorkerServer,
    WorkerServerStatus,
    worker_status,
)


class _CountingWorker(Worker):
    def __init__(self, server):
        super().__init__(server)
        self.polls = 0

    def _configure(self, config):
        pass

    def _poll(self):
        self.polls += 1
        time.sleep(0.005)
        return PollResult(sample_count=1, batch_count=1)


def test_worker_control_roundtrip(tmp_name_resolve, experiment_context):
    exp, trial = experiment_context
    server = WorkerServer(exp, trial, "w0")
    w = _CountingWorker(server)
    w.configure(object(), exp, trial, "w0")

    t = threading.Thread(target=w.run, daemon=True)
    t.start()
    try:
        ctl = WorkerControl(exp, trial, "w0", timeout=10)
        assert ctl.command("status", timeout_ms=5000) == "RUNNING"

        ctl.command("pause", timeout_ms=5000)
        time.sleep(0.05)
        p0 = w.polls
        time.sleep(0.1)
        assert w.polls == p0  # paused: no progress
        assert worker_status(exp, trial, "w0") == WorkerServerStatus.PAUSED

        ctl.command("start", timeout_ms=5000)
        time.sleep(0.1)
        assert w.polls > p0

        ctl.command("exit", timeout_ms=5000)
        t.join(timeout=5)
        assert not t.is_alive()
        assert worker_status(exp, trial, "w0") == WorkerServerStatus.COMPLETED
        ctl.close()
    finally:
        w.exit()
        t.join(timeout=2)
        server.close()

"""Test-only bench phases: cheap, deterministic, registered off the
default set so the real bench never runs them.

The runner subprocess imports this module through
``AREAL_BENCH_PHASE_MODULES=tests.system.bench_phases``, so a phase a
test registers here exists in the child that executes it. Each phase
body bumps a per-(phase, pass) call counter under
``AREAL_BENCH_TEST_SCRATCH`` — that is how tests prove a resumed run
re-executed ONLY the unbanked phases.
"""

import os
import time

from areal_tpu.bench import phases

SCRATCH_ENV = "AREAL_BENCH_TEST_SCRATCH"


def bump_counter(name: str) -> int:
    d = os.environ.get(SCRATCH_ENV)
    if not d:
        return 0
    path = os.path.join(d, f"{name}.calls")
    n = 1
    if os.path.exists(path):
        with open(path) as f:
            n = int(f.read()) + 1
    with open(path, "w") as f:
        f.write(str(n))
    return n


def read_counter(scratch: str, name: str) -> int:
    path = os.path.join(scratch, f"{name}.calls")
    if not os.path.exists(path):
        return 0
    with open(path) as f:
        return int(f.read())


def alpha(pass_: str) -> dict:
    bump_counter(f"t_alpha.{pass_}")
    if pass_ == "compile":
        return {"compile_s": 0.01}
    return {"alpha_metric": 42.0}


def beta(pass_: str) -> dict:
    bump_counter(f"t_beta.{pass_}")
    if pass_ == "compile":
        return {"compile_s": 0.01}
    return {"beta_metric": 7.0}


def broken(pass_: str) -> dict:
    bump_counter(f"t_broken.{pass_}")
    raise RuntimeError("this phase always fails (test)")


def slow(pass_: str) -> dict:
    bump_counter(f"t_slow.{pass_}")
    time.sleep(float(os.environ.get("AREAL_BENCH_TEST_SLOW_S", 3600)))
    return {"slow_metric": 1.0}


def _reg(name, entry, **kw):
    # Idempotent under repeated pytest imports of this module path.
    try:
        phases.get(name)
        return
    except KeyError:
        pass
    phases.register(phases.PhaseSpec(
        name=name, entrypoint=f"tests.system.bench_phases:{entry}",
        default=False, est_compile_s=1.0, est_measure_s=1.0,
        min_window_s=0.0, **kw,
    ))


_reg("t_alpha", "alpha", priority=90)
_reg("t_beta", "beta", priority=91)
_reg("t_broken", "broken", priority=92)
_reg("t_slow", "slow", priority=93)

"""Versioned KV-handoff wire format for disaggregated prefill/decode.

A prefill-role engine finishes a prompt's chunked prefill, then exports
the request's filled KV pages plus the first sampled token as a
*handoff blob*: a JSON meta dict describing typed array segments inside
one contiguous payload, chunk-indexed with the same content hashing the
weight-distribution plane uses (base/chunking.py) so the decode-side
server can pull it over HTTP with per-chunk verification and mid-chunk
Range resume. The hash, not the peer, is the authority — exactly the
weight-plane rule.

Wire layout is page-agnostic (token-major ``[L, Hkv, n_tokens, hd]``):
the exporting and importing engines may run different page sizes or
even different KV pool precisions. ``kv_wire`` is either a float dtype
name (the exporter's pool precision), ``"int8"`` (quantized
``data + scales`` pairs via engine/paged.quantize_kv — the exporter
either holds an int8 pool already or compressed at export), or
``"fp8"`` (e4m3 ``data + scales`` pairs via quantize_kv_fp8 below —
same 1-byte-per-element wire footprint as int8 but a floating
mantissa, so small-magnitude KV keeps relative precision instead of
collapsing onto integer steps); the importer always reconstructs
float K/V and lets ``scatter_prefill`` re-quantize if its own pool
is int8.

Kept jax-free (numpy + stdlib) so the server-side transfer code and
tests can use it without touching a device.
"""

from __future__ import annotations

import hashlib
from typing import Dict, List, Tuple

import numpy as np

from areal_tpu.base.chunking import chunk_spans, hash_chunk
from areal_tpu.base.wire_schemas import KV_HANDOFF_V1 as HANDOFF_SCHEMA

# 256 KiB: handoff blobs are MB-scale (one request's KV), so chunks are
# small enough that a torn transfer re-pays little and large enough
# that per-chunk HTTP overhead stays noise.
DEFAULT_CHUNK_BYTES = 256 << 10


class KVHandoffError(RuntimeError):
    """Malformed / incompatible handoff blob."""


class KVHandoffVersionMismatch(KVHandoffError):
    """The blob's weight version differs from the importing engine's —
    importing would decode against KV from other weights."""


# Largest finite e4m3 value: the fp8 wire normalizes each
# per-(layer, head, token) vector's absmax onto it, mirroring the int8
# wire's KV_INT8_MAX convention (paged.py) with a floating mantissa.
KV_FP8_MAX = 448.0


def _np_dtype(name: str) -> np.dtype:
    if name == "bfloat16" or name.startswith("float8"):
        import ml_dtypes  # noqa: F401  registers the dtype by name
    return np.dtype(name)


def quantize_kv_fp8(x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """(data, scales) for the e4m3 spill/handoff wire: data is
    ``float8_e4m3fn [L, Hkv, n, hd]`` scaled so each (L, H, token)
    vector's absmax lands on KV_FP8_MAX (full exponent range used),
    scales is ``float32 [L, Hkv, n]``. Numpy-only — runs on the spill
    worker thread, no device round trip."""
    import ml_dtypes

    xh = np.asarray(x, np.float32)
    s = np.maximum(np.max(np.abs(xh), axis=-1), 1e-8)
    w = (xh / s[..., None] * KV_FP8_MAX).astype(
        ml_dtypes.float8_e4m3fn)
    return w, s.astype(np.float32)


def pack_arrays(
    arrays: List[Tuple[str, np.ndarray]],
    chunk_bytes: int = DEFAULT_CHUNK_BYTES,
) -> Tuple[List[Dict], Dict, bytes]:
    """Serialize named arrays into (segments, chunk_index, payload).

    ``segments`` records name/dtype/shape/offset per array;
    ``chunk_index`` is the base/chunking-style hash index over the
    whole payload ({chunk_bytes, total_bytes, n_chunks, hashes})."""
    segments: List[Dict] = []
    parts: List[bytes] = []
    off = 0
    for name, arr in arrays:
        arr = np.ascontiguousarray(arr)
        raw = arr.tobytes()
        segments.append({
            "name": name,
            "dtype": arr.dtype.name,
            "shape": list(arr.shape),
            "offset": off,
            "nbytes": len(raw),
        })
        parts.append(raw)
        off += len(raw)
    payload = b"".join(parts)
    index = {
        "chunk_bytes": int(chunk_bytes),
        "total_bytes": len(payload),
        "n_chunks": -(-len(payload) // chunk_bytes) if payload else 0,
        "hashes": [
            hash_chunk(payload[o: o + ln])
            for o, ln in chunk_spans(len(payload), chunk_bytes)
        ],
    }
    return segments, index, payload


def unpack_arrays(meta: Dict, payload: bytes, verify: bool = True) -> Dict[str, np.ndarray]:
    """Segments back to named arrays (zero-copy views over ``payload``).

    With ``verify`` the payload is re-hashed against the chunk index —
    cheap relative to the device write, and it makes the blob
    self-authenticating even when the transport already verified."""
    if meta.get("schema") != HANDOFF_SCHEMA:
        raise KVHandoffError(
            f"schema {meta.get('schema')!r} != {HANDOFF_SCHEMA!r}"
        )
    index = meta.get("chunks") or {}
    if len(payload) != int(index.get("total_bytes", -1)):
        raise KVHandoffError(
            f"payload is {len(payload)} bytes, index says "
            f"{index.get('total_bytes')}"
        )
    if verify:
        cb = int(index["chunk_bytes"])
        for i, (off, ln) in enumerate(chunk_spans(len(payload), cb)):
            if hash_chunk(payload[off: off + ln]) != index["hashes"][i]:
                raise KVHandoffError(f"chunk {i} hash mismatch")
    out: Dict[str, np.ndarray] = {}
    for seg in meta["segments"]:
        dt = _np_dtype(seg["dtype"])
        off, nb = int(seg["offset"]), int(seg["nbytes"])
        out[seg["name"]] = np.frombuffer(
            payload, dtype=dt, count=nb // dt.itemsize, offset=off
        ).reshape(seg["shape"])
    return out


def build_meta(
    qid: str,
    version: int,
    tokens: List[int],
    kv_wire: str,
    cfg,
    segments: List[Dict],
    chunks: Dict,
) -> Dict:
    return {
        "schema": HANDOFF_SCHEMA,
        "qid": str(qid),
        "version": int(version),
        "n_tokens": len(tokens),
        # Prefix identity for the tiered-KV plane's global index: two
        # holders of the same hash hold interchangeable KV (same tokens,
        # same version check at import).
        "content_hash": prefix_content_hash(tokens),
        "tokens": [int(t) for t in tokens],
        "kv_wire": kv_wire,
        "n_layers": int(cfg.n_layers),
        "n_kv_heads": int(cfg.n_kv_heads),
        "head_dim": int(cfg.head_dim),
        "segments": segments,
        "chunks": chunks,
    }


def check_geometry(meta: Dict, cfg) -> None:
    """The importing engine must share the exporter's attention geometry
    (page size may differ — the wire is token-major — but layer count,
    KV heads, and head dim are baked into the gathered arrays)."""
    for field, want in (
        ("n_layers", cfg.n_layers),
        ("n_kv_heads", cfg.n_kv_heads),
        ("head_dim", cfg.head_dim),
    ):
        got = meta.get(field)
        if int(got) != int(want):
            raise KVHandoffError(
                f"geometry mismatch: blob {field}={got}, engine has {want}"
            )


def prefix_content_hash(tokens: List[int]) -> str:
    """Content hash of a token prefix — the identity the tiered-KV
    plane's global index keys on besides the qid (two sessions sharing
    an exact prefix hash identically; a qid reused for different content
    does not). Stable across processes: hashes the int64-LE encoding."""
    return hashlib.sha256(
        np.asarray(tokens, np.int64).tobytes()
    ).hexdigest()


def unpack_kv_int8(meta: Dict, payload: bytes, verify: bool = True):
    """(k_data, k_scales, v_data, v_scales) for an int8 wire WITHOUT the
    float round trip: an int8 KV pool scatters these straight back in
    (paged.scatter_prefill_int8), so a spill + restore of an int8 pool
    is bit-exact and never pays quantize→dequantize→quantize.

    Raises KVHandoffError for non-int8 wires — the caller dispatches on
    ``meta["kv_wire"]``."""
    if meta.get("kv_wire") != "int8":
        raise KVHandoffError(
            f"unpack_kv_int8 on a {meta.get('kv_wire')!r} wire"
        )
    arrs = unpack_arrays(meta, payload, verify=verify)
    return (
        np.asarray(arrs["k_data"], np.int8),
        np.asarray(arrs["k_scales"], np.float32),
        np.asarray(arrs["v_data"], np.int8),
        np.asarray(arrs["v_scales"], np.float32),
    )


def unpack_kv_float(meta: Dict, payload: bytes, verify: bool = True):
    """(k, v) as float32 numpy [L, Hkv, n_tokens, hd], dequantizing an
    int8 wire via the paged-pool convention (KV_INT8_MAX) or an fp8
    wire via KV_FP8_MAX."""
    arrs = unpack_arrays(meta, payload, verify=verify)
    if meta["kv_wire"] == "int8":
        from areal_tpu.engine.paged import KV_INT8_MAX

        def deq(w, s):
            return (
                w.astype(np.float32) * (s[..., None] / KV_INT8_MAX)
            ).astype(np.float32)

        return (
            deq(arrs["k_data"], arrs["k_scales"]),
            deq(arrs["v_data"], arrs["v_scales"]),
        )
    if meta["kv_wire"] == "fp8":

        def deq8(w, s):
            return (
                w.astype(np.float32) * (s[..., None] / KV_FP8_MAX)
            ).astype(np.float32)

        return (
            deq8(arrs["k_data"], arrs["k_scales"]),
            deq8(arrs["v_data"], arrs["v_scales"]),
        )
    return (
        np.asarray(arrs["k"], dtype=np.float32),
        np.asarray(arrs["v"], dtype=np.float32),
    )

"""Expert parallelism: MoE expert weights shard E over the fsdp mesh
axis (parallel/sharding.py), the GShard-style einsum dispatch makes XLA
insert the token all-to-all, and sharded results match single-device
bit-for-near (the reference has no expert parallelism — this exceeds
parity)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from areal_tpu.base.topology import MeshSpec
from areal_tpu.models.config import MoEConfig, TransformerConfig
from areal_tpu.models.transformer import forward, init_params
from areal_tpu.parallel.mesh import make_mesh
from areal_tpu.parallel.sharding import param_shardings, shard_params

CFG = TransformerConfig(
    n_layers=2,
    hidden_dim=32,
    n_q_heads=4,
    n_kv_heads=2,
    head_dim=8,
    intermediate_dim=64,
    vocab_size=64,
    compute_dtype="float32",
    param_dtype="float32",
    moe=MoEConfig(
        num_experts=8, top_k=2, expert_intermediate_dim=32,
        capacity_factor=2.0,
    ),
)


@pytest.fixture(scope="module")
def params():
    return init_params(CFG, jax.random.PRNGKey(0))


def test_expert_weights_shard_over_fsdp(params):
    mesh = make_mesh(MeshSpec.parse("d1f4t2"))
    sh = param_shardings(params, mesh)
    mlp = sh["layers"]["mlp"]
    assert mlp["w_gate"].spec == P(None, "fsdp", None, "tensor")
    assert mlp["w_up"].spec == P(None, "fsdp", None, "tensor")
    assert mlp["w_down"].spec == P(None, "fsdp", "tensor", None)
    assert mlp["router"].spec == P(None, None, None)
    # 8 experts / fsdp=4 -> 2 experts per shard.
    shard_shape = mlp["w_gate"].shard_shape(
        params["layers"]["mlp"]["w_gate"].shape
    )
    assert shard_shape[1] == 2


@pytest.mark.parametrize("spec_str", ["d1f4t2", "d2f2s1t2", "f8"])
def test_moe_forward_matches_single_device(params, spec_str):
    rng = np.random.RandomState(0)
    R, T = 2, 32
    ids = jnp.asarray(rng.randint(0, CFG.vocab_size, size=(R, T)))
    seg = jnp.ones((R, T), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(T), (R, T)).astype(jnp.int32)

    ref = forward(params, CFG, ids, seg, pos, attn_impl="reference")

    mesh = make_mesh(MeshSpec.parse(spec_str))
    sharded = shard_params(params, mesh)

    @jax.jit
    def f(p, i, s, po):
        return forward(p, CFG, i, s, po, attn_impl="reference")

    with jax.sharding.use_mesh(mesh) if hasattr(jax.sharding, "use_mesh") else mesh:
        out = f(sharded, ids, seg, pos)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4
    )


def test_moe_ep_gradients_match(params):
    """Grad parity: expert-sharded backward (all-to-all transposes) ==
    single-device backward."""
    rng = np.random.RandomState(1)
    R, T = 2, 16
    ids = jnp.asarray(rng.randint(0, CFG.vocab_size, size=(R, T)))
    seg = jnp.ones((R, T), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(T), (R, T)).astype(jnp.int32)

    def loss(p):
        lg = forward(p, CFG, ids, seg, pos, attn_impl="reference")
        return jnp.mean(lg.astype(jnp.float32) ** 2)

    g_ref = jax.grad(loss)(params)

    mesh = make_mesh(MeshSpec.parse("d1f4t2"))
    sharded = shard_params(params, mesh)
    g_sh = jax.jit(jax.grad(loss))(sharded)

    ref_leaf = g_ref["layers"]["mlp"]["w_gate"]
    sh_leaf = g_sh["layers"]["mlp"]["w_gate"]
    np.testing.assert_allclose(
        np.asarray(sh_leaf), np.asarray(ref_leaf), rtol=2e-3, atol=2e-4
    )


def _layer_mlp(params):
    return jax.tree_util.tree_map(lambda a: a[0], params["layers"]["mlp"])


def _dropless_cfg():
    import dataclasses

    return dataclasses.replace(
        CFG, moe=dataclasses.replace(CFG.moe, dispatch="dropless")
    )


@pytest.mark.parametrize("spec_str", ["f2", "f4", "d2f2", "d1f2t2"])
def test_moe_dropless_ep_matches_single_device(params, spec_str):
    """The shard_map EP dropless path (all-gather + local ragged_dot +
    psum_scatter) must agree with the single-device ragged_dot oracle —
    per-row matmuls are order-independent, so float32 agreement is
    essentially exact."""
    from areal_tpu.models.moe import moe_ep_degree, moe_mlp

    cfg = _dropless_cfg()
    lp = _layer_mlp(params)
    x = jax.random.normal(jax.random.PRNGKey(7), (4, 16, CFG.hidden_dim),
                          jnp.float32)
    y_ref, aux_ref = moe_mlp(x, lp, cfg, jnp.float32)

    spec = MeshSpec.parse(spec_str)
    mesh = make_mesh(spec, jax.devices()[: spec.size])
    assert moe_ep_degree(cfg, mesh, x.shape) == mesh.shape["fsdp"]
    y_ep, aux_ep = jax.jit(
        lambda xx: moe_mlp(xx, lp, cfg, jnp.float32, mesh=mesh)
    )(x)
    np.testing.assert_allclose(
        np.asarray(y_ep), np.asarray(y_ref), rtol=1e-6, atol=1e-6
    )
    assert float(aux_ep["drop_rate"]) == 0.0
    assert float(aux_ep["a2a_bytes"]) > 0.0
    np.testing.assert_allclose(
        np.asarray(aux_ep["load_balance_loss"]),
        np.asarray(aux_ref["load_balance_loss"]), rtol=1e-5,
    )
    np.testing.assert_allclose(
        np.asarray(aux_ep["expert_load"]),
        np.asarray(aux_ref["expert_load"]), rtol=1e-5, atol=1e-7,
    )
    np.testing.assert_allclose(
        np.asarray(aux_ep["router_entropy"]),
        np.asarray(aux_ref["router_entropy"]), rtol=1e-5,
    )


def test_moe_dropless_ep_gradients_match(params):
    """Backward through the exchange (all_gather <-> psum_scatter are
    transposes) must match the single-device dropless backward."""
    from areal_tpu.models.moe import moe_mlp

    cfg = _dropless_cfg()
    lp = _layer_mlp(params)
    x = jax.random.normal(jax.random.PRNGKey(8), (4, 16, CFG.hidden_dim),
                          jnp.float32)

    def loss(p, xx, mesh):
        y, aux = moe_mlp(xx, p, cfg, jnp.float32, mesh=mesh)
        return jnp.sum(y.astype(jnp.float32) ** 2) + aux["load_balance_loss"]

    g_ref = jax.grad(loss)(lp, x, None)
    mesh = make_mesh(MeshSpec.parse("f2"), jax.devices()[:2])
    g_ep = jax.jit(jax.grad(lambda p, xx: loss(p, xx, mesh)))(lp, x)
    for k in ("router", "w_gate", "w_up", "w_down"):
        np.testing.assert_allclose(
            np.asarray(g_ep[k]), np.asarray(g_ref[k]),
            rtol=2e-5, atol=2e-6, err_msg=k,
        )


def test_moe_ep_degree_gating():
    """moe_ep_degree: fsdp extent when it divides E and the activation
    tiling fits; 1 (GSPMD fallback) otherwise."""
    import dataclasses

    from areal_tpu.models.moe import moe_ep_degree

    cfg = _dropless_cfg()
    mesh = make_mesh(MeshSpec.parse("f4"), jax.devices()[:4])
    assert moe_ep_degree(cfg, mesh) == 4
    assert moe_ep_degree(cfg, None) == 1
    # E=6 doesn't divide fsdp=4 -> no shard_map (sharding falls back to
    # hidden-dim ZeRO, ragged_dot contracts an unsharded expert axis).
    cfg6 = dataclasses.replace(
        cfg, moe=dataclasses.replace(cfg.moe, num_experts=6)
    )
    assert moe_ep_degree(cfg6, mesh) == 1
    # Activation rows must tile over (data, fsdp): 3 rows on f2 don't.
    mesh2 = make_mesh(MeshSpec.parse("f2"), jax.devices()[:2])
    assert moe_ep_degree(cfg, mesh2, (3, 16, 32)) == 1
    assert moe_ep_degree(cfg, mesh2, (4, 16, 32)) == 2
    assert moe_ep_degree(cfg, mesh2, (4, 16)) == 1  # decode [T, D] shapes


def test_indivisible_experts_fall_back_to_zero_sharding():
    """E=6 on fsdp=4 can't shard experts — the hidden dim takes the fsdp
    axis instead, so ZeRO-3 never silently degrades to replication."""
    import dataclasses

    cfg = dataclasses.replace(
        CFG,
        moe=dataclasses.replace(CFG.moe, num_experts=6),
    )
    p6 = init_params(cfg, jax.random.PRNGKey(3))
    mesh = make_mesh(MeshSpec.parse("d1f4t2"))
    sh = param_shardings(p6, mesh)
    mlp = sh["layers"]["mlp"]
    assert mlp["w_gate"].spec == P(None, None, "fsdp", "tensor")
    assert mlp["w_down"].spec == P(None, None, "tensor", "fsdp")
    # And the fallback numerics still match single-device.
    rng = np.random.RandomState(2)
    R, T = 2, 16
    ids = jnp.asarray(rng.randint(0, cfg.vocab_size, size=(R, T)))
    seg = jnp.ones((R, T), jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(T), (R, T)).astype(jnp.int32)
    ref = forward(p6, cfg, ids, seg, pos, attn_impl="reference")
    sharded = shard_params(p6, mesh)
    out = jax.jit(
        lambda p, i, s, po: forward(p, cfg, i, s, po, attn_impl="reference")
    )(sharded, ids, seg, pos)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4
    )

"""Content-addressed chunking (base/chunking.py): span math, index
build, torn-write detection, and hash verification — the shared
"what is a chunk" definition the weight plane's source, client, and
bench workload all depend on."""

import os

import pytest

from areal_tpu.base.chunking import (
    CHUNK_SCHEMA,
    build_chunk_index,
    chunk_spans,
    gather_stream,
    hash_chunk,
    shard_stream_plan,
    slice_byte_ranges,
    verify_chunk,
)


def test_chunk_spans_cover_exactly():
    spans = chunk_spans(10, 4)
    assert spans == [(0, 4), (4, 4), (8, 2)]
    # Exact multiple: no short tail.
    assert chunk_spans(8, 4) == [(0, 4), (4, 4)]
    # Zero-byte payload has zero chunks.
    assert chunk_spans(0, 4) == []


def test_chunk_spans_rejects_bad_chunk_size():
    with pytest.raises(ValueError, match="chunk_bytes"):
        chunk_spans(10, 0)


def test_build_index_roundtrip(tmp_path):
    payload = bytes(range(256)) * 40  # 10240 bytes
    p = tmp_path / "params.bin"
    p.write_bytes(payload)
    idx = build_chunk_index(str(p), chunk_bytes=4096)
    assert idx["schema"] == CHUNK_SCHEMA
    assert idx["total_bytes"] == len(payload)
    assert idx["n_chunks"] == 3
    # Every hash verifies against the actual bytes, and a flipped byte
    # fails exactly its own chunk.
    for i, (off, length) in enumerate(chunk_spans(len(payload), 4096)):
        assert verify_chunk(payload[off:off + length], idx["hashes"][i])
    corrupt = bytearray(payload)
    corrupt[4100] ^= 0xFF
    assert not verify_chunk(corrupt[4096:8192], idx["hashes"][1])
    assert verify_chunk(corrupt[:4096], idx["hashes"][0])


def test_build_index_detects_concurrent_truncation(tmp_path):
    """The GC/torn-write race: the bin shrinks between getsize and the
    read — build_chunk_index must raise (callers retry on a refreshed
    manifest), never return an index for bytes it didn't hash."""
    p = tmp_path / "params.bin"
    p.write_bytes(b"x" * 8192)

    real_getsize = os.path.getsize

    def lying_getsize(path):
        return real_getsize(path) + 4096  # pretends the bin is longer

    orig = os.path.getsize
    os.path.getsize = lying_getsize
    try:
        with pytest.raises(OSError, match="short read"):
            build_chunk_index(str(p), chunk_bytes=4096)
    finally:
        os.path.getsize = orig


def test_hash_accepts_memoryview():
    data = b"hello chunk"
    assert hash_chunk(memoryview(data)) == hash_chunk(data)
    assert verify_chunk(memoryview(data), hash_chunk(data))


# ----------------------------------------------------------------------
# Slice -> byte-range resolution (the shard-aware manifest layer)
# ----------------------------------------------------------------------


def test_slice_byte_ranges_match_numpy_row_major():
    """The covering ranges must gather exactly the bytes numpy's own
    row-major slicing produces, with contiguous runs maximized."""
    import numpy as np

    cases = [
        ((4, 6), [(0, 4), (2, 5)]),
        ((3, 4, 8), [(1, 3), (0, 4), (0, 8)]),  # fully-covered suffix
        ((3, 4, 8), [(0, 3), (1, 3), (2, 6)]),
        ((5,), [(2, 5)]),
        ((), []),  # scalar leaf: one full-extent range
        ((2, 2, 2, 2), [(0, 2), (1, 2), (0, 2), (0, 1)]),
    ]
    for shape, slices in cases:
        arr = np.arange(
            int(np.prod(shape, dtype=np.int64) or 1), dtype=np.int32
        ).reshape(shape)
        blob = b"\0" * 128 + arr.tobytes()
        ranges = slice_byte_ranges(128, shape, 4, slices)
        got = b"".join(blob[o:o + n] for o, n in ranges)
        want = np.ascontiguousarray(
            arr[tuple(slice(a, b) for a, b in slices)]
        ).tobytes()
        assert got == want, (shape, slices)
        # Sorted, non-overlapping, non-adjacent (maximally coalesced).
        for (o1, n1), (o2, _) in zip(ranges, ranges[1:]):
            assert o1 + n1 < o2
    # Full coverage of every dim collapses to ONE range.
    assert slice_byte_ranges(0, (3, 4), 4, [(0, 3), (0, 4)]) == [(0, 48)]
    # Empty slice: nothing to fetch.
    assert slice_byte_ranges(0, (3, 4), 4, [(1, 1), (0, 4)]) == []
    with pytest.raises(ValueError, match="out of bounds"):
        slice_byte_ranges(0, (3, 4), 4, [(0, 5), (0, 4)])


def test_shard_plan_tiles_exactly_per_rank():
    """ISSUE 8 round-trip: over every tensor-parallel coordinate, the
    sharded leaves' ranges tile each leaf's extent exactly — no overlap,
    no gap — and replicated leaves appear once per rank (the epsilon).
    Slices come from the REAL partition specs (parallel/sharding.py),
    so this pins the manifest layer to what the engine actually
    places."""
    import numpy as np

    from areal_tpu.parallel.sharding import tensor_shard_slices

    leaves = {
        "embedding/weight": (64, 32),
        "head/weight": (32, 64),
        "layers/attn/wq": (4, 32, 48),   # column-parallel
        "layers/attn/wo": (4, 48, 32),   # row-parallel
        "layers/mlp/w_up": (4, 32, 128),
        "layers/norm/scale": (4, 32),    # replicated
    }
    itemsize = 4
    for degree in (1, 2, 4):
        offset = 0
        for path, shape in leaves.items():
            nbytes = int(np.prod(shape)) * itemsize
            per_rank = [
                slice_byte_ranges(
                    offset, shape, itemsize,
                    tensor_shard_slices(path, shape, degree, r),
                )
                for r in range(degree)
            ]
            replicated = (
                tensor_shard_slices(path, shape, degree, 0)
                == [(0, d) for d in shape]
            )
            if replicated:
                for rr in per_rank:
                    assert rr == [(offset, nbytes)]
            else:
                counts = np.zeros(nbytes, np.int32)
                for rr in per_rank:
                    for o, n in rr:
                        assert offset <= o and o + n <= offset + nbytes
                        counts[o - offset:o - offset + n] += 1
                # Exact tiling: every byte covered exactly once.
                assert (counts == 1).all(), (path, degree)
            offset += nbytes


def test_shard_stream_plan_and_gather_roundtrip():
    import numpy as np

    rng = np.random.default_rng(0)
    blob = bytearray()
    segs, arrs, off = [], {}, 0
    for name, shape, slices in [
        ("a", (4, 6), [(0, 4), (0, 3)]),
        ("b", (8,), [(0, 8)]),
        ("c", (2, 3, 4), [(0, 2), (1, 2), (0, 4)]),
    ]:
        arr = rng.integers(0, 127, size=shape).astype(np.int32)
        arrs[name] = (arr, slices)
        blob += arr.tobytes()
        segs.append({"path": name, "offset": off, "shape": list(shape),
                     "nbytes": arr.nbytes, "slices": slices})
        off += arr.nbytes
    plan = shard_stream_plan(segs)

    def read_at(o, n):
        return bytes(blob[o:o + n])

    stream = gather_stream(read_at, plan["ranges"], 0, plan["total_bytes"])
    for seg in plan["segments"]:
        arr, slices = arrs[seg["path"]]
        want = np.ascontiguousarray(
            arr[tuple(slice(a, b) for a, b in slices)]
        )
        got = np.frombuffer(
            stream, np.int32, count=seg["local_nbytes"] // 4,
            offset=seg["local_offset"],
        ).reshape(seg["local_shape"])
        assert np.array_equal(got, want)
    # Windowed gathers agree with the full stream (the origin serves
    # chunk windows of the virtual stream this way).
    for start, ln in [(0, 7), (5, 33), (plan["total_bytes"] - 9, 9)]:
        assert gather_stream(
            read_at, plan["ranges"], start, ln
        ) == stream[start:start + ln]
    with pytest.raises(ValueError, match="past end"):
        gather_stream(read_at, plan["ranges"], plan["total_bytes"] - 1, 2)


def test_spec_slices_match_jax_devices_indices_map():
    """Ground truth: the pure slice math must agree with jax's own
    NamedSharding placement for every device of a 4-axis mesh."""
    import jax
    import numpy as np
    from jax.sharding import Mesh, NamedSharding

    from areal_tpu.parallel.sharding import fitted_param_spec, spec_slices

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU platform")
    devs = np.asarray(jax.devices()[:8]).reshape(2, 2, 1, 2)
    mesh = Mesh(devs, ("data", "fsdp", "seq", "tensor"))
    sizes = dict(mesh.shape)
    for path, shape in [
        ("embedding/weight", (64, 32)),
        ("head/weight", (32, 64)),
        ("layers/attn/wq", (4, 32, 32)),
        ("layers/attn/wo", (4, 32, 32)),
        ("layers/mlp/w_down", (4, 128, 32)),
        ("layers/norm/scale", (4, 32)),
        ("layers/attn/bq", (4, 32)),
    ]:
        fitted = fitted_param_spec(path, shape, mesh)
        idx_map = NamedSharding(mesh, fitted).devices_indices_map(shape)
        for coord, dev in np.ndenumerate(devs):
            coords = dict(zip(("data", "fsdp", "seq", "tensor"), coord))
            mine = spec_slices(fitted, shape, sizes, coords)
            theirs = [
                ((s.start or 0), (s.stop if s.stop is not None else d))
                for s, d in zip(idx_map[dev], shape)
            ]
            assert mine == theirs, (path, coords)

"""chaos-registry checker fixtures: seeded violations (undeclared
maybe_fail/arm points, unknown AREAL_FAULTS spec points in every env
shape, non-literal names) plus the exempt patterns (the test.*
namespace, interpolated scopes, dead-entry gating)."""

import textwrap

from areal_tpu.lint.chaos import ChaosConfig
from areal_tpu.lint.runner import LintConfig, run_lint

_CFG = ChaosConfig(
    declared={"good.point", "other.point"},
    registry_rel="fault_points.py",
)


def _lint(tmp_path, source, *, name="mod.py"):
    p = tmp_path / name
    p.write_text(textwrap.dedent(source))
    cfg = LintConfig(root=str(tmp_path), chaos_cfg=_CFG,
                     checkers={"chaos-registry"})
    return run_lint([str(p)], cfg)


def test_undeclared_maybe_fail_flagged(tmp_path):
    findings = _lint(tmp_path, """
        from areal_tpu.base.fault_injection import faults

        def work():
            faults.maybe_fail("good.point")
            faults.maybe_fail("renamed.point")
    """)
    assert len(findings) == 1
    assert "renamed.point" in findings[0].message


def test_bare_import_maybe_fail_flagged(tmp_path):
    # ``from ..fault_injection import maybe_fail`` then a bare call is
    # the same contract as the faults.maybe_fail spelling — it must not
    # slip past the attribute-call match.
    findings = _lint(tmp_path, """
        from areal_tpu.base.fault_injection import maybe_fail

        def work():
            maybe_fail("renamed.point")
    """)
    assert len(findings) == 1
    assert "renamed.point" in findings[0].message


def test_non_literal_point_flagged(tmp_path):
    findings = _lint(tmp_path, """
        from areal_tpu.base.fault_injection import faults

        def work(p):
            faults.maybe_fail(p)
    """)
    assert len(findings) == 1
    assert "non-literal" in findings[0].message


def test_non_literal_arm_flagged(tmp_path):
    # Arming a computed point is the same silent-no-op hazard as firing
    # one: a renamed production point leaves the arm matching nothing.
    findings = _lint(tmp_path, """
        from areal_tpu.base.fault_injection import faults

        def work(p):
            faults.arm(p, action="raise")
    """)
    assert len(findings) == 1
    assert "non-literal" in findings[0].message
    assert "arm" in findings[0].message


def test_test_namespace_exempt(tmp_path):
    findings = _lint(tmp_path, """
        from areal_tpu.base.fault_injection import faults

        def work(i):
            faults.maybe_fail("test.anything")
            faults.maybe_fail(f"test.fake{i}.generate")
            faults.arm(f"test.fake{i}.generate", action="raise")
    """)
    assert findings == []


def test_declared_variants_allow_dynamic_but_check_literals(tmp_path):
    # arm_declared/hits_declared carry the registry contract at
    # runtime (the injector raises on an undeclared name), so a
    # computed point is fine — that's how the all-points campaign
    # sweeps the registry. A LITERAL name is still verified statically:
    # the free check catches the typo before any test runs.
    findings = _lint(tmp_path, """
        from areal_tpu.base.fault_injection import faults

        def sweep(points):
            for p in points:
                faults.arm_declared(p, action="raise")
                assert faults.hits_declared(p) >= 0
            faults.arm_declared("good.point", action="raise")
            faults.arm_declared("renamed.point", action="raise")
            assert faults.hits_declared("also.renamed") == 0
    """)
    assert len(findings) == 2
    assert "renamed.point" in findings[0].message
    assert "also.renamed" in findings[1].message


def test_arm_and_hits_unknown_point_flagged(tmp_path):
    findings = _lint(tmp_path, """
        from areal_tpu.base.fault_injection import faults

        def work():
            faults.arm("unknown.armed", action="die")
            assert faults.hits("unknown.hits") == 0
    """)
    assert len(findings) == 2
    assert "unknown.armed" in findings[0].message
    assert "unknown.hits" in findings[1].message


def test_env_spec_shapes_flagged(tmp_path):
    findings = _lint(tmp_path, """
        def work(monkeypatch, child_env, scope):
            monkeypatch.setenv("AREAL_FAULTS", "nope.a=die:k=3")
            child_env["AREAL_FAULTS"] = "good.point=raise;nope.b=die"
            env = {"AREAL_FAULTS": f"nope.c@{scope}=hang"}
            return env
    """)
    assert sorted(
        f.message.split("chaos point ")[1].split(":")[0]
        for f in findings
    ) == ["'nope.a'", "'nope.b'", "'nope.c'"]


def test_env_spec_interpolated_scope_ok(tmp_path):
    # The point is literal, the scope interpolated: verifiable, clean.
    findings = _lint(tmp_path, """
        def work(monkeypatch, name):
            monkeypatch.setenv(
                "AREAL_FAULTS", f"good.point@{name}=raise:k=2"
            )
    """)
    assert findings == []


def test_env_spec_point_cut_by_interpolation_skipped(tmp_path):
    # A point assembled across the interpolation boundary cannot be
    # verified; it must be skipped, not half-matched.
    findings = _lint(tmp_path, """
        def work(monkeypatch, suffix):
            monkeypatch.setenv("AREAL_FAULTS", f"good.{suffix}=raise")
    """)
    assert findings == []


def test_dead_point_gated_on_registry_scan(tmp_path):
    (tmp_path / "fault_points.py").write_text(
        '_p = dict\nPTS = [_p("good.point"), _p("other.point")]\n'
    )
    (tmp_path / "user.py").write_text(textwrap.dedent("""
        from areal_tpu.base.fault_injection import faults

        def work():
            faults.maybe_fail("good.point")
    """))
    cfg = LintConfig(root=str(tmp_path), chaos_cfg=_CFG,
                     checkers={"chaos-registry"})
    findings = run_lint([str(tmp_path)], cfg)
    assert len(findings) == 1
    assert "dead chaos point other.point" in findings[0].message

    findings = run_lint([str(tmp_path / "user.py")], cfg)
    assert findings == []

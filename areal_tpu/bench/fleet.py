"""Real-process serving-fleet harness for bench phases.

The ROADMAP item-2 gap: `serving_openloop` measured in-process engines,
so scheduler results never crossed a process or HTTP boundary. This
module spawns REAL `GenerationServer` worker processes (CPU jax in the
bench's proxy mode, TPU when a window is live) behind a REAL in-thread
`GserverManager`, and drives open-loop load through the manager's
routing — the same path production rollout workers take. Both
`serving_openloop` and `serving_disagg` build on it.

Latency is read server-side: each point diffs the fleet's /metrics
TTFT/ITL histogram counters (base/latency.py sparse encoding) before
and after, then merges per-server buckets — the ratio-of-sums rule, no
client-side clock skew.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request
import uuid
from typing import Callable, Dict, List, Optional

import numpy as np

from areal_tpu.base import metrics_registry as mreg
from areal_tpu.bench._util import log, repo_root

_CHILD = '''
import os, sys
sys.path.insert(0, %(repo)r)
from areal_tpu.utils.jaxenv import apply_jax_platform_override
apply_jax_platform_override()
from areal_tpu.base import name_resolve
name_resolve.reconfigure("nfs", record_root=%(nr)r)
from areal_tpu.api.system_api import GenerationServerConfig
from areal_tpu.api.config import ModelAbstraction
from areal_tpu.system.generation_server import GenerationServer
import areal_tpu.engine.factories  # registry
cfg = GenerationServerConfig(
    experiment_name=%(exp)r, trial_name=%(trial)r, server_index=%(idx)d,
    model=ModelAbstraction("tpu_transformer", args=dict(config=%(model_cfg)r)),
    seed=0, **%(srv)r)
w = GenerationServer()
w.configure(cfg, experiment_name=cfg.experiment_name,
            trial_name=cfg.trial_name, worker_name=cfg.worker_name)
w.run()
'''

_MGR_CHILD = '''
import os, sys
sys.path.insert(0, %(repo)r)
from areal_tpu.base import name_resolve
name_resolve.reconfigure("nfs", record_root=%(nr)r)
from areal_tpu.api.system_api import GserverManagerConfig
from areal_tpu.system.gserver_manager import GserverManager
cfg = GserverManagerConfig(
    experiment_name=%(exp)r, trial_name=%(trial)r, model_name="actor",
    n_servers=%(n)d, train_batch_size=4, max_head_offpolicyness=1 << 20,
    health_check_interval=0.5, **%(mgr)r)
m = GserverManager()
m.configure(cfg, experiment_name=cfg.experiment_name,
            trial_name=cfg.trial_name, worker_name=cfg.worker_name)
m.run()
'''


def _post(url: str, path: str, payload: Dict, timeout: float = 300.0) -> Dict:
    req = urllib.request.Request(
        url + path, json.dumps(payload).encode(),
        {"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


class ProcessFleet:
    """N real GenerationServer subprocesses + a real GserverManager
    (in a thread). Context manager; `close()` tears everything down and
    restores name_resolve."""

    def __init__(
        self,
        model_cfg: Dict,
        servers: List[Dict],
        manager_kw: Optional[Dict] = None,
        tmp_dir: Optional[str] = None,
        tag: str = "fleet",
        spawn_timeout_s: float = 600.0,
        manager_subprocess: bool = False,
        manager_env: Optional[Dict] = None,
        models: Optional[List[Dict]] = None,
    ):
        import tempfile

        from areal_tpu.base import name_resolve, names

        self._names = names
        self._name_resolve = name_resolve
        self.tmp = tmp_dir or tempfile.mkdtemp(prefix=f"areal_{tag}_")
        self.exp = f"bench-{tag}-{uuid.uuid4().hex[:6]}"
        self.trial = "t0"
        self._model_cfg = dict(model_cfg)
        self._nr = os.path.join(self.tmp, "nr")
        self._repo_handle = name_resolve.reconfigure(
            "nfs", record_root=self._nr
        )
        # Multi-model fleets: register every served family in the
        # discovery-plane registry BEFORE anything spawns — the manager
        # builds its pool map from list_models at configure time, and a
        # heartbeat naming an unregistered model_id is quarantined, not
        # adopted. Each entry is ModelRecord kwargs.
        if models:
            from areal_tpu.system import model_registry

            for rec in models:
                model_registry.register_model(
                    self.exp, self.trial,
                    model_registry.ModelRecord(**rec),
                )
        repo = repo_root()
        env = dict(os.environ)
        env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
        env.setdefault("AREAL_HEALTH_TTL", "60")
        self._env = env
        self._repo = repo
        self.procs: List[subprocess.Popen] = []
        self.logs: List[str] = []
        self._log_files = []
        self.urls: List[Optional[str]] = []
        for idx, srv in enumerate(servers):
            self._spawn_server_child(idx, dict(srv))
        self._await_discovery(
            range(len(servers)), spawn_timeout_s=spawn_timeout_s
        )
        # Manager: in-thread (legacy, cheap) or a REAL subprocess —
        # required by the fleet_elastic killover arm (you cannot
        # SIGKILL a thread) and by manager-HA e2es.
        self.manager = None
        self._mthread = None
        self.mgr_procs: List[subprocess.Popen] = []
        self._manager_kw = dict(manager_kw or {})
        self._manager_env = dict(manager_env or {})
        self._n_servers0 = len(servers)
        if manager_subprocess:
            self.spawn_manager()
        else:
            from areal_tpu.api.system_api import GserverManagerConfig
            from areal_tpu.system.gserver_manager import GserverManager

            self.manager = GserverManager()
            self.manager.configure(GserverManagerConfig(
                experiment_name=self.exp, trial_name=self.trial,
                model_name="actor", n_servers=len(servers),
                train_batch_size=4, max_head_offpolicyness=1 << 20,
                health_check_interval=0.5,
                **self._manager_kw,
            ))
            self._mthread = threading.Thread(
                target=self.manager.run, daemon=True
            )
            self._mthread.start()
        self.wait_healthy(len(servers))

    # ------------------------------------------------------------------
    # Elastic-fleet harness surface (ISSUE 12)
    # ------------------------------------------------------------------

    def _spawn_server_child(self, idx: int, srv: Dict) -> subprocess.Popen:
        child_env = dict(self._env)
        for k, v in (srv.pop("env", None) or {}).items():
            child_env[k] = v
        # A multi-model fleet serves genuinely different weights per
        # pool: a server dict may override the fleet-level model config
        # (and carries its model_id through the remaining srv kwargs).
        model_cfg = srv.pop("model_cfg", None) or self._model_cfg
        log_path = os.path.join(self.tmp, f"server{idx}.log")
        self.logs.append(log_path)
        log_f = open(log_path, "w")
        self._log_files.append(log_f)
        p = subprocess.Popen(
            [sys.executable, "-c", _CHILD % dict(
                repo=self._repo, nr=self._nr, exp=self.exp,
                trial=self.trial, idx=idx, model_cfg=dict(model_cfg),
                srv=srv,
            )],
            env=child_env, cwd=self._repo, stdout=log_f,
            stderr=subprocess.STDOUT,
        )
        self.procs.append(p)
        while len(self.urls) <= idx:
            self.urls.append(None)
        return p

    def _await_discovery(self, indices, spawn_timeout_s: float = 600.0):
        deadline = time.monotonic() + spawn_timeout_s
        pending = [i for i in indices if self.urls[i] is None]
        while pending:
            for i in list(pending):
                if self.procs[i].poll() is not None:
                    with open(self.logs[i]) as f:
                        tail = f.read()[-3000:]
                    raise RuntimeError(f"fleet server {i} died:\n{tail}")
                try:
                    self.urls[i] = self._name_resolve.get(
                        self._names.gen_server_url(
                            self.exp, self.trial, str(i)
                        )
                    )
                    pending.remove(i)
                except Exception:
                    pass
            if time.monotonic() > deadline:
                raise TimeoutError("fleet servers never registered")
            time.sleep(0.2)

    def spawn_server(self, srv: Optional[Dict] = None,
                     spawn_timeout_s: float = 600.0) -> str:
        """Runtime JOIN: spawn one more GenerationServer child (next
        index) and wait for its discovery registration; the manager
        adopts it from its first heartbeat. Returns its url."""
        idx = len(self.procs)
        self._spawn_server_child(idx, dict(srv or {}))
        self._await_discovery([idx], spawn_timeout_s=spawn_timeout_s)
        return self.urls[idx]

    def spawn_manager(self, env: Optional[Dict] = None) -> subprocess.Popen:
        """Spawn a gserver-manager subprocess (successors take over the
        HA lease from a dead predecessor). ``env`` overrides the
        construction-time manager_env — a successor must not re-inherit
        a predecessor's chaos arm."""
        if env is not None:
            self._manager_env = dict(env)
        i = len(self.mgr_procs)
        log_path = os.path.join(self.tmp, f"manager{i}.log")
        log_f = open(log_path, "w")
        self._log_files.append(log_f)
        p = subprocess.Popen(
            [sys.executable, "-c", _MGR_CHILD % dict(
                repo=self._repo, nr=self._nr, exp=self.exp,
                trial=self.trial, n=self._n_servers0,
                mgr=self._manager_kw,
            )],
            env={**self._env, **self._manager_env},
            cwd=self._repo, stdout=log_f, stderr=subprocess.STDOUT,
        )
        self.mgr_procs.append(p)
        return p

    def manager_addr(self) -> str:
        """The CURRENT manager address: in-thread manager's directly, a
        subprocess manager's via its name_resolve registration (which a
        successor overwrites on takeover)."""
        if self.manager is not None:
            return self.manager.address
        return self._name_resolve.get(
            self._names.gen_server_manager(self.exp, self.trial)
        )

    def status(self) -> Dict:
        with urllib.request.urlopen(
            self.manager_addr() + "/status", timeout=30
        ) as r:
            return json.loads(r.read())

    def wait_healthy(self, n: int, timeout_s: float = 120.0,
                     epoch: Optional[int] = None):
        """Block until /status shows n healthy servers (and, when
        given, the manager epoch — takeover convergence)."""
        deadline = time.monotonic() + timeout_s
        last = None
        while time.monotonic() < deadline:
            try:
                st = self.status()
                last = (len(st["healthy_servers"]),
                        st.get("fleet", {}).get("epoch"))
                if len(st["healthy_servers"]) == n and (
                    epoch is None or last[1] == epoch
                ):
                    return st
            except Exception:
                pass
            time.sleep(0.2)
        raise TimeoutError(
            f"manager never reached {n} healthy servers"
            + (f" at epoch {epoch}" if epoch is not None else "")
            + f" (last seen: {last})"
        )

    def drain_server(self, url: str, reason: str = "harness") -> Dict:
        return _post(self.manager_addr(), "/drain_server",
                     {"url": url, "reason": reason}, timeout=30)

    # ------------------------------------------------------------------

    def wait_roles(self, roles: List[str], timeout_s: float = 60.0):
        """Block until the manager's /metrics poll learned every
        server's role (pool routing engages only then)."""
        want = {self.urls[i]: r for i, r in enumerate(roles)}
        deadline = time.monotonic() + timeout_s
        got = None
        while time.monotonic() < deadline:
            try:
                st_roles = self.status()["pools"]["roles"]
                got = {u: st_roles.get(u) for u in want}
                if got == want:
                    return
            except Exception:
                pass
            time.sleep(0.2)
        raise TimeoutError(f"manager never learned roles {want} ({got})")

    def metrics(self, url: str) -> Dict:
        from areal_tpu.system.fleet_controller import parse_metrics

        text = urllib.request.urlopen(
            url + "/metrics", timeout=30).read().decode()
        return parse_metrics(text)

    def hist_counts(self, urls: List[str]) -> Dict[str, List[int]]:
        """Fleet-merged raw TTFT/ITL bucket counts over `urls`."""
        from areal_tpu.base.latency import decode_counts, merge_counts

        ttft, itl = [], []
        for u in urls:
            m = self.metrics(u)
            ttft.append(decode_counts(str(m.get(mreg.TTFT_HIST) or "")))
            itl.append(decode_counts(str(m.get(mreg.ITL_HIST) or "")))
        return {"ttft": merge_counts(ttft), "itl": merge_counts(itl)}

    def configure_servers(self, payload: Dict, urls: Optional[List[str]] = None):
        for u in urls or self.urls:
            _post(u, "/configure", payload, timeout=30)

    def schedule(self, meta: Dict) -> Dict:
        return _post(self.manager_addr(), "/schedule_request", meta,
                     timeout=30)

    def generate_direct(self, url: str, qid: str, input_ids: List[int],
                        max_new: int, timeout: float = 600.0) -> Dict:
        """One greedy request straight at a server (no manager routing)
        — the single place the bench builds a raw /generate body."""
        return _post(url, "/generate", {
            "qid": qid, "input_ids": list(input_ids),
            "gconfig": {"max_new_tokens": int(max_new), "greedy": True},
        }, timeout=timeout)

    def generate_routed(self, qid: str, input_ids: List[int],
                        max_new: int, timeout: float = 300.0,
                        model: Optional[str] = None) -> Dict:
        """One request through the manager's routing (pairing included),
        like a rollout worker. ``model`` pins the request to that
        model's pool on a multi-model fleet (the manager refuses to
        route it anywhere else). Returns the /generate body; a dict
        with 'shed'/'error' on 429/failure."""
        meta = {
            "qid": qid, "prompt_len": len(input_ids),
            "new_token_budget": max_new,
        }
        if model:
            meta["model"] = model
        try:
            sched = self.schedule(meta)
        except urllib.error.HTTPError as e:
            return {"error": f"schedule {e.code}: {e.read()[:200]}"}
        if "url" not in sched:
            return {"error": f"unroutable: {sched}"}
        payload = {
            "qid": qid, "input_ids": input_ids,
            "gconfig": {"max_new_tokens": max_new, "greedy": True},
        }
        if sched.get("decode_url"):
            payload["decode_url"] = sched["decode_url"]
        if sched.get("kv_source"):
            payload["kv_source"] = sched["kv_source"]
        try:
            return _post(sched["url"], "/generate", payload, timeout=timeout)
        except urllib.error.HTTPError as e:
            if e.code == 429:
                return {"shed": True}
            return {"error": f"{e.code}: {e.read()[:200]}"}
        except Exception as e:  # noqa: BLE001 — counted, not raised
            return {"error": repr(e)}

    def close(self):
        try:
            self._name_resolve.add(
                self._names.experiment_status(self.exp, self.trial),
                "COMPLETE", replace=True,
            )
        except Exception:
            pass
        for p in self.procs + self.mgr_procs:
            try:
                p.terminate()
            except Exception:
                pass
        for p in self.procs + self.mgr_procs:
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()
            except Exception:
                pass
        try:
            if self._mthread is not None:
                self._mthread.join(timeout=10)
        except Exception:
            pass
        for f in self._log_files:
            try:
                f.close()
            except Exception:
                pass
        try:
            self._repo_handle.reset()
        except Exception:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def open_loop_point(
    fleet: ProcessFleet,
    rate: float,
    duration_s: float,
    prompt_fn: Callable[[int], List[int]],
    max_new: int,
    tag: str,
    ttft_urls: Optional[List[str]] = None,
    itl_urls: Optional[List[str]] = None,
    rng: Optional[np.random.RandomState] = None,
    drain_timeout_s: float = 120.0,
    model: Optional[str] = None,
) -> Dict:
    """One Poisson-arrival sweep point against the real fleet, routed
    through the manager (``model`` pins every request to one model's
    pool on a multi-model fleet). Fixed arrival COUNT
    (ceil(rate * duration)) so the overload A/B is deterministic;
    p50/p99 come from the per-server histogram DIFF over the point
    (the /metrics counters never reset)."""
    from areal_tpu.base.latency import merge_counts, percentile_from_counts

    rng = rng or np.random.RandomState(0)
    ttft_urls = ttft_urls or list(fleet.urls)
    itl_urls = itl_urls or list(fleet.urls)
    base_t = fleet.hist_counts(ttft_urls)["ttft"]
    base_i = fleet.hist_counts(itl_urls)["itl"]
    n_target = max(2, int(-(-rate * duration_s // 1)))
    results = {"completed": 0, "shed": 0, "failed": 0}
    rlock = threading.Lock()
    threads: List[threading.Thread] = []

    def fire(i: int):
        out = fleet.generate_routed(
            f"{tag}{i}", prompt_fn(i), max_new,
            timeout=max(60.0, drain_timeout_s), model=model,
        )
        with rlock:
            if out.get("shed"):
                results["shed"] += 1
            elif "error" in out:
                results["failed"] += 1
            else:
                results["completed"] += 1

    t0 = time.monotonic()
    t_next = t0
    for i in range(n_target):
        now = time.monotonic()
        if now < t_next:
            time.sleep(t_next - now)
        th = threading.Thread(target=fire, args=(i,), daemon=True)
        th.start()
        threads.append(th)
        t_next += rng.exponential(1.0 / rate)
    arrival_window = time.monotonic() - t0
    deadline = time.monotonic() + drain_timeout_s
    for th in threads:
        th.join(timeout=max(0.1, deadline - time.monotonic()))
    elapsed = time.monotonic() - t0
    after_t = fleet.hist_counts(ttft_urls)["ttft"]
    after_i = fleet.hist_counts(itl_urls)["itl"]
    dt = [max(0, a - b) for a, b in zip(after_t, base_t)]
    di = [max(0, a - b) for a, b in zip(after_i, base_i)]
    pt = {
        "nominal_rate_rps": float(rate),
        "offered_rps": n_target / arrival_window,
        "duration_s": arrival_window,
        "n_arrivals": float(n_target),
        "n_admitted": float(n_target - results["shed"]),
        "n_shed": float(results["shed"]),
        "n_failed": float(results["failed"]),
        "n_completed": float(results["completed"]),
        "goodput_rps": results["completed"] / elapsed,
        "p50_ttft_ms": percentile_from_counts(dt, 50.0),
        "p99_ttft_ms": percentile_from_counts(dt, 99.0),
        "itl_p50_ms": percentile_from_counts(di, 50.0),
        "itl_p99_ms": percentile_from_counts(di, 99.0),
    }
    log(f"bench: {tag} point: {pt}")
    return pt


def interference_point(
    fleet: ProcessFleet,
    n_streams: int,
    stream_plen: int,
    stream_max_new: int,
    n_long: int,
    long_plen: int,
    long_gap_s: float,
    long_max_new: int,
    tag: str,
    ttft_urls: Optional[List[str]] = None,
    itl_urls: Optional[List[str]] = None,
    rng: Optional[np.random.RandomState] = None,
    timeout_s: float = 300.0,
) -> Dict:
    """Deterministic prefill/decode interference probe: `n_streams`
    long-decode sessions run for the whole window while `n_long` long
    prompts arrive at fixed gaps — every long admission is GUARANTEED
    to land while decode streams are running (a Poisson point at this
    scale only collides by luck, which made the A/B noisy). The ITL
    histogram diff over `itl_urls` is then a direct read of how much
    decode latency the long prefills steal."""
    from areal_tpu.base.latency import percentile_from_counts

    rng = rng or np.random.RandomState(0)
    ttft_urls = ttft_urls or list(fleet.urls)
    itl_urls = itl_urls or list(fleet.urls)
    vocab = 200
    results = {"completed": 0, "failed": 0}
    rlock = threading.Lock()

    def fire(qid, ids, max_new):
        out = fleet.generate_routed(qid, ids, max_new, timeout=timeout_s)
        with rlock:
            if "output_ids" in out:
                results["completed"] += 1
            else:
                results["failed"] += 1

    # Start the decode streams and wait until every one has sampled its
    # first token ON the decode pool. The predicate is the MONOTONIC
    # TTFT sample count, not an instantaneous num_running read: under
    # heavy CPU contention a polling loop can miss the running peak
    # entirely and burn its whole deadline while the streams complete —
    # leaving the baseline snapshot AFTER the window it was meant to
    # open (measured as a 21-sample, 62 s degenerate point).
    base_ttft_n = sum(fleet.hist_counts(itl_urls)["ttft"])
    threads = [
        threading.Thread(
            target=fire,
            args=(f"{tag}st{i}",
                  rng.randint(1, vocab, size=stream_plen).tolist(),
                  stream_max_new),
            daemon=True,
        )
        for i in range(n_streams)
    ]
    t0 = time.monotonic()
    for th in threads:
        th.start()
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        if sum(fleet.hist_counts(itl_urls)["ttft"]) >= base_ttft_n + n_streams:
            break
        time.sleep(0.1)
    # Hist baseline AFTER the streams admitted: the diff then holds the
    # streams' steady decode cadence + whatever the long prompts steal.
    base_t = fleet.hist_counts(ttft_urls)["ttft"]
    base_i = fleet.hist_counts(itl_urls)["itl"]
    for i in range(n_long):
        time.sleep(long_gap_s)
        th = threading.Thread(
            target=fire,
            args=(f"{tag}lg{i}",
                  rng.randint(1, vocab, size=long_plen).tolist(),
                  long_max_new),
            daemon=True,
        )
        th.start()
        threads.append(th)
    for th in threads:
        th.join(timeout=max(0.1, timeout_s - (time.monotonic() - t0)))
    elapsed = time.monotonic() - t0
    after_t = fleet.hist_counts(ttft_urls)["ttft"]
    after_i = fleet.hist_counts(itl_urls)["itl"]
    dt = [max(0, a - b) for a, b in zip(after_t, base_t)]
    di = [max(0, a - b) for a, b in zip(after_i, base_i)]
    pt = {
        "n_streams": float(n_streams),
        "n_long": float(n_long),
        "offered_rps": (n_streams + n_long) / elapsed,
        "duration_s": elapsed,
        "n_failed": float(results["failed"]),
        "n_completed": float(results["completed"]),
        "goodput_rps": results["completed"] / elapsed,
        "p50_ttft_ms": percentile_from_counts(dt, 50.0),
        "p99_ttft_ms": percentile_from_counts(dt, 99.0),
        "itl_p50_ms": percentile_from_counts(di, 50.0),
        "itl_p99_ms": percentile_from_counts(di, 99.0),
        "itl_samples": float(sum(di)),
    }
    log(f"bench: {tag} interference point: {pt}")
    return pt


def warm_admit_shapes(
    fleet: ProcessFleet, plen: int, max_new: int, vocab: int,
    rng: np.random.RandomState, max_batch: int = 8, rounds: int = 2,
):
    """Compile every pow2 admit-batch shape on every server BEFORE
    measuring: the engine pads batched prefill to pow2 row counts, so a
    burst size never seen warm compiles INSIDE a sweep point and
    masquerades as multi-second queueing delay (measured: an unwarmed
    pad-4 batch put p99 TTFT at 4096 ms in whichever A/B arm ran
    first). Bursts go DIRECT to each server; a burst may split across
    admission laps, so run a couple of rounds."""
    for _ in range(rounds):
        for u in fleet.urls:
            for k in (1, 2, 3, 4, 6, max_batch):
                threads = []

                def fire(i):
                    try:
                        fleet.generate_direct(
                            u, f"warm{k}-{i}",
                            rng.randint(1, vocab, size=plen).tolist(),
                            max_new,
                        )
                    except Exception:
                        pass

                for i in range(k):
                    th = threading.Thread(target=fire, args=(i,),
                                          daemon=True)
                    th.start()
                    threads.append(th)
                for th in threads:
                    th.join(timeout=600)


def closed_loop_capacity(
    fleet: ProcessFleet, n: int, plen: int, max_new: int, tag: str,
    vocab: int, rng: np.random.RandomState,
) -> float:
    """Closed-loop peak: n concurrent requests direct to the servers
    (round-robin), completions per second."""
    threads = []
    done = []

    def fire(i):
        url = fleet.urls[i % len(fleet.urls)]
        try:
            out = fleet.generate_direct(
                url, f"{tag}{i}",
                rng.randint(1, vocab, size=plen).tolist(), max_new,
            )
            if "output_ids" in out:
                done.append(1)
        except Exception:
            pass

    t0 = time.monotonic()
    for i in range(n):
        th = threading.Thread(target=fire, args=(i,), daemon=True)
        th.start()
        threads.append(th)
    for th in threads:
        th.join(timeout=600)
    dt = time.monotonic() - t0
    if not done:
        raise RuntimeError(f"capacity probe: no completions ({tag})")
    return len(done) / dt

"""Benchmark CLI: thin front-end over areal_tpu/bench/.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

Metric: achieved model TFLOP/s per chip for the full training step
(fwd + bwd + sharded optimizer) on a Qwen2.5-style packed-varlen model in
bfloat16, plus serving tok/s phases. FLOPs are computed analytically from
the model dims (the reference does the same for its TFLOP/s logs —
realhf/base/monitor.py:288 llama formulas).

vs_baseline: ratio against 198 TFLOP/s/GPU — the reference's efficiency
class on its H800 benchmark hardware (~40% MFU of H800 dense bf16
~495 TFLOP/s; its headline runs are throughput-bound on exactly this
train path, benchmark/verl_v0_3_0_post1_76084d3/README.md). >1.0 means a
chip running this framework outruns an H800 running the reference.

Modes:
  python bench.py                 one-shot: run every unbanked default
                                  phase (compile pass, then measure),
                                  each in its own deadline-guarded
                                  subprocess; assemble + print the report
  python bench.py --daemon        opportunistic: poll for a device
                                  window, spend each one on the highest-
                                  value unbanked phase that fits it
  python bench.py --phases a,b    restrict to named phases
  python bench.py --fresh         drop banked records first (new round)

This process NEVER touches jax itself: device probes and phases run in
subprocesses, so a wedged tunnel can hang a phase (killed at its
deadline) but not the bench. Every phase result is flushed atomically to
the bank the moment it exists — a tunnel drop mid-run loses at most the
phase in flight, and the next invocation resumes from banked phases.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from areal_tpu.bench import bank, phases, report, runner  # noqa: E402
from areal_tpu.bench.daemon import BenchDaemon, probe_devices  # noqa: E402

# Shared with scripts/mfu_sweep.py and scripts/long_context_probe.py so
# every probe measures the SAME model and formula as the banked numbers.
from areal_tpu.bench.workloads import (  # noqa: E402,F401
    BASELINE_TFLOPS,
    flagship_cfg,
    train_step_flops,
)


def log(*a):
    print(*a, file=sys.stderr, flush=True)


def bench_json_path() -> str:
    return os.environ.get(
        "AREAL_BENCH_JSON",
        os.path.join(tempfile.gettempdir(), "areal_bench_result.json"),
    )


def flush_report(bank_path: str) -> dict:
    """Rebuild the report from the bank and persist it — called after
    EVERY phase so a mid-run tunnel drop still leaves the newest full
    artifact on disk."""
    rep = report.build_report(bank_path)
    report.write_report(rep, bench_json_path())
    return rep


def emit_and_exit(bank_path: str, code: int, error: str = None):
    rep = flush_report(bank_path)
    line = report.result_line(rep)
    if error:
        line["error"] = (line.get("error", "") + "; " + error).strip("; ")
        line["partial"] = True
    print(json.dumps(line), flush=True)
    # os._exit: the deadline path fires on a timer thread while the main
    # thread may be blocked on a wedged subprocess wait.
    os._exit(code) if code == 3 else sys.exit(code)


def _arm_deadline(bank_path: str, seconds: float):
    """Emit an honest JSON (with whatever phases DID bank) and hard-exit
    if the run overstays its welcome. The bank already holds every
    completed phase, so this handler just reads disk — no mirrored
    module state (the old bench kept a _PARTIAL global in sync by hand;
    the atomic per-phase bank made that hack unnecessary)."""
    import threading

    def fire():
        log(f"bench: deadline {seconds:.0f}s exceeded")
        emit_and_exit(bank_path, 3,
                      error=f"bench deadline {seconds:.0f}s exceeded")

    t = threading.Timer(seconds, fire)
    t.daemon = True
    t.start()
    return t


def wait_for_platform(budget_s: float) -> str:
    """Probe (in subprocesses) until a backend answers; returns the
    platform. Tunnel-class failures poll with backoff inside the budget;
    a driver/version error aborts immediately — retrying replays it."""
    deadline = time.monotonic() + budget_s
    delay = float(os.environ.get("AREAL_BENCH_INIT_BACKOFF_S", 5.0))
    while True:
        # Each probe gets at most the REMAINING budget (floor 10s so a
        # probe can at least import jax): a wedged probe must not push
        # the total wait past the wall-clock budget.
        remaining = deadline - time.monotonic()
        p = probe_devices(timeout_s=min(120.0, max(remaining, 10.0)))
        if p.status == "up":
            log(f"bench: platform={p.platform} n_devices={p.n_devices}")
            return p.platform
        if p.status == "driver":
            raise RuntimeError(f"driver/version error: {p.detail[:500]}")
        remaining = deadline - time.monotonic()
        log(f"bench: devices unavailable ({p.status}), "
            f"{remaining:.0f}s budget left: {p.detail[:200]}")
        if remaining <= 0:
            raise TimeoutError(
                f"no device within {budget_s:.0f}s ({p.status})"
            )
        time.sleep(min(delay, remaining))
        delay = min(delay * 2, 60.0)


def run_oneshot(phase_list, bank_path: str, platform: str) -> bool:
    """Compile-then-measure every unbanked phase, priority order. Returns
    True if every phase banked an ok measure record."""
    ok = True
    for spec in phase_list:
        plat = "cpu" if spec.proxy else platform
        if bank.is_banked(bank_path, spec.name, "measure", plat):
            log(f"bench: {spec.name} already banked; skipping")
            continue
        if spec.est_compile_s > 0 and not bank.is_banked(
                bank_path, spec.name, "compile", plat):
            rec = runner.run_phase(spec.name, "compile", bank_path)
            flush_report(bank_path)
            if rec["status"] != "ok":
                ok = False
                continue  # no point measuring what cannot compile
        rec = runner.run_phase(spec.name, "measure", bank_path)
        flush_report(bank_path)
        ok = ok and rec["status"] == "ok"
    return ok


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--daemon", action="store_true",
                        help="opportunistic mode: poll for device windows")
    parser.add_argument("--phases", default=None,
                        help="comma-separated phase names (default: the "
                             "registry's default set)")
    parser.add_argument("--bank", default=None, help="bank directory")
    parser.add_argument("--fresh", action="store_true",
                        help="clear banked records first (new round)")
    parser.add_argument("--max-runtime-s", type=float, default=None,
                        help="daemon runtime budget")
    parser.add_argument("--list-phases", action="store_true")
    args = parser.parse_args(argv)

    if args.list_phases:
        for s in phases.all_phases():
            print(f"{s.priority:3d} {s.name:18s} compile~{s.est_compile_s:.0f}s "
                  f"measure~{s.est_measure_s:.0f}s "
                  f"{'proxy ' if s.proxy else ''}"
                  f"{'headline ' if s.headline else ''}- {s.description}")
        return 0

    bank_path = bank.bank_dir(args.bank)
    if args.fresh:
        bank.clear_bank(bank_path)
    if args.phases:
        phase_list = [phases.get(n.strip())
                      for n in args.phases.split(",") if n.strip()]
    else:
        phase_list = phases.default_phases()

    if args.daemon:
        def dispatch(name, pass_, b):
            # Flush the report after EVERY banked pass — a daemon killed
            # mid-round must still leave the newest artifact on disk.
            rec = runner.run_phase(name, pass_, bank_path=b)
            flush_report(b)
            return rec

        d = BenchDaemon(bank_path=bank_path, phase_list=phase_list,
                        dispatch_fn=dispatch)
        state = d.run(max_runtime_s=args.max_runtime_s)
        log(f"bench: daemon finished: {state}")
        rep = flush_report(bank_path)
        print(json.dumps(report.result_line(rep)), flush=True)
        if state == "complete" and not args.phases:
            bank.clear_bank(bank_path)  # next invocation = fresh round
        return 0 if state == "complete" else 2

    deadline = _arm_deadline(
        bank_path, float(os.environ.get("AREAL_BENCH_DEADLINE_S", 2700))
    )
    try:
        platform = wait_for_platform(
            float(os.environ.get("AREAL_BENCH_DEVICE_BUDGET_S", 300.0))
        )
    except (RuntimeError, TimeoutError) as e:
        log(f"bench: {e}")
        emit_and_exit(bank_path, 2, error=str(e))
    complete = run_oneshot(phase_list, bank_path, platform)
    deadline.cancel()
    rep = flush_report(bank_path)
    print(json.dumps(report.result_line(rep)), flush=True)
    if complete and not args.phases:
        # The report file is the artifact; the bank is resume state for
        # THIS round only — a completed round must not leak into the
        # next. A --phases-restricted run keeps its records: a later
        # full run resumes from them.
        bank.clear_bank(bank_path)
    return 0 if complete else 1


if __name__ == "__main__":
    sys.exit(main())

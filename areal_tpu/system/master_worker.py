"""Master worker: drives one DFG traversal per train step.

Counterpart of the reference's MasterWorker
(realhf/system/master_worker.py:49-606): configure streams + buffer +
executor, then per poll run a step, manage save/eval/ckpt frequency
control, publish step/experiment status, and dump recover info.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from areal_tpu.api.dfg import build_graph
from areal_tpu.api.system_api import MasterWorkerConfig
from areal_tpu.base import constants, logging, name_resolve, names, recover, timeutil, tracing
from areal_tpu.base import metrics_registry as mreg
from areal_tpu.base.fault_injection import faults
from areal_tpu.base.recover import RecoverInfo, StepInfo
from areal_tpu.system import request_reply_stream as rrs
from areal_tpu.system.buffer import AsyncIOSequenceBuffer
from areal_tpu.system.function_executor import FunctionExecutor
from areal_tpu.system.model_function_call import RPCCorountineControl
from areal_tpu.system.worker_base import PollResult, Worker

logger = logging.getLogger("master_worker")


class MasterWorker(Worker):
    def _configure(self, config: MasterWorkerConfig):
        self.cfg = config
        constants.set_experiment_trial_names(
            config.experiment_name, config.trial_name
        )
        self.stream = rrs.make_master_stream(
            config.experiment_name, config.trial_name
        )
        self.graph = build_graph(config.rpcs)
        self.buffer = AsyncIOSequenceBuffer(
            config.rpcs, max_size=config.buffer_max_size
        )
        self.ctrl = RPCCorountineControl()
        self.executor = FunctionExecutor(
            graph=self.graph,
            stream=self.stream,
            buffer=self.buffer,
            model_topos=config.model_topos,
            data_hosts=config.data_hosts,
            ctrl=self.ctrl,
            experiment_name=config.experiment_name,
            trial_name=config.trial_name,
        )

        ctl = config.exp_ctrl
        self.save_ctl = timeutil.FrequencyControl(
            frequency_epoch=ctl.save_freq_epochs,
            frequency_step=ctl.save_freq_steps,
            frequency_sec=ctl.save_freq_secs,
        )
        self.ckpt_ctl = timeutil.FrequencyControl(
            frequency_epoch=ctl.ckpt_freq_epochs,
            frequency_step=ctl.ckpt_freq_steps,
            frequency_sec=ctl.ckpt_freq_secs,
        )
        self.eval_ctl = timeutil.FrequencyControl(
            frequency_epoch=ctl.eval_freq_epochs,
            frequency_step=ctl.eval_freq_steps,
            frequency_sec=ctl.eval_freq_secs,
        )

        self.step_info = StepInfo()
        self._steps_per_epoch = max(
            1, config.dataset_size // max(1, config.train_batch_size)
        ) if config.dataset_size else None
        # Derive epoch boundaries from _steps_per_epoch only when the
        # dataset size was configured explicitly (async experiments: the
        # prompt dataset lives in rollout workers and the stream dataset
        # never reports epoch_done). Sync runs get real boundaries from
        # the dataloader; deriving there too would double-count.
        self._derive_epoch_boundary = bool(config.dataset_size)
        self._total_steps_cap = ctl.benchmark_steps
        self._start_time = time.monotonic()
        # Cumulative throughput accounting for the async-vs-sync speedup
        # benchmark (reference benchmark/.../README.md:26-36: effective
        # trained tokens / end-to-end seconds). Filled by _log_step_perf;
        # returned through the controller's run() result.
        self.perf_summary = {
            "steps": 0, "total_e2e_s": 0.0, "train_tokens": 0.0,
            "wall_s": 0.0,
            # Per-step [e2e_s, train_tokens] so benchmark consumers can
            # drop compile-dominated warmup steps from the rate.
            "history": [],
            # Input-pipeline health (running means over steps that
            # reported): how dense the packed batches are and how much
            # of the step the host blocked on pack/H2D vs dispatch gaps
            # (jax_engine overlap telemetry; definitions in
            # docs/perf_notes.md "overlap pipeline").
            "overlap": {},
        }
        # metric -> [sum, count] (running, NOT a per-step list: an
        # open-ended RL run must not grow it for the process lifetime).
        self._overlap_acc: Dict[str, List[float]] = {}
        self._init_metric_trackers()

        # Wait for every model worker to finish its lazy setup.
        handlers = [f"model_worker/{i}" for i in range(config.n_model_workers)]
        specs = self.stream.call(handlers, "spec", timeout=600)
        self._dataset_size = sum(
            s.get("dataset_size", 0) for s in specs if isinstance(s, dict)
        )
        if self._dataset_size and not self._steps_per_epoch:
            self._steps_per_epoch = max(
                1, self._dataset_size // max(1, config.train_batch_size)
            )
        logger.info(
            f"master configured: {len(config.rpcs)} MFCs, "
            f"{config.n_model_workers} model workers, "
            f"dataset size {self._dataset_size}"
        )

        if config.recover_mode in ("auto", "resume"):
            self._maybe_recover()

        name_resolve.add(
            names.experiment_status(config.experiment_name, config.trial_name),
            "RUNNING",
            replace=True,
        )

    # ------------------------------------------------------------------

    def _init_metric_trackers(self):
        """Tensorboard (always, under the trial log path) + wandb (only
        when the user configured credentials) — reference
        master_worker.py:291-350 initializes the same sinks."""
        self._summary_writer = None
        self._wandb_run = None
        try:
            from torch.utils.tensorboard import SummaryWriter

            self._summary_writer = SummaryWriter(
                log_dir=constants.get_log_path() + "/tb"
            )
        except Exception:
            pass
        import os

        if os.environ.get("WANDB_API_KEY") or os.environ.get("WANDB_MODE"):
            try:
                import wandb

                self._wandb_run = wandb.init(
                    project=os.environ.get("WANDB_PROJECT", "areal_tpu"),
                    name=f"{self.cfg.experiment_name}/{self.cfg.trial_name}",
                    resume="allow",
                )
            except Exception:
                logger.warning("wandb unavailable; metrics go to tensorboard only")

    def _maybe_recover(self):
        try:
            info = recover.load(self.cfg.experiment_name, self.cfg.trial_name)
        except FileNotFoundError:
            logger.info("no recover info found; fresh start")
            return
        self.step_info = info.last_step_info.next()
        self.save_ctl.load_state_dict(info.save_ctl_info)
        self.ckpt_ctl.load_state_dict(info.ckpt_ctl_info)
        self.eval_ctl.load_state_dict(info.eval_ctl_info)
        self.buffer.ignore_ids |= set(info.hash_vals_to_ignore)
        # Re-arm the exactly-once ledger from the same durable cut the
        # engine state was taken at: WAL replay and pusher redelivery
        # of already-consumed sequences are filtered at admission.
        # (getattr: a pre-ledger recover record unpickles without the
        # field — dataclass defaults do not apply on unpickle.)
        self.buffer.seed_consumed_seqs(getattr(info, "consumed_seqs", None))
        req = self.stream.request(
            self.cfg.data_hosts + self._all_model_workers(),
            "restore",
            [None] * (len(self.cfg.data_hosts) + len(self._all_model_workers())),
        )
        self.stream.gather(req, timeout=600)
        logger.info(f"recovered at step {self.step_info.global_step}")

    def _all_model_workers(self) -> List[str]:
        return [f"model_worker/{i}" for i in range(self.cfg.n_model_workers)]

    def _recover_save(self):
        info = RecoverInfo(
            recover_start=self.step_info,
            last_step_info=self.step_info,
            save_ctl_info=self.save_ctl.state_dict(),
            ckpt_ctl_info=self.ckpt_ctl.state_dict(),
            eval_ctl_info=self.eval_ctl.state_dict(),
            hash_vals_to_ignore=sorted(self.buffer.consumed_this_epoch),
            # The consumed-seq watermark commits atomically WITH the
            # step counters (one fsynced rename in recover.dump) — the
            # exactly-once cut. Model workers compact their WALs against
            # this record at the NEXT ckpt barrier (one-barrier lag:
            # truncation is GC, safe to run behind).
            consumed_seqs=self.buffer.consumed_seqs(),
        )
        recover.dump(info, self.cfg.experiment_name, self.cfg.trial_name)

    def _broadcast(self, handle: str, timeout: float = 3600):
        workers = self._all_model_workers()
        return self.stream.call(workers, handle, timeout=timeout)

    # ------------------------------------------------------------------

    def _poll(self) -> Optional[PollResult]:
        # Chaos injection point: arming this simulates a master-plane
        # failure, which must escalate to the whole-experiment relaunch
        # (the master is NOT a restartable fault domain).
        faults.maybe_fail("master.step")
        t0 = time.monotonic()
        epoch_before = self.step_info.epoch

        # Keep the shared coroutine-control step info (shipped in every
        # MFC request: param-realloc stamps, trace attributes) in sync
        # with the authoritative counter.
        self.ctrl.step_info.update(
            epoch=self.step_info.epoch,
            epoch_step=self.step_info.epoch_step,
            global_step=self.step_info.global_step,
        )
        self.buffer.current_train_step = self.step_info.global_step
        with tracing.span(
            "master.step", step=self.step_info.global_step
        ):
            stats = self.executor.execute_step_sync()

        epoch_boundary = self.executor.epoch_done
        if (
            not epoch_boundary
            and self._derive_epoch_boundary
            and self._steps_per_epoch
        ):
            # Async runs: derive the boundary from the configured prompt
            # dataset size so epoch-based save/eval frequencies and
            # total_train_epochs terminate them too (ADVICE r1 finding b).
            epoch_boundary = (
                self.step_info.epoch_step + 1 >= self._steps_per_epoch
            )
        self.step_info.epoch_step += 1
        self.step_info.global_step += 1
        if epoch_boundary:
            self.step_info.epoch += 1
            self.step_info.epoch_step = 0
            self.buffer.on_epoch_boundary()

        e2e = time.monotonic() - t0
        logger.info(
            f"step {self.step_info.global_step} "
            f"(epoch {self.step_info.epoch}.{self.step_info.epoch_step}) "
            f"e2e={e2e:.3f}s stats={ {k: {kk: round(vv, 5) for kk, vv in v.items()} for k, v in stats.items()} }"
        )
        self._log_step_perf(e2e)

        epochs_inc = self.step_info.epoch - epoch_before
        if self.save_ctl.check(steps=1, epochs=epochs_inc):
            self._broadcast("save")
        if self.ckpt_ctl.check(steps=1, epochs=epochs_inc):
            self._broadcast("ckpt")
            self._recover_save()
        if self.eval_ctl.check(steps=1, epochs=epochs_inc):
            self._broadcast("evaluate")

        done = False
        if self._total_steps_cap is not None:
            done = self.step_info.global_step >= self._total_steps_cap
        elif self.step_info.epoch >= (self.cfg.exp_ctrl.total_train_epochs or 1):
            done = True
        if done:
            self.experiment_complete_exit()
            return None
        return PollResult(sample_count=1, batch_count=1)

    def _log_step_perf(self, e2e: float):
        """Per-step performance telemetry (reference master_worker.py:497-533:
        `timeperf/e2e`, per-MFC wall time, analytic TFLOP/s) mirrored to
        tensorboard/wandb."""
        mfc_stats = dict(self.executor.ctrl.mfc_stats)
        self.executor.ctrl.mfc_stats = {}
        scalars = {"timeperf/e2e": e2e}
        total_flops = 0.0
        for name, st in mfc_stats.items():
            for k, v in st.items():
                if not isinstance(v, (int, float)):
                    continue
                if k == mreg.PERF_ELAPSED:
                    scalars[f"timeperf/{name}"] = v
                elif k == mreg.PERF_TFLOPS:
                    scalars[f"tflops/{name}"] = v
                elif k == mreg.PERF_FLOPS:
                    total_flops += v
                elif k == mreg.PERF_GEN_TOKENS_PER_SEC:
                    scalars[f"gen_tokens_per_sec/{name}"] = v
                elif k in (
                    mreg.PERF_PACKING_EFFICIENCY,
                    mreg.PERF_H2D_WAIT_MS,
                    mreg.PERF_DISPATCH_GAP_MS,
                    # Regression note: perf/overlap_events was shipped
                    # nowhere and parsed by the bench anyway until the
                    # metrics-registry checker caught it; now the
                    # engine emits it and the master folds it into the
                    # overlap series like its sibling telemetry.
                    mreg.PERF_OVERLAP_EVENTS,
                    # Rollout-pipeline series (PR 3): episode e2e latency
                    # percentiles + interruption re-prefill tokens, from
                    # trajectory metadata (async runs only).
                    mreg.PERF_ROLLOUT_E2E_P50_MS,
                    mreg.PERF_ROLLOUT_E2E_P95_MS,
                    mreg.PERF_REPREFILL_TOKENS,
                    # MoE router health (PR 17): realized drop rate,
                    # entropy, hottest-expert load, a2a volume — per-MFC
                    # series + running mean in perf_summary for the
                    # moe_scaling bench passthrough.
                    mreg.PERF_MOE_DROP_RATE,
                    mreg.PERF_MOE_ROUTER_ENTROPY,
                    mreg.PERF_MOE_EXPERT_OVERLOAD,
                    mreg.PERF_MOE_A2A_BYTES,
                    # Agentic episodes (PR 18): turn/tool-call volume and
                    # the PER-TASK staleness means that back the split
                    # admission windows (math tight, agentic loose).
                    mreg.PERF_EPISODE_TURNS,
                    mreg.PERF_EPISODE_TOOL_CALLS,
                    mreg.PERF_TASK_STALENESS_MATH,
                    mreg.PERF_TASK_STALENESS_AGENTIC,
                    # Mixed-stream runs (PR 19): admission-side drop
                    # attribution, the per-task split of the buffer's
                    # stale-drop counter.
                    mreg.PERF_TASK_STALE_DROPPED_MATH,
                    mreg.PERF_TASK_STALE_DROPPED_AGENTIC,
                ):
                    # Input-pipeline telemetry: per-MFC series + running
                    # mean in perf_summary["overlap"].
                    metric = k[len("perf/"):]
                    scalars[f"{metric}/{name}"] = v
                    acc = self._overlap_acc.setdefault(metric, [0.0, 0])
                    acc[0] += v
                    acc[1] += 1
                elif not k.startswith("perf/"):
                    scalars[k] = v
        if total_flops:
            scalars["tflops/e2e"] = total_flops / e2e / 1e12
        self.perf_summary["steps"] += 1
        self.perf_summary["total_e2e_s"] += e2e
        self.perf_summary["wall_s"] = time.monotonic() - self._start_time
        # Effective trained tokens: every train interface reports an
        # additive <name>/n_tokens (e.g. ppo_actor/n_tokens).
        step_tokens = sum(
            v for k, v in scalars.items()
            if k.endswith("/n_tokens") and isinstance(v, (int, float))
        )
        self.perf_summary["train_tokens"] += step_tokens
        # Per-step history only for bounded benchmark runs (its consumer
        # is the speedup benchmark's warmup-drop); an open-ended RL run
        # would grow it for the process lifetime.
        if self._total_steps_cap is not None:
            self.perf_summary["history"].append([e2e, step_tokens])
        self.perf_summary["overlap"] = {
            m: float(s / n) for m, (s, n) in self._overlap_acc.items() if n
        }
        perf_keys = [
            k for k in sorted(scalars)
            if k.startswith((
                "timeperf/", "tflops/", "gen_tokens_per_sec/",
                "packing_efficiency/", "h2d_wait_ms/", "dispatch_gap_ms/",
                "overlap_events/", "rollout_e2e_p50_ms/",
                "rollout_e2e_p95_ms/", "reprefill_tokens/",
                "moe_drop_rate/", "moe_router_entropy/",
                "moe_expert_overload/", "moe_a2a_bytes/",
            ))
        ]
        logger.info(
            "benchmark: "
            + " ".join(f"{k}={scalars[k]:.4g}" for k in perf_keys)
        )
        logging.log_scalars_to_trackers(
            scalars,
            step=self.step_info.global_step,
            summary_writer=self._summary_writer,
            wandb_run=self._wandb_run,
        )

    def experiment_complete_exit(self):
        """Signal completion + tell workers to exit (reference
        master_worker.py:538)."""
        logger.info(
            f"experiment complete after {self.step_info.global_step} steps "
            f"({time.monotonic() - self._start_time:.1f}s)"
        )
        name_resolve.add(
            names.experiment_status(
                self.cfg.experiment_name, self.cfg.trial_name
            ),
            "COMPLETE",
            replace=True,
        )
        try:
            self._broadcast("exit", timeout=60)
        except Exception:
            logger.warning("some workers did not ack exit", exc_info=True)
        self._collect_rl_trace_summary()

    def _collect_rl_trace_summary(self):
        """With AREAL_RL_TRACE=1, fold the merged-trace verdict (overlap
        score, staleness histogram, phase latencies) into perf_summary —
        the run's timeline evidence next to its throughput numbers.

        Best-effort by construction: workers ack the exit broadcast
        BEFORE their run-loop finally flushes their shard, so this reads
        a short grace period later and may still miss a worker's last
        batch. The authoritative artifact is scripts/merge_rl_trace.py
        over the shard dir after every process has exited."""
        if not tracing.enabled():
            return
        time.sleep(1.0)
        tracing.flush()
        try:
            from areal_tpu.utils import rl_trace

            self.perf_summary["rl_trace"] = rl_trace.summarize(
                tracing.trace_dir()
            )
            logger.info(
                "rl_trace summary: overlap_score="
                f"{self.perf_summary['rl_trace'].get('overlap_score', 0):.3f} "
                f"staleness={self.perf_summary['rl_trace'].get('staleness_hist')}"
            )
        except Exception:
            logger.warning("rl_trace summary failed", exc_info=True)

    def _exit_hook(self):
        try:
            self.stream.close()
        except Exception:
            pass
        if getattr(self, "_summary_writer", None) is not None:
            try:
                self._summary_writer.close()
            except Exception:
                pass

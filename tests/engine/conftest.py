import jax
import pytest

from areal_tpu.models.transformer import init_params
from tests.engine.serving_utils import TINY_SERVING_CFG


@pytest.fixture(scope="package")
def params():
    """Params for serving_utils.TINY_SERVING_CFG, shared package-wide.
    Modules that need a different model define their own `params`."""
    return init_params(TINY_SERVING_CFG, jax.random.PRNGKey(0))

import numpy as np
import pytest

from areal_tpu.base.datapack import (
    balanced_partition,
    ffd_allocate,
    flat2d,
    min_abs_diff_partition,
)


def test_flat2d():
    assert flat2d([[1, 2], [3], []]) == [1, 2, 3]


@pytest.mark.parametrize("seed", range(5))
def test_ffd_allocate_respects_capacity(seed):
    rng = np.random.RandomState(seed)
    lengths = rng.randint(1, 500, size=50)
    cap = 1000
    groups = ffd_allocate(lengths, capacity=cap, min_groups=1)
    seen = sorted(flat2d(groups))
    assert seen == list(range(50))
    for g in groups:
        if len(g) > 1:
            assert sum(lengths[i] for i in g) <= cap


def test_ffd_min_groups():
    groups = ffd_allocate([5, 5, 5, 5], capacity=1000, min_groups=3)
    assert len(groups) >= 3


def test_ffd_oversized_item_own_bin():
    groups = ffd_allocate([2000, 10], capacity=100, min_groups=1)
    assert sorted(flat2d(groups)) == [0, 1]


@pytest.mark.parametrize("k", [1, 2, 3, 7])
def test_min_abs_diff_partition(k):
    rng = np.random.RandomState(0)
    nums = rng.randint(1, 100, size=23)
    groups = min_abs_diff_partition(nums, k)
    assert len(groups) == k
    assert flat2d(groups) == list(range(23))  # contiguous, ordered
    assert all(groups)
    sums = [sum(nums[i] for i in g) for g in groups]
    assert max(sums) - min(sums) <= max(nums) * 2  # roughly balanced


def test_balanced_partition():
    groups = balanced_partition([10, 1, 1, 1, 10, 1], 2)
    sums = [sum([10, 1, 1, 1, 10, 1][i] for i in g) for g in groups]
    assert abs(sums[0] - sums[1]) <= 2

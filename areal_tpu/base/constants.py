"""Per-process global experiment context.

Counterpart of the reference's constants module (realhf/base/constants.py):
holds the experiment/trial names, the current model scope (the model an MFC
is executing for), filesystem layout helpers, and a registry of per-model
mesh/engine handles. Everything here is host-side Python state — device
state lives in the engines.
"""

from __future__ import annotations

import contextlib
import getpass
import os
from typing import Any, Dict, Optional

# ---------------------------------------------------------------------------
# Experiment identity
# ---------------------------------------------------------------------------

_experiment_name: Optional[str] = None
_trial_name: Optional[str] = None

# Filesystem root for logs/checkpoints/realloc params. AREAL_FILEROOT is
# resolved at CALL time, not import time: spawned workers import this
# module while unpickling their config (before the controller-provided
# env lands in os.environ), so an import-time snapshot would silently
# pin every worker to the default root. The module-level names below
# stay as explicit overrides (tests monkeypatch them).
MODEL_SAVE_ROOT: Optional[str] = None
LOG_ROOT: Optional[str] = None
RECOVER_ROOT: Optional[str] = None
PARAM_REALLOC_ROOT: Optional[str] = None


def get_fileroot() -> str:
    from areal_tpu.base import env_registry

    return (
        env_registry.get_str("AREAL_FILEROOT")
        or f"/tmp/areal_tpu/{getpass.getuser()}"
    )

# Mirrors the reference's NCCL timeout role: how long collective setup /
# barrier operations may block before we declare a peer dead.
DEFAULT_COLLECTIVE_TIMEOUT_SECONDS = 3600


def set_experiment_trial_names(experiment_name: str, trial_name: str):
    global _experiment_name, _trial_name
    _experiment_name = experiment_name
    _trial_name = trial_name


def experiment_name() -> str:
    if _experiment_name is None:
        raise RuntimeError("experiment_name accessed before set_experiment_trial_names")
    return _experiment_name


def trial_name() -> str:
    if _trial_name is None:
        raise RuntimeError("trial_name accessed before set_experiment_trial_names")
    return _trial_name


def has_experiment_trial_names() -> bool:
    return _experiment_name is not None and _trial_name is not None


# ---------------------------------------------------------------------------
# Paths
# ---------------------------------------------------------------------------


def get_log_path(experiment: Optional[str] = None, trial: Optional[str] = None) -> str:
    root = LOG_ROOT or os.path.join(get_fileroot(), "logs")
    p = os.path.join(root, experiment or experiment_name(), trial or trial_name())
    os.makedirs(p, exist_ok=True)
    return p


def get_save_path(experiment: Optional[str] = None, trial: Optional[str] = None) -> str:
    root = MODEL_SAVE_ROOT or os.path.join(get_fileroot(), "checkpoints")
    p = os.path.join(root, experiment or experiment_name(), trial or trial_name())
    os.makedirs(p, exist_ok=True)
    return p


def get_recover_path(experiment: Optional[str] = None, trial: Optional[str] = None) -> str:
    root = RECOVER_ROOT or os.path.join(get_fileroot(), "recover")
    p = os.path.join(root, experiment or experiment_name(), trial or trial_name())
    os.makedirs(p, exist_ok=True)
    return p


def get_param_realloc_path(
    experiment: Optional[str] = None, trial: Optional[str] = None
) -> str:
    root = PARAM_REALLOC_ROOT or os.path.join(get_fileroot(), "param_realloc")
    p = os.path.join(
        root, experiment or experiment_name(), trial or trial_name()
    )
    os.makedirs(p, exist_ok=True)
    return p


# ---------------------------------------------------------------------------
# Model scope
# ---------------------------------------------------------------------------

_model_scope_stack = []

# Per-model host-side handles (mesh, engine, tokenizer, ...). Keyed by the
# string form of a ModelName.
_model_registries: Dict[str, Dict[str, Any]] = {}


@contextlib.contextmanager
def model_scope(model_name):
    """Execute a block with `current_model_name()` set (MFC execution)."""
    _model_scope_stack.append(model_name)
    try:
        yield
    finally:
        _model_scope_stack.pop()


def current_model_name():
    if not _model_scope_stack:
        raise RuntimeError("current_model_name accessed outside model_scope")
    return _model_scope_stack[-1]


def has_model_scope() -> bool:
    return bool(_model_scope_stack)


def set_model_attr(model_name, key: str, value: Any):
    _model_registries.setdefault(str(model_name), {})[key] = value


def get_model_attr(model_name, key: str) -> Any:
    try:
        return _model_registries[str(model_name)][key]
    except KeyError:
        raise KeyError(f"no attr {key!r} registered for model {model_name}")


def has_model_attr(model_name, key: str) -> bool:
    return key in _model_registries.get(str(model_name), {})


def clear_model_registry():
    _model_registries.clear()

"""Model worker: hosts model shards + datasets, executes MFCs.

Counterpart of the reference's ModelWorker
(realhf/system/model_worker.py:101-1610). One model worker drives one
jax mesh (its local TPU devices) and acts as one DP rank of every model
it hosts. Request handlers:

- "spec": dataset size + readiness handshake
- "fetch": next dataloader batch -> DataManager, reply metadata
- "mfc": execute pre-hooks (data_transfer pulls, param_realloc, ...),
  assemble the input batch, run the interface method under
  `constants.model_scope`, store outputs, reply meta + stats
- "save"/"ckpt"/"evaluate"/"restore": persistence + recovery
- "clear_data_cache": per-step sample GC
- "exit": leave the poll loop
"""

from __future__ import annotations

import os
import queue
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

from areal_tpu.api import data_api
from areal_tpu.api.config import ModelName
from areal_tpu.api.data_api import MicroBatchSpec, SequenceSample
from areal_tpu.base import monitor
from areal_tpu.utils import profiling
from areal_tpu.api.model_api import (
    FinetuneSpec,
    Model,
    make_backend,
    make_interface,
    make_model,
)
from areal_tpu.api.system_api import ModelWorkerConfig
from areal_tpu.base import constants, env_registry, logging, metrics_registry, name_resolve, names, recover, seeding, stats_tracker, timeutil, tracing
from areal_tpu.system import eval_scores
from areal_tpu.system import request_reply_stream as rrs
from areal_tpu.system.data_manager import DataManager
from areal_tpu.system.redistributor import RedistribStep
from areal_tpu.system.worker_base import PollResult, Worker

logger = logging.getLogger("model_worker")


class ModelWorker(Worker):
    def _configure(self, config: ModelWorkerConfig):
        self.cfg = config
        constants.set_experiment_trial_names(
            config.experiment_name, config.trial_name
        )
        seeding.set_random_seed(config.seed, config.worker_name)
        # Import factories/interfaces so registries are populated.
        import areal_tpu.engine.factories  # noqa: F401
        import areal_tpu.interfaces  # noqa: F401
        import areal_tpu.datasets  # noqa: F401

        self.stream = rrs.make_worker_stream(
            config.experiment_name, config.trial_name, config.worker_name
        )
        self.data_manager = DataManager(
            config.experiment_name, config.trial_name, config.worker_name
        )

        # Multi-host sharded training: join the train partition's host
        # group BEFORE any model (or device) is touched — jax.distributed
        # must initialize before the first backend acquires devices, and
        # every host must rendezvous or the global mesh never forms.
        self._train_group = None
        if int(getattr(config, "train_n_hosts", 1) or 1) > 1:
            from areal_tpu.parallel.distributed import setup_host_group

            self._train_group = setup_host_group(
                config.experiment_name,
                config.trial_name,
                "train",
                config.train_host_rank,
                config.train_n_hosts,
            )
            logger.info(
                f"{config.worker_name}: joined train host group as "
                f"{config.train_host_rank}/{config.train_n_hosts} "
                f"(coordinator {self._train_group.coordinator_address})"
            )

        # Datasets (only on data-hosting workers).
        self.dataloader = None
        self._dataset = None
        if config.stream_dataset:
            from areal_tpu.system.stream_dataset import PullerStreamDataset

            self._dataset = PullerStreamDataset(
                config.experiment_name,
                config.trial_name,
                puller_index=config.dataset_dp_rank,
            )
            self.dataloader = None
        elif config.datasets:
            tokenizer = (
                data_api.load_hf_tokenizer(config.tokenizer_path)
                if config.tokenizer_path
                else None
            )
            util = data_api.DatasetUtility(
                seed=config.seed,
                dp_rank=config.dataset_dp_rank,
                world_size=config.dataset_dp_size,
                tokenizer=tokenizer,
            )
            datasets = [
                data_api.make_dataset(d, util) for d in config.datasets
            ]
            self._dataset = (
                datasets[0]
                if len(datasets) == 1
                else data_api.ConcatDataset(datasets)
                if hasattr(data_api, "ConcatDataset")
                else datasets[0]
            )
            self.dataloader = data_api.PackedDataLoader(
                self._dataset,
                batch_size=max(
                    1, config.train_batch_size // config.dataset_dp_size
                ),
                shuffle=config.shuffle_dataset,
                seed=config.seed,
            )

        # Models.
        self.models: Dict[str, Model] = {}
        self.interfaces: Dict[str, Any] = {}
        self.backends: Dict[str, Any] = {}
        dataset_size = len(self._dataset) * config.dataset_dp_size if self._dataset is not None else 0
        self._host_rank: Dict[str, int] = {}
        for shard in config.shards:
            mn = shard.id.model_name
            self._host_rank[str(mn)] = shard.id.host_rank
            ft_spec = FinetuneSpec(
                total_train_epochs=config.total_train_epochs,
                dataset_size=dataset_size,
                train_batch_size=config.train_batch_size,
            )
            model = make_model(shard.model, name=mn)
            backend = make_backend(shard.backend)
            model = backend.initialize(model, ft_spec)
            # Startup verification that this process hosts exactly its
            # slice of every multi-device train mesh (the training-side
            # mirror of the serving fleet's weight-shard check): a
            # misconfigured host must fail HERE with an actionable
            # message, not deep inside the first collective.
            mesh = getattr(model.module, "mesh", None)
            if mesh is not None and mesh.size > 1:
                from areal_tpu.parallel.distributed import (
                    verify_host_mesh_slice,
                )

                info = verify_host_mesh_slice(
                    mesh,
                    getattr(config, "train_host_rank", 0),
                    int(getattr(config, "train_n_hosts", 1) or 1),
                )
                logger.info(
                    f"{config.worker_name}: {mn} mesh "
                    f"{dict(mesh.shape)} verified — hosts "
                    f"{info['local_devices']}/{info['mesh_devices']} "
                    f"devices as slice {info['host_rank']}/"
                    f"{info['n_hosts']}"
                )
            self.models[str(mn)] = model
            self.backends[str(mn)] = backend
            self.interfaces[str(mn)] = make_interface(shard.interface)
        logger.info(
            f"{config.worker_name} configured: models={list(self.models)}, "
            f"dataset_size={dataset_size}"
        )

    # ------------------------------------------------------------------
    # Handlers
    # ------------------------------------------------------------------

    def _handle_spec(self, req):
        # LOCAL size only; the master sums across data hosts.
        local = len(self._dataset) if self._dataset is not None else 0
        return {"dataset_size": local, "models": list(self.models)}

    def _handle_fetch(self, req):
        if self.dataloader is None and self._dataset is None:
            return {"meta": None, "epoch_done": False}
        if self.dataloader is not None:
            batch, epoch_done = self.dataloader.next_batch()
            if epoch_done:
                # Curriculum step at the epoch boundary (reference
                # model_worker.py:576-618 filters on dataloader
                # StopIteration): drop prompts the policy already solves;
                # the dataloader detects the size change and reshuffles.
                eval_scores.apply_filter(
                    self._dataset,
                    self.cfg.experiment_name,
                    self.cfg.trial_name,
                    tag=f"data{self.cfg.worker_index}",
                    # Floor at the per-rank fetch batch: dropping below it
                    # would starve the master's batch assembly forever.
                    min_size=self.dataloader.batch_size,
                )
        else:
            batch = self._dataset.poll_batch()
            epoch_done = False
            if batch is None:
                return {"meta": None, "epoch_done": False}
        self.data_manager.store(batch)
        return {"meta": batch.meta(), "epoch_done": epoch_done}

    def _exec_hook(self, hook: Dict, model_name: str, step: int = 0):
        htype = hook.get("type")
        if htype == "data_transfer":
            steps = [RedistribStep(**s) for s in hook["plan"]]
            self.data_manager.redistribute(steps)
        elif htype == "save":
            self._save_model(model_name)
        elif htype == "evaluate":
            self._evaluate_model(model_name)
        elif htype == "offload":
            model = self.models.get(model_name)
            if model is not None and hasattr(model.module, "offload"):
                # Free the idle model's HBM; the engine restores lazily
                # on its next call (jax_engine.offload).
                model.module.offload()
            else:
                logger.debug("offload hook: engine has no offload; no-op")
        elif htype == "param_realloc":
            self._param_realloc(hook, step)
        else:
            raise ValueError(f"unknown hook {hook!r}")

    def _handle_mfc(self, req) -> Dict:
        d = req.data
        model_name = d["model_name"]
        model = self.models[model_name]
        interface = self.interfaces[model_name]

        step = int(d.get("step_info", {}).get("global_step", 0))
        # Pre-hooks: data transfer plan is embedded in the request.
        if d.get("plan"):
            self.data_manager.redistribute(
                [RedistribStep(**s) for s in d["plan"]]
            )
        for hook in req.pre_hooks:
            self._exec_hook(hook, model_name, step)

        input_ = self.data_manager.gather(d["ids"], d["input_keys"])
        if d.get("input_key_remap"):
            input_.remap_keys_(d["input_key_remap"])
        mb_spec = MicroBatchSpec(**d["mb_spec"])

        itype = d["interface_type"]
        mn = ModelName.parse(model_name)
        t0 = time.monotonic()
        # Worker-side MFC execution span, parented under the master's
        # MFC span (trace_ctx rides the request payload). The train-step
        # spans are the "training busy" track of the merged timeline's
        # overlap score.
        with constants.model_scope(mn), tracing.span(
            f"mfc.{d.get('mfc_name', itype)}",
            ctx=tracing.extract(req.trace_ctx),
            itype=itype,
            model=model_name,
            step=step,
            n_seqs=len(d["ids"]),
        ), profiling.maybe_profile(
            d.get("mfc_name", itype), step
        ):
            if itype == "generate":
                out = interface.generate(model, input_, mb_spec)
                stats = {}
            elif itype == "inference":
                out = interface.inference(model, input_, mb_spec)
                stats = {}
            elif itype == "train_step":
                res = interface.train_step(model, input_, mb_spec)
                out = None
                stats = res[-1] if isinstance(res, list) else res
            else:
                raise ValueError(f"bad interface_type {itype!r}")
        # Per-MFC perf accounting shipped back to the master (counterpart
        # of the reference's FlopsCounter + time_record,
        # realhf/system/flops_counter.py, model_function_call.py:460-472).
        # Worker-side because only the worker knows the model config and
        # the true packed shapes.
        stats = dict(stats or {})
        # Stats recorded through the tracker during the interface call ship
        # with their declared reduce types so the master merges MIN/MAX/SUM
        # stats correctly across DP workers (merge_worker_stats).
        tracked, ttypes = stats_tracker.export(return_types=True)
        stats.update(tracked)
        if ttypes:
            stats["__reduce_types__"] = ttypes
        stats["perf/sec"] = time.monotonic() - t0
        # HBM telemetry + OOM guard after every MFC (reference
        # model_worker.py:1507-1610 GPU-memory watch + kill threshold):
        # zeros on backends without memory_stats, so always logged.
        mem = monitor.device_memory_stats()
        # Regression note: this used to f-string-build `perf/{k}` keys,
        # invisible to the metrics registry — a renamed monitor stat
        # would ship an undeclared key downstream consumers silently
        # drop. perf_mem_stats validates every key against the
        # registry (metrics-registry lint checker).
        stats.update(metrics_registry.perf_mem_stats(mem))
        monitor.check_memory_kill_threshold(mem)
        cfg = getattr(model.module, "model_cfg", None)
        if cfg is not None:
            in_lens = [
                l for sl in input_.seqlens[input_._main_key()] for l in sl
            ]
            # Packing-density accounting for train/inference MFCs: the
            # engine records the REALIZED density of what it shipped to
            # HBM (tracked export above); when it did not run a packed
            # path (mock engines, custom interfaces) fall back to the
            # analytic FFD estimate over this MFC's input lengths.
            # Generate MFCs are deliberately excluded — the serving
            # engine admits prompts into a paged pool, so a row-pack
            # density over its inputs would be a made-up number.
            row_mult = getattr(model.module, "row_len_multiple", None)
            if (
                itype in ("train_step", "inference")
                and in_lens
                and row_mult
                and "perf/packing_efficiency" not in stats
            ):
                from areal_tpu.base import datapack

                stats["perf/packing_efficiency"] = datapack.packing_density(
                    in_lens,
                    row_len_multiple=row_mult,
                    max_row_len=getattr(model.module, "max_row_len", None),
                )
            out_lens = None
            if out is not None and itype == "generate":
                try:
                    ok = out._main_key()
                    out_lens = [l for sl in out.seqlens[ok] for l in sl]
                except Exception:
                    out_lens = None
            stats["perf/flops"] = float(
                monitor.mfc_flops(cfg, itype, in_lens, out_lens)
            )
            if itype == "generate" and out_lens:
                # Group sampling replicates each prompt gconfig.n times in
                # the output, so subtract each prompt once per replica.
                group = (
                    len(out_lens) // len(in_lens)
                    if in_lens and len(out_lens) % len(in_lens) == 0
                    else 1
                )
                stats["perf/gen_tokens"] = float(
                    sum(out_lens) - group * sum(in_lens)
                )

        output_meta = None
        if out is not None:
            # Per-prompt eval scores from the reward MFC feed the dataset
            # curriculum filter (reference model_worker.py:956-994; the
            # all-gather is replaced by a locked file merge). Popped so
            # scores don't ride along into downstream MFC inputs. EVERY
            # worker writes: DP ranks hold disjoint id slices, so skipping
            # non-zero ranks would leave their prompts unscorable.
            scores = out.metadata.pop("scores", None)
            if scores:
                eval_scores.merge_scores(
                    self.cfg.experiment_name,
                    self.cfg.trial_name,
                    dict(zip(out.ids, scores)),
                )
            if d.get("output_key_remap"):
                out.remap_keys_(d["output_key_remap"])
            self.data_manager.store(out)
            output_meta = out.meta()

        for hook in req.post_hooks:
            self._exec_hook(hook, model_name, step)

        if itype == "train_step" and self._host_rank.get(model_name, 0) == 0:
            # Publish AFTER post-hooks: the param-realloc dump the gserver
            # manager fans out must be on disk before the version appears,
            # or servers would load the previous step's weights under the
            # new version number. Only DP rank 0 publishes (and dumps).
            self._publish_version(mn)

        return {"stats": stats, "output_meta": output_meta}

    def _publish_version(self, model_name: ModelName):
        model = self.models[str(model_name)]
        name_resolve.add(
            names.model_version(
                self.cfg.experiment_name, self.cfg.trial_name, model_name.role
            ),
            str(model.version),
            replace=True,
        )

    def _save_model(self, model_name: Optional[str] = None):
        for mn, model in self.models.items():
            if model_name is not None and mn != model_name:
                continue
            iface = self.interfaces[mn]
            save_dir = os.path.join(
                constants.get_save_path(
                    self.cfg.experiment_name, self.cfg.trial_name
                ),
                ModelName.parse(mn).role,
                f"step{model.version}",
                f"dp{self.cfg.worker_index}",
            )
            iface.save(model, save_dir)

    def _ckpt_dir(self, mn: str) -> str:
        return os.path.join(
            constants.get_recover_path(
                self.cfg.experiment_name, self.cfg.trial_name
            ),
            ModelName.parse(mn).role,
            f"dp{self.cfg.worker_index}",
        )

    def _handle_ckpt(self, req):
        for mn, model in self.models.items():
            self.backends[mn].save(model, self._ckpt_dir(mn))
        if self.dataloader is not None:
            import json

            state_path = os.path.join(
                constants.get_recover_path(
                    self.cfg.experiment_name, self.cfg.trial_name
                ),
                f"dataloader_{self.cfg.worker_index}.json",
            )
            os.makedirs(os.path.dirname(state_path), exist_ok=True)
            # Atomic like every other recovery artifact: a kill
            # mid-write must leave the previous cursor, not a torn file.
            tmp = state_path + f".tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(self.dataloader.state_dict(), f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, state_path)
        self._compact_stream_wal()
        return {"ok": True}

    def _compact_stream_wal(self):
        """Checkpoint-barrier WAL truncation, one barrier behind: drop
        journaled rollouts whose seqs the PREVIOUS durable recover
        record already marked consumed. (The record for THIS barrier is
        written by the master after this handler returns; compacting
        against the previous one keeps truncation strictly behind the
        durable ledger — it is GC, safe to lag, never safe to lead.)"""
        dataset = self._dataset
        if dataset is None or not hasattr(dataset, "compact_wal"):
            return
        try:
            info = recover.load(self.cfg.experiment_name, self.cfg.trial_name)
        except (FileNotFoundError, ValueError):
            return
        from areal_tpu.system.wal import SeqLedger

        snapshot = getattr(info, "consumed_seqs", None)
        if not snapshot:
            return
        try:
            dropped = dataset.compact_wal(SeqLedger.from_dict(snapshot))
            if dropped:
                logger.info("WAL compaction dropped %d consumed record(s)",
                            dropped)
        except Exception:
            logger.exception("WAL compaction failed (journal kept as-is)")

    def _handle_restore(self, req):
        from areal_tpu.engine.checkpoint import has_engine_state

        for mn, model in self.models.items():
            d = self._ckpt_dir(mn)
            if has_engine_state(d):
                self.backends[mn].load(model, d)
        if self.dataloader is not None:
            import json

            # Curriculum state first: the dataloader snapshot records the
            # FILTERED dataset size, so indices must be restored before
            # load_state_dict's size check (reference
            # model_worker.py:368-385 does the same at model setup).
            eval_scores.restore_indices(
                self._dataset,
                self.cfg.experiment_name,
                self.cfg.trial_name,
                tag=f"data{self.cfg.worker_index}",
            )
            state_path = os.path.join(
                constants.get_recover_path(
                    self.cfg.experiment_name, self.cfg.trial_name
                ),
                f"dataloader_{self.cfg.worker_index}.json",
            )
            if os.path.exists(state_path):
                with open(state_path) as f:
                    self.dataloader.load_state_dict(json.load(f))
                self.dataloader.restart_epoch()
        return {"ok": True}

    def _evaluate_model(self, model_name: Optional[str] = None):
        stats = {}
        for mn, model in self.models.items():
            if model_name is not None and mn != model_name:
                continue
            iface = self.interfaces[mn]
            stats[mn] = iface.evaluate(model, None)
        return stats

    # ------------------------------------------------------------------

    def _poll(self) -> Optional[PollResult]:
        try:
            req = self.stream.poll(block=True, timeout_ms=50)
        except rrs.NoMessage:
            return PollResult(batch_count=0)
        try:
            h = req.handle_name
            if h == "spec":
                resp = self._handle_spec(req)
            elif h == "fetch":
                resp = self._handle_fetch(req)
            elif h == "mfc":
                resp = self._handle_mfc(req)
            elif h == "save":
                self._save_model()
                resp = {"ok": True}
            elif h == "ckpt":
                resp = self._handle_ckpt(req)
            elif h == "restore":
                resp = self._handle_restore(req)
            elif h == "evaluate":
                resp = self._evaluate_model()
            elif h == "clear_data_cache":
                self.data_manager.clear(req.data)
                resp = {"ok": True}
            elif h == "exit":
                self.stream.reply_to(req, {"ok": True})
                self.exit()
                return PollResult(batch_count=1)
            else:
                resp = {"error": f"unknown handle {h!r}"}
        except Exception as e:
            logger.exception(f"error handling {req.handle_name}")
            resp = {"error": repr(e)}
        self.stream.reply_to(req, resp)
        return PollResult(batch_count=1)

    def _exit_hook(self):
        try:
            # A clean exit must not abandon an in-flight async
            # checkpoint write (the daemon writer dies with the process).
            from areal_tpu.engine.checkpoint import wait_pending_writes

            wait_pending_writes(timeout=60)
        except Exception:
            logger.exception("pending checkpoint writes not drained on exit")
        try:
            for src in getattr(self, "_wp_sources", {}).values():
                src.close()
            self.stream.close()
            self.data_manager.close()
            if self._dataset is not None and hasattr(self._dataset, "close"):
                self._dataset.close()
        except Exception:
            pass

    def _ensure_weight_plane_source(self, role: str, dump_dir: str):
        """Start (once per role) the trainer-side origin of the weight
        plane and register its URL for manager discovery."""
        sources = getattr(self, "_wp_sources", None)
        if sources is None:
            sources = self._wp_sources = {}
        if role in sources:
            return
        from areal_tpu.base import network
        from areal_tpu.system.weight_plane import WeightPlaneSource

        src = WeightPlaneSource(
            dump_dir,
            chunk_bytes=getattr(self.cfg, "weight_chunk_bytes", 8 << 20),
            host=network.gethostip(),
        ).start()
        src.register(self.cfg.experiment_name, self.cfg.trial_name, role)
        sources[role] = src
        logger.info(
            f"weight-plane source for {role} at {src.address} over {dump_dir}"
        )

    def _param_realloc(self, hook: Dict, step: int = 0):
        """Disk-mediated weight sync between model replicas (reference
        __param_realloc, model_worker.py:1046; DISK impl is the reference
        default). The source stamps the dump with the global step; the
        target WAITS for a stamp >= its step, so a cross-worker load can
        never silently pick up stale (or missing) weights."""
        import time as _time

        src, dst = hook.get("source"), hook.get("target")
        realloc_root = constants.get_param_realloc_path(
            self.cfg.experiment_name, self.cfg.trial_name
        )
        src_model = self.models.get(src) if src is not None else None
        multi_proc = False
        mesh_size = 1
        if src_model is not None:
            import jax

            mesh = getattr(src_model.module, "mesh", None)
            mesh_size = int(getattr(mesh, "size", 1) or 1)
            multi_proc = any(
                isinstance(l, jax.Array) and not l.is_fully_addressable
                for l in jax.tree_util.tree_leaves(
                    src_model.module.get_params()
                )
            )
        # Single writer per shard: DP replicas hold identical logical
        # params, so only rank 0 dumps — EXCEPT on a multi-process
        # (jax.distributed) train mesh, where every process must write
        # its own slab of the shard-local dump (rank 0 alone cannot even
        # address the other hosts' shards).
        if src_model is not None and (
            self._host_rank.get(src, 0) == 0 or multi_proc
        ):
            model = src_model
            role = ModelName.parse(src).role
            d = os.path.join(realloc_root, role)
            from areal_tpu.engine.checkpoint import save_engine_state
            from areal_tpu.system.weight_transfer import (
                LAST_DUMP_STATS, dump_raw_params, dump_raw_params_sharded,
                mirror_dump_version, shm_transfer_dir,
            )

            import jax

            sharded = mesh_size > 1 or multi_proc
            is_rank0 = (
                self._host_rank.get(src, 0) == 0
                and (not multi_proc or jax.process_index() == 0)
            )
            if is_rank0 and not sharded:
                # The realloc dump is a TRANSFER format, not a recover
                # checkpoint: the destination reads engine_state.pkl
                # directly (below) — an orbax (collective, shard-wise)
                # save here would deadlock multi-host and break the
                # reader. Sharded engines skip the pickle entirely: it
                # would host-gather the full model (the exact cost the
                # shard-local dump removes); a dst model falls back to
                # assembling the raw dump (below).
                save_engine_state(model.module, d, backend="pickle")
            # Stamp the dump with model.version — the exact value
            # _publish_version later announces — NOT the global step:
            # the two counters differ (step counts MFC dispatches from
            # 0; version increments inside train_step), and the
            # generation server now VERIFIES the loaded dump matches
            # the requested version (WeightVersionMismatch otherwise).
            # Match the sidecar's chunk size to the plane's knob so the
            # source serves the dump-time index instead of re-hashing.
            cb = getattr(self.cfg, "weight_chunk_bytes", 8 << 20)
            # Quantized wire: the dump pass also publishes the int8
            # companion bin the plane serves at ~half the bytes per
            # version (weight_wire_dtype knob; servers dequantize).
            wire = getattr(self.cfg, "weight_wire_dtype", None)
            shm = shm_transfer_dir(
                self.cfg.experiment_name, self.cfg.trial_name, role
            )
            if multi_proc:
                # The tmpfs fast path is a SAME-HOST optimization; a
                # multi-host dump's slabs would land on N different
                # hosts' /dev/shm and no single origin could ever
                # assemble the stream. Every reader (origin included)
                # uses the shared disk dir instead.
                shm = None
            if sharded:
                # Shard-local dump: each process writes only its
                # addressable shard slabs — no whole-model host gather,
                # host high-water ~1/mesh_size of the full payload; the
                # weight-plane origin reassembles the identical byte
                # stream from the slabs (weight_transfer.py).
                raw = model.module.get_params()
                pi = jax.process_index() if multi_proc else 0
                pn = jax.process_count() if multi_proc else 1
                dump_s = dump_raw_params_sharded(
                    raw, d, version=model.version, chunk_bytes=cb,
                    process_index=pi, n_processes=pn, wire_dtype=wire,
                )
                if is_rank0:
                    # A pre-sharding run may have left engine_state.pkl
                    # in this dir; the dst realloc branch prefers it, so
                    # a stale pickle would silently shadow every fresh
                    # sharded dump after a mixed-mode restart.
                    try:
                        os.unlink(os.path.join(d, "engine_state.pkl"))
                    except OSError:
                        pass
                if shm is not None:
                    # Mirror the finished artifacts at the FILE level
                    # (page-cache reads) — a second dump call would
                    # re-materialize every shard off the device.
                    dump_s += mirror_dump_version(d, shm, model.version)
            else:
                # Raw mmap-able dumps for the generation servers: tmpfs
                # same-host fast path + disk fallback.
                params = jax.tree_util.tree_map(
                    lambda x: np.asarray(x), model.module.get_params()
                )
                dump_s = dump_raw_params(
                    params, d, version=model.version, chunk_bytes=cb,
                    wire_dtype=wire,
                )
                if shm is not None:
                    dump_s += dump_raw_params(
                        params, shm, version=model.version, chunk_bytes=cb,
                        wire_dtype=wire,
                    )
            hw = LAST_DUMP_STATS.get("high_water_bytes", 0)
            logger.info(
                f"param_realloc dump for {role} step {step}: "
                f"{'shard-local ' if sharded else ''}raw dump "
                f"v{model.version} {dump_s:.3f}s host-high-water "
                f"{hw / float(1 << 20):.1f}MiB "
                f"(shm={'yes' if shm is not None else 'no'})"
            )
            # Streaming weight-distribution plane: the dump rank exposes
            # this role's raw-bin dumps over chunked HTTP so the gserver
            # manager can fan the bytes out through a peer tree instead
            # of every generation server re-reading the checkpoint from
            # NFS. The source serves the tmpfs copy when one exists
            # (page-cache-hot either way); armed by the experiment's
            # gen_weight_plane knob or the AREAL_WEIGHT_PLANE env gate,
            # so legacy deployments keep zero extra listeners.
            if is_rank0 and (
                getattr(self.cfg, "weight_plane", False)
                or env_registry.get_bool("AREAL_WEIGHT_PLANE")
            ):
                self._ensure_weight_plane_source(role, shm or d)
            if is_rank0:
                # One stamp writer: non-zero slab ranks of a multi-host
                # mesh dumped above but must not publish step.txt (a
                # reader could race a stamp ahead of missing slabs; the
                # slab-completeness check in DumpStreamReader is the
                # backstop either way).
                tmp = os.path.join(d, "step.txt.tmp")
                with open(tmp, "w") as f:
                    f.write(str(step))
                os.replace(tmp, os.path.join(d, "step.txt"))
        if dst is not None and dst in self.models:
            model = self.models[dst]
            role = ModelName.parse(dst).role
            # The source role's dump is what we load from.
            src_role = ModelName.parse(src).role if src else role
            d = os.path.join(realloc_root, src_role)
            stamp = os.path.join(d, "step.txt")
            deadline = _time.monotonic() + 300
            while True:
                try:
                    with open(stamp) as f:
                        if int(f.read().strip() or -1) >= step:
                            break
                except (FileNotFoundError, ValueError):
                    pass
                if _time.monotonic() > deadline:
                    raise TimeoutError(
                        f"param_realloc: no fresh dump for {src_role} "
                        f"(step {step}) within 300s"
                    )
                _time.sleep(0.05)
            # Only params move; optimizer state stays local.
            import pickle

            pkl = os.path.join(d, "engine_state.pkl")
            if os.path.exists(pkl):
                with open(pkl, "rb") as f:
                    state = pickle.load(f)
                model.module.set_params(state["params"])
            else:
                # Sharded trainer source: no pickle was written (it
                # would host-gather the full model). Assemble the full
                # tree from the shard-local raw dump instead — with a
                # bounded retry: the step.txt stamp only proves rank 0
                # dumped, while peer hosts' slabs can still be landing
                # on shared storage (load_raw_params reads a
                # slab-incomplete dump as absent by design).
                from areal_tpu.system.weight_transfer import load_raw_params

                got = None
                fallback_deadline = _time.monotonic() + 60
                while got is None:
                    got = load_raw_params(d)
                    if got is not None:
                        break
                    if _time.monotonic() > fallback_deadline:
                        raise FileNotFoundError(
                            f"param_realloc: neither engine_state.pkl "
                            f"nor a complete raw dump in {d} within 60s"
                        )
                    _time.sleep(0.25)
                model.module.set_params(got[0])

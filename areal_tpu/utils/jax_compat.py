"""Tolerance shims for jax APIs that moved or were renamed across
releases.

The codebase targets current jax; driver/CI containers sometimes pin an
older release (observed: 0.4.37) where:

- `jax.shard_map` still lives at `jax.experimental.shard_map.shard_map`
  and takes `check_rep` instead of `check_vma`;
- `jax.experimental.pallas.tpu.CompilerParams` is still named
  `TPUCompilerParams` (same fields);
- `jax.sharding.set_mesh` does not exist; entering the `Mesh` as a
  context manager sets the ambient mesh, which is what the generate
  path's sharding constraints need.

Each shim prefers the current API and only falls back when it is
absent, so on an up-to-date jax these are pass-throughs.
"""

from __future__ import annotations

import jax


def shard_map(f, mesh=None, in_specs=None, out_specs=None,
              check_vma: bool = True, **kwargs):
    """`jax.shard_map` with fallback to the pre-promotion
    `jax.experimental.shard_map.shard_map` (where `check_vma` was called
    `check_rep`)."""
    sm = getattr(jax, "shard_map", None)
    if sm is not None:
        return sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                  check_vma=check_vma, **kwargs)
    from jax.experimental.shard_map import shard_map as _sm

    return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
               check_rep=check_vma, **kwargs)


def set_mesh(mesh):
    """Context manager: `jax.sharding.set_mesh` where available, else the
    Mesh's own context-manager entry (which installs it as the ambient
    mesh for sharding constraints on pre-set_mesh releases)."""
    sm = getattr(jax.sharding, "set_mesh", None)
    if sm is not None:
        return sm(mesh)
    return mesh


def tpu_compiler_params(**kwargs):
    """`pltpu.CompilerParams` across its `TPUCompilerParams` rename
    (identical fields)."""
    from jax.experimental.pallas import tpu as pltpu

    cls = getattr(pltpu, "CompilerParams", None) or pltpu.TPUCompilerParams
    return cls(**kwargs)

"""Chunked, interruptible generation client.

Counterpart of the reference's PartialRolloutManager
(realhf/system/partial_rollout.py:29-290): generation is issued in
chunks of at most `new_tokens_per_chunk` tokens so a weight update only
ever discards one chunk of work; unfinished (interrupted or chunk-
exhausted) requests are re-scheduled — possibly onto a different server
with newer weights — with the concatenated prefix, whose KV the server
recomputes under the new weights. Groups of n samples per prompt are
gathered into `BundledGenerationOutputs`.
"""

from __future__ import annotations

import asyncio
from typing import Dict, List, Optional

import aiohttp

from areal_tpu.api.model_api import (
    APIGenerateInput,
    APIGenerateOutput,
    BundledGenerationOutputs,
    GenerationHyperparameters,
)
from areal_tpu.base import logging, rpc, tracing

logger = logging.getLogger("partial_rollout")


class ServerFailure(RuntimeError):
    """A generation server failed a request (connection error or 5xx).

    Retryable: the accumulated prefix is resubmitted through the manager,
    which routes around the failed server after the client reports it."""

    def __init__(self, url: str, detail: str):
        super().__init__(f"generate failed on {url}: {detail}")
        self.url = url


class PartialRolloutManager:
    def __init__(
        self,
        manager_addr: str,
        new_tokens_per_chunk: int = 1 << 30,
        request_timeout: float = 300.0,
        max_retries: int = 8,
        retry_backoff_s: float = 0.05,
        addr_resolver=None,
        schedule_headers: Optional[Dict[str, str]] = None,
        headers_resolver=None,
    ):
        self.manager_addr = manager_addr
        self.new_tokens_per_chunk = max(1, new_tokens_per_chunk)
        self.request_timeout = request_timeout
        # Failover budget per sample: a dead server costs one retry (the
        # resubmission lands on a healthy one); the cap only aborts when
        # the fleet stays unroutable through the whole backoff ramp.
        self.max_retries = max_retries
        self.retry_backoff_s = retry_backoff_s
        # Declared retry disciplines (base/rpc.py): the per-sample
        # failure policy keeps this client's historical ctor knobs; the
        # manager-rediscovery policy is the FLEET-WIDE one shared with
        # rollout_worker, so a manager blip has exactly one declared
        # budget instead of two private ones.
        self.policy = rpc.RetryPolicy(
            attempts=max(1, max_retries),
            backoff_base_s=retry_backoff_s,
            backoff_max_s=2.0,
            attempt_timeout_s=request_timeout,
        )
        self.mgr_policy = rpc.rediscovery_policy(
            backoff_base_s=retry_backoff_s
        )
        # Optional () -> current manager address. A restarted gserver
        # manager re-registers at a NEW address; in-flight samples follow
        # it instead of dying with their accumulated tokens.
        self._addr_resolver = addr_resolver
        # Extra headers on the /schedule_request hop only (the
        # trainer-via-gateway internal token — system/gateway.py). The
        # optional resolver re-reads them alongside address rediscovery:
        # a restarted gateway mints a fresh token with its fresh URL.
        self._schedule_headers: Dict[str, str] = dict(
            schedule_headers or {})
        self._headers_resolver = headers_resolver
        self._session: Optional[aiohttp.ClientSession] = None
        # Session continuation state: member qid -> total tokens
        # (prompt + output) the fleet has already prefilled/generated
        # for that session. A continuation turn re-prefills only the
        # delta beyond this (the parked prefix KV covers the rest via
        # the manager's sticky-qid affinity), so multi-turn agents pay
        # per-turn deltas instead of whole-conversation re-prefills.
        self._session_prefix: Dict[str, int] = {}
        self._session_prefix_cap = 4096
        # Client-side prefill accounting (successful chunks only):
        # reprefill is what the fleet actually re-prefilled, full is the
        # session-blind counterfactual — the bench's re-prefill ratio is
        # their quotient. Plain ints mutated from this client's single
        # owning loop.
        self.reprefill_tokens_total = 0
        self.full_prefill_tokens_total = 0

    def _refresh_manager_addr(self):
        if self._headers_resolver is not None:
            try:
                headers = self._headers_resolver()
                if headers:
                    self._schedule_headers = dict(headers)
            except Exception:
                pass
        if self._addr_resolver is None:
            return
        try:
            addr = self._addr_resolver()
        except Exception:
            return
        if addr and addr != self.manager_addr:
            logger.warning(
                f"gserver manager moved {self.manager_addr} -> {addr}"
            )
            self.manager_addr = addr

    async def _sess(self) -> aiohttp.ClientSession:
        if self._session is None or self._session.closed:
            self._session = aiohttp.ClientSession(
                timeout=aiohttp.ClientTimeout(total=self.request_timeout)
            )
        return self._session

    async def close(self):
        if self._session and not self._session.closed:
            await self._session.close()

    def _backoff(self, attempt: int, sched: Optional[Dict] = None) -> float:
        """Declared-policy backoff (base/rpc.py): jittered exponential,
        a 503's retry_after hint floors the wait."""
        ra = float(sched.get("retry_after", 0.0)) if sched else None
        return self.policy.backoff(attempt, retry_after=ra)

    async def _schedule(self, meta: Dict) -> Dict:
        sess = await self._sess()
        headers = rpc.Deadline.after(self.request_timeout).headers()
        headers.update(self._schedule_headers)
        async with sess.post(
            f"{self.manager_addr}/schedule_request", json=meta,
            headers=headers,
        ) as r:
            return await r.json()

    async def _generate_one(
        self,
        qid: str,
        prompt_ids: List[int],
        gconfig: GenerationHyperparameters,
        continuation: bool = False,
    ) -> APIGenerateOutput:
        """Generate one sample, chunk by chunk, resubmitting with the
        accumulated prefix after interrupts (reference _run_gen:92,
        refresh_generation:181)."""
        with tracing.span(
            "gen.sample", qid=qid, prompt_len=len(prompt_ids),
            continuation=continuation,
        ):
            return await self._generate_one_impl(
                qid, prompt_ids, gconfig, continuation
            )

    async def _generate_one_impl(
        self,
        qid: str,
        prompt_ids: List[int],
        gconfig: GenerationHyperparameters,
        continuation: bool = False,
    ) -> APIGenerateOutput:
        acc_out: List[int] = []
        acc_lp: List[float] = []
        version_start = -1
        version_end = -1
        no_eos = True
        prev_url, prev_version = "", -1
        failed_url: Optional[str] = None
        retries = 0
        # 429 load-shedding is DELIBERATE backpressure, not a failure:
        # it gets its own (generous) budget, jittered backoff around the
        # server's Retry-After, and a shed hint to the manager (which
        # spills the session's affinity instead of evicting the server).
        shed_url: Optional[str] = None
        shed_ra_hint = 0.0
        n_shed = 0
        consec_shed = 0
        shed_budget = max(32, self.max_retries * 8)
        # Manager-unreachable is a CONTROL-PLANE condition with its own
        # (generous) budget: a manager restart/failover costs seconds
        # and every sample sees it at once — burning the per-sample
        # server-failure budget on it turned one manager blip into
        # fleet-wide aborted rollouts (and, through the failure
        # reports, spurious eviction pressure). Rediscovery runs
        # against the name_resolve key on every attempt, with jittered
        # backoff so thousands of workers don't hammer the successor
        # the instant it registers.
        mgr_fails = 0
        consec_mgr_fails = 0
        mgr_budget = self.mgr_policy.attempts
        # Interruption-cost accounting: any submission carrying an
        # already-accumulated prefix makes the server (re-)prefill
        # prompt+prefix under (possibly new) weights; prefix caching may
        # discount it server-side, so this is the upper bound the
        # re-prefill report quantifies.
        reprefill_tokens = 0
        n_interruptions = 0
        # Continuation turns: the session key already generated earlier
        # turns on the fleet, so only the tokens BEYOND the known prefix
        # (the previous turn's feedback / tool output) are re-prefill
        # work — the sticky-qid route lands on the server whose prefix
        # cache holds the rest. A session this client never saw gets the
        # conservative full-prompt accounting.
        known_len = (
            self._session_prefix.get(qid, 0) if continuation else 0
        )
        budget = gconfig.max_new_tokens
        sess = await self._sess()
        while budget > 0:
            try:
                sched = await self._schedule(
                    tracing.inject_into(
                        dict(
                            # Session key for the manager's prefix-
                            # affinity routing (the server's parked KV is
                            # keyed by this same qid).
                            qid=qid,
                            prompt_len=len(prompt_ids) + len(acc_out),
                            group_size=1,
                            new_token_budget=budget,
                            previous_server_url=prev_url,
                            previous_version=prev_version,
                            # Report the server the previous attempt died
                            # on, so the manager evicts it before routing
                            # this retry.
                            failed_server_url=failed_url,
                            # A server that shed us with 429: routed
                            # around for its Retry-After window, NOT
                            # evicted.
                            shed_server_url=shed_url,
                            shed_retry_after=shed_ra_hint,
                        )
                    )
                )
            except (aiohttp.ClientError, asyncio.TimeoutError) as e:
                # The manager itself blipped (or was restarted at a new
                # address): accumulated tokens must survive a control-
                # plane failure too. Retryable REDISCOVERY, never part
                # of the server-failure budget.
                mgr_fails += 1
                consec_mgr_fails += 1
                if mgr_fails > mgr_budget:
                    raise RuntimeError(
                        f"{qid}: gserver manager unreachable after "
                        f"{mgr_fails} attempts (last: {e!r})"
                    ) from e
                logger.warning(
                    f"{qid}: schedule_request failed ({e!r}); "
                    f"rediscovering manager "
                    f"({mgr_fails}/{mgr_budget})"
                )
                self._refresh_manager_addr()
                await asyncio.sleep(
                    self.mgr_policy.backoff(consec_mgr_fails)
                )
                continue
            consec_mgr_fails = 0
            failed_url = None
            shed_url, shed_ra_hint = None, 0.0
            if "url" not in sched:
                # 503: no healthy servers right now. Back off and retry —
                # the watchdog restarting a server or the health registry
                # readmitting one unblocks us.
                retries += 1
                if retries > self.max_retries:
                    raise RuntimeError(
                        f"{qid}: no healthy generation servers after "
                        f"{self.max_retries} retries: {sched}"
                    )
                await asyncio.sleep(self._backoff(retries, sched))
                continue
            url, server_version = sched["url"], int(sched.get("version", -1))
            # Disaggregated pairing: the manager chose a decode server
            # for this chunk; the prefill server hands the KV off to it
            # and proxies the combined result back (docs/serving.md).
            decode_url = sched.get("decode_url")
            # Tiered-KV hint: a DIFFERENT server holds this session's
            # prefix — the routed server pulls it over the /kv plane
            # before admission instead of re-prefilling.
            kv_source = sched.get("kv_source")
            chunk = min(budget, self.new_tokens_per_chunk)
            # A resubmission carries the accumulated prefix: every token
            # of prompt+prefix is prefill work the server repeats. A
            # continuation's FIRST submission repeats only the turn
            # delta past the fleet-known session prefix.
            full_prefill = len(prompt_ids) + len(acc_out)
            if acc_out:
                chunk_reprefill = full_prefill
            elif continuation:
                chunk_reprefill = max(0, len(prompt_ids) - known_len)
            else:
                chunk_reprefill = 0
            # Manual span: reprefill_tokens is stamped only on the
            # SUCCESSFUL attempt, so the trace-derived re-prefill total
            # matches the client accounting below even when failed
            # attempts are retried. Created before the payload so the
            # server's span parents under THIS chunk (per-chunk server
            # attribution in the merged timeline), not the whole sample.
            chunk_span = tracing.start_span("gen.chunk", qid=qid, server=url)
            payload = tracing.inject_ctx_into(
                dict(
                    qid=qid,
                    decode_url=decode_url,
                    kv_source=kv_source,
                    input_ids=list(prompt_ids) + acc_out,
                    # Continuations/re-prefills admit ahead of fresh
                    # requests (engine priority class 0): their prefix
                    # pages are already paid for. Session continuations
                    # (multi-turn episodes) ride the same class — an
                    # in-flight episode beats a fresh prompt.
                    priority=0 if (acc_out or continuation) else 1,
                    gconfig=dict(
                        max_new_tokens=chunk,
                        min_new_tokens=max(
                            0, gconfig.min_new_tokens - len(acc_out)
                        ),
                        greedy=gconfig.greedy,
                        temperature=gconfig.temperature,
                        top_p=gconfig.top_p,
                        top_k=gconfig.top_k,
                        stop_token_ids=list(gconfig.stop_token_ids),
                    ),
                ),
                chunk_span.ctx if chunk_span is not None else None,
            )
            shed_ra: Optional[float] = None
            try:
                # Outermost deadline mint (base/rpc.py): the server and
                # every hop it makes on our behalf (decode pairing, KV
                # pulls) inherit this chunk's remaining budget.
                chunk_dl = rpc.Deadline.after(self.request_timeout)
                async with sess.post(
                    f"{url}/generate", json=payload,
                    headers=chunk_dl.headers(),
                ) as r:
                    if r.status == 429:
                        # Deliberate load-shedding, not a failure: honor
                        # Retry-After, tell the manager (shed hint, for
                        # spill routing), and keep the retry out of the
                        # failure budget.
                        try:
                            body = await r.json()
                        except Exception:
                            body = {}
                        shed_ra = float(
                            body.get("retry_after")
                            or r.headers.get("Retry-After")
                            or 1.0
                        )
                        if chunk_span is not None:
                            chunk_span.end(shed=True)
                    elif r.status != 200:
                        raise ServerFailure(
                            url, f"{r.status} {await r.text()}"
                        )
                    else:
                        out = await r.json()
                        # Success end INSIDE the try: the finally's
                        # failed=True end is then a no-op (ManualSpan.end
                        # is idempotent).
                        if chunk_span is not None:
                            chunk_span.end(
                                reprefill_tokens=chunk_reprefill,
                                # The counterfactual: what a session-
                                # blind client would have re-prefilled.
                                # The trace e2e asserts continuation
                                # deltas stay strictly below it.
                                full_prefill_tokens=full_prefill,
                                continuation=continuation,
                                n_tokens=len(out.get("output_ids") or []),
                            )
            except (
                ServerFailure, aiohttp.ClientError, asyncio.TimeoutError,
            ) as e:
                # Server died mid-request. Work already accumulated in
                # acc_out is NOT lost: the retry resubmits the full
                # prefix to whichever healthy server the manager picks
                # (same path as a weight-update interrupt).
                retries += 1
                if retries > self.max_retries:
                    raise
                failed_url = url
                prev_url, prev_version = "", -1  # sticky hint is dead
                logger.warning(
                    f"{qid}: generate attempt failed on {url} ({e!r}); "
                    f"retry {retries}/{self.max_retries}"
                )
                await asyncio.sleep(self._backoff(retries))
                continue
            finally:
                # Covers BaseException paths too (task cancellation mid
                # POST): the server may already have recorded a child
                # span under this id, so leaving it unrecorded would be
                # a zero-drop dangling parent — fatal to the validator.
                if chunk_span is not None:
                    chunk_span.end(failed=True)
            if shed_ra is not None:
                n_shed += 1
                consec_shed += 1
                if n_shed > shed_budget:
                    raise RuntimeError(
                        f"{qid}: load-shed {n_shed} times without "
                        f"progress (last Retry-After {shed_ra:.2f}s from "
                        f"{url}); fleet persistently overloaded"
                    )
                shed_url, shed_ra_hint = url, shed_ra
                tracing.event(
                    "gen.shed", qid=qid, server=url, retry_after=shed_ra
                )
                # Jittered backoff around the server's hint (plus a mild
                # exponential ramp on consecutive sheds): synchronized
                # retries from many workers would re-create the very
                # burst that tripped the watermark (rpc.shed_backoff is
                # the one declared client-shed discipline).
                await asyncio.sleep(rpc.shed_backoff(consec_shed, shed_ra))
                continue
            consec_shed = 0
            if version_start < 0:
                version_start = int(out.get("version_start", server_version))
            version_end = int(out.get("version_end", server_version))
            reprefill_tokens += chunk_reprefill
            self.reprefill_tokens_total += chunk_reprefill
            self.full_prefill_tokens_total += full_prefill
            if out.get("interrupted", False):
                n_interruptions += 1
                tracing.event(
                    "gen.interrupted", qid=qid, server=url,
                    acc_len=len(prompt_ids) + len(acc_out),
                )
            made_progress = len(out["output_ids"]) > 0
            acc_out.extend(int(t) for t in out["output_ids"])
            acc_lp.extend(float(x) for x in out["output_logprobs"])
            budget = gconfig.max_new_tokens - len(acc_out)
            prev_url, prev_version = url, version_end
            if not out.get("no_eos", True):
                no_eos = False
                break
            if not made_progress and not out.get("interrupted", False):
                # The server cannot extend this sequence (e.g. the prefix
                # hit its cache limit): stop instead of resubmitting the
                # identical request forever.
                logger.warning(
                    f"{qid}: server returned no progress at len "
                    f"{len(prompt_ids) + len(acc_out)}; truncating"
                )
                break
            # no_eos: either interrupted (resubmit under new weights) or the
            # chunk budget ran out (continue with the next chunk).
            if budget <= 0:
                break
        # The fleet now holds prompt+output KV for this session key; a
        # continuation turn built on top pays only its delta. Bounded:
        # evict oldest entries past the cap (insertion-ordered dict).
        self._session_prefix.pop(qid, None)
        self._session_prefix[qid] = len(prompt_ids) + len(acc_out)
        while len(self._session_prefix) > self._session_prefix_cap:
            self._session_prefix.pop(
                next(iter(self._session_prefix))
            )
        return APIGenerateOutput(
            qid=qid,
            prompt_ids=list(prompt_ids),
            input_ids=list(prompt_ids),
            output_ids=acc_out,
            output_logprobs=acc_lp,
            no_eos=no_eos,
            version_start=version_start,
            version_end=version_end,
            reprefill_tokens=reprefill_tokens,
            n_interruptions=n_interruptions,
        )

    async def generate_group(
        self,
        qid: str,
        prompt_ids: List[int],
        gconfig: GenerationHyperparameters,
        continuation: bool = False,
    ) -> BundledGenerationOutputs:
        """n samples for one prompt, concurrently. ``continuation=True``
        marks a follow-up turn of a session this manager generated
        earlier (same qid): members keep their qid-stable session keys
        (``{qid}/{i}``), admit at priority 0, and account only the turn
        delta as re-prefill."""
        outs = await asyncio.gather(
            *[
                self._generate_one(
                    f"{qid}/{i}", prompt_ids, gconfig, continuation
                )
                for i in range(gconfig.n)
            ]
        )
        for o in outs:
            o.qid = qid  # group members share the prompt's qid
        return BundledGenerationOutputs.from_api_outputs(list(outs))

"""Disaggregated serving in-process: prefill/decode pairing + KV
handoff through real GenerationServer workers behind a real
GserverManager, and the elastic re-role state machine (ISSUE 7).

Covered:
- the manager pairs a prefill and a decode server for a fresh request
  (policy=disagg, decode_url in the schedule response), the prefill
  server hands the KV off over HTTP (hash-verified chunk pull), and the
  client receives the combined stream — identical tokens to a unified
  greedy run;
- the session's affinity lands on the DECODE server (where its KV
  parked), so the follow-up chunk routes there directly;
- `manager.pair` / `server.kv_export` / `server.kv_import` spans land
  in the PR 3 trace;
- elastic sizing: watermark pressure flips a unified server
  prefill-ward and back, visible in /status pools.reroles, with zero
  failed rollouts.

Time budget: ~35 s (two in-process CPU servers, shared tiny-model
compiled programs with the affinity suite).
"""

import asyncio
import json
import threading
import time
import urllib.request
import uuid

import pytest

from areal_tpu.api.config import ModelAbstraction
from areal_tpu.api.model_api import GenerationHyperparameters
from areal_tpu.api.system_api import (
    GenerationServerConfig,
    GserverManagerConfig,
)
from tests import fixtures

pytestmark = pytest.mark.serial

MODEL_CFG = dict(
    n_layers=2, hidden_dim=64, n_q_heads=4, n_kv_heads=2, head_dim=16,
    intermediate_dim=128, vocab_size=256, max_position_embeddings=512,
    compute_dtype="float32",
)
PROMPT = list(range(20, 40))  # 20 tokens >= one 16-token page


def _get_json(url, timeout=30):
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read())


def _metrics(url):
    text = urllib.request.urlopen(url + "/metrics", timeout=30).read().decode()
    out = {}
    for line in text.splitlines():
        parts = line.split()
        if len(parts) == 2:
            try:
                out[parts[0]] = float(parts[1])
            except ValueError:
                out[parts[0]] = parts[1]
    return out


def _wait_until(cond, timeout, msg):
    deadline = time.monotonic() + fixtures.scale_timeout(timeout)
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(0.05)
    raise AssertionError(f"timed out waiting for {msg}")


def _mk_server(exp, trial, idx, role, **extra):
    from areal_tpu.system.generation_server import GenerationServer

    kw = dict(
        experiment_name=exp, trial_name=trial, server_index=idx,
        model=ModelAbstraction(
            "tpu_transformer", args=dict(config=dict(MODEL_CFG))
        ),
        max_concurrent_requests=4, max_seq_len=256,
        kv_page_size=16, decode_block_steps=4, prompt_bucket=16,
        prefix_cache_tokens=2048, role=role, seed=idx,
    )
    kw.update(extra)
    cfg = GenerationServerConfig(**kw)
    w = GenerationServer()
    w.configure(cfg, experiment_name=exp, trial_name=trial,
                worker_name=cfg.worker_name)
    return w


def _mk_manager(exp, trial, n, **extra):
    from areal_tpu.system.gserver_manager import GserverManager

    mgr = GserverManager()
    mgr.configure(
        GserverManagerConfig(
            experiment_name=exp, trial_name=trial, model_name="actor",
            n_servers=n, schedule_policy="least_requests",
            train_batch_size=4, max_head_offpolicyness=1000,
            health_check_interval=0.5, **extra,
        ),
        experiment_name=exp, trial_name=trial,
        worker_name="gserver_manager",
    )
    t = threading.Thread(target=mgr.run, daemon=True)
    t.start()
    return mgr, t


@pytest.mark.timeout(600)
def test_disagg_pairing_handoff_and_trace(tmp_path, monkeypatch):
    from areal_tpu.base import name_resolve, names, tracing
    from areal_tpu.system.partial_rollout import PartialRolloutManager
    from areal_tpu.utils import rl_trace

    exp, trial = f"disagg-{uuid.uuid4().hex[:6]}", "t0"
    trace_dir = str(tmp_path / "rl_trace")
    monkeypatch.setenv("AREAL_HEALTH_TTL", "120")
    monkeypatch.setenv("AREAL_RL_TRACE", "1")
    monkeypatch.setenv("AREAL_RL_TRACE_DIR", trace_dir)
    tracing.reconfigure()
    name_resolve.reconfigure("nfs", record_root=str(tmp_path / "nr"))

    servers, mgr, mgr_thread, prm = [], None, None, None
    loop = asyncio.new_event_loop()
    try:
        servers.append(_mk_server(exp, trial, 0, "prefill"))
        servers.append(_mk_server(exp, trial, 1, "decode"))
        by_role = {w.role: w for w in servers}
        mgr, mgr_thread = _mk_manager(exp, trial, 2)
        _wait_until(lambda: len(mgr._healthy_urls()) == 2, 60,
                    "manager sees both servers")
        # Roles flow in via /metrics polling (no heartbeats in-process).
        _wait_until(
            lambda: set(mgr._server_roles.values()) == {"prefill", "decode"},
            30, "manager learned the pool roles",
        )

        prm = PartialRolloutManager(
            mgr.address, request_timeout=fixtures.scale_timeout(120)
        )
        g = GenerationHyperparameters(max_new_tokens=8, greedy=True)
        out = loop.run_until_complete(prm._generate_one("d/0", PROMPT, g))
        assert len(out.output_ids) == 8

        pre, dec = by_role["prefill"], by_role["decode"]
        # The KV crossed the wire: export on the prefill engine, a
        # hash-verified import + priority-0 continuation on the decode
        # engine (delta prefill via its parked prefix).
        assert pre.engine.kv_exports == 1
        assert dec.engine.kv_imports == 1
        assert dec.engine.prefix_cache_hits == 1
        assert dec.engine.prefix_tokens_reused == len(PROMPT)
        assert pre._handoff_ok == 1 and pre._handoff_failed == 0
        m_pre, m_dec = _metrics(pre.address), _metrics(dec.address)
        assert m_pre["areal:role"] == "prefill"
        assert m_pre["areal:kv_export_total"] == 1.0
        assert m_pre["areal:kv_export_bytes"] > 0
        assert m_dec["areal:kv_import_total"] == 1.0
        assert m_dec["areal:last_kv_transfer_ms"] >= 0.0

        # Affinity re-homed onto the decode server; the follow-up chunk
        # routes there directly (no second handoff).
        assert mgr._affinity.get("d/0") == dec.address
        follow = loop.run_until_complete(prm._generate_one(
            "d/0", PROMPT + out.output_ids,
            GenerationHyperparameters(max_new_tokens=4, greedy=True),
        ))
        assert len(follow.output_ids) == 4
        assert pre.engine.kv_exports == 1  # no new handoff
        assert dec.engine.prefix_cache_hits >= 2

        # Greedy parity: the handed-off stream must match a direct
        # single-engine run of the same prompt token for token.
        from areal_tpu.engine.serving import GenRequest

        got = {}
        done = threading.Event()

        def cb(res):
            got["res"] = res
            done.set()

        dec.engine.submit(GenRequest(
            qid="ref", input_ids=list(PROMPT), max_new_tokens=8,
            greedy=True, done_cb=cb,
        ))
        assert done.wait(fixtures.scale_timeout(60))
        assert out.output_ids == got["res"].output_ids
        # A second fresh session pairs (and hands off) again.
        uni = loop.run_until_complete(
            prm._generate_one("u/0", list(PROMPT), g)
        )
        assert uni.output_ids == got["res"].output_ids
        assert pre.engine.kv_exports == 2
        assert dec.engine.kv_imports == 2

        # Manager /status: pools surface with roles, pool membership,
        # and the fleet handoff totals (after a metrics poll cycle).
        _wait_until(
            lambda: _get_json(mgr.address + "/status")["pools"][
                "kv_handoff"]["imports"] >= 1,
            30, "kv handoff totals on /status",
        )
        st = _get_json(mgr.address + "/status")
        assert st["pools"]["roles"][pre.address] == "prefill"
        assert st["pools"]["roles"][dec.address] == "decode"
        assert st["pools"]["prefill"] == [pre.address]
        assert st["pools"]["decode"] == [dec.address]
        assert st["pools"]["kv_handoff"]["export_bytes"] > 0

        # PR 3 trace: pairing + export/import spans, linked.
        tracing.flush()
        shards = rl_trace.load_shards(trace_dir)
        spans = [sp for s in shards for sp in s.spans]
        names_seen = {sp["name"] for sp in spans}
        assert {"manager.pair", "server.kv_export",
                "server.kv_import"} <= names_seen, names_seen
        pair = next(sp for sp in spans if sp["name"] == "manager.pair")
        assert pair["attrs"]["prefill"] == pre.address
        assert pair["attrs"]["decode"] == dec.address
    finally:
        try:
            name_resolve.add(
                names.experiment_status(exp, trial), "COMPLETE",
                replace=True,
            )
        except Exception:
            pass
        if mgr_thread is not None:
            mgr_thread.join(timeout=15)
        for w in servers:
            w._exit_hook()
        if prm is not None:
            loop.run_until_complete(prm.close())
        loop.run_until_complete(asyncio.sleep(0))
        loop.close()
        tracing.reconfigure()


@pytest.mark.timeout(600)
def test_elastic_rerole_flips_and_returns_under_watermark_pressure(
    tmp_path, monkeypatch
):
    """A unified server flips prefill-ward when the prefill queue
    crosses the high watermark, then flips back once it drains — zero
    failed rollouts, both transitions in /status pools.reroles."""
    from areal_tpu.base import name_resolve, names
    from areal_tpu.engine.serving import GenRequest
    from areal_tpu.system.partial_rollout import PartialRolloutManager

    exp, trial = f"rerole-{uuid.uuid4().hex[:6]}", "t0"
    monkeypatch.setenv("AREAL_HEALTH_TTL", "120")
    name_resolve.reconfigure("nfs", record_root=str(tmp_path / "nr"))

    servers, mgr, mgr_thread, prm = [], None, None, None
    loop = asyncio.new_event_loop()
    try:
        # Both unified (elastic); one will be pulled prefill-ward. A
        # deep max_seq_len lets the blocker requests below hold their
        # slots for the whole pressure phase.
        servers.append(_mk_server(exp, trial, 0, "unified",
                                  max_seq_len=2048))
        servers.append(_mk_server(exp, trial, 1, "unified",
                                  max_seq_len=2048))
        mgr, mgr_thread = _mk_manager(
            exp, trial, 2,
            elastic_pools=True,
            rerole_cooldown_s=0.0,
            prefill_queue_high_tokens=100,
            prefill_queue_low_tokens=10,
            # Isolate the queue-watermark path: parked prefix-cache
            # pages read as used, so the free-page floor would also
            # fire here and interleave decode-ward flips.
            decode_free_page_min_frac=0.0,
            pool_min_decode=1, pool_min_prefill=0,
        )
        _wait_until(lambda: len(mgr._healthy_urls()) == 2, 60,
                    "manager sees both servers")
        _wait_until(
            lambda: len(mgr._server_elastic) == 2, 30,
            "manager learned elastic eligibility",
        )

        # Watermark pressure, SUSTAINED: four blocker requests occupy
        # every slot for ~2000 decode tokens, so the 10 queued prompts
        # behind them (400 tokens >= the 100-token watermark) cannot
        # admit until we deliberately interrupt — a fast engine
        # draining the queue between two manager metrics polls
        # (measured: 600 tokens gone in <10 s) must not be able to
        # hide the pressure from the sizer.
        victim = servers[0]
        for i in range(4):
            victim.engine.submit(GenRequest(
                qid=f"blk{i}", input_ids=[5, 6, 7],
                max_new_tokens=2000, greedy=True, done_cb=lambda r: None,
            ))
        for i in range(10):
            victim.engine.submit(GenRequest(
                qid=f"p{i}", input_ids=list(range(1, 41)),
                max_new_tokens=60, greedy=True, done_cb=lambda r: None,
            ))
        _wait_until(
            lambda: victim.engine.queued_prompt_tokens >= 100, 30,
            "queued-token watermark pressure",
        )
        # The signal must actually REACH the sizer (manager-side view).
        _wait_until(
            lambda: mgr._server_queued_toks.get(victim.address, 0) >= 100,
            60, "manager observed the queue pressure",
        )
        # The sizer flips the most page-free elastic decode-side server
        # prefill-ward (cheapest to take from the decode pool) — not
        # necessarily the pressured one.
        _wait_until(
            lambda: "prefill" in mgr._server_roles.values(), 90,
            "elastic flip to prefill",
        )
        flipped = next(
            w for w in servers
            if mgr._server_roles.get(w.address) == "prefill"
        )
        _wait_until(lambda: flipped.role == "prefill", 10,
                    "server-side role flip")
        # The decode pool floor holds: no second flip drains it.
        assert sum(
            1 for r in mgr._server_roles.values() if r != "prefill"
        ) >= 1

        # Release the pressure: interrupt the blockers (the weight-swap
        # path — partial results return, the queued prompts admit and
        # drain), then the sizer returns the server to its original
        # pool.
        victim.engine.update_params(
            victim.engine.params, allow_interrupt=True
        )

        # Traffic through the re-roled fleet still completes (drain +
        # flip loses nothing). After the release, so a decode pairing
        # onto the (formerly fully-blocked) victim can't stall behind
        # the blockers' whole token budget.
        prm = PartialRolloutManager(
            mgr.address, request_timeout=fixtures.scale_timeout(120)
        )
        out = loop.run_until_complete(prm._generate_one(
            "live/0", PROMPT,
            GenerationHyperparameters(max_new_tokens=6, greedy=True),
        ))
        assert len(out.output_ids) == 6
        _wait_until(
            lambda: sum(
                w.engine.queued_prompt_tokens for w in servers
            ) <= 10, 240,
            "pressure drained",
        )
        _wait_until(
            lambda: mgr._server_roles.get(flipped.address) == "unified", 120,
            "elastic flip back",
        )
        _wait_until(lambda: flipped.role == "unified", 20,
                    "server-side flip back")

        st = _get_json(mgr.address + "/status")
        transitions = [(e["from"], e["to"]) for e in st["pools"]["reroles"]]
        assert ("unified", "prefill") in transitions, transitions
        assert ("prefill", "unified") in transitions, transitions
        assert all(
            e["url"] == flipped.address for e in st["pools"]["reroles"]
        )
    finally:
        try:
            name_resolve.add(
                names.experiment_status(exp, trial), "COMPLETE",
                replace=True,
            )
        except Exception:
            pass
        if mgr_thread is not None:
            mgr_thread.join(timeout=15)
        for w in servers:
            w._exit_hook()
        if prm is not None:
            loop.run_until_complete(prm.close())
        loop.run_until_complete(asyncio.sleep(0))
        loop.close()

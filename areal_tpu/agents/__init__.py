from areal_tpu.agents import math_single_step  # noqa: F401  (registers)
from areal_tpu.agents import envs  # noqa: F401
from areal_tpu.agents import math_multi_turn  # noqa: F401
from areal_tpu.agents import null  # noqa: F401
from areal_tpu.agents import tool_use  # noqa: F401

"""Offline code evaluation harness.

Counterpart of the reference's evaluation/code_eval.py (548 LoC around a
vLLM generate + code_verifier.local_verify pipeline): load a saved
checkpoint, generate solutions over a benchmark jsonl of coding problems,
extract the final code block, run it against the per-problem test cases in
the sandboxed verifier (areal_tpu/functioncall/code_verify.py), and write
results.json with pass@1-style accuracy.

jsonl rows: {"prompt", "query_id", "input_output": {"inputs", "outputs",
"fn_name"?}} — the math_code_prompt dataset's code-task schema.

Usage:
    python evaluation/code_eval.py ckpt=/save/actor/step10/dp0 \
        data=/data/lcb.jsonl output=/tmp/results.json max_new_tokens=1024
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Eval jobs are schedulable onto CPU workers: honor JAX_PLATFORMS before
# any device use (utils/jaxenv.py explains the early-import dance).
from areal_tpu.utils.jaxenv import apply_jax_platform_override

apply_jax_platform_override()


def evaluate_checkpoint(
    ckpt: str,
    data: str,
    output: str = "",
    max_new_tokens: int = 1024,
    greedy: bool = True,
    temperature: float = 1.0,
    n_samples: int = 1,
    max_prompts: int = 0,
    case_timeout: float = 6.0,
    max_cases: int = 0,
    seed: int = 1,
) -> dict:
    import jax

    from areal_tpu.api import data_api
    from areal_tpu.api.model_api import GenerationHyperparameters
    from areal_tpu.functioncall.code_verify import code_verify
    from areal_tpu.models.generation import generate_tokens
    from areal_tpu.models.hf import load_hf_model

    cfg, params = load_hf_model(ckpt)
    tokenizer = data_api.load_hf_tokenizer(ckpt)

    with open(data) as f:
        rows = [json.loads(l) for l in f if l.strip()]
    if max_prompts:
        rows = rows[:max_prompts]

    g = GenerationHyperparameters(
        max_new_tokens=max_new_tokens, greedy=greedy, temperature=temperature
    )
    prompts = [tokenizer(r["prompt"])["input_ids"] for r in rows]

    n_correct, per_prompt = 0, []
    batch = 8
    for s in range(n_samples):
        rng = jax.random.PRNGKey(seed + s)
        for i in range(0, len(prompts), batch):
            chunk = prompts[i : i + batch]
            outs = generate_tokens(
                params, cfg, chunk, g, jax.random.fold_in(rng, i),
                eos_token_id=tokenizer.eos_token_id,
            )
            for j, o in enumerate(outs):
                row = rows[i + j]
                text = tokenizer.decode(o["output_ids"])
                io = row["input_output"]
                if isinstance(io, str):
                    io = json.loads(io)
                ok = code_verify(
                    text, io, timeout=case_timeout,
                    max_cases=max_cases or None,
                )
                n_correct += bool(ok)
                per_prompt.append(
                    {"query_id": str(row.get("query_id", i + j)), "correct": bool(ok)}
                )

    total = len(prompts) * n_samples
    result = {
        "ckpt": ckpt,
        "data": data,
        "task": "code",
        "n_prompts": len(prompts),
        "n_samples": n_samples,
        "accuracy": n_correct / max(1, total),
        "details": per_prompt,
    }
    if output:
        os.makedirs(os.path.dirname(output) or ".", exist_ok=True)
        with open(output, "w") as f:
            json.dump(result, f)
    print(json.dumps({k: v for k, v in result.items() if k != "details"}))
    return result


if __name__ == "__main__":
    kwargs = {}
    for arg in sys.argv[1:]:
        k, v = arg.split("=", 1)
        if k in ("max_new_tokens", "n_samples", "max_prompts", "max_cases", "seed"):
            v = int(v)
        elif k in ("greedy",):
            v = v.lower() in ("1", "true")
        elif k in ("temperature", "case_timeout"):
            v = float(v)
        kwargs[k] = v
    evaluate_checkpoint(**kwargs)

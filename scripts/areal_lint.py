#!/usr/bin/env python3
"""Repo-specific static analysis gate — see areal_tpu/lint/ and
docs/static_analysis.md.

    python scripts/areal_lint.py areal_tpu/
    python scripts/areal_lint.py --emit-env-docs docs/env_vars.md

Kept jax-free on purpose: the tier-1 gate runs this in a subprocess
and asserts jax never loads, so the check costs AST time, not XLA
time."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from areal_tpu.lint.cli import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main())

"""Networking helpers: hostname/IP discovery and lock-protected free-port finding.

Counterpart of the reference's network utilities (realhf/base/network.py).
The lockfile protocol prevents two workers racing to bind the same port on
one host between `find_free_port` and the actual bind.
"""

from __future__ import annotations

import fcntl
import os
import socket
from contextlib import closing
from typing import List

_PORT_LOCK_DIR = "/tmp/areal_tpu/ports"


def gethostname() -> str:
    return socket.gethostname()


def gethostip() -> str:
    try:
        with closing(socket.socket(socket.AF_INET, socket.SOCK_DGRAM)) as s:
            s.connect(("8.8.8.8", 80))
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"


def find_free_port(low: int = 10000, high: int = 60000, exp_name: str = "port") -> int:
    """Find a free TCP port and hold a lockfile so peers skip it."""
    os.makedirs(_PORT_LOCK_DIR, exist_ok=True)
    while True:
        with closing(socket.socket(socket.AF_INET, socket.SOCK_STREAM)) as s:
            s.bind(("", 0))
            port = s.getsockname()[1]
        if not (low <= port <= high):
            continue
        lock_path = os.path.join(_PORT_LOCK_DIR, f"{port}.lock")
        fd = os.open(lock_path, os.O_CREAT | os.O_RDWR)
        try:
            fcntl.flock(fd, fcntl.LOCK_EX | fcntl.LOCK_NB)
            return port
        except OSError:
            os.close(fd)
            continue


def find_multiple_free_ports(count: int, **kwargs) -> List[int]:
    return [find_free_port(**kwargs) for _ in range(count)]

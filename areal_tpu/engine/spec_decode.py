"""N-gram (prompt-lookup) speculative decoding for the paged engine.

Decode at small batch is weight-streaming-bound: every step reads the
full parameter set from HBM to emit ONE token per slot. Speculative
decoding amortizes that read across several tokens — draft k candidate
continuations, feed them all in one multi-row step (extra rows are
nearly free while weights dominate the bytes), and keep the verified
prefix. The reference's serving stack has no speculative decoding
(realhf/impl/model/backend/sglang.py) — this is a TPU-side extension,
opt-in via ServingEngine(speculative_draft_len=...).

Drafts come from prompt-lookup (n-gram matching): the last `g` tokens
of a slot's history are matched against earlier history; the tokens
that followed the most recent earlier occurrence become the draft.
Math-RL generations repeat prompt fragments, numbers, and derivation
spans constantly, so acceptance is high exactly where the async design
needs throughput. Everything is device-resident (history buffer,
matching, verification) — no host round trips inside the block, which
matters doubly on a remote-tunneled TPU.

Verification is lossless:
- greedy rows accept a draft token iff it IS the argmax — the emitted
  stream is bit-identical to plain greedy decode;
- sampled rows use standard speculative sampling with a point-mass
  draft distribution: accept draft t with prob p(t); on rejection,
  resample from p with t removed and renormalized. The emitted stream
  is distributed EXACTLY as plain sampling (Leviathan et al.'s
  correctness argument with q = delta_t).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from areal_tpu.engine.paged import (
    NEG_INF,
    paged_decode_step,
    warp_logits,
)
from areal_tpu.models.config import TransformerConfig


def propose_ngram_drafts(
    history: jnp.ndarray,  # [B, S+1] int32; col S is a scratch column
    lengths: jnp.ndarray,  # [B] int32: position of the PENDING token
    ngram: int,
    draft_len: int,
    window: int = 0,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Match the n-gram ending at the pending token against earlier
    history; return (draft [B, draft_len] int32, eff [B] int32 — number
    of proposed tokens, 0 when no match / not enough history).

    history[b, 0..lengths[b]] are known tokens (prompt + emitted, the
    last one pending, its KV not yet written). The draft is the
    continuation after the MOST RECENT earlier occurrence of the
    window; continuation tokens must themselves be known history.

    `window > 0` bounds the backward search to each slot's last `window`
    candidate match positions instead of the full max_seq_len: the
    [B, S, g] sliding-window compare is the one spec-decode term that
    scales with the CONFIGURED S rather than the live lengths, so at
    16-32k contexts an unbounded scan dominates draft cost. A bounded
    window only ever drops matches older than `window` tokens — the
    most-recent-match-within-window semantics are otherwise identical
    (verification is unchanged, so the output is still lossless)."""
    B, S1 = history.shape
    S = S1 - 1
    g, d = ngram, draft_len
    last_idx = jnp.clip(
        lengths[:, None] - (g - 1) + jnp.arange(g)[None, :], 0, S - 1
    )
    lastgram = jnp.take_along_axis(history, last_idx, axis=1)  # [B, g]
    if window and window < S:
        # Candidate match starts: the last W positions whose n-gram can
        # end strictly before the pending token (latest legal start is
        # lengths - g). Per-slot absolute positions, gathered instead of
        # scanned, so the compare is [B, W, g] independent of S.
        W = int(window)
        base = jnp.maximum(lengths[:, None] - g - W + 1, 0)  # [B, 1]
        s_pos = base + jnp.arange(W)[None, :]  # [B, W] absolute starts
        win_idx = jnp.minimum(
            s_pos[:, :, None] + jnp.arange(g)[None, None, :], S - 1
        )  # [B, W, g]
        windows = jnp.take_along_axis(
            history, win_idx.reshape(B, W * g), axis=1
        ).reshape(B, W, g)
    else:
        # Unbounded: sliding windows [B, S, g] (clip keeps the tail
        # in-bounds; those positions are excluded by the validity mask).
        win_idx = jnp.minimum(
            jnp.arange(S)[:, None] + jnp.arange(g)[None, :], S - 1
        )
        windows = history[:, win_idx]  # [B, S, g]
        s_pos = jnp.broadcast_to(jnp.arange(S)[None, :], (B, S))
    eq = jnp.all(windows == lastgram[:, None, :], axis=2)  # [B, S or W]
    # The earlier occurrence must end strictly before the pending
    # position, and there must be at least g tokens of history.
    valid = eq & (s_pos + g - 1 < lengths[:, None]) & (lengths[:, None] + 1 >= g)
    best = jnp.max(jnp.where(valid, s_pos, -1), axis=1)  # [B]
    start = best + g  # continuation start (a known position <= lengths)
    cont_idx = jnp.clip(
        start[:, None] + jnp.arange(d)[None, :], 0, S - 1
    )
    draft = jnp.take_along_axis(history, cont_idx, axis=1).astype(jnp.int32)
    eff = jnp.where(
        best >= 0,
        jnp.clip(lengths - start + 1, 0, d),
        0,
    ).astype(jnp.int32)
    return draft, eff


def spec_verify(
    logits: jnp.ndarray,  # [B, d+1, V] fp32, row j = dist after feeding
    #                       token j (0 = pending input, j>0 = draft[j-1])
    draft: jnp.ndarray,  # [B, d] int32
    eff: jnp.ndarray,  # [B] int32 proposed tokens (<= d)
    rng,
    temps, top_ps, top_ks, greedy_mask, forbid_rows, eos_mask,
    active_rows=None,
):
    """Vectorized accept/resample. Returns (emitted [B, d+1] int32,
    n_emit [B] int32 in 1..d+1, logprobs [B, d+1] under the base
    distribution). Row semantics per slot:
      a = length of the accepted draft prefix (greedy: argmax matches;
          sampled: u_j < p_j(draft_j)), capped at eff
      emitted = draft[:a] + one final token from position a's
          distribution (argmax for greedy; for sampled: the rejected
          token removed + renormalized when a < eff, plain sample when
          a == eff)
    Slots with eff = 0 reduce exactly to one plain warp_sample step."""
    B, d1, V = logits.shape
    d = d1 - 1
    flat = logits.reshape(B * d1, V)

    def rep(x):
        return jnp.repeat(x, d1, axis=0)

    warped_f, base_f = warp_logits(
        flat, rep(temps), rep(top_ps), rep(top_ks), rep(forbid_rows),
        eos_mask,
        active_rows=rep(active_rows) if active_rows is not None else None,
    )
    warped = warped_f.reshape(B, d1, V)
    base_logp = base_f.reshape(B, d1, V)
    probs = jax.nn.softmax(warped, axis=-1)

    rng_u, rng_cat = jax.random.split(rng)
    u = jax.random.uniform(rng_u, (B, d))
    p_draft = jnp.take_along_axis(
        probs[:, :d], draft[:, :, None], axis=2
    )[:, :, 0]  # [B, d]: p_j(draft_j)
    # Greedy acceptance is judged on the BASE distribution — the same
    # argmax the plain decode path emits (paged.warp_sample) — so greedy
    # speculative decoding is bit-identical to plain greedy by
    # construction, not merely when warping preserves the argmax.
    argmax_d = jnp.argmax(base_logp[:, :d], axis=2)  # [B, d]
    ok_greedy = argmax_d == draft
    ok_sample = u < p_draft
    ok = jnp.where(greedy_mask[:, None], ok_greedy, ok_sample)
    ok = ok & (jnp.arange(d)[None, :] < eff[:, None])
    # a = length of the accepted prefix
    acc = jnp.cumprod(ok.astype(jnp.int32), axis=1)
    a = jnp.sum(acc, axis=1)  # [B] in 0..eff

    # Final token from position a's distribution.
    w_a = jnp.take_along_axis(
        warped, a[:, None, None], axis=1
    )[:, 0]  # [B, V]
    # On rejection (a < eff) remove the rejected draft token and let
    # categorical renormalize; argmax rows are unaffected by removal
    # semantics (the rejected token was not the argmax).
    rej_tok = jnp.take_along_axis(
        draft, jnp.minimum(a, d - 1)[:, None], axis=1
    )[:, 0] if d > 0 else jnp.zeros((B,), jnp.int32)
    remove = (a < eff)
    remove_mask = remove[:, None] & (
        jnp.arange(V)[None, :] == rej_tok[:, None]
    )
    w_final = jnp.where(remove_mask, NEG_INF, w_a)
    sampled = jax.random.categorical(rng_cat, w_final, axis=-1)
    # Greedy final token from the BASE distribution (matching
    # warp_sample's greedy path); the rejected-token mask is a no-op for
    # greedy rows (a rejected draft is never the base argmax) but keeps
    # the row semantics uniform.
    b_a = jnp.take_along_axis(
        base_logp, a[:, None, None], axis=1
    )[:, 0]  # [B, V]
    greedy_tok = jnp.argmax(jnp.where(remove_mask, NEG_INF, b_a), axis=-1)
    final = jnp.where(greedy_mask, greedy_tok, sampled).astype(jnp.int32)

    # emitted[j] = draft[j] for j < a, final at j == a, zeros after.
    emitted = jnp.where(
        jnp.arange(d1)[None, :] < a[:, None],
        jnp.pad(draft, ((0, 0), (0, 1))),
        0,
    )
    emitted = emitted.at[jnp.arange(B), a].set(final).astype(jnp.int32)
    n_emit = a + 1
    logprobs = jnp.take_along_axis(
        base_logp, emitted[:, :, None], axis=2
    )[:, :, 0]
    logprobs = jnp.where(jnp.arange(d1)[None, :] < n_emit[:, None],
                         logprobs, 0.0)
    return emitted, n_emit, logprobs


@functools.partial(jax.jit, donate_argnames=("history",))
def set_history(history, slots, valid, rows):
    """Write admitted requests' token history (prompt + first sampled
    token) into their slots' rows. rows: [m, S+1] int32; invalid
    (padding) entries route to a scratch row, same trick as
    apply_admits."""
    B = history.shape[0]
    idx = jnp.where(valid, slots, B).astype(jnp.int32)
    ext = jnp.concatenate([history, history[:1]], axis=0)
    ext = ext.at[idx].set(rows)
    return ext[:B]


@functools.partial(
    jax.jit,
    static_argnames=("cfg", "n_steps", "draft_len", "ngram", "ngram_window",
                     "attn_impl", "mesh"),
    donate_argnames=(
        "k_pages", "v_pages", "lengths", "next_input", "active",
        "remaining", "min_remaining", "rng", "history",
    ),
)
def paged_spec_decode_block(
    params,
    cfg: TransformerConfig,
    k_pages,
    v_pages,
    page_indices,  # [B, P]
    lengths,
    next_input,
    active,
    remaining,
    min_remaining,
    temps,
    top_ps,
    top_ks,
    greedy_mask,
    eos_mask,  # [V] bool
    rng,
    history,  # [B, S+1] int32 (see set_history)
    n_steps: int,
    draft_len: int,
    ngram: int = 2,
    ngram_window: int = 0,
    attn_impl: str = "auto",
    mesh=None,
):
    """paged_decode_block with n-gram speculative decoding: each step
    feeds 1 + draft_len rows per slot (pending token + drafts, staggered
    lengths sharing the slot's page-table row — the same trick as
    chunked prefill) and emits the verified prefix + one token. Output
    layout matches paged_decode_block with n_out = n_steps*(draft_len+1)
    token/logprob columns. The host must reserve pages for
    lengths + n_steps*(draft_len+1) tokens per active slot: rejected
    rows still write (stale) KV, overwritten by later steps and never
    attended (position >= the slot's length)."""
    B = lengths.shape[0]
    d1 = draft_len + 1
    n_out = n_steps * d1
    S1 = history.shape[1]

    def body(i, carry):
        del i
        (kp, vp, lengths, next_input, active, remaining, min_remaining,
         rng, history, total, steps_act, out_t, out_lp, out_m,
         hit_eos) = carry
        steps_act = steps_act + active.astype(jnp.int32)
        # Drafting is disabled while the EOS-forbid floor is live (the
        # per-position forbid interaction isn't worth the complexity)
        # and for inactive slots.
        draft, eff = propose_ngram_drafts(history, lengths, ngram,
                                          draft_len, window=ngram_window)
        eff = jnp.where(active & (min_remaining <= 0), eff, 0)
        # Also never propose past the remaining budget: tokens beyond it
        # would be dropped anyway; skipping them keeps n_emit <= budget.
        eff = jnp.minimum(eff, jnp.maximum(remaining - 1, 0))

        # [B, d1] rows: j=0 feeds the pending token, j>0 the drafts.
        toks = jnp.concatenate([next_input[:, None], draft], axis=1)
        j_idx = jnp.arange(d1)[None, :]
        row_lengths = (lengths[:, None] + j_idx).reshape(-1)
        row_active = (active[:, None] & (j_idx <= eff[:, None])).reshape(-1)
        row_pages = jnp.repeat(page_indices, d1, axis=0)
        logits, kp, vp = paged_decode_step(
            params, cfg, toks.reshape(-1), kp, vp, row_pages, row_lengths,
            row_active, mesh=mesh, attn_impl=attn_impl,
        )
        rng, sub = jax.random.split(rng)
        emitted, n_emit, logprobs = spec_verify(
            logits.reshape(B, d1, -1), draft, eff, sub,
            temps, top_ps, top_ks, greedy_mask, min_remaining > 0,
            eos_mask, active_rows=active,
        )

        # Truncate the emitted group at the first EOS, then at budget.
        pos_mask = j_idx < n_emit[:, None]
        is_eos = eos_mask[emitted] & pos_mask
        any_eos = jnp.any(is_eos, axis=1)
        first_eos = jnp.argmax(is_eos, axis=1)
        n_emit = jnp.where(any_eos, first_eos + 1, n_emit)
        n_emit = jnp.minimum(n_emit, jnp.maximum(remaining, 0))
        n_emit = jnp.where(active, n_emit, 0)
        emit_mask = j_idx < n_emit[:, None]
        emitted = jnp.where(emit_mask, emitted, 0)
        logprobs = jnp.where(emit_mask, logprobs, 0.0)

        # State advance (mirrors the plain block, in units of n_emit).
        got_eos = any_eos & (first_eos < n_emit) & active
        remaining = remaining - n_emit
        min_remaining = jnp.maximum(min_remaining - n_emit, 0)
        exhausted = (remaining <= 0) & active & (n_emit > 0)
        hit_eos = hit_eos | got_eos
        new_active = active & ~got_eos & ~exhausted

        # next_input = last emitted token (only meaningful where
        # n_emit > 0; inactive slots keep their stale value).
        last_tok = jnp.take_along_axis(
            emitted, jnp.maximum(n_emit - 1, 0)[:, None], axis=1
        )[:, 0]
        next_input = jnp.where(n_emit > 0, last_tok, next_input)

        # History append: emitted[i] lands at position lengths + 1 + i;
        # masked writes route to the scratch column S.
        brow = jnp.broadcast_to(jnp.arange(B)[:, None], (B, d1))
        wpos = jnp.where(
            emit_mask, jnp.minimum(lengths[:, None] + 1 + j_idx, S1 - 1),
            S1 - 1,
        )
        history = history.at[brow, wpos].set(emitted)
        lengths = lengths + n_emit

        # Emission buffers, compacted per slot: the host consumes the
        # FIRST n_emitted columns, so each step's group scatters at the
        # slot's running offset (masked entries route to the scratch
        # column n_out).
        wcol = jnp.where(emit_mask, total[:, None] + j_idx, n_out)
        out_t = out_t.at[brow, wcol].set(emitted)
        out_lp = out_lp.at[brow, wcol].set(logprobs)
        out_m = out_m.at[brow, wcol].set(emit_mask)
        total = total + n_emit
        return (kp, vp, lengths, next_input, new_active, remaining,
                min_remaining, rng, history, total, steps_act, out_t,
                out_lp, out_m, hit_eos)

    # One scratch column (n_out) absorbs masked scatter writes.
    out_t = jnp.zeros((B, n_out + 1), jnp.int32)
    out_lp = jnp.zeros((B, n_out + 1), jnp.float32)
    out_m = jnp.zeros((B, n_out + 1), bool)
    hit_eos = jnp.zeros((B,), bool)
    total0 = jnp.zeros((B,), jnp.int32)
    steps0 = jnp.zeros((B,), jnp.int32)
    carry = (k_pages, v_pages, lengths, next_input, active, remaining,
             min_remaining, rng, history, total0, steps0, out_t, out_lp,
             out_m, hit_eos)
    carry = jax.lax.fori_loop(0, n_steps, body, carry)
    (k_pages, v_pages, lengths, next_input, active, remaining, min_remaining,
     rng, history, _total, steps_act, out_t, out_lp, out_m, hit_eos) = carry
    out_t, out_lp, out_m = out_t[:, :n_out], out_lp[:, :n_out], out_m[:, :n_out]
    # One extra column vs the plain block: per-slot steps the slot was
    # ACTIVE for — the exact denominator for the speculation yield
    # (charging full blocks to early-finishing slots would deflate it).
    packed = jnp.concatenate(
        [
            out_t.astype(jnp.float32),
            out_lp,
            jnp.sum(out_m, axis=1, keepdims=True).astype(jnp.float32),
            hit_eos[:, None].astype(jnp.float32),
            active[:, None].astype(jnp.float32),
            lengths[:, None].astype(jnp.float32),
            steps_act[:, None].astype(jnp.float32),
        ],
        axis=1,
    )
    return (packed, k_pages, v_pages, lengths, next_input, active,
            remaining, min_remaining, rng, history)

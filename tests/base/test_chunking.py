"""Content-addressed chunking (base/chunking.py): span math, index
build, torn-write detection, and hash verification — the shared
"what is a chunk" definition the weight plane's source, client, and
bench workload all depend on."""

import os

import pytest

from areal_tpu.base.chunking import (
    CHUNK_SCHEMA,
    build_chunk_index,
    chunk_spans,
    hash_chunk,
    verify_chunk,
)


def test_chunk_spans_cover_exactly():
    spans = chunk_spans(10, 4)
    assert spans == [(0, 4), (4, 4), (8, 2)]
    # Exact multiple: no short tail.
    assert chunk_spans(8, 4) == [(0, 4), (4, 4)]
    # Zero-byte payload has zero chunks.
    assert chunk_spans(0, 4) == []


def test_chunk_spans_rejects_bad_chunk_size():
    with pytest.raises(ValueError, match="chunk_bytes"):
        chunk_spans(10, 0)


def test_build_index_roundtrip(tmp_path):
    payload = bytes(range(256)) * 40  # 10240 bytes
    p = tmp_path / "params.bin"
    p.write_bytes(payload)
    idx = build_chunk_index(str(p), chunk_bytes=4096)
    assert idx["schema"] == CHUNK_SCHEMA
    assert idx["total_bytes"] == len(payload)
    assert idx["n_chunks"] == 3
    # Every hash verifies against the actual bytes, and a flipped byte
    # fails exactly its own chunk.
    for i, (off, length) in enumerate(chunk_spans(len(payload), 4096)):
        assert verify_chunk(payload[off:off + length], idx["hashes"][i])
    corrupt = bytearray(payload)
    corrupt[4100] ^= 0xFF
    assert not verify_chunk(corrupt[4096:8192], idx["hashes"][1])
    assert verify_chunk(corrupt[:4096], idx["hashes"][0])


def test_build_index_detects_concurrent_truncation(tmp_path):
    """The GC/torn-write race: the bin shrinks between getsize and the
    read — build_chunk_index must raise (callers retry on a refreshed
    manifest), never return an index for bytes it didn't hash."""
    p = tmp_path / "params.bin"
    p.write_bytes(b"x" * 8192)

    real_getsize = os.path.getsize

    def lying_getsize(path):
        return real_getsize(path) + 4096  # pretends the bin is longer

    orig = os.path.getsize
    os.path.getsize = lying_getsize
    try:
        with pytest.raises(OSError, match="short read"):
            build_chunk_index(str(p), chunk_bytes=4096)
    finally:
        os.path.getsize = orig


def test_hash_accepts_memoryview():
    data = b"hello chunk"
    assert hash_chunk(memoryview(data)) == hash_chunk(data)
    assert verify_chunk(memoryview(data), hash_chunk(data))

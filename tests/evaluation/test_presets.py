"""Benchmark presets: prompt templates, few-shot rendering, per-dataset
field mapping (role of reference evaluation/{utils,examples,parser}.py)."""

import json

import pytest

from evaluation.presets import (
    BENCHMARKS,
    MATH_FEW_SHOT,
    PROMPT_TEMPLATES,
    boxed_shots,
    build_prompt,
    load_benchmark,
)


def test_templates_render_question():
    q = "What is 2 + 2?"
    for name, t in PROMPT_TEMPLATES.items():
        p = t.wrap(q)
        assert q in p, name
        # Chat-style templates end mid-assistant-turn (generation point).
        if name == "chatml-boxed":
            assert p.endswith("<|im_start|>assistant\n")
        if name == "r1-distill":
            assert p.endswith("<think>\n")


def test_few_shot_prepends_demos_in_order():
    q = "How many sides does a hexagon have?"
    p = build_prompt(q, "cot", num_shots=3)
    positions = [p.index(dq) for dq, _ in MATH_FEW_SHOT[:3]]
    assert positions == sorted(positions)
    assert p.index(q) > positions[-1]
    # Zero-shot has no demo text.
    p0 = build_prompt(q, "cot", num_shots=0)
    assert MATH_FEW_SHOT[0][0] not in p0


def test_boxed_shots_rewrite_terminal_answer():
    shots = boxed_shots(MATH_FEW_SHOT)
    for (_, plain), (_, boxed) in zip(MATH_FEW_SHOT, shots):
        assert "The answer is " in plain
        assert "\\boxed{" in boxed
        assert "The answer is " not in boxed
    # The boxed demo still grades correct under the repo's own grader.
    from areal_tpu.functioncall.math_grader import grade_answer

    assert grade_answer(shots[0][1], ["29"])


def test_gsm8k_ground_truth_extraction():
    preset = BENCHMARKS["gsm8k"]
    row = {"question": "q", "answer": "6 - 2 = 4 dollars\n#### 4,000"}
    assert preset.ground_truth(row) == "4000"


def test_benchmark_field_fallbacks(tmp_path):
    """aime-style rows use problem/answer; repo-native rows use
    prompt/solutions — both resolve through the ordered candidates."""
    preset = BENCHMARKS["aime24"]
    rows = [
        {"problem": "Find x.", "answer": "7", "query_id": "a"},
        {"question": "Find y.", "answer": "8"},
    ]
    path = tmp_path / "b.jsonl"
    path.write_text("".join(json.dumps(r) + "\n" for r in rows))
    loaded = load_benchmark(str(path), preset)
    assert [r["question"] for r in loaded] == ["Find x.", "Find y."]
    assert [r["gt"] for r in loaded] == ["7", "8"]
    assert loaded[0]["query_id"] == "a"
    assert loaded[1]["query_id"] == "1"  # falls back to line index


def test_unknown_question_field_raises():
    with pytest.raises(KeyError):
        BENCHMARKS["math500"].question({"text": "nope"})


def test_preset_defaults_shape():
    """Contest sets default to multi-sample; gsm8k is few-shot CoT."""
    assert BENCHMARKS["aime24"].n_samples > 1
    assert BENCHMARKS["gsm8k"].num_shots == 4
    assert BENCHMARKS["gsm8k"].prompt_type == "cot"
    for b in BENCHMARKS.values():
        assert b.prompt_type in PROMPT_TEMPLATES


def test_gpqa_choice_preset():
    """Multiple-choice preset: lettered options live in the question
    text, ground truth is the letter, and a boxed letter grades true."""
    from areal_tpu.functioncall.math_grader import grade_answer

    preset = BENCHMARKS["gpqa_diamond"]
    row = {"question": "Which is even?\n\nA. 3\nB. 4\nC. 5\nD. 7",
           "answer": "B"}
    assert preset.ground_truth(row) == "B"
    p = build_prompt(preset.question(row), preset.prompt_type, 0)
    assert "letter" in p
    assert grade_answer("The even number is 4, so \\boxed{B}.", ["B"])
    assert not grade_answer("\\boxed{A}", ["B"])


def test_boxed_choice_rejects_few_shot():
    from evaluation.presets import build_prompt

    with pytest.raises(ValueError, match="few-shot"):
        build_prompt("q", "boxed-choice", num_shots=1)

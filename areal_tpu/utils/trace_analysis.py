"""XLA trace post-processing: device-op time by kernel category.

TPU counterpart of the reference's chrome-trace kernel-time analysis
(realhf/base/monitor.py:404-610: CUDAKernelTimeCategory classification +
interval-union accounting per category, incl. idle time): parses the
`*.trace.json(.gz)` Chrome-format dump that `jax.profiler.trace` writes
next to the xplane.pb, classifies each device-lane op by its HLO name
into attention / gemm / collective / memory / fusion / misc, and computes
per-device interval-union time so overlapping ops on parallel lanes are
not double-counted. Idle = profile span minus the union of all op time.

Used by `scripts/analyze_trace.py` on the per-MFC dumps produced by
`areal_tpu.utils.profiling.maybe_profile` (AREAL_DUMP_TRACE=1).
"""

from __future__ import annotations

import dataclasses
import glob
import gzip
import json
import os
from typing import Dict, Iterable, List, Optional, Tuple

# Order matters: first match wins (e.g. a "fusion" whose name mentions
# attention is attention, not generic fusion).
CATEGORY_KEYS: List[Tuple[str, Tuple[str, ...]]] = [
    (
        "attention",
        ("flash_attention", "splash", "attention", "mha", "paged_attn"),
    ),
    (
        "collective",
        (
            "all-reduce", "all-gather", "reduce-scatter", "all-to-all",
            "collective-permute", "collective-broadcast", "psum",
            "ppermute", "send", "recv",
        ),
    ),
    ("gemm", ("dot", "conv", "matmul", "einsum", "megacore_fusion")),
    (
        "memory",
        (
            "copy", "transpose", "dynamic-update-slice", "dynamic-slice",
            "broadcast", "concatenate", "reshape", "pad", "slice",
            "gather", "scatter", "convert", "bitcast", "memset",
            "infeed", "outfeed", "tuple", "iota",
        ),
    ),
    ("fusion", ("fusion", "custom-call", "custom_call", "loop", "while")),
]
CATEGORIES = [c for c, _ in CATEGORY_KEYS] + ["misc", "idle"]


def categorize(name: str, long_name: str = "") -> str:
    """Map an HLO/kernel op name to a category. `long_name` (the
    `args.long_name`/`args.hlo_op` xprof attaches) is consulted too, so
    `fusion.123` whose expression contains a dot lands in gemm."""
    s = f"{name} {long_name}".lower()
    for cat, keys in CATEGORY_KEYS:
        if any(k in s for k in keys):
            return cat
    return "misc"


@dataclasses.dataclass
class DeviceOpStats:
    """Interval-union op time per category (microseconds) for one device."""

    device: str
    times_us: Dict[str, float]
    span_us: float
    n_ops: int

    @property
    def busy_us(self) -> float:
        return sum(
            v for k, v in self.times_us.items() if k != "idle"
        )


def _union_us(intervals: List[Tuple[float, float]]) -> float:
    """Total length of the union of [start, end) intervals."""
    if not intervals:
        return 0.0
    intervals.sort()
    total = 0.0
    cur_s, cur_e = intervals[0]
    for s, e in intervals[1:]:
        if s > cur_e:
            total += cur_e - cur_s
            cur_s, cur_e = s, e
        else:
            cur_e = max(cur_e, e)
    return total + (cur_e - cur_s)


def resolve_trace_file(path: str) -> str:
    """Accept a trace file, a jax.profiler dump dir, or an
    AREAL_TRACE_DIR root; return the newest *.trace.json(.gz) under it."""
    if os.path.isfile(path):
        return path
    cands = sorted(
        glob.glob(
            os.path.join(path, "**", "*.trace.json*"), recursive=True
        ),
        key=os.path.getmtime,
    )
    if not cands:
        raise FileNotFoundError(f"no *.trace.json(.gz) under {path}")
    return cands[-1]


def load_trace(path: str) -> Dict:
    path = resolve_trace_file(path)
    if path.endswith(".gz"):
        with gzip.open(path, "rt") as f:
            return json.load(f)
    with open(path) as f:
        return json.load(f)


def device_lanes(trace: Dict) -> Dict[int, str]:
    """pid -> device name for accelerator processes in the trace.

    xprof names device processes '/device:TPU:0' (and the op rows live on
    threads named 'XLA Ops...'); host processes are '/host:CPU'."""
    out = {}
    for e in trace.get("traceEvents", []):
        if e.get("ph") == "M" and e.get("name") == "process_name":
            pname = (e.get("args") or {}).get("name", "")
            if "/device:" in pname:
                out[e["pid"]] = pname
    return out


def analyze(
    trace: Dict, include_host: bool = False
) -> List[DeviceOpStats]:
    """Per-device category breakdown. Falls back to host lanes when the
    trace has no device processes (CPU-only runs) and `include_host`."""
    lanes = device_lanes(trace)
    events = [
        e
        for e in trace.get("traceEvents", [])
        if e.get("ph") == "X" and "dur" in e and "ts" in e
    ]
    if not lanes and include_host:
        lanes = {
            e.get("pid"): f"host:{e.get('pid')}"
            for e in events
        }
    stats = []
    for pid, dev in sorted(lanes.items(), key=lambda kv: kv[1]):
        by_cat: Dict[str, List[Tuple[float, float]]] = {}
        all_iv: List[Tuple[float, float]] = []
        t0, t1 = float("inf"), float("-inf")
        n = 0
        for e in events:
            if e.get("pid") != pid:
                continue
            args = e.get("args") or {}
            cat = categorize(
                e.get("name", ""),
                str(args.get("long_name", "")) + str(args.get("hlo_op", "")),
            )
            s, d = float(e["ts"]), float(e["dur"])
            by_cat.setdefault(cat, []).append((s, s + d))
            all_iv.append((s, s + d))
            t0, t1 = min(t0, s), max(t1, s + d)
            n += 1
        if not n:
            continue
        span = t1 - t0
        times = {c: 0.0 for c in CATEGORIES}
        for cat, ivs in by_cat.items():
            times[cat] = _union_us(ivs)
        times["idle"] = max(0.0, span - _union_us(all_iv))
        stats.append(
            DeviceOpStats(device=dev, times_us=times, span_us=span, n_ops=n)
        )
    return stats


def aggregate(stats: List[DeviceOpStats]) -> Dict:
    """Summary dict: summed + per-device-average category times and
    percentages (the reference's CUDAKernelTimeStat.gpu_average)."""
    n = len(stats)
    total = {c: sum(s.times_us.get(c, 0.0) for s in stats) for c in CATEGORIES}
    span = sum(s.span_us for s in stats)
    return {
        "n_devices": n,
        "span_us": span,
        "total_us": total,
        "avg_us": {c: (v / n if n else 0.0) for c, v in total.items()},
        "pct": {
            c: (v / span if span > 0 else 0.0) for c, v in total.items()
        },
        "n_ops": sum(s.n_ops for s in stats),
    }


def top_ops(
    trace: Dict, pids: Optional[Iterable[int]] = None, k: int = 15
) -> List[Tuple[str, str, float, int]]:
    """(name, category, total_us, count), heaviest first — the quick
    'which kernel is eating the step' view."""
    if pids is None:
        pids = set(device_lanes(trace))
    else:
        pids = set(pids)
    acc: Dict[str, List[float]] = {}
    for e in trace.get("traceEvents", []):
        if e.get("ph") != "X" or "dur" not in e:
            continue
        if pids and e.get("pid") not in pids:
            continue
        acc.setdefault(e.get("name", "?"), []).append(float(e["dur"]))
    rows = [
        (name, categorize(name), sum(durs), len(durs))
        for name, durs in acc.items()
    ]
    rows.sort(key=lambda r: -r[2])
    return rows[:k]


def format_report(stats: List[DeviceOpStats], agg: Dict, top: List) -> str:
    lines = []
    lines.append(
        f"devices: {agg['n_devices']}   ops: {agg['n_ops']}   "
        f"span: {agg['span_us'] / 1e3:.3f} ms (summed)"
    )
    lines.append(
        f"{'category':<12}{'total ms':>12}{'avg/dev ms':>14}{'%':>8}"
    )
    for c in CATEGORIES:
        lines.append(
            f"{c:<12}{agg['total_us'][c] / 1e3:>12.3f}"
            f"{agg['avg_us'][c] / 1e3:>14.3f}"
            f"{agg['pct'][c] * 100:>7.1f}%"
        )
    if top:
        lines.append("")
        lines.append(f"top ops ({len(top)}):")
        for name, cat, us, cnt in top:
            lines.append(
                f"  {us / 1e3:>10.3f} ms  x{cnt:<5} [{cat}] {name[:80]}"
            )
    return "\n".join(lines)

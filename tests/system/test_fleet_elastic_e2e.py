"""ISSUE 12 acceptance: the elastic fleet control plane across real
process boundaries.

Join/drain leg (`test_fleet_join_drain_e2e`, ~35 s warm, slow lane —
tier-1 keeps the fleet_controller units incl. the real
successor-manager rebuild, plus the bench validator teeth): 2 real
GenerationServer processes behind a real subprocess GserverManager
with the weight plane armed. A third server JOINS at runtime — adopted from its first
heartbeat and weight-bootstrapped from PEERS (zero origin bytes) —
serves routed traffic, parks prefixes, then DRAINS: every parked
prefix migrates to the survivors over the hash-verified /kv wire
(zero lost), the departure is a clean forget (no eviction), and the
migrated sessions resume on the survivors via the global prefix index.

Slow lane (`test_fleet_elastic_full_e2e`, ~150 s): the full 2→4→2
story under sustained PartialRolloutManager load with the manager
SIGKILLed mid-run via AREAL_FAULTS — a successor takes the HA lease
(epoch 2), rebuilds membership/roles from heartbeats + /metrics within
the heartbeat horizon, adopts the in-flight joiner, and the run ends
with ZERO failed rollouts and fleet kv_prefix_lost_total == 0.
"""

import os
import time

import numpy as np
import pytest

from tests import fixtures

# Multi-process, compile-bound: keep off shared workers (pytest.ini).
pytestmark = [pytest.mark.serial, pytest.mark.chaos]

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

TIER_ENV = {"AREAL_KV_TIER_BYTES": str(64 << 20)}
PLEN = 48
TURN_NEW = 6


def _arm_plane(fleet, chunk_bytes):
    """Trainer-side dump v1 + weight-plane source + version publish —
    the substrate joins bootstrap from. Returns the source (caller
    closes)."""
    import jax

    from areal_tpu.base import constants, name_resolve, names
    from areal_tpu.models.config import TransformerConfig
    from areal_tpu.models.transformer import init_params
    from areal_tpu.system.weight_plane import WeightPlaneSource
    from areal_tpu.system.weight_transfer import dump_raw_params
    from areal_tpu.bench.workloads import _OPENLOOP_MODEL

    role_dir = os.path.join(
        constants.get_param_realloc_path(fleet.exp, fleet.trial), "actor"
    )
    os.makedirs(role_dir, exist_ok=True)
    with open(os.path.join(role_dir, "engine_state.pkl"), "wb") as f:
        f.write(b"gate")  # existence gate for check_new_params
    cfg = TransformerConfig(**_OPENLOOP_MODEL)
    p1 = jax.tree_util.tree_map(
        lambda x: np.asarray(x), init_params(cfg, jax.random.PRNGKey(7))
    )
    dump_raw_params(p1, role_dir, version=1, chunk_bytes=chunk_bytes)
    src = WeightPlaneSource(role_dir, chunk_bytes=chunk_bytes).start()
    src.register(fleet.exp, fleet.trial, "actor")
    name_resolve.add(
        names.model_version(fleet.exp, fleet.trial, "actor"), "1",
        replace=True,
    )
    return src


def _wait(cond, timeout, msg):
    deadline = time.monotonic() + fixtures.scale_timeout(timeout)
    while time.monotonic() < deadline:
        try:
            if cond():
                return
        except Exception:
            pass
        time.sleep(0.3)
    raise AssertionError(f"timed out waiting for {msg}")


def _mk_fleet(n, tag, manager_env=None, **mgr_extra):
    from areal_tpu.bench.fleet import ProcessFleet
    from areal_tpu.bench.workloads import _FLEET_SRV, _OPENLOOP_MODEL

    chunk = 1 << 15
    mgr_kw = dict(
        weight_plane=True, weight_chunk_bytes=chunk,
        weight_fanout_degree=2,
        flush_request_timeout=fixtures.scale_timeout(120.0),
        drain_timeout_s=fixtures.scale_timeout(240.0),
        join_bootstrap="peers", **mgr_extra,
    )
    fleet = ProcessFleet(
        _OPENLOOP_MODEL, [dict(_FLEET_SRV, env=TIER_ENV)] * n,
        manager_kw=mgr_kw, manager_subprocess=True,
        manager_env={"AREAL_FLEET_LEASE_TTL": "2",
                     **(manager_env or {})},
        tag=tag,
    )
    return fleet, chunk


def _park_direct(fleet, url, n, seed=55):
    from areal_tpu.bench.workloads import _OPENLOOP_MODEL

    rng = np.random.RandomState(seed)
    parked = {}
    for i in range(n):
        p = rng.randint(1, _OPENLOOP_MODEL["vocab_size"],
                        size=PLEN).tolist()
        out = fleet.generate_direct(url, f"park{seed}-{i}", p, TURN_NEW)
        assert "output_ids" in out, out
        parked[f"park{seed}-{i}"] = (p, [int(t) for t in out["output_ids"]])
    return parked


def _drain_and_assert(fleet, url, n_after):
    res = fleet.drain_server(url, reason="e2e scale-in")
    assert res.get("success"), res
    _wait(
        lambda: any(
            e["url"] == url and e["status"] == "departed"
            for e in fleet.status()["fleet"]["drains"]
        ),
        240, "drain departure",
    )
    entry = [
        e for e in fleet.status()["fleet"]["drains"]
        if e["url"] == url and e["status"] == "departed"
    ][-1]
    assert entry["lost"] == 0, entry
    fleet.wait_healthy(n_after, timeout_s=fixtures.scale_timeout(60))
    return entry


@pytest.mark.slow  # ~35 s warm of 3 jax child processes; tier-1 keeps
# the fleet_controller units (incl. the real successor-manager rebuild
# over fake servers) + the bench validator teeth, and wall clock sits
# ~800 s/870 s — this rides the slow lane with the full acceptance.
@pytest.mark.timeout(600)
def test_fleet_join_drain_e2e(tmp_path, monkeypatch):
    """Runtime join (peer weight bootstrap, zero origin bytes) then
    drain-then-leave (KV migration, zero loss, clean forget, sessions
    resume on survivors). Time budget: ~35 s warm."""
    monkeypatch.setenv("AREAL_FILEROOT", str(tmp_path / "fileroot"))
    from areal_tpu.bench.workloads import _FLEET_SRV

    fleet, chunk = _mk_fleet(2, "fjd")
    src = None
    try:
        src = _arm_plane(fleet, chunk)
        _wait(lambda: fleet.status()["weight_version"] == 1, 120,
              "v1 plane fanout")

        # ---- JOIN: spawn server 2; the manager adopts it from its
        # first heartbeat and bootstraps its weights from PEERS.
        url2 = fleet.spawn_server(dict(_FLEET_SRV, env=TIER_ENV))
        st = fleet.wait_healthy(3, timeout_s=fixtures.scale_timeout(240))
        joins = st["fleet"]["joins"]
        jp = [e for e in joins if e["url"] == url2][-1]
        assert jp["source"] == "peer", jp
        assert jp["bytes_from_origin"] == 0.0, jp
        assert jp["bytes_from_peers"] > 0, jp
        m2 = fleet.metrics(url2)
        assert m2["areal:weight_bytes_from_origin"] == 0.0
        assert m2["areal:weight_bytes_from_peers"] > 0
        assert m2["areal:weight_version"] == 1.0

        # The joiner serves manager-routed traffic.
        out = fleet.generate_routed("joined0", list(range(1, 9)), 2)
        assert "output_ids" in out, out

        # ---- DRAIN: park prefixes on the joiner, then drain it. The
        # parked KV migrates to the survivors (NOT lost), the joiner
        # departs cleanly (forgotten, never evicted), and the parked
        # sessions resume elsewhere via the global prefix index.
        parked = _park_direct(fleet, url2, 3)
        entry = _drain_and_assert(fleet, url2, 2)
        assert entry["migrated"] >= 3, entry
        st = fleet.status()
        assert url2 not in st["servers"]
        assert url2 not in st["evicted_servers"]
        accepted = lost = 0.0
        for u in fleet.urls[:2]:
            m = fleet.metrics(u)
            accepted += m["areal:kv_accepted"]
            lost += m["areal:kv_prefix_lost_total"]
        assert accepted >= 3, accepted
        assert lost == 0.0
        for qid, (p, out1) in parked.items():
            out = fleet.generate_routed(qid, p + out1 + [3], TURN_NEW,
                                        timeout=120)
            assert "output_ids" in out, (qid, out)
    finally:
        if src is not None:
            src.close()
        fleet.close()


@pytest.mark.slow  # ~150 s: 4 server processes + 2 manager
# incarnations + sustained client load; tier-1 keeps the join/drain
# e2e above, the fleet_controller units, and the bench validator teeth.
@pytest.mark.timeout(900)
def test_fleet_elastic_full_e2e(tmp_path, monkeypatch):
    """The full acceptance: 2→4→2 under sustained load with the
    manager SIGKILLed mid-run via AREAL_FAULTS; zero failed rollouts,
    joiners peer-bootstrapped, successor converges, nothing lost."""
    monkeypatch.setenv("AREAL_FILEROOT", str(tmp_path / "fileroot"))
    from areal_tpu.bench.workloads import _FleetLoad, _FLEET_SRV

    # The chaos arm: the manager's poll loop dies (os._exit) on lap
    # 450 — ~25-45 s in on this host, which lands mid-run while load
    # flows and the first joiner is coming up. The e2e does not depend
    # on WHERE in that window it fires: whichever manager is alive
    # adopts/bootstraps joiners, and the successor rebuilds the rest.
    fleet, chunk = _mk_fleet(
        2, "flfe",
        manager_env={
            "AREAL_FAULTS": "worker.poll@gserver_manager=die:k=450",
        },
    )
    src = None
    load = None
    try:
        src = _arm_plane(fleet, chunk)
        _wait(lambda: fleet.status()["weight_version"] == 1, 120,
              "v1 plane fanout")
        load = _FleetLoad(fleet, n_streams=2)
        _wait(lambda: load.completed >= 2, 180, "load warm-up")

        # ---- Grow 2 -> 3 while the doomed manager is still up.
        url2 = fleet.spawn_server(dict(_FLEET_SRV, env=TIER_ENV))

        # ---- The kill lands (AREAL_FAULTS die). Spawn the successor;
        # it waits out the lease, takes epoch 2, and rebuilds
        # membership/roles/shards from heartbeats + /metrics — the
        # joiner included, wherever its bootstrap got to.
        _wait(lambda: fleet.mgr_procs[0].poll() is not None, 240,
              "chaos kill of the manager")
        t_kill = time.monotonic()
        fleet.spawn_manager(env={"AREAL_FLEET_LEASE_TTL": "2"})
        st = fleet.wait_healthy(
            3, timeout_s=fixtures.scale_timeout(240), epoch=2
        )
        recovery_s = time.monotonic() - t_kill
        # Convergence within the failure-detection horizon: lease
        # expiry (3 x 2 s) + configure + the joiner's bootstrap —
        # bounded by one heartbeat TTL (60 s here), not the run.
        assert recovery_s < fixtures.scale_timeout(90), recovery_s

        # ---- Grow 3 -> 4 under the successor.
        url3 = fleet.spawn_server(dict(_FLEET_SRV, env=TIER_ENV))
        st = fleet.wait_healthy(4, timeout_s=fixtures.scale_timeout(240))
        for u in (url2, url3):
            m = fleet.metrics(u)
            assert m["areal:weight_bytes_from_origin"] == 0.0, u
            assert m["areal:weight_bytes_from_peers"] > 0, u
            assert m["areal:weight_version"] == 1.0, u
        roles = st["pools"]["roles"]
        assert set(roles) == set(st["servers"]) and len(st["servers"]) == 4

        # ---- Shrink 4 -> 2: drain both joiners (parked prefixes
        # migrate; zero lost; clean departures).
        _park_direct(fleet, url2, 2, seed=60)
        _drain_and_assert(fleet, url2, 3)
        _park_direct(fleet, url3, 2, seed=61)
        _drain_and_assert(fleet, url3, 2)

        # ---- The whole story cost ZERO rollouts and ZERO prefixes.
        stats = load.stop()
        load = None
        assert stats["failed"] == 0, stats
        assert stats["completed"] >= 4, stats
        lost = 0.0
        for u in fleet.urls[:2]:
            lost += fleet.metrics(u)["areal:kv_prefix_lost_total"]
        assert lost == 0.0
        st = fleet.status()
        assert st["fleet"]["epoch"] == 2
        assert sorted(st["healthy_servers"]) == sorted(fleet.urls[:2])
        assert st["evicted_servers"] == {}
    finally:
        if load is not None:
            load.stop(timeout=30)
        if src is not None:
            src.close()
        fleet.close()

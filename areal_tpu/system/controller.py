"""Experiment controller: spawn workers, run the master, reap results.

Counterpart of the reference's controller (realhf/system/controller.py:
98-689) in its "local" form: every worker is a separate OS process
(multiprocessing spawn so each gets a clean JAX runtime), the master runs
inline in the controller process, and worker health is watched while the
master drives the experiment. This is also the in-process e2e test
harness (reference tests/experiments/utils.py:22-52).
"""

from __future__ import annotations

import dataclasses
import multiprocessing as mp
import os
import sys
import time
import traceback
from typing import Any, Dict, List, Optional, Set

from areal_tpu.api.system_api import ExperimentConfig
from areal_tpu.base import constants, health, logging, name_resolve, names

logger = logging.getLogger("controller")

# Worker roles the supervisor restarts in place on death/hang. The
# trainer plane (model workers, master) holds in-flight step state the
# request/reply stream can't rebuild mid-step, so those still escalate
# to the whole-experiment relaunch in training/utils.run_experiment;
# the serving plane is designed to re-register, re-sync weights, and
# re-enter rotation.
RESTARTABLE_ROLES = frozenset(
    {"generation_server", "rollout_worker", "gserver_manager"}
)


def _run_worker_proc(
    worker_type: str,
    config: Any,
    name_resolve_cfg: Dict,
    env: Dict[str, str],
    error_queue,
):
    """Subprocess entry: reconfigure name_resolve, build + run the worker."""
    worker_name = getattr(config, "worker_name", worker_type)
    try:
        os.environ.update(env)
        from areal_tpu.utils.jaxenv import apply_jax_platform_override

        apply_jax_platform_override()
        name_resolve.reconfigure(**name_resolve_cfg)
        from areal_tpu.system import load_worker

        cls = load_worker(worker_type)
        w = cls()
        w.configure(
            config,
            experiment_name=config.experiment_name,
            trial_name=config.trial_name,
            worker_name=worker_name,
        )
        w.run()
    except Exception:
        error_queue.put(f"{worker_name}: " + traceback.format_exc())
        raise


@dataclasses.dataclass
class _WorkerRecord:
    worker_type: str
    config: Any
    proc: mp.Process
    restarts: int = 0
    last_restart: float = 0.0
    last_seen_alive: float = 0.0  # last fresh heartbeat (0 = never beat)


class LocalController:
    """Run one trial on this host: subprocess workers + inline master."""

    def __init__(
        self,
        exp_cfg: ExperimentConfig,
        name_resolve_cfg: Optional[Dict] = None,
        worker_env: Optional[Dict[str, str]] = None,
        max_worker_restarts: int = 2,
        restartable_roles: Optional[Set[str]] = None,
    ):
        self.exp_cfg = exp_cfg
        self.name_resolve_cfg = name_resolve_cfg or {"backend": "nfs"}
        self.worker_env = worker_env or {}
        # Per-worker fault domain: how many times one worker role may be
        # restarted in place before the failure escalates to the
        # whole-experiment relaunch loop.
        self.max_worker_restarts = max_worker_restarts
        self.restartable_roles = (
            RESTARTABLE_ROLES if restartable_roles is None
            else frozenset(restartable_roles)
        )
        self._workers: Dict[str, _WorkerRecord] = {}
        # Guarded by _err_lock: appended by the supervisor thread while
        # the main thread drains/raises in run()'s teardown.
        self._pending_errors: List[str] = []
        import threading

        self._err_lock = threading.Lock()
        self._ctx = mp.get_context("spawn")
        self._errors = self._ctx.Queue()

    @property
    def _procs(self) -> List[mp.Process]:
        return [r.proc for r in self._workers.values()]

    def _spawn(self, worker_type: str, config) -> mp.Process:
        # Spawned children must be able to import areal_tpu before the
        # target function runs (unpickling imports this module), so the
        # repo root has to be on PYTHONPATH at process start.
        import areal_tpu

        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(areal_tpu.__file__)))
        existing = os.environ.get("PYTHONPATH", "")
        if repo_root not in existing.split(os.pathsep):
            os.environ["PYTHONPATH"] = (
                repo_root + (os.pathsep + existing if existing else "")
            )
        p = self._ctx.Process(
            target=_run_worker_proc,
            args=(
                worker_type,
                config,
                self.name_resolve_cfg,
                self.worker_env,
                self._errors,
            ),
            daemon=True,
        )
        p.start()
        name = getattr(config, "worker_name", worker_type)
        rec = self._workers.get(name)
        if rec is None:
            self._workers[name] = _WorkerRecord(worker_type, config, p)
        else:  # restart: keep the record's history
            rec.proc = p
        return p

    def start_workers(self):
        from areal_tpu.system import _WORKER_CLASSES

        async_types = ["generation_server", "gserver_manager", "rollout_worker"]
        wants_async = bool(
            self.exp_cfg.generation_servers
            or self.exp_cfg.gserver_manager
            or self.exp_cfg.rollout_workers
        )
        missing = [t for t in async_types if t not in _WORKER_CLASSES]
        if wants_async and missing:
            raise NotImplementedError(
                f"async worker roles not available yet: {missing}"
            )
        for cfg in self.exp_cfg.model_workers:
            self._spawn("model_worker", cfg)
        for cfg in self.exp_cfg.generation_servers:
            self._spawn("generation_server", cfg)
        if self.exp_cfg.gserver_manager is not None:
            self._spawn("gserver_manager", self.exp_cfg.gserver_manager)
        for cfg in self.exp_cfg.rollout_workers:
            self._spawn("rollout_worker", cfg)

    def _drain_errors(self):
        while True:
            try:
                err = self._errors.get_nowait()
            except Exception:
                return
            with self._err_lock:
                self._pending_errors.append(err)

    def _discard_errors_for(self, worker_name: str):
        """Drop queued tracebacks attributed to a worker the supervisor
        is restarting — a handled failure must not fail the run later."""
        with self._err_lock:
            kept, dropped = [], []
            for err in self._pending_errors:
                (dropped if err.startswith(f"{worker_name}: ")
                 else kept).append(err)
            self._pending_errors = kept
        for err in dropped:
            logger.warning(
                f"restarting {worker_name}; absorbed its failure:\n{err}"
            )
        return len(dropped)

    def check_worker_errors(self):
        self._drain_errors()
        with self._err_lock:
            if self._pending_errors:
                raise RuntimeError(
                    f"worker failed:\n{self._pending_errors[0]}"
                )

    # ------------------------------------------------------------------
    # Supervision: per-worker restart, heartbeat hang detection,
    # escalation to the whole-experiment relaunch
    # ------------------------------------------------------------------

    def _escalate(self, why: str):
        import _thread

        logger.error(f"{why}; interrupting master")
        self._watchdog_fired = True
        _thread.interrupt_main()

    def _restart_worker(self, name: str, rec: _WorkerRecord, why: str) -> bool:
        """Restart one worker role in place. Returns False when the
        failure must escalate instead (role not restartable / budget
        spent)."""
        if (
            rec.worker_type not in self.restartable_roles
            or rec.restarts >= self.max_worker_restarts
        ):
            return False
        if rec.proc.is_alive():
            # Hung, not dead: kill the wedged process first.
            rec.proc.kill()
            rec.proc.join(timeout=10)
        rec.restarts += 1
        rec.last_restart = time.monotonic()
        self._discard_errors_for(name)
        logger.warning(
            f"restarting {name} ({why}; "
            f"attempt {rec.restarts}/{self.max_worker_restarts})"
        )
        self._spawn(rec.worker_type, rec.config)
        return True

    def supervise_once(self, registry: Optional[health.HealthRegistry] = None) -> bool:
        """One supervision pass. Returns False once a failure escalated
        (supervision should stop); True to keep supervising."""
        self._drain_errors()
        alive_members = registry.snapshot() if registry is not None else {}
        stopped = registry.stopped_members() if registry is not None else {}
        now = time.monotonic()
        for name, rec in list(self._workers.items()):
            # Only THIS incarnation's beats count: a dead worker's record
            # stays fresh for up to 3*TTL, and crediting it to the
            # replacement would end its startup grace before its first
            # beat (and hang-kill it mid model load).
            if (
                name in alive_members
                and alive_members[name].get("pid") == rec.proc.pid
            ):
                rec.last_seen_alive = now
            dead = (not rec.proc.is_alive()) and rec.proc.exitcode not in (0, None)
            # Hang: the process is up but its poll loop stopped beating
            # AFTER this incarnation was last seen healthy (never-beat
            # workers get startup grace; freshly restarted ones too), and
            # it did not announce a graceful shutdown. Only judged for
            # restartable (serving-plane) roles: trainer-plane poll loops
            # legitimately block for minutes inside jit compiles.
            hung = (
                rec.worker_type in self.restartable_roles
                and rec.proc.is_alive()
                and rec.last_seen_alive > rec.last_restart
                and name not in alive_members
                and name not in stopped
            )
            if not dead and not hung:
                continue
            why = "process died" if dead else "heartbeat went stale"
            if not self._restart_worker(name, rec, why):
                self._escalate(f"{name} failed ({why})")
                return False
        # Queued tracebacks. A traceback whose process is still alive is
        # either in-flight death (handled as a proc exit on a later pass)
        # or a leftover from an incarnation we already replaced.
        with self._err_lock:
            pending_snapshot = list(self._pending_errors)
        for err in pending_snapshot:
            name = err.split(": ", 1)[0]
            rec = self._workers.get(name)
            if rec is not None and rec.proc.is_alive():
                if rec.restarts > 0:
                    self._discard_errors_for(name)
                continue
            if rec is not None and self._restart_worker(name, rec, "raised"):
                continue
            self._escalate(f"worker failure: {name}")
            return False
        return True

    def _watchdog(self, stop_event):
        """Supervise workers while the inline master runs: restart failed
        serving-plane workers in place; interrupt the master (so its
        relaunch-recovery path runs) for anything non-recoverable."""
        registry = health.HealthRegistry(
            self.exp_cfg.experiment_name, self.exp_cfg.trial_name
        )
        while not stop_event.wait(0.5):
            try:
                keep_going = self.supervise_once(registry)
            except Exception:
                logger.warning("supervision pass failed", exc_info=True)
                continue
            if not keep_going:
                return

    def run(self, timeout: Optional[float] = None) -> Dict:
        """Blocking: start workers, run master inline, join everything."""
        import threading

        name_resolve.reconfigure(**self.name_resolve_cfg)
        self.start_workers()
        self._watchdog_fired = False
        user_interrupt = False
        stop_watchdog = threading.Event()
        watchdog = threading.Thread(
            target=self._watchdog, args=(stop_watchdog,), daemon=True
        )
        watchdog.start()

        from areal_tpu.system.master_worker import MasterWorker

        master = MasterWorker()
        try:
            master.configure(
                self.exp_cfg.master,
                experiment_name=self.exp_cfg.experiment_name,
                trial_name=self.exp_cfg.trial_name,
                worker_name="master",
            )
            master.run()
        except KeyboardInterrupt:
            # Distinguish the two interrupt sources by WHO fired: only
            # the watchdog's interrupt means a worker died (traceback or
            # not) and must become RuntimeError for relaunch-recovery. A
            # genuine Ctrl-C propagates as-is — the terminal delivers
            # SIGINT to the whole process group, so workers also die
            # nonzero, and exit codes alone can't tell the cases apart.
            if self._watchdog_fired:
                self.check_worker_errors()
                dead = [
                    p.pid for p in self._procs
                    if (not p.is_alive()) and p.exitcode not in (0, None)
                ]
                raise RuntimeError(
                    f"worker process(es) died without a traceback "
                    f"(killed/native crash): pids={dead}"
                )
            user_interrupt = True
            raise
        finally:
            stop_watchdog.set()
            if not user_interrupt:
                # Surface worker failures the watchdog hadn't polled yet
                # (died in its 0.5s window as the master finished). Only
                # a genuine Ctrl-C suppresses this — teardown noise from
                # interrupted workers must not override the user's stop.
                self.check_worker_errors()
            self.join(timeout=30)
        return {"global_step": master.step_info.global_step,
                "perf_summary": dict(master.perf_summary)}

    def join(self, timeout: float = 30):
        deadline = time.monotonic() + timeout
        for p in self._procs:
            p.join(timeout=max(0.1, deadline - time.monotonic()))
        for p in self._procs:
            if p.is_alive():
                logger.warning(f"terminating straggler worker pid={p.pid}")
                p.terminate()
        self._workers.clear()


class ClusterController:
    """Scheduler-submitted workers + inline master: the multi-host control
    plane (reference counterpart: realhf/apps/main.py submitting
    `apps.remote worker` lines through the SLURM scheduler,
    scheduler/slurm/utils.py).

    Differences from LocalController: workers are launched through a
    `SchedulerClient` (local subprocesses for one machine; a registered
    cluster scheduler for pods) with their configs spooled as pickles to
    `spool_dir` (a shared filesystem on real clusters), and discovery
    runs over any name_resolve backend — typically the 'kv' TCP service
    (base/name_resolve_kv.py), which needs no shared FS at all. When
    `kv_address` is omitted a KvStoreServer is started in-process next to
    the master (the usual topology: control plane on the launch host).
    """

    def __init__(
        self,
        exp_cfg: ExperimentConfig,
        spool_dir: str,
        scheduler_mode: str = "local",
        kv_address: Optional[str] = None,
        worker_env: Optional[Dict[str, str]] = None,
        scheduler_kwargs: Optional[Dict] = None,
    ):
        self.exp_cfg = exp_cfg
        self.spool_dir = spool_dir
        self.scheduler_mode = scheduler_mode
        self.worker_env = worker_env or {}
        self._kv_server = None
        if kv_address is None:
            from areal_tpu.base.name_resolve_kv import KvStoreServer
            from areal_tpu.base import network

            self._kv_server = KvStoreServer(network.gethostip(), 0).start()
            kv_address = self._kv_server.address
        self.kv_address = kv_address
        self.name_resolve_cfg = {"backend": "kv", "address": kv_address}
        # Importing the client initializes the scheduler package, whose
        # __init__ registers the cluster backends (gke).
        from areal_tpu.scheduler.client import make_scheduler

        kwargs = dict(scheduler_kwargs or {})
        if scheduler_mode != "local":
            # Cluster job names must be scoped per trial: two experiments
            # sharing a namespace would otherwise collide on worker names
            # (and submit()'s stale-job cleanup would delete the other
            # trial's live workers).
            kwargs.setdefault(
                "name_prefix",
                f"{exp_cfg.experiment_name}-{exp_cfg.trial_name}",
            )
        self._sched = make_scheduler(
            scheduler_mode,
            log_dir=os.path.join(spool_dir, "logs"),
            **kwargs,
        )
        self._job_names: List[str] = []

    def _submit(self, worker_type: str, config) -> str:
        import json as _json
        import pickle

        os.makedirs(self.spool_dir, exist_ok=True)
        cfg_path = os.path.join(
            self.spool_dir, f"{config.worker_name.replace('/', '_')}.pkl"
        )
        with open(cfg_path, "wb") as f:
            pickle.dump(config, f)
        import areal_tpu

        repo_root = os.path.dirname(
            os.path.dirname(os.path.abspath(areal_tpu.__file__))
        )
        env = dict(self.worker_env)
        env["PYTHONPATH"] = (
            repo_root + os.pathsep + env.get(
                "PYTHONPATH", os.environ.get("PYTHONPATH", "")
            )
        ).rstrip(os.pathsep)
        name = self._sched.submit(
            config.worker_name,
            [
                sys.executable, "-m", "areal_tpu.system.worker_main",
                "--worker-type", worker_type,
                "--config", cfg_path,
                "--name-resolve", _json.dumps(self.name_resolve_cfg),
            ],
            env=env,
            cwd=repo_root,
        )
        self._job_names.append(name)
        return name

    def start_workers(self):
        for cfg in self.exp_cfg.model_workers:
            self._submit("model_worker", cfg)
        for cfg in self.exp_cfg.generation_servers:
            self._submit("generation_server", cfg)
        if self.exp_cfg.gserver_manager is not None:
            self._submit("gserver_manager", self.exp_cfg.gserver_manager)
        for cfg in self.exp_cfg.rollout_workers:
            self._submit("rollout_worker", cfg)

    def check_worker_errors(self):
        from areal_tpu.scheduler.client import JobState

        for n in self._job_names:
            info = self._sched.find(n)
            if info.state in (JobState.FAILED, JobState.CANCELLED):
                log = os.path.join(
                    self.spool_dir, "logs", n.replace("/", "_") + ".log"
                )
                tail = ""
                try:
                    with open(log) as f:
                        tail = f.read()[-3000:]
                except OSError:
                    pass
                raise RuntimeError(f"worker {n} -> {info.state}:\n{tail}")

    def _watchdog(self, stop_event):
        import _thread

        from areal_tpu.scheduler.client import JobState

        while not stop_event.wait(0.5):
            for n in self._job_names:
                if self._sched.find(n).state in (
                    JobState.FAILED, JobState.CANCELLED
                ):
                    logger.error(
                        f"worker {n} failed; interrupting master"
                    )
                    self._watchdog_fired = True
                    _thread.interrupt_main()
                    return

    def run(self) -> Dict:
        """Blocking: start workers via the scheduler, run master inline."""
        import threading

        name_resolve.reconfigure(**self.name_resolve_cfg)
        self.start_workers()
        self._watchdog_fired = False
        user_interrupt = False
        stop_watchdog = threading.Event()
        watchdog = threading.Thread(
            target=self._watchdog, args=(stop_watchdog,), daemon=True
        )
        watchdog.start()

        from areal_tpu.system.master_worker import MasterWorker

        master = MasterWorker()
        try:
            master.configure(
                self.exp_cfg.master,
                experiment_name=self.exp_cfg.experiment_name,
                trial_name=self.exp_cfg.trial_name,
                worker_name="master",
            )
            master.run()
        except KeyboardInterrupt:
            # See LocalController.run: only the watchdog's interrupt is a
            # worker failure; genuine Ctrl-C re-raises untouched.
            if self._watchdog_fired:
                self.check_worker_errors()
                raise RuntimeError(
                    "a worker job failed (state captured by scheduler)"
                )
            user_interrupt = True
            raise
        finally:
            stop_watchdog.set()
            try:
                if not user_interrupt:
                    self.check_worker_errors()
            finally:
                # Always tear down: leaking scheduler jobs + the KV
                # server would collide with a recovery relaunch.
                self.stop()
        return {"global_step": master.step_info.global_step,
                "perf_summary": dict(master.perf_summary)}

    def stop(self):
        self._sched.stop_all()
        if self._kv_server is not None:
            self._kv_server.stop()
            self._kv_server = None

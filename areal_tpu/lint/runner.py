"""Orchestrates the four checkers over a file set and applies the
allowlist. Two passes: parse + collect cross-file facts (loop-only
registries, env-knob uses), then check."""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional, Set

from areal_tpu.lint import blocking_async, env_knobs, loop_only, wire_schema
from areal_tpu.lint.common import (
    Finding,
    Module,
    apply_allowlist,
    iter_py_files,
    parse_allowlist,
    parse_module,
)


@dataclasses.dataclass
class LintConfig:
    root: str  # repo root all finding paths are relative to
    allowlist_path: Optional[str] = None
    env_cfg: Optional[env_knobs.EnvKnobConfig] = None
    # None = auto: dead-knob check runs iff the scan covers the
    # registry module (linting one file must not misreport the whole
    # registry as dead).
    check_dead_knobs: Optional[bool] = None
    wire_constants_rel: str = wire_schema.CONSTANTS_REL
    checkers: Set[str] = dataclasses.field(default_factory=lambda: {
        "loop-only", "blocking-async", "env-knob", "wire-schema",
    })


def run_lint(paths: List[str], cfg: LintConfig) -> List[Finding]:
    files = iter_py_files(paths)
    modules: List[Module] = []
    findings: List[Finding] = []
    for path in files:
        mod, err = parse_module(path, cfg.root)
        if err is not None:
            findings.append(err)
        if mod is not None:
            modules.append(mod)

    env_cfg = cfg.env_cfg
    if env_cfg is None and "env-knob" in cfg.checkers:
        env_cfg = env_knobs.default_config()

    # -- pass 1: cross-file facts ---------------------------------------
    registries: Dict[str, Dict] = {}  # rel -> registry
    hint_map: Dict[str, Set[str]] = {}  # attr -> instance hint names
    registry_mod: Optional[Module] = None
    for mod in modules:
        if "loop-only" in cfg.checkers:
            reg = loop_only.collect_registry(mod)
            if reg:
                registries[mod.rel] = reg
                for spec in reg.values():
                    if not isinstance(spec, dict):
                        continue
                    for attr in spec.get("attrs", ()):
                        hint_map.setdefault(attr, set()).update(
                            spec.get("instance_hints", ())
                        )
        if env_cfg is not None and mod.rel == env_cfg.registry_rel:
            registry_mod = mod

    # -- pass 2: checks --------------------------------------------------
    env_uses: Dict[str, int] = {}
    for mod in modules:
        if "blocking-async" in cfg.checkers:
            findings.extend(blocking_async.check(mod))
        if "wire-schema" in cfg.checkers:
            findings.extend(wire_schema.check(mod, cfg.wire_constants_rel))
        if "env-knob" in cfg.checkers and env_cfg is not None:
            findings.extend(env_knobs.check(mod, env_cfg, env_uses))
        if "loop-only" in cfg.checkers:
            if mod.rel in registries:
                findings.extend(loop_only.check_declaring_module(
                    mod, registries[mod.rel]
                ))
            elif registries:
                findings.extend(loop_only.check_instance_hints(
                    mod, hint_map
                ))

    if "env-knob" in cfg.checkers and env_cfg is not None:
        dead = cfg.check_dead_knobs
        if dead is None:
            dead = registry_mod is not None
        if dead:
            decl_lines = (
                env_knobs.registry_decl_lines(registry_mod)
                if registry_mod is not None else {}
            )
            findings.extend(
                env_knobs.check_dead(env_cfg, env_uses, decl_lines)
            )

    # -- allowlist -------------------------------------------------------
    if cfg.allowlist_path and os.path.exists(cfg.allowlist_path):
        entries = parse_allowlist(cfg.allowlist_path)
        rel = os.path.relpath(
            os.path.abspath(cfg.allowlist_path), cfg.root
        ).replace(os.sep, "/")
        findings = apply_allowlist(
            findings, entries, rel,
            scanned_rels={m.rel for m in modules},
            active_checkers=set(cfg.checkers) | {"parse", "allowlist"},
        )

    findings.sort(key=lambda f: (f.path, f.line, f.checker))
    return findings

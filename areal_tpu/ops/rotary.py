"""Rotary position embeddings with scaling variants.

Replaces the reference's torch rotary module
(realhf/impl/model/modules/rotary.py) with position-indexed jnp: because
batches are packed, every token carries an explicit position id and the
embedding is gathered per token rather than sliced per sequence.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp
import numpy as np


SUPPORTED_ROPE_TYPES = (None, "default", "linear", "llama3")


def rotary_inv_freq(
    head_dim: int,
    base: float = 10000.0,
    scaling: Optional[float] = None,
    scaling_type: Optional[str] = None,
    scaling_params: Optional[dict] = None,
) -> np.ndarray:
    if scaling_type not in SUPPORTED_ROPE_TYPES:
        raise NotImplementedError(
            f"rope scaling type {scaling_type!r} not supported "
            f"(supported: {SUPPORTED_ROPE_TYPES})"
        )
    inv_freq = 1.0 / (base ** (np.arange(0, head_dim, 2, dtype=np.float64) / head_dim))
    if scaling_type == "linear" and scaling:
        inv_freq = inv_freq / scaling
    elif scaling_type == "llama3" and scaling:
        # llama3-style NTK frequency interpolation: low frequencies scaled,
        # high frequencies kept, smooth ramp between. Factors come from the
        # checkpoint's rope_scaling config.
        p = scaling_params or {}
        low_freq_factor = p.get("low_freq_factor", 1.0)
        high_freq_factor = p.get("high_freq_factor", 4.0)
        orig_ctx = p.get("original_max_position_embeddings", 8192)
        wavelen = 2 * np.pi / inv_freq
        low_wl = orig_ctx / low_freq_factor
        high_wl = orig_ctx / high_freq_factor
        scaled = inv_freq / scaling
        smooth = (orig_ctx / wavelen - low_freq_factor) / (
            high_freq_factor - low_freq_factor
        )
        smoothed = (1 - smooth) * scaled + smooth * inv_freq
        inv_freq = np.where(
            wavelen < high_wl, inv_freq, np.where(wavelen > low_wl, scaled, smoothed)
        )
    return inv_freq.astype(np.float32)


def rotary_cos_sin(positions: jnp.ndarray, inv_freq: jnp.ndarray):
    """cos/sin of shape (*positions.shape, head_dim/2), fp32."""
    freqs = positions.astype(jnp.float32)[..., None] * inv_freq[None, :]
    return jnp.cos(freqs), jnp.sin(freqs)


def apply_rotary(
    x: jnp.ndarray,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
    interleaved: bool = False,
) -> jnp.ndarray:
    """Apply rotary embedding.

    x: (..., n_heads, head_dim); cos/sin: (..., head_dim/2) broadcast over heads.
    Non-interleaved (HF neox style): pairs are (x[:d/2], x[d/2:]).
    """
    dtype = x.dtype
    x = x.astype(jnp.float32)
    cos = cos[..., None, :]
    sin = sin[..., None, :]
    d2 = x.shape[-1] // 2
    if interleaved:
        x1 = x[..., 0::2]
        x2 = x[..., 1::2]
        o1 = x1 * cos - x2 * sin
        o2 = x2 * cos + x1 * sin
        out = jnp.stack([o1, o2], axis=-1).reshape(x.shape)
    else:
        x1 = x[..., :d2]
        x2 = x[..., d2:]
        o1 = x1 * cos - x2 * sin
        o2 = x2 * cos + x1 * sin
        out = jnp.concatenate([o1, o2], axis=-1)
    return out.astype(dtype)

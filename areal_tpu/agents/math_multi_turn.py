"""Multi-turn math RL agent.

Counterpart of the reference's MathMultiTurnAgent
(realhf/impl/agent/math_multi_turn_agent.py:23-246): the agent generates
an answer, the environment verifies it, and verbal feedback is appended
to the conversation before the next turn regenerates. Each turn's full
sequence (conversation so far + new answer) becomes one packed sequence
of the trajectory sample; per-turn rewards are backward-accumulated with
`turn_level_discount` (reference :211-215).

Differences from the reference, by design:
- `stop_on_success=True` (default) ends the episode at the first correct
  answer instead of always running `num_turns` turns — set it False for
  reference-identical rollouts.
- logprobs use this framework's shifted frame (logprob of the token at
  position p stored at p-1, seqlens equal to sequence lengths), matching
  MathSingleStepAgent and the PPO interface.

Requires >1 generation request per episode — the rollout worker's
`service_gen` loops for exactly this (system/rollout_worker.py).
"""

from __future__ import annotations

import asyncio
from typing import Any, List, Optional

import numpy as np

from areal_tpu.api.agent_api import Agent, register_agent
from areal_tpu.api.data_api import SequenceSample
from areal_tpu.api.env_api import EnvironmentService
from areal_tpu.api.model_api import BundledGenerationOutputs, GenerationHyperparameters
from areal_tpu.base import logging

logger = logging.getLogger("math_multi_turn_agent")

CORRECT_FEEDBACK = "Congratulations! You are correct!"
WRONG_FEEDBACK = "Unfortunately your answer is wrong. Let's try again."


class MathMultiTurnAgent(Agent):
    def __init__(
        self,
        gconfig: Optional[GenerationHyperparameters] = None,
        tokenizer: Any = None,
        num_turns: int = 4,
        turn_level_discount: float = 1.0,
        reward_scaling: float = 1.0,
        reward_bias: float = 0.0,
        correct_reward: float = 1.0,
        wrong_reward: float = -1.0,
        stop_on_success: bool = True,
        **gconfig_kwargs,
    ):
        if gconfig is None:
            gconfig = GenerationHyperparameters(**gconfig_kwargs)
        elif isinstance(gconfig, dict):
            gconfig = GenerationHyperparameters(**gconfig)
        # One sequence per turn; grouping happens across episodes.
        self.gconfig = gconfig.new(n=1)
        self.tokenizer = tokenizer
        self.num_turns = num_turns
        self.turn_level_discount = turn_level_discount
        self.reward_scaling = reward_scaling
        self.reward_bias = reward_bias
        self.correct_reward = correct_reward
        self.wrong_reward = wrong_reward
        self.stop_on_success = stop_on_success

    def _encode_feedback(self, text: str) -> List[int]:
        tok = self.tokenizer
        if hasattr(tok, "apply_chat_template"):
            try:
                rendered = "\n" + tok.apply_chat_template(
                    [dict(content=text, role="user")],
                    add_generation_prompt=True,
                    tokenize=False,
                )
                return tok(rendered, add_special_tokens=False)["input_ids"]
            except Exception:  # tokenizer without a chat template
                pass
        return tok("\n" + text + "\n", add_special_tokens=False)["input_ids"]

    async def collect_trajectory(
        self,
        prompt: SequenceSample,
        env: EnvironmentService,
        obs_queue: asyncio.Queue,
        act_queue: asyncio.Queue,
    ) -> List[SequenceSample]:
        await env.reset()
        assert prompt.bs == 1
        qid = prompt.ids[0]
        token_ids = np.asarray(prompt.data["packed_prompts"]).tolist()
        task = (prompt.metadata.get("tasks") or ["math"])[0]
        answer_info = (prompt.metadata.get("solutions") or [None])[0]

        turn_seqs: List[List[int]] = []
        turn_lps: List[np.ndarray] = []
        turn_prompt_lens: List[int] = []
        turn_no_eos: List[bool] = []
        turn_rewards: List[float] = []
        successes: List[bool] = []
        v_start: List[int] = []
        v_end: List[int] = []

        for _turn in range(self.num_turns):
            await obs_queue.put((qid, token_ids, self.gconfig))
            bundle: BundledGenerationOutputs = await act_queue.get()
            seq = list(bundle.seqs[0])
            plen = bundle.prompt_len

            answer = self.tokenizer.decode(seq[plen:])
            ok_list, *_ = await env.step((qid, [answer], task, answer_info))
            ok = bool(ok_list[0])
            successes.append(ok)

            turn_seqs.append(seq)
            turn_lps.append(np.asarray(bundle.logprobs[0], np.float32))
            turn_prompt_lens.append(plen)
            turn_no_eos.append(bool(bundle.no_eos[0]))
            turn_rewards.append(
                (self.correct_reward if ok else self.wrong_reward)
                * self.reward_scaling
                + self.reward_bias
            )
            v_start.append(min(bundle.version_start))
            v_end.append(max(bundle.version_end))

            if ok and self.stop_on_success:
                break
            feedback = CORRECT_FEEDBACK if ok else WRONG_FEEDBACK
            token_ids = seq + self._encode_feedback(feedback)

        # Turn-level discounted returns (reference :211-215).
        for i in reversed(range(len(turn_rewards) - 1)):
            turn_rewards[i] += self.turn_level_discount * turn_rewards[i + 1]

        n = len(turn_seqs)
        seq_lens = [len(s) for s in turn_seqs]
        pmask = np.concatenate(
            [
                np.concatenate(
                    [np.ones(p, np.int64), np.zeros(l - p, np.int64)]
                )
                for l, p in zip(seq_lens, turn_prompt_lens)
            ]
        )
        shifted_lps = []
        for seq, lp, plen in zip(turn_seqs, turn_lps, turn_prompt_lens):
            out_lp = np.asarray(lp[plen:], np.float32)
            full = np.zeros(len(seq), np.float32)
            full[plen - 1 : len(seq) - 1] = out_lp
            shifted_lps.append(full)

        sample = SequenceSample(
            ids=[qid],
            keys={
                "packed_input_ids", "prompt_mask", "packed_logprobs",
                "seq_no_eos_mask", "rewards",
            },
            data={
                "packed_input_ids": np.concatenate(
                    [np.asarray(s, np.int32) for s in turn_seqs]
                ),
                "prompt_mask": pmask,
                "packed_logprobs": np.concatenate(shifted_lps),
                "seq_no_eos_mask": np.asarray(
                    [1.0 if x else 0.0 for x in turn_no_eos], np.float32
                ),
                "rewards": np.asarray(turn_rewards, np.float32),
            },
            seqlens={
                "packed_input_ids": [seq_lens],
                "prompt_mask": [seq_lens],
                "packed_logprobs": [seq_lens],
                "seq_no_eos_mask": [[1] * n],
                "rewards": [[1] * n],
            },
            metadata={
                "version_start": [min(v_start)],
                "version_end": [max(v_end)],
                "scores": [float(np.mean(successes))],
                "birth_time": [0],
                # Per-task staleness tag: math rides the TIGHT admission
                # window (AREAL_TASK_STALENESS_WINDOWS).
                "task": [task],
            },
        )
        return [sample]


register_agent("math-multi-turn", MathMultiTurnAgent)

"""Math answer verification: extraction + normalization + equivalence.

Counterpart of the reference's local math grader
(functioncall/math/function/grader.py, realhf/impl/dataset/math_parser.py)
built from scratch: extract the final answer (\\boxed{...} or last line),
normalize LaTeX-ish syntax, then test equivalence by exact string match,
numeric comparison, and sympy simplification when available.
"""

from __future__ import annotations

import re
from typing import List, Optional


def extract_boxed(text: str) -> Optional[str]:
    """Last \\boxed{...} / \\fbox{...} content, brace-aware."""
    best = None
    for m in re.finditer(r"\\(?:boxed|fbox)\s*\{", text):
        start = m.end()
        depth = 1
        i = start
        while i < len(text) and depth:
            if text[i] == "{":
                depth += 1
            elif text[i] == "}":
                depth -= 1
            i += 1
        if depth == 0:
            best = text[start : i - 1]
    return best


def extract_answer(text: str) -> Optional[str]:
    boxed = extract_boxed(text)
    if boxed is not None:
        return boxed
    # "The answer is X" patterns (commas allowed: "1,000,000"), else the
    # last number in the text.
    m = re.findall(
        r"(?:answer is|answer:)\s*([^\n;]+?)(?:\.\s|\.$|$)", text, re.IGNORECASE
    )
    if m:
        return m[-1].strip()
    nums = re.findall(r"-?\d+(?:\.\d+)?(?:/\d+)?", text)
    return nums[-1] if nums else None


_LATEX_STRIP = [
    (r"\\left\s*", ""), (r"\\right\s*", ""), (r"\\!", ""), (r"\\,", ""),
    (r"\\;", ""), (r"\\:", ""), (r"~", ""), (r"\\\$", ""), (r"\$", ""),
    (r"\\%", ""), (r"%", ""), (r"\\text\{([^}]*)\}", r"\1"),
    (r"\\mathrm\{([^}]*)\}", r"\1"), (r"\\mbox\{([^}]*)\}", r"\1"),
    (r"\\mathbf\{([^}]*)\}", r"\1"), (r"\\operatorname\{([^}]*)\}", r"\1"),
    (r"\\cdot", "*"), (r"\\times", "*"), (r"\\div", "/"),
    (r"\\pi", "pi"), (r"\\infty", "oo"), (r"dollars?", ""), (r"degrees?", ""),
    (r"\\circ", ""), (r"\^\{\\circ\}", ""), (r"\\ ", " "),
]


def normalize_answer(ans: str) -> str:
    s = ans.strip()
    for pat, rep in _LATEX_STRIP:
        s = re.sub(pat, rep, s)
    # \frac{a}{b} -> (a)/(b); \sqrt{a} -> sqrt(a); x^{y} -> x**(y)
    for _ in range(4):
        s = re.sub(r"\\[dt]?frac\{([^{}]*)\}\{([^{}]*)\}", r"((\1)/(\2))", s)
        s = re.sub(r"\\[dt]?frac(\d)(\d)", r"((\1)/(\2))", s)
        s = re.sub(r"\\sqrt\{([^{}]*)\}", r"sqrt(\1)", s)
        s = re.sub(r"\\sqrt(\d)", r"sqrt(\1)", s)
        s = re.sub(r"\^\{([^{}]*)\}", r"**(\1)", s)
    s = s.replace("^", "**")
    s = s.replace("{", "(").replace("}", ")")
    s = re.sub(r"\\([a-zA-Z]+)", r"\1", s)  # remaining latex commands
    s = re.sub(r"\s+", "", s)
    s = s.rstrip(".").lstrip("+")
    # 1,234 -> 1234 (but keep tuple-like "(1,2)")
    if "(" not in s and "[" not in s:
        s = re.sub(r"(\d),(\d)", r"\1\2", s)
    return s.lower()


def _to_number(s: str) -> Optional[float]:
    try:
        return float(s)
    except ValueError:
        pass
    m = re.fullmatch(r"\(?\(?(-?\d+(?:\.\d+)?)\)?/\(?(-?\d+(?:\.\d+)?)\)?\)?", s)
    if m:
        denom = float(m.group(2))
        if denom != 0:
            return float(m.group(1)) / denom
    return None


def _sympy_equal(a: str, b: str) -> bool:
    try:
        import sympy
        from sympy.parsing.sympy_parser import (
            implicit_multiplication_application,
            parse_expr,
            standard_transformations,
        )

        tf = standard_transformations + (implicit_multiplication_application,)
        ea = parse_expr(a, transformations=tf, evaluate=True)
        eb = parse_expr(b, transformations=tf, evaluate=True)
        return bool(sympy.simplify(ea - eb) == 0)
    except Exception:
        return False


def answers_equal(given: str, reference: str, tol: float = 1e-6) -> bool:
    ng, nr = normalize_answer(given), normalize_answer(reference)
    if not ng and not nr:
        return True
    if ng == nr:
        return True
    fg, fr = _to_number(ng), _to_number(nr)
    if fg is not None and fr is not None:
        return abs(fg - fr) <= tol * max(1.0, abs(fr))
    # Tuple/set-like answers: compare element-wise.
    if ("," in ng) and ("," in nr):
        pg = [p for p in re.split(r"[(),\[\]]", ng) if p]
        pr = [p for p in re.split(r"[(),\[\]]", nr) if p]
        if len(pg) == len(pr):
            return all(answers_equal(x, y, tol) for x, y in zip(pg, pr))
    return _sympy_equal(ng, nr)


def grade_answer(solution_text: str, reference_answer: str) -> bool:
    """True if the final answer in `solution_text` matches the reference."""
    ans = extract_answer(solution_text)
    if ans is None:
        return False
    refs: List[str] = (
        [reference_answer] if isinstance(reference_answer, str) else list(reference_answer)
    )
    return any(answers_equal(ans, r) for r in refs)

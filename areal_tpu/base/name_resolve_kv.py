"""Networked KV name-resolve backend: a lease-based TCP service.

Counterpart of the reference's production backends — etcd3 with leases +
keepalive (realhf/base/name_resolve.py:560) and the Ray-actor KV
(:1031). Those exist because NFS polling doesn't give reliable liveness
on real clusters; the same holds for TPU pods, where there is typically
no etcd — so the service itself ships with the framework:

- `KvStoreServer`: a threaded TCP server holding the name table with
  per-key TTL leases. Keys with a lease expire unless refreshed; expiry
  is enforced on read and by a background sweeper (so watchers see
  dead workers disappear, the etcd lease semantic). Runs standalone
  (`python -m areal_tpu.base.name_resolve_kv --port 2379`) — typically
  next to the experiment controller — or in-process for tests.
- `KvNameRecordRepository`: the client, implementing NameRecordRepository
  over a persistent connection with newline-JSON framing, automatic
  reconnect, and a keepalive thread that refreshes this process's leases
  every ttl/3 (the etcd lease-refresh loop).

Protocol: one JSON object per line; request {"op", "name", ...} ->
response {"ok": true, ...} | {"ok": false, "err": "exists"|"not_found"}.
"""

from __future__ import annotations

import argparse
import json
import socket
import socketserver
import threading
import time
from typing import Dict, List, Optional, Tuple

from areal_tpu.base import logging
from areal_tpu.base.name_resolve import (
    NameEntryExistsError,
    NameEntryNotFoundError,
    NameRecordRepository,
)

logger = logging.getLogger("name_resolve_kv")


# ----------------------------------------------------------------------
# Server
# ----------------------------------------------------------------------


class _Store:
    def __init__(self):
        # name -> (value, ttl seconds or None, expire_at monotonic or None)
        self._d: Dict[str, Tuple[str, Optional[float], Optional[float]]] = {}
        self._lock = threading.Lock()

    def _expired(self, rec, now) -> bool:
        return rec[2] is not None and now > rec[2]

    def _sweep_locked(self, now):
        dead = [k for k, rec in self._d.items() if self._expired(rec, now)]
        for k in dead:
            del self._d[k]

    def handle(self, req: Dict) -> Dict:
        op = req.get("op")
        name = (req.get("name") or "").rstrip("/")
        now = time.monotonic()
        with self._lock:
            self._sweep_locked(now)
            if op == "add":
                if name in self._d and not req.get("replace"):
                    return {"ok": False, "err": "exists"}
                ttl = req.get("ttl")
                # Expire at now + ttl (etcd lease semantics): clients
                # refresh every ttl/3, so a live holder gets ~3 refresh
                # attempts before its lease lapses, while a dead one
                # disappears within one ttl instead of three.
                self._d[name] = (
                    str(req["value"]), ttl, now + ttl if ttl else None
                )
                return {"ok": True}
            if op == "get":
                rec = self._d.get(name)
                if rec is None:
                    return {"ok": False, "err": "not_found"}
                return {"ok": True, "value": rec[0]}
            if op == "delete":
                if name not in self._d:
                    return {"ok": False, "err": "not_found"}
                del self._d[name]
                return {"ok": True}
            if op == "clear_subtree":
                for k in [k for k in self._d
                          if k == name or k.startswith(name + "/")]:
                    del self._d[k]
                return {"ok": True}
            if op == "find_subtree":
                keys = sorted(k for k in self._d
                              if k == name or k.startswith(name + "/"))
                return {"ok": True, "keys": keys}
            if op == "get_subtree":
                keys = sorted(k for k in self._d
                              if k == name or k.startswith(name + "/"))
                return {"ok": True, "values": [self._d[k][0] for k in keys]}
            if op == "keepalive":
                refreshed = []
                for k in req.get("names", []):
                    rec = self._d.get(k)
                    if rec is not None and rec[1]:
                        self._d[k] = (rec[0], rec[1], now + rec[1])
                        refreshed.append(k)
                return {"ok": True, "refreshed": refreshed}
            if op == "ping":
                return {"ok": True, "n_keys": len(self._d)}
        return {"ok": False, "err": f"bad op {op!r}"}


class KvStoreServer:
    """Threaded TCP server around a _Store (one thread per connection,
    keys swept lazily under the store lock)."""

    def __init__(self, host: str = "0.0.0.0", port: int = 0):
        store = self._store = _Store()

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                while True:
                    line = self.rfile.readline()
                    if not line:
                        return
                    try:
                        resp = store.handle(json.loads(line))
                    except Exception as e:  # malformed request
                        resp = {"ok": False, "err": repr(e)}
                    self.wfile.write((json.dumps(resp) + "\n").encode())

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._server = Server((host, port), Handler)
        self.address = (
            f"{self._server.server_address[0]}:{self._server.server_address[1]}"
        )
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self._server.server_address[1]

    def start(self):
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def serve_forever(self):
        self._server.serve_forever()

    def stop(self):
        self._server.shutdown()
        self._server.server_close()


# ----------------------------------------------------------------------
# Client repository
# ----------------------------------------------------------------------


class KvNameRecordRepository(NameRecordRepository):
    """NameRecordRepository over the KV service (etcd-equivalent client)."""

    def __init__(self, address: str, connect_timeout: float = 10.0):
        host, port = address.rsplit(":", 1)
        self._addr = (host, int(port))
        self._connect_timeout = connect_timeout
        self._sock: Optional[socket.socket] = None
        self._sock_file = None
        self._lock = threading.Lock()
        self._my_keys: set = set()
        self._leased: Dict[str, float] = {}  # name -> ttl
        self._stop = threading.Event()
        self._kick = threading.Event()
        self._keepalive_thread: Optional[threading.Thread] = None

    def _connect(self):
        deadline = time.monotonic() + self._connect_timeout
        last = None
        while time.monotonic() < deadline:
            try:
                s = socket.create_connection(self._addr, timeout=5.0)
                s.settimeout(10.0)
                self._sock = s
                self._sock_file = s.makefile("rb")
                return
            except OSError as e:
                last = e
                time.sleep(0.2)
        raise ConnectionError(f"cannot reach KV service at {self._addr}: {last!r}")

    def _call(self, req: Dict) -> Dict:
        with self._lock:
            for attempt in (0, 1):  # one transparent reconnect
                if self._sock is None:
                    self._connect()
                try:
                    self._sock.sendall((json.dumps(req) + "\n").encode())
                    line = self._sock_file.readline()
                    if not line:
                        raise ConnectionError("KV service closed connection")
                    return json.loads(line)
                except (OSError, ConnectionError, json.JSONDecodeError):
                    self._close_socket()
                    if attempt:
                        raise
        raise AssertionError("unreachable")

    def _close_socket(self):
        try:
            if self._sock_file is not None:
                self._sock_file.close()
            if self._sock is not None:
                self._sock.close()
        except OSError:
            pass
        self._sock = None
        self._sock_file = None

    def _ensure_keepalive(self):
        # Wake the refresher so it re-derives its period: a new lease with
        # a smaller TTL than the current period would otherwise expire
        # before the next tick.
        self._kick.set()
        if self._keepalive_thread is not None:
            return

        def _loop():
            while True:
                ttls = list(self._leased.values())
                period = max(min(ttls) / 3, 0.2) if ttls else 1.0
                kicked = self._kick.wait(period)
                if self._stop.is_set():
                    return
                if kicked:
                    self._kick.clear()
                names = list(self._leased)
                if not names:
                    continue
                try:
                    self._call({"op": "keepalive", "names": names})
                except (ConnectionError, OSError):
                    pass  # reconnect happens on the next call

        self._keepalive_thread = threading.Thread(target=_loop, daemon=True)
        self._keepalive_thread.start()

    # -- NameRecordRepository ------------------------------------------

    def add(self, name, value, delete_on_exit=True, keepalive_ttl=None,
            replace=False):
        name = name.rstrip("/")
        req = {"op": "add", "name": name, "value": str(value),
               "replace": bool(replace)}
        if keepalive_ttl is not None:
            req["ttl"] = float(keepalive_ttl)
        resp = self._call(req)
        if not resp["ok"]:
            # _call transparently retries once after a dropped connection;
            # if the FIRST send landed, the retry of this non-idempotent
            # add sees its own key. Confirm by value before treating a
            # successful registration as a conflict.
            try:
                if self.get(name) == str(value):
                    resp = {"ok": True}
            except NameEntryNotFoundError:
                pass
            if not resp["ok"]:
                raise NameEntryExistsError(name)
        if delete_on_exit:
            self._my_keys.add(name)
        if keepalive_ttl is not None:
            self._leased[name] = float(keepalive_ttl)
            self._ensure_keepalive()

    def delete(self, name):
        name = name.rstrip("/")
        resp = self._call({"op": "delete", "name": name})
        self._my_keys.discard(name)
        self._leased.pop(name, None)
        if not resp["ok"]:
            raise NameEntryNotFoundError(name)

    def clear_subtree(self, name_root):
        self._call({"op": "clear_subtree", "name": name_root.rstrip("/")})

    def get(self, name):
        resp = self._call({"op": "get", "name": name.rstrip("/")})
        if not resp["ok"]:
            raise NameEntryNotFoundError(name)
        return resp["value"]

    def get_subtree(self, name_root):
        return self._call(
            {"op": "get_subtree", "name": name_root.rstrip("/")}
        )["values"]

    def find_subtree(self, name_root):
        return self._call(
            {"op": "find_subtree", "name": name_root.rstrip("/")}
        )["keys"]

    def reset(self):
        self._stop.set()
        for name in list(self._my_keys):
            try:
                self.delete(name)
            except (NameEntryNotFoundError, ConnectionError, OSError):
                pass
        self._my_keys.clear()
        self._leased.clear()
        self._close_socket()


if __name__ == "__main__":
    ap = argparse.ArgumentParser(description="areal_tpu name-resolve KV service")
    ap.add_argument("--host", default="0.0.0.0")
    ap.add_argument("--port", type=int, default=2379)
    args = ap.parse_args()
    srv = KvStoreServer(args.host, args.port)
    logger.info(f"name-resolve KV service on {srv.address}")
    srv.serve_forever()

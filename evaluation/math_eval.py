"""Offline math evaluation harness.

Counterpart of the reference's evaluation/math_eval.py: load a saved
checkpoint, greedy/sampled generation over a benchmark jsonl
(prompt + solutions rows), grade with the math verifier, write
results.json with pass@1-style accuracy. Invoked standalone or by the
AutomaticEvaluator per saved checkpoint.

Usage:
    python evaluation/math_eval.py ckpt=/save/actor/step10/dp0 \
        data=/data/aime.jsonl output=/tmp/results.json max_new_tokens=512
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Eval jobs are schedulable onto CPU workers: honor JAX_PLATFORMS before
# any device use (utils/jaxenv.py explains the early-import dance).
from areal_tpu.utils.jaxenv import apply_jax_platform_override

apply_jax_platform_override()

import numpy as np


def evaluate_checkpoint(
    ckpt: str,
    data: str,
    output: str = "",
    max_new_tokens: int = 512,
    greedy: bool = True,
    temperature: float = 1.0,
    n_samples: int = 1,
    max_prompts: int = 0,
    seed: int = 1,
) -> dict:
    import jax

    from areal_tpu.api import data_api
    from areal_tpu.api.model_api import GenerationHyperparameters
    from areal_tpu.functioncall.math_grader import (
        extract_answer,
        grade_answer,
        normalize_answer,
    )
    from areal_tpu.models.generation import generate_tokens
    from areal_tpu.models.hf import load_hf_model

    cfg, params = load_hf_model(ckpt)
    tokenizer = data_api.load_hf_tokenizer(ckpt)

    with open(data) as f:
        rows = [json.loads(l) for l in f if l.strip()]
    if max_prompts:
        rows = rows[:max_prompts]

    g = GenerationHyperparameters(
        max_new_tokens=max_new_tokens, greedy=greedy, temperature=temperature
    )
    prompts = [tokenizer(r["prompt"])["input_ids"] for r in rows]

    n_correct, per_prompt = 0, []
    # Per-prompt sample records for multi-sample metrics (pass@k +
    # majority vote, reference evaluation/rm_maj_eval.py).
    by_prompt: dict = {}
    batch = 8
    for s in range(n_samples):
        rng = jax.random.PRNGKey(seed + s)
        for i in range(0, len(prompts), batch):
            chunk = prompts[i : i + batch]
            outs = generate_tokens(
                params, cfg, chunk, g, jax.random.fold_in(rng, i),
                eos_token_id=tokenizer.eos_token_id,
            )
            for j, o in enumerate(outs):
                row = rows[i + j]
                text = tokenizer.decode(o["output_ids"])
                ok = grade_answer(text, row.get("solutions") or row.get("answers"))
                n_correct += bool(ok)
                qid = str(row.get("query_id", i + j))
                per_prompt.append({"query_id": qid, "correct": bool(ok)})
                ans = extract_answer(text)
                by_prompt.setdefault(qid, []).append(
                    (normalize_answer(ans) if ans else None, bool(ok))
                )

    total = len(prompts) * n_samples
    result = {
        "ckpt": ckpt,
        "data": data,
        "n_prompts": len(prompts),
        "n_samples": n_samples,
        "accuracy": n_correct / max(1, total),
        "details": per_prompt,
    }
    if n_samples > 1:
        # pass@k: any sample correct; maj@k: the most common extracted
        # answer is correct (unextractable answers never win the vote).
        from collections import Counter

        pass_k = maj_k = 0
        for samples in by_prompt.values():
            pass_k += any(ok for _, ok in samples)
            counts = Counter(a for a, _ in samples if a is not None)
            if counts:
                top_ans, _ = counts.most_common(1)[0]
                maj_k += any(ok for a, ok in samples if a == top_ans)
        result["pass_at_k"] = pass_k / max(1, len(by_prompt))
        result["maj_at_k"] = maj_k / max(1, len(by_prompt))
    if output:
        os.makedirs(os.path.dirname(output) or ".", exist_ok=True)
        with open(output, "w") as f:
            json.dump(result, f)
    print(json.dumps({k: v for k, v in result.items() if k != "details"}))
    return result


if __name__ == "__main__":
    kwargs = {}
    for arg in sys.argv[1:]:
        k, v = arg.split("=", 1)
        if k in ("max_new_tokens", "n_samples", "max_prompts", "seed"):
            v = int(v)
        elif k in ("greedy",):
            v = v.lower() in ("1", "true")
        elif k in ("temperature",):
            v = float(v)
        kwargs[k] = v
    evaluate_checkpoint(**kwargs)

"""areal-lint: repo-specific AST static analysis (stdlib ``ast`` only).

Four checkers over the contracts the system already relies on but no
generic tool enforces:

- ``loop-only`` — engine-loop thread discipline (serving.py state that
  has no locks *by design* may only be touched from the loop call
  graph or through the ``_run_on_loop`` door);
- ``blocking-async`` — no blocking work on an asyncio event loop
  (``time.sleep``, sync HTTP, file I/O, subprocess, jax device ops
  inside ``async def`` unless pushed to an executor);
- ``env-knob`` — every ``AREAL_*`` env read goes through
  ``areal_tpu.base.env_registry`` and every registry entry is alive;
- ``wire-schema`` — ``areal-*/vN`` schema strings come from
  ``areal_tpu.base.wire_schemas`` only.

CLI: ``python scripts/areal_lint.py [paths...]``. Gate: a tier-1 test
runs the linter over ``areal_tpu/`` and fails on any unallowlisted
finding. See docs/static_analysis.md.

This package must import neither jax nor anything that does: the gate
asserts ``jax`` stays out of ``sys.modules``.
"""

from areal_tpu.lint.common import Finding, LintConfigError  # noqa: F401
from areal_tpu.lint.runner import LintConfig, run_lint  # noqa: F401

"""Throughput accounting: analytic FLOP formulas and rollout statistics.

Counterpart of the reference's monitor module (realhf/base/monitor.py),
minus CUDA-specific kernel-trace parsing (the TPU analogue is
`jax.profiler` traces, handled in `areal_tpu.utils.profiling`). The FLOP
formulas are the standard dense-transformer counts used to report
TFLOP/s-per-chip.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence


@dataclasses.dataclass
class RolloutStat:
    """Counters the generation manager logs per interval."""

    submitted: int = 0
    accepted: int = 0
    running: int = 0
    gen_tokens: int = 0


def caculuate_llama_forward_flops(
    batch_size: int,
    seqlens: Sequence[int],
    hidden_size: int,
    intermediate_size: int,
    vocab_size: int,
    n_layers: int,
    num_heads: int,
    num_kv_heads: int,
) -> int:
    """Forward FLOPs of a llama-family model over packed sequences.

    Matmul-only accounting (2*m*n*k per matmul), including the quadratic
    attention term computed per-sequence from `seqlens`.
    """
    total_tokens = int(sum(seqlens))
    head_dim = hidden_size // num_heads
    kv_size = head_dim * num_kv_heads
    # Projections: q (h->h), k/v (h->kv), o (h->h)
    attn_proj = 2 * total_tokens * hidden_size * (2 * hidden_size + 2 * kv_size)
    # Attention scores + values: 2 * sum(len^2) * h per each of QK^T and PV
    attn_quad = 4 * sum(int(l) ** 2 for l in seqlens) * hidden_size
    # Gated MLP: gate+up (h->i each), down (i->h)
    mlp = 2 * total_tokens * hidden_size * intermediate_size * 3
    # LM head
    head = 2 * total_tokens * hidden_size * vocab_size
    return n_layers * (attn_proj + attn_quad + mlp) + head


def calculate_llama_train_flops(*args, **kwargs) -> int:
    """Training = forward + backward ~= 3x forward."""
    return 3 * caculuate_llama_forward_flops(*args, **kwargs)


def calculate_llama_gen_flops(
    batch_size: int,
    prompt_lens: Sequence[int],
    gen_len: int,
    hidden_size: int,
    intermediate_size: int,
    vocab_size: int,
    n_layers: int,
    num_heads: int,
    num_kv_heads: int,
) -> int:
    """Generation FLOPs: one prefill over prompts plus `gen_len` decode steps."""
    flops = caculuate_llama_forward_flops(
        batch_size,
        prompt_lens,
        hidden_size,
        intermediate_size,
        vocab_size,
        n_layers,
        num_heads,
        num_kv_heads,
    )
    head_dim = hidden_size // num_heads
    kv_size = head_dim * num_kv_heads
    # Closed form of sum_i sum_j (prompt_j + i) over decode steps i:
    # gen_len * sum(prompt) + B * gen_len*(gen_len-1)/2.
    total_ctx = gen_len * sum(int(l) for l in prompt_lens) + batch_size * (
        gen_len * (gen_len - 1) // 2
    )
    attn_proj = 2 * batch_size * hidden_size * (2 * hidden_size + 2 * kv_size)
    mlp = 2 * batch_size * hidden_size * intermediate_size * 3
    head = 2 * batch_size * hidden_size * vocab_size
    flops += gen_len * (n_layers * (attn_proj + mlp) + head)
    flops += n_layers * 4 * total_ctx * hidden_size
    return flops

"""GkeLauncher: the elastic-fleet Launcher protocol actuated over the
Kubernetes scheduler client, driven against the fake kubectl (pods are
real local processes, so launch/drain/failure paths exercise the whole
submit/find/delete plumbing)."""

import json
import os
import signal
import stat
import sys
import time

import pytest

from areal_tpu.scheduler.client import JobState, make_scheduler
from areal_tpu.scheduler.gke import GkeLauncher

FAKE = os.path.join(os.path.dirname(__file__), "fake_kubectl.py")


@pytest.fixture()
def kubectl(tmp_path, monkeypatch):
    state = tmp_path / "k8s_state"
    monkeypatch.setenv("FAKE_K8S_STATE", str(state))
    wrapper = tmp_path / "kubectl"
    wrapper.write_text(f"#!/bin/sh\nexec {sys.executable} {FAKE} \"$@\"\n")
    wrapper.chmod(wrapper.stat().st_mode | stat.S_IEXEC)
    return str(wrapper), state


def _launcher(cmd, body="import time; time.sleep(60)", env_fn=None):
    client = make_scheduler("gke", kubectl_cmd=cmd)
    return (
        GkeLauncher(
            client,
            cmd_fn=lambda i: [sys.executable, "-c", body],
            env_fn=env_fn,
        ),
        client,
    )


def _wait_state(client, name, want, timeout=10):
    deadline = time.monotonic() + timeout
    while client.find(name).state != want:
        assert time.monotonic() < deadline, f"{name} never reached {want}"
        time.sleep(0.05)


def test_launch_runs_job_and_records_handle(kubectl):
    cmd, _ = kubectl
    launcher, client = _launcher(cmd)
    handle = launcher.launch(0)
    assert handle == "gen-server-0"
    assert launcher.launched == {"gen-server-0": 0}
    _wait_state(client, handle, JobState.RUNNING)
    # A healthy running job is neither reaped nor reported as a failure.
    launcher.reap()
    assert launcher.launched == {"gen-server-0": 0}
    assert launcher.failures == []
    client.stop_all()


def test_launch_passes_env(kubectl):
    cmd, state = kubectl
    launcher, client = _launcher(
        cmd,
        body="import os, sys; sys.exit(0 if os.environ['SRV'] == '3' else 9)",
        env_fn=lambda i: {"SRV": str(i)},
    )
    launcher.launch(3)
    _wait_state(client, "gen-server-3", JobState.COMPLETED)


def test_stop_drains_job(kubectl):
    cmd, _ = kubectl
    launcher, client = _launcher(cmd)
    handle = launcher.launch(1)
    _wait_state(client, handle, JobState.RUNNING)
    launcher.stop(handle)
    assert client.find(handle).state == JobState.NOT_FOUND
    # A drained (deleted) job is forgotten without counting as a failure.
    launcher.reap()
    assert launcher.launched == {}
    assert launcher.failures == []


def test_killed_pod_reaps_as_failure(kubectl):
    cmd, state = kubectl
    launcher, client = _launcher(cmd)
    handle = launcher.launch(2)
    _wait_state(client, handle, JobState.RUNNING)
    with open(state / f"{handle}.json") as f:
        pid = json.load(f)["pid"]
    os.killpg(pid, signal.SIGKILL)
    _wait_state(client, handle, JobState.FAILED)
    launcher.reap()
    assert launcher.launched == {}
    assert launcher.failures == [handle]


def test_completed_job_reaps_without_failure(kubectl):
    cmd, _ = kubectl
    launcher, client = _launcher(cmd, body="print('ok')")
    handle = launcher.launch(0)
    _wait_state(client, handle, JobState.COMPLETED)
    launcher.reap()
    assert launcher.launched == {}
    assert launcher.failures == []


def test_apply_failure_raises_and_leaves_no_handle(tmp_path, monkeypatch):
    """kubectl apply rc!=0 must surface as a raise (fleet controller
    retries the decision next poll) with no phantom bookkeeping."""
    monkeypatch.setenv("FAKE_K8S_STATE", str(tmp_path / "k8s_state"))
    broken = tmp_path / "kubectl"
    broken.write_text("#!/bin/sh\necho 'boom' >&2\nexit 1\n")
    broken.chmod(broken.stat().st_mode | stat.S_IEXEC)
    launcher, _ = _launcher(str(broken))
    with pytest.raises(RuntimeError, match="apply failed"):
        launcher.launch(0)
    assert launcher.launched == {}
    assert launcher.failures == []


def test_stop_swallows_kubectl_errors(tmp_path, monkeypatch):
    monkeypatch.setenv("FAKE_K8S_STATE", str(tmp_path / "k8s_state"))
    missing = str(tmp_path / "no-such-kubectl")
    launcher, _ = _launcher(missing)
    launcher.stop("gen-server-0")  # must not raise

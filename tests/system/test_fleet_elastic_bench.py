"""ISSUE 12 acceptance (bench leg): the `fleet_elastic` phase banks an
attested CPU-proxy record for the elastic control plane — runtime join
peer-vs-origin A/B (join-to-first-routed-token + origin bytes), manager
SIGKILL + lease-takeover recovery, drain-then-leave KV migration —
under sustained PartialRolloutManager load, and `validate_bench.py`
refuses records with ANY failed rollout, a 'peer' join that actually
read origin bytes, or drained prefixes that were lost instead of
migrated.

Time budget (slow lane): ~300 s — one real-process fleet lives through
six server spawns and two manager incarnations. Tier-1 keeps the
validator-teeth test (milliseconds) plus the join/drain e2e and the
fleet_controller units.
"""

import importlib.util
import json
import os

import pytest

from areal_tpu.bench import bank

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

pytestmark = pytest.mark.serial


def _load_validator():
    spec = importlib.util.spec_from_file_location(
        "validate_bench", os.path.join(REPO, "scripts", "validate_bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _fake_record():
    """A well-formed fleet_elastic value (what a healthy run banks)."""
    return {
        "n_servers_start": 2.0,
        "n_servers_max": 4.0,
        "n_servers_end": 3.0,
        "join_peer_ms": 12000.0,
        "join_peer_bootstrap_ms": 300.0,
        "join_peer_source": "peer",
        "join_peer_origin_bytes": 0.0,
        "join_peer_peer_bytes": 427264.0,
        "join_origin_ms": 14000.0,
        "join_origin_source": "origin",
        "join_origin_bytes": 427264.0,
        "killover_recovery_ms": 9000.0,
        "killover_epoch": 2.0,
        "failed_rollouts": 0.0,
        "completed_rollouts": 12.0,
        "drain_held": 3.0,
        "drain_migrated": 3.0,
        "drain_lost": 0.0,
        "drain_resumed_sessions": 3.0,
        "kv_accepted": 3.0,
        "kv_prefix_lost": 0.0,
        "autoscale_n_before": 1.0,
        "autoscale_n_after": 2.0,
        "autoscale_out_actions": 1.0,
        "autoscale_launched": 1.0,
        "autoscale_grow_ms": 8000.0,
        "autoscale_load_failed": 0.0,
    }


def test_validator_teeth_for_fleet_elastic():
    """Tier-1 guard: the schema refuses records that could launder a
    broken control plane into elasticity evidence."""
    validator = _load_validator()
    rec = {"status": "ok", "pass": "measure", "value": _fake_record()}
    assert validator.validate_phase_value("fleet_elastic", rec) == []

    def probs(**edits):
        bad = json.loads(json.dumps(rec))
        bad["value"].update(edits)
        for k, v in list(edits.items()):
            if v is None:
                del bad["value"][k]
        return validator.validate_phase_value("fleet_elastic", bad)

    # ANY failed rollout poisons the record.
    assert any("failed rollout" in p for p in probs(failed_rollouts=1.0))
    assert any("failed rollout" in p for p in probs(failed_rollouts=None))
    # A 'peer' join that fell back to the origin broadcast.
    assert any("origin" in p for p in probs(join_peer_source="origin"))
    assert any(
        "origin" in p for p in probs(join_peer_origin_bytes=1024.0)
    )
    assert any(
        "never engaged" in p for p in probs(join_peer_peer_bytes=0.0)
    )
    # Drained prefixes must migrate, never be lost.
    assert any("lost" in p for p in probs(drain_lost=1.0))
    assert any("lost" in p for p in probs(kv_prefix_lost=2.0))
    assert any("KV wire" in p for p in probs(drain_migrated=0.0))
    # Killover evidence requires a real lease takeover and a join.
    assert any("lease" in p for p in probs(killover_epoch=1.0))
    assert any("grew" in p for p in probs(n_servers_max=2.0))
    # Missing required numerics.
    assert any("killover_recovery_ms" in p
               for p in probs(killover_recovery_ms=None))
    # Autoscale-arm growth must be AUTOSCALER-driven, attributable to
    # the attached launcher, and loss-free — harness-driven growth
    # (more servers than the launcher launched, or zero launcher
    # actions) is refused.
    assert any("scale-out" in p for p in probs(autoscale_out_actions=0.0))
    assert any(
        "harness-driven" in p for p in probs(autoscale_launched=0.0)
    )
    assert any(
        "harness-driven" in p
        for p in probs(autoscale_n_after=3.0, autoscale_launched=1.0)
    )
    assert any("never grew" in p for p in probs(autoscale_n_after=1.0))
    assert any(
        "loss-free" in p for p in probs(autoscale_load_failed=2.0)
    )
    assert any(
        "autoscale_load_failed" in p
        for p in probs(autoscale_load_failed=None)
    )


@pytest.mark.slow  # ~300 s: one fleet, six server spawns, two manager
# incarnations; tier-1 keeps the validator teeth + e2e + units.
@pytest.mark.timeout(1200)
def test_fleet_elastic_banks_and_validates(tmp_path, monkeypatch):
    b = str(tmp_path / "bank")
    monkeypatch.setenv("AREAL_BENCH_BANK", b)
    from areal_tpu.bench.workloads import fleet_elastic_phase

    val = fleet_elastic_phase("measure")
    path = bank.write_record(
        bank.make_record("fleet_elastic", "measure", "ok", value=val), b
    )
    with open(path) as f:
        rec = json.load(f)
    bank.validate_record(rec)
    assert rec["attestation"]["platform"] == "cpu"
    assert rec["attestation"]["driver_verified"] is False

    validator = _load_validator()
    assert validator.validate_phase_value("fleet_elastic", rec) == []
    assert validator.validate_bank_dir(b) == []

"""Controller watchdog: restart a single failed worker role in place
(observed via the health registry) without touching the others; escalate
once the per-worker budget is spent."""

import os
import signal
import time
import uuid

import pytest

from areal_tpu.api.system_api import ExperimentConfig
from areal_tpu.base import name_resolve
from areal_tpu.base.health import HealthRegistry
from areal_tpu.system.controller import LocalController
from tests.system.chaos_workers import SleeperConfig
from tests import fixtures

pytestmark = pytest.mark.chaos

SLEEPER = "tests.system.chaos_workers:SleeperWorker"


def _wait_until(cond, timeout=20.0, interval=0.1, msg="condition"):
    timeout = fixtures.scale_timeout(timeout)
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


def _controller(tmp_path, exp, trial, extra_env=None, max_restarts=1):
    cfg = ExperimentConfig(experiment_name=exp, trial_name=trial, master=None)
    env = {"JAX_PLATFORMS": "cpu", "AREAL_HEALTH_TTL": "0.3"}
    env.update(extra_env or {})
    ctl = LocalController(
        cfg,
        name_resolve_cfg={
            "backend": "nfs",
            "record_root": str(tmp_path / "name_resolve"),
        },
        worker_env=env,
        max_worker_restarts=max_restarts,
        restartable_roles={SLEEPER},
    )
    name_resolve.reconfigure(**ctl.name_resolve_cfg)
    return ctl


def test_watchdog_restarts_single_killed_worker(tmp_path):
    exp, trial = f"restart-{uuid.uuid4().hex[:6]}", "t0"
    ctl = _controller(tmp_path, exp, trial, max_restarts=1)
    escalations = []
    ctl._escalate = lambda why: escalations.append(why)
    try:
        ctl._spawn(SLEEPER, SleeperConfig(exp, trial, 0))
        ctl._spawn(SLEEPER, SleeperConfig(exp, trial, 1))
        registry = HealthRegistry(exp, trial)
        _wait_until(
            lambda: {"sleeper/0", "sleeper/1"} <= set(registry.snapshot()),
            msg="both workers heartbeating",
        )
        pid0 = ctl._workers["sleeper/0"].proc.pid
        pid1 = ctl._workers["sleeper/1"].proc.pid

        os.kill(pid0, signal.SIGKILL)

        def supervise_and_restarted():
            ctl.supervise_once(registry)
            return ctl._workers["sleeper/0"].restarts == 1

        _wait_until(supervise_and_restarted, msg="restart of sleeper/0")
        rec0 = ctl._workers["sleeper/0"]
        assert rec0.proc.pid != pid0 and rec0.proc.is_alive()
        # The sibling fault domain was never touched.
        rec1 = ctl._workers["sleeper/1"]
        assert rec1.proc.pid == pid1 and rec1.proc.is_alive()
        assert escalations == []
        # The replacement re-registers in the health registry.
        _wait_until(
            lambda: "sleeper/0" in registry.snapshot(),
            msg="restarted worker heartbeating",
        )

        # Budget spent: the next death escalates instead of restarting.
        os.kill(rec0.proc.pid, signal.SIGKILL)

        def supervise_and_escalated():
            ctl.supervise_once(registry)
            return bool(escalations)

        _wait_until(supervise_and_escalated, msg="escalation")
        assert "sleeper/0" in escalations[0]
        # The sibling STILL was not torn down by supervision itself.
        assert rec1.proc.is_alive()
    finally:
        ctl.join(timeout=10)


def test_watchdog_restarts_hung_worker_via_heartbeat(tmp_path):
    """A worker whose process is alive but whose poll loop wedged (armed
    worker.poll hang) stops beating; the supervisor kills and restarts
    it off the stale heartbeat."""
    exp, trial = f"hang-{uuid.uuid4().hex[:6]}", "t0"
    ctl = _controller(
        tmp_path, exp, trial,
        # Hang sleeper/0's poll loop on its 5th iteration.
        extra_env={"AREAL_FAULTS": "worker.poll@sleeper/0=hang:k=5"},
        max_restarts=1,
    )
    escalations = []
    ctl._escalate = lambda why: escalations.append(why)
    try:
        ctl._spawn(SLEEPER, SleeperConfig(exp, trial, 0))
        registry = HealthRegistry(exp, trial)
        _wait_until(
            lambda: "sleeper/0" in registry.snapshot(),
            msg="worker heartbeating",
        )

        def supervise_and_restarted():
            ctl.supervise_once(registry)
            return ctl._workers["sleeper/0"].restarts == 1

        _wait_until(supervise_and_restarted, msg="hang-triggered restart")
        assert escalations == []
        # The replacement (same AREAL_FAULTS, fresh hit counter) beats
        # again before its own injected hang.
        _wait_until(
            lambda: "sleeper/0" in registry.snapshot(),
            msg="restarted worker heartbeating",
        )
    finally:
        ctl.join(timeout=10)

"""Shard-local trainer dump (PR 9 tentpole leg): each process writes
only its addressable shard slabs (no whole-model host gather), and the
virtual full byte stream the slabs encode is BYTE-IDENTICAL to a
contiguous `dump_raw_params` of the same values — so every downstream
consumer (mmap fallback loader, weight-plane origin, TP-sliced shard
manifests) sees exactly the PR 5/8 contract.

All host-side + loopback HTTP on the conftest fake-device CPU mesh.
Time budget: ~10 s total (tiny trees; tier-1 headroom note per PR 7's
discipline)."""

import json
import os

import jax
import ml_dtypes
import numpy as np
import pytest

from areal_tpu.base.topology import MeshSpec
from areal_tpu.parallel.mesh import make_mesh
from areal_tpu.parallel.sharding import shard_params
from areal_tpu.system import weight_transfer as wt

CB = 1 << 12  # 4 KiB chunks: multi-chunk streams on tiny payloads


def make_tree(seed=0):
    """Leaf names chosen so parallel/sharding.py specs engage: wq
    column-parallel, wo row-parallel, embedding/head vocab-parallel,
    norm scale replicated (the per-rank dedup case)."""
    rng = np.random.RandomState(seed)
    L, D, V = 2, 16, 64
    return {
        "embedding": {
            "weight": rng.standard_normal((V, D)).astype(ml_dtypes.bfloat16)
        },
        "head": {
            "weight": rng.standard_normal((D, V)).astype(ml_dtypes.bfloat16)
        },
        "layers": {
            "attn": {
                "wq": rng.standard_normal((L, D, D)).astype(np.float32),
                "wo": rng.standard_normal((L, D, D)).astype(np.float32),
            },
            "norm": {
                "scale": rng.standard_normal((L, D)).astype(np.float32)
            },
        },
    }


def flat_leaves(tree, prefix=()):
    if isinstance(tree, dict):
        for k in sorted(tree):
            yield from flat_leaves(tree[k], prefix + (k,))
    else:
        yield "/".join(prefix), tree


def assert_trees_bitwise_equal(a, b):
    for (pa, la), (pb, lb) in zip(flat_leaves(a), flat_leaves(b)):
        assert pa == pb
        np.testing.assert_array_equal(
            np.asarray(la).view(np.uint8), np.asarray(lb).view(np.uint8),
            err_msg=pa,
        )


def f2_sharded(tree):
    mesh = make_mesh(MeshSpec.parse("f2"), jax.devices()[:2])
    return shard_params(tree, mesh)


def test_sharded_dump_roundtrips_and_matches_contiguous_stream(tmp_path):
    tree = make_tree()
    da, db = str(tmp_path / "full"), str(tmp_path / "shard")
    wt.dump_raw_params(tree, da, version=1, chunk_bytes=CB)
    full_stats = dict(wt.LAST_DUMP_STATS)
    wt.dump_raw_params_sharded(
        f2_sharded(tree), db, version=1, chunk_bytes=CB
    )
    shard_stats = dict(wt.LAST_DUMP_STATS)

    # Manifest advertises the storage; loader reassembles bit-for-bit.
    man = json.load(open(os.path.join(db, "params.json")))
    assert man["storage"] == "sharded" and man["n_slabs"] == 1
    got, v = wt.load_raw_params(db)
    assert v == 1
    assert_trees_bitwise_equal(tree, got)

    # The dump-time chunk sidecar (single-process sharded dumps publish
    # it) hashes the SAME byte stream the contiguous dump wrote.
    ca = json.load(open(os.path.join(da, "params-v1.chunks.json")))
    cb_ = json.load(open(os.path.join(db, "params-v1.chunks.json")))
    assert ca["hashes"] == cb_["hashes"]
    assert ca["total_bytes"] == cb_["total_bytes"]

    # THE high-water claim: the sharded dump never materialized a full
    # leaf (largest leaves halve on the 2-way fsdp mesh).
    assert shard_stats["sharded"] and not full_stats["sharded"]
    assert (
        shard_stats["high_water_bytes"]
        <= 0.6 * full_stats["high_water_bytes"]
    )


def test_sharded_dump_serves_through_weight_plane(tmp_path):
    """Origin over a slab-backed dump: full stream and TP2-sliced shard
    streams are hash-identical to a contiguous dump's, and a ChunkStore
    fetch assembles the exact tree — the PR 5/8 distribution contract
    holds with no host ever holding the whole model."""
    from areal_tpu.engine.weight_client import (
        ChunkStore, assemble_params, fetch_manifest,
    )
    from areal_tpu.system.weight_plane import WeightPlaneSource

    tree = make_tree(seed=3)
    da, db = str(tmp_path / "full"), str(tmp_path / "shard")
    wt.dump_raw_params(tree, da, version=1, chunk_bytes=CB)
    wt.dump_raw_params_sharded(
        f2_sharded(tree), db, version=1, chunk_bytes=CB
    )
    src_a = src_b = None
    try:
        src_a = WeightPlaneSource(da, chunk_bytes=CB).start()
        src_b = WeightPlaneSource(db, chunk_bytes=CB).start()
        man_a = fetch_manifest(src_a.address, version=1)
        man_b = fetch_manifest(src_b.address, version=1)
        assert man_a["hashes"] == man_b["hashes"]
        st = ChunkStore(man_b)
        st.fetch([src_b.address], origin=src_b.address)
        assembled, v = assemble_params(st)
        assert v == 1
        assert_trees_bitwise_equal(tree, assembled)
        # TP-sliced serving streams built over the slabs == over the bin
        # (what a sharded gserver fleet actually fetches).
        for rank in range(2):
            sa = fetch_manifest(
                src_a.address, version=1, tp_degree=2, tp_rank=rank
            )
            sb = fetch_manifest(
                src_b.address, version=1, tp_degree=2, tp_rank=rank
            )
            assert sa["hashes"] == sb["hashes"], f"rank {rank}"
            assert sa["total_bytes"] == sb["total_bytes"]
    finally:
        for s in (src_a, src_b):
            if s is not None:
                s.close()


def test_sharded_dump_gc_removes_slab_artifacts(tmp_path):
    d = str(tmp_path / "dumps")
    sharded = f2_sharded(make_tree())
    for v in (1, 2, 3):
        wt.dump_raw_params_sharded(sharded, d, version=v, chunk_bytes=CB)
    names = os.listdir(d)
    assert not any(n.startswith("params-v1.") for n in names), names
    for v in (2, 3):
        assert wt.slab_bin_name(v, 0) in names
    got, v = wt.load_raw_params(d)
    assert v == 3


def test_sharded_dump_skips_quantized_wire(tmp_path):
    """The int8 wire's per-output-channel scales reduce axis -2, which
    FSDP shards — a per-shard absmax would silently diverge from the
    global convention, so sharded dumps refuse to publish the companion
    (warned, raw wire served) rather than publish wrong scales."""
    d = str(tmp_path / "dumps")
    wt.dump_raw_params_sharded(
        f2_sharded(make_tree()), d, version=1, chunk_bytes=CB,
        wire_dtype="int8",
    )
    names = os.listdir(d)
    assert wt.wire_bin_name(1, "int8") not in names
    man = json.load(open(os.path.join(d, "params.json")))
    assert "wire_dtypes" not in man
    # And the plane 404s an int8-wire manifest request instead of
    # serving garbage scales.
    from areal_tpu.system.weight_plane import chunk_manifest_for_dump

    assert chunk_manifest_for_dump(d, CB, wire="int8") is None
    assert chunk_manifest_for_dump(d, CB) is not None


def test_sharded_dump_missing_slab_reads_as_absent(tmp_path):
    """Multi-process discipline: a manifest that lands before every slab
    (process 0 cannot see sibling hosts' writes) must read as ABSENT —
    retried by load_for_serving / 404'd by the origin — never as a torn
    tree."""
    d = str(tmp_path / "dumps")
    wt.dump_raw_params_sharded(
        f2_sharded(make_tree()), d, version=1, chunk_bytes=CB,
        process_index=0, n_processes=2,
    )
    # Slab 1 (the "other host") never landed: reader refuses.
    assert wt.load_raw_params(d) is None
    from areal_tpu.system.weight_plane import chunk_manifest_for_dump

    assert chunk_manifest_for_dump(d, CB) is None


def test_mirror_dump_version_copies_sharded_artifacts(tmp_path):
    """model_worker's tmpfs fast path mirrors a finished sharded dump at
    the FILE level (a second dump call would re-materialize every shard
    off the device): the mirror must be a complete, readable dump —
    bit-identical leaves — with its own GC applied."""
    tree = make_tree(seed=5)
    d, shm = str(tmp_path / "disk"), str(tmp_path / "shm")
    sharded = f2_sharded(tree)
    for v in (1, 2, 3):
        wt.dump_raw_params_sharded(sharded, d, version=v, chunk_bytes=CB)
        wt.mirror_dump_version(d, shm, v)
    got, v = wt.load_raw_params(shm)
    assert v == 3
    assert_trees_bitwise_equal(tree, got)
    names = os.listdir(shm)
    assert not any(n.startswith("params-v1.") for n in names), names
    assert not any(".tmp." in n for n in names), names


def test_manager_manifest_falls_back_to_raw_wire(tmp_path, monkeypatch):
    """gserver manager + sharded trainer dump + weight_wire_dtype=int8:
    the quantized companion does not exist (sharded dumps never publish
    it), so _fetch_plane_manifest must FALL BACK to the raw wire instead
    of failing every fleet weight update. Budget: ~6 s (the fallback
    spends a capped slice of its retry budget on the configured wire
    first)."""
    from types import SimpleNamespace

    from areal_tpu.system.gserver_manager import GserverManager
    from areal_tpu.system.weight_plane import WeightPlaneSource

    d = str(tmp_path / "dumps")
    wt.dump_raw_params_sharded(
        f2_sharded(make_tree()), d, version=1, chunk_bytes=CB,
        wire_dtype="int8",
    )
    src = WeightPlaneSource(d, chunk_bytes=CB).start()
    try:
        mgr = GserverManager.__new__(GserverManager)
        mgr.cfg = SimpleNamespace(weight_wire_dtype="int8")
        man = mgr._fetch_plane_manifest(src.address, version=1)
        assert man["wire"] == "raw"
        assert man["version"] == 1
    finally:
        src.close()


def test_param_realloc_dst_falls_back_to_raw_dump(tmp_path):
    """model_worker's dst branch: a sharded source writes no
    engine_state.pkl — the destination assembles the raw dump instead
    (weight_transfer.load_raw_params handles sharded storage)."""
    d = str(tmp_path / "dumps")
    tree = make_tree(seed=9)
    wt.dump_raw_params_sharded(
        f2_sharded(tree), d, version=4, chunk_bytes=CB
    )
    assert not os.path.exists(os.path.join(d, "engine_state.pkl"))
    got, v = wt.load_raw_params(d)
    assert v == 4
    assert_trees_bitwise_equal(tree, got)

"""TPU op library. Env-tunable knobs are snapshotted per engine
construction via snapshot_env_tuning()."""


def snapshot_env_tuning():
    """Validate + pin every AREAL_* op-tuning env var (CE chunk size,
    splash block targets) in one place. Engines call this once at
    construction: a mid-run retrace then reuses the pinned settings
    instead of re-reading a possibly-mutated environment, and malformed
    values fail at init instead of inside a jit trace."""
    from areal_tpu.ops import attention, loss

    return {
        "ce_chunk": loss.snapshot_ce_chunk(),
        "splash_blocks": attention.snapshot_splash_blocks(),
    }

"""int8 paged-attention decode kernel (Pallas, TPU).

The stock jax paged-attention kernel handles quantized pools by
broadcasting the per-token scales to full head_dim in f32 BEFORE
pallas_call (jax .../paged_attention_kernel.py:421-431) — materializing
2x the bf16 pool's bytes in HBM per call and streaming 4 B/elem of
scales, which inverts the bandwidth win int8 exists for. This kernel
streams the pool AS STORED:

  data   [Hkv, N, pg, hd] int8
  scales [Hkv, N, pg]     f32   (squeezed; pg is the lane axis)

and dequantizes in VMEM, so HBM traffic per (kv head, page) is
pg*(hd + 4) bytes vs 2*pg*hd for a bf16 pool — ~1.94x less at hd=128.

Design (counterpart of the stock kernel's role, not its structure —
engine/paged.py docstring maps this to SGLang/vLLM paged attention in
the reference, realhf/impl/model/backend/sglang.py):

- Grid (B, Hkv, P) with P minor: flash-style online softmax
  (running max / sum / weighted accumulator in VMEM scratch) across a
  sequence's pages; the output block is written once, on the last page.
- Page blocks are selected straight out of the global pool by
  scalar-prefetched page_indices driving the BlockSpec index_map — no
  gather materialization, and Pallas double-buffers the page DMAs
  against compute automatically.
- GQA runs as one MQA problem per kv head: the q block is that head's
  contiguous group of q heads (same convention as the engine's
  reshape(B, Hkv, group, hd) and ops/attention's splash adoption).
- Pages at or past a sequence's length are skipped via pl.when (their
  DMA still runs; bounding that needs manual copies, deliberately
  avoided for simplicity) and partially-filled pages mask per-token.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# Dequant convention shared with engine/paged.quantize_kv (and the stock
# kernel's quantization_utils): x ~= int8 * scale / 127.5. Re-exported
# from the one dependency-free source of truth (ops/quant_const) —
# structural identity pinned in tests/engine/test_kv_int8.py.
from areal_tpu.ops.quant_const import KV_INT8_MAX  # noqa: F401  (re-export)

_NEG_INF = -1e30  # finite: keeps exp() clean for fully-masked positions
_LANES = 128


def int8_paged_kernel_ok(page_size: int, head_dim: int) -> bool:
    """Shape gate: hd rides the lane axis of the data blocks and pg the
    lane axis of the scales blocks, so both must be 128-aligned (the
    engine defaults — page_size=128, head_dim=128 — qualify)."""
    return head_dim % _LANES == 0 and page_size % _LANES == 0


def _kernel(lengths_ref, pi_ref, q_ref, kd_ref, ks_ref, vd_ref, vs_ref,
            o_ref, m_sc, l_sc, acc_sc):
    b = pl.program_id(0)
    p = pl.program_id(2)
    pg = kd_ref.shape[1]

    @pl.when(p == 0)
    def _init():
        m_sc[...] = jnp.full(m_sc.shape, _NEG_INF, m_sc.dtype)
        l_sc[...] = jnp.zeros_like(l_sc)
        acc_sc[...] = jnp.zeros_like(acc_sc)

    length = lengths_ref[b]

    @pl.when(p * pg < length)
    def _compute():
        q = q_ref[...].astype(jnp.float32)  # [g, hd], pre-scaled
        k = kd_ref[0].astype(jnp.float32) * (
            ks_ref[0] * (1.0 / KV_INT8_MAX))[:, None]  # [pg, hd]
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [g, pg]
        pos = p * pg + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(pos < length, s, _NEG_INF)

        m_prev = m_sc[...][:, :1]  # [g, 1]
        l_prev = l_sc[...][:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)  # [g, 1]
        p_ij = jnp.exp(s - m_new)  # [g, pg]
        v = vd_ref[0].astype(jnp.float32) * (
            vs_ref[0] * (1.0 / KV_INT8_MAX))[:, None]  # [pg, hd]
        l_new = l_prev * alpha + jnp.sum(p_ij, axis=1, keepdims=True)
        acc_sc[...] = acc_sc[...] * alpha + jax.lax.dot_general(
            p_ij, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_sc[...] = jnp.broadcast_to(m_new, m_sc.shape)
        l_sc[...] = jnp.broadcast_to(l_new, l_sc.shape)

    @pl.when(p == pl.num_programs(2) - 1)
    def _finish():
        l = jnp.maximum(l_sc[...][:, :1], 1e-30)
        o_ref[...] = (acc_sc[...] / l).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("interpret",))
def int8_paged_decode_attention(
    qs,  # [B, Hq, hd] float, already multiplied by the softmax scale
    k_pool,  # (data [Hkv, N, pg, hd] int8, scales [Hkv, N, pg] f32)
    v_pool,
    lengths,  # [B] int32, INCLUDING the token written this step
    page_indices,  # [B, P] int32
    interpret: bool = False,
):
    kd, ks = k_pool
    vd, vs = v_pool
    B, Hq, hd = qs.shape
    Hkv, _, pg, _ = kd.shape
    P = page_indices.shape[1]
    g = Hq // Hkv

    def page_map(extra):
        # Block index (h-th kv head, pool page for (b, p)); extra 0s for
        # the in-page dims.
        def f(b, h, p, lr, pr):
            return (h, pr[b, p]) + (0,) * extra

        return f

    def head_map(b, h, p, lr, pr):
        return (b, h, 0)

    return pl.pallas_call(
        _kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(B, Hkv, P),
            in_specs=[
                pl.BlockSpec((None, g, hd), head_map),
                pl.BlockSpec((None, 1, pg, hd), page_map(2)),
                pl.BlockSpec((None, 1, pg), page_map(1)),
                pl.BlockSpec((None, 1, pg, hd), page_map(2)),
                pl.BlockSpec((None, 1, pg), page_map(1)),
            ],
            out_specs=pl.BlockSpec((None, g, hd), head_map),
            scratch_shapes=[
                pltpu.VMEM((g, _LANES), jnp.float32),  # running max
                pltpu.VMEM((g, _LANES), jnp.float32),  # running sum
                pltpu.VMEM((g, hd), jnp.float32),  # output accumulator
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((B, Hq, hd), qs.dtype),
        interpret=interpret,
    )(lengths, page_indices, qs, kd, ks, vd, vs)

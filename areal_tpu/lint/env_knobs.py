"""Checker ``env-knob``: every ``AREAL_*`` env read goes through the
registry, and every registry entry is alive.

Flags, per module:

- a raw ``os.environ[...]`` / ``os.environ.get(...)`` / ``os.getenv``
  read of an ``AREAL_*`` name that is NOT declared in
  ``areal_tpu.base.env_registry`` (undeclared knob — the drift class
  PR 1's snapshotting bolt-on was cleaning up after);
- a raw read of a *declared* name anywhere but the registry module
  itself (migrate to the typed accessor — per-call-site defaults are
  how two sites end up disagreeing);
- an ``env_registry.get_*()`` call naming an undeclared knob;
- a dynamically-built ``AREAL_*`` name (f-string) — unverifiable, so
  disallowed;
- registry entries no scanned module reads (dead knob) — only when the
  scan includes the registry module itself, so linting a file subset
  doesn't misreport the whole registry dead.

Writes (``os.environ[k] = v``, ``setdefault``, ``pop``) are exempt:
arming a child process's env is how knobs propagate.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Set, Tuple

from areal_tpu.lint.common import Finding, Module

CHECKER = "env-knob"

ENV_PREFIX = "AREAL_"
REGISTRY_MODULE = "areal_tpu.base.env_registry"
REGISTRY_REL = "areal_tpu/base/env_registry.py"


@dataclasses.dataclass
class EnvKnobConfig:
    declared: Set[str]
    accessor_names: Tuple[str, ...]
    registry_rel: str = REGISTRY_REL
    registry_module: str = REGISTRY_MODULE


def default_config() -> EnvKnobConfig:
    # Import is deliberate (not AST-parsing the registry): it validates
    # the declarations execute, and the module is stdlib-only so the
    # no-jax gate is preserved.
    from areal_tpu.base import env_registry

    return EnvKnobConfig(
        declared=set(env_registry.REGISTRY),
        accessor_names=tuple(env_registry.ACCESSOR_NAMES),
    )


def _env_read_name(mod: Module, node: ast.AST) -> Optional[ast.AST]:
    """Return the name-expression node of a raw env READ, else None."""
    # os.environ[...] loads
    if isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Load):
        if mod.dotted_name(node.value) == "os.environ":
            return node.slice
        return None
    if not isinstance(node, ast.Call):
        return None
    dotted = mod.dotted_name(node.func)
    if dotted in ("os.environ.get", "os.getenv") and node.args:
        return node.args[0]
    return None


def check(mod: Module, cfg: EnvKnobConfig,
          uses: Dict[str, int]) -> List[Finding]:
    """Per-module pass; records knob uses into ``uses`` for the
    cross-module dead-entry check."""
    findings: List[Finding] = []
    is_registry = mod.rel == cfg.registry_rel

    for node in mod.nodes:
        # -- raw reads ---------------------------------------------------
        name_node = _env_read_name(mod, node)
        if name_node is not None:
            if (
                isinstance(name_node, ast.JoinedStr)
                and name_node.values
                and isinstance(name_node.values[0], ast.Constant)
                and str(name_node.values[0].value).startswith(ENV_PREFIX)
            ):
                findings.append(Finding(
                    mod.rel, node.lineno, CHECKER,
                    "dynamically-built AREAL_* env name: the registry "
                    "cannot verify it; read a declared knob instead",
                ))
                continue
            name = mod.resolve_str(name_node)
            if name is None or not name.startswith(ENV_PREFIX):
                continue
            uses[name] = uses.get(name, 0) + 1
            if name not in cfg.declared:
                findings.append(Finding(
                    mod.rel, node.lineno, CHECKER,
                    f"read of undeclared env knob {name}: declare it in "
                    f"{cfg.registry_module} (name, type, default, doc)",
                ))
            elif not is_registry:
                findings.append(Finding(
                    mod.rel, node.lineno, CHECKER,
                    f"raw os.environ read of declared knob {name}: use "
                    f"the {cfg.registry_module} accessor so the default "
                    f"lives in one place",
                ))
            continue

        # -- accessor calls ----------------------------------------------
        if isinstance(node, ast.Call):
            dotted = mod.dotted_name(node.func)
            if dotted is None or not node.args:
                continue
            head, _, attr = dotted.rpartition(".")
            if attr not in cfg.accessor_names:
                continue
            if head:
                if head != cfg.registry_module and not head.endswith(
                    "env_registry"
                ):
                    continue
            elif not mod.imports.get(attr, "").startswith(
                cfg.registry_module
            ):
                # bare get_int(...) not imported from the registry
                continue
            name = mod.resolve_str(node.args[0])
            if name is None:
                findings.append(Finding(
                    mod.rel, node.lineno, CHECKER,
                    f"{attr}() with a non-literal knob name: the "
                    f"registry checker cannot verify it",
                ))
                continue
            uses[name] = uses.get(name, 0) + 1
            if name not in cfg.declared:
                findings.append(Finding(
                    mod.rel, node.lineno, CHECKER,
                    f"accessor read of undeclared env knob {name}: "
                    f"declare it in {cfg.registry_module}",
                ))
    return findings


def check_dead(cfg: EnvKnobConfig, uses: Dict[str, int],
               registry_lines: Dict[str, int]) -> List[Finding]:
    """Registry entries nothing reads. ``registry_lines`` maps knob
    name -> declaration line in the registry source (best effort)."""
    findings: List[Finding] = []
    for name in sorted(cfg.declared):
        if not uses.get(name):
            findings.append(Finding(
                cfg.registry_rel, registry_lines.get(name, 1), CHECKER,
                f"dead registry entry {name}: no scanned module reads "
                f"it — delete the Knob or the feature that grew past it",
            ))
    return findings


def registry_decl_lines(mod: Module) -> Dict[str, int]:
    """Line of each ``_k("NAME", ...)`` / ``Knob(name=...)`` call in the
    registry module, for anchoring dead-entry findings."""
    lines: Dict[str, int] = {}
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        fname = None
        if isinstance(node.func, ast.Name):
            fname = node.func.id
        elif isinstance(node.func, ast.Attribute):
            fname = node.func.attr
        if fname not in ("_k", "Knob"):
            continue
        name = None
        if node.args and isinstance(node.args[0], ast.Constant):
            name = node.args[0].value
        else:
            for kw in node.keywords:
                if kw.arg == "name" and isinstance(kw.value, ast.Constant):
                    name = kw.value.value
        if isinstance(name, str):
            lines[name] = node.lineno
    return lines

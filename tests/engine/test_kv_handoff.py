"""Disaggregated-serving KV handoff at the engine layer (ISSUE 7
tentpole): a prefill-role engine exports a finished prompt's KV pages as
a versioned blob; a decode-role engine imports it and continues the
sequence with a one-token delta prefill, matching a unified engine's
output token for token.

Time budget: ~15 s (tiny float32 model, shared compiled programs with
the other engine suites).
"""

import numpy as np
import pytest

from areal_tpu.engine import kv_handoff as kvh
from tests.engine.serving_utils import TINY_SERVING_CFG, run_requests

PAGE = 16
PROMPT = [7, 3, 9, 11, 2, 5 + 10, 30, 31] * 4  # 32 tokens = 2 pages


class _Cfg:
    n_layers, n_kv_heads, head_dim = 2, 1, 16


def test_pack_unpack_roundtrip_and_hash_authority():
    rng = np.random.RandomState(0)
    k = rng.randn(2, 1, 5, 16).astype(np.float32)
    v = rng.randn(2, 1, 5, 16).astype(np.float32)
    segments, chunks, payload = kvh.pack_arrays(
        [("k", k), ("v", v)], chunk_bytes=64
    )
    meta = kvh.build_meta("q0", 3, [1, 2, 3, 4, 5], "float32", _Cfg,
                          segments, chunks)
    kvh.check_geometry(meta, _Cfg)
    k2, v2 = kvh.unpack_kv_float(meta, payload)
    np.testing.assert_array_equal(k, k2)
    np.testing.assert_array_equal(v, v2)
    # The hash, not the sender, is the authority: one flipped byte fails.
    bad = bytearray(payload)
    bad[10] ^= 0xFF
    with pytest.raises(kvh.KVHandoffError, match="hash"):
        kvh.unpack_kv_float(meta, bytes(bad))
    # Geometry mismatches are rejected before any device work.
    meta_bad = dict(meta, n_kv_heads=2)
    with pytest.raises(kvh.KVHandoffError, match="geometry"):
        kvh.check_geometry(meta_bad, _Cfg)


def test_fp8_wire_roundtrip_ratio_and_precision():
    """The e4m3 spill/handoff wire (ISSUE 20 satellite): 1-byte data +
    float32 per-(L,H,token) scales — the same layout as the int8 wire,
    so bytes/token stays at its 0.31x of float32 for head_dim 16 — and
    the absmax normalization keeps e4m3 relative precision per vector
    through unpack_kv_float."""
    rng = np.random.RandomState(1)
    # Magnitudes spanning three decades across tokens: the per-token
    # scales, not the e4m3 exponent alone, must absorb the dynamic
    # range.
    mags = np.logspace(-2.0, 1.0, 5)[None, None, :, None]
    k = (rng.randn(2, 1, 5, 16) * mags).astype(np.float32)
    v = (rng.randn(2, 1, 5, 16) * mags).astype(np.float32)
    kw, ks = kvh.quantize_kv_fp8(k)
    vw, vs = kvh.quantize_kv_fp8(v)
    assert kw.dtype.name == "float8_e4m3fn"
    assert ks.dtype == np.float32 and ks.shape == (2, 1, 5)
    segments, chunks, payload = kvh.pack_arrays([
        ("k_data", kw), ("k_scales", ks),
        ("v_data", vw), ("v_scales", vs),
    ])
    _, _, payload_f32 = kvh.pack_arrays([("k", k), ("v", v)])
    assert len(payload) <= 0.32 * len(payload_f32)
    meta = kvh.build_meta("q0", 3, [1, 2, 3, 4, 5], "fp8", _Cfg,
                          segments, chunks)
    k2, v2 = kvh.unpack_kv_float(meta, payload)
    for orig, back in ((k, k2), (v, v2)):
        vec_max = np.max(np.abs(orig), axis=-1, keepdims=True)
        assert np.all(
            np.abs(back - orig) <= 0.07 * np.abs(orig) + 2e-3 * vec_max
        )


def _mk_engine(params, **kw):
    from areal_tpu.engine.serving import ServingEngine

    kw.setdefault("max_batch_size", 2)
    kw.setdefault("max_seq_len", 256)
    kw.setdefault("decode_block_steps", 4)
    kw.setdefault("prompt_bucket", 16)
    kw.setdefault("page_size", PAGE)
    kw.setdefault("eos_token_id", None)
    kw.setdefault("prefix_cache_tokens", 4096)
    e = ServingEngine(TINY_SERVING_CFG, params, **kw)
    e.start()
    return e


@pytest.fixture(scope="module")
def tiny_params():
    import jax

    from areal_tpu.models.transformer import init_params

    return init_params(TINY_SERVING_CFG, jax.random.PRNGKey(4))


def test_export_import_matches_unified_greedy(tiny_params):
    from areal_tpu.engine.serving import GenRequest

    prefill = _mk_engine(tiny_params, seed=1)
    decode = _mk_engine(tiny_params, seed=2)
    unified = _mk_engine(tiny_params, seed=3)
    try:
        # Prefill role: run the prompt to its first sampled token only.
        r1 = run_requests(prefill, [GenRequest(
            qid="s0", input_ids=list(PROMPT), max_new_tokens=1, greedy=True,
        )])["s0"]
        assert len(r1.output_ids) == 1
        first = r1.output_ids[0]

        meta, payload = prefill.export_kv_handoff("s0")
        assert meta["schema"] == kvh.HANDOFF_SCHEMA
        assert meta["n_tokens"] == len(PROMPT)
        assert meta["tokens"] == list(PROMPT)
        assert prefill.kv_exports == 1
        assert prefill.kv_export_bytes == len(payload)
        # The entry was consumed: a second export has nothing to ship.
        with pytest.raises(KeyError):
            prefill.export_kv_handoff("s0")

        # Decode role: import + continue with priority-0 admission.
        decode.import_kv_handoff(meta, payload)
        assert decode.kv_imports == 1
        r2 = run_requests(decode, [GenRequest(
            qid="s0", input_ids=list(PROMPT) + [first],
            max_new_tokens=8, greedy=True, priority=0,
        )])["s0"]
        # The import parked a prefix: admission prefilled only the
        # one-token delta, not the whole prompt.
        assert decode.prefix_cache_hits == 1
        assert decode.prefix_tokens_reused == len(PROMPT)

        # Unified reference: same prompt, same budget, one engine.
        r3 = run_requests(unified, [GenRequest(
            qid="u0", input_ids=list(PROMPT), max_new_tokens=9, greedy=True,
        )])["u0"]
        assert r3.output_ids == [first] + r2.output_ids
    finally:
        for e in (prefill, decode, unified):
            e.stop()


def test_budget_trim_never_evicts_pinned_import(tiny_params):
    """A handoff-import burst must not evict queued continuations'
    parked KV for prefix-cache BUDGET reasons: the oldest parks under a
    burst are exactly the imports whose consumers are queued, and
    evicting one turns its one-token delta into a full re-prefill on
    the serve loop (measured as multi-hundred-ms ITL spikes in the
    serving_disagg bench before the pin)."""
    from areal_tpu.engine.serving import GenRequest

    pre = _mk_engine(tiny_params, seed=7)
    # Budget far below what the burst parks: every trim would fire.
    dec = _mk_engine(tiny_params, seed=8, prefix_cache_tokens=48,
                     kv_pool_tokens=4096)
    try:
        n_sessions = 4
        blobs = {}
        for i in range(n_sessions):
            qid = f"pin{i}"
            r = run_requests(pre, [GenRequest(
                qid=qid, input_ids=list(PROMPT), max_new_tokens=1,
                greedy=True,
            )])[qid]
            blobs[qid] = (*pre.export_kv_handoff(qid), r.output_ids[0])
        # Import everything, then submit all continuations at once: the
        # parks total 4x32=128 tokens against a 48-token budget, so an
        # unpinned trim would evict the oldest imports before their
        # continuations admit.
        for qid, (meta, payload, _) in blobs.items():
            dec.import_kv_handoff(meta, payload)
        res = run_requests(dec, [
            GenRequest(qid=qid, input_ids=list(PROMPT) + [first],
                       max_new_tokens=4, greedy=True, priority=0)
            for qid, (_, _, first) in blobs.items()
        ])
        assert all(len(r.output_ids) == 4 for r in res.values())
        # Every continuation consumed its import as a delta prefill.
        assert dec.prefix_cache_hits == n_sessions
        assert dec.prefix_tokens_reused == n_sessions * len(PROMPT)
    finally:
        pre.stop()
        dec.stop()


def test_import_rejects_version_mismatch_and_int8_wire_decodes(tiny_params):
    from areal_tpu.engine.serving import GenRequest

    prefill = _mk_engine(tiny_params, seed=5)
    decode = _mk_engine(tiny_params, seed=6)
    try:
        r1 = run_requests(prefill, [GenRequest(
            qid="z0", input_ids=list(PROMPT), max_new_tokens=1, greedy=True,
        )])["z0"]
        meta, payload = prefill.export_kv_handoff("z0", compress="int8")
        assert meta["kv_wire"] == "int8"
        # int8 wire is ~half the float32 KV footprint (scales add ~1/hd).
        kv_f32 = 2 * 2 * 1 * len(PROMPT) * 16 * 4  # k+v * L*H*n*hd * 4B
        assert len(payload) < 0.6 * kv_f32

        # A stale version must never park: decoding against KV computed
        # under other weights is silent corruption.
        stale = dict(meta, version=meta["version"] + 1)
        with pytest.raises(kvh.KVHandoffVersionMismatch):
            decode.import_kv_handoff(stale, payload)
        assert decode.kv_imports == 0

        decode.import_kv_handoff(meta, payload)
        r2 = run_requests(decode, [GenRequest(
            qid="z0", input_ids=list(PROMPT) + [r1.output_ids[0]],
            max_new_tokens=4, greedy=True, priority=0,
        )])["z0"]
        assert len(r2.output_ids) == 4
        assert decode.prefix_cache_hits == 1
    finally:
        for e in (prefill, decode):
            e.stop()

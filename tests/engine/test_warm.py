"""AOT warm hooks: compile without perturbing state (train engine) /
compile the serving programs through the live loop (serving engine).
These back the bench compile pass (docs/benchmarking.md) and
`warm_on_start` serving pods."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")

from areal_tpu.api.data_api import MicroBatchSpec, SequenceSample
from areal_tpu.engine.jax_engine import JaxTrainEngine
from areal_tpu.engine.optimizer import OptimizerConfig
from areal_tpu.models.config import TransformerConfig
from areal_tpu.models.transformer import init_params
from areal_tpu.ops.loss import sft_loss_from_logprobs


def _tiny_cfg():
    return TransformerConfig(
        n_layers=2, hidden_dim=64, n_q_heads=4, n_kv_heads=2, head_dim=16,
        intermediate_dim=128, vocab_size=256, compute_dtype="float32",
    )


def _batch(cfg, seqlen=64, n_seqs=4, seed=0):
    rng = np.random.RandomState(seed)
    total = seqlen * n_seqs
    return SequenceSample.from_default(
        ids=[f"b{i}" for i in range(n_seqs)],
        seqlens=[seqlen] * n_seqs,
        data={
            "packed_input_ids": rng.randint(0, cfg.vocab_size, size=total),
            "loss_mask": np.ones(total, np.float32),
        },
    )


def _loss(lp, rows):
    tot, n = sft_loss_from_logprobs(lp, rows["loss_mask"])
    return tot, {}


def _weight(mb):
    return float(np.sum(mb.data["loss_mask"]))


def test_train_warm_compiles_without_touching_state():
    cfg = _tiny_cfg()
    eng = JaxTrainEngine(
        cfg, init_params(cfg, jax.random.PRNGKey(0)),
        optimizer_config=OptimizerConfig(lr=1e-3, warmup_steps_proportion=0.0),
        total_train_steps=10, row_len_multiple=64, max_row_len=64,
    )
    batch = _batch(cfg)
    before = jax.tree_util.tree_map(np.asarray, eng.params)
    dt = eng.warm(batch, MicroBatchSpec(n_mbs=1), _loss, loss_name="bench")
    assert dt >= 0.0
    after = jax.tree_util.tree_map(np.asarray, eng.params)
    for a, b in zip(jax.tree_util.tree_leaves(before),
                    jax.tree_util.tree_leaves(after)):
        np.testing.assert_array_equal(a, b)  # AOT: no step executed
    # The warmed engine trains normally (and identically to a cold one).
    cold = JaxTrainEngine(
        cfg, init_params(cfg, jax.random.PRNGKey(0)),
        optimizer_config=OptimizerConfig(lr=1e-3, warmup_steps_proportion=0.0),
        total_train_steps=10, row_len_multiple=64, max_row_len=64,
    )
    s_warm = eng.train_batch(batch, MicroBatchSpec(n_mbs=1), _loss, _weight,
                             loss_name="bench")
    s_cold = cold.train_batch(batch, MicroBatchSpec(n_mbs=1), _loss, _weight,
                              loss_name="bench")
    assert s_warm["bench/loss"] == pytest.approx(s_cold["bench/loss"],
                                                 rel=1e-5)


def test_train_warm_multi_microbatch_shapes():
    cfg = _tiny_cfg()
    eng = JaxTrainEngine(
        cfg, init_params(cfg, jax.random.PRNGKey(0)),
        optimizer_config=OptimizerConfig(lr=1e-3, warmup_steps_proportion=0.0),
        total_train_steps=10, row_len_multiple=64, max_row_len=64,
    )
    eng.warm(_batch(cfg, n_seqs=8), MicroBatchSpec(n_mbs=4), _loss)
    eng.train_batch(_batch(cfg, n_seqs=8), MicroBatchSpec(n_mbs=4),
                    _loss, _weight)


def test_serving_warm_compiles_then_serves():
    import threading

    from areal_tpu.engine.serving import GenRequest, ServingEngine

    cfg = _tiny_cfg()
    eng = ServingEngine(
        cfg, init_params(cfg, jax.random.PRNGKey(1)),
        max_batch_size=2, max_seq_len=128, decode_block_steps=4,
        prompt_bucket=8, page_size=8, eos_token_id=None,
        kv_pool_tokens=2 * 128,
    )
    eng.start()
    try:
        dt = eng.warm([8, 16])
        assert dt > 0.0
        done = threading.Event()
        out = []
        eng.submit(GenRequest(
            qid="q0", input_ids=[1] * 8, max_new_tokens=8, greedy=True,
            done_cb=lambda r: (out.append(r), done.set()),
        ))
        assert done.wait(60)
        assert len(out[0].output_ids) == 8
    finally:
        eng.stop()


def test_serving_warm_requires_start():
    from areal_tpu.engine.serving import ServingEngine

    cfg = _tiny_cfg()
    eng = ServingEngine(
        cfg, init_params(cfg, jax.random.PRNGKey(1)),
        max_batch_size=2, max_seq_len=64, decode_block_steps=4,
        prompt_bucket=8, page_size=8, kv_pool_tokens=128,
    )
    with pytest.raises(AssertionError):
        eng.warm([8])

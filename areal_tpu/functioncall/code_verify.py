"""Local code-correctness verification: run candidate code against tests.

Counterpart of the reference's local code verifier
(functioncall/code/local_verify.py + testing_util.py:1-803), built from
scratch with the same judging behavior but a stronger isolation model:
where the reference exec()s candidate code in-process behind a
"reliability guard", every case here runs in a fresh subprocess with
CPU/memory rlimits, a kill-on-timeout, and a preamble that disables the
most dangerous host escapes. Two problem styles are supported, matching
the reference dataset format:

- **standard input**: program reads stdin, stdout compared against the
  expected output (whitespace-insensitive, float-tolerant per token);
- **call-based** (`fn_name` in the case metadata): the candidate defines
  a function (possibly on a `Solution` class, LeetCode-style); a driver
  appended to the file calls it with the case's JSON args and prints the
  JSON result, compared structurally with float tolerance.

`code_verify` returns overall pass/fail; `run_test_cases` returns the
per-case outcome list (the reference's testing_util contract) for
partial-credit rewards and debugging.
"""

from __future__ import annotations

import json
import os
import re
import subprocess
import sys
import tempfile
from typing import Any, Dict, List, Optional, Tuple

DEFAULT_TIMEOUT = 8.0
FLOAT_TOL = 1e-6

_GUARD_PREAMBLE = """\
import resource, sys
resource.setrlimit(resource.RLIMIT_AS, ({mem}, {mem}))
resource.setrlimit(resource.RLIMIT_CPU, ({cpu}, {cpu}))
sys.setrecursionlimit(100000)
import builtins as _b
import os as _os
for _name in ("system", "popen", "execv", "execve", "execvp", "fork",
              "kill", "killpg", "removedirs", "rmdir", "unlink", "remove",
              "rename", "renames", "truncate", "replace", "chmod", "chown"):
    if hasattr(_os, _name):
        setattr(_os, _name, None)
_os.environ.clear()
"""


def extract_code_block(text: str) -> Optional[str]:
    """Last fenced code block (``` or ```python), else None."""
    blocks = re.findall(r"```(?:python|py)?\n(.*?)```", text, re.DOTALL)
    return blocks[-1] if blocks else None


def _driver_for_call(fn_name: str) -> str:
    """Appended to a call-based candidate: call fn with JSON args from
    argv file, print JSON result on the last line."""
    return f"""
if __name__ == "__main__":
    import json as _json, sys as _sys
    _args = _json.loads(_sys.stdin.read())
    _fn = globals().get({fn_name!r})
    if _fn is None and "Solution" in globals():
        _fn = getattr(Solution(), {fn_name!r}, None)
    if _fn is None:
        raise SystemExit("function {fn_name} not found")
    _res = _fn(*_args)
    print("\\n___CALL_RESULT___")
    print(_json.dumps(_res))
"""


def run_one_case(
    code: str,
    stdin_data: str,
    timeout: float = DEFAULT_TIMEOUT,
    fn_name: Optional[str] = None,
    mem_bytes: int = 2 << 30,
) -> Tuple[bool, str, str]:
    """Execute one case in a fresh subprocess; (ok, stdout, err)."""
    preamble = _GUARD_PREAMBLE.format(mem=mem_bytes, cpu=int(timeout) + 2)
    body = preamble + code
    if fn_name:
        body += _driver_for_call(fn_name)
    with tempfile.NamedTemporaryFile("w", suffix=".py", delete=False) as f:
        f.write(body)
        path = f.name
    try:
        proc = subprocess.run(
            [sys.executable, path],
            input=stdin_data,
            capture_output=True,
            text=True,
            timeout=timeout,
            cwd=tempfile.gettempdir(),
        )
        return proc.returncode == 0, proc.stdout, proc.stderr
    except subprocess.TimeoutExpired:
        return False, "", "timeout"
    finally:
        os.unlink(path)


def _tokens_match(a: str, b: str) -> bool:
    if a == b:
        return True
    try:
        return abs(float(a) - float(b)) <= FLOAT_TOL * max(
            1.0, abs(float(b))
        )
    except ValueError:
        return False


def _stdout_matches(got: str, expected: str) -> bool:
    """Line-by-line, token-by-token; numeric tokens compared with float
    tolerance (reference testing_util's custom_compare behavior)."""
    gl = [line.split() for line in got.rstrip().splitlines() if line.strip()]
    el = [
        line.split() for line in expected.rstrip().splitlines() if line.strip()
    ]
    if len(gl) != len(el):
        return False
    for gr, er in zip(gl, el):
        if len(gr) != len(er):
            return False
        if not all(_tokens_match(x, y) for x, y in zip(gr, er)):
            return False
    return True


def _values_match(got: Any, expected: Any) -> bool:
    """Structural compare of call-based results with float tolerance;
    tuples (JSON arrays) and lists compare interchangeably."""
    if isinstance(got, (int, float)) and isinstance(expected, (int, float)):
        return abs(float(got) - float(expected)) <= FLOAT_TOL * max(
            1.0, abs(float(expected))
        )
    if isinstance(got, (list, tuple)) and isinstance(expected, (list, tuple)):
        return len(got) == len(expected) and all(
            _values_match(x, y) for x, y in zip(got, expected)
        )
    if isinstance(got, dict) and isinstance(expected, dict):
        return set(got) == set(expected) and all(
            _values_match(got[k], expected[k]) for k in got
        )
    return got == expected


def normalize_test_cases(obj) -> Tuple[List[Dict[str, Any]], Optional[str]]:
    """Accept the dataset wire format {"inputs": [...], "outputs": [...],
    "fn_name"?} (reference math_code_dataset rows) or an explicit list of
    {input, output} dicts. Returns (cases, fn_name)."""
    if isinstance(obj, str):
        obj = json.loads(obj)
    if isinstance(obj, dict) and "inputs" in obj:
        fn = obj.get("fn_name") or (obj.get("metadata") or {}).get("fn_name")
        return (
            [
                {"input": i, "output": o}
                for i, o in zip(obj["inputs"], obj["outputs"])
            ],
            fn,
        )
    return list(obj), None


def run_test_cases(
    solution_text: str,
    test_cases,
    timeout: float = DEFAULT_TIMEOUT,
    max_cases: Optional[int] = None,
    stop_on_first_failure: bool = False,
) -> List[bool]:
    """Per-case pass/fail for the extracted program (empty list when no
    code block is present). With `stop_on_first_failure`, remaining cases
    after the first failure are recorded False without being run — wrong
    candidates (most early-RL rollouts) must not cost N * timeout."""
    cases, fn_name = normalize_test_cases(test_cases)
    if max_cases is not None:
        cases = cases[:max_cases]
    code = extract_code_block(solution_text)
    if code is None:
        return [False] * len(cases)
    results: List[bool] = []
    for ci, case in enumerate(cases):
        if stop_on_first_failure and results and not results[-1]:
            results.extend([False] * (len(cases) - ci))
            break
        if fn_name:
            args = case.get("input", [])
            ok, out, _ = run_one_case(
                code, json.dumps(args), timeout, fn_name=fn_name
            )
            if not ok or "___CALL_RESULT___" not in out:
                results.append(False)
                continue
            payload = out.rsplit("___CALL_RESULT___", 1)[1].strip()
            try:
                got = json.loads(payload)
            except json.JSONDecodeError:
                results.append(False)
                continue
            expected = case.get("output")
            # dataset wire format wraps the expected value in a 1-list
            if isinstance(expected, list) and len(expected) == 1:
                ok_val = _values_match(got, expected[0]) or _values_match(
                    got, expected
                )
            else:
                ok_val = _values_match(got, expected)
            results.append(bool(ok_val))
        else:
            stdin_data = case.get("input", "")
            if isinstance(stdin_data, list):
                stdin_data = "\n".join(map(str, stdin_data))
            expected = case.get("output", "")
            if isinstance(expected, list):
                expected = "\n".join(map(str, expected))
            ok, out, _ = run_one_case(code, stdin_data, timeout)
            results.append(bool(ok) and _stdout_matches(out, expected))
    return results


def code_verify(
    solution_text: str,
    test_cases,
    timeout: float = DEFAULT_TIMEOUT,
    max_cases: Optional[int] = None,
) -> bool:
    """True if the extracted program passes every case."""
    results = run_test_cases(
        solution_text, test_cases, timeout, max_cases,
        stop_on_first_failure=True,
    )
    return bool(results) and all(results)

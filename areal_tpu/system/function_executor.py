"""Runs one DFG traversal per training step: data loading + all MFC
coroutines concurrently.

Counterpart of the reference's FunctionExecutor
(realhf/system/function_executor.py:24-224). Data loading fetches
metadata from the dataset-hosting model workers into the buffer; each
MFC coroutine fires as soon as its input keys are ready (ordering falls
out of the buffer); after the traversal the per-step sample cache is
cleared on every worker.
"""

from __future__ import annotations

import asyncio
import functools
from typing import Dict, List, Optional

from areal_tpu.api.data_api import SequenceSample
from areal_tpu.api.dfg import DFGraph
from areal_tpu.base import logging, name_resolve, names
from areal_tpu.system.buffer import AsyncIOSequenceBuffer
from areal_tpu.system.model_function_call import (
    ModelFunctionCall,
    RPCCorountineControl,
    async_poll,
)
from areal_tpu.system.redistributor import GlobalStorageTracker, RedistribPlanner

logger = logging.getLogger("function_executor")


class FunctionExecutor:
    def __init__(
        self,
        graph: DFGraph,
        stream,
        buffer: AsyncIOSequenceBuffer,
        model_topos: Dict[str, List[str]],  # model_name str -> worker names
        data_hosts: List[str],
        ctrl: Optional[RPCCorountineControl] = None,
        experiment_name: str = "",
        trial_name: str = "",
    ):
        self.graph = graph
        self.stream = stream
        self.buffer = buffer
        self.data_hosts = data_hosts
        self.ctrl = ctrl or RPCCorountineControl()
        self.tracker = GlobalStorageTracker()
        self.planner = RedistribPlanner(self.tracker)
        self.experiment_name = experiment_name
        self.trial_name = trial_name
        self._data_epoch_done = False
        self._samples_loaded = 0

        # One persistent loop for all steps: asyncio primitives in the
        # buffer bind to the loop they first wait on, so a fresh loop per
        # step (asyncio.run) would break on step 2.
        self._loop = asyncio.new_event_loop()

        self.mfcs: List[ModelFunctionCall] = []
        for name, rpc in graph.rpcs.items():
            workers = model_topos[str(rpc.model_name)]
            self.mfcs.append(
                ModelFunctionCall(
                    rpc=rpc,
                    stream=self.stream,
                    buffer=buffer,
                    tracker=self.tracker,
                    planner=self.planner,
                    workers=workers,
                    ctrl=self.ctrl,
                )
            )

    # ------------------------------------------------------------------

    @property
    def src_rpcs(self):
        return [m.rpc for m in self.mfcs if m.rpc.is_src]

    async def load_data(self):
        """Fetch dataset batches (metadata) until every src MFC can draw a
        full batch this step (reference function_executor.py:121)."""
        need = max(r.n_seqs for r in self.src_rpcs)
        while True:
            counts = [
                await self.buffer.poll_ready_count(r) for r in self.src_rpcs
            ]
            if all(c >= r.n_seqs for c, r in zip(counts, self.src_rpcs)):
                return
            req_ids = self.stream.request(self.data_hosts, "fetch")
            replies = await asyncio.gather(
                *[async_poll(self.stream, rid) for rid in req_ids]
            )
            epoch_done = False
            total_new = 0
            for p in replies:
                meta: Optional[SequenceSample] = p.data.get("meta")
                epoch_done = epoch_done or p.data.get("epoch_done", False)
                if meta is None or meta.bs == 0:
                    continue
                self.tracker.add_batch(list(meta.ids), list(meta.keys), p.sender)
                total_new += await self.buffer.put_batch([meta])
            self._samples_loaded += total_new
            if epoch_done:
                self._data_epoch_done = True
            if total_new == 0 and not any(
                p.data.get("meta") is not None for p in replies
            ):
                # Dataset exhausted and nothing new: avoid a hot loop.
                await asyncio.sleep(0.01)
            # Publish the global sample counter for the staleness gate
            # (reference function_executor.py:192-201). Off-loop: the
            # write is file I/O (NFS-backed in production) and this
            # loop also drives every MFC request round-trip — an inline
            # write per fetch lap stalled them all (areal-lint
            # blocking-async regression note).
            if self.experiment_name:
                await asyncio.get_running_loop().run_in_executor(
                    None,
                    functools.partial(
                        name_resolve.add,
                        names.training_samples(
                            self.experiment_name, self.trial_name
                        ),
                        str(self._samples_loaded),
                        replace=True,
                        keepalive_ttl=None,
                    ),
                )

    async def clear_gpu_cache(self):
        """Drop this step's consumed samples everywhere
        (reference function_executor.py:100-105)."""
        used = set(self.ctrl.used_ids)
        # Epoch carryover: a consumed id may have been RE-admitted to the
        # buffer mid-step (tiny datasets re-issue row ids every epoch).
        # Clearing such an id now would wipe the tracker ownership and
        # worker-side data its resident copy needs next step ("no owner"
        # at derive_plan). Defer it — its next consumption re-adds it to
        # used_ids and the clear happens then.
        resident = self.buffer.resident_ids(used)
        ids = sorted(used - resident)
        self.ctrl.used_ids.clear()
        if resident:
            logger.warning(
                "deferring cache clear of %d id(s) re-admitted to the "
                "buffer (epoch carryover), e.g. %r",
                len(resident), next(iter(resident)),
            )
        if not ids:
            return
        all_workers = sorted(
            {w for m in self.mfcs for w in m.workers} | set(self.data_hosts)
        )
        req_ids = self.stream.request(
            all_workers, "clear_data_cache", [ids for _ in all_workers]
        )
        await asyncio.gather(*[async_poll(self.stream, rid) for rid in req_ids])
        self.tracker.drop_samples(ids)

    async def execute_step(self) -> Dict:
        """One DFG traversal; returns train stats keyed by MFC name."""
        self.ctrl.train_stats.clear()
        tasks = [asyncio.create_task(self.load_data())]
        tasks += [asyncio.create_task(m.run_step()) for m in self.mfcs]
        try:
            await asyncio.gather(*tasks)
        except Exception:
            for t in tasks:
                t.cancel()
            raise
        await self.clear_gpu_cache()
        return dict(self.ctrl.train_stats)

    def execute_step_sync(self) -> Dict:
        return self._loop.run_until_complete(self.execute_step())

    @property
    def epoch_done(self) -> bool:
        """True once the underlying dataset signalled an epoch boundary."""
        v = self._data_epoch_done
        self._data_epoch_done = False
        return v

"""Blocked reverse affine-scan kernel (Pallas, TPU) — the GAE core.

The GAE recursion over packed rows is a reverse scan of affine maps
x_t = a_t * x_{t+1} + b_t (ops/gae._gae_affine_elems builds a and b; the
segment structure lives entirely inside them, so this kernel is a plain
segment-free scan). ``jax.lax.associative_scan`` already gives O(log T)
*depth*, but it materializes ~log2(T) full [R, T] intermediates through
HBM. This kernel reads (a, b) once and writes x once:

- Grid (T // bt,) walking time blocks in REVERSE order via the BlockSpec
  index_map. TPU grid execution is sequential by construction, which the
  inter-block carry relies on (this kernel is wrong on a parallel-grid
  backend; interpret mode is sequential too).
- Within a block: an inclusive reverse scan of the affine pairs by
  doubling — log2(bt) vectorized combine steps entirely in VMEM/VPU,
  shifting with static slices + identity fill (a=1, b=0) past the block
  end. C[t] then composes e_t .. e_blockend.
- Across blocks: a [R, LANES] VMEM scratch carries x at the NEXT (later)
  block's first position; x[t] = C[t].a * x_carry + C[t].b, and this
  block's first column becomes the next carry.

Shape gate ``gae_pallas_ok``: T must be lane-aligned (128 | T) and R
sublane-aligned (8 | R, f32 tiles are 8x128). Padding a packed batch to
those is the caller's trade (ops/gae dispatches to 'assoc' otherwise).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128
_SUBLANES = 8
# Largest time block held in VMEM at once: 4 live [R, bt] f32 arrays
# (a, b and their shifted halves) + in/out blocks — at R=256, bt=512
# that is ~3 MB, comfortably under the ~16 MB budget.
_BLOCK_T = 512


def gae_pallas_ok(r: int, t: int) -> bool:
    """Shape gate: t rides the lane axis (128-aligned), r the sublane
    axis (8-aligned for f32 tiles)."""
    return t % _LANES == 0 and r % _SUBLANES == 0 and r > 0


def _largest_block(n: int, cap: int) -> int:
    d = (min(cap, n) // _LANES) * _LANES
    while n % d:
        d -= _LANES
    return d


def _scan_kernel(a_ref, b_ref, x_ref, carry_sc):
    j = pl.program_id(0)

    @pl.when(j == 0)
    def _init():
        # First processed block == LAST time block: x past the end is 0.
        carry_sc[...] = jnp.zeros_like(carry_sc)

    A = a_ref[...].astype(jnp.float32)
    B = b_ref[...].astype(jnp.float32)
    rows, bt = A.shape
    # Inclusive reverse scan by doubling: after step s, (A, B)[t]
    # composes e_t .. e_{min(t + 2s - 1, end)}. Shift-by-s reads the
    # partial composition starting at t+s; identity (a=1, b=0) past the
    # block end leaves the suffix combines unchanged.
    s = 1
    while s < bt:
        A_s = jnp.concatenate(
            [A[:, s:], jnp.ones((rows, s), jnp.float32)], axis=1
        )
        B_s = jnp.concatenate(
            [B[:, s:], jnp.zeros((rows, s), jnp.float32)], axis=1
        )
        # (f_t . f_{t+s..}): outer = the earlier element (this lane).
        B = B + A * B_s
        A = A * A_s
        s *= 2
    x_next = carry_sc[...][:, :1]  # [rows, 1]: x at blockend + 1
    x = A * x_next + B
    x_ref[...] = x
    # This block's first column is x at its first position — the carry
    # for the NEXT processed (earlier-time) block.
    carry_sc[...] = jnp.broadcast_to(x[:, :1], carry_sc.shape)


@functools.partial(jax.jit, static_argnames=("interpret", "block_t"))
def segment_scan_reverse(
    a: jnp.ndarray,  # [R, T] f32 multipliers (0 at segment boundaries)
    b: jnp.ndarray,  # [R, T] f32 offsets (deltas)
    interpret: bool = False,
    block_t: int = _BLOCK_T,
) -> jnp.ndarray:
    """x[t] = a[t] * x[t+1] + b[t], scanned right-to-left per row, with
    x[T] = 0. Returns [R, T] f32."""
    R, T = a.shape
    if not gae_pallas_ok(R, T):
        raise ValueError(
            f"segment_scan_reverse needs 128 | T and 8 | R, got "
            f"[R={R}, T={T}]"
        )
    bt = _largest_block(T, block_t)
    nb = T // bt

    def imap(j):
        return (0, nb - 1 - j)  # reverse time order

    return pl.pallas_call(
        _scan_kernel,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((R, bt), imap),
            pl.BlockSpec((R, bt), imap),
        ],
        out_specs=pl.BlockSpec((R, bt), imap),
        out_shape=jax.ShapeDtypeStruct((R, T), jnp.float32),
        scratch_shapes=[pltpu.VMEM((R, _LANES), jnp.float32)],
        interpret=interpret,
    )(a.astype(jnp.float32), b.astype(jnp.float32))

from areal_tpu.scheduler import gke  # noqa: F401  (registers "gke" mode)

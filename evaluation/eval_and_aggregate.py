"""Evaluate checkpoints over multiple benchmarks and aggregate results.

Counterpart of the reference's evaluation/eval_and_aggregate.py (356 LoC:
launch math/code evals per benchmark per checkpoint, then merge pass@1 and
response-length stats into one table). Here each benchmark is a jsonl with
a declared task family; the right harness (math_eval / code_eval) runs per
(checkpoint, benchmark) and results merge into aggregate.json plus a
printed table.

Usage:
    python evaluation/eval_and_aggregate.py save_root=/save/actor \
        benchmarks=aime:/data/aime.jsonl:math,lcb:/data/lcb.jsonl:code \
        output_root=/tmp/evals max_new_tokens=512
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
import sys
from typing import Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


@dataclasses.dataclass
class Benchmark:
    name: str
    data_path: str
    task: str  # math | code

    @staticmethod
    def parse_many(spec: str) -> List["Benchmark"]:
        """"name:path:task,name:path:task" (task defaults to math)."""
        out = []
        for part in spec.split(","):
            bits = part.split(":")
            if len(bits) == 2:
                bits.append("math")
            name, path, task = bits
            if task not in ("math", "code"):
                raise ValueError(f"unknown task {task!r} for benchmark {name}")
            out.append(Benchmark(name, path, task))
        return out


def discover_checkpoints(save_root: str) -> Dict[int, str]:
    """step -> checkpoint dir (dp0 preferred), completed saves only."""
    found: Dict[int, str] = {}
    if not os.path.isdir(save_root):
        return found
    for name in sorted(os.listdir(save_root)):
        m = re.fullmatch(r"step(\d+)", name)
        if not m:
            continue
        d = os.path.join(save_root, name)
        dp0 = os.path.join(d, "dp0")
        ckpt = dp0 if os.path.isdir(dp0) else d
        if os.path.exists(os.path.join(ckpt, "config.json")):
            found[int(m.group(1))] = ckpt
    return found


def run_eval(ckpt: str, bench: Benchmark, output: str,
             has_preset_peer: bool = False, **eval_args) -> dict:
    """has_preset_peer: True when ANOTHER math benchmark in the same run
    resolves to a preset — only then may shared preset-only kwargs
    (prompt_type/num_shots) be dropped for this non-preset benchmark;
    otherwise they were clearly meant for THIS one and math_eval's hard
    error must fire rather than silently recording a methodology that
    never ran."""
    if bench.task == "code":
        from evaluation.code_eval import evaluate_checkpoint
    else:
        from evaluation.math_eval import evaluate_checkpoint
    # The harnesses accept different knobs (e.g. case_timeout is
    # code-only); in a mixed run forward each only what it understands.
    import inspect

    accepted = set(inspect.signature(evaluate_checkpoint).parameters)
    if bench.task == "math":
        # A benchmark named after a preset (aime24/math500/gsm8k/...)
        # gets that preset's prompt template, few-shot demos, and
        # sampling defaults (evaluation/presets.py).
        from evaluation.presets import BENCHMARKS

        if bench.name in BENCHMARKS:
            eval_args = {"benchmark": bench.name, **eval_args}
        elif has_preset_peer:
            dropped = {
                k for k in ("prompt_type", "num_shots") if k in eval_args
            }
            if dropped:
                print(
                    f"[eval_and_aggregate] benchmark {bench.name!r} has "
                    f"no preset; prompts run verbatim and {sorted(dropped)} "
                    f"apply only to the preset benchmarks in this run"
                )
            eval_args = {
                k: v for k, v in eval_args.items() if k not in dropped
            }
    return evaluate_checkpoint(
        ckpt=ckpt, data=bench.data_path, output=output,
        **{k: v for k, v in eval_args.items() if k in accepted},
    )


def eval_and_aggregate(
    save_root: str,
    benchmarks: List[Benchmark],
    output_root: str,
    steps: Optional[List[int]] = None,
    **eval_args,
) -> dict:
    """Run every (checkpoint, benchmark) pair, reusing results.json files
    already on disk (idempotent reruns), then aggregate."""
    from evaluation.presets import BENCHMARKS

    ckpts = discover_checkpoints(save_root)
    if steps:
        ckpts = {s: d for s, d in ckpts.items() if s in steps}
    has_preset = any(
        b.task == "math" and b.name in BENCHMARKS for b in benchmarks
    )
    table: Dict[str, Dict[str, float]] = {}
    for step in sorted(ckpts):
        row: Dict[str, float] = {}
        for bench in benchmarks:
            out_path = os.path.join(
                output_root, f"step{step}", f"{bench.name}.json"
            )
            if os.path.exists(out_path):
                with open(out_path) as f:
                    res = json.load(f)
            else:
                res = run_eval(ckpts[step], bench, out_path,
                               has_preset_peer=has_preset, **eval_args)
            row[bench.name] = res["accuracy"]
        row["avg"] = sum(row.values()) / max(1, len(row))
        table[f"step{step}"] = row

    agg = {
        "save_root": save_root,
        "benchmarks": [dataclasses.asdict(b) for b in benchmarks],
        "table": table,
    }
    os.makedirs(output_root, exist_ok=True)
    with open(os.path.join(output_root, "aggregate.json"), "w") as f:
        json.dump(agg, f, indent=2)

    # Human-readable table on stdout.
    names = [b.name for b in benchmarks] + ["avg"]
    header = "ckpt".ljust(12) + "".join(n.rjust(12) for n in names)
    print(header)
    for step_name in sorted(table, key=lambda s: int(s[4:])):
        row = table[step_name]
        print(step_name.ljust(12)
              + "".join(f"{row[n]:.4f}".rjust(12) for n in names))
    return agg


if __name__ == "__main__":
    kwargs = {}
    benchmarks: List[Benchmark] = []
    save_root = output_root = None
    for arg in sys.argv[1:]:
        k, v = arg.split("=", 1)
        if k == "benchmarks":
            benchmarks = Benchmark.parse_many(v)
        elif k == "save_root":
            save_root = v
        elif k == "output_root":
            output_root = v
        elif k == "steps":
            kwargs["steps"] = [int(s) for s in v.split(",")]
        elif k in ("max_new_tokens", "n_samples", "max_prompts", "max_cases",
                   "seed", "num_shots"):
            kwargs[k] = int(v)
        elif k in ("greedy",):
            kwargs[k] = v.lower() in ("1", "true")
        elif k in ("temperature", "case_timeout"):
            kwargs[k] = float(v)
        else:
            kwargs[k] = v
    assert save_root and output_root and benchmarks, (
        "need save_root=, output_root=, benchmarks="
    )
    eval_and_aggregate(save_root, benchmarks, output_root, **kwargs)

"""Expert-sliced weight streams (ISSUE 17 tentpole b): a
``(wire, ep_degree, ep_rank)`` manifest serves only that rank's experts
with its own chunk-hash grid, ingress payload_equivalents scale ~1/EP
for expert-dominated checkpoints, EP composes with TP on disjoint dims,
and ``cutover_shard_leaves(axis="fsdp")`` lands the slices under an
expert-parallel serving mesh with greedy decode parity."""

import queue as _queue
import shutil

import numpy as np
import pytest

from areal_tpu.engine.weight_client import (
    ChunkStore, assemble_leaves, fetch_manifest,
)
from areal_tpu.parallel.sharding import (
    compose_shard_slices, expert_shard_slices, tensor_shard_slices,
)
from areal_tpu.system.weight_plane import WeightPlaneSource, manifest_stream_key
from areal_tpu.system.weight_transfer import dump_raw_params


def _moe_cfg():
    from areal_tpu.models.config import MoEConfig, TransformerConfig

    # expert_intermediate_dim >> attention dims so the expert weights
    # dominate total bytes (the regime the 1/EP claim is about).
    return TransformerConfig(
        n_layers=2, hidden_dim=32, n_q_heads=4, n_kv_heads=2, head_dim=8,
        intermediate_dim=32, vocab_size=64, compute_dtype="float32",
        param_dtype="float32",
        moe=MoEConfig(num_experts=4, top_k=2, dispatch="dropless",
                      expert_intermediate_dim=128),
    )


# ----------------------------------------------------------------------
# Slice math
# ----------------------------------------------------------------------


def test_expert_shard_slices_moe_leaves_only():
    # Stacked expert leaf [L, E, D, F]: E slices degree-ways.
    assert expert_shard_slices(
        "layers/mlp/w_gate", (2, 4, 32, 64), 2, 0
    ) == [(0, 2), (0, 2), (0, 32), (0, 64)]
    assert expert_shard_slices(
        "layers/mlp/w_gate", (2, 4, 32, 64), 2, 1
    ) == [(0, 2), (2, 4), (0, 32), (0, 64)]
    assert expert_shard_slices(
        "layers/mlp/w_down", (2, 4, 64, 32), 4, 3
    )[1] == (3, 4)
    # Router, attention, norms: full extent on every rank.
    assert expert_shard_slices(
        "layers/mlp/router", (2, 32, 4), 2, 1
    ) == [(0, 2), (0, 32), (0, 4)]
    assert expert_shard_slices(
        "layers/attn/wq", (2, 32, 32), 2, 1
    ) == [(0, 2), (0, 32), (0, 32)]
    # Indivisible expert dim degrades to full extent, never slices a
    # different dim.
    assert expert_shard_slices(
        "layers/mlp/w_gate", (2, 6, 32, 64), 4, 1
    ) == [(0, 2), (0, 6), (0, 32), (0, 64)]
    with pytest.raises(ValueError, match="expert shard"):
        expert_shard_slices("layers/mlp/w_gate", (2, 4, 32, 64), 2, 2)


def test_expert_slices_match_devices_indices_map():
    """The byte slicer must agree with what an fsdp-mesh NamedSharding
    actually places (the PR 8 spec-test discipline)."""
    import jax
    from jax.sharding import NamedSharding

    from areal_tpu.base.topology import MeshSpec
    from areal_tpu.parallel.mesh import make_mesh
    from areal_tpu.parallel.sharding import fitted_param_spec

    mesh = make_mesh(MeshSpec.parse("f2"), jax.devices()[:2])
    shape = (2, 4, 32, 64)
    spec = fitted_param_spec("layers/mlp/w_gate", shape, mesh)
    idx_map = NamedSharding(mesh, spec).devices_indices_map(shape)
    f_ax = list(mesh.axis_names).index("fsdp")
    for idx, dev in np.ndenumerate(mesh.devices):
        rank = int(idx[f_ax])
        want = [
            (sl.start or 0, sl.stop if sl.stop is not None else dim)
            for sl, dim in zip(idx_map[dev], shape)
        ]
        assert expert_shard_slices(
            "layers/mlp/w_gate", shape, 2, rank
        ) == want


def test_compose_shard_slices_disjoint_dims():
    shape = (2, 4, 32, 64)
    tp = tensor_shard_slices("layers/mlp/w_gate", shape, 2, 1)
    ep = expert_shard_slices("layers/mlp/w_gate", shape, 2, 0)
    both = compose_shard_slices(tp, ep, shape)
    assert both == [(0, 2), (0, 2), (0, 32), (32, 64)]
    with pytest.raises(ValueError, match="same dim"):
        compose_shard_slices(ep, ep[:1] + [(0, 2)] + ep[2:], shape)


# ----------------------------------------------------------------------
# EP manifests over a live origin
# ----------------------------------------------------------------------


def _dump_moe(tmp, seed=9, chunk_bytes=64 << 10):
    import jax

    from areal_tpu.models.transformer import init_params

    cfg = _moe_cfg()
    params = jax.tree_util.tree_map(
        np.asarray, init_params(cfg, jax.random.PRNGKey(seed))
    )
    dump_raw_params(params, tmp, version=1, chunk_bytes=chunk_bytes)
    return cfg, params


def test_ep_manifest_ingress_shrinks_and_roundtrips(tmp_path):
    tmp = str(tmp_path)
    cfg, params = _dump_moe(tmp)
    src = WeightPlaneSource(tmp, chunk_bytes=64 << 10).start()
    try:
        hashes = {}
        for rank in range(2):
            man = fetch_manifest(
                src.address, version=1, ep_degree=2, ep_rank=rank
            )
            assert manifest_stream_key(man) == ("raw", 1, 0, 2, rank)
            frac = man["total_bytes"] / man["model_total_bytes"]
            # Expert-dominated checkpoint: ~1/EP + eps per rank.
            assert frac <= 0.5 + 0.2, frac
            hashes[rank] = tuple(man["hashes"])
            st = ChunkStore(man)
            st.fetch([src.address], origin=src.address)
            assert st.stats(src.address)[
                "ingress_payload_equivalents"
            ] == pytest.approx(1.0)
            leaves = assemble_leaves(st)
            # Expert leaves carry this rank's E/2 slice; the router
            # (and attention weights) ride along in full.
            w = leaves["layers/mlp/w_gate"]
            full = params["layers"]["mlp"]["w_gate"]
            assert w.shape[1] == full.shape[1] // 2
            lo, hi = (0, 2) if rank == 0 else (2, 4)
            np.testing.assert_array_equal(w, full[:, lo:hi])
            np.testing.assert_array_equal(
                leaves["layers/mlp/router"],
                params["layers"]["mlp"]["router"],
            )
        # Different ranks are different byte streams (own hash grids).
        assert hashes[0] != hashes[1]
        # Both ranks together cost the origin ~one payload + the
        # replicated-leaf epsilon (O(1)-origin invariant holds for EP).
        eq = src.stats()["full_payload_equivalents"][1]
        assert 1.0 <= eq <= 1.3, eq
    finally:
        src.close()


def test_ep_composes_with_tp(tmp_path):
    tmp = str(tmp_path)
    cfg, params = _dump_moe(tmp)
    src = WeightPlaneSource(tmp, chunk_bytes=64 << 10).start()
    try:
        man = fetch_manifest(
            src.address, version=1,
            tp_degree=2, tp_rank=0, ep_degree=2, ep_rank=1,
        )
        assert manifest_stream_key(man) == ("raw", 2, 0, 2, 1)
        by_path = {e["path"]: e for e in man["leaves"]}
        e = by_path["layers/mlp/w_gate"]
        g = list(e["global_shape"])
        # E sliced by EP, F by TP — disjoint dims compose.
        assert list(e["shape"]) == [g[0], g[1] // 2, g[2], g[3] // 2]
        st = ChunkStore(man)
        st.fetch([src.address], origin=src.address)
        leaves = assemble_leaves(st)
        full = params["layers"]["mlp"]["w_gate"]
        np.testing.assert_array_equal(
            leaves["layers/mlp/w_gate"], full[:, 2:4, :, : g[3] // 2]
        )
    finally:
        src.close()


# ----------------------------------------------------------------------
# EP serving cutover
# ----------------------------------------------------------------------


@pytest.mark.slow
def test_ep_cutover_greedy_parity(tmp_path):
    import jax

    from areal_tpu.engine.serving import (
        GenRequest, ServingEngine, serving_mesh,
    )
    from areal_tpu.models.transformer import init_params

    def greedy(eng, ids, n=8):
        q = _queue.Queue()
        eng.submit(GenRequest(qid="q", input_ids=list(ids),
                              max_new_tokens=n, greedy=True, done_cb=q.put))
        r = q.get(timeout=300)
        if r.error is not None:
            raise RuntimeError(r.error)
        return r.output_ids

    tmp = str(tmp_path / "dump")
    cfg, p_serve = _dump_moe(tmp)
    src = None
    engines = []
    try:
        src = WeightPlaneSource(tmp, chunk_bytes=64 << 10).start()
        leaves_by_rank, gshapes = {}, {}
        for rank in range(2):
            man = fetch_manifest(
                src.address, version=1, ep_degree=2, ep_rank=rank
            )
            st = ChunkStore(man)
            st.fetch([src.address], origin=src.address)
            leaves_by_rank[rank] = assemble_leaves(st)
            gshapes.update({
                e["path"]: tuple(e["global_shape"]) for e in man["leaves"]
            })
        base = ServingEngine(
            cfg, p_serve, max_batch_size=2, max_seq_len=128,
            decode_block_steps=4, page_size=8, seed=0,
        )
        base.start()
        engines.append(base)
        want = greedy(base, [5, 6, 7])

        p_boot = jax.tree_util.tree_map(
            np.asarray, init_params(cfg, jax.random.PRNGKey(0))
        )
        ep = ServingEngine(
            cfg, p_boot, max_batch_size=2, max_seq_len=128,
            decode_block_steps=4, page_size=8, seed=0,
            mesh=serving_mesh(2, axis="fsdp"),
        )
        ep.start()
        engines.append(ep)
        ep.cutover_shard_leaves(
            leaves_by_rank, 2, version=1, global_shapes=gshapes,
            axis="fsdp",
        )
        assert greedy(ep, [5, 6, 7]) == want
    finally:
        for e in engines:
            try:
                e.stop()
            except Exception:
                pass
        if src is not None:
            src.close()
        shutil.rmtree(tmp, ignore_errors=True)

"""Content-addressed chunking for the weight-distribution plane.

The raw-bin dump format (system/weight_transfer.py) is one contiguous
byte blob per version. The distribution plane (system/weight_plane.py)
moves that blob over HTTP in fixed-size chunks; every chunk is named by
its content hash so a receiver can verify each piece independently,
resume a torn connection mid-chunk, and safely accept bytes from ANY
holder (trainer origin or a sibling generation server) — the hash, not
the peer, is the authority.

Kept in ``base`` (stdlib-only, no jax/numpy) so the trainer-side source,
the engine-side fetch client, and the bench workload all share one
definition of "a chunk".
"""

from __future__ import annotations

import hashlib
import os
from typing import Dict, List, Tuple

CHUNK_SCHEMA = "areal-weight-chunks/v1"

# 8 MiB default: large enough that per-chunk HTTP overhead is noise for
# GB-scale payloads, small enough that a resumed transfer re-pays at
# most one chunk and a fanout tree pipelines across peers quickly.
DEFAULT_CHUNK_BYTES = 8 << 20


def hash_chunk(data) -> str:
    """Content hash of one chunk (sha256; full hex so a collision-forged
    chunk is out of reach for anything short of breaking sha256)."""
    return hashlib.sha256(bytes(data)).hexdigest()


def chunk_spans(total_bytes: int, chunk_bytes: int) -> List[Tuple[int, int]]:
    """[(offset, length), ...] covering [0, total_bytes). The final chunk
    is short; a zero-byte payload has zero chunks."""
    if chunk_bytes <= 0:
        raise ValueError(f"chunk_bytes must be > 0, got {chunk_bytes}")
    return [
        (off, min(chunk_bytes, total_bytes - off))
        for off in range(0, total_bytes, chunk_bytes)
    ]


def build_chunk_index(bin_path: str, chunk_bytes: int = DEFAULT_CHUNK_BYTES) -> Dict:
    """Stream the bin once and return its chunk index:

    ``{schema, chunk_bytes, total_bytes, n_chunks, hashes: [hex, ...]}``

    Raises OSError if the bin vanishes mid-read (GC race — the caller
    retries against the refreshed manifest, weight_transfer.py).
    """
    total = os.path.getsize(bin_path)
    hashes: List[str] = []
    with open(bin_path, "rb") as f:
        for _, length in chunk_spans(total, chunk_bytes):
            data = f.read(length)
            if len(data) != length:
                raise OSError(
                    f"short read on {bin_path}: wanted {length}, "
                    f"got {len(data)} (torn write or concurrent GC)"
                )
            hashes.append(hash_chunk(data))
    return {
        "schema": CHUNK_SCHEMA,
        "chunk_bytes": int(chunk_bytes),
        "total_bytes": int(total),
        "n_chunks": len(hashes),
        "hashes": hashes,
    }


class StreamChunker:
    """Incrementally hash a byte stream into the same chunk index
    ``build_chunk_index`` produces, without materializing the stream.

    The dump path (system/weight_transfer.dump_raw_params) feeds each
    leaf's bytes through this while writing the bin, then publishes the
    index as a sidecar — so the weight-plane origin never has to re-read
    and re-hash a multi-GB bin it just wrote."""

    def __init__(self, chunk_bytes: int = DEFAULT_CHUNK_BYTES):
        if chunk_bytes <= 0:
            raise ValueError(f"chunk_bytes must be > 0, got {chunk_bytes}")
        self.chunk_bytes = int(chunk_bytes)
        self.total = 0
        self.hashes: List[str] = []
        self._h = hashlib.sha256()
        self._fill = 0  # bytes fed into the current (open) chunk

    def update(self, data) -> None:
        mv = memoryview(data).cast("B")
        while len(mv):
            take = min(len(mv), self.chunk_bytes - self._fill)
            self._h.update(mv[:take])
            self._fill += take
            self.total += take
            if self._fill == self.chunk_bytes:
                self.hashes.append(self._h.hexdigest())
                self._h = hashlib.sha256()
                self._fill = 0
            mv = mv[take:]

    def finish(self) -> Dict:
        if self._fill:
            self.hashes.append(self._h.hexdigest())
            self._h = hashlib.sha256()
            self._fill = 0
        return {
            "schema": CHUNK_SCHEMA,
            "chunk_bytes": self.chunk_bytes,
            "total_bytes": int(self.total),
            "n_chunks": len(self.hashes),
            "hashes": list(self.hashes),
        }


def verify_chunk(data, expected_hash: str) -> bool:
    return hash_chunk(data) == expected_hash

"""Minimal heartbeat-only workers for controller-supervision chaos tests.

Spawned through LocalController via the "module:Class" worker spec
(system.load_worker), so the real subprocess + supervision machinery is
exercised without booting a model."""

from __future__ import annotations

import dataclasses
import time

from areal_tpu.system.worker_base import PollResult, Worker


@dataclasses.dataclass
class SleeperConfig:
    experiment_name: str = ""
    trial_name: str = ""
    worker_index: int = 0

    @property
    def worker_name(self) -> str:
        return f"sleeper/{self.worker_index}"


class SleeperWorker(Worker):
    """Polls forever; its only observable behavior is the heartbeat the
    Worker base class maintains (plus the worker.poll injection point)."""

    def _configure(self, config: SleeperConfig):
        self.cfg = config

    def _poll(self):
        time.sleep(0.02)
        return PollResult(batch_count=0)

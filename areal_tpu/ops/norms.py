"""Normalization ops (RMSNorm / LayerNorm), computed in fp32 for stability.

Replaces the reference's torch RMSNorm module (realhf/impl/model/modules/rms.py)
with fused-friendly jnp — XLA fuses the normalize into neighbouring
elementwise ops on TPU.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float = 1e-6) -> jnp.ndarray:
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    var = jnp.mean(x32 * x32, axis=-1, keepdims=True)
    out = x32 * jax.lax.rsqrt(var + eps)
    return (out * weight.astype(jnp.float32)).astype(dtype)


def layer_norm(
    x: jnp.ndarray, weight: jnp.ndarray, bias: jnp.ndarray, eps: float = 1e-5
) -> jnp.ndarray:
    dtype = x.dtype
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.var(x32, axis=-1, keepdims=True)
    out = (x32 - mean) * (var + eps) ** -0.5
    out = out * weight.astype(jnp.float32)
    if bias is not None:
        out = out + bias.astype(jnp.float32)
    return out.astype(dtype)

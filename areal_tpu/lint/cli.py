"""areal-lint CLI. Entry point: ``scripts/areal_lint.py``.

Exit codes: 0 clean, 1 findings, 2 configuration error."""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

from areal_tpu.lint.common import LintConfigError
from areal_tpu.lint.runner import ALL_CHECKERS, LintConfig, run_lint

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)
)))
DEFAULT_ALLOWLIST = os.path.join(
    REPO_ROOT, "areal_tpu", "lint", "allowlist.txt"
)


def _docs_sources():
    """name -> (render callable, emit flag) for every generated doc.
    Imported lazily so ``--help`` costs nothing."""
    from areal_tpu.base import env_registry, fault_points, metrics_registry

    return {
        "env": (env_registry.render_docs, "--emit-env-docs"),
        "metrics": (metrics_registry.render_docs, "--emit-metrics-docs"),
        "fault": (fault_points.render_docs, "--emit-fault-docs"),
    }


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="areal_lint",
        description="repo-specific AST checks: loop-only, "
                    "blocking-async, env-knob, wire-schema, "
                    "wire-contract, metrics-registry, chaos-registry, "
                    "lock-order, rpc-discipline "
                    "(docs/static_analysis.md)",
    )
    ap.add_argument("paths", nargs="*", help="files/dirs to lint")
    ap.add_argument("--allowlist", default=DEFAULT_ALLOWLIST,
                    help="allowlist file (default: "
                         "areal_tpu/lint/allowlist.txt)")
    ap.add_argument("--checker", action="append", dest="checkers",
                    choices=list(ALL_CHECKERS),
                    help="run only these checkers (repeatable)")
    ap.add_argument("--dead-knobs", action="store_true",
                    help="force the dead-registry-entry check even when "
                         "the scan does not cover env_registry.py")
    ap.add_argument("--no-dead-knobs", action="store_true",
                    help="suppress the dead-registry-entry check")
    ap.add_argument("--emit-env-docs", metavar="FILE",
                    help="write generated docs/env_vars.md content to "
                         "FILE and exit")
    ap.add_argument("--check-env-docs", metavar="FILE",
                    help="fail if FILE differs from the generated "
                         "env-knob registry docs (drift gate)")
    ap.add_argument("--emit-metrics-docs", metavar="FILE",
                    help="write generated docs/metrics.md content to "
                         "FILE")
    ap.add_argument("--check-metrics-docs", metavar="FILE",
                    help="fail if FILE differs from the generated "
                         "metrics registry docs (drift gate)")
    ap.add_argument("--emit-fault-docs", metavar="FILE",
                    help="write generated docs/fault_points.md content "
                         "to FILE")
    ap.add_argument("--check-fault-docs", metavar="FILE",
                    help="fail if FILE differs from the generated "
                         "fault-point registry docs (drift gate)")
    args = ap.parse_args(argv)

    docs = _docs_sources()
    emit_args = {
        "env": args.emit_env_docs,
        "metrics": args.emit_metrics_docs,
        "fault": args.emit_fault_docs,
    }
    check_args = {
        "env": args.check_env_docs,
        "metrics": args.check_metrics_docs,
        "fault": args.check_fault_docs,
    }

    emitted = False
    for name, target in emit_args.items():
        if not target:
            continue
        render, _ = docs[name]
        with open(target, "w", encoding="utf-8") as f:
            f.write(render())
        print(f"wrote {target}")
        emitted = True
    if emitted and not args.paths and not any(check_args.values()):
        return 0

    if not args.paths and not any(check_args.values()):
        ap.error("no paths given")

    rc = 0
    for name, target in check_args.items():
        if not target:
            continue
        render, emit_flag = docs[name]
        try:
            with open(target, "r", encoding="utf-8") as f:
                on_disk = f.read()
        except OSError as e:
            print(f"{name}-docs drift gate: cannot read {target}: {e}",
                  file=sys.stderr)
            return 2
        if on_disk != render():
            print(
                f"{target}: stale — regenerate with "
                f"'python scripts/areal_lint.py {emit_flag} {target}'",
                file=sys.stderr,
            )
            rc = 1

    if args.paths:
        dead = None
        if args.dead_knobs:
            dead = True
        if args.no_dead_knobs:
            dead = False
        cfg = LintConfig(
            root=REPO_ROOT,
            allowlist_path=args.allowlist,
            check_dead_knobs=dead,
            checkers=set(args.checkers) if args.checkers else
            set(ALL_CHECKERS),
        )
        try:
            findings = run_lint(args.paths, cfg)
        except LintConfigError as e:
            print(f"areal-lint config error: {e}", file=sys.stderr)
            return 2
        for f in findings:
            print(f.render())
        if findings:
            print(f"\nareal-lint: {len(findings)} finding(s). Fix them, "
                  f"or allowlist with justification in "
                  f"{os.path.relpath(args.allowlist, REPO_ROOT)} "
                  f"(docs/static_analysis.md).", file=sys.stderr)
            rc = 1
        elif rc == 0:
            n = len(args.paths)
            print(f"areal-lint: clean ({n} path(s))")
    return rc


if __name__ == "__main__":
    sys.exit(main())

"""Experiment controller: spawn workers, run the master, reap results.

Counterpart of the reference's controller (realhf/system/controller.py:
98-689) in its "local" form: every worker is a separate OS process
(multiprocessing spawn so each gets a clean JAX runtime), the master runs
inline in the controller process, and worker health is watched while the
master drives the experiment. This is also the in-process e2e test
harness (reference tests/experiments/utils.py:22-52).
"""

from __future__ import annotations

import dataclasses
import multiprocessing as mp
import os
import sys
import time
import traceback
from typing import Any, Dict, List, Optional

from areal_tpu.api.system_api import ExperimentConfig
from areal_tpu.base import constants, logging, name_resolve, names

logger = logging.getLogger("controller")


def _run_worker_proc(
    worker_type: str,
    config: Any,
    name_resolve_cfg: Dict,
    env: Dict[str, str],
    error_queue,
):
    """Subprocess entry: reconfigure name_resolve, build + run the worker."""
    try:
        os.environ.update(env)
        from areal_tpu.utils.jaxenv import apply_jax_platform_override

        apply_jax_platform_override()
        name_resolve.reconfigure(**name_resolve_cfg)
        from areal_tpu.system import load_worker

        cls = load_worker(worker_type)
        w = cls()
        w.configure(
            config,
            experiment_name=config.experiment_name,
            trial_name=config.trial_name,
            worker_name=config.worker_name,
        )
        w.run()
    except Exception:
        error_queue.put(
            f"{worker_type}/{getattr(config, 'worker_index', '?')}: "
            + traceback.format_exc()
        )
        raise


class LocalController:
    """Run one trial on this host: subprocess workers + inline master."""

    def __init__(
        self,
        exp_cfg: ExperimentConfig,
        name_resolve_cfg: Optional[Dict] = None,
        worker_env: Optional[Dict[str, str]] = None,
    ):
        self.exp_cfg = exp_cfg
        self.name_resolve_cfg = name_resolve_cfg or {"backend": "nfs"}
        self.worker_env = worker_env or {}
        self._procs: List[mp.Process] = []
        self._ctx = mp.get_context("spawn")
        self._errors = self._ctx.Queue()

    def _spawn(self, worker_type: str, config):
        # Spawned children must be able to import areal_tpu before the
        # target function runs (unpickling imports this module), so the
        # repo root has to be on PYTHONPATH at process start.
        import areal_tpu

        repo_root = os.path.dirname(os.path.dirname(os.path.abspath(areal_tpu.__file__)))
        existing = os.environ.get("PYTHONPATH", "")
        if repo_root not in existing.split(os.pathsep):
            os.environ["PYTHONPATH"] = (
                repo_root + (os.pathsep + existing if existing else "")
            )
        p = self._ctx.Process(
            target=_run_worker_proc,
            args=(
                worker_type,
                config,
                self.name_resolve_cfg,
                self.worker_env,
                self._errors,
            ),
            daemon=True,
        )
        p.start()
        self._procs.append(p)
        return p

    def start_workers(self):
        from areal_tpu.system import _WORKER_CLASSES

        async_types = ["generation_server", "gserver_manager", "rollout_worker"]
        wants_async = bool(
            self.exp_cfg.generation_servers
            or self.exp_cfg.gserver_manager
            or self.exp_cfg.rollout_workers
        )
        missing = [t for t in async_types if t not in _WORKER_CLASSES]
        if wants_async and missing:
            raise NotImplementedError(
                f"async worker roles not available yet: {missing}"
            )
        for cfg in self.exp_cfg.model_workers:
            self._spawn("model_worker", cfg)
        for cfg in self.exp_cfg.generation_servers:
            self._spawn("generation_server", cfg)
        if self.exp_cfg.gserver_manager is not None:
            self._spawn("gserver_manager", self.exp_cfg.gserver_manager)
        for cfg in self.exp_cfg.rollout_workers:
            self._spawn("rollout_worker", cfg)

    def check_worker_errors(self):
        try:
            err = self._errors.get_nowait()
        except Exception:
            return
        raise RuntimeError(f"worker failed:\n{err}")

    def _watchdog(self, stop_event):
        """Interrupt the inline master as soon as any worker dies, so its
        real traceback surfaces instead of a later stream timeout."""
        import _thread

        while not stop_event.wait(0.5):
            failed = not self._errors.empty() or any(
                (not p.is_alive()) and p.exitcode not in (0, None)
                for p in self._procs
            )
            if failed:
                logger.error("worker failure detected; interrupting master")
                self._watchdog_fired = True
                _thread.interrupt_main()
                return

    def run(self, timeout: Optional[float] = None) -> Dict:
        """Blocking: start workers, run master inline, join everything."""
        import threading

        name_resolve.reconfigure(**self.name_resolve_cfg)
        self.start_workers()
        self._watchdog_fired = False
        user_interrupt = False
        stop_watchdog = threading.Event()
        watchdog = threading.Thread(
            target=self._watchdog, args=(stop_watchdog,), daemon=True
        )
        watchdog.start()

        from areal_tpu.system.master_worker import MasterWorker

        master = MasterWorker()
        try:
            master.configure(
                self.exp_cfg.master,
                experiment_name=self.exp_cfg.experiment_name,
                trial_name=self.exp_cfg.trial_name,
                worker_name="master",
            )
            master.run()
        except KeyboardInterrupt:
            # Distinguish the two interrupt sources by WHO fired: only
            # the watchdog's interrupt means a worker died (traceback or
            # not) and must become RuntimeError for relaunch-recovery. A
            # genuine Ctrl-C propagates as-is — the terminal delivers
            # SIGINT to the whole process group, so workers also die
            # nonzero, and exit codes alone can't tell the cases apart.
            if self._watchdog_fired:
                self.check_worker_errors()
                dead = [
                    p.pid for p in self._procs
                    if (not p.is_alive()) and p.exitcode not in (0, None)
                ]
                raise RuntimeError(
                    f"worker process(es) died without a traceback "
                    f"(killed/native crash): pids={dead}"
                )
            user_interrupt = True
            raise
        finally:
            stop_watchdog.set()
            if not user_interrupt:
                # Surface worker failures the watchdog hadn't polled yet
                # (died in its 0.5s window as the master finished). Only
                # a genuine Ctrl-C suppresses this — teardown noise from
                # interrupted workers must not override the user's stop.
                self.check_worker_errors()
            self.join(timeout=30)
        return {"global_step": master.step_info.global_step,
                "perf_summary": dict(master.perf_summary)}

    def join(self, timeout: float = 30):
        deadline = time.monotonic() + timeout
        for p in self._procs:
            p.join(timeout=max(0.1, deadline - time.monotonic()))
        for p in self._procs:
            if p.is_alive():
                logger.warning(f"terminating straggler worker pid={p.pid}")
                p.terminate()
        self._procs.clear()


class ClusterController:
    """Scheduler-submitted workers + inline master: the multi-host control
    plane (reference counterpart: realhf/apps/main.py submitting
    `apps.remote worker` lines through the SLURM scheduler,
    scheduler/slurm/utils.py).

    Differences from LocalController: workers are launched through a
    `SchedulerClient` (local subprocesses for one machine; a registered
    cluster scheduler for pods) with their configs spooled as pickles to
    `spool_dir` (a shared filesystem on real clusters), and discovery
    runs over any name_resolve backend — typically the 'kv' TCP service
    (base/name_resolve_kv.py), which needs no shared FS at all. When
    `kv_address` is omitted a KvStoreServer is started in-process next to
    the master (the usual topology: control plane on the launch host).
    """

    def __init__(
        self,
        exp_cfg: ExperimentConfig,
        spool_dir: str,
        scheduler_mode: str = "local",
        kv_address: Optional[str] = None,
        worker_env: Optional[Dict[str, str]] = None,
        scheduler_kwargs: Optional[Dict] = None,
    ):
        self.exp_cfg = exp_cfg
        self.spool_dir = spool_dir
        self.scheduler_mode = scheduler_mode
        self.worker_env = worker_env or {}
        self._kv_server = None
        if kv_address is None:
            from areal_tpu.base.name_resolve_kv import KvStoreServer
            from areal_tpu.base import network

            self._kv_server = KvStoreServer(network.gethostip(), 0).start()
            kv_address = self._kv_server.address
        self.kv_address = kv_address
        self.name_resolve_cfg = {"backend": "kv", "address": kv_address}
        # Importing the client initializes the scheduler package, whose
        # __init__ registers the cluster backends (gke).
        from areal_tpu.scheduler.client import make_scheduler

        kwargs = dict(scheduler_kwargs or {})
        if scheduler_mode != "local":
            # Cluster job names must be scoped per trial: two experiments
            # sharing a namespace would otherwise collide on worker names
            # (and submit()'s stale-job cleanup would delete the other
            # trial's live workers).
            kwargs.setdefault(
                "name_prefix",
                f"{exp_cfg.experiment_name}-{exp_cfg.trial_name}",
            )
        self._sched = make_scheduler(
            scheduler_mode,
            log_dir=os.path.join(spool_dir, "logs"),
            **kwargs,
        )
        self._job_names: List[str] = []

    def _submit(self, worker_type: str, config) -> str:
        import json as _json
        import pickle

        os.makedirs(self.spool_dir, exist_ok=True)
        cfg_path = os.path.join(
            self.spool_dir, f"{config.worker_name.replace('/', '_')}.pkl"
        )
        with open(cfg_path, "wb") as f:
            pickle.dump(config, f)
        import areal_tpu

        repo_root = os.path.dirname(
            os.path.dirname(os.path.abspath(areal_tpu.__file__))
        )
        env = dict(self.worker_env)
        env["PYTHONPATH"] = (
            repo_root + os.pathsep + env.get(
                "PYTHONPATH", os.environ.get("PYTHONPATH", "")
            )
        ).rstrip(os.pathsep)
        name = self._sched.submit(
            config.worker_name,
            [
                sys.executable, "-m", "areal_tpu.system.worker_main",
                "--worker-type", worker_type,
                "--config", cfg_path,
                "--name-resolve", _json.dumps(self.name_resolve_cfg),
            ],
            env=env,
            cwd=repo_root,
        )
        self._job_names.append(name)
        return name

    def start_workers(self):
        for cfg in self.exp_cfg.model_workers:
            self._submit("model_worker", cfg)
        for cfg in self.exp_cfg.generation_servers:
            self._submit("generation_server", cfg)
        if self.exp_cfg.gserver_manager is not None:
            self._submit("gserver_manager", self.exp_cfg.gserver_manager)
        for cfg in self.exp_cfg.rollout_workers:
            self._submit("rollout_worker", cfg)

    def check_worker_errors(self):
        from areal_tpu.scheduler.client import JobState

        for n in self._job_names:
            info = self._sched.find(n)
            if info.state in (JobState.FAILED, JobState.CANCELLED):
                log = os.path.join(
                    self.spool_dir, "logs", n.replace("/", "_") + ".log"
                )
                tail = ""
                try:
                    with open(log) as f:
                        tail = f.read()[-3000:]
                except OSError:
                    pass
                raise RuntimeError(f"worker {n} -> {info.state}:\n{tail}")

    def _watchdog(self, stop_event):
        import _thread

        from areal_tpu.scheduler.client import JobState

        while not stop_event.wait(0.5):
            for n in self._job_names:
                if self._sched.find(n).state in (
                    JobState.FAILED, JobState.CANCELLED
                ):
                    logger.error(
                        f"worker {n} failed; interrupting master"
                    )
                    self._watchdog_fired = True
                    _thread.interrupt_main()
                    return

    def run(self) -> Dict:
        """Blocking: start workers via the scheduler, run master inline."""
        import threading

        name_resolve.reconfigure(**self.name_resolve_cfg)
        self.start_workers()
        self._watchdog_fired = False
        user_interrupt = False
        stop_watchdog = threading.Event()
        watchdog = threading.Thread(
            target=self._watchdog, args=(stop_watchdog,), daemon=True
        )
        watchdog.start()

        from areal_tpu.system.master_worker import MasterWorker

        master = MasterWorker()
        try:
            master.configure(
                self.exp_cfg.master,
                experiment_name=self.exp_cfg.experiment_name,
                trial_name=self.exp_cfg.trial_name,
                worker_name="master",
            )
            master.run()
        except KeyboardInterrupt:
            # See LocalController.run: only the watchdog's interrupt is a
            # worker failure; genuine Ctrl-C re-raises untouched.
            if self._watchdog_fired:
                self.check_worker_errors()
                raise RuntimeError(
                    "a worker job failed (state captured by scheduler)"
                )
            user_interrupt = True
            raise
        finally:
            stop_watchdog.set()
            try:
                if not user_interrupt:
                    self.check_worker_errors()
            finally:
                # Always tear down: leaking scheduler jobs + the KV
                # server would collide with a recovery relaunch.
                self.stop()
        return {"global_step": master.step_info.global_step,
                "perf_summary": dict(master.perf_summary)}

    def stop(self):
        self._sched.stop_all()
        if self._kv_server is not None:
            self._kv_server.stop()
            self._kv_server = None

"""Parity tests for the native host ops (csrc/host_ops.cpp) against the
pure-Python fallbacks and the in-jit GAE scan.

Mirrors the reference's tests/cpp_extensions/test_interval_ops.py and
test_cugae.py (CUDA-vs-Python parity), but the native side is the C++
host library and the accelerator side is the lax.scan GAE.
"""

import numpy as np
import pytest

from areal_tpu.base.datapack import ffd_allocate_py as py_ffd
from areal_tpu.ops import host_ops


def test_native_builds():
    # The library should compile in this environment; if not, every other
    # test still passes on fallbacks, but flag it loudly here.
    assert host_ops.native_available(), "native host_ops failed to build"


@pytest.mark.parametrize("seed", range(5))
@pytest.mark.parametrize("capacity,min_groups", [(100, 1), (64, 4), (10, 1), (1000, 2)])
def test_ffd_parity(seed, capacity, min_groups):
    rng = np.random.RandomState(seed)
    lengths = rng.randint(1, 80, size=rng.randint(1, 200)).astype(np.int64)
    expect = py_ffd(lengths, capacity, min_groups)
    got = host_ops.ffd_allocate_native(lengths, capacity, min_groups)
    assert got == expect


def test_ffd_oversized_items_and_empty():
    assert host_ops.ffd_allocate_native([50, 50], 10, 1) == py_ffd([50, 50], 10, 1)
    assert host_ops.ffd_allocate_native([5], 10, 4) == py_ffd([5], 10, 4)


def test_merge_intervals():
    iv = np.array([[0, 3], [3, 5], [7, 9], [8, 12], [20, 21]], dtype=np.int64)
    out = host_ops.merge_intervals(iv)
    assert out.tolist() == [[0, 5], [7, 12], [20, 21]]
    assert host_ops.merge_intervals(np.zeros((0, 2), np.int64)).shape == (0, 2)


@pytest.mark.parametrize("dtype", [np.float32, np.float16, np.int32, np.uint8])
def test_slice_set_roundtrip(dtype):
    rng = np.random.RandomState(0)
    src = (rng.rand(1000) * 100).astype(dtype)
    iv = np.array([[0, 10], [50, 51], [100, 300], [999, 1000]], dtype=np.int64)
    sl = host_ops.slice_intervals(src, iv)
    expect = np.concatenate([src[s:e] for s, e in iv])
    np.testing.assert_array_equal(sl, expect)

    dst = np.zeros_like(src)
    host_ops.set_intervals(sl, dst, iv)
    for s, e in iv:
        np.testing.assert_array_equal(dst[s:e], src[s:e])
    mask = np.ones(1000, bool)
    for s, e in iv:
        mask[s:e] = False
    assert not dst[mask].any()


def test_interval_bounds_rejected():
    src = np.arange(10, dtype=np.float32)
    dst = np.zeros(10, np.float32)
    for bad in ([[5, 12]], [[-1, 3]], [[4, 2]]):
        iv = np.array(bad, np.int64)
        with pytest.raises(ValueError):
            host_ops.slice_intervals(src, iv)
        with pytest.raises(ValueError):
            host_ops.set_intervals(src[:1], dst, iv)


def test_native_available_nonblocking_converges():
    # wait=False must never raise and must eventually report the built lib.
    import time

    for _ in range(100):
        if host_ops.native_available(wait=False):
            break
        time.sleep(0.05)
    assert host_ops.native_available(wait=False)


def _py_gae_reference(rewards, values, cu, trunc, gamma, lam):
    """Direct transcription of the misaligned-values recurrence."""
    adv = np.zeros_like(rewards)
    ret = np.zeros_like(rewards)
    n_seqs = len(cu) - 1
    for s in range(n_seqs):
        r0, r1 = int(cu[s]), int(cu[s + 1])
        v0 = r0 + s
        nxt_adv, v_next = 0.0, (float(values[v0 + (r1 - r0)]) if trunc[s] else 0.0)
        for t in range(r1 - r0 - 1, -1, -1):
            delta = rewards[r0 + t] + gamma * v_next - values[v0 + t]
            nxt_adv = delta + gamma * lam * nxt_adv
            adv[r0 + t] = nxt_adv
            ret[r0 + t] = nxt_adv + values[v0 + t]
            v_next = float(values[v0 + t])
    return adv, ret


@pytest.mark.parametrize("seed", range(3))
@pytest.mark.parametrize("gamma,lam", [(1.0, 1.0), (0.99, 0.95)])
def test_gae_native_vs_python(seed, gamma, lam):
    rng = np.random.RandomState(seed)
    seqlens = rng.randint(1, 30, size=8)
    cu = np.concatenate([[0], np.cumsum(seqlens)]).astype(np.int64)
    total = int(cu[-1])
    rewards = rng.randn(total).astype(np.float32)
    values = rng.randn(total + len(seqlens)).astype(np.float32)
    trunc = rng.randint(0, 2, size=len(seqlens)).astype(np.uint8)
    adv, ret = host_ops.gae_1d_packed(rewards, values, cu, trunc, gamma, lam)
    eadv, eret = _py_gae_reference(rewards, values, cu, trunc, gamma, lam)
    np.testing.assert_allclose(adv, eadv, rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(ret, eret, rtol=1e-5, atol=1e-5)


def test_gae_host_matches_jit_scan():
    """Host packed GAE == in-jit row-packed lax.scan GAE (areal_tpu.ops.gae)."""
    import jax.numpy as jnp

    from areal_tpu.ops.gae import gae_rows

    rng = np.random.RandomState(1)
    seqlens = [5, 9, 3]
    cu = np.concatenate([[0], np.cumsum(seqlens)]).astype(np.int64)
    total = int(cu[-1])
    rewards = rng.randn(total).astype(np.float32)
    values = rng.randn(total + len(seqlens)).astype(np.float32)
    trunc = np.array([1, 0, 1], dtype=np.uint8)
    gamma, lam = 0.99, 0.95
    adv, ret = host_ops.gae_1d_packed(rewards, values, cu, trunc, gamma, lam)

    # Pack into one [1, T] row for gae_rows.
    T = total
    seg = np.zeros(T, np.int32)
    vrow = np.zeros(T, np.float32)
    boot = np.zeros(T, np.float32)
    for s in range(len(seqlens)):
        r0, r1 = int(cu[s]), int(cu[s + 1])
        seg[r0:r1] = s + 1
        vrow[r0:r1] = values[r0 + s : r1 + s]
        if trunc[s]:
            boot[r1 - 1] = values[r1 + s]
    jadv, jret = gae_rows(
        jnp.asarray(rewards)[None], jnp.asarray(vrow)[None], jnp.asarray(seg)[None],
        jnp.asarray(boot)[None], gamma=gamma, lam=lam,
    )
    np.testing.assert_allclose(adv, np.asarray(jadv)[0], rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(ret, np.asarray(jret)[0], rtol=1e-4, atol=1e-4)
